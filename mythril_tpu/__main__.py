"""``python -m mythril_tpu`` — the ``myth`` console entry analog."""

import sys

from .interfaces.cli import main

sys.exit(main())
