"""Disassembler layer: bytecode -> instruction metadata + device arrays.

TPU-native counterpart of the reference's ``mythril/disassembler/`` and
``mythril/laser/ethereum/instruction_data.py`` (⚠unv, SURVEY.md §2): the
same opcode metadata, but exported additionally as dense uint tables
indexed by opcode byte so the vmapped interpreter can gather
stack-arity/gas/push-width without Python dispatch.
"""

from .opcodes import OPCODES, OpInfo, opcode_by_name  # noqa: F401
from .disassembly import Disassembly, disassemble, ContractImage  # noqa: F401
