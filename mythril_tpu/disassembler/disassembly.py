"""Bytecode -> instruction list + device-ready contract image.

Counterpart of the reference's ``mythril/disassembler/{asm,disassembly}.py``
(⚠unv, SURVEY.md §2): linear-sweep disassembly, JUMPDEST mapping (excluding
push immediates), function-selector extraction from the dispatcher prologue,
and EASM rendering.

TPU-first addition: :class:`ContractImage` packs a contract into fixed-shape
arrays (padded code bytes + jumpdest/is-code bitmaps) so a whole corpus
stacks into ``u8[N_CONTRACTS, MAX_CODE]`` and ships to the device once.
"""

from __future__ import annotations

import re
from dataclasses import dataclass, field
from typing import List, Optional, Dict, Tuple

import numpy as np

from .opcodes import OPCODES, PUSH_WIDTH, name_of


def _to_bytes(code) -> bytes:
    if isinstance(code, (bytes, bytearray)):
        return bytes(code)
    s = str(code).strip()
    if s.startswith(("0x", "0X")):
        s = s[2:]
    s = re.sub(r"\s", "", s)
    # solc appends a non-hex metadata marker in some outputs; keep strict here
    if len(s) % 2:
        s = s[:-1]
    return bytes.fromhex(s)


@dataclass(frozen=True)
class EvmInstruction:
    """One decoded instruction (reference: ``EvmInstruction`` in asm.py ⚠unv)."""

    address: int
    opcode: int
    name: str
    argument: Optional[bytes] = None  # push immediate, if any

    @property
    def arg_int(self) -> Optional[int]:
        return int.from_bytes(self.argument, "big") if self.argument is not None else None

    def as_easm(self) -> str:
        if self.argument is not None:
            return f"{self.address:04x} {self.name} 0x{self.argument.hex()}"
        return f"{self.address:04x} {self.name}"


def disassemble(code) -> List[EvmInstruction]:
    """Linear-sweep disassembly (reference: ``asm.disassemble`` ⚠unv)."""
    raw = _to_bytes(code)
    out: List[EvmInstruction] = []
    pc = 0
    n = len(raw)
    while pc < n:
        op = raw[pc]
        width = int(PUSH_WIDTH[op])
        if width:
            arg = raw[pc + 1 : pc + 1 + width]
            # trailing truncated push: pad with zeros like every EVM client
            arg = arg + b"\x00" * (width - len(arg))
            out.append(EvmInstruction(pc, op, name_of(op), arg))
            pc += 1 + width
        else:
            out.append(EvmInstruction(pc, op, name_of(op)))
            pc += 1
    return out


@dataclass
class ContractImage:
    """Fixed-shape device image of one contract.

    ``code`` is zero-padded (0x00 = STOP, the correct EVM semantics for
    running off the end of code). ``is_jumpdest[i]`` is true iff byte i is a
    0x5b that is *not* inside a push immediate. ``is_code`` marks real
    opcode positions (false inside immediates).
    """

    code: np.ndarray  # u8[max_code]
    code_len: int
    is_jumpdest: np.ndarray  # bool[max_code]
    is_code: np.ndarray  # bool[max_code]

    @staticmethod
    def from_bytecode(code, max_code: int) -> "ContractImage":
        raw = _to_bytes(code)
        if len(raw) > max_code:
            raise ValueError(f"bytecode length {len(raw)} exceeds max_code {max_code}")
        buf = np.zeros(max_code, dtype=np.uint8)
        buf[: len(raw)] = np.frombuffer(raw, dtype=np.uint8)
        is_code = np.zeros(max_code, dtype=bool)
        is_jumpdest = np.zeros(max_code, dtype=bool)
        pc = 0
        while pc < len(raw):
            is_code[pc] = True
            op = raw[pc]
            if op == 0x5B:
                is_jumpdest[pc] = True
            pc += 1 + int(PUSH_WIDTH[op])
        return ContractImage(buf, len(raw), is_jumpdest, is_code)


_DISPATCH_RE_DOC = """Function-selector extraction pattern.

The solc dispatcher prologue compares the calldata selector against each
function hash:  DUP1 PUSH4 <sel> EQ PUSH<n> <dest> JUMPI   (or with the
selector pushed first). We scan the instruction list for PUSH4 followed
within a few instructions by EQ and a JUMPI whose destination was pushed.
(reference: ``disassembly.get_function_info`` / signature DB wiring ⚠unv)
"""


def extract_function_entries(instrs: List[EvmInstruction]) -> Dict[str, int]:
    """selector hex ('0x...') -> jumpdest address of the function body."""
    entries: Dict[str, int] = {}
    for i, ins in enumerate(instrs):
        if ins.name != "PUSH4" or ins.argument is None:
            continue
        window = instrs[i + 1 : i + 5]
        names = [w.name for w in window]
        if "EQ" not in names:
            continue
        dest = None
        for w in window:
            if w.name.startswith("PUSH") and w.name not in ("PUSH4",) and w.argument is not None:
                dest = w.arg_int
            if w.name == "JUMPI" and dest is not None:
                entries[f"0x{ins.argument.hex()}"] = dest
                break
    return entries


class Disassembly:
    """Host-side disassembly view (reference: ``Disassembly`` ⚠unv).

    Holds the instruction list, jumpdest map, and extracted function
    selectors; renders EASM. The device-side twin is :class:`ContractImage`.
    """

    def __init__(self, code, enable_online_lookup: bool = False):
        self.bytecode = _to_bytes(code)
        self.instruction_list = disassemble(self.bytecode)
        self.func_hashes = extract_function_entries(self.instruction_list)
        self.addr_to_func: Dict[int, str] = {v: k for k, v in self.func_hashes.items()}
        self.jumpdests = {i.address for i in self.instruction_list if i.name == "JUMPDEST"}
        self._addr_index = {ins.address: idx for idx, ins in enumerate(self.instruction_list)}

    def get_easm(self) -> str:
        return "\n".join(i.as_easm() for i in self.instruction_list) + "\n"

    def instruction_at(self, address: int) -> Optional[EvmInstruction]:
        idx = self._addr_index.get(address)
        return self.instruction_list[idx] if idx is not None else None

    def image(self, max_code: int) -> ContractImage:
        return ContractImage.from_bytecode(self.bytecode, max_code)

    def __len__(self):
        return len(self.instruction_list)
