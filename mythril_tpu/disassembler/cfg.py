"""Static control-flow graph over disassembled bytecode.

Reference: ``mythril/laser/ethereum/cfg.py`` (⚠unv, SURVEY.md §2 row
"CFG") builds Node/Edge/JumpType DURING symbolic execution. Frontier-
first that bookkeeping would serialize the hot loop, so the graph here is
built STATICALLY from the instruction stream (basic blocks, fall-through,
push-immediate jump targets — which covers solc's dispatcher and loop
shapes), and the exploration's visited-pc bitmap (``sym_run
track_coverage``) can be overlaid afterwards to mark reached blocks.
Feeds ``--graph`` DOT output; the bounded-loops policy intentionally does
NOT depend on it (it counts dynamic back-jumps per lane instead).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional

import numpy as np

from .disassembly import EvmInstruction, disassemble

BLOCK_ENDERS = {"JUMP", "JUMPI", "STOP", "RETURN", "REVERT", "SELFDESTRUCT",
                "INVALID"}


class JumpType(Enum):
    CONDITIONAL = "conditional"
    UNCONDITIONAL = "unconditional"
    FALLTHROUGH = "fallthrough"


@dataclass
class Node:
    uid: int
    start: int                    # pc of first instruction
    end: int                      # pc of last instruction
    instructions: List[EvmInstruction] = field(default_factory=list)
    reached: Optional[bool] = None  # filled from a visited bitmap

    def lines(self, limit: int = 20):
        """Formatted instruction lines (one truncation rule for every
        rendering — DOT and HTML must not drift)."""
        out = [f"{i.address} {i.name}"
               + (f" 0x{i.argument.hex()}" if i.argument else "")
               for i in self.instructions[:limit]]
        if len(self.instructions) > limit:
            out.append("...")
        return out

    @property
    def label(self) -> str:
        head = f"{self.start}..{self.end}"
        return head + "\\l" + "\\l".join(self.lines()) + "\\l"


@dataclass
class Edge:
    src: int    # node uid
    dst: int
    jump_type: JumpType


class CFG:
    """Basic blocks + static edges for one contract's bytecode."""

    def __init__(self, code: bytes):
        self.instructions = disassemble(code)
        self.nodes: List[Node] = []
        self.edges: List[Edge] = []
        self._build()

    def _build(self) -> None:
        instrs = self.instructions
        if not instrs:
            return
        # leaders: entry, jumpdests, instruction after a block ender
        leaders = {instrs[0].address}
        for i, ins in enumerate(instrs):
            if ins.name == "JUMPDEST":
                leaders.add(ins.address)
            if ins.name in BLOCK_ENDERS and i + 1 < len(instrs):
                leaders.add(instrs[i + 1].address)
        node_of_pc: Dict[int, int] = {}
        cur: Optional[Node] = None
        for ins in instrs:
            if ins.address in leaders or cur is None:
                cur = Node(uid=len(self.nodes), start=ins.address,
                           end=ins.address)
                self.nodes.append(cur)
            cur.instructions.append(ins)
            cur.end = ins.address
            node_of_pc[ins.address] = cur.uid
        self._node_of_pc = node_of_pc

        # edges
        for n in self.nodes:
            last = n.instructions[-1]
            nxt = last.address + 1 + len(last.argument or b"")
            if last.name == "JUMPI" and nxt in node_of_pc:
                self.edges.append(Edge(n.uid, node_of_pc[nxt],
                                       JumpType.FALLTHROUGH))
            elif last.name not in BLOCK_ENDERS and nxt in node_of_pc:
                self.edges.append(Edge(n.uid, node_of_pc[nxt],
                                       JumpType.FALLTHROUGH))
            if last.name in ("JUMP", "JUMPI"):
                tgt = self._static_target(n)
                if tgt is not None and tgt in node_of_pc:
                    jt = (JumpType.CONDITIONAL if last.name == "JUMPI"
                          else JumpType.UNCONDITIONAL)
                    self.edges.append(Edge(n.uid, node_of_pc[tgt], jt))

    @staticmethod
    def _static_target(n: Node) -> Optional[int]:
        """PUSH immediately feeding the jump (solc's canonical shape)."""
        if len(n.instructions) >= 2:
            prev = n.instructions[-2]
            if prev.name.startswith("PUSH") and prev.argument:
                return int.from_bytes(prev.argument, "big")
        return None

    def node_at(self, pc: int) -> Optional[Node]:
        uid = self._node_of_pc.get(pc)
        return self.nodes[uid] if uid is not None else None

    def mark_reached(self, visited: np.ndarray) -> None:
        """Overlay a visited-pc bitmap (bool[max_code]) from sym_run."""
        for n in self.nodes:
            n.reached = bool(visited[n.start]) if n.start < len(visited) else False

    def as_dot(self, name: str = "cfg") -> str:
        out = [f'digraph "{name}" {{', '  node [shape=box fontname="monospace"];']
        for n in self.nodes:
            style = ""
            if n.reached is True:
                style = ' style=filled fillcolor="#c8e6c9"'
            elif n.reached is False:
                style = ' style=filled fillcolor="#eeeeee"'
            out.append(f'  n{n.uid} [label="{n.label}"{style}];')
        styles = {JumpType.CONDITIONAL: "dashed",
                  JumpType.UNCONDITIONAL: "solid",
                  JumpType.FALLTHROUGH: "dotted"}
        for e in self.edges:
            out.append(f'  n{e.src} -> n{e.dst} [style={styles[e.jump_type]}];')
        out.append("}")
        return "\n".join(out)

    def as_html(self, name: str = "cfg") -> str:
        """Self-contained interactive HTML view (reference:
        ``--graph out.html`` renders the LASER graph with a bundled JS
        layout, ``mythril/analysis/callgraph.py`` ⚠unv). Zero external
        resources — the layout is a small inline script (layered by
        basic-block order, SVG edges, hover highlights, reached blocks
        tinted), so the file opens anywhere including air-gapped boxes.
        """
        import html as _html
        import json as _json

        nodes = [{
            "uid": n.uid, "start": n.start, "end": n.end,
            "reached": n.reached,
            "text": "\n".join(n.lines()),
        } for n in self.nodes]
        edges = [{"src": e.src, "dst": e.dst, "kind": e.jump_type.name}
                 for e in self.edges]
        payload = _json.dumps({"name": name, "nodes": nodes,
                               "edges": edges})
        # placeholders a hostile contract NAME cannot smuggle into the
        # other substitution: the data slot includes quotes (json escapes
        # any quote in `name` to \"), the title slot includes <> (html
        # escaping turns them into entities)
        return (_HTML_TEMPLATE
                .replace('"@DATA@"', payload)
                .replace("<!--TITLE-->", _html.escape(name)))


_HTML_TEMPLATE = """<!DOCTYPE html>
<html><head><meta charset="utf-8"><title><!--TITLE--> — CFG</title>
<style>
 body{font-family:monospace;background:#1e1e1e;color:#ddd;margin:0}
 #hdr{padding:8px 12px;background:#2d2d2d;position:sticky;top:0}
 svg{display:block}
 .blk rect{fill:#263238;stroke:#546e7a;rx:4}
 .blk.reached rect{fill:#1b3a2a;stroke:#66bb6a}
 .blk.unreached rect{fill:#2a2a2a;stroke:#555}
 .blk:hover rect{stroke:#ffca28;stroke-width:2}
 .blk text{fill:#ddd;font-size:11px;white-space:pre}
 path.CONDITIONAL{stroke:#ffb74d;stroke-dasharray:6 3}
 path.UNCONDITIONAL{stroke:#4fc3f7}
 path.FALLTHROUGH{stroke:#9e9e9e;stroke-dasharray:2 3}
 path{fill:none;stroke-width:1.5;opacity:.8}
</style></head><body>
<div id="hdr"><!--TITLE--> — control-flow graph (green = explored)</div>
<div id="g"></div>
<script>
const D = "@DATA@";
const CW = 8, LH = 13, PADX = 10, PADY = 8, GAPX = 40, GAPY = 46;
// flow layout: blocks in pc order, wrapping rows; curved SVG edges
let x = 20, y = 20, rowH = 0, maxW = 0;
const pos = {};
D.nodes.sort((a,b)=>a.start-b.start).forEach(n => {
  const lines = n.text.split("\\n");
  const w = PADX*2 + CW*Math.max(...lines.map(l=>l.length), 8);
  const h = PADY*2 + LH*lines.length;
  if (x + w > 1500) { x = 20; y += rowH + GAPY; rowH = 0; }
  pos[n.uid] = {x, y, w, h, n, lines};
  x += w + GAPX; rowH = Math.max(rowH, h); maxW = Math.max(maxW, x);
});
const H = y + rowH + 40;
let svg = `<svg width="${Math.max(maxW,800)}" height="${H}" xmlns="http://www.w3.org/2000/svg">`;
D.edges.forEach(e => {
  const a = pos[e.src], b = pos[e.dst]; if (!a || !b) return;
  const x1 = a.x + a.w/2, y1 = a.y + a.h, x2 = b.x + b.w/2, y2 = b.y;
  const my = (y1 + y2) / 2;
  svg += `<path class="${e.kind}" d="M${x1},${y1} C${x1},${my} ${x2},${my} ${x2},${y2}"/>`;
});
D.nodes.forEach(n => {
  const p = pos[n.uid];
  const cls = n.reached === true ? "blk reached" :
              n.reached === false ? "blk unreached" : "blk";
  svg += `<g class="${cls}"><rect x="${p.x}" y="${p.y}" width="${p.w}" height="${p.h}"/>`;
  p.lines.forEach((l, i) => {
    svg += `<text x="${p.x+PADX}" y="${p.y+PADY+LH*(i+0.8)}">${l
      .replace(/&/g,"&amp;").replace(/</g,"&lt;")}</text>`;
  });
  svg += `</g>`;
});
svg += `</svg>`;
document.getElementById("g").innerHTML = svg;
</script></body></html>
"""
