"""Static control-flow graph over disassembled bytecode.

Reference: ``mythril/laser/ethereum/cfg.py`` (⚠unv, SURVEY.md §2 row
"CFG") builds Node/Edge/JumpType DURING symbolic execution. Frontier-
first that bookkeeping would serialize the hot loop, so the graph here is
built STATICALLY from the instruction stream (basic blocks, fall-through,
push-immediate jump targets — which covers solc's dispatcher and loop
shapes), and the exploration's visited-pc bitmap (``sym_run
track_coverage``) can be overlaid afterwards to mark reached blocks.
Feeds ``--graph`` DOT output; the bounded-loops policy intentionally does
NOT depend on it (it counts dynamic back-jumps per lane instead).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from enum import Enum
from typing import Dict, List, Optional

import numpy as np

from .disassembly import EvmInstruction, disassemble

BLOCK_ENDERS = {"JUMP", "JUMPI", "STOP", "RETURN", "REVERT", "SELFDESTRUCT",
                "INVALID"}


class JumpType(Enum):
    CONDITIONAL = "conditional"
    UNCONDITIONAL = "unconditional"
    FALLTHROUGH = "fallthrough"


@dataclass
class Node:
    uid: int
    start: int                    # pc of first instruction
    end: int                      # pc of last instruction
    instructions: List[EvmInstruction] = field(default_factory=list)
    reached: Optional[bool] = None  # filled from a visited bitmap

    @property
    def label(self) -> str:
        head = f"{self.start}..{self.end}"
        body = "\\l".join(
            f"{i.address} {i.name}"
            + (f" 0x{i.argument.hex()}" if i.argument else "")
            for i in self.instructions[:20]
        )
        more = "\\l..." if len(self.instructions) > 20 else ""
        return f"{head}\\l{body}{more}\\l"


@dataclass
class Edge:
    src: int    # node uid
    dst: int
    jump_type: JumpType


class CFG:
    """Basic blocks + static edges for one contract's bytecode."""

    def __init__(self, code: bytes):
        self.instructions = disassemble(code)
        self.nodes: List[Node] = []
        self.edges: List[Edge] = []
        self._build()

    def _build(self) -> None:
        instrs = self.instructions
        if not instrs:
            return
        # leaders: entry, jumpdests, instruction after a block ender
        leaders = {instrs[0].address}
        for i, ins in enumerate(instrs):
            if ins.name == "JUMPDEST":
                leaders.add(ins.address)
            if ins.name in BLOCK_ENDERS and i + 1 < len(instrs):
                leaders.add(instrs[i + 1].address)
        node_of_pc: Dict[int, int] = {}
        cur: Optional[Node] = None
        for ins in instrs:
            if ins.address in leaders or cur is None:
                cur = Node(uid=len(self.nodes), start=ins.address,
                           end=ins.address)
                self.nodes.append(cur)
            cur.instructions.append(ins)
            cur.end = ins.address
            node_of_pc[ins.address] = cur.uid
        self._node_of_pc = node_of_pc

        # edges
        for n in self.nodes:
            last = n.instructions[-1]
            nxt = last.address + 1 + len(last.argument or b"")
            if last.name == "JUMPI" and nxt in node_of_pc:
                self.edges.append(Edge(n.uid, node_of_pc[nxt],
                                       JumpType.FALLTHROUGH))
            elif last.name not in BLOCK_ENDERS and nxt in node_of_pc:
                self.edges.append(Edge(n.uid, node_of_pc[nxt],
                                       JumpType.FALLTHROUGH))
            if last.name in ("JUMP", "JUMPI"):
                tgt = self._static_target(n)
                if tgt is not None and tgt in node_of_pc:
                    jt = (JumpType.CONDITIONAL if last.name == "JUMPI"
                          else JumpType.UNCONDITIONAL)
                    self.edges.append(Edge(n.uid, node_of_pc[tgt], jt))

    @staticmethod
    def _static_target(n: Node) -> Optional[int]:
        """PUSH immediately feeding the jump (solc's canonical shape)."""
        if len(n.instructions) >= 2:
            prev = n.instructions[-2]
            if prev.name.startswith("PUSH") and prev.argument:
                return int.from_bytes(prev.argument, "big")
        return None

    def node_at(self, pc: int) -> Optional[Node]:
        uid = self._node_of_pc.get(pc)
        return self.nodes[uid] if uid is not None else None

    def mark_reached(self, visited: np.ndarray) -> None:
        """Overlay a visited-pc bitmap (bool[max_code]) from sym_run."""
        for n in self.nodes:
            n.reached = bool(visited[n.start]) if n.start < len(visited) else False

    def as_dot(self, name: str = "cfg") -> str:
        out = [f'digraph "{name}" {{', '  node [shape=box fontname="monospace"];']
        for n in self.nodes:
            style = ""
            if n.reached is True:
                style = ' style=filled fillcolor="#c8e6c9"'
            elif n.reached is False:
                style = ' style=filled fillcolor="#eeeeee"'
            out.append(f'  n{n.uid} [label="{n.label}"{style}];')
        styles = {JumpType.CONDITIONAL: "dashed",
                  JumpType.UNCONDITIONAL: "solid",
                  JumpType.FALLTHROUGH: "dotted"}
        for e in self.edges:
            out.append(f'  n{e.src} -> n{e.dst} [style={styles[e.jump_type]}];')
        out.append("}")
        return "\n".join(out)
