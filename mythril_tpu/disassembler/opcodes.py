"""EVM opcode metadata (through Shanghai: PUSH0) + dense device tables.

Counterpart of the reference's ``mythril/laser/ethereum/instruction_data.py``
(⚠unv, SURVEY.md §2 "Gas/opcode metadata"): per-opcode mnemonic, stack
in/out arity, and (min, max) static gas. Dynamic gas components (memory
expansion, copy cost, cold/warm access, SSTORE cases) are accounted in the
interpreter, as in the reference's ``StateTransition`` decorator +
per-handler logic.

The TPU-first addition: everything is also exported as dense ``uint``
tables of length 256 indexed by the opcode byte (``STACK_IN``, ``STACK_OUT``,
``GAS_MIN``, ``GAS_MAX``, ``PUSH_WIDTH``, ``IS_VALID``, ``CLASS_ID``), so a
vmapped interpreter reads metadata with a single gather instead of Python
dict dispatch.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional

import numpy as np


@dataclass(frozen=True)
class OpInfo:
    opcode: int
    name: str
    stack_in: int
    stack_out: int
    gas_min: int
    gas_max: int
    push_width: int = 0  # bytes of immediate data (PUSH1..32); PUSH0 is 0


# Gas figures follow the Istanbul-era schedule the reference models
# (min, max) pairs where the cost is state-dependent.
_G_ZERO = 0
_G_BASE = 2
_G_VERYLOW = 3
_G_LOW = 5
_G_MID = 8
_G_HIGH = 10
_G_SLOAD = 800
_G_BALANCE = 700
_G_EXTCODE = 700
_G_EXTCODEHASH = 700
_G_CALL = 700
_G_CREATE = 32000
_G_JUMPDEST = 1
_G_SSTORE_MIN = 5000  # dirty/no-op floor modeled as min
_G_SSTORE_MAX = 20000  # fresh slot write
_G_LOG = 375
_G_LOGDATA = 8  # per byte — dynamic
_G_SELFDESTRUCT_MIN = 5000
_G_SELFDESTRUCT_MAX = 30000  # + new-account surcharge
_G_CALL_MAX = _G_CALL + 9000 + 25000  # value transfer + new account


def _ops() -> Dict[int, OpInfo]:
    t: Dict[int, OpInfo] = {}

    def op(code, name, sin, sout, gmin, gmax=None, push=0):
        t[code] = OpInfo(code, name, sin, sout, gmin, gmax if gmax is not None else gmin, push)

    op(0x00, "STOP", 0, 0, _G_ZERO)
    op(0x01, "ADD", 2, 1, _G_VERYLOW)
    op(0x02, "MUL", 2, 1, _G_LOW)
    op(0x03, "SUB", 2, 1, _G_VERYLOW)
    op(0x04, "DIV", 2, 1, _G_LOW)
    op(0x05, "SDIV", 2, 1, _G_LOW)
    op(0x06, "MOD", 2, 1, _G_LOW)
    op(0x07, "SMOD", 2, 1, _G_LOW)
    op(0x08, "ADDMOD", 3, 1, _G_MID)
    op(0x09, "MULMOD", 3, 1, _G_MID)
    op(0x0A, "EXP", 2, 1, _G_HIGH, _G_HIGH + 50 * 32)  # + 50/byte of exponent
    op(0x0B, "SIGNEXTEND", 2, 1, _G_LOW)

    op(0x10, "LT", 2, 1, _G_VERYLOW)
    op(0x11, "GT", 2, 1, _G_VERYLOW)
    op(0x12, "SLT", 2, 1, _G_VERYLOW)
    op(0x13, "SGT", 2, 1, _G_VERYLOW)
    op(0x14, "EQ", 2, 1, _G_VERYLOW)
    op(0x15, "ISZERO", 1, 1, _G_VERYLOW)
    op(0x16, "AND", 2, 1, _G_VERYLOW)
    op(0x17, "OR", 2, 1, _G_VERYLOW)
    op(0x18, "XOR", 2, 1, _G_VERYLOW)
    op(0x19, "NOT", 1, 1, _G_VERYLOW)
    op(0x1A, "BYTE", 2, 1, _G_VERYLOW)
    op(0x1B, "SHL", 2, 1, _G_VERYLOW)
    op(0x1C, "SHR", 2, 1, _G_VERYLOW)
    op(0x1D, "SAR", 2, 1, _G_VERYLOW)

    op(0x20, "SHA3", 2, 1, 30, 30 + 6 * 32)  # + 6/word — dynamic

    op(0x30, "ADDRESS", 0, 1, _G_BASE)
    op(0x31, "BALANCE", 1, 1, _G_BALANCE)
    op(0x32, "ORIGIN", 0, 1, _G_BASE)
    op(0x33, "CALLER", 0, 1, _G_BASE)
    op(0x34, "CALLVALUE", 0, 1, _G_BASE)
    op(0x35, "CALLDATALOAD", 1, 1, _G_VERYLOW)
    op(0x36, "CALLDATASIZE", 0, 1, _G_BASE)
    op(0x37, "CALLDATACOPY", 3, 0, _G_VERYLOW, _G_VERYLOW + 3 * 768)
    op(0x38, "CODESIZE", 0, 1, _G_BASE)
    op(0x39, "CODECOPY", 3, 0, _G_VERYLOW, _G_VERYLOW + 3 * 768)
    op(0x3A, "GASPRICE", 0, 1, _G_BASE)
    op(0x3B, "EXTCODESIZE", 1, 1, _G_EXTCODE)
    op(0x3C, "EXTCODECOPY", 4, 0, _G_EXTCODE, _G_EXTCODE + 3 * 768)
    op(0x3D, "RETURNDATASIZE", 0, 1, _G_BASE)
    op(0x3E, "RETURNDATACOPY", 3, 0, _G_VERYLOW, _G_VERYLOW + 3 * 768)
    op(0x3F, "EXTCODEHASH", 1, 1, _G_EXTCODEHASH)

    op(0x40, "BLOCKHASH", 1, 1, 20)
    op(0x41, "COINBASE", 0, 1, _G_BASE)
    op(0x42, "TIMESTAMP", 0, 1, _G_BASE)
    op(0x43, "NUMBER", 0, 1, _G_BASE)
    op(0x44, "PREVRANDAO", 0, 1, _G_BASE)  # a.k.a. DIFFICULTY
    op(0x45, "GASLIMIT", 0, 1, _G_BASE)
    op(0x46, "CHAINID", 0, 1, _G_BASE)
    op(0x47, "SELFBALANCE", 0, 1, _G_LOW)
    op(0x48, "BASEFEE", 0, 1, _G_BASE)

    op(0x50, "POP", 1, 0, _G_BASE)
    op(0x51, "MLOAD", 1, 1, _G_VERYLOW)
    op(0x52, "MSTORE", 2, 0, _G_VERYLOW)
    op(0x53, "MSTORE8", 2, 0, _G_VERYLOW)
    op(0x54, "SLOAD", 1, 1, _G_SLOAD)
    op(0x55, "SSTORE", 2, 0, _G_SSTORE_MIN, _G_SSTORE_MAX)
    op(0x56, "JUMP", 1, 0, _G_MID)
    op(0x57, "JUMPI", 2, 0, _G_HIGH)
    op(0x58, "PC", 0, 1, _G_BASE)
    op(0x59, "MSIZE", 0, 1, _G_BASE)
    op(0x5A, "GAS", 0, 1, _G_BASE)
    op(0x5B, "JUMPDEST", 0, 0, _G_JUMPDEST)
    op(0x5F, "PUSH0", 0, 1, _G_BASE)

    for n in range(1, 33):
        op(0x5F + n, f"PUSH{n}", 0, 1, _G_VERYLOW, push=n)
    for n in range(1, 17):
        op(0x7F + n, f"DUP{n}", n, n + 1, _G_VERYLOW)
    for n in range(1, 17):
        op(0x8F + n, f"SWAP{n}", n + 1, n + 1, _G_VERYLOW)
    for n in range(0, 5):
        op(0xA0 + n, f"LOG{n}", 2 + n, 0, _G_LOG * (n + 1), _G_LOG * (n + 1) + _G_LOGDATA * 256)

    op(0xF0, "CREATE", 3, 1, _G_CREATE)
    op(0xF1, "CALL", 7, 1, _G_CALL, _G_CALL_MAX)
    op(0xF2, "CALLCODE", 7, 1, _G_CALL, _G_CALL + 9000)
    op(0xF3, "RETURN", 2, 0, _G_ZERO)
    op(0xF4, "DELEGATECALL", 6, 1, _G_CALL)
    op(0xF5, "CREATE2", 4, 1, _G_CREATE, _G_CREATE + 6 * 768)
    op(0xFA, "STATICCALL", 6, 1, _G_CALL)
    op(0xFD, "REVERT", 2, 0, _G_ZERO)
    op(0xFE, "INVALID", 0, 0, _G_ZERO)
    op(0xFF, "SELFDESTRUCT", 1, 0, _G_SELFDESTRUCT_MIN, _G_SELFDESTRUCT_MAX)
    return t


OPCODES: Dict[int, OpInfo] = _ops()
_BY_NAME: Dict[str, OpInfo] = {v.name: v for v in OPCODES.values()}
_BY_NAME["DIFFICULTY"] = OPCODES[0x44]
_BY_NAME["KECCAK256"] = OPCODES[0x20]


def opcode_by_name(name: str) -> OpInfo:
    return _BY_NAME[name.upper()]


def name_of(opcode: int) -> str:
    info = OPCODES.get(opcode)
    return info.name if info else f"UNKNOWN_0x{opcode:02x}"


# ---------------------------------------------------------------------------
# Dense device tables (numpy; interpreter wraps them in jnp once)
# ---------------------------------------------------------------------------

STACK_IN = np.zeros(256, dtype=np.int32)
STACK_OUT = np.zeros(256, dtype=np.int32)
GAS_MIN = np.zeros(256, dtype=np.int64)
GAS_MAX = np.zeros(256, dtype=np.int64)
PUSH_WIDTH = np.zeros(256, dtype=np.int32)
IS_VALID = np.zeros(256, dtype=bool)
for _code, _info in OPCODES.items():
    STACK_IN[_code] = _info.stack_in
    STACK_OUT[_code] = _info.stack_out
    GAS_MIN[_code] = _info.gas_min
    GAS_MAX[_code] = _info.gas_max
    PUSH_WIDTH[_code] = _info.push_width
    IS_VALID[_code] = True

# EIP-2929 (Berlin) static tables: state-access opcodes carry their WARM
# cost here; the symbolic engine adds the cold surcharge dynamically from
# its per-lane warm sets (see engine._berlin_gas_fixup). Reference keeps
# an Istanbul-era schedule (SURVEY §2 "Gas/opcode metadata"); the rebuild
# supports both via LimitsConfig.gas_schedule.
G_WARM_ACCESS = 100
G_COLD_SLOAD = 2100
G_COLD_ACCOUNT = 2600

GAS_MIN_BERLIN = GAS_MIN.copy()
GAS_MAX_BERLIN = GAS_MAX.copy()
for _c in (0x31, 0x3B, 0x3C, 0x3F):  # BALANCE EXTCODESIZE EXTCODECOPY EXTCODEHASH
    GAS_MIN_BERLIN[_c] = GAS_MIN[_c] - _G_EXTCODE + G_WARM_ACCESS
    GAS_MAX_BERLIN[_c] = GAS_MAX[_c] - _G_EXTCODE + G_WARM_ACCESS
GAS_MIN_BERLIN[0x54] = G_WARM_ACCESS                 # SLOAD
GAS_MAX_BERLIN[0x54] = G_WARM_ACCESS
GAS_MIN_BERLIN[0x55] = 100                           # SSTORE warm dirty
GAS_MAX_BERLIN[0x55] = 20000                         # fresh slot write
for _c in (0xF1, 0xF2, 0xF4, 0xFA):                  # CALL family
    GAS_MIN_BERLIN[_c] = GAS_MIN[_c] - _G_CALL + G_WARM_ACCESS
    GAS_MAX_BERLIN[_c] = GAS_MAX[_c] - _G_CALL + G_WARM_ACCESS
GAS_MIN_BERLIN[0xFF] = G_WARM_ACCESS + 4900          # SELFDESTRUCT (5000 kept)
GAS_MAX_BERLIN[0xFF] = GAS_MAX[0xFF]

# Halting / control metadata for the interpreter & CFG builder
HALTS = np.zeros(256, dtype=bool)  # STOP RETURN REVERT INVALID SELFDESTRUCT
for _c in (0x00, 0xF3, 0xFD, 0xFE, 0xFF):
    HALTS[_c] = True
IS_JUMP = np.zeros(256, dtype=bool)
IS_JUMP[0x56] = True
IS_JUMPI = np.zeros(256, dtype=bool)
IS_JUMPI[0x57] = True
IS_CALL = np.zeros(256, dtype=bool)  # CALL-family (sub-transaction boundary)
for _c in (0xF1, 0xF2, 0xF4, 0xFA):
    IS_CALL[_c] = True
IS_CREATE = np.zeros(256, dtype=bool)
for _c in (0xF0, 0xF5):
    IS_CREATE[_c] = True
# Invalid opcodes consume all gas (modeled as HALTS + error flag in interp).
