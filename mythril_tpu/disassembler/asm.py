"""Tiny two-pass EVM assembler with label support.

Counterpart of the reference's ``mythril/disassembler/asm.py`` (⚠unv,
SURVEY.md §2 "Disassembler") going the other direction: we need to *author*
representative bytecode in-repo because the image carries no ``solc``
binary. Used by ``bench.py``, sample contracts, and tests.

Token forms accepted by :func:`assemble`:

- ``"ADD"`` — opcode by name (case-insensitive)
- ``int`` — PUSH with the minimal width holding the value
- ``("pushN", value)`` — explicit ``PUSHN`` with ``value``
- ``("label", "name")`` — define a jump label at the current offset
- ``("ref", "name")`` — ``PUSH2`` of the label's final offset
"""

from __future__ import annotations

from typing import Dict, List, Sequence, Tuple, Union

from .opcodes import opcode_by_name

Token = Union[str, int, Tuple[str, Union[int, str]]]


def _min_push_width(value: int) -> int:
    if value == 0:
        return 1
    return max(1, (value.bit_length() + 7) // 8)


def assemble(*tokens: Token) -> bytes:
    """Assemble tokens into bytecode; two passes to resolve label refs."""
    # pass 1: lay out, recording label defs and 2-byte ref placeholders
    out = bytearray()
    labels: Dict[str, int] = {}
    refs: List[Tuple[int, str]] = []  # (patch offset, label)
    for t in tokens:
        if isinstance(t, str):
            out.append(opcode_by_name(t).opcode)
        elif isinstance(t, int):
            w = _min_push_width(t)
            if t < 0 or w > 32:
                raise ValueError(f"push value out of range: {t!r}")
            out.append(0x5F + w)
            out.extend(t.to_bytes(w, "big"))
        elif isinstance(t, tuple) and t[0] == "label":
            labels[t[1]] = len(out)
            out.append(opcode_by_name("JUMPDEST").opcode)
        elif isinstance(t, tuple) and t[0] == "ref":
            out.append(0x61)  # PUSH2
            refs.append((len(out), t[1]))
            out.extend(b"\x00\x00")
        elif isinstance(t, tuple) and t[0].lower().startswith("push"):
            n = int(t[0][4:])
            if not 0 <= n <= 32:
                raise ValueError(f"bad push width: {t!r}")
            out.append(0x5F + n)
            out.extend(int(t[1]).to_bytes(n, "big"))
        else:
            raise ValueError(f"bad asm token: {t!r}")
    # pass 2: patch refs
    for off, name in refs:
        out[off : off + 2] = labels[name].to_bytes(2, "big")
    return bytes(out)


def selector_prologue() -> List[Token]:
    """Dispatcher prologue fragment: leaves the 4-byte selector on stack."""
    return [0, "CALLDATALOAD", (1 << 224), "SWAP1", "DIV"]


def mapping_key(slot: int) -> List[Token]:
    """Solidity mapping-slot idiom: top-of-stack key ->
    keccak(key . slot). Shared by the in-repo fixtures (erc20_like,
    config-4, realworld) so the storage-layout convention lives in ONE
    place."""
    return [0, "MSTORE", slot, 32, "MSTORE", 64, 0, "SHA3"]


def erc20_like() -> bytes:
    """A hand-written token contract exercising the representative opcode
    mix (dispatcher, keccak mapping keys, storage, branches, arithmetic).

    Storage layout: balances[addr] at keccak(addr . 0x00), totalSupply at
    slot 1. Functions:
      0xa9059cbb transfer(address,uint256)
      0x70a08231 balanceOf(address)
      0x18160ddd totalSupply()
    Fallback reverts. The reference's bench fixture would be a
    solc-compiled OpenZeppelin ERC-20 (BASELINE config 1); this is the
    no-solc stand-in with the same structural profile.
    """

    mapkey = mapping_key

    return assemble(
        # -- dispatcher --
        *selector_prologue(),
        "DUP1", 0xA9059CBB, "EQ", ("ref", "transfer"), "JUMPI",
        "DUP1", 0x70A08231, "EQ", ("ref", "balanceOf"), "JUMPI",
        "DUP1", 0x18160DDD, "EQ", ("ref", "totalSupply"), "JUMPI",
        0, 0, "REVERT",
        # -- transfer(to, amount) --
        ("label", "transfer"),
        "POP",
        4, "CALLDATALOAD",            # to
        36, "CALLDATALOAD",           # amount   [to, amount]
        "CALLER", *mapkey(0),         # keccak(caller.0)        [to, amount, fromKey]
        "DUP1", "SLOAD",              # [to, amount, fromKey, fromBal]
        "DUP3", "DUP2", "LT",         # fromBal < amount ?
        ("ref", "insufficient"), "JUMPI",
        "DUP3", "SWAP1", "SUB",       # newFromBal = fromBal - amount
        "SWAP1", "SSTORE",            # balances[from] = newFromBal  [to, amount]
        "SWAP1", *mapkey(0),          # keccak(to.0)   [amount, toKey]
        "DUP1", "SLOAD",              # [amount, toKey, toBal]
        "DUP3", "ADD",                # toBal + amount
        "SWAP1", "SSTORE",            # balances[to] = ...   [amount]
        "POP",
        1, 0, "MSTORE", 32, 0, "RETURN",
        ("label", "insufficient"),
        0, 0, "REVERT",
        # -- balanceOf(addr) --
        ("label", "balanceOf"),
        "POP",
        4, "CALLDATALOAD", *mapkey(0), "SLOAD",
        0, "MSTORE", 32, 0, "RETURN",
        # -- totalSupply() --
        ("label", "totalSupply"),
        "POP",
        1, "SLOAD", 0, "MSTORE", 32, 0, "RETURN",
    )


def abi_call(selector4: int, *args: int) -> bytes:
    """Build calldata: 4-byte selector + 32-byte big-endian args."""
    out = selector4.to_bytes(4, "big")
    for a in args:
        out += int(a).to_bytes(32, "big")
    return out
