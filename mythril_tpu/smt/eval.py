"""Exact evaluation of a HostTape under a candidate assignment.

The semantic ground truth for the solver: plain Python ints with EVM
wrap-around semantics, real keccak for hash chains (so witnesses agree
with what concrete re-execution would produce — the reference gets this
via Z3 models + its KeccakFunctionManager linking ⚠unv).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..ops.keccak import keccak256_host_int
from ..symbolic.ops import SymOp, FreeKind

M256 = (1 << 256) - 1
SIGN = 1 << 255

# Reference's well-known actors (mythril/laser/ethereum/transaction ⚠unv):
# concrete attacker/creator addresses used when the caller isn't symbolic.
ATTACKER_ADDRESS = 0xDEADBEEFDEADBEEFDEADBEEFDEADBEEFDEADBEEF
CREATOR_ADDRESS = 0xAFFEAFFEAFFEAFFEAFFEAFFEAFFEAFFEAFFEAFFE


def _s(x: int) -> int:
    return x - (1 << 256) if x & SIGN else x


TX_STRIDE = 1 << 16  # leaf b-encoding: b = tx_id * TX_STRIDE + byte offset


@dataclass
class TxInput:
    """One transaction's attacker-chosen inputs."""

    calldata: bytearray = field(default_factory=lambda: bytearray(256))
    calldatasize: Optional[int] = None  # None -> len(calldata)
    caller: int = ATTACKER_ADDRESS
    callvalue: int = 0

    def copy(self) -> "TxInput":
        return TxInput(bytearray(self.calldata), self.calldatasize,
                       self.caller, self.callvalue)

    def read_word(self, off: int) -> int:
        """32-byte big-endian read, zero-padded past the effective
        calldatasize — matching concrete CALLDATALOAD so a sat witness
        can't diverge from replay on short-calldata paths."""
        size = self.calldatasize if self.calldatasize is not None else len(self.calldata)
        size = max(0, min(size, len(self.calldata)))
        w = bytes(self.calldata[off : off + 32])[: max(0, size - off)]
        w = w + b"\x00" * (32 - len(w))
        return int.from_bytes(w, "big")

    def write_word(self, off: int, value: int) -> None:
        need = off + 32
        if len(self.calldata) < need:
            self.calldata.extend(b"\x00" * (need - len(self.calldata)))
        self.calldata[off : off + 32] = (value & M256).to_bytes(32, "big")


@dataclass
class Assignment:
    """Candidate model: per-transaction inputs + global scalar vars.

    Calldata leaves are byte windows over the owning tx's byte array, so
    overlapping leaves (offset 0 vs offset 4) stay mutually consistent by
    construction. Single-tx call sites can keep using the tx-0 proxy
    properties (calldata/caller/callvalue/calldatasize)."""

    txs: List["TxInput"] = field(default_factory=lambda: [TxInput()])
    scalars: Dict[Tuple[int, int], int] = field(default_factory=dict)
    # STORAGE/RETVAL/HAVOC/RETDATASIZE leaves keyed by node id
    by_node: Dict[int, int] = field(default_factory=dict)

    def tx(self, i: int) -> "TxInput":
        while len(self.txs) <= i:
            self.txs.append(TxInput())
        return self.txs[i]

    def copy(self) -> "Assignment":
        return Assignment(
            txs=[t.copy() for t in self.txs],
            scalars=dict(self.scalars),
            by_node=dict(self.by_node),
        )

    # --- tx-0 proxies (single-tx API compatibility) ---
    @property
    def calldata(self) -> bytearray:
        return self.tx(0).calldata

    @property
    def calldatasize(self) -> Optional[int]:
        return self.tx(0).calldatasize

    @calldatasize.setter
    def calldatasize(self, v) -> None:
        self.tx(0).calldatasize = v

    @property
    def caller(self) -> int:
        return self.tx(0).caller

    @caller.setter
    def caller(self, v) -> None:
        self.tx(0).caller = v

    @property
    def callvalue(self) -> int:
        return self.tx(0).callvalue

    @callvalue.setter
    def callvalue(self, v) -> None:
        self.tx(0).callvalue = v

    def read_calldata_word(self, off: int, tx: int = 0) -> int:
        return self.tx(tx).read_word(off)

    def write_calldata_word(self, off: int, value: int, tx: int = 0) -> None:
        self.tx(tx).write_word(off, value)


#: FreeKinds whose values live in ``Assignment.by_node`` (keyed by node
#: id, not by (kind, index)). SINGLE source of truth — the assigner
#: (solver._assign_leaf), the evaluator (_free_value) and the
#: independence partitioner (solver._leaf_keys) all key off this tuple.
BY_NODE_KINDS = (
    int(FreeKind.STORAGE), int(FreeKind.RETVAL), int(FreeKind.HAVOC),
    int(FreeKind.RETDATASIZE), int(FreeKind.BLOCKHASH),
    int(FreeKind.ECRECOVER), int(FreeKind.PRECOMPILE),
)


def _free_value(node_id: int, kind: int, index: int, asn: Assignment) -> int:
    if kind == int(FreeKind.CALLDATA_WORD):
        return asn.tx(index // TX_STRIDE).read_word(index % TX_STRIDE)
    if kind == int(FreeKind.CALLER):
        return asn.tx(index).caller
    if kind == int(FreeKind.ORIGIN):
        return asn.scalars.get((kind, index), asn.caller)
    if kind == int(FreeKind.CALLVALUE):
        return asn.tx(index).callvalue
    if kind == int(FreeKind.CALLDATASIZE):
        t = asn.tx(index)
        return t.calldatasize if t.calldatasize is not None else len(t.calldata)
    if kind in BY_NODE_KINDS:
        return asn.by_node.get(node_id, 0)
    # block-env leaves default to plausible mainnet-ish values
    defaults = {
        int(FreeKind.TIMESTAMP): 1_700_000_000,
        int(FreeKind.NUMBER): 17_000_000,
        int(FreeKind.BALANCE): 10**18,
        int(FreeKind.GASPRICE): 10**9,
        int(FreeKind.PREVRANDAO): 0x123456789ABCDEF,
    }
    return asn.scalars.get((kind, index), defaults.get(kind, 0))


def _packed_tape(tape):
    """ctypes-ready arrays for the native evaluator, cached on the tape
    object (nodes are append-only; a length change invalidates)."""
    import ctypes

    nodes = tape.nodes
    n = len(nodes)
    cached = getattr(tape, "_native_pack", None)
    if cached is not None and cached[0] == n:
        return cached
    op = (ctypes.c_int32 * n)()
    a = (ctypes.c_int32 * n)()
    b = (ctypes.c_int32 * n)()
    imm = bytearray(n * 32)
    leaves = []
    FREE = int(SymOp.FREE)
    for i, nd in enumerate(nodes):
        op[i], a[i], b[i] = nd.op, nd.a, nd.b
        if nd.imm:
            imm[i * 32:(i + 1) * 32] = (nd.imm & M256).to_bytes(32, "big")
        if nd.op == FREE:
            leaves.append(i)
    pack = (n, op, a, b, bytes(imm), tuple(leaves))
    try:
        tape._native_pack = pack  # HostTape is a plain dataclass
    except Exception:
        pass
    return pack


def _evaluate_native(tape, asn: Assignment, lib) -> Optional[List[int]]:
    import ctypes

    n, op, a, b, imm, leaves = _packed_tape(tape)
    vals = bytearray(n * 32)
    for i in leaves:
        nd = tape.nodes[i]
        v = _free_value(i, nd.a, nd.b, asn) & M256
        if v:
            vals[i * 32:(i + 1) * 32] = v.to_bytes(32, "big")
    buf = (ctypes.c_uint8 * len(vals)).from_buffer(vals)
    rc = lib.tape_eval(n, op, a, b, imm,
                       ctypes.cast(buf, ctypes.POINTER(ctypes.c_uint8)))
    if rc != 0:
        return None
    mv = memoryview(vals)
    return [int.from_bytes(mv[i * 32:(i + 1) * 32], "big") for i in range(n)]


def evaluate(tape, asn: Assignment) -> List[int]:
    """Value of every node under `asn` (keccak chains evaluated exactly).
    Returns vals[id]; chain-carrier nodes (SEED/ABS) hold 0.

    Dispatches to the native (C) evaluator when available — the witness
    search calls this hundreds of times per query; the Python big-int
    loop below is the semantic reference and the fallback
    (``MYTHRIL_NO_NATIVE=1``)."""
    from ..native import tape_eval_lib

    lib = tape_eval_lib()
    if lib is not None:
        out = _evaluate_native(tape, asn, lib)
        if out is not None:
            return out
    return _evaluate_py(tape, asn)


def _evaluate_py(tape, asn: Assignment) -> List[int]:
    n = len(tape.nodes)
    vals = [0] * n
    # chain id -> (bytes-so-far, declared_len, start_offset_in_first_word)
    chains: Dict[int, Tuple[bytes, int, int]] = {}

    for i in range(1, n):
        nd = tape.nodes[i]
        op = nd.op
        if op == int(SymOp.NULL):
            continue
        if op == int(SymOp.CONST):
            vals[i] = nd.imm & M256
            continue
        if op == int(SymOp.FREE):
            vals[i] = _free_value(i, nd.a, nd.b, asn) & M256
            continue
        if op == int(SymOp.KECCAK_SEED):
            ln = nd.imm & 0xFFFFFFFF
            r = (nd.imm >> 32) & 0xFFFFFFFF
            chains[i] = (b"", ln, r)
            continue
        if op == int(SymOp.KECCAK_ABS):
            prev = chains.get(nd.a, (b"", 0, 0))
            word = vals[nd.b] if nd.b else (nd.imm & M256)
            chains[i] = (prev[0] + word.to_bytes(32, "big"), prev[1], prev[2])
            continue
        if op == int(SymOp.KECCAK):
            data, ln, r = chains.get(nd.a, (b"", 0, 0))
            vals[i] = keccak256_host_int(data[r : r + ln])
            continue

        a = vals[nd.a]
        b = vals[nd.b]
        if op == int(SymOp.ADD):
            vals[i] = (a + b) & M256
        elif op == int(SymOp.SUB):
            vals[i] = (a - b) & M256
        elif op == int(SymOp.MUL):
            vals[i] = (a * b) & M256
        elif op == int(SymOp.DIV):
            vals[i] = a // b if b else 0
        elif op == int(SymOp.SDIV):
            sa, sb = _s(a), _s(b)
            vals[i] = (abs(sa) // abs(sb) * (1 if (sa < 0) == (sb < 0) else -1)) & M256 if sb else 0
        elif op == int(SymOp.MOD):
            vals[i] = a % b if b else 0
        elif op == int(SymOp.SMOD):
            sa, sb = _s(a), _s(b)
            vals[i] = ((abs(sa) % abs(sb)) * (1 if sa >= 0 else -1)) & M256 if sb else 0
        elif op == int(SymOp.EXP):
            vals[i] = pow(a, b, 1 << 256)
        elif op == int(SymOp.SIGNEXTEND):
            if a < 31:
                bit = 8 * a + 7
                if b & (1 << bit):
                    vals[i] = (b | (M256 ^ ((1 << (bit + 1)) - 1))) & M256
                else:
                    vals[i] = b & ((1 << (bit + 1)) - 1)
            else:
                vals[i] = b
        elif op == int(SymOp.LT):
            vals[i] = int(a < b)
        elif op == int(SymOp.GT):
            vals[i] = int(a > b)
        elif op == int(SymOp.SLT):
            vals[i] = int(_s(a) < _s(b))
        elif op == int(SymOp.SGT):
            vals[i] = int(_s(a) > _s(b))
        elif op == int(SymOp.EQ):
            vals[i] = int(a == b)
        elif op == int(SymOp.ISZERO):
            vals[i] = int(a == 0)
        elif op == int(SymOp.AND):
            vals[i] = a & b
        elif op == int(SymOp.OR):
            vals[i] = a | b
        elif op == int(SymOp.XOR):
            vals[i] = a ^ b
        elif op == int(SymOp.NOT):
            vals[i] = a ^ M256
        elif op == int(SymOp.BYTE):
            vals[i] = (b >> (8 * (31 - a))) & 0xFF if a < 32 else 0
        elif op == int(SymOp.SHL):
            vals[i] = (b << a) & M256 if a < 256 else 0
        elif op == int(SymOp.SHR):
            vals[i] = b >> a if a < 256 else 0
        elif op == int(SymOp.SAR):
            if a >= 256:
                vals[i] = M256 if b & SIGN else 0
            else:
                vals[i] = (_s(b) >> a) & M256
        else:
            vals[i] = 0
    return vals
