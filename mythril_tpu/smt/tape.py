"""Host-side view of one lane's SSA tape.

Pulls the device arrays for a single lane into plain Python structures so
the solver can walk them without touching JAX. This is the boundary where
the reference would hold Z3 ASTs; here an expression IS its tape row.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import List, Tuple

import numpy as np

from ..ops import u256
from ..symbolic.ops import SymOp, FreeKind


@dataclass(frozen=True)
class HostNode:
    op: int
    a: int
    b: int
    imm: int  # u256 immediate as a Python int


@dataclass
class HostTape:
    nodes: List[HostNode]           # index = node id; [0] is concrete zero
    constraints: List[Tuple[int, bool]]  # (node id, asserted sign)
    pcs: List[int] = field(default_factory=list)  # branch pc per constraint (may be shorter)


def node_index(nodes: List[HostNode]):
    """Hash index for :func:`intern_node`: node -> FIRST id carrying it
    (HostNode is frozen, hence hashable). Build once per tape, copy per
    mutation batch — turns each intern from an O(n) dataclass-equality
    scan into an O(1) lookup."""
    idx = {}
    for i, nd in enumerate(nodes):
        idx.setdefault(nd, i)
    return idx


def intern_node(nodes: List[HostNode], node: HostNode, index=None) -> int:
    """Id of `node` in `nodes`, appending only when absent — the host
    analog of the device tape's hash-consing. Detection modules MUST
    build attack predicates through this: a predicate that re-creates a
    node the path already asserts (e.g. the LT(a,b) a SafeMath guard
    branched on) then shares its id, so the refuter sees the polarity
    conflict and proves UNSAT instead of burning witness-search budget
    into an `unknown` (round 4: this was every second solver query on
    the ERC-20 workload). Pass the tape's :func:`node_index` when
    interning repeatedly; it is kept in sync with appends."""
    if index is not None:
        hit = index.get(node)
        if hit is None:
            nodes.append(node)
            hit = index[node] = len(nodes) - 1
        return hit
    try:
        return nodes.index(node)
    except ValueError:
        nodes.append(node)
        return len(nodes) - 1


def support(tape: HostTape, root: int):
    """(leaf node ids, FreeKind set) reachable from `root` (iterative)."""
    ids, kinds, seen, stack = [], set(), set(), [root]
    while stack:
        i = stack.pop()
        if i in seen or i <= 0 or i >= len(tape.nodes):
            continue
        seen.add(i)
        nd = tape.nodes[i]
        if nd.op == int(SymOp.FREE):
            ids.append(i)
            kinds.add(nd.a)
        elif nd.op not in (int(SymOp.CONST), int(SymOp.NULL)):
            stack.extend((nd.a, nd.b))
    return ids, kinds


def constraint_support(tape: HostTape):
    """Union of leaf supports over every path constraint."""
    ids, kinds = set(), set()
    for node, _ in tape.constraints:
        i, k = support(tape, node)
        ids.update(i)
        kinds.update(k)
    return ids, kinds


def cone(tape: HostTape, roots, storage_key_div: int = 0) -> set:
    """Node ids in the dependency cone of ``roots`` — the backward
    closure over the DAG (every node whose value can influence any
    root). ``storage_key_div`` is the account-table size ``A`` when the
    caller wants FREE(STORAGE) leaves traversed into their symbolic key
    node (the engine packs ``b = key_sym * A + account_slot``,
    ``symbolic/engine.py`` SLOAD-miss leaf) — which slot a storage read
    hits observably depends on the key, so taint flows through it."""
    nodes = tape.nodes
    n = len(nodes)
    leafish = (int(SymOp.CONST), int(SymOp.NULL), int(SymOp.FREE))
    storage = int(FreeKind.STORAGE)
    seen: set = set()
    stack = [int(r) for r in roots]
    while stack:
        i = stack.pop()
        if i in seen or i <= 0 or i >= n:
            continue
        seen.add(i)
        nd = nodes[i]
        if nd.op not in leafish:
            stack.extend((nd.a, nd.b))
        elif (storage_key_div and nd.op == int(SymOp.FREE)
                and nd.a == storage):
            stack.append(nd.b // storage_key_div)
    return seen


class AnnotationSpace:
    """Reference-parity annotation channel (``laser/smt`` wrappers carry
    an ``annotations`` set propagated through every operation ⚠unv,
    SURVEY.md §2.1 "SMT abstraction layer" — the mechanism taint
    analysis rides on). Here an expression IS its tape row, so the
    channel is a thin view over :func:`cone`: a tag attached at node t
    appears in ``annotations(x)`` exactly when t lies in x's dependency
    cone. Single reachability implementation — sink-semantics fixes in
    ``cone`` apply here automatically."""

    def __init__(self, tape: HostTape, storage_key_div: int = 0):
        self.tape = tape
        self.storage_key_div = storage_key_div
        self._own: dict = {}
        self._cones: dict = {}

    def annotate(self, node: int, tag) -> None:
        self._own.setdefault(int(node), set()).add(tag)

    def _cone_of(self, node: int) -> set:
        c = self._cones.get(node)
        if c is None:
            c = cone(self.tape, [node], self.storage_key_div)
            self._cones[node] = c
        return c

    def annotations(self, node: int) -> frozenset:
        c = self._cone_of(int(node))
        out = set()
        for t, tags in self._own.items():
            if t in c:
                out |= tags
        return frozenset(out)

    def any_sink(self, sinks, tag) -> bool:
        """Does `tag` reach any node id in `sinks`?"""
        c = cone(self.tape, [int(s) for s in sinks], self.storage_key_div)
        return any(tag in tags and t in c for t, tags in self._own.items())


# tx.origin IS the attacker EOA: every symbolic transaction originates
# from the ATTACKER actor (reference: symbolic tx setup constrains origin
# to the attacker/creator pair ⚠unv, SURVEY §3.2), so a value sink keyed
# on ORIGIN is attacker-directed — e.g. the config-4 vault's ``sweep()``
# paying out to tx.origin at call depth 3.
ATTACKER_KINDS = {
    int(FreeKind.CALLDATA_WORD), int(FreeKind.CALLDATASIZE),
    int(FreeKind.CALLVALUE), int(FreeKind.CALLER),
    int(FreeKind.ORIGIN),
}


def attacker_controlled(tape: HostTape, root: int) -> bool:
    """Does `root` depend on tx inputs the attacker chooses?"""
    _, kinds = support(tape, root)
    return bool(kinds & ATTACKER_KINDS)


def keccak_derived(tape: HostTape, root: int) -> bool:
    """Does `root`'s value flow through a KECCAK digest? (A storage key
    that is a hash of something is solidity mapping access, not an
    arbitrary-write primitive.)"""
    seen, stack = set(), [root]
    while stack:
        i = stack.pop()
        if i in seen or i <= 0 or i >= len(tape.nodes):
            continue
        seen.add(i)
        nd = tape.nodes[i]
        if nd.op == int(SymOp.KECCAK):
            return True
        if nd.op not in (int(SymOp.CONST), int(SymOp.NULL), int(SymOp.FREE)):
            stack.extend((nd.a, nd.b))
    return False


class TapeHostCache:
    """One bulk device->host copy of the tape + constraint arrays.

    Per-lane ``extract_tape`` used to slice device arrays element-wise —
    hundreds of device round-trips PER LANE, which measured as ~90% of
    ``fire_lasers`` wall time on a 1024-lane analyze. Build one of these
    per finished frontier and thread it through."""

    def __init__(self, sf):
        self.tape_len = np.asarray(sf.tape_len)
        self.tape_op = np.asarray(sf.tape_op)
        self.tape_a = np.asarray(sf.tape_a)
        self.tape_b = np.asarray(sf.tape_b)
        self.tape_imm = np.asarray(sf.tape_imm)
        self.con_len = np.asarray(sf.con_len)
        self.con_node = np.asarray(sf.con_node)
        self.con_sign = np.asarray(sf.con_sign)
        self.con_pc = np.asarray(sf.con_pc)


def extract_tape(sf, lane: int, extra_constraints=(),
                 cache: "TapeHostCache | None" = None) -> HostTape:
    """Materialize lane `lane` of a SymFrontier as a HostTape."""
    c = cache if cache is not None else TapeHostCache(sf)
    n = int(c.tape_len[lane])
    ops = c.tape_op[lane, :n]
    a = c.tape_a[lane, :n]
    b = c.tape_b[lane, :n]
    imm = c.tape_imm[lane, :n]
    nodes = [
        HostNode(int(ops[i]), int(a[i]), int(b[i]), u256.to_int(imm[i]))
        for i in range(n)
    ]
    cn = int(c.con_len[lane])
    cons = [
        (int(c.con_node[lane, i]), bool(c.con_sign[lane, i]))
        for i in range(cn)
    ]
    pcs = [int(c.con_pc[lane, i]) for i in range(cn)]
    cons.extend(extra_constraints)
    return HostTape(nodes=nodes, constraints=cons, pcs=pcs)
