"""Host-side view of one lane's SSA tape.

Pulls the device arrays for a single lane into plain Python structures so
the solver can walk them without touching JAX. This is the boundary where
the reference would hold Z3 ASTs; here an expression IS its tape row.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Tuple

import numpy as np

from ..ops import u256
from ..symbolic.ops import SymOp, FreeKind


@dataclass(frozen=True)
class HostNode:
    op: int
    a: int
    b: int
    imm: int  # u256 immediate as a Python int


@dataclass
class HostTape:
    nodes: List[HostNode]           # index = node id; [0] is concrete zero
    constraints: List[Tuple[int, bool]]  # (node id, asserted sign)


def extract_tape(sf, lane: int, extra_constraints=()) -> HostTape:
    """Materialize lane `lane` of a SymFrontier as a HostTape."""
    n = int(sf.tape_len[lane])
    ops = np.asarray(sf.tape_op[lane, :n])
    a = np.asarray(sf.tape_a[lane, :n])
    b = np.asarray(sf.tape_b[lane, :n])
    imm = np.asarray(sf.tape_imm[lane, :n])
    nodes = [
        HostNode(int(ops[i]), int(a[i]), int(b[i]), u256.to_int(imm[i]))
        for i in range(n)
    ]
    cn = int(sf.con_len[lane])
    cons = [
        (int(sf.con_node[lane, i]), bool(sf.con_sign[lane, i]))
        for i in range(cn)
    ]
    cons.extend(extra_constraints)
    return HostTape(nodes=nodes, constraints=cons)
