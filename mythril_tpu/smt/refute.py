"""Unsat proofs for host tapes (VERDICT r3 ask #4a/4b).

The witness search (``smt/solver.py``) can only ever answer sat-or-
unknown; every `unknown` is a potential silent false negative. This
module proves the easy majority of genuinely-unsat queries — EVM path
conditions are dominated by dispatcher selector EQs and require()-style
comparisons over injective chains of one free leaf — by FORCED-VALUE
propagation:

- every constraint is reduced (through chains of injective ops: ADD,
  SUB, XOR, NOT, odd MUL, and the boolean EQ/ISZERO structure) to facts
  about a single free LEAF: ``leaf == v``, ``leaf != v``, or an interval
  bound when the leaf is compared bare;
- facts are merged per leaf; any contradiction (two different forced
  values, a forced value that is forbidden or out of bounds, an empty
  interval, or a closed constraint evaluating false) is an UNSAT proof.

This is the analog of the reference's unsat verdicts from Z3
(``laser/smt/solver`` ⚠unv, SURVEY §2.2) for the structural fragment;
anything it cannot decide stays with the randomized search.
"""

from __future__ import annotations

from typing import Dict, Optional, Set, Tuple

from ..symbolic.ops import SymOp, FreeKind
from .eval import Assignment, M256, evaluate
from .tape import HostTape

_INJECTIVE = (int(SymOp.ADD), int(SymOp.SUB), int(SymOp.XOR),
              int(SymOp.NOT), int(SymOp.MUL))


def _free_reach(tape: HostTape):
    hf = [False] * len(tape.nodes)
    for i, nd in enumerate(tape.nodes):
        if i == 0 or nd.op == int(SymOp.NULL):
            continue
        if nd.op == int(SymOp.FREE):
            hf[i] = True
        elif nd.op != int(SymOp.CONST):
            hf[i] = (nd.a and nd.a < i and hf[nd.a]) or \
                    (nd.b and nd.b < i and hf[nd.b])
    return hf


def _reduce_to_leaf(tape, vals, hf, i: int, target: int
                    ) -> Optional[Tuple[int, int]]:
    """Solve f(leaf) == target where f is a chain of INJECTIVE ops with
    exactly one free side per node. Returns (leaf_node, forced_value) or
    None. Injectivity matters: the caller also uses the result negated
    (f(leaf) != target  <=>  leaf != forced_value)."""
    target &= M256
    while True:
        nd = tape.nodes[i]
        if nd.op == int(SymOp.FREE):
            return i, target
        a, b = nd.a, nd.b
        a_free = bool(a) and hf[a]
        b_free = bool(b) and hf[b]
        if a_free and b_free:
            return None
        av = vals[a] if a else 0
        bv = vals[b] if b else 0
        op = nd.op
        if op == int(SymOp.ADD):
            i, target = (a, target - bv) if a_free else (b, target - av)
        elif op == int(SymOp.SUB):
            i, target = (a, target + bv) if a_free else (b, av - target)
        elif op == int(SymOp.XOR):
            i, target = (a, target ^ bv) if a_free else (b, target ^ av)
        elif op == int(SymOp.NOT):
            i, target = a, target ^ M256
        elif op == int(SymOp.MUL):
            c, x = (bv, a) if a_free else (av, b)
            if not (c & 1):
                return None
            i, target = x, target * pow(c, -1, 1 << 256)
        else:
            return None
        target &= M256


class _Facts:
    """Per-leaf merged facts; raises _Conflict on contradiction."""

    def __init__(self):
        self.eq: Dict[int, int] = {}
        self.neq: Dict[int, Set[int]] = {}
        self.lo: Dict[int, int] = {}
        self.hi: Dict[int, int] = {}

    def force(self, leaf: int, v: int) -> bool:
        if leaf in self.eq and self.eq[leaf] != v:
            return False
        if v in self.neq.get(leaf, ()):
            return False
        if not (self.lo.get(leaf, 0) <= v <= self.hi.get(leaf, M256)):
            return False
        self.eq[leaf] = v
        return True

    def forbid(self, leaf: int, v: int) -> bool:
        if self.eq.get(leaf) == v:
            return False
        self.neq.setdefault(leaf, set()).add(v)
        return True

    def bound(self, leaf: int, lo: Optional[int] = None,
              hi: Optional[int] = None) -> bool:
        if lo is not None:
            self.lo[leaf] = max(self.lo.get(leaf, 0), lo)
        if hi is not None:
            self.hi[leaf] = min(self.hi.get(leaf, M256), hi)
        l, h = self.lo.get(leaf, 0), self.hi.get(leaf, M256)
        if l > h:
            return False
        if leaf in self.eq and not (l <= self.eq[leaf] <= h):
            return False
        # a pinched interval whose every value is forbidden is empty
        if h - l < 8 and all(v in self.neq.get(leaf, ())
                             for v in range(l, h + 1)):
            return False
        return True


def refute_tape(tape: HostTape) -> Optional[str]:
    """Return a human-readable unsat reason if the tape's constraint set
    is PROVABLY unsatisfiable, else None (decide nothing)."""
    if not tape.constraints:
        return None
    # direct polarity conflict on one node
    signs: Dict[int, bool] = {}
    for node, sign in tape.constraints:
        if node in signs and signs[node] != bool(sign):
            return f"node {node} asserted both true and false"
        signs[node] = bool(sign)

    hf = _free_reach(tape)
    vals = evaluate(tape, Assignment())
    facts = _Facts()
    for node, sign in tape.constraints:
        if node <= 0 or node >= len(tape.nodes):
            continue
        if not hf[node]:
            # closed constraint: its value is assignment-independent
            if bool(vals[node]) != bool(sign):
                return f"closed constraint at node {node} is false"
            continue
        if not _apply(tape, vals, hf, facts, node, bool(sign)):
            return f"conflicting facts at constraint node {node}"
    return None


def _apply(tape, vals, hf, facts: _Facts, i: int, want: bool) -> bool:
    """Derive leaf facts from `node i must evaluate truthy == want`.
    Returns False ONLY on a proven conflict (unknown structure -> True)."""
    nd = tape.nodes[i]
    op = nd.op
    a, b = nd.a, nd.b
    a_free = bool(a) and hf[a]
    b_free = bool(b) and hf[b]

    if op == int(SymOp.ISZERO):
        # ISZERO(a) truthy <=> a == 0
        red = _reduce_to_leaf(tape, vals, hf, a, 0)
        if red is None:
            return True
        leaf, v = red
        return facts.force(leaf, v) if want else facts.forbid(leaf, v)

    if op == int(SymOp.EQ):
        if a_free and b_free:
            return True
        free, const = (a, vals[b] if b else 0) if a_free else (b, vals[a] if a else 0)
        red = _reduce_to_leaf(tape, vals, hf, free, const)
        if red is None:
            return True
        leaf, v = red
        return facts.force(leaf, v) if want else facts.forbid(leaf, v)

    if op in (int(SymOp.LT), int(SymOp.GT)):
        if a_free and b_free:
            return True
        # interval facts only for a BARE free leaf (arith chains wrap mod
        # 2^256, so monotone reasoning through them would be unsound)
        free, const = (a, vals[b] if b else 0) if a_free else (b, vals[a] if a else 0)
        if tape.nodes[free].op != int(SymOp.FREE):
            return True
        leaf_lt = (op == int(SymOp.LT)) == a_free  # "leaf < const" form?
        if leaf_lt and want:          # leaf < const
            if const == 0:
                return False
            return facts.bound(free, hi=const - 1)
        if leaf_lt and not want:      # leaf >= const
            return facts.bound(free, lo=const)
        if want:                      # leaf > const
            if const == M256:
                return False
            return facts.bound(free, lo=const + 1)
        return facts.bound(free, hi=const)  # leaf <= const

    # a bare free leaf used directly as a branch condition
    if op == int(SymOp.FREE):
        return facts.forbid(i, 0) if want else facts.force(i, 0)

    # AND of two boolean-ish sides asserted true forces both sides
    if op == int(SymOp.AND) and want:
        ok = True
        if a_free:
            ok = ok and _apply(tape, vals, hf, facts, a, True)
        if b_free and ok:
            ok = ok and _apply(tape, vals, hf, facts, b, True)
        return ok

    return True
