"""Witness search: inversion heuristics + randomized repair.

``Solver`` keeps the reference front-door shape
(``laser/smt/solver/solver.py``: add / check / model ⚠unv) but the
engine is different: EVM path conditions are overwhelmingly chains of
(keccak | calldata-window | const) compared through EQ/LT/GT/ISZERO, so a
directed inversion pass (solve EQ(f(leaf), const) by inverting f) settles
the dispatcher/require structure, and a bounded randomized repair loop
mops up the rest. Returns unknown (not unsat) when search fails — same
degrade-to-no-issue semantics as the reference's solver timeout
(SURVEY.md §5.3).
"""

from __future__ import annotations

import os
import random
import threading
import time
from collections import OrderedDict
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..obs import metrics as obs_metrics
from ..symbolic.ops import SymOp, FreeKind
from .eval import Assignment, M256, evaluate
from .tape import HostTape


class UnsatError(Exception):
    """No witness found (unsat OR search exhausted — like a Z3 timeout)."""


@dataclass
class SolverStatistics:
    """Run counters for the witness search (reference:
    ``laser/smt/solver/solver_statistics.py`` ⚠unv, SURVEY.md §5.1).
    ``unknown`` is the silent false-negative channel (VERDICT r2 weak #3):
    every query that returns None and therefore drops a candidate finding
    is counted here, so the undecided rate is observable in the report."""

    attempts: int = 0
    sat: int = 0
    unsat: int = 0
    unknown: int = 0
    cache_hits: int = 0
    time_sec: float = 0.0
    partitioned: int = 0  # queries split into >1 independent cluster

    #: class-level (not a dataclass field — snapshot() builds positionally);
    #: only the process singleton records, so sharing one lock is fine
    _lock = threading.Lock()

    def record(self, verdict: str, dt: float, cached: bool = False) -> None:
        # lock, not bare +=: --parallel-solving runs module threads that
        # record concurrently, and a torn read-modify-write would leak
        # counts exactly where the unknown-rate observable matters
        with self._lock:
            self.attempts += 1
            if verdict == "sat":
                self.sat += 1
            elif verdict == "unsat":
                self.unsat += 1
            else:
                self.unknown += 1
            if cached:
                self.cache_hits += 1
            self.time_sec += dt

    def reset(self) -> None:
        self.attempts = self.sat = self.unsat = self.unknown = 0
        self.cache_hits = 0
        self.time_sec = 0.0
        self.partitioned = 0

    def snapshot(self) -> "SolverStatistics":
        return SolverStatistics(self.attempts, self.sat, self.unsat,
                                self.unknown, self.cache_hits, self.time_sec,
                                self.partitioned)

    def delta(self, since: "SolverStatistics") -> dict:
        return {
            "attempts": self.attempts - since.attempts,
            "sat": self.sat - since.sat,
            "unsat": self.unsat - since.unsat,
            "unknown": self.unknown - since.unknown,
            "cache_hits": self.cache_hits - since.cache_hits,
            "partitioned": self.partitioned - since.partitioned,
            "time_sec": round(self.time_sec - since.time_sec, 3),
        }

    def as_dict(self) -> dict:
        return {
            "attempts": self.attempts, "sat": self.sat, "unsat": self.unsat,
            "unknown": self.unknown, "cache_hits": self.cache_hits,
            "partitioned": self.partitioned,
            "time_sec": round(self.time_sec, 3),
        }


#: process-wide statistics (the reference uses a singleton too)
SOLVER_STATS = SolverStatistics()


def _dump_unknown(tape: HostTape) -> None:
    """Residue collection (VERDICT r4 ask #3): with
    ``MYTHRIL_DUMP_UNKNOWN=<dir>`` every query the search gives up on is
    serialized for offline analysis — the evidence base for deciding
    which inverter/refuter extension actually shrinks the unknown rate."""
    import os

    d = os.environ.get("MYTHRIL_DUMP_UNKNOWN")
    if not d:
        return
    try:
        import json
        import uuid

        os.makedirs(d, exist_ok=True)
        doc = {
            "nodes": [[nd.op, nd.a, nd.b, hex(nd.imm)]
                      for nd in tape.nodes],
            "constraints": [[int(n), bool(s)] for n, s in tape.constraints],
        }
        with open(os.path.join(d, f"unknown_{uuid.uuid4().hex[:12]}.json"),
                  "w") as fh:
            json.dump(doc, fh)
    except Exception:  # noqa: BLE001 — diagnostics must never kill a run
        pass


_INTERESTING = (0, 1, 2, 0xFF, 1 << 31, 1 << 128, M256, M256 - 1, 1 << 255)


def _sat_vector(tape: HostTape, vals: List[int]) -> List[bool]:
    return [bool(vals[n]) == sign for n, sign in tape.constraints]


class _Inverter:
    """Solve f(leaf) == target for supported op chains."""

    def __init__(self, tape: HostTape, vals: List[int]):
        self.tape = tape
        self.vals = vals
        # SSA order (children precede parents): one linear bottom-up pass
        # decides free-variable reachability for every node — recursion on
        # the shared DAG would blow up exponentially
        hf = [False] * len(tape.nodes)
        for i, nd in enumerate(tape.nodes):
            if i == 0 or nd.op == int(SymOp.NULL):
                continue
            if nd.op == int(SymOp.FREE):
                hf[i] = True
            elif nd.op not in (int(SymOp.CONST),):
                hf[i] = (nd.a and nd.a < i and hf[nd.a]) or (nd.b and nd.b < i and hf[nd.b])
        self._has_free = hf

    def has_free(self, i: int) -> bool:
        return bool(self._has_free[i]) if 0 <= i < len(self._has_free) else False

    def apply(self, i: int, target: int, asn: Assignment) -> bool:
        """Try to force node i to value `target` by editing `asn`."""
        target &= M256
        nd = self.tape.nodes[i]
        op = nd.op
        if op == int(SymOp.FREE):
            return self._set_leaf(i, nd, target, asn)
        a, b = nd.a, nd.b
        av, bv = self.vals[a] if a else 0, self.vals[b] if b else 0
        a_free, b_free = (a and self.has_free(a)), (b and self.has_free(b))
        if a_free and b_free:
            return False  # both sides free: out of scope for inversion
        if op == int(SymOp.ADD):
            return self.apply(a, target - bv, asn) if a_free else self.apply(b, target - av, asn)
        if op == int(SymOp.SUB):
            return self.apply(a, target + bv, asn) if a_free else self.apply(b, av - target, asn)
        if op == int(SymOp.XOR):
            return self.apply(a, target ^ bv, asn) if a_free else self.apply(b, target ^ av, asn)
        if op == int(SymOp.NOT):
            return self.apply(a, target ^ M256, asn)
        if op == int(SymOp.MUL):
            c, x = (bv, a) if a_free else (av, b)
            if c & 1:  # odd constants are invertible mod 2^256
                inv = pow(c, -1, 1 << 256)
                return self.apply(x, (target * inv) & M256, asn)
            return False
        if op == int(SymOp.DIV) and a_free:
            # a // c == target: pick a = target * c (representative)
            if bv and target * bv <= M256:
                return self.apply(a, target * bv, asn)
            return False
        if op == int(SymOp.SHR) and b_free:
            # b >> k == target
            k = av
            if k < 256 and (target << k) <= M256:
                return self.apply(b, target << k, asn)
            return False
        if op == int(SymOp.SHL) and b_free:
            k = av
            if k < 256 and (target & ((1 << k) - 1)) == 0:
                return self.apply(b, target >> k, asn)
            return False
        if op == int(SymOp.AND) and (a_free != b_free):
            x = a if a_free else b
            mask = bv if a_free else av
            if target & ~mask & M256:
                return False
            return self.apply(x, target, asn)
        if op == int(SymOp.ISZERO):
            if target == 1:
                return self.apply(a, 0, asn)
            if target == 0 and a:
                # need a != 0; try 1 (works for bool-ish and value chains)
                return self.apply(a, 1, asn)
            return False
        if op == int(SymOp.EQ):
            if target == 1:
                return self.apply(a, bv, asn) if a_free else self.apply(b, av, asn)
            if target == 0:
                x, other = (a, bv) if a_free else (b, av)
                return self.apply(x, (other + 1) & M256, asn)
            return False
        if op in (int(SymOp.LT), int(SymOp.GT)):
            lt = op == int(SymOp.LT)
            want_true = target == 1
            const = bv if a_free else av
            x = a if a_free else b
            # strictly-below cases: LT(a<const) wanting true with a free,
            # or GT(const>b) wanting true with b free; the negations allow
            # equality, where `const` itself is a valid choice.
            strictly_below = want_true and (lt == a_free)
            strictly_above = want_true and (lt != a_free)
            if strictly_below:
                if const == 0:
                    return False
                return self.apply(x, const - 1, asn)
            if strictly_above:
                if const == M256:
                    return False
                return self.apply(x, const + 1, asn)
            return self.apply(x, const, asn)  # non-strict: equality suffices
        return False

    def _set_leaf(self, node_id: int, nd, target: int, asn: Assignment) -> bool:
        return _assign_leaf(node_id, nd, target, asn)


def _assign_leaf(node_id: int, nd, target: int, asn: Assignment) -> bool:
    from .eval import TX_STRIDE

    kind = nd.a
    if kind == int(FreeKind.CALLDATA_WORD):
        asn.tx(nd.b // TX_STRIDE).write_word(nd.b % TX_STRIDE, target)
        return True
    if kind == int(FreeKind.CALLER):
        asn.tx(nd.b).caller = target
        return True
    if kind == int(FreeKind.CALLVALUE):
        asn.tx(nd.b).callvalue = target
        return True
    if kind == int(FreeKind.CALLDATASIZE):
        asn.tx(nd.b).calldatasize = target
        return True
    from .eval import BY_NODE_KINDS

    if kind in BY_NODE_KINDS:
        asn.by_node[node_id] = target
        return True
    asn.scalars[(kind, nd.b)] = target
    return True


def _leaf_support(tape: HostTape, root: int) -> List[int]:
    out, seen, stack = [], set(), [root]
    while stack:
        i = stack.pop()
        if i in seen or i <= 0 or i >= len(tape.nodes):
            continue
        seen.add(i)
        nd = tape.nodes[i]
        if nd.op == int(SymOp.FREE):
            out.append(i)
        else:
            stack.extend((nd.a, nd.b))
    return out


# --- independence partitioning (reference: IndependenceSolver,
# ``laser/smt/solver/independence_solver.py`` ⚠unv, SURVEY §2.1 "SMT
# solvers" — "partitions constraint set into independent clusters
# (shared-variable union-find) and solves separately — the reference's
# main solver optimization"). Here independence is computed at the
# ASSIGNMENT-KEY granularity, not the node granularity: two distinct
# CALLDATA_WORD leaves whose 32-byte windows overlap mutate the same
# underlying tx bytes, so they must share a cluster even though their
# node ids differ.

def _leaf_keys(tape: HostTape, leaves: List[int], cds_txs: frozenset) -> set:
    """Assignment-granular variable keys touched by `leaves`. Calldata
    words expand to their byte windows; when tx ``t``'s CALLDATASIZE is
    constrained somewhere (``t in cds_txs``), every calldata read of tx
    ``t`` couples to it (reads zero-pad past the chosen size, see
    ``TxInput.read_word``). ORIGIN aliases CALLER(tx0) — the evaluator
    defaults an unassigned origin to ``asn.caller`` — so ORIGIN leaves
    carry the caller key too."""
    from .eval import BY_NODE_KINDS, TX_STRIDE

    keys = set()
    for i in leaves:
        nd = tape.nodes[i]
        kind, b = nd.a, nd.b
        if kind == int(FreeKind.CALLDATA_WORD):
            tx, off = divmod(b, TX_STRIDE)
            keys.update(("cd", tx, off + k) for k in range(32))
            if tx in cds_txs:
                keys.add((int(FreeKind.CALLDATASIZE), tx))
        elif kind in BY_NODE_KINDS:
            keys.add(("n", i))  # keyed by node id in Assignment.by_node
        elif kind == int(FreeKind.ORIGIN):
            keys.add((kind, b))
            keys.add((int(FreeKind.CALLER), 0))  # default-aliases tx0 caller
        else:
            keys.add((kind, b))  # caller/callvalue/cds/env scalars
    return keys


def partition_constraints(tape: HostTape) -> List[List[int]]:
    """Constraint indices grouped into independent clusters (union-find
    over shared assignment keys). Constraints over no free variables are
    singleton clusters — they evaluate concretely."""
    n = len(tape.constraints)
    if n <= 1:
        return [list(range(n))] if n else []
    supports = [_leaf_support(tape, node) for node, _ in tape.constraints]
    # couple tx t's calldata reads to its CALLDATASIZE only when some
    # constraint actually mentions THAT tx's cds: the tape pre-seeds an
    # (unconstrained) cds node, and an unconstrained cds is never
    # assigned by the search, so reads keep their default zero-padding
    # regardless of cluster order
    cds_txs = frozenset(
        tape.nodes[i].b
        for sup in supports for i in sup
        if tape.nodes[i].a == int(FreeKind.CALLDATASIZE))
    parent = list(range(n))

    def find(x: int) -> int:
        while parent[x] != x:
            parent[x] = parent[parent[x]]
            x = parent[x]
        return x

    owner: Dict[tuple, int] = {}
    for j in range(n):
        for k in _leaf_keys(tape, supports[j], cds_txs):
            if k in owner:
                ra, rb = find(j), find(owner[k])
                if ra != rb:
                    parent[rb] = ra
            else:
                owner[k] = j
    clusters: Dict[int, List[int]] = {}
    for j in range(n):
        clusters.setdefault(find(j), []).append(j)
    return list(clusters.values())


def _solve_partitioned(tape: HostTape, seed: int, max_iters: int,
                       base: Optional[Assignment],
                       deadline: Optional[float] = None
                       ) -> Tuple[str, Optional[Assignment]]:
    """Split the query into independent clusters and solve each with the
    FULL search budget (smaller supports decide in far fewer iterations,
    and a miss in one cluster can't thrash another's solved variables).
    Clusters chain through one accumulating assignment — their key sets
    are disjoint, so later solves cannot disturb earlier ones."""
    clusters = partition_constraints(tape)
    if len(clusters) <= 1:
        out = _solve_tape_inner(tape, seed, max_iters, base, deadline)
        return ("sat" if out is not None else "unknown"), out
    with SOLVER_STATS._lock:  # parallel-solving threads race this too
        SOLVER_STATS.partitioned += 1
    asn = base.copy() if base is not None else Assignment()
    for cl in clusters:
        sub = HostTape(nodes=tape.nodes,
                       constraints=[tape.constraints[j] for j in cl])
        res = _solve_tape_inner(sub, seed, max_iters, base=asn,
                                deadline=deadline)
        if res is None:
            # (a cluster over NO free variables can't reach here: a
            # concretely-false closed constraint is proven unsat by
            # refute_tape before partitioning runs)
            return "unknown", None
        asn = res
    # safety net: the merged model must satisfy the WHOLE tape; a
    # violation means a dependence the keys missed — fall back to the
    # unpartitioned search rather than return a bogus model
    vals = evaluate(tape, asn)
    if all(bool(vals[n]) == s for n, s in tape.constraints):
        return "sat", asn
    out = _solve_tape_inner(tape, seed, max_iters, base, deadline)
    return ("sat" if out is not None else "unknown"), out


def _mutate_leaf(tape: HostTape, leaf: int, asn: Assignment, rng: random.Random):
    nd = tape.nodes[leaf]
    v = rng.choice(_INTERESTING) if rng.random() < 0.6 else rng.getrandbits(256)
    _assign_leaf(leaf, nd, v, asn)


#: memoized solve front door (reference: ``support/model.py get_model``'s
#: lru cache ⚠unv, SURVEY §2 "Model cache"). Key = CANONICAL constraint
#: hash (``smt/canon.py`` — alpha-renamed repeats from cloned bytecode
#: share one entry; pre-portfolio this was the raw structural
#: fingerprint, see docs/solver.md) + search budget; a TRUE LRU (hits
#: refresh recency) capped at ``_SOLVE_CACHE_CAP`` so a 10k-contract
#: campaign — whose dispatcher queries recur heavily within a batch but
#: churn across the corpus — keeps the hot working set without growing
#: without bound. Values are ``(verdict, canonical witness doc | None)``
#: — sat witnesses travel in renaming-independent coordinates and are
#: rehydrated + re-verified per hit by ``smt/portfolio.py``. Caching
#: `unknown` is safe because the budget is in the key. The cap is
#: configurable via :func:`set_solve_cache_cap` or the
#: ``MYTHRIL_SOLVE_CACHE_CAP`` env var (0 disables caching); size and
#: eviction totals are published as ``solver_cache_size`` /
#: ``solver_cache_evictions_total`` in the metrics registry.
_SOLVE_CACHE: "OrderedDict[tuple, Tuple[str, Optional[dict]]]" = \
    OrderedDict()
_SOLVE_CACHE_CAP = int(os.environ.get("MYTHRIL_SOLVE_CACHE_CAP", "") or 8192)
_SOLVE_CACHE_LOCK = threading.Lock()


def set_solve_cache_cap(cap: int) -> int:
    """Set the solve-cache entry cap (evicting down immediately);
    returns the previous cap. 0 disables memoization."""
    global _SOLVE_CACHE_CAP
    prev = _SOLVE_CACHE_CAP
    _SOLVE_CACHE_CAP = max(0, int(cap))
    with _SOLVE_CACHE_LOCK:
        _cache_evict_locked()
    return prev


def _cache_evict_locked() -> None:
    """Evict oldest entries down to the cap; callers hold the lock.
    Publishes the size gauge + eviction counter on every mutation."""
    evicted = 0
    while len(_SOLVE_CACHE) > _SOLVE_CACHE_CAP:
        _SOLVE_CACHE.popitem(last=False)
        evicted += 1
    if evicted:
        obs_metrics.REGISTRY.counter(
            "solver_cache_evictions_total",
            help="LRU evictions from the solve memo cache").inc(evicted)
    obs_metrics.REGISTRY.gauge(
        "solver_cache_size",
        help="entries in the solve memo cache").set(len(_SOLVE_CACHE))


def solve_tape_ex(tape: HostTape, seed: int = 0, max_iters: int = 400,
                  base: Optional[Assignment] = None,
                  max_time: Optional[float] = None
                  ) -> Tuple[str, Optional[Assignment]]:
    """(verdict, assignment) with verdict in {"sat", "unsat", "unknown"}.

    Front door over the staged solver portfolio (``smt/portfolio.py``,
    docs/solver.md): canonical-hash LRU → structural refutation →
    model probe → durable cross-campaign verdict store → the witness
    search below. Proven UNSAT is recorded distinctly from
    search-exhausted UNKNOWN in ``SOLVER_STATS`` (VERDICT r3 ask #4);
    per-stage attempt/hit/latency lands in
    ``portfolio.PORTFOLIO_STATS`` and the metrics registry.
    ``base``-seeded queries skip every cache (the seed assignment is an
    input the canonical hash does not cover) and run refute → probe →
    search only. ``max_time`` is a per-query wall-clock budget in
    seconds (reference: ``--solver-timeout`` ms ⚠unv) checked between
    repair iterations; expiry returns unknown — same
    degrade-to-no-issue semantics as an exhausted iteration budget —
    and is never cached."""
    from .portfolio import solve_query

    return solve_query(tape, seed=seed, max_iters=max_iters, base=base,
                       max_time=max_time)


def solve_tape(tape: HostTape, seed: int = 0, max_iters: int = 400,
               base: Optional[Assignment] = None,
               max_time: Optional[float] = None) -> Optional[Assignment]:
    """Find an assignment satisfying every tape constraint, or None."""
    return solve_tape_ex(tape, seed, max_iters, base, max_time)[1]


def _solve_tape_inner(tape: HostTape, seed: int = 0, max_iters: int = 400,
                      base: Optional[Assignment] = None,
                      deadline: Optional[float] = None) -> Optional[Assignment]:
    rng = random.Random(seed)
    asn = base.copy() if base is not None else Assignment()
    vals = evaluate(tape, asn)

    # pass 1: directed inversion, weakest constraints first (EQ before
    # inequalities so dispatcher selectors land before bound nudging)
    order = sorted(
        range(len(tape.constraints)),
        key=lambda j: 0 if tape.nodes[tape.constraints[j][0]].op == int(SymOp.EQ) else 1,
    )
    for j in order:
        node, sign = tape.constraints[j]
        vals = evaluate(tape, asn)
        if bool(vals[node]) == sign:
            continue
        inv = _Inverter(tape, vals)
        inv.apply(node, 1 if sign else 0, asn)

    # pass 2: randomized repair (vals always reflects `asn`)
    vals = evaluate(tape, asn)
    sat = _sat_vector(tape, vals)
    if all(sat):
        return asn
    inv = _Inverter(tape, vals)
    for _ in range(max_iters):
        if deadline is not None and time.perf_counter() >= deadline:
            return None  # budget expired mid-search -> unknown
        unsat_idx = [j for j, ok in enumerate(sat) if not ok]
        if not unsat_idx:
            return asn
        j = rng.choice(unsat_idx)
        node, sign = tape.constraints[j]
        support = _leaf_support(tape, node)
        if not support:
            return None  # constraint over no free vars and unsat: dead
        cand = asn.copy()
        if rng.random() < 0.5:
            inv.vals = vals
            inv.apply(node, 1 if sign else 0, cand)
        else:
            _mutate_leaf(tape, rng.choice(support), cand, rng)
        cvals = evaluate(tape, cand)
        csat = _sat_vector(tape, cvals)
        if sum(csat) >= sum(sat):
            asn, sat, vals = cand, csat, cvals
            if all(sat):
                return asn
    return None


class Solver:
    """Reference-shaped front door: add constraints, check, get model."""

    def __init__(self, tape: HostTape, seed: int = 0, max_iters: int = 400,
                 max_time: Optional[float] = None):
        self.tape = HostTape(nodes=tape.nodes, constraints=list(tape.constraints))
        self.seed = seed
        self.max_iters = max_iters
        self.max_time = max_time
        self._model: Optional[Assignment] = None

    def add(self, node: int, sign: bool = True) -> None:
        self.tape.constraints.append((node, sign))

    def check(self) -> str:
        verdict, self._model = solve_tape_ex(self.tape, self.seed,
                                             self.max_iters,
                                             max_time=self.max_time)
        return verdict

    def model(self) -> Assignment:
        if self._model is None:
            raise UnsatError("no model (check() not sat)")
        return self._model


def solve_lane(sf, lane: int, extra_constraints=(), seed: int = 0,
               max_iters: int = 400, cache=None) -> Optional[Assignment]:
    """Witness for lane `lane`'s path condition + extra (node, sign)
    pairs. Pass a ``TapeHostCache`` when solving many lanes of one
    frontier — the cacheless default bulk-copies the tape arrays per
    call."""
    from .tape import extract_tape

    tape = extract_tape(sf, lane, extra_constraints, cache=cache)
    return solve_tape(tape, seed=seed, max_iters=max_iters)
