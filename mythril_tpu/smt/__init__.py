"""Host-side satisfiability layer (the ``mythril.laser.smt`` counterpart).

The reference wraps Z3 (``mythril/laser/smt/{bitvec,solver}`` ⚠unv,
SURVEY.md §2); this image has no Z3, so the stack is self-built:

- the EASY majority of checks is decided on-device by
  ``symbolic.propagate`` (interval abstract interpretation);
- the residue — "give me a concrete witness for this path + predicate" —
  is handled here by :class:`Solver`: tape extraction, exact Python
  evaluation (real keccak), constraint-inversion heuristics, and
  randomized repair search. ``check()``/``model()`` keep the reference's
  solver front-door shape (``support/model.py:get_model`` ⚠unv).
"""

from .tape import HostTape, HostNode, extract_tape
from .eval import Assignment, TxInput, evaluate
from .solver import Solver, UnsatError, solve_lane
from .canon import canonical_digest, canonical_query
from .vstore import VerdictStore

__all__ = [
    "HostTape", "HostNode", "extract_tape",
    "Assignment", "TxInput", "evaluate",
    "Solver", "UnsatError", "solve_lane",
    "canonical_digest", "canonical_query", "VerdictStore",
]
