"""Structural canonicalization of path-condition sets.

At 10k+ contract scale the corpus is dominated by proxy/clone bytecode,
so most solver queries are alpha-renamed repeats of queries some lane,
worker, or earlier campaign already answered: the same dispatcher EQ,
the same require() comparison, reached through a tape whose NODE IDS
differ (different lane history, different interning order, a dead
subexpression shifting every id). The raw ``(op, a, b, imm)``
fingerprint PR 4's solve memo keyed on sees every such variant as a new
query. This module computes a canonical content hash under which all of
them collapse to one key — the cache key of the in-process LRU and of
the durable cross-campaign verdict store (``smt/vstore.py``), and the
canonical constraint representation the zkEVM constraint-design survey
(arxiv 2510.05376, PAPERS.md) motivates for reusable constraint traces.

What the hash is invariant under:

- **node-id renaming** — hashes are computed structurally, bottom-up
  over the dependency cone of the constraint roots; absolute tape
  positions (and unreachable/dead nodes) never enter the digest;
- **constraint-set reordering** — per-constraint digests are sorted
  (and duplicates dropped: a constraint list is semantically a set)
  before the final digest;
- **commutative operand order** — ADD/MUL/EQ/AND/OR/XOR operands are
  sorted by sub-digest, so ``EQ(x, 5)`` and ``EQ(5, x)`` collide;
- **by-node variable naming** — leaves whose identity IS their node id
  (``eval.BY_NODE_KINDS``: storage/retval/havoc/...) get de-Bruijn-
  style indices assigned by first occurrence in a canonical traversal,
  order-independent across the constraint set.

What it deliberately does NOT abstract (soundness over hit rate):

- leaves with SEMANTIC indices (calldata byte windows, per-tx
  caller/callvalue, block env) keep ``(kind, index)`` verbatim —
  renaming a calldata offset changes which bytes overlap which window,
  which changes satisfiability;
- by-node leaves keep their ``(kind, b, imm)`` payload in the leaf
  label (a storage leaf's packed key/slot is identity, not a name);
- constants are normalized to their 256-bit value but never folded
  through operators — the canonicalizer must not have opinions the
  evaluator doesn't share.

Equal digests therefore imply a leaf bijection making the constraint
sets identical terms — alpha-equivalence — up to digest collision
(blake2b-128 per node, sha256 over the set). The one residual
ambiguity is de-Bruijn numbering across constraints whose round-0
digests tie (mutually symmetric constraints): those may hash UNEQUAL
across orderings — a missed dedupe, never a wrong hit. And because a
stored SAT verdict carries a model, every witness served off this hash
is re-verified against the querying tape by exact evaluation before it
is trusted (``smt/portfolio.py``), so even a digest collision cannot
produce a wrong sat model; unsat reuse leans on the digest alone.
"""

from __future__ import annotations

import hashlib
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple

from ..symbolic.ops import SymOp, FreeKind
from .eval import BY_NODE_KINDS, M256, Assignment, TxInput, evaluate
from .tape import HostTape

_COMMUTATIVE = frozenset((int(SymOp.ADD), int(SymOp.MUL), int(SymOp.EQ),
                          int(SymOp.AND), int(SymOp.OR), int(SymOp.XOR)))
_UNARY = frozenset((int(SymOp.ISZERO), int(SymOp.NOT), int(SymOp.KECCAK)))
_NO_CHILDREN = frozenset((int(SymOp.NULL), int(SymOp.CONST),
                          int(SymOp.FREE), int(SymOp.KECCAK_SEED)))


def _h(*parts) -> bytes:
    h = hashlib.blake2b(digest_size=16)
    for p in parts:
        h.update(p if isinstance(p, bytes) else str(p).encode())
        h.update(b"\x1f")
    return h.digest()


_ZERO = _h("c", 0)


def _leaf_base(nd) -> bytes:
    """Round-0 label of a FREE leaf. By-node leaves drop their node id
    (that is the name being canonicalized away) but keep kind + packed
    payload; everything else keeps its full semantic identity."""
    if nd.a in BY_NODE_KINDS:
        return _h("bn", nd.a, nd.b, nd.imm & M256)
    return _h("ix", nd.a, nd.b, nd.imm & M256)


def _reach(tape: HostTape) -> List[int]:
    """Dependency cone of every constraint root, as a sorted id list
    (children precede parents in SSA order, so a single ascending pass
    can hash bottom-up)."""
    nodes = tape.nodes
    n = len(nodes)
    seen = set()
    stack = [int(r) for r, _ in tape.constraints]
    while stack:
        i = stack.pop()
        if i in seen or i <= 0 or i >= n:
            continue
        seen.add(i)
        nd = nodes[i]
        op = nd.op
        if op in _NO_CHILDREN:
            continue
        if op == int(SymOp.KECCAK_ABS):
            if 0 < nd.a < i:
                stack.append(nd.a)
            if 0 < nd.b < i:
                stack.append(nd.b)
        elif op in _UNARY:
            if 0 < nd.a < i:
                stack.append(nd.a)
        else:
            if 0 < nd.a < i:
                stack.append(nd.a)
            if 0 < nd.b < i:
                stack.append(nd.b)
    return sorted(seen)


def _node_hashes(tape: HostTape, reach: List[int],
                 colors: Optional[Dict[int, bytes]]) -> Dict[int, bytes]:
    """Bottom-up structural digest per reachable node. ``colors``
    overrides the label of numbered by-node leaves (round 1); None
    uses the round-0 base labels throughout."""
    nodes = tape.nodes
    hs: Dict[int, bytes] = {}

    for i in reach:
        nd = nodes[i]
        op = nd.op

        def ch(j, i=i):
            # out-of-SSA refs and id 0 evaluate concretely to zero
            if j <= 0 or j >= i:
                return _ZERO
            return hs.get(j, _ZERO)

        if op == int(SymOp.NULL):
            hs[i] = _ZERO
        elif op == int(SymOp.CONST):
            hs[i] = _h("c", nd.imm & M256)
        elif op == int(SymOp.FREE):
            if colors is not None and i in colors:
                hs[i] = colors[i]
            else:
                hs[i] = _leaf_base(nd)
        elif op == int(SymOp.KECCAK_SEED):
            hs[i] = _h("ks", nd.imm)
        elif op == int(SymOp.KECCAK_ABS):
            # b == 0 means the absorbed word is the concrete imm
            w = ch(nd.b) if nd.b else _h("c", nd.imm & M256)
            hs[i] = _h("ka", ch(nd.a), w)
        elif op in _UNARY:
            hs[i] = _h(op, ch(nd.a))
        elif op in _COMMUTATIVE:
            a, b = ch(nd.a), ch(nd.b)
            if b < a:
                a, b = b, a
            hs[i] = _h(op, a, b)
        else:
            hs[i] = _h(op, ch(nd.a), ch(nd.b))
    return hs


def _number_leaves(tape: HostTape, order: List[int],
                   h0: Dict[int, bytes]) -> Dict[int, int]:
    """De-Bruijn numbering of by-node leaves: first occurrence in a
    canonical DFS over the constraints in ``order``. Traversal order
    within a node is the round-0 digest order used for hashing, so two
    alpha-variants walk their cones in lockstep."""
    nodes = tape.nodes
    var_of: Dict[int, int] = {}
    visited = set()
    for j in order:
        root = tape.constraints[j][0]
        stack = [int(root)]
        while stack:
            i = stack.pop()
            if i in visited or i <= 0 or i >= len(nodes):
                continue
            visited.add(i)
            nd = nodes[i]
            op = nd.op
            if op == int(SymOp.FREE):
                if nd.a in BY_NODE_KINDS and i not in var_of:
                    var_of[i] = len(var_of)
                continue
            if op in _NO_CHILDREN:
                continue
            if op in _UNARY:
                kids = [nd.a]
            elif op == int(SymOp.KECCAK_ABS):
                kids = [nd.a] + ([nd.b] if nd.b else [])
            elif op in _COMMUTATIVE:
                kids = sorted(
                    (k for k in (nd.a, nd.b)),
                    key=lambda k: h0.get(k, _ZERO) if 0 < k < i else _ZERO)
            else:
                kids = [nd.a, nd.b]
            # reversed push => left-to-right pop order
            for k in reversed(kids):
                if 0 < k < i:
                    stack.append(k)
    return var_of


@dataclass
class CanonicalQuery:
    """One query's canonical identity + the leaf-renaming dictionary
    needed to serialize/rehydrate witnesses in canonical coordinates."""

    digest: str                                  # sha256 hex (32 chars)
    var_of_node: Dict[int, int] = field(default_factory=dict)
    node_of_var: Dict[int, int] = field(default_factory=dict)
    n_constraints: int = 0


def canonical_query(tape: HostTape) -> CanonicalQuery:
    """Canonical content hash of the tape's constraint set (see module
    docstring for the invariances), plus the by-node leaf numbering."""
    if not tape.constraints:
        return CanonicalQuery(digest=hashlib.sha256(b"empty")
                              .hexdigest()[:32])
    reach = _reach(tape)
    h0 = _node_hashes(tape, reach, None)
    # canonical constraint order: round-0 digest breaks input order
    order = sorted(
        range(len(tape.constraints)),
        key=lambda j: (h0.get(int(tape.constraints[j][0]), _ZERO),
                       bool(tape.constraints[j][1])))
    var_of = _number_leaves(tape, order, h0)
    if var_of:
        nodes = tape.nodes
        colors = {i: _h("v", g, nodes[i].a, nodes[i].b,
                        nodes[i].imm & M256)
                  for i, g in var_of.items()}
        h1 = _node_hashes(tape, reach, colors)
    else:
        h1 = h0
    tokens = sorted({
        (h1.get(int(n), _ZERO), bool(s)) for n, s in tape.constraints})
    out = hashlib.sha256()
    out.update(str(len(var_of)).encode())
    for t, s in tokens:
        out.update(t)
        out.update(b"1" if s else b"0")
    return CanonicalQuery(
        digest=out.hexdigest()[:32],
        var_of_node=var_of,
        node_of_var={g: i for i, g in var_of.items()},
        n_constraints=len(tokens))


def canonical_digest(tape: HostTape) -> str:
    return canonical_query(tape).digest


# --- witness (de)hydration in canonical coordinates --------------------
#
# A SAT verdict is only reusable across alpha-variants if its model
# travels in renaming-independent coordinates: tx inputs and scalar env
# leaves are already semantic (same keys on every variant), by-node
# values are re-keyed through the de Bruijn numbering. JSON-safe so the
# verdict store can persist it.

def witness_to_doc(asn: Assignment, canon: CanonicalQuery) -> Dict:
    txs = []
    for t in asn.txs:
        txs.append({"cd": bytes(t.calldata).hex(),
                    "cds": t.calldatasize,
                    "cl": int(t.caller), "cv": int(t.callvalue)})
    return {
        "txs": txs,
        "scalars": {f"{int(k)}:{int(i)}": int(v)
                    for (k, i), v in asn.scalars.items()},
        # values whose node has no var id cannot influence the hashed
        # constraint cone; dropping them loses nothing the verifier sees
        "vars": {str(canon.var_of_node[int(n)]): int(v)
                 for n, v in asn.by_node.items()
                 if int(n) in canon.var_of_node},
    }


def witness_from_doc(tape: HostTape, canon: CanonicalQuery,
                     doc: Dict) -> Optional[Assignment]:
    """Rehydrate a canonical witness onto ``tape``'s coordinates, or
    None if the document is malformed. Callers MUST :func:`witness_ok`
    the result before serving it — rehydration trusts nothing."""
    try:
        asn = Assignment(txs=[])
        for t in doc.get("txs") or []:
            cds = t.get("cds")
            asn.txs.append(TxInput(
                bytearray(bytes.fromhex(t["cd"])),
                int(cds) if cds is not None else None,
                int(t["cl"]), int(t["cv"])))
        if not asn.txs:
            asn.txs.append(TxInput())
        for key, v in (doc.get("scalars") or {}).items():
            k, i = key.split(":")
            asn.scalars[(int(k), int(i))] = int(v)
        for g, v in (doc.get("vars") or {}).items():
            node = canon.node_of_var.get(int(g))
            if node is not None:
                asn.by_node[node] = int(v)
        return asn
    except (KeyError, ValueError, TypeError, AttributeError):
        return None


def witness_ok(tape: HostTape, asn: Assignment) -> bool:
    """Exact check: does ``asn`` satisfy EVERY tape constraint? One
    (native-evaluator) pass — the guard that makes hash-keyed sat reuse
    collision-proof."""
    vals = evaluate(tape, asn)
    return all(bool(vals[int(n)]) == bool(s) for n, s in tape.constraints)


__all__ = ["CanonicalQuery", "canonical_digest", "canonical_query",
           "witness_from_doc", "witness_ok", "witness_to_doc"]
