"""Staged solver portfolio: refute → model-probe → verdict store →
in-process LRU → witness search.

This is the front door every solver query in the process goes through
(``solver.solve_tape_ex`` delegates here) — the piece that turns the
full witness search (this repo's host-Z3 analog, the expensive slow
path) from the default into the rare last resort:

  1. **lru**     — the PR 4 solve memo, re-keyed on the CANONICAL
                   constraint hash (``smt/canon.py``) so alpha-renamed
                   repeats from cloned bytecode hit; entries hold
                   canonical-coordinate witnesses that are rehydrated
                   and re-verified per hit;
  2. **refute**  — structural unsat proof (``smt/refute.py``'s
                   forced-value propagation over the tape the device
                   produced): proven UNSAT without any search;
  3. **probe**   — model probe via exact tape evaluation
                   (``smt/eval.py``, the native evaluator): if the
                   seed assignment already satisfies every constraint
                   the query is SAT for free — the dominant case for
                   the default-path constraints cloned dispatchers
                   emit. Identical output to what the search's own
                   fast path would return, just counted as its stage;
  4. **store**   — the durable cross-campaign verdict store
                   (``smt/vstore.py``) shared by fleet workers and
                   repeat campaigns; sat witnesses are rehydrated
                   through the canonical leaf numbering and verified
                   by exact evaluation before being served;
  5. **search**  — the full partitioned inversion + randomized-repair
                   witness search (``smt/solver.py``). Its decided
                   verdicts are what the store persists.

Per-stage attempt/hit/latency lands in ``PORTFOLIO_STATS`` (snapshot/
delta like ``SolverStatistics``) and on the PR 3 metrics registry
(``solver_queries_total``, ``solver_queries_stage_<stage>_total``,
``solver_hits_stage_<stage>_total``, ``solver_stage_seconds_<stage>``)
— the serve daemon's ``/metrics`` exposes them verbatim, the campaign
heartbeat derives its Z3-avoided %% from them, and
``tools/trace_report.py`` section 8 renders the ladder.

Result-parity contract (tested): with the store cold, warm, or
disabled, issue output is byte-identical — a warm hit serves exactly
the witness the deterministic search would have recomputed, and every
sat witness served from any cache is re-verified against the querying
tape before use (a failed verification falls through to the next
stage, counted in ``solver_witness_mismatch_total``).

What is never cached anywhere durable: ``unknown`` (a budget property,
not a query property), wall-clock-expired queries, and ``base``-seeded
queries (the seed assignment is an input the canonical hash does not
cover — they run refute → probe → search only).
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional, Tuple

from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from . import solver as _sv
from .canon import (CanonicalQuery, canonical_query, witness_from_doc,
                    witness_ok, witness_to_doc)
from .eval import Assignment, evaluate
from .refute import refute_tape
from .tape import HostTape
from .vstore import VerdictStore

#: ladder order (also the reporting order everywhere)
STAGES = ("lru", "refute", "probe", "store", "search")


class PortfolioStats:
    """Process-wide per-stage counters (attempts / hits / per-verdict
    hit split / wall time). Snapshot/delta-style like
    ``solver.SolverStatistics`` so campaigns report per-session deltas
    while the singleton accumulates for the daemon's lifetime."""

    def __init__(self):
        self._lock = threading.Lock()
        self.reset()

    def reset(self) -> None:
        self.queries = 0
        self.witness_mismatch = 0
        self.stages: Dict[str, Dict[str, float]] = {
            s: {"attempts": 0, "hits": 0, "sat": 0, "unsat": 0,
                "time_sec": 0.0}
            for s in STAGES}

    def query(self) -> None:
        with self._lock:
            self.queries += 1

    def attempt(self, stage: str) -> None:
        with self._lock:
            self.stages[stage]["attempts"] += 1

    def hit(self, stage: str, verdict: str) -> None:
        with self._lock:
            st = self.stages[stage]
            st["hits"] += 1
            if verdict in ("sat", "unsat"):
                st[verdict] += 1

    def add_time(self, stage: str, dt: float) -> None:
        with self._lock:
            self.stages[stage]["time_sec"] += dt

    def mismatch(self) -> None:
        with self._lock:
            self.witness_mismatch += 1

    def snapshot(self) -> Dict:
        with self._lock:
            return {
                "queries": self.queries,
                "witness_mismatch": self.witness_mismatch,
                "stages": {s: dict(v) for s, v in self.stages.items()},
            }


def stats_delta(now: Dict, since: Optional[Dict] = None) -> Dict:
    """``now - since`` over :meth:`PortfolioStats.snapshot` dicts, with
    the derived headline: the share of queries resolved BEFORE the
    search stage (the Z3-avoided rate)."""
    z = {"queries": 0, "witness_mismatch": 0, "stages": {}}
    since = since or z
    out: Dict = {
        "queries": now["queries"] - since.get("queries", 0),
        "witness_mismatch": (now["witness_mismatch"]
                             - since.get("witness_mismatch", 0)),
        "stages": {},
    }
    for s in STAGES:
        a = now["stages"].get(s, {})
        b = (since.get("stages") or {}).get(s, {})
        out["stages"][s] = {
            k: round(a.get(k, 0) - b.get(k, 0), 6)
            for k in ("attempts", "hits", "sat", "unsat", "time_sec")}
    q = out["queries"]
    searched = out["stages"]["search"]["attempts"]
    out["z3_avoided_pct"] = (round(100.0 * (1.0 - searched / q), 2)
                             if q else 0.0)
    return out


def z3_avoided_pct(now: Dict, since: Optional[Dict] = None) -> float:
    return stats_delta(now, since)["z3_avoided_pct"]


#: the process singleton (mirrors solver.SOLVER_STATS)
PORTFOLIO_STATS = PortfolioStats()


# --- the shared verdict store (process-global, like the LRU) -----------

_STORE: Optional[VerdictStore] = None
_STORE_LOCK = threading.Lock()


def set_store(store) -> Optional[VerdictStore]:
    """Install the process-wide verdict store (a directory path, a
    VerdictStore, or None to disable) and return the PREVIOUS one so
    scoped users (a campaign run) can restore it. Also pre-registers
    the portfolio metrics so a scrape before the first query already
    sees the counter names."""
    global _STORE
    with _STORE_LOCK:
        prev = _STORE
        if store is None:
            _STORE = None
        elif isinstance(store, VerdictStore):
            _STORE = store
        else:
            _STORE = VerdictStore(str(store))
    register_metrics()
    return prev


def get_store() -> Optional[VerdictStore]:
    return _STORE


def register_metrics() -> None:
    """Create the portfolio's registry entries at zero (idempotent):
    the serve ``/metrics`` surface should list the ladder even before
    the first query arrives."""
    reg = obs_metrics.REGISTRY
    reg.counter("solver_queries_total",
                help="solver queries entering the staged portfolio")
    reg.counter("solver_witness_mismatch_total",
                help="cached sat witnesses that failed re-verification "
                     "and fell through to the next stage")
    for s in STAGES:
        reg.counter(f"solver_queries_stage_{s}_total",
                    help=f"queries that reached the {s} stage")
        reg.counter(f"solver_hits_stage_{s}_total",
                    help=f"queries resolved by the {s} stage")


# --- internals ---------------------------------------------------------

def _stage_begin(stage: str) -> float:
    PORTFOLIO_STATS.attempt(stage)
    obs_metrics.REGISTRY.counter(
        f"solver_queries_stage_{stage}_total").inc()
    return time.perf_counter()


def _stage_end(stage: str, t0: float,
               verdict: Optional[str] = None) -> None:
    dt = time.perf_counter() - t0
    PORTFOLIO_STATS.add_time(stage, dt)
    obs_metrics.REGISTRY.histogram(
        f"solver_stage_seconds_{stage}",
        help=f"wall time spent in the {stage} stage").observe(dt)
    if verdict is not None:
        PORTFOLIO_STATS.hit(stage, verdict)
        obs_metrics.REGISTRY.counter(
            f"solver_hits_stage_{stage}_total").inc()
        # one instant event per DECIDED query (not per attempted
        # stage): carries the ambient trace_id, so a request's trace
        # shows which ladder stage settled each of its queries —
        # volume-bounded by queries, not stages
        if obs_trace.active():
            obs_trace.event("solver_stage", stage=stage,
                            dur=round(dt, 6), verdict=verdict)


def _lru_get(key):
    with _sv._SOLVE_CACHE_LOCK:
        hit = _sv._SOLVE_CACHE.get(key)
        if hit is not None:
            # a hit is a *use*: refresh recency so the corpus's hot
            # recurring queries stay resident while one-offs age out
            _sv._SOLVE_CACHE.move_to_end(key)
    return hit


def _lru_put(key, verdict: str, doc: Optional[Dict]) -> None:
    with _sv._SOLVE_CACHE_LOCK:
        _sv._SOLVE_CACHE[key] = (verdict, doc)
        _sv._SOLVE_CACHE.move_to_end(key)
        _sv._cache_evict_locked()


def _serve_sat(tape: HostTape, canon: CanonicalQuery, stage: str,
               t0: float, doc: Optional[Dict]) -> Optional[Assignment]:
    """Rehydrate + verify a cached sat witness; None (with the
    mismatch counters ticked) means fall through to the next stage."""
    asn = witness_from_doc(tape, canon, doc) if doc is not None else None
    if asn is not None and witness_ok(tape, asn):
        _stage_end(stage, t0, "sat")
        return asn
    PORTFOLIO_STATS.mismatch()
    obs_metrics.REGISTRY.counter("solver_witness_mismatch_total").inc()
    _stage_end(stage, t0)
    return None


def solve_query(tape: HostTape, seed: int = 0, max_iters: int = 400,
                base: Optional[Assignment] = None,
                max_time: Optional[float] = None
                ) -> Tuple[str, Optional[Assignment]]:
    """Run one query down the stage ladder. Same signature and verdict
    semantics as the pre-portfolio ``solve_tape_ex`` (which now
    delegates here)."""
    t_query = time.perf_counter()
    deadline = None if max_time is None else t_query + max_time
    PORTFOLIO_STATS.query()
    obs_metrics.REGISTRY.counter(
        "solver_queries_total",
        help="solver queries entering the staged portfolio").inc()

    canon: Optional[CanonicalQuery] = None
    key = None
    cacheable_query = base is None  # base is an input the hash misses

    # --- stage 1: in-process LRU (canonical-hash keyed) ---------------
    if cacheable_query and _sv._SOLVE_CACHE_CAP > 0:
        t0 = _stage_begin("lru")
        canon = canonical_query(tape)
        # the search budget stays in the key: `unknown` is cacheable
        # here exactly because a bigger budget is a different key
        key = (canon.digest, seed, max_iters, max_time)
        hit = _lru_get(key)
        if hit is not None:
            verdict, doc = hit
            if verdict == "sat":
                asn = _serve_sat(tape, canon, "lru", t0, doc)
                if asn is not None:
                    _sv.SOLVER_STATS.record(
                        "sat", time.perf_counter() - t_query, cached=True)
                    return "sat", asn
            else:
                _stage_end("lru", t0, verdict)
                _sv.SOLVER_STATS.record(
                    verdict, time.perf_counter() - t_query, cached=True)
                return verdict, None
        else:
            _stage_end("lru", t0)

    verdict: Optional[str] = None
    out: Optional[Assignment] = None
    decided_by = None

    # --- stage 2: structural refutation (proven unsat, no search) -----
    t0 = _stage_begin("refute")
    if refute_tape(tape) is not None:
        _stage_end("refute", t0, "unsat")
        verdict, out, decided_by = "unsat", None, "refute"
    else:
        _stage_end("refute", t0)

    # --- stage 3: model probe (exact evaluation of the seed model) ----
    if verdict is None:
        t0 = _stage_begin("probe")
        probe = base.copy() if base is not None else Assignment()
        vals = evaluate(tape, probe)
        if all(bool(vals[int(n)]) == bool(s) for n, s in tape.constraints):
            _stage_end("probe", t0, "sat")
            verdict, out, decided_by = "sat", probe, "probe"
        else:
            _stage_end("probe", t0)

    # --- stage 4: durable cross-campaign verdict store ----------------
    store = _STORE
    if verdict is None and cacheable_query and store is not None:
        t0 = _stage_begin("store")
        if canon is None:
            canon = canonical_query(tape)
        doc = store.get(canon.digest)
        if doc is not None:
            if doc["verdict"] == "unsat":
                _stage_end("store", t0, "unsat")
                verdict, out, decided_by = "unsat", None, "store"
            else:
                asn = _serve_sat(tape, canon, "store", t0,
                                 doc.get("witness"))
                if asn is not None:
                    verdict, out, decided_by = "sat", asn, "store"
        else:
            _stage_end("store", t0)

    # --- stage 5: the witness search (the host-Z3 slow path) ----------
    if verdict is None:
        t0 = _stage_begin("search")
        verdict, out = _sv._solve_partitioned(tape, seed, max_iters, base,
                                              deadline)
        _stage_end("search", t0,
                   verdict if verdict != "unknown" else None)
        decided_by = "search"

    # --- bookkeeping + cache write-back -------------------------------
    if verdict == "unknown":
        _sv._dump_unknown(tape)
    # a wall-clock expiry is load-dependent, not a property of the
    # query — caching it would poison this key for re-queries issued
    # after contention subsides
    expired = (verdict == "unknown" and deadline is not None
               and time.perf_counter() >= deadline)
    if cacheable_query and not expired and key is not None:
        doc = (witness_to_doc(out, canon)
               if verdict == "sat" and out is not None else None)
        _lru_put(key, verdict, doc)
    if (cacheable_query and store is not None and decided_by == "search"
            and verdict in ("sat", "unsat")):
        # persist only what cost real work to decide: search verdicts.
        # Refute/probe hits re-derive in microseconds and would hit
        # their own (earlier) stage on a warm run anyway — storing
        # them is pure dead weight in the shared dir.
        if canon is None:
            canon = canonical_query(tape)
        try:
            store.put(canon.digest, verdict,
                      witness_to_doc(out, canon)
                      if out is not None else None)
        except OSError:
            pass  # a full/readonly store dir must not fail the query
    _sv.SOLVER_STATS.record(verdict, time.perf_counter() - t_query,
                            cached=(decided_by == "store"))
    return verdict, out


__all__ = ["PORTFOLIO_STATS", "PortfolioStats", "STAGES", "get_store",
           "register_metrics", "set_store", "solve_query", "stats_delta",
           "z3_avoided_pct"]
