"""Durable cross-campaign verdict store, keyed on the canonical
constraint hash (``smt/canon.py``).

PR 6's serve store caches per-CONTRACT verdicts; this is the per-QUERY
half ROADMAP calls the missing piece of the verdict-store direction: a
shared directory (the fleet ledger dir, a serve daemon's data dir — any
NFS/GCS mount the fleet machinery already uses) where every decided
SAT/UNSAT query lands as one JSON file, so fleet workers, resident
daemons, and repeat campaigns share solver work across processes and
restarts. On a clone-heavy corpus most queries are alpha-renamed
repeats; once one worker has paid the witness search, every other
worker's identical query is a file read.

Contracts kept deliberately identical to the rest of the repo's
durability story:

- every write goes through the repo-wide exclusive-write discipline
  (``utils/checkpoint.exclusive_write``: tmp + fsync + link-exclusive
  create) — FIRST WINS, concurrent writers of the same key cannot tear
  a file or flip an already-served verdict, and a losing writer simply
  drops its copy (the verdicts are equal by construction);
- corrupt or newer-schema files are counted MISSES, never errors, and
  the corrupt file is unlinked so re-analysis can rewrite it (a
  first-wins create would otherwise preserve the corruption forever);
- ``unknown``/timeout verdicts are NEVER stored — unknown is a property
  of a search budget, not of the query, and a persisted unknown would
  poison the key for every future (possibly bigger-budget) campaign.
  Only ``sat`` (with its canonical-coordinate witness, re-verified at
  serve time) and ``unsat`` are durable facts.
"""

from __future__ import annotations

import json
import os
import threading
import time
from collections import OrderedDict
from typing import Dict, Optional

from ..obs import metrics as obs_metrics
from ..utils.checkpoint import exclusive_write

#: verdict-file schema (readers reject newer-than-known)
VSTORE_SCHEMA = 1

#: in-RAM read-through cache entries (per store instance): repeat hits
#: on one canonical key skip the file read
_RAM_CAP = 4096


class VerdictStore:
    """One directory of per-query verdict files: ``<dir>/q<hash>.json``.

    Many writers, many readers, across processes and hosts; file-level
    atomicity (exclusive create) is the whole concurrency story — no
    lock file, no index to corrupt."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self._ram: "OrderedDict[str, Dict]" = OrderedDict()
        self._lock = threading.Lock()

    def _file(self, digest: str) -> str:
        return os.path.join(self.path, f"q{digest}.json")

    def get(self, digest: str) -> Optional[Dict]:
        """The stored verdict doc ({"verdict", "witness", ...}) or None
        on miss. Corruption (unparseable, wrong key, unknown schema,
        bogus verdict) is a counted miss and the file is removed so the
        next decided query can re-write it."""
        with self._lock:
            doc = self._ram.get(digest)
            if doc is not None:
                self._ram.move_to_end(digest)
                return doc
        p = self._file(digest)
        try:
            with open(p) as fh:
                doc = json.load(fh)
        except FileNotFoundError:
            return None
        except (OSError, ValueError):
            doc = None
        if (not isinstance(doc, dict)
                or int(doc.get("schema", 0) or 0) > VSTORE_SCHEMA
                or doc.get("key") != digest
                or doc.get("verdict") not in ("sat", "unsat")):
            obs_metrics.REGISTRY.counter(
                "solver_vstore_corrupt_total",
                help="unreadable verdict-store files treated as "
                     "misses (and unlinked)").inc()
            try:
                os.unlink(p)
            except OSError:
                pass
            return None
        with self._lock:
            self._ram[digest] = doc
            while len(self._ram) > _RAM_CAP:
                self._ram.popitem(last=False)
        return doc

    def put(self, digest: str, verdict: str,
            witness: Optional[Dict] = None) -> bool:
        """Durably persist one decided verdict (first-wins). Refuses
        ``unknown`` by contract. Returns whether this caller's file is
        the one on disk."""
        if verdict not in ("sat", "unsat"):
            raise ValueError(
                f"verdict store only persists sat/unsat, not {verdict!r}")
        doc = {"schema": VSTORE_SCHEMA, "key": digest, "verdict": verdict,
               "witness": witness, "t": round(time.time(), 3)}
        won = exclusive_write(self._file(digest),
                              json.dumps(doc, sort_keys=True).encode())
        reg = obs_metrics.REGISTRY
        if won:
            reg.counter(
                "solver_vstore_writes_total",
                help="verdicts persisted to the shared solver "
                     "store").inc()
            with self._lock:
                self._ram[digest] = doc
                while len(self._ram) > _RAM_CAP:
                    self._ram.popitem(last=False)
        else:
            reg.counter(
                "solver_vstore_write_races_total",
                help="verdict writes dropped because another worker "
                     "committed the key first").inc()
        return won

    def count(self) -> int:
        """Number of stored verdicts (diagnostics; O(dir))."""
        try:
            return sum(1 for f in os.listdir(self.path)
                       if f.startswith("q") and f.endswith(".json"))
        except OSError:
            return 0


__all__ = ["VSTORE_SCHEMA", "VerdictStore"]
