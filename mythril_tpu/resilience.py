"""Fault-isolated execution: watchdogs, fault injection, backend management.

The pod-scale north star (10k contracts in minutes, ROADMAP) is only as
strong as its weakest failure mode, and this repo has hit every one of
them on real hardware:

- a wedged TPU runtime hangs ``jax.devices()`` forever
  (``docs/tpu-wedge-round5.md`` — two multi-hour wedges, round 4 + 5);
- a hung XLA compile can exceed any outer budget (round 4: >580 s for
  one cold-cache program through the axon tunnel);
- one pathological contract can stall or crash a whole campaign batch,
  and ``CorpusCampaign.run`` only checked its deadline *between*
  batches.

This module is the shared answer (DTVM's fault-contained-execution
property, PAPERS.md; EVMx assumes a host-side supervisor that survives
device faults):

- :func:`run_with_watchdog` — run a callable under a hard wall-clock
  deadline in a worker thread; expiry raises :class:`BatchTimeout`
  instead of stalling the supervisor (the stuck thread is abandoned,
  exactly like bench.py abandons an unkillable D-state probe child).
- :class:`FaultInjector` — deterministic, env/constructor-driven fault
  injection (hang / raise / device-lost / kill at a batch index or on a
  contract name) so every recovery path is testable on CPU.
- :class:`BackendManager` — subprocess-isolated backend probe with a
  timeout, bounded re-init attempts with backoff, and an explicit CPU
  fallback, all recorded as structured events for the campaign report.
  Generalizes ``bench.py``'s ad-hoc ``_probe_backend``.

IMPORTANT: nothing in this module may touch a JAX backend at import or
probe time — the whole point is to stay alive when the backend is the
thing that is wedged. The probe runs ``jax.devices()`` in a *child*
process only.
"""

from __future__ import annotations

import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple


class ResilienceError(RuntimeError):
    """Base for supervisor-level failures."""


class BatchTimeout(ResilienceError):
    """A watchdogged unit of work exceeded its wall-clock budget."""


class DeviceLostError(ResilienceError):
    """The accelerator went away mid-run (injected or detected)."""


class ResourceExhausted(ResilienceError):
    """Device/host memory exhaustion (injected or classified from a
    backend error). The campaign answers this with the degradation
    ladder — shrink the work, don't abort the run."""


class InjectedKill(BaseException):
    """Simulates SIGKILL mid-batch for kill/resume testing.

    Deliberately a ``BaseException``: the campaign's retry/bisect
    machinery catches ``Exception`` — a simulated kill must blow through
    it uncheckpointed, exactly like a real SIGKILL would.
    """


# --- watchdog ---------------------------------------------------------


def run_with_watchdog(fn: Callable, timeout: Optional[float],
                      label: str = "work"):
    """Run ``fn()`` under a hard wall-clock deadline.

    ``timeout=None`` runs inline (no thread). Otherwise the work runs in
    a daemon thread; if it has not finished after ``timeout`` seconds a
    :class:`BatchTimeout` is raised and the thread is ABANDONED — a hung
    XLA compile or wedged device call cannot be interrupted from Python,
    so the supervisor walks away from it (the abandoned thread dies with
    the process; an injected hang just sleeps). Exceptions from ``fn``
    (including ``BaseException``s like :class:`InjectedKill`) re-raise
    in the caller.
    """
    if timeout is None:
        return fn()
    box: Dict[str, object] = {}
    done = threading.Event()

    def work():
        try:
            box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — relayed to caller
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=work, daemon=True,
                         name=f"watchdog:{label}")
    t.start()
    if not done.wait(timeout):
        raise BatchTimeout(
            f"{label} exceeded {timeout:.1f}s wall-clock budget")
    if "error" in box:
        raise box["error"]  # type: ignore[misc]
    return box.get("value")


# --- backend-error classification -------------------------------------

# message fragments (lowercased) that identify device/host memory
# exhaustion in XLA/JAX runtime errors across backends: TPU and GPU
# allocators raise XlaRuntimeError with a RESOURCE_EXHAUSTED status,
# CPU-side failures surface as MemoryError or "out of memory" strings
_OOM_MARKERS = ("resource_exhausted", "resource exhausted",
                "out of memory", "oom ", "allocation failure",
                "failed to allocate")
_DEVICE_LOST_MARKERS = ("device_lost", "device lost", "data_loss",
                        "failed_precondition: device",
                        "unavailable: device", "device or resource busy",
                        "device not found")
_COMPILE_MARKERS = ("compilation failure", "compile failed",
                    "xla compilation", "error during compilation",
                    "unimplemented:", "mlir")


def classify_backend_error(e: BaseException) -> Optional[str]:
    """Best-effort triage of a batch failure into the recovery path
    that can actually cure it: ``"oom"`` (degradation ladder),
    ``"device-lost"`` (backend re-probe), ``"compile"`` (no point
    retrying the identical shape — bisect immediately), or ``None``
    (unclassified: the generic retry → bisect path).

    Matches by type first (:class:`ResourceExhausted`,
    :class:`DeviceLostError`, ``MemoryError``), then by message
    fragments of ``XlaRuntimeError``-family exceptions — jaxlib does not
    export stable subclasses per status code, so the status string in
    the message is the only portable discriminator."""
    if isinstance(e, ResourceExhausted) or isinstance(e, MemoryError):
        return "oom"
    if isinstance(e, DeviceLostError):
        return "device-lost"
    text = f"{type(e).__name__}: {e}".lower()
    if any(m in text for m in _OOM_MARKERS):
        return "oom"
    if any(m in text for m in _DEVICE_LOST_MARKERS):
        return "device-lost"
    if any(m in text for m in _COMPILE_MARKERS):
        return "compile"
    return None


# --- degradation ladder ----------------------------------------------

#: the rungs a campaign batch walks on RESOURCE_EXHAUSTED, in order and
#: cumulatively: halve the per-contract frontier lanes (displaced forks
#: park and spill through the engine's defer/rebalance machinery), then
#: additionally halve the batch width (two half-width sub-batches), then
#: additionally pin execution to the CPU backend (host RAM >> HBM)
DEGRADE_RUNGS = ("halve-lanes", "halve-batch", "cpu")


def parse_ladder(text: Optional[str]) -> Tuple[str, ...]:
    """``--oom-ladder`` parser: comma-separated rung names in walk
    order; ``"none"`` (or empty) disables degradation entirely."""
    if text is None:
        return DEGRADE_RUNGS
    rungs = tuple(r.strip() for r in text.split(",") if r.strip())
    if rungs in ((), ("none",)):
        return ()
    for r in rungs:
        if r not in DEGRADE_RUNGS:
            raise ValueError(
                f"oom ladder rung {r!r}: must be of {DEGRADE_RUNGS}")
    return rungs


# --- fault injection --------------------------------------------------

FAULT_MODES = ("hang", "raise", "device-lost", "kill", "oom")

#: how long an injected hang sleeps per check; the watchdog is expected
#: to fire long before the total (a daemon thread naps harmlessly after)
_HANG_TOTAL_S = 3600.0


@dataclass
class FaultSpec:
    """One trigger: ``mode`` fires when the batch index and/or contract
    name matches, at most ``times`` times (None = every time — a
    persistent poison; ``times=1`` models a transient fault the
    retry-once policy cures). ``nth=N`` instead fires on the Nth
    matching attempt seen by THIS process (1-based) — worker-LOCAL
    ordering, for fleet tests where global batch indices are claimed
    nondeterministically across racing workers (docs/fleet.md)."""

    mode: str
    batch: Optional[int] = None
    contract: Optional[str] = None
    times: Optional[int] = None
    nth: Optional[int] = None
    fired: int = 0
    calls: int = 0

    def matches(self, batch: Optional[int],
                contracts: Sequence[str]) -> bool:
        if self.times is not None and self.fired >= self.times:
            return False
        if self.batch is not None and batch != self.batch:
            return False
        if self.contract is not None and self.contract not in contracts:
            return False
        if self.nth is not None:
            self.calls += 1
            if self.calls != self.nth:
                return False
        return True

    @classmethod
    def parse(cls, text: str) -> "FaultSpec":
        """``mode[:key=value]*`` — e.g. ``raise:contract=c002``,
        ``hang:batch=1``, ``raise:batch=0:times=1``, ``kill:batch=2``,
        ``kill:nth=2`` (this worker's 2nd attempt, wherever it lands)."""
        parts = [p for p in text.strip().split(":") if p]
        if not parts or parts[0] not in FAULT_MODES:
            raise ValueError(
                f"fault spec {text!r}: mode must be one of {FAULT_MODES}")
        spec = cls(mode=parts[0])
        for kv in parts[1:]:
            if "=" not in kv:
                raise ValueError(f"fault spec {text!r}: expected key=value, "
                                 f"got {kv!r}")
            k, v = kv.split("=", 1)
            if k == "batch":
                spec.batch = int(v)
            elif k == "contract":
                spec.contract = v
            elif k == "times":
                spec.times = int(v)
            elif k == "nth":
                spec.nth = int(v)
                if spec.nth < 1:
                    raise ValueError(
                        f"fault spec {text!r}: nth is 1-based")
            else:
                raise ValueError(f"fault spec {text!r}: unknown key {k!r}")
        if spec.batch is None and spec.contract is None \
                and spec.nth is None:
            raise ValueError(
                f"fault spec {text!r}: need batch=, contract= and/or "
                "nth= (an unconditional fault would poison every batch)")
        return spec


class FaultInjector:
    """Deterministic fault source, checked at the top of every guarded
    batch attempt. Specs parse from a ``;``-separated string — the
    ``MYTHRIL_FAULT_INJECT`` env var or ``--fault-inject`` — or are
    built directly. The log of fires is kept for test assertions."""

    def __init__(self, specs: Sequence[FaultSpec] = ()):
        self.specs = list(specs)
        self.log: List[Dict] = []

    @classmethod
    def from_string(cls, text: Optional[str]) -> Optional["FaultInjector"]:
        if not text:
            return None
        return cls([FaultSpec.parse(p)
                    for p in text.split(";") if p.strip()])

    @classmethod
    def from_env(cls) -> Optional["FaultInjector"]:
        return cls.from_string(os.environ.get("MYTHRIL_FAULT_INJECT"))

    def fire(self, batch: Optional[int] = None,
             contracts: Sequence[str] = ()) -> None:
        """Raise/hang per the first matching spec (called INSIDE the
        watchdog, so a hang surfaces as :class:`BatchTimeout`)."""
        for spec in self.specs:
            if not spec.matches(batch, contracts):
                continue
            spec.fired += 1
            self.log.append({"mode": spec.mode, "batch": batch,
                             "contracts": list(contracts)})
            if spec.mode == "hang":
                t0 = time.monotonic()
                while time.monotonic() - t0 < _HANG_TOTAL_S:
                    time.sleep(0.05)
                return
            if spec.mode == "raise":
                raise ResilienceError(
                    f"injected fault (batch={batch}, "
                    f"contracts={list(contracts)})")
            if spec.mode == "device-lost":
                raise DeviceLostError(
                    f"injected device loss (batch={batch})")
            if spec.mode == "kill":
                raise InjectedKill(
                    f"injected kill (batch={batch})")
            if spec.mode == "oom":
                # message mirrors a real XLA allocator failure so the
                # classifier exercises the same string path it would on
                # hardware; ``times=N`` models pressure that clears
                # after N ladder steps shrink the working set
                raise ResourceExhausted(
                    f"injected RESOURCE_EXHAUSTED: out of memory "
                    f"(batch={batch})")


# --- backend management ----------------------------------------------


class BackendManager:
    """Probe/recover the JAX backend without ever letting a wedge reach
    this process: the probe child runs ``jax.devices()`` and is
    abandoned (not waited on) if it hangs — a child wedged in an
    uninterruptible driver call survives SIGKILL (round-3/5 evidence).

    ``probe_fn`` swaps the subprocess probe for a callable
    ``(timeout_s) -> (ok, diag)`` in tests. Every attempt, backoff, and
    fallback lands in ``events`` (list of dicts) so campaign reports
    and bench records carry the full backend story.
    """

    def __init__(self, init_timeout: float = 75.0, max_attempts: int = 2,
                 backoff: float = 5.0,
                 probe_fn: Optional[Callable[[float], Tuple[bool, str]]] = None):
        self.init_timeout = init_timeout
        self.max_attempts = max(1, int(max_attempts))
        self.backoff = backoff
        self.probe_fn = probe_fn
        self.events: List[Dict] = []

    def _event(self, kind: str, detail: str = "", attempt: int = 0) -> None:
        self.events.append({"kind": kind, "detail": detail[:300],
                            "attempt": attempt,
                            "t": round(time.time(), 3)})

    def _subprocess_probe(self, timeout_s: float) -> Tuple[bool, str]:
        """One isolated backend init (lifted from bench.py's round-3
        hardening). Returns (ok, diagnosis)."""
        import tempfile

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with tempfile.TemporaryFile(mode="w+") as out:
            p = subprocess.Popen(
                [sys.executable, "-c",
                 "import sys; sys.path.insert(0, %r); " % root
                 + "import mythril_tpu, jax; d = jax.devices(); "
                   "print('OK', jax.default_backend(), len(d))"],
                stdout=out, stderr=subprocess.STDOUT,
            )
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                if p.poll() is not None:
                    break
                time.sleep(0.2)
            if p.poll() is None:
                p.kill()
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass  # unkillable (D-state): abandon it
                return False, f"backend init hung >{timeout_s:.0f}s"
            out.seek(0)
            text = out.read()
            if p.returncode == 0 and "OK" in text:
                return True, text.strip().splitlines()[-1]
            return False, "backend init failed (rc=%s): %s" % (
                p.returncode, text.strip()[-300:])

    def probe(self) -> Tuple[bool, str]:
        """Bounded re-init attempts with backoff between them."""
        probe = self.probe_fn or self._subprocess_probe
        diag = "no probe attempt made"
        for attempt in range(1, self.max_attempts + 1):
            ok, diag = probe(self.init_timeout)
            self._event("probe_ok" if ok else "probe_fail", diag, attempt)
            if ok:
                return True, diag
            if attempt < self.max_attempts and self.backoff > 0:
                # linear backoff: a wedged runtime sometimes clears after
                # the stuck client's grpc deadline lapses
                time.sleep(self.backoff * attempt)
        return False, diag

    def ensure_or_fallback(self) -> Tuple[bool, str]:
        """Probe; on failure pin this process to the CPU backend via
        JAX_PLATFORMS (heavy engine imports must not have run yet) and
        record an explicit ``cpu_fallback`` event. Returns
        (backend_ok, diagnosis)."""
        ok, diag = self.probe()
        if ok:
            return True, diag
        os.environ["JAX_PLATFORMS"] = "cpu"
        self._event("cpu_fallback",
                    "configured backend unreachable; JAX_PLATFORMS=cpu")
        return False, diag

    def recover(self, reason: str = "device-lost") -> bool:
        """After a device loss mid-campaign: record it, re-probe with the
        usual bounded attempts. Returns whether the backend answered."""
        self._event("device_lost", reason)
        ok, _ = self.probe()
        return ok


__all__ = [
    "BackendManager", "BatchTimeout", "DEGRADE_RUNGS", "DeviceLostError",
    "FaultInjector", "FaultSpec", "InjectedKill", "ResilienceError",
    "ResourceExhausted", "classify_backend_error", "parse_ladder",
    "run_with_watchdog",
]
