"""Fault-isolated execution: watchdogs, fault injection, backend management.

The pod-scale north star (10k contracts in minutes, ROADMAP) is only as
strong as its weakest failure mode, and this repo has hit every one of
them on real hardware:

- a wedged TPU runtime hangs ``jax.devices()`` forever
  (``docs/tpu-wedge-round5.md`` — two multi-hour wedges, round 4 + 5);
- a hung XLA compile can exceed any outer budget (round 4: >580 s for
  one cold-cache program through the axon tunnel);
- one pathological contract can stall or crash a whole campaign batch,
  and ``CorpusCampaign.run`` only checked its deadline *between*
  batches.

This module is the shared answer (DTVM's fault-contained-execution
property, PAPERS.md; EVMx assumes a host-side supervisor that survives
device faults):

- :func:`run_with_watchdog` — run a callable under a hard wall-clock
  deadline in a worker thread; expiry raises :class:`BatchTimeout`
  instead of stalling the supervisor (the stuck thread is abandoned,
  exactly like bench.py abandons an unkillable D-state probe child).
- :class:`FaultInjector` — deterministic, env/constructor-driven fault
  injection (hang / raise / device-lost / kill at a batch index or on a
  contract name) so every recovery path is testable on CPU.
- :class:`BackendManager` — subprocess-isolated backend probe with a
  timeout, bounded re-init attempts with backoff, and an explicit CPU
  fallback, all recorded as structured events for the campaign report.
  Generalizes ``bench.py``'s ad-hoc ``_probe_backend``.

IMPORTANT: nothing in this module may touch a JAX backend at import or
probe time — the whole point is to stay alive when the backend is the
thing that is wedged. The probe runs ``jax.devices()`` in a *child*
process only.
"""

from __future__ import annotations

import collections
import os
import select
import signal
import subprocess
import sys
import threading
import time
from dataclasses import dataclass, field
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from .backend import (TIER_ORDER, TIER_RUNG, TIER_RUNG_ALIAS,
                      default_oom_ladder, profile as tier_profile,
                      probe_tier, terminal_tier, tiers_below)


class ResilienceError(RuntimeError):
    """Base for supervisor-level failures."""


class BatchTimeout(ResilienceError):
    """A watchdogged unit of work exceeded its wall-clock budget."""


class DeviceLostError(ResilienceError):
    """The accelerator went away mid-run (injected or detected)."""


class ResourceExhausted(ResilienceError):
    """Device/host memory exhaustion (injected or classified from a
    backend error). The campaign answers this with the degradation
    ladder — shrink the work, don't abort the run."""


class WorkerDied(ResilienceError):
    """The supervised engine worker subprocess died (segfault, OOM
    kill, torn IPC reply, init failure). The batch it was running is
    NOT lost — the supervisor restarts the worker and the campaign's
    retry→ladder→bisect machinery replays the batch."""


class WorkerError(ResilienceError):
    """An exception raised INSIDE the engine worker, rehydrated on the
    parent side. The message carries the original type name + text so
    :func:`classify_backend_error`'s string triage still applies."""


class WorkerCrashLoop(ResilienceError):
    """The crash-loop circuit breaker is open: N worker deaths within
    the window. The supervisor refuses to spawn until the cooldown
    lapses; the campaign answers by pinning the batch to the in-process
    CPU path (the trusted fallback the accelerator crash loop cannot
    reach)."""


class InjectedKill(BaseException):
    """Simulates SIGKILL mid-batch for kill/resume testing.

    Deliberately a ``BaseException``: the campaign's retry/bisect
    machinery catches ``Exception`` — a simulated kill must blow through
    it uncheckpointed, exactly like a real SIGKILL would.
    """


# --- watchdog ---------------------------------------------------------


def run_with_watchdog(fn: Callable, timeout: Optional[float],
                      label: str = "work"):
    """Run ``fn()`` under a hard wall-clock deadline.

    ``timeout=None`` runs inline (no thread). Otherwise the work runs in
    a daemon thread; if it has not finished after ``timeout`` seconds a
    :class:`BatchTimeout` is raised and the thread is ABANDONED — a hung
    XLA compile or wedged device call cannot be interrupted from Python,
    so the supervisor walks away from it (the abandoned thread dies with
    the process; an injected hang just sleeps). Exceptions from ``fn``
    (including ``BaseException``s like :class:`InjectedKill`) re-raise
    in the caller.
    """
    if timeout is None:
        return fn()
    box: Dict[str, object] = {}
    done = threading.Event()

    def work():
        try:
            box["value"] = fn()
        except BaseException as e:  # noqa: BLE001 — relayed to caller
            box["error"] = e
        finally:
            done.set()

    t = threading.Thread(target=work, daemon=True,
                         name=f"watchdog:{label}")
    t.start()
    if not done.wait(timeout):
        raise BatchTimeout(
            f"{label} exceeded {timeout:.1f}s wall-clock budget")
    if "error" in box:
        raise box["error"]  # type: ignore[misc]
    return box.get("value")


# --- backend-error classification -------------------------------------

# message fragments (lowercased) that identify device/host memory
# exhaustion in XLA/JAX runtime errors across backends: TPU and GPU
# allocators raise XlaRuntimeError with a RESOURCE_EXHAUSTED status,
# CPU-side failures surface as MemoryError or "out of memory" strings
_OOM_MARKERS = ("resource_exhausted", "resource exhausted",
                "out of memory", "oom ", "allocation failure",
                "failed to allocate")
_DEVICE_LOST_MARKERS = ("device_lost", "device lost", "data_loss",
                        "failed_precondition: device",
                        "unavailable: device", "device or resource busy",
                        "device not found")
_COMPILE_MARKERS = ("compilation failure", "compile failed",
                    "xla compilation", "error during compilation",
                    "unimplemented:", "mlir")


def classify_backend_error(e: BaseException) -> Optional[str]:
    """Best-effort triage of a batch failure into the recovery path
    that can actually cure it: ``"oom"`` (degradation ladder),
    ``"device-lost"`` (backend re-probe), ``"compile"`` (no point
    retrying the identical shape — bisect immediately), or ``None``
    (unclassified: the generic retry → bisect path).

    Matches by type first (:class:`ResourceExhausted`,
    :class:`DeviceLostError`, ``MemoryError``), then by message
    fragments of ``XlaRuntimeError``-family exceptions — jaxlib does not
    export stable subclasses per status code, so the status string in
    the message is the only portable discriminator."""
    if isinstance(e, ResourceExhausted) or isinstance(e, MemoryError):
        return "oom"
    if isinstance(e, DeviceLostError):
        return "device-lost"
    text = f"{type(e).__name__}: {e}".lower()
    if any(m in text for m in _OOM_MARKERS):
        return "oom"
    if any(m in text for m in _DEVICE_LOST_MARKERS):
        return "device-lost"
    if any(m in text for m in _COMPILE_MARKERS):
        return "compile"
    return None


# --- degradation ladder ----------------------------------------------

#: the rungs a campaign batch walks on RESOURCE_EXHAUSTED, in order and
#: cumulatively: halve the per-contract frontier lanes (displaced forks
#: park and spill through the engine's defer/rebalance machinery), then
#: additionally halve the batch width (two half-width sub-batches), then
#: additionally demote execution to the next available backend tier
#: (host RAM >> HBM on the floor). The ladder shape is owned by the
#: BackendProfile registry; the terminal rung keeps its historical name
#: ``"cpu"`` but is resolved against the tier ladder at walk time.
DEGRADE_RUNGS = default_oom_ladder()


def parse_ladder(text: Optional[str]) -> Tuple[str, ...]:
    """``--oom-ladder`` parser: comma-separated rung names in walk
    order; ``"none"`` (or empty) disables degradation entirely. The
    terminal rung accepts both its historical spelling (``cpu``) and
    ``next-tier``; both mean "demote to the next available tier"."""
    if text is None:
        return DEGRADE_RUNGS
    rungs = tuple(TIER_RUNG if r.strip() == TIER_RUNG_ALIAS else r.strip()
                  for r in text.split(",") if r.strip())
    if rungs in ((), ("none",)):
        return ()
    for r in rungs:
        if r not in DEGRADE_RUNGS:
            raise ValueError(
                f"oom ladder rung {r!r}: must be of {DEGRADE_RUNGS}")
    return rungs


# --- fault injection --------------------------------------------------

FAULT_MODES = ("hang", "raise", "device-lost", "kill", "oom",
               "worker-kill", "worker-segv", "flap")

#: fault modes handled by the WorkerSupervisor (a signal is delivered
#: to the engine worker SUBPROCESS) rather than raised in-process by
#: :meth:`FaultInjector.fire`
_WORKER_FAULT_SIGNALS = {"worker-kill": signal.SIGKILL,
                         "worker-segv": signal.SIGSEGV}

#: how long an injected hang sleeps per check; the watchdog is expected
#: to fire long before the total (a daemon thread naps harmlessly after)
_HANG_TOTAL_S = 3600.0


@dataclass
class FaultSpec:
    """One trigger: ``mode`` fires when the batch index and/or contract
    name matches, at most ``times`` times (None = every time — a
    persistent poison; ``times=1`` models a transient fault the
    retry-once policy cures). ``nth=N`` instead fires on the Nth
    matching attempt seen by THIS process (1-based) — worker-LOCAL
    ordering, for fleet tests where global batch indices are claimed
    nondeterministically across racing workers (docs/fleet.md).

    ``flap`` models an oscillating backend: odd matching attempts lose
    the device (:class:`DeviceLostError`, which demotes the campaign's
    backend tier), even attempts pass — so demote, repromote, demote
    alternate deterministically until flap damping holds the tier
    (docs/resilience.md "Backend tiers"). ``times`` bounds the number
    of down-phases; only down-phases count as fires."""

    mode: str
    batch: Optional[int] = None
    contract: Optional[str] = None
    times: Optional[int] = None
    nth: Optional[int] = None
    fired: int = 0
    calls: int = 0
    flap_calls: int = 0

    def matches(self, batch: Optional[int],
                contracts: Sequence[str]) -> bool:
        if self.times is not None and self.fired >= self.times:
            return False
        if self.batch is not None and batch != self.batch:
            return False
        if self.contract is not None and self.contract not in contracts:
            return False
        if self.nth is not None:
            self.calls += 1
            if self.calls != self.nth:
                return False
        return True

    @classmethod
    def parse(cls, text: str) -> "FaultSpec":
        """``mode[:key=value]*`` — e.g. ``raise:contract=c002``,
        ``hang:batch=1``, ``raise:batch=0:times=1``, ``kill:batch=2``,
        ``kill:nth=2`` (this worker's 2nd attempt, wherever it lands)."""
        parts = [p for p in text.strip().split(":") if p]
        if not parts or parts[0] not in FAULT_MODES:
            raise ValueError(
                f"fault spec {text!r}: mode must be one of {FAULT_MODES}")
        spec = cls(mode=parts[0])
        for kv in parts[1:]:
            if "=" not in kv:
                raise ValueError(f"fault spec {text!r}: expected key=value, "
                                 f"got {kv!r}")
            k, v = kv.split("=", 1)
            if k == "batch":
                spec.batch = int(v)
            elif k == "contract":
                spec.contract = v
            elif k == "times":
                spec.times = int(v)
            elif k == "nth":
                spec.nth = int(v)
                if spec.nth < 1:
                    raise ValueError(
                        f"fault spec {text!r}: nth is 1-based")
            else:
                raise ValueError(f"fault spec {text!r}: unknown key {k!r}")
        if spec.batch is None and spec.contract is None \
                and spec.nth is None and spec.mode != "flap":
            # flap is exempt: its down/up alternation IS its bound —
            # every even attempt passes, so it cannot poison a batch
            raise ValueError(
                f"fault spec {text!r}: need batch=, contract= and/or "
                "nth= (an unconditional fault would poison every batch)")
        return spec


class FaultInjector:
    """Deterministic fault source, checked at the top of every guarded
    batch attempt. Specs parse from a ``;``-separated string — the
    ``MYTHRIL_FAULT_INJECT`` env var or ``--fault-inject`` — or are
    built directly. The log of fires is kept for test assertions."""

    def __init__(self, specs: Sequence[FaultSpec] = ()):
        self.specs = list(specs)
        self.log: List[Dict] = []

    @classmethod
    def from_string(cls, text: Optional[str]) -> Optional["FaultInjector"]:
        if not text:
            return None
        return cls([FaultSpec.parse(p)
                    for p in text.split(";") if p.strip()])

    @classmethod
    def from_env(cls) -> Optional["FaultInjector"]:
        return cls.from_string(os.environ.get("MYTHRIL_FAULT_INJECT"))

    def fire(self, batch: Optional[int] = None,
             contracts: Sequence[str] = ()) -> None:
        """Raise/hang per the first matching spec (called INSIDE the
        watchdog, so a hang surfaces as :class:`BatchTimeout`).
        ``worker-*`` specs are skipped — they are the supervisor's to
        deliver (:meth:`worker_signal`), not in-process raises."""
        for spec in self.specs:
            if spec.mode in _WORKER_FAULT_SIGNALS:
                continue
            if not spec.matches(batch, contracts):
                continue
            if spec.mode == "flap":
                # oscillation: odd matching attempts are the down-phase
                # (device lost), even attempts the up-phase (clean pass
                # — and other specs still get their look)
                spec.flap_calls += 1
                if spec.flap_calls % 2 == 0:
                    continue
                spec.fired += 1
                self.log.append({"mode": "flap", "batch": batch,
                                 "contracts": list(contracts)})
                raise DeviceLostError(
                    f"injected flapping backend: device lost "
                    f"(batch={batch}, down-phase {spec.fired})")
            spec.fired += 1
            self.log.append({"mode": spec.mode, "batch": batch,
                             "contracts": list(contracts)})
            if spec.mode == "hang":
                t0 = time.monotonic()
                while time.monotonic() - t0 < _HANG_TOTAL_S:
                    time.sleep(0.05)
                return
            if spec.mode == "raise":
                raise ResilienceError(
                    f"injected fault (batch={batch}, "
                    f"contracts={list(contracts)})")
            if spec.mode == "device-lost":
                raise DeviceLostError(
                    f"injected device loss (batch={batch})")
            if spec.mode == "kill":
                raise InjectedKill(
                    f"injected kill (batch={batch})")
            if spec.mode == "oom":
                # message mirrors a real XLA allocator failure so the
                # classifier exercises the same string path it would on
                # hardware; ``times=N`` models pressure that clears
                # after N ladder steps shrink the working set
                raise ResourceExhausted(
                    f"injected RESOURCE_EXHAUSTED: out of memory "
                    f"(batch={batch})")

    def worker_signal(self, batch: Optional[int] = None,
                      contracts: Sequence[str] = ()) -> Optional[int]:
        """Signal number of the first matching ``worker-kill`` /
        ``worker-segv`` spec (the supervisor delivers it to the engine
        worker subprocess right before dispatching the batch, so the
        batch attempt observes an externally-killed worker), or None.
        ``worker-kill:nth=K`` counts THIS process's worker-batch
        dispatches — K specs with nth=1..K model a crash loop. EVERY
        worker spec sees every dispatch (no early return), so stacked
        nth counters stay aligned."""
        hit: Optional[int] = None
        for spec in self.specs:
            sig = _WORKER_FAULT_SIGNALS.get(spec.mode)
            if sig is None:
                continue
            if not spec.matches(batch, contracts):
                continue
            if hit is None:
                spec.fired += 1
                self.log.append({"mode": spec.mode, "batch": batch,
                                 "contracts": list(contracts)})
                hit = sig
        return hit


# --- backend management ----------------------------------------------


class BackendManager:
    """Probe/recover the JAX backend without ever letting a wedge reach
    this process: the probe child runs ``jax.devices()`` and is
    abandoned (not waited on) if it hangs — a child wedged in an
    uninterruptible driver call survives SIGKILL (round-3/5 evidence).

    ``probe_fn`` swaps the subprocess probe for a callable
    ``(timeout_s) -> (ok, diag)`` in tests. Every attempt, backoff, and
    fallback lands in ``events`` (list of dicts) so campaign reports
    and bench records carry the full backend story.
    """

    def __init__(self, init_timeout: float = 75.0, max_attempts: int = 2,
                 backoff: float = 5.0,
                 probe_fn: Optional[Callable[[float], Tuple[bool, str]]] = None):
        self.init_timeout = init_timeout
        self.max_attempts = max(1, int(max_attempts))
        self.backoff = backoff
        self.probe_fn = probe_fn
        self.events: List[Dict] = []

    def _event(self, kind: str, detail: str = "", attempt: int = 0) -> None:
        self.events.append({"kind": kind, "detail": detail[:300],
                            "attempt": attempt,
                            "t": round(time.time(), 3)})

    def _subprocess_probe(self, timeout_s: float) -> Tuple[bool, str]:
        """One isolated backend init (lifted from bench.py's round-3
        hardening). Returns (ok, diagnosis)."""
        import tempfile

        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        with tempfile.TemporaryFile(mode="w+") as out:
            p = subprocess.Popen(
                [sys.executable, "-c",
                 "import sys; sys.path.insert(0, %r); " % root
                 + "import mythril_tpu, jax; d = jax.devices(); "
                   "print('OK', jax.default_backend(), len(d))"],
                stdout=out, stderr=subprocess.STDOUT,
            )
            deadline = time.monotonic() + timeout_s
            while time.monotonic() < deadline:
                if p.poll() is not None:
                    break
                time.sleep(0.2)
            if p.poll() is None:
                p.kill()
                try:
                    p.wait(timeout=5)
                except subprocess.TimeoutExpired:
                    pass  # unkillable (D-state): abandon it
                return False, f"backend init hung >{timeout_s:.0f}s"
            out.seek(0)
            text = out.read()
            if p.returncode == 0 and "OK" in text:
                return True, text.strip().splitlines()[-1]
            return False, "backend init failed (rc=%s): %s" % (
                p.returncode, text.strip()[-300:])

    def probe(self) -> Tuple[bool, str]:
        """Bounded re-init attempts with backoff between them."""
        probe = self.probe_fn or self._subprocess_probe
        diag = "no probe attempt made"
        for attempt in range(1, self.max_attempts + 1):
            ok, diag = probe(self.init_timeout)
            self._event("probe_ok" if ok else "probe_fail", diag, attempt)
            if ok:
                return True, diag
            if attempt < self.max_attempts and self.backoff > 0:
                # linear backoff: a wedged runtime sometimes clears after
                # the stuck client's grpc deadline lapses
                time.sleep(self.backoff * attempt)
        return False, diag

    def ensure_or_fallback(self, tiers: Optional[Sequence[str]] = None
                           ) -> Tuple[bool, str]:
        """Probe the configured tier; on failure walk DOWN the ranked
        tier ladder (mythril_tpu.backend) probing each lower tier once,
        and pin this process — via JAX_PLATFORMS, so heavy engine
        imports must not have run yet — to the first tier that answers.
        The floor tier (host CPU) needs no probe and is where the walk
        always terminates; landing there records the historical
        ``cpu_fallback`` event kind, landing on an intermediate tier
        records ``tier_fallback``. Returns (backend_ok, diagnosis) for
        the *configured* backend."""
        ok, diag = self.probe()
        if ok:
            return True, diag
        configured = self._configured_tier()
        landed = None
        for tier in tiers_below(configured, tiers):
            if tier == terminal_tier():
                break  # the floor is trusted, not probed
            if self.probe_fn is not None:
                tok, tdiag = self.probe_fn(tier_profile(tier).probe_timeout)
            else:
                tok, tdiag = probe_tier(tier)
            self._event("probe_ok" if tok else "probe_fail",
                        f"tier {tier}: {tdiag}")
            if tok:
                landed = tier
                break
        if landed is None:
            landed = terminal_tier()
        os.environ["JAX_PLATFORMS"] = tier_profile(landed).jax_platform
        kind = ("cpu_fallback" if landed == terminal_tier()
                else "tier_fallback")
        self._event(kind,
                    f"configured backend ({configured}) unreachable; "
                    f"demoted to the {landed} tier "
                    f"(JAX_PLATFORMS={tier_profile(landed).jax_platform})")
        return False, diag

    @staticmethod
    def _configured_tier() -> str:
        """The tier this process was asked to run on: a pinned
        JAX_PLATFORMS if it names a known tier, else the best rank
        (an unpinned process is assumed to want the best hardware)."""
        pinned = os.environ.get("JAX_PLATFORMS", "")
        for part in pinned.split(","):
            tier = part.strip().lower()
            if tier == "cuda":
                tier = "gpu"
            try:
                return tier_profile(tier).name
            except ValueError:
                continue
        return TIER_ORDER[0]

    def recover(self, reason: str = "device-lost") -> bool:
        """After a device loss mid-campaign: record it, re-probe with the
        usual bounded attempts. Returns whether the backend answered."""
        self._event("device_lost", reason)
        ok, _ = self.probe()
        return ok


# --- supervised engine worker (docs/resilience.md) ---------------------


class WorkerSupervisor:
    """Parent-side supervisor of ONE engine-worker subprocess
    (mythril_tpu/engine_worker.py): the worker owns the JAX backend and
    runs device batches; this class owns the worker.

    The isolation contract: a libtpu segfault, an OOM kill, or a hard
    hang inside the worker surfaces HERE as :class:`WorkerDied` /
    :class:`BatchTimeout` — a recoverable event the campaign's
    retry→ladder→bisect machinery already knows how to replay — never
    as parent-process death. Three layers:

    - **per-batch deadline, enforced from the parent** — the reply is
      awaited with ``select`` on the raw pipe fd; expiry SIGKILLs the
      worker (a wedged libtpu call cannot be interrupted any other
      way) and raises :class:`BatchTimeout`;
    - **restart with capped exponential backoff** — consecutive deaths
      double the respawn delay up to ``backoff_cap``, so a dying
      backend is probed, not hammered;
    - **crash-loop circuit breaker** — ``breaker_threshold`` deaths
      within ``breaker_window`` seconds open the breaker:
      :meth:`run_batch` raises :class:`WorkerCrashLoop` (the campaign
      pins the batch to the in-process CPU path) until
      ``breaker_cooldown`` lapses, then ONE half-open attempt decides
      whether to close (success) or re-open (another death).

    Every transition lands as a ``worker_spawn`` / ``worker_death`` /
    ``worker_restart`` / ``breaker_open`` / ``breaker_close`` event
    (via ``on_event`` — the campaign routes them into
    ``backend_events`` + the trace bus) and on the metrics registry
    (``engine_worker_{spawns,deaths,restarts}_total``,
    ``engine_worker_rss_bytes``, ``engine_worker_breaker_open``).

    ``stub=True`` spawns the protocol-only worker (no engine import) —
    the fast path for supervision-machinery tests; the child process,
    pipes, signals and deaths are all real either way.
    """

    def __init__(self, config: Optional[Dict] = None, *,
                 stub: bool = False,
                 batch_timeout: Optional[float] = None,
                 spawn_timeout: float = 300.0,
                 backoff_base: float = 0.5, backoff_cap: float = 30.0,
                 breaker_threshold: int = 3,
                 breaker_window: float = 60.0,
                 breaker_cooldown: float = 30.0,
                 fault_injector: Optional[FaultInjector] = None,
                 on_event: Optional[Callable] = None,
                 worker_env: Optional[Dict[str, str]] = None):
        self.config = dict(config or {})
        self.stub = bool(stub)
        self.batch_timeout = batch_timeout
        self.spawn_timeout = float(spawn_timeout)
        self.backoff_base = max(0.0, float(backoff_base))
        self.backoff_cap = max(0.0, float(backoff_cap))
        self.breaker_threshold = max(1, int(breaker_threshold))
        self.breaker_window = max(0.01, float(breaker_window))
        self.breaker_cooldown = max(0.0, float(breaker_cooldown))
        self.fault_injector = fault_injector
        self.on_event = on_event
        self.worker_env = dict(worker_env or {})
        self.proc: Optional[subprocess.Popen] = None
        self.events: List[Dict] = []
        self.restarts = 0
        self.spawns = 0
        self.rss_bytes = 0
        #: clock handshake result: ``parent_mono - child_mono``, set at
        #: init and refreshed per batch reply — added to worker-side
        #: ``mono`` readings so both processes share one timeline
        self.mono_offset: Optional[float] = None
        self._deaths: "collections.deque[float]" = collections.deque()
        self._consecutive = 0
        self._breaker_opened: Optional[float] = None
        self._lock = threading.RLock()

    # --- events / metrics ----------------------------------------------
    def _event(self, kind: str, detail: str = "", **kw) -> None:
        e = {"kind": kind, "detail": detail[:300],
             "t": round(time.time(), 3)}
        e.update(kw)
        self.events.append(e)
        if self.on_event is not None:
            self.on_event(kind, detail=detail[:300], **kw)
        else:
            from .obs import trace as obs_trace

            obs_trace.event(kind, **{k: v for k, v in e.items()
                                     if k != "kind"})

    def _counter(self, name: str, help: str = ""):
        from .obs import metrics as obs_metrics

        return obs_metrics.REGISTRY.counter(name, help=help)

    def _gauge(self, name: str, help: str = ""):
        from .obs import metrics as obs_metrics

        return obs_metrics.REGISTRY.gauge(name, help=help)

    # --- breaker --------------------------------------------------------
    def breaker_state(self) -> str:
        """``closed`` | ``open`` | ``half-open`` (cooldown lapsed; the
        next :meth:`run_batch` probes the worker once)."""
        if self._breaker_opened is None:
            return "closed"
        if time.monotonic() - self._breaker_opened < self.breaker_cooldown:
            return "open"
        return "half-open"

    def status(self) -> Dict:
        with self._lock:
            return {"alive": self.alive(),
                    "pid": self.proc.pid if self.proc else None,
                    "stub": self.stub,
                    "spawns": self.spawns,
                    "restarts": self.restarts,
                    "deaths_in_window": len(self._deaths),
                    "breaker": self.breaker_state(),
                    "rss_bytes": self.rss_bytes}

    def _check_breaker(self) -> None:
        state = self.breaker_state()
        if state == "open":
            raise WorkerCrashLoop(
                f"engine-worker breaker open ({len(self._deaths)} "
                f"deaths within {self.breaker_window:.0f}s); work is "
                f"pinned to the in-process CPU path for "
                f"{self.breaker_cooldown:.0f}s")
        if state == "half-open":
            self._event("breaker_half_open",
                        detail="cooldown lapsed; probing the worker "
                               "with one live batch")

    def _record_death(self, detail: str) -> None:
        now = time.monotonic()
        self._deaths.append(now)
        while self._deaths and now - self._deaths[0] > self.breaker_window:
            self._deaths.popleft()
        self._consecutive += 1
        rc = self.proc.poll() if self.proc is not None else None
        self._counter("engine_worker_deaths_total",
                      help="engine-worker subprocess deaths observed "
                           "by the supervisor").inc()
        self._event("worker_death", detail=detail, rc=rc,
                    deaths_in_window=len(self._deaths))
        self._flag_cache_dirty()
        self._reap()
        if self._breaker_opened is not None:
            # the half-open probe died: re-open for a fresh cooldown
            self._breaker_opened = now
            self._event("breaker_open",
                        detail="half-open probe died; breaker re-opened")
            self._gauge("engine_worker_breaker_open",
                        help="1 while the crash-loop breaker is open").set(1)
        elif len(self._deaths) >= self.breaker_threshold:
            self._breaker_opened = now
            self._counter("engine_worker_breaker_opens_total",
                          help="crash-loop breaker open transitions").inc()
            self._event("breaker_open",
                        detail=f"{len(self._deaths)} worker deaths "
                               f"within {self.breaker_window:.0f}s; "
                               "pinning work to the in-process CPU "
                               "path")
            self._gauge("engine_worker_breaker_open",
                        help="1 while the crash-loop breaker is open").set(1)

    def _flag_cache_dirty(self) -> None:
        """Drop the ``.dirty`` marker into the shared XLA cache dir (if
        one is configured): this worker died uncleanly, so it may have
        left a torn cache entry behind — the NEXT engine spawn probes
        the cache before trusting it (engine_worker._maybe_probe_cache)
        instead of segfaulting on a poisoned read. Best-effort: a
        missing marker just means no probe, which was the status quo."""
        cache = (self.worker_env.get("MYTHRIL_WORKER_JAX_CACHE")
                 or os.environ.get("MYTHRIL_WORKER_JAX_CACHE"))
        if not cache or not os.path.isdir(cache):
            return
        from .engine_worker import CACHE_DIRTY_MARKER

        try:
            with open(os.path.join(cache, CACHE_DIRTY_MARKER), "w") as fh:
                fh.write(f"pid={os.getpid()} t={time.time():.3f}\n")
        except OSError:
            pass

    def _note_success(self) -> None:
        self._consecutive = 0
        if self._breaker_opened is not None:
            self._breaker_opened = None
            self._deaths.clear()
            self._event("breaker_close",
                        detail="half-open probe succeeded; worker path "
                               "restored")
            self._gauge("engine_worker_breaker_open",
                        help="1 while the crash-loop breaker is open").set(0)

    # --- process lifecycle ---------------------------------------------
    def alive(self) -> bool:
        return self.proc is not None and self.proc.poll() is None

    def _exit_code(self) -> Optional[int]:
        """The worker's exit code right after an EOF: the pipe closes a
        beat before the process is waitable, so give it a moment —
        ``-11`` vs ``-9`` in the death event is real diagnostic
        signal."""
        if self.proc is None:
            return None
        try:
            return self.proc.wait(timeout=2)
        except (subprocess.TimeoutExpired, OSError):
            return self.proc.poll()

    def _reap(self) -> None:
        if self.proc is None:
            return
        try:
            if self.proc.poll() is None:
                self.proc.kill()
            self.proc.wait(timeout=5)
        except (OSError, subprocess.TimeoutExpired):
            pass  # unkillable (D-state): abandon, like the probe child
        for stream in (self.proc.stdin, self.proc.stdout):
            try:
                if stream is not None:
                    stream.close()
            except OSError:
                pass
        self.proc = None

    def _spawn_and_init(self) -> None:
        """Spawn + init-handshake one worker, honoring the restart
        backoff. Raises :class:`WorkerDied` when the worker cannot come
        up (counted as a death — a failing init IS the crash loop)."""
        if self._consecutive > 0:
            delay = min(self.backoff_cap,
                        self.backoff_base * (2 ** (self._consecutive - 1)))
            if delay > 0:
                time.sleep(delay)
        root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
        env = dict(os.environ)
        env.update(self.worker_env)
        self.proc = subprocess.Popen(
            [sys.executable, "-c",
             "import sys; sys.path.insert(0, %r); "
             "from mythril_tpu.engine_worker import worker_main; "
             "raise SystemExit(worker_main())" % root],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, env=env)
        self.spawns += 1
        self._counter("engine_worker_spawns_total",
                      help="engine-worker subprocesses spawned").inc()
        if self.spawns > 1:
            self.restarts += 1
            self._counter("engine_worker_restarts_total",
                          help="engine-worker respawns after a "
                               "death").inc()
            self._event("worker_restart", pid=self.proc.pid,
                        attempt=self.spawns,
                        detail=f"respawn #{self.restarts}")
        self._event("worker_spawn", pid=self.proc.pid,
                    detail="stub" if self.stub else "engine")
        from .obs import trace as obs_trace

        try:
            self._send({"op": "init", "stub": self.stub,
                        "trace": obs_trace.active(),
                        "config": self.config})
            rep = self._read_frame(time.monotonic() + self.spawn_timeout)
        except TimeoutError:
            self._record_death(
                f"worker init exceeded {self.spawn_timeout:.0f}s; "
                "killed")
            raise WorkerDied(
                f"engine worker init hung >{self.spawn_timeout:.0f}s "
                "(killed)") from None
        except (EOFError, OSError):
            rc = self._exit_code()
            self._record_death(f"worker died during init (rc={rc})")
            raise WorkerDied(
                f"engine worker died during init (rc={rc})") from None
        if not rep.get("ok"):
            # the worker is alive but could not build its engine (bad
            # config, missing dep): not a crash, but not usable either
            self._reap()
            raise self._rehydrate(rep)
        child_mono = (rep.get("value") or {}).get("mono")
        if isinstance(child_mono, (int, float)):
            self.mono_offset = time.monotonic() - float(child_mono)

    def close(self) -> None:
        """Orderly shutdown: ask the worker to exit, then reap."""
        with self._lock:
            if self.alive():
                try:
                    self._send({"op": "exit"})
                    self.proc.wait(timeout=5)
                except (OSError, subprocess.TimeoutExpired, TimeoutError):
                    pass
            self._reap()

    # --- framed IPC (length-prefixed pickle over the pipes) -------------
    def _send(self, msg: Dict) -> None:
        from .engine_worker import pack_frame

        self.proc.stdin.write(pack_frame(msg))
        self.proc.stdin.flush()

    def _read_frame(self, deadline: Optional[float]) -> Dict:
        """One reply frame from the worker, or TimeoutError (deadline)
        / EOFError (worker death, incl. a torn mid-reply frame)."""
        import pickle

        from .engine_worker import FRAME_HEADER

        hdr = self._read_exact(FRAME_HEADER.size, deadline)
        (n,) = FRAME_HEADER.unpack(hdr)
        return pickle.loads(self._read_exact(n, deadline))

    def _read_exact(self, n: int, deadline: Optional[float]) -> bytes:
        fd = self.proc.stdout.fileno()
        buf = b""
        while len(buf) < n:
            if deadline is not None:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    raise TimeoutError()
                wait = min(remaining, 0.5)
            else:
                wait = 0.5
            ready, _, _ = select.select([fd], [], [], wait)
            if not ready:
                if self.proc.poll() is not None:
                    raise EOFError()
                continue
            chunk = os.read(fd, n - len(buf))
            if not chunk:
                raise EOFError()
            buf += chunk
        return buf

    def _rehydrate(self, rep: Dict) -> BaseException:
        """Parent-side exception for a worker error reply, typed so the
        existing recovery paths (ladder / re-probe / bisect) classify
        it exactly like an in-process failure."""
        msg = f"{rep.get('etype', 'Error')}: {rep.get('emsg', '')}"[:500]
        kind = rep.get("classify")
        if kind == "oom":
            return ResourceExhausted(msg)
        if kind == "device-lost":
            return DeviceLostError(msg)
        return WorkerError(msg)

    # --- telemetry backhaul (docs/observability.md "Distributed
    # --- tracing") ------------------------------------------------------
    def _absorb_telemetry(self, tel, bi: int) -> None:
        """Land one batch reply's worker-side telemetry in this
        process: refresh the clock offset from the reply's fresh child
        ``mono`` reading, re-emit the drained spans/events offset-
        corrected (tagged ``proc="worker"``), and fold the metric
        delta into the parent registry."""
        if not isinstance(tel, dict):
            return
        from .obs import metrics as obs_metrics
        from .obs import trace as obs_trace

        child_mono = tel.get("mono")
        if isinstance(child_mono, (int, float)):
            self.mono_offset = time.monotonic() - float(child_mono)
        off = self.mono_offset or 0.0
        recs = tel.get("records") or ()
        if recs:
            obs_trace.reemit_records(
                recs, mono_offset=off, proc="worker",
                wpid=self.proc.pid if self.proc else None)
        obs_metrics.apply_delta(tel.get("metrics"))

    def _telemetry_lost(self, bi: int, detail: str) -> None:
        """The worker died with undelivered telemetry (its buffered
        spans/events die with the process): declare the loss instead of
        dropping it silently — an invisible device phase is exactly the
        blind spot this machinery exists to close."""
        from .obs import trace as obs_trace

        if not obs_trace.active():
            return  # worker was never tracing: nothing was lost
        self._counter(
            "engine_worker_telemetry_lost_total",
            help="batches whose worker-side spans/events died with "
                 "the worker before backhaul").inc()
        self._event("worker_telemetry_lost", detail=detail, batch=bi)

    def _update_rss(self) -> None:
        try:
            with open(f"/proc/{self.proc.pid}/statm") as fh:
                pages = int(fh.read().split()[1])
            self.rss_bytes = pages * os.sysconf("SC_PAGE_SIZE")
        except (OSError, ValueError, IndexError, AttributeError):
            return
        self._gauge("engine_worker_rss_bytes",
                    help="resident set size of the engine worker "
                         "subprocess").set(self.rss_bytes)

    # --- the one entry point -------------------------------------------
    def run_batch(self, bi: int, names: Sequence[str],
                  codes: Sequence[bytes],
                  lanes: Optional[int] = None,
                  width: Optional[int] = None,
                  on_cpu: bool = False,
                  on_tier: Optional[str] = None) -> Dict:
        """Run one batch in the worker under the parent-side deadline.
        Raises :class:`WorkerCrashLoop` (breaker open),
        :class:`BatchTimeout` (deadline; worker killed),
        :class:`WorkerDied` (crash mid-batch), or the rehydrated typed
        error the worker reported. Returns the batch's partial-result
        dict (``issues``/``paths``/``dropped``/``iprof``)."""
        with self._lock:
            self._check_breaker()
            if not self.alive():
                self._spawn_and_init()
            if self.fault_injector is not None:
                sig = self.fault_injector.worker_signal(
                    batch=bi, contracts=names)
                if sig is not None:
                    try:
                        os.kill(self.proc.pid, sig)
                    except OSError:
                        pass
            deadline = (time.monotonic() + self.batch_timeout
                        if self.batch_timeout is not None else None)
            from .obs import trace as obs_trace

            try:
                self._send({"op": "batch", "bi": int(bi),
                            "names": [str(x) for x in names],
                            "codes": [bytes(c) for c in codes],
                            "lanes": lanes, "width": width,
                            "on_cpu": bool(on_cpu or on_tier == "cpu"),
                            "on_tier": on_tier,
                            "trace": obs_trace.context_snapshot()})
                rep = self._read_frame(deadline)
            except TimeoutError:
                self._telemetry_lost(
                    bi, f"batch {bi} deadline; worker killed with "
                        "its span buffer")
                self._record_death(
                    f"batch {bi} exceeded {self.batch_timeout:.1f}s; "
                    "worker killed")
                raise BatchTimeout(
                    f"batch {bi} exceeded {self.batch_timeout:.1f}s "
                    "wall-clock budget in the engine worker (worker "
                    "killed)") from None
            except (EOFError, OSError):
                rc = self._exit_code()
                self._telemetry_lost(
                    bi, f"worker died mid-batch {bi} (rc={rc}); span "
                        "buffer lost with it")
                self._record_death(f"worker died mid-batch {bi} (rc={rc})")
                raise WorkerDied(
                    f"engine worker died mid-batch {bi} (rc={rc})"
                ) from None
            if not rep.get("ok"):
                # an error REPLY means the worker survived: the fault
                # was contained inside the engine, not the process
                self._note_success()
                self._update_rss()
                raise self._rehydrate(rep)
            self._note_success()
            self._update_rss()
            value = rep["value"]
            if isinstance(value, dict):
                self._absorb_telemetry(value.pop("telemetry", None), bi)
            return value

    def prewarm(self, buckets: Sequence[Dict],
                on_tier: Optional[str] = None) -> Dict:
        """AOT-prewarm a list of shape buckets in the worker (the
        compile-store recovery path, docs/serving.md "Compile artifacts
        & prewarm"). Same lifecycle discipline as :meth:`run_batch` —
        breaker check, spawn-on-demand, parent-side deadline (the spawn
        timeout: a prewarm is all compile, which is exactly what that
        budget was sized for), death accounting — so a wedged prewarm
        can never outlive its budget and a crashy one trips the same
        breaker live batches do. Returns the worker's ``{done, total}``
        reply; raises the same typed errors as ``run_batch``."""
        buckets = [dict(b) for b in buckets]
        with self._lock:
            self._check_breaker()
            if not self.alive():
                self._spawn_and_init()
            deadline = time.monotonic() + self.spawn_timeout
            from .obs import trace as obs_trace

            try:
                self._send({"op": "prewarm", "buckets": buckets,
                            "on_tier": on_tier,
                            "trace": obs_trace.context_snapshot()})
                rep = self._read_frame(deadline)
            except TimeoutError:
                self._record_death(
                    f"prewarm ({len(buckets)} buckets) exceeded "
                    f"{self.spawn_timeout:.0f}s; worker killed")
                raise BatchTimeout(
                    f"prewarm exceeded {self.spawn_timeout:.0f}s "
                    "wall-clock budget in the engine worker (worker "
                    "killed)") from None
            except (EOFError, OSError):
                rc = self._exit_code()
                self._record_death(f"worker died mid-prewarm (rc={rc})")
                raise WorkerDied(
                    f"engine worker died mid-prewarm (rc={rc})"
                ) from None
            if not rep.get("ok"):
                self._note_success()
                raise self._rehydrate(rep)
            self._note_success()
            self._update_rss()
            value = rep["value"]
            if isinstance(value, dict):
                self._absorb_telemetry(value.pop("telemetry", None), -1)
            return value


__all__ = [
    "BackendManager", "BatchTimeout", "DEGRADE_RUNGS", "DeviceLostError",
    "FaultInjector", "FaultSpec", "InjectedKill", "ResilienceError",
    "ResourceExhausted", "WorkerCrashLoop", "WorkerDied", "WorkerError",
    "WorkerSupervisor", "classify_backend_error", "parse_ladder",
    "run_with_watchdog",
]
