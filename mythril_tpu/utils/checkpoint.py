"""Durable frontier / campaign checkpoints (crash-consistent resume).

The reference has NO checkpointing (SURVEY.md §5.4 marks it absent and
required for pod-scale runs). The SoA design makes it nearly free: a
:class:`SymFrontier` is a pytree of fixed-shape arrays, so a checkpoint
is one ``npz`` of named leaves plus a JSON meta blob (tx index, segment
counter). Resume = load the arrays back into a template frontier of the
same shape config.

What "durable" adds (docs/checkpointing.md has the full story): the
checkpoint is the ONLY resume point of a multi-hour campaign, so a kill
mid-write must never cost more than one batch of work. Every writer
here therefore goes tmp-file → flush → fsync → atomic rename, rotates
the previous good file to ``<path>.1`` first, and embeds a schema
version plus per-leaf and whole-file sha256 digests. Loaders verify
integrity before trusting a single byte and raise the typed
:class:`CheckpointCorrupt` (never a bare ``ValueError``) so callers can
distinguish "this file is torn — fall back to the rotated copy" from
"this file is healthy but was written under a different shape config"
(which stays ``ValueError``: falling back would silently resume the
wrong run).

v1 files (pre-versioning: raw npz / raw JSON, no digests) still load —
they simply skip the integrity verification they never carried.
"""

from __future__ import annotations

import hashlib
import io
import json
import logging
import os
import threading
from typing import Any, Dict, Optional, Tuple

import numpy as np
import jax

from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace

log = logging.getLogger(__name__)

#: current on-disk schema of both the npz frontier checkpoint and the
#: JSON campaign checkpoint. v1 = the unversioned formats of PR <= 1.
CHECKPOINT_SCHEMA = 2

#: rotated last-known-good suffix: ``save`` moves the previous file to
#: ``<path>.1`` before renaming the new one into place
ROTATE_SUFFIX = ".1"

# whole-file integrity trailer appended AFTER the npz payload: zip
# readers locate the archive from its end, so the trailer must be
# stripped before np.load — which is exactly what lets a loader verify
# the digest before handing bytes to the zip machinery. (Trailing junk
# breaks np.load, so a v1 reader would loudly reject a v2 file instead
# of silently misreading it.)
_TRAILER_MAGIC = b"MYTHCKPT2:"
_TRAILER_LEN = len(_TRAILER_MAGIC) + 64  # magic + sha256 hexdigest


class CheckpointCorrupt(RuntimeError):
    """The checkpoint file is torn, truncated, or fails its checksums —
    the caller should fall back to the rotated last-known-good copy.
    Deliberately NOT a ``ValueError``: a shape/config mismatch (healthy
    file, wrong run) keeps raising ``ValueError`` so resume logic can
    tell the two apart."""


def _quarantine_corrupt(path: str) -> None:
    """Move a verified-corrupt newest file to ``<path>.corrupt``
    (best-effort, evidence preserved): if it stayed in place, the next
    save's rotation would shove the garbage over the last-known-good
    ``<path>.1`` — destroying the only fallback."""
    try:
        os.replace(path, path + ".corrupt")
    except OSError:
        pass


def _leaf_names(tree) -> Tuple[list, Any]:
    """Stable dotted names for every leaf + the treedef."""
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(tree)
    names, leaves = [], []
    for path, leaf in leaves_with_path:
        names.append("/".join(str(getattr(p, "name", getattr(p, "idx", p)))
                              for p in path))
        leaves.append(leaf)
    return list(zip(names, leaves)), treedef


def _leaf_sha256(arr: np.ndarray) -> str:
    """Content digest of one leaf: dtype + shape + raw bytes, so a
    bit-identical buffer reinterpreted under another dtype still fails."""
    h = hashlib.sha256()
    h.update(str(arr.dtype.str).encode())
    h.update(str(tuple(arr.shape)).encode())
    h.update(np.ascontiguousarray(arr).tobytes())
    return h.hexdigest()


def fsync_dir(path: str) -> None:
    """Flush the directory entry so the rename itself survives a power
    cut (best-effort: not every filesystem supports dir fds). Shared
    with the fleet ledger's link-exclusive writes (mythril_tpu/fleet.py)."""
    d = os.path.dirname(os.path.abspath(path))
    try:
        fd = os.open(d, os.O_RDONLY)
    except OSError:
        return
    try:
        os.fsync(fd)
    except OSError:
        pass
    finally:
        os.close(fd)


def durable_write(path: str, data: bytes, rotate: bool = True) -> None:
    """THE atomic-write discipline every durable artifact in this repo
    shares (checkpoints here, unit results and manifests in
    mythril_tpu/fleet.py): tmp file + flush + fsync +
    rotate-previous + atomic rename."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    if rotate and os.path.exists(path):
        # the previous (verified-at-write-time) file becomes the
        # last-known-good fallback; a crash between the two renames
        # leaves only <path>.1, which loaders try next
        os.replace(path, path + ROTATE_SUFFIX)
    os.replace(tmp, path)
    fsync_dir(path)


def exclusive_write(path: str, data: bytes) -> bool:
    """Atomically create ``path`` with ``data`` IFF it does not already
    exist: tmp file + fsync + ``os.link`` (which fails with EEXIST
    instead of overwriting, unlike rename). Returns whether this caller
    won — the first-wins primitive behind fleet unit commits
    (mythril_tpu/fleet.py), create-once manifests, and the solver
    verdict store (mythril_tpu/smt/vstore.py). The tmp name carries pid
    AND thread id so in-process fleets (threaded workers) never
    collide."""
    import threading

    tmp = f"{path}.{os.getpid()}-{threading.get_ident()}.tmp"
    with open(tmp, "wb") as fh:
        fh.write(data)
        fh.flush()
        os.fsync(fh.fileno())
    try:
        os.link(tmp, path)
        won = True
    except FileExistsError:
        won = False
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass
    if won:
        fsync_dir(path)
    return won


# --- frontier (npz) checkpoints ---------------------------------------


def save_frontier(path: str, sf, meta: Dict | None = None,
                  rotate: bool = True) -> None:
    """Serialize a SymFrontier (or any pytree of arrays) + meta to a
    versioned, checksummed npz, written durably (tmp + fsync + atomic
    rename) with the previous file rotated to ``<path>.1``."""
    with obs_trace.timer("checkpoint_save", what="frontier",
                         file=os.path.basename(path)) as sp:
        named, _ = _leaf_names(sf)
        arrays = {}
        leaf_sha: Dict[str, str] = {}
        for i, (name, leaf) in enumerate(named):
            arr = np.asarray(leaf)
            arrays[f"leaf{i}::{name}"] = arr
            leaf_sha[name] = _leaf_sha256(arr)
        arrays["__meta__"] = np.frombuffer(
            json.dumps(meta or {}).encode(), dtype=np.uint8)
        arrays["__schema__"] = np.frombuffer(
            json.dumps({"version": CHECKPOINT_SCHEMA,
                        "leaf_sha256": leaf_sha}).encode(), dtype=np.uint8)
        buf = io.BytesIO()
        np.savez_compressed(buf, **arrays)
        body = buf.getvalue()
        digest = hashlib.sha256(body).hexdigest().encode()
        durable_write(path, body + _TRAILER_MAGIC + digest, rotate=rotate)
    obs_metrics.REGISTRY.histogram(
        "checkpoint_write_seconds",
        help="durable checkpoint save latency").observe(sp.elapsed)


def _read_npz_body(path: str) -> Tuple[bytes, bool]:
    """``(raw npz bytes, had_trailer)`` with the whole-file digest
    verified and stripped. A v1 file (no trailer) returns as-is — it
    never carried a digest; the caller cross-checks ``had_trailer``
    against the schema version INSIDE the archive, so a tear that chops
    only the trailer off a v2 file (zip readers tolerate trailing junk)
    is still detected."""
    with open(path, "rb") as fh:
        raw = fh.read()
    if len(raw) >= _TRAILER_LEN and \
            raw[-_TRAILER_LEN:-64] == _TRAILER_MAGIC:
        body, digest = raw[:-_TRAILER_LEN], raw[-64:]
        got = hashlib.sha256(body).hexdigest().encode()
        if got != digest:
            raise CheckpointCorrupt(
                f"{path}: whole-file sha256 mismatch (torn write?)")
        return body, True
    return raw, False  # v1: unversioned, no trailer


def load_frontier(path: str, template) -> Tuple[Any, Dict]:
    """Rebuild a pytree from ``path`` using ``template`` for the
    structure, verifying integrity first.

    The template must have the same shape configuration (lanes + limits)
    the checkpoint was written with. Leaves match by NAME (not index),
    so field reordering between versions cannot silently transpose
    arrays. Raises:

    - :class:`CheckpointCorrupt` — torn/truncated file, checksum
      mismatch, unreadable npz, missing/renamed leaf, dtype mismatch,
      or a schema newer than this reader;
    - ``ValueError`` — healthy file whose leaf SHAPES disagree with the
      template (a different lanes/limits config, not corruption).
    """
    with obs_trace.timer("checkpoint_load", what="frontier",
                         file=os.path.basename(path)) as sp:
        out = _load_frontier_inner(path, template)
    obs_metrics.REGISTRY.histogram(
        "checkpoint_load_seconds",
        help="checkpoint load+verify latency").observe(sp.elapsed)
    return out


def _load_frontier_inner(path: str, template) -> Tuple[Any, Dict]:
    body, had_trailer = _read_npz_body(path)
    try:
        # eager member reads: zip CRC errors surface lazily at access
        # time, and a v1 file has no whole-file digest to catch a torn
        # member earlier — every read must land inside this typed guard
        data = np.load(io.BytesIO(body))
        arrays = {k: data[k] for k in data.files}
    except Exception as e:  # noqa: BLE001 — zip/format errors vary
        raise CheckpointCorrupt(f"{path}: unreadable npz ({e})") from e
    try:
        meta = (json.loads(bytes(arrays["__meta__"]).decode())
                if "__meta__" in arrays else {})
        schema = (json.loads(bytes(arrays["__schema__"]).decode())
                  if "__schema__" in arrays else {"version": 1})
    except (ValueError, UnicodeDecodeError) as e:
        raise CheckpointCorrupt(f"{path}: undecodable meta blob ({e})") from e
    version = int(schema.get("version", 1))
    if version > CHECKPOINT_SCHEMA:
        raise CheckpointCorrupt(
            f"{path}: schema v{version} is newer than this reader "
            f"(supports <= v{CHECKPOINT_SCHEMA})")
    if version >= 2 and not had_trailer:
        # the archive says v2 but the trailer is gone: a tear that
        # chopped only the trailing digest — the zip machinery tolerates
        # trailing junk, so this is the one tear shape the digest itself
        # cannot catch
        raise CheckpointCorrupt(
            f"{path}: v{version} checkpoint missing its integrity "
            "trailer (torn write?)")
    leaf_sha = schema.get("leaf_sha256", {})

    by_name: Dict[str, np.ndarray] = {}
    for key, arr in arrays.items():
        if key.startswith("__"):
            continue
        try:
            _, name = key.split("::", 1)
        except ValueError:
            raise CheckpointCorrupt(
                f"{path}: malformed leaf key {key!r}") from None
        by_name[name] = arr

    named, treedef = _leaf_names(template)
    leaves = []
    for name, tmpl_leaf in named:
        if name not in by_name:
            if name.endswith("op_resid"):
                # v1 frontiers predate the iprof residual sidecar; it
                # starts empty on resume (its content was already
                # harvested or lost with the old format's fold-in)
                leaves.append(np.asarray(tmpl_leaf))
                continue
            raise CheckpointCorrupt(
                f"{path}: checkpoint missing leaf {name!r}")
        arr = by_name[name]
        want = leaf_sha.get(name)
        if want is not None and _leaf_sha256(arr) != want:
            raise CheckpointCorrupt(
                f"{path}: leaf {name!r} fails its sha256")
        tmpl_arr = np.asarray(tmpl_leaf)
        if tuple(arr.shape) != tuple(tmpl_arr.shape):
            raise ValueError(
                f"shape mismatch for {name}: {arr.shape} vs "
                f"{tmpl_arr.shape}")
        if arr.dtype != tmpl_arr.dtype:
            raise CheckpointCorrupt(
                f"{path}: dtype mismatch for {name}: {arr.dtype} vs "
                f"{tmpl_arr.dtype}")
        leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, leaves), meta


def load_frontier_resilient(path: str, template) -> Tuple[Any, Dict, str]:
    """``load_frontier`` with fallback to the rotated last-known-good
    copy: returns ``(tree, meta, source_path)``. A corrupt (or missing)
    newest file degrades to ``<path>.1``; only when both are unusable
    does the newest file's error propagate."""
    first_err: Optional[BaseException] = None
    for p in (path, path + ROTATE_SUFFIX):
        try:
            tree, meta = load_frontier(p, template)
            if p != path:
                log.warning("checkpoint %s unusable (%s); resumed from "
                            "rotated copy %s", path, first_err, p)
            return tree, meta, p
        except FileNotFoundError as e:
            if first_err is None:
                first_err = e
        except CheckpointCorrupt as e:
            if first_err is None:
                first_err = e
            if p == path:
                _quarantine_corrupt(p)
    raise first_err  # type: ignore[misc]


# --- campaign (JSON) checkpoints --------------------------------------


def save_json_checkpoint(path: str, state: Dict, rotate: bool = True) -> None:
    """Durable, checksummed JSON state: the payload is wrapped as
    ``{"__schema__": 2, "sha256": <hex of canonical state>, "state":
    ...}`` and written tmp + fsync + rotate + atomic rename."""
    with obs_trace.timer("checkpoint_save", what="campaign",
                         file=os.path.basename(path)) as sp:
        payload = json.dumps(state, sort_keys=True)
        doc = {"__schema__": CHECKPOINT_SCHEMA,
               "sha256": hashlib.sha256(payload.encode()).hexdigest(),
               "state": state}
        durable_write(path, json.dumps(doc).encode(), rotate=rotate)
    obs_metrics.REGISTRY.histogram(
        "checkpoint_write_seconds",
        help="durable checkpoint save latency").observe(sp.elapsed)


def load_json_checkpoint(path: str) -> Dict:
    """Verified state dict from ``path``. A v1 file (bare state dict, no
    ``__schema__`` wrapper) loads as-is. Raises
    :class:`CheckpointCorrupt` on torn JSON / checksum mismatch /
    unsupported schema, ``FileNotFoundError`` when absent."""
    with obs_trace.span("checkpoint_load", what="campaign",
                        file=os.path.basename(path)):
        return _load_json_checkpoint_inner(path)


def _load_json_checkpoint_inner(path: str) -> Dict:
    with open(path, "rb") as fh:
        raw = fh.read()
    try:
        doc = json.loads(raw.decode())
    except (ValueError, UnicodeDecodeError) as e:
        raise CheckpointCorrupt(f"{path}: unreadable JSON ({e})") from e
    if not isinstance(doc, dict):
        raise CheckpointCorrupt(f"{path}: expected a JSON object")
    if "__schema__" not in doc:
        return doc  # v1: the file IS the state
    version = int(doc["__schema__"])
    if version > CHECKPOINT_SCHEMA:
        raise CheckpointCorrupt(
            f"{path}: schema v{version} is newer than this reader")
    state = doc.get("state")
    if not isinstance(state, dict):
        raise CheckpointCorrupt(f"{path}: missing state payload")
    want = doc.get("sha256")
    got = hashlib.sha256(
        json.dumps(state, sort_keys=True).encode()).hexdigest()
    if want != got:
        raise CheckpointCorrupt(f"{path}: state sha256 mismatch")
    return state


class BackgroundCheckpointWriter:
    """Serialize + durably write campaign JSON checkpoints off the
    critical path (the pipelined campaign's host phase must not stall
    on fsync — docs/performance.md).

    One worker thread; submissions COALESCE (latest state wins). That is
    safe because every submitted state is a complete, self-contained
    snapshot: skipping an intermediate one only widens the replay window
    after a crash, it never breaks consistency. Each write goes through
    :func:`save_json_checkpoint` — the identical v2
    tmp+fsync+rotate+atomic-rename contract as the synchronous path, so
    a kill at ANY instant (including mid-background-write) still leaves
    either the previous durable file or its rotated ``.1`` loadable.

    A write failure is remembered and re-raised at the next ``submit``
    or ``flush``/``close`` — a campaign must not silently run on without
    durability. The thread is a daemon: an abrupt interpreter death
    behaves exactly like kill -9 mid-write, which the loaders' checksum
    + rotation fallback already covers.
    """

    def __init__(self, path: str):
        self.path = path
        self._cond = threading.Condition()
        self._pending: Optional[Tuple[Dict, Optional[Any]]] = None
        self._writing = False
        self._stop = False
        self._error: Optional[BaseException] = None
        self._thread = threading.Thread(
            target=self._run, daemon=True,
            name=f"ckpt-writer:{os.path.basename(path)}")
        self._thread.start()

    def _run(self) -> None:
        while True:
            with self._cond:
                while self._pending is None and not self._stop:
                    self._cond.wait()
                if self._pending is None:
                    return  # stopped with nothing left to write
                state, on_durable = self._pending
                self._pending = None
                self._writing = True
            try:
                save_json_checkpoint(self.path, state)
                if on_durable is not None:
                    on_durable()
            except Exception as e:  # noqa: BLE001 — surfaced at submit
                with self._cond:
                    self._error = e
            finally:
                with self._cond:
                    self._writing = False
                    self._cond.notify_all()

    def _raise_pending_error_locked(self) -> None:
        if self._error is not None:
            e, self._error = self._error, None
            raise e

    def submit(self, state: Dict,
               on_durable: Optional[Any] = None) -> None:
        """Queue ``state`` for a durable write (replacing any not-yet-
        started queued state). ``on_durable`` (zero-arg callable) runs in
        the writer thread after the rename lands. The caller must not
        mutate ``state`` afterwards — pass a snapshot."""
        with self._cond:
            self._raise_pending_error_locked()
            if self._stop:
                raise RuntimeError(f"checkpoint writer for {self.path} "
                                   "is closed")
            self._pending = (state, on_durable)
            self._cond.notify_all()

    def flush(self) -> None:
        """Block until everything submitted so far is durably on disk."""
        with self._cond:
            while self._pending is not None or self._writing:
                self._cond.wait()
            self._raise_pending_error_locked()

    def close(self, discard_pending: bool = False) -> None:
        """Stop the writer. By default the queued state (if any) is
        written first; ``discard_pending`` drops it — the simulated-kill
        path, where flushing would grant durability a real SIGKILL never
        would. An in-flight write always completes (it cannot be
        interrupted, same as a real kill racing the rename)."""
        with self._cond:
            if discard_pending:
                self._pending = None
            self._stop = True
            self._cond.notify_all()
        self._thread.join(timeout=60.0)
        if not discard_pending:
            with self._cond:
                self._raise_pending_error_locked()


def load_json_checkpoint_resilient(
        path: str) -> Tuple[Optional[Dict], Optional[str]]:
    """``(state, source_path)`` trying ``path`` then ``<path>.1``.
    ``(None, None)`` when no checkpoint exists at all (fresh start).
    Raises :class:`CheckpointCorrupt` only when a newest-file corruption
    has NO healthy rotated copy to fall back to AND a rotated file
    exists but is itself corrupt — a torn first-ever checkpoint (no
    rotation yet) degrades to a fresh start, because nothing older was
    ever persisted."""
    try:
        return load_json_checkpoint(path), path
    except FileNotFoundError:
        return None, None
    except CheckpointCorrupt as newest_err:
        _quarantine_corrupt(path)
        try:
            state = load_json_checkpoint(path + ROTATE_SUFFIX)
        except FileNotFoundError:
            # first checkpoint torn before any rotation: at most one
            # batch of work existed, and none of it was durably recorded
            log.warning("checkpoint %s corrupt (%s) with no rotated "
                        "copy; starting fresh", path, newest_err)
            return None, None
        except CheckpointCorrupt as e:
            raise CheckpointCorrupt(
                f"{path} and its rotated copy are both corrupt "
                f"({newest_err}; {e})") from e
        log.warning("checkpoint %s corrupt (%s); resumed from rotated "
                    "copy", path, newest_err)
        return state, path + ROTATE_SUFFIX


__all__ = [
    "BackgroundCheckpointWriter", "CHECKPOINT_SCHEMA", "CheckpointCorrupt",
    "ROTATE_SUFFIX", "durable_write", "exclusive_write", "fsync_dir",
    "load_frontier",
    "load_frontier_resilient", "load_json_checkpoint",
    "load_json_checkpoint_resilient", "save_frontier",
    "save_json_checkpoint",
]
