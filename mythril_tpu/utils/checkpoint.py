"""Frontier checkpoint / resume.

The reference has NO checkpointing (SURVEY.md §5.4 marks it absent and
required for pod-scale runs). The SoA design makes it nearly free: a
:class:`SymFrontier` is a pytree of fixed-shape arrays, so a checkpoint
is one ``npz`` of named leaves plus a JSON meta blob (tx index, segment
counter). Resume = load the arrays back into a template frontier of the
same shape config.
"""

from __future__ import annotations

import io
import json
from typing import Any, Dict, Tuple

import numpy as np
import jax


def _leaf_names(tree) -> Tuple[list, Any]:
    """Stable dotted names for every leaf + the treedef."""
    leaves_with_path, treedef = jax.tree_util.tree_flatten_with_path(tree)
    names, leaves = [], []
    for path, leaf in leaves_with_path:
        names.append("/".join(str(getattr(p, "name", getattr(p, "idx", p)))
                              for p in path))
        leaves.append(leaf)
    return list(zip(names, leaves)), treedef


def save_frontier(path: str, sf, meta: Dict | None = None) -> None:
    """Serialize a SymFrontier (or any pytree of arrays) + meta to npz."""
    named, _ = _leaf_names(sf)
    arrays = {f"leaf{i}::{name}": np.asarray(leaf)
              for i, (name, leaf) in enumerate(named)}
    arrays["__meta__"] = np.frombuffer(
        json.dumps(meta or {}).encode(), dtype=np.uint8)
    with open(path, "wb") as fh:
        np.savez_compressed(fh, **arrays)


def load_frontier(path: str, template) -> Tuple[Any, Dict]:
    """Rebuild a pytree from `path` using `template` for the structure.

    The template must have the same shape configuration (lanes + limits)
    the checkpoint was written with; leaf names are cross-checked.
    """
    with open(path, "rb") as fh:
        data = np.load(io.BytesIO(fh.read()))
    meta = json.loads(bytes(data["__meta__"]).decode()) if "__meta__" in data else {}
    named, treedef = _leaf_names(template)
    by_index = {}
    for key in data.files:
        if key == "__meta__":
            continue
        idx_s, name = key.split("::", 1)
        by_index[int(idx_s[4:])] = (name, data[key])
    leaves = []
    for i, (name, tmpl_leaf) in enumerate(named):
        if i not in by_index:
            raise ValueError(f"checkpoint missing leaf {i} ({name})")
        got_name, arr = by_index[i]
        if got_name != name:
            raise ValueError(
                f"checkpoint layout mismatch at leaf {i}: {got_name!r} != {name!r}")
        if tuple(arr.shape) != tuple(np.shape(tmpl_leaf)):
            raise ValueError(
                f"shape mismatch for {name}: {arr.shape} vs {np.shape(tmpl_leaf)}")
        leaves.append(arr)
    return jax.tree_util.tree_unflatten(treedef, leaves), meta
