"""4-byte selector -> function signature database.

Reference: ``mythril/support/signatures.py`` (⚠unv) — sqlite cache +
remote 4byte.directory lookups. This environment has no network, so the
DB is local-only: a built-in table of common signatures (selectors
computed with the in-repo keccak, which doubles as a self-check), plus an
optional user JSON file. ``Issue.function`` is labeled through this
(VERDICT r2: "Signature DB absent; Issue.function always empty").
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Union

from ..ops.keccak import keccak256_host

_COMMON_SIGNATURES = [
    "transfer(address,uint256)",
    "transferFrom(address,address,uint256)",
    "approve(address,uint256)",
    "balanceOf(address)",
    "allowance(address,address)",
    "totalSupply()",
    "name()",
    "symbol()",
    "decimals()",
    "owner()",
    "transferOwnership(address)",
    "renounceOwnership()",
    "mint(address,uint256)",
    "burn(uint256)",
    "burnFrom(address,uint256)",
    "deposit()",
    "withdraw(uint256)",
    "withdraw()",
    "pause()",
    "unpause()",
    "kill()",
    "destroy()",
    "setOwner(address)",
    "initialize()",
    "fallback()",
    "safeTransferFrom(address,address,uint256)",
    "ownerOf(uint256)",
    "tokenURI(uint256)",
    "getApproved(uint256)",
    "setApprovalForAll(address,bool)",
    "isApprovedForAll(address,address)",
    "permit(address,address,uint256,uint256,uint8,bytes32,bytes32)",
    "swapExactTokensForTokens(uint256,uint256,address[],address,uint256)",
    "flashLoan(address,address,uint256,bytes)",
]


def selector_of(signature: str) -> str:
    """4-byte selector hex (no 0x) of a canonical signature string."""
    return keccak256_host(signature.encode())[:4].hex()


class SignatureDB:
    """selector (8 hex chars) -> list of signature strings."""

    def __init__(self, path: Optional[str] = None):
        self._by_sel: Dict[str, List[str]] = {}
        for sig in _COMMON_SIGNATURES:
            self.add(sig)
        self.path = path
        if path and os.path.exists(path):
            with open(path) as fh:
                for sel, sigs in json.load(fh).items():
                    self._by_sel.setdefault(sel.lower().removeprefix("0x"),
                                            []).extend(sigs)

    def add(self, signature: str) -> str:
        sel = selector_of(signature)
        bucket = self._by_sel.setdefault(sel, [])
        if signature not in bucket:
            bucket.append(signature)
        return sel

    def lookup(self, selector: Union[str, bytes, int]) -> List[str]:
        if isinstance(selector, bytes):
            sel = selector[:4].hex()
        elif isinstance(selector, int):
            sel = f"{selector & 0xFFFFFFFF:08x}"
        else:
            sel = selector.lower().removeprefix("0x")[:8]
        return list(self._by_sel.get(sel, []))

    def save(self, path: Optional[str] = None) -> None:
        if not (path or self.path):
            raise ValueError("SignatureDB.save: no path configured")
        with open(path or self.path, "w") as fh:
            json.dump(self._by_sel, fh, indent=1, sort_keys=True)
