"""4-byte selector -> function signature database.

Reference: ``mythril/support/signatures.py`` (⚠unv) — sqlite cache +
remote 4byte.directory lookups. Three tiers here: a built-in table of
common signatures (selectors computed with the in-repo keccak, which
doubles as a self-check), an optional user JSON file, and an optional
REMOTE 4byte.directory-shaped endpoint (``MYTHRIL_4BYTE_URL`` or the
``remote_url`` parameter) queried on local miss and memoized into the
local table. The public 4byte.directory is unreachable in this
zero-egress image, so the remote tier is loopback-tested the same way
the RPC client is (tests/test_signatures_remote.py). ``Issue.function``
is labeled through this (VERDICT r2: "Signature DB absent").
"""

from __future__ import annotations

import json
import os
from typing import Dict, List, Optional, Union

from ..ops.keccak import keccak256_host

_COMMON_SIGNATURES = [
    "transfer(address,uint256)",
    "transferFrom(address,address,uint256)",
    "approve(address,uint256)",
    "balanceOf(address)",
    "allowance(address,address)",
    "totalSupply()",
    "name()",
    "symbol()",
    "decimals()",
    "owner()",
    "transferOwnership(address)",
    "renounceOwnership()",
    "mint(address,uint256)",
    "burn(uint256)",
    "burnFrom(address,uint256)",
    "deposit()",
    "withdraw(uint256)",
    "withdraw()",
    "pause()",
    "unpause()",
    "kill()",
    "destroy()",
    "setOwner(address)",
    "initialize()",
    "fallback()",
    "safeTransferFrom(address,address,uint256)",
    "ownerOf(uint256)",
    "tokenURI(uint256)",
    "getApproved(uint256)",
    "setApprovalForAll(address,bool)",
    "isApprovedForAll(address,address)",
    "permit(address,address,uint256,uint256,uint8,bytes32,bytes32)",
    "swapExactTokensForTokens(uint256,uint256,address[],address,uint256)",
    "flashLoan(address,address,uint256,bytes)",
]


def selector_of(signature: str) -> str:
    """4-byte selector hex (no 0x) of a canonical signature string."""
    return keccak256_host(signature.encode())[:4].hex()


class SignatureDB:
    """selector (8 hex chars) -> list of signature strings."""

    def __init__(self, path: Optional[str] = None,
                 remote_url: Optional[str] = None,
                 remote_timeout: float = 3.0):
        self._by_sel: Dict[str, List[str]] = {}
        for sig in _COMMON_SIGNATURES:
            self.add(sig)
        self.path = path
        if path and os.path.exists(path):
            with open(path) as fh:
                for sel, sigs in json.load(fh).items():
                    self._by_sel.setdefault(sel.lower().removeprefix("0x"),
                                            []).extend(sigs)
        # remote 4byte.directory tier (reference: signature lookups hit
        # https://www.4byte.directory/api/v1/signatures/?hex_signature=…
        # ⚠unv); opt-in via arg or env, misses memoized as misses for
        # the process so an offline endpoint costs one timeout per
        # selector, not one per issue
        self.remote_url = remote_url or os.environ.get("MYTHRIL_4BYTE_URL")
        self.remote_timeout = remote_timeout
        self._remote_miss: set = set()

    def add(self, signature: str) -> str:
        sel = selector_of(signature)
        bucket = self._by_sel.setdefault(sel, [])
        if signature not in bucket:
            bucket.append(signature)
        return sel

    def lookup(self, selector: Union[str, bytes, int]) -> List[str]:
        if isinstance(selector, bytes):
            sel = selector[:4].hex()
        elif isinstance(selector, int):
            sel = f"{selector & 0xFFFFFFFF:08x}"
        else:
            sel = selector.lower().removeprefix("0x")[:8]
        hit = self._by_sel.get(sel)
        if hit:
            return list(hit)
        if self.remote_url and sel not in self._remote_miss:
            for sig in self._lookup_remote(sel):
                self.add(sig)
            if sel not in self._by_sel:
                self._remote_miss.add(sel)
        return list(self._by_sel.get(sel, []))

    def _lookup_remote(self, sel: str) -> List[str]:
        """Query a 4byte.directory-shaped endpoint:
        ``GET {url}?hex_signature=0x{sel}`` returning
        ``{"results": [{"text_signature": "..."}]}``. Any failure is a
        silent miss — labeling must never break an analysis."""
        import urllib.parse
        import urllib.request

        try:
            q = urllib.parse.urlencode({"hex_signature": "0x" + sel})
            join = "&" if "?" in self.remote_url else "?"
            with urllib.request.urlopen(
                    f"{self.remote_url}{join}{q}",
                    timeout=self.remote_timeout) as resp:
                doc = json.load(resp)
            return [r["text_signature"] for r in doc.get("results", [])
                    if isinstance(r.get("text_signature"), str)]
        except Exception:  # noqa: BLE001 — offline/any failure = miss
            return []

    def save(self, path: Optional[str] = None) -> None:
        if not (path or self.path):
            raise ValueError("SignatureDB.save: no path configured")
        with open(path or self.path, "w") as fh:
            json.dump(self._by_sel, fh, indent=1, sort_keys=True)
