"""Dynamic on-chain loading interface (reference: ``mythril/support/
loader.py`` + ``mythril/ethereum/interface/rpc`` ⚠unv).

This environment has ZERO network egress, so there is no live JSON-RPC
client — the surface is interface-shaped and pluggable: anything with
``eth_getCode`` / ``eth_getStorageAt`` works (the reference's tests mock
RPC the same way, SURVEY.md §4 "RPC tests"). Loaded code/storage feed the
analysis as ordinary bytecode / concrete storage seeds; there is no
mid-execution dynamic loading (the corpus is device-resident and static
per run — a deliberate frontier-first divergence).
"""

from __future__ import annotations

from typing import Optional, Protocol


class RpcClient(Protocol):
    def eth_getCode(self, address: str) -> str: ...
    def eth_getStorageAt(self, address: str, slot: str) -> str: ...


class DynLoaderError(RuntimeError):
    pass


class DynLoader:
    """Front door for on-chain lookups (reference: ``DynLoader.dynld`` /
    ``read_storage`` ⚠unv)."""

    def __init__(self, client: Optional[RpcClient] = None):
        self.client = client

    def _require(self) -> RpcClient:
        if self.client is None:
            raise DynLoaderError(
                "no RPC client configured (this environment has no network "
                "egress; plug in any object with eth_getCode/eth_getStorageAt)"
            )
        return self.client

    def dynld(self, address: int) -> bytes:
        """Runtime bytecode of a live contract."""
        code = self._require().eth_getCode(f"0x{address:040x}")
        return bytes.fromhex(code.removeprefix("0x"))

    def read_storage(self, address: int, slot: int) -> int:
        word = self._require().eth_getStorageAt(
            f"0x{address:040x}", f"0x{slot:x}")
        return int(word, 16)
