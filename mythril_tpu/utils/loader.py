"""Dynamic on-chain loading (reference: ``mythril/support/loader.py`` +
``mythril/ethereum/interface/rpc`` ⚠unv).

Three client tiers behind one Protocol: :class:`HttpRpcClient` (a real
``EthJsonRpc``-shaped JSON-RPC-over-HTTP client, loopback-tested since
this image has zero egress), :class:`FileRpcClient` (the JSON-file mock
the reference's RPC tests use, SURVEY.md §4), and anything duck-typed
with ``eth_getCode`` / ``eth_getStorageAt``. Loaded code/storage feed
the analysis two ways (reference ``DynLoader.dynld`` resolves CALL
targets the moment LASER reaches them; the frontier's corpus is a
static jit shape, so loading happens at host seams instead):

- **pre-pass**: :meth:`DynLoader.prefetch_callees` scans the target's
  PUSH20 immediates up front and loads statically-referenced callees;
- **between-tx**: ``SymExecWrapper._dynld_between_txs`` harvests tx N's
  concrete-but-unknown call targets (runtime-computed addresses the
  pre-pass cannot see), fetches them, and registers them so tx N+1's
  calls resolve into real code — load-on-first-touch, one tx later.
"""

from __future__ import annotations

import logging
from typing import Optional, Protocol

log = logging.getLogger(__name__)


class RpcClient(Protocol):
    def eth_getCode(self, address: str) -> str: ...
    def eth_getStorageAt(self, address: str, slot: str) -> str: ...


class DynLoaderError(RuntimeError):
    pass


class FileRpcClient:
    """Mock RPC backed by a JSON file:
    ``{"0xaddr": {"code": "0x...", "storage": {"0x0": "0x..."}}}`` —
    the same shape the reference's RPC tests mock (SURVEY.md §4)."""

    def __init__(self, path: str):
        import json

        with open(path) as fh:
            self._db = {k.lower(): v for k, v in json.load(fh).items()}

    def eth_getCode(self, address: str) -> str:
        return self._db.get(address.lower(), {}).get("code", "0x")

    def eth_getStorageAt(self, address: str, slot: str) -> str:
        st = self._db.get(address.lower(), {}).get("storage", {})
        norm = {int(k, 16): v for k, v in st.items()}
        return norm.get(int(slot, 16), "0x0")


class HttpRpcClient:
    """JSON-RPC-over-HTTP client (reference: ``EthJsonRpc``,
    ``mythril/ethereum/interface/rpc/client.py`` ⚠unv). stdlib
    ``urllib`` transport (no requests dependency), bounded retries on
    transport failure, JSON-RPC error surfacing as :class:`DynLoaderError`.
    Egress does not exist in this image, so coverage comes from a real
    loopback HTTP server in ``tests/test_rpc_client.py`` — the same way
    the reference's RPC tests mock their node (SURVEY.md §4)."""

    def __init__(self, url: str, timeout: float = 10.0, retries: int = 2):
        self.url = url
        self.timeout = timeout
        self.retries = retries
        self._id = 0

    def _call(self, method: str, params):
        import json
        import time
        import urllib.error
        import urllib.request

        self._id += 1
        payload = json.dumps({"jsonrpc": "2.0", "id": self._id,
                              "method": method, "params": params}).encode()
        last: Exception = DynLoaderError("unreachable")
        for attempt in range(self.retries + 1):
            req = urllib.request.Request(
                self.url, data=payload,
                headers={"Content-Type": "application/json"},
            )
            try:
                with urllib.request.urlopen(req, timeout=self.timeout) as resp:
                    body = json.load(resp)
                break
            except urllib.error.HTTPError as e:
                # an HTTP status error IS an answer (4xx/5xx with a body,
                # often a JSON-RPC error object) — surface it, don't
                # re-POST the identical payload. 5xx is the one class
                # worth retrying (transient node trouble).
                if 500 <= e.code < 600 and attempt < self.retries:
                    last = e
                    time.sleep(0.1 * (attempt + 1))
                    continue
                detail = ""
                try:
                    detail = e.read(512).decode("utf-8", "replace")
                except Exception:  # noqa: BLE001 — body read is best-effort
                    pass
                raise DynLoaderError(
                    f"rpc http {e.code}: {detail or e.reason}") from e
            except (urllib.error.URLError, OSError, ValueError) as e:
                # transport/decoding failure: retry with a short backoff;
                # a JSON-RPC *error response* below is NOT retried — the
                # node answered, repeating the question won't change it
                last = e
                if attempt < self.retries:
                    time.sleep(0.1 * (attempt + 1))
        else:
            raise DynLoaderError(f"rpc transport failed: {last}") from last
        if not isinstance(body, dict):
            raise DynLoaderError(f"malformed rpc response: {body!r}")
        if "error" in body:
            raise DynLoaderError(f"rpc error: {body['error']}")
        if "result" not in body:
            raise DynLoaderError(f"rpc response missing result: {body!r}")
        return body["result"]

    def eth_getCode(self, address: str) -> str:
        return self._call("eth_getCode", [address, "latest"])

    def eth_getStorageAt(self, address: str, slot: str) -> str:
        return self._call("eth_getStorageAt", [address, slot, "latest"])

    def eth_getBalance(self, address: str) -> str:
        return self._call("eth_getBalance", [address, "latest"])

    def eth_getTransactionCount(self, address: str) -> str:
        return self._call("eth_getTransactionCount", [address, "latest"])

    def eth_blockNumber(self) -> str:
        return self._call("eth_blockNumber", [])

    def eth_getBlockByNumber(self, number: str, full: bool = False):
        """Block document (``None`` for an unknown block). ``full``
        inlines transaction objects — the serve follower's creation
        scan (serve/follower.py) needs ``to``/``hash`` per tx."""
        return self._call("eth_getBlockByNumber", [number, bool(full)])

    def eth_getTransactionReceipt(self, txhash: str):
        """Receipt document (``None`` while pending) — carries
        ``contractAddress`` for creation transactions."""
        return self._call("eth_getTransactionReceipt", [txhash])


def rpc_client_from_uri(uri: str):
    """``file:PATH`` -> mock client; anything http(s) -> JSON-RPC."""
    if uri.startswith("file:"):
        return FileRpcClient(uri[len("file:"):])
    return HttpRpcClient(uri)


class DynLoader:
    """Front door for on-chain lookups (reference: ``DynLoader.dynld`` /
    ``read_storage`` ⚠unv)."""

    def __init__(self, client: Optional[RpcClient] = None):
        self.client = client

    def _require(self) -> RpcClient:
        if self.client is None:
            raise DynLoaderError(
                "no RPC client configured (this environment has no network "
                "egress; plug in any object with eth_getCode/eth_getStorageAt)"
            )
        return self.client

    def dynld(self, address: int) -> bytes:
        """Runtime bytecode of a live contract. Every malformed-response
        shape (null / non-string / odd or garbage hex) surfaces as
        :class:`DynLoaderError` — callers treat any failure as "no code,
        degrade to havoc" and must never crash an in-flight analysis."""
        code = self._require().eth_getCode(f"0x{address:040x}")
        try:
            return bytes.fromhex(code.removeprefix("0x"))
        except (AttributeError, TypeError, ValueError) as e:
            raise DynLoaderError(
                f"malformed eth_getCode result {code!r}: {e}") from e

    def read_storage(self, address: int, slot: int) -> int:
        word = self._require().eth_getStorageAt(
            f"0x{address:040x}", f"0x{slot:x}")
        try:
            return int(word, 16)
        except (TypeError, ValueError) as e:
            raise DynLoaderError(
                f"malformed eth_getStorageAt result {word!r}: {e}") from e

    def prefetch_callees(self, code: bytes, limit: int = 4, exclude=()):
        """Dynamic loading of statically-referenced callees (reference:
        ``DynLoader.dynld`` resolving CALL targets mid-execution ⚠unv,
        SURVEY §3.4). The frontier's corpus is compiled-in and static per
        run, so loading happens as a PRE-PASS instead of mid-execution:
        scan the target's PUSH20 immediates — the solc idiom for
        hardcoded contract references (and the EIP-1167 embedded
        implementation) — and fetch code for each distinct plausible
        address. Returns ``[(address, code)]`` for the ones that ARE
        contracts; everything else (EOAs, unknown addresses) is skipped
        and those calls degrade to the sound havoc path exactly as
        before. Documented divergence: targets computed at runtime
        (storage-loaded proxy slots) are not discovered by this pass.
        """
        from ..disassembler.disassembly import Disassembly

        out, seen = [], set()
        # bound total ROUND TRIPS, not just successes: linear-sweep
        # disassembly decodes metadata/data sections too, and each
        # garbage PUSH20 would otherwise cost a full (possibly slow)
        # eth_getCode probe that returns nothing
        attempts_left = 4 * max(limit, 0)
        skipped = 0  # distinct candidates dropped by either cap
        for ins in Disassembly(code).instruction_list:
            if ins.name != "PUSH20":
                continue
            addr = ins.arg_int
            if not addr or addr in seen or addr in (exclude or ()):
                continue
            seen.add(addr)
            if len(out) >= limit or attempts_left <= 0:
                skipped += 1
                continue
            attempts_left -= 1
            try:
                callee = self.dynld(addr)
            except DynLoaderError:
                continue
            if callee:
                out.append((addr, callee))
        if skipped:
            log.warning(
                "dynld prefetch truncated: %d candidate address(es) not "
                "probed (limit=%d); calls to them degrade to havoc",
                skipped, limit)
        return out

    def read_balance(self, address: int) -> int:
        """Live balance in wei (reference: ``DynLoader`` balance reads for
        EtherThief witness checks ⚠unv). Clients without eth_getBalance
        (the file mock predates it) report zero rather than failing."""
        client = self._require()
        get = getattr(client, "eth_getBalance", None)
        if get is None:
            return 0
        return int(get(f"0x{address:040x}"), 16)
