"""Dynamic on-chain loading interface (reference: ``mythril/support/
loader.py`` + ``mythril/ethereum/interface/rpc`` ⚠unv).

This environment has ZERO network egress, so there is no live JSON-RPC
client — the surface is interface-shaped and pluggable: anything with
``eth_getCode`` / ``eth_getStorageAt`` works (the reference's tests mock
RPC the same way, SURVEY.md §4 "RPC tests"). Loaded code/storage feed the
analysis as ordinary bytecode / concrete storage seeds; there is no
mid-execution dynamic loading (the corpus is device-resident and static
per run — a deliberate frontier-first divergence).
"""

from __future__ import annotations

from typing import Optional, Protocol


class RpcClient(Protocol):
    def eth_getCode(self, address: str) -> str: ...
    def eth_getStorageAt(self, address: str, slot: str) -> str: ...


class DynLoaderError(RuntimeError):
    pass


class FileRpcClient:
    """Mock RPC backed by a JSON file:
    ``{"0xaddr": {"code": "0x...", "storage": {"0x0": "0x..."}}}`` —
    the same shape the reference's RPC tests mock (SURVEY.md §4)."""

    def __init__(self, path: str):
        import json

        with open(path) as fh:
            self._db = {k.lower(): v for k, v in json.load(fh).items()}

    def eth_getCode(self, address: str) -> str:
        return self._db.get(address.lower(), {}).get("code", "0x")

    def eth_getStorageAt(self, address: str, slot: str) -> str:
        st = self._db.get(address.lower(), {}).get("storage", {})
        norm = {int(k, 16): v for k, v in st.items()}
        return norm.get(int(slot, 16), "0x0")


class HttpRpcClient:
    """Minimal JSON-RPC-over-HTTP client (reference: ``EthJsonRpc``
    ⚠unv). Functional code path; unreachable in this zero-egress image,
    exercised through the same interface as :class:`FileRpcClient`."""

    def __init__(self, url: str, timeout: float = 10.0):
        self.url = url
        self.timeout = timeout
        self._id = 0

    def _call(self, method: str, params):
        import json
        import urllib.request

        self._id += 1
        req = urllib.request.Request(
            self.url,
            data=json.dumps({"jsonrpc": "2.0", "id": self._id,
                             "method": method, "params": params}).encode(),
            headers={"Content-Type": "application/json"},
        )
        with urllib.request.urlopen(req, timeout=self.timeout) as resp:
            body = json.load(resp)
        if "error" in body:
            raise DynLoaderError(f"rpc error: {body['error']}")
        return body["result"]

    def eth_getCode(self, address: str) -> str:
        return self._call("eth_getCode", [address, "latest"])

    def eth_getStorageAt(self, address: str, slot: str) -> str:
        return self._call("eth_getStorageAt", [address, slot, "latest"])


def rpc_client_from_uri(uri: str):
    """``file:PATH`` -> mock client; anything http(s) -> JSON-RPC."""
    if uri.startswith("file:"):
        return FileRpcClient(uri[len("file:"):])
    return HttpRpcClient(uri)


class DynLoader:
    """Front door for on-chain lookups (reference: ``DynLoader.dynld`` /
    ``read_storage`` ⚠unv)."""

    def __init__(self, client: Optional[RpcClient] = None):
        self.client = client

    def _require(self) -> RpcClient:
        if self.client is None:
            raise DynLoaderError(
                "no RPC client configured (this environment has no network "
                "egress; plug in any object with eth_getCode/eth_getStorageAt)"
            )
        return self.client

    def dynld(self, address: int) -> bytes:
        """Runtime bytecode of a live contract."""
        code = self._require().eth_getCode(f"0x{address:040x}")
        return bytes.fromhex(code.removeprefix("0x"))

    def read_storage(self, address: int, slot: int) -> int:
        word = self._require().eth_getStorageAt(
            f"0x{address:040x}", f"0x{slot:x}")
        return int(word, 16)
