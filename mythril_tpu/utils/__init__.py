"""Cross-cutting support (reference: ``mythril/support/`` ⚠unv)."""
