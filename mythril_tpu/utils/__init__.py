"""Cross-cutting support (reference: ``mythril/support/`` ⚠unv)."""

from __future__ import annotations

import json
import os


def atomic_write_json(path: str, obj, indent: int | None = None) -> None:
    """Write JSON via a pid-suffixed temp file + flush + fsync +
    ``os.replace``: a mid-write kill can never truncate the target,
    concurrent writers cannot collide on the temp file
    (last-replace-wins), and the payload is on disk before the rename
    makes it visible — an fsync-less rename can surface as an EMPTY
    file after a power cut on common filesystems. Shared by the
    profiler's measurement history and the soak tool; the campaign
    checkpoint uses the checksummed ``utils.checkpoint`` writers."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as fh:
        json.dump(obj, fh, indent=indent)
        fh.flush()
        os.fsync(fh.fileno())
    os.replace(tmp, path)
