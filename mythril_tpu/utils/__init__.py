"""Cross-cutting support (reference: ``mythril/support/`` ⚠unv)."""

from __future__ import annotations

import json
import os


def atomic_write_json(path: str, obj, indent: int | None = None) -> None:
    """Write JSON via a pid-suffixed temp file + ``os.replace``: a
    mid-write kill can never truncate the target, and concurrent
    writers cannot collide on the temp file (last-replace-wins). Shared
    by the campaign checkpoint, the profiler's measurement history, and
    the soak tool."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "w") as fh:
        json.dump(obj, fh, indent=indent)
    os.replace(tmp, path)
