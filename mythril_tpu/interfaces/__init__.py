"""User-facing interfaces (reference: ``mythril/interfaces/`` ⚠unv)."""
