"""``myth``-style command line (reference: ``mythril/interfaces/cli.py``
⚠unv, SURVEY.md §2 row "CLI").

Commands: ``analyze`` (``a``), ``disassemble`` (``d``),
``list-detectors``, ``version``. Flag names follow the reference where
the concept carries over (``-t``, ``-m``, ``-o``, ``--loop-bound``,
``--execution-timeout``); TPU-frontier knobs (``--max-steps``,
``--lanes-per-contract``) replace the reference's per-state depth flags.

Run as ``python -m mythril_tpu <command> ...``.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def create_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mythril_tpu",
        description="TPU-native symbolic-execution security analyzer for EVM bytecode",
    )
    sub = p.add_subparsers(dest="command")

    def add_input_flags(cmd):
        cmd.add_argument("-f", "--codefile", metavar="PATH",
                         help="file holding runtime bytecode as hex")
        cmd.add_argument("-c", "--code", metavar="HEX",
                         help="runtime bytecode as a hex string")
        cmd.add_argument("--creation-code", metavar="PATH",
                         help="file holding CREATION bytecode as hex; enables "
                              "the constructor transaction")
        cmd.add_argument("--artifact", metavar="PATH",
                         help="solc standard-JSON output artifact (loads all "
                              "contracts with source maps)")
        cmd.add_argument("--solc-input", metavar="PATH",
                         help="solc standard-JSON INPUT (source text for line "
                              "numbers; used with --artifact)")
        cmd.add_argument("--name", default="MAIN", help="contract display name")

    a = sub.add_parser("analyze", aliases=["a"], help="symbolically analyze bytecode")
    add_input_flags(a)
    a.add_argument("-t", "--transaction-count", type=int, default=2,
                   help="number of attacker message-call transactions")
    a.add_argument("-m", "--modules", metavar="LIST",
                   help="comma-separated detection-module allow list")
    a.add_argument("-o", "--outform",
                   choices=["text", "markdown", "json", "jsonv2"],
                   default="text")
    a.add_argument("--max-steps", type=int, default=512,
                   help="superstep budget per transaction")
    a.add_argument("--lanes-per-contract", type=int, default=64,
                   help="frontier lanes (seed + fork headroom) per contract")
    a.add_argument("--loop-bound", type=int, default=None,
                   help="max taken backward jumps per loop target (bounded-"
                        "loops policy)")
    a.add_argument("--solver-iters", type=int, default=400,
                   help="witness-search repair iterations per query")
    a.add_argument("--execution-timeout", type=float, default=None,
                   help="wall-clock budget in seconds for the exploration")
    a.add_argument("--strategy", choices=["bfs", "dfs"], default="bfs",
                   help="fork-admission policy when frontier slots run "
                        "short (the frontier itself steps breadth-first)")
    a.add_argument("--limits-profile", choices=["default", "test"],
                   default="default",
                   help="frontier shape caps: 'test' compiles a much "
                        "smaller engine (CI / quick scans)")
    a.add_argument("--concrete-storage", action="store_true",
                   help="model unknown storage as zero instead of symbolic "
                        "(reference default; symbolic is --unconstrained-storage there)")
    a.add_argument("--graph", metavar="PATH",
                   help="write the contract CFG as graphviz DOT, explored "
                        "blocks highlighted")

    d = sub.add_parser("disassemble", aliases=["d"], help="print EASM")
    add_input_flags(d)

    sub.add_parser("list-detectors", help="list registered detection modules")
    sub.add_parser("version", help="print version")
    return p


def _load_contracts(args):
    from ..mythril import MythrilDisassembler

    if getattr(args, "artifact", None):
        from ..solidity import get_contracts_from_standard_json

        contracts = get_contracts_from_standard_json(
            args.artifact, getattr(args, "solc_input", None))
        if not contracts:
            print("error: artifact holds no deployed bytecode", file=sys.stderr)
            raise SystemExit(2)
        return contracts
    if args.code:
        return [MythrilDisassembler.load_from_bytecode(args.code, name=args.name)]
    if args.codefile:
        return [MythrilDisassembler.load_from_file(
            args.codefile, creation_path=args.creation_code, name=args.name)]
    print("error: provide bytecode via -c/--code, -f/--codefile, or --artifact",
          file=sys.stderr)
    raise SystemExit(2)


def exec_analyze(args) -> int:
    import dataclasses

    from ..mythril import MythrilAnalyzer, MythrilConfig
    from ..symbolic import SymSpec

    contracts = _load_contracts(args)
    if args.code and args.creation_code:
        with open(args.creation_code) as fh:
            from ..disassembler.disassembly import _to_bytes

            contracts[0] = dataclasses.replace(
                contracts[0], creation_code=_to_bytes(fh.read()))
    from ..config import DEFAULT_LIMITS, TEST_LIMITS

    cfg = MythrilConfig(
        limits=TEST_LIMITS if args.limits_profile == "test" else DEFAULT_LIMITS,
        transaction_count=args.transaction_count,
        max_steps=args.max_steps,
        lanes_per_contract=args.lanes_per_contract,
        solver_iters=args.solver_iters,
        loop_bound=args.loop_bound,
        execution_timeout=args.execution_timeout,
        strategy=args.strategy,
        spec=SymSpec(storage=not args.concrete_storage),
    )
    analyzer = MythrilAnalyzer(contracts, cfg)
    modules = args.modules.split(",") if args.modules else None
    report = analyzer.fire_lasers(modules=modules)
    if args.graph:
        _write_graph(args.graph, contracts[0], analyzer)
    if args.outform == "json":
        print(report.as_json())
    elif args.outform == "jsonv2":
        print(report.as_jsonv2())
    elif args.outform == "markdown":
        print(report.as_markdown())
    else:
        print(report.as_text())
    return 0


def _write_graph(path: str, contract, analyzer) -> None:
    """DOT CFG of the first contract, explored blocks highlighted."""
    from ..disassembler.cfg import CFG

    cfg = CFG(contract.code)
    sym = analyzer.sym
    if sym is not None and getattr(sym, "_visited", None) is not None:
        # runtime image index: with creation bytecodes the runtime images
        # occupy the second half of the corpus
        ci = len(sym.images) - len(analyzer.contracts)
        cfg.mark_reached(sym._visited[ci])
    with open(path, "w") as fh:
        fh.write(cfg.as_dot(contract.name))


def exec_disassemble(args) -> int:
    contract = _load_contracts(args)[0]
    print(contract.get_easm(), end="")
    return 0


def exec_list_detectors(args) -> int:
    from ..analysis import ModuleLoader

    for m in ModuleLoader().get_detection_modules():
        print(f"{m.name} (SWC-{m.swc_id}): {m.description}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = create_parser()
    args = parser.parse_args(argv)
    if args.command in ("analyze", "a"):
        return exec_analyze(args)
    if args.command in ("disassemble", "d"):
        return exec_disassemble(args)
    if args.command == "list-detectors":
        return exec_list_detectors(args)
    if args.command == "version":
        from .. import __version__

        print(f"mythril_tpu {__version__}")
        return 0
    parser.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
