"""``myth``-style command line (reference: ``mythril/interfaces/cli.py``
⚠unv, SURVEY.md §2 row "CLI").

Commands: ``analyze`` (``a``), ``disassemble`` (``d``),
``list-detectors``, ``version``. Flag names follow the reference where
the concept carries over (``-t``, ``-m``, ``-o``, ``--loop-bound``,
``--execution-timeout``); TPU-frontier knobs (``--max-steps``,
``--lanes-per-contract``) replace the reference's per-state depth flags.

Run as ``python -m mythril_tpu <command> ...``.
"""

from __future__ import annotations

import argparse
import sys
from typing import List, Optional


def create_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="mythril_tpu",
        description="TPU-native symbolic-execution security analyzer for EVM bytecode",
    )
    sub = p.add_subparsers(dest="command")

    def add_input_flags(cmd):
        cmd.add_argument("-f", "--codefile", metavar="PATH",
                         help="file holding runtime bytecode as hex")
        cmd.add_argument("-c", "--code", metavar="HEX",
                         help="runtime bytecode as a hex string")
        cmd.add_argument("--creation-code", metavar="PATH",
                         help="file holding CREATION bytecode as hex; enables "
                              "the constructor transaction")
        cmd.add_argument("--artifact", metavar="PATH",
                         help="solc standard-JSON output artifact (loads all "
                              "contracts with source maps)")
        cmd.add_argument("--solc-input", metavar="PATH",
                         help="solc standard-JSON INPUT (source text for line "
                              "numbers; used with --artifact)")
        cmd.add_argument("--name", default="MAIN", help="contract display name")

    a = sub.add_parser("analyze", aliases=["a"], help="symbolically analyze bytecode")
    add_input_flags(a)
    a.add_argument("-t", "--transaction-count", type=int, default=2,
                   help="number of attacker message-call transactions")
    a.add_argument("-m", "--modules", metavar="LIST",
                   help="comma-separated detection-module allow list")
    a.add_argument("-o", "--outform",
                   choices=["text", "markdown", "json", "jsonv2"],
                   default="text")
    a.add_argument("--max-steps", type=int, default=512,
                   help="superstep budget per transaction")
    a.add_argument("--max-depth", type=int, default=None,
                   help="reference-name alias: per-path instruction depth "
                        "== frontier superstep budget (overrides "
                        "--max-steps when given)")
    a.add_argument("--call-depth-limit", type=int, default=None,
                   help="max nested CALL/CREATE frames per lane (reference "
                        "default 3; here the frontier frame-stack cap)")
    a.add_argument("--lanes-per-contract", type=int, default=64,
                   help="frontier lanes (seed + fork headroom) per contract")
    a.add_argument("--loop-bound", type=int, default=None,
                   help="max taken backward jumps per loop target (bounded-"
                        "loops policy)")
    a.add_argument("--solver-iters", type=int, default=400,
                   help="witness-search repair iterations per query")
    a.add_argument("--solver-timeout", type=int, default=None, metavar="MS",
                   help="wall-clock budget per solver query, milliseconds "
                        "(reference units); expiry degrades to no-issue")
    a.add_argument("--parallel-solving", action="store_true",
                   help="run detection modules concurrently (thread pool "
                        "over the GIL-releasing native tape evaluator)")
    a.add_argument("--execution-timeout", type=float, default=None,
                   help="wall-clock budget in seconds for the exploration")
    a.add_argument("--create-timeout", type=float, default=None,
                   help="wall-clock budget in seconds for the CREATION "
                        "transaction (constructor) only")
    a.add_argument("--strategy",
                   choices=["bfs", "dfs", "naive-random", "weighted-random",
                            "coverage", "beam"],
                   default="bfs",
                   help="fork-admission policy when frontier slots run "
                        "short (the frontier itself steps breadth-first): "
                        "bfs=fifo, dfs=deepest-first, naive-random="
                        "unbiased hash order, weighted-random="
                        "depth-weighted hash, coverage=unvisited-target "
                        "first, beam=capped shallowest-first")
    a.add_argument("--limits-profile", choices=["default", "test"],
                   default="default",
                   help="frontier shape caps: 'test' compiles a much "
                        "smaller engine (CI / quick scans)")
    a.add_argument("--concrete-storage", action="store_true",
                   help="model unknown storage as zero instead of symbolic "
                        "(reference default; symbolic is --unconstrained-storage there)")
    a.add_argument("--unconstrained-storage", action="store_true",
                   help="model unknown storage as fully symbolic (this "
                        "engine's default; the reference flag name, kept "
                        "for parity — conflicts with --concrete-storage)")
    a.add_argument("--graph", metavar="PATH",
                   help="write the contract CFG with explored blocks "
                        "highlighted: *.html gets a self-contained "
                        "interactive page, anything else graphviz DOT")
    a.add_argument("--statespace-json", metavar="PATH",
                   help="dump the explored statespace as JSON: per-tx "
                        "surviving paths (pc, depth, constraints) + "
                        "per-contract instruction coverage")
    a.add_argument("--enable-iprof", action="store_true",
                   help="print a per-opcode executed-instruction profile "
                        "after the report")
    a.add_argument("--plugin-dir", metavar="DIR",
                   help="load external plugins (detection modules and/or "
                        "laser plugins) from every *.py in DIR; installed "
                        "entry-point plugins load automatically")

    a.add_argument("--corpus", metavar="DIR",
                   help="campaign mode: analyze every *.hex/*.bin under "
                        "DIR in constant-shape batches (one compiled "
                        "engine), with checkpoint/resume; prints a "
                        "throughput+issues JSON")
    a.add_argument("--batch-size", type=int, default=32,
                   help="contracts per compiled batch (campaign mode)")
    a.add_argument("--checkpoint-dir", metavar="DIR",
                   help="campaign checkpoint directory (resume-able)")
    a.add_argument("--batch-timeout", type=float, default=None,
                   metavar="SEC",
                   help="campaign mode: hard wall-clock watchdog per "
                        "batch — a hung compile or wedged device call "
                        "becomes a batch failure (retried, then bisected "
                        "to quarantine the poison contract) instead of "
                        "an indefinite stall")
    a.add_argument("--init-timeout", type=float, default=None,
                   metavar="SEC",
                   help="campaign mode: probe backend init in a "
                        "subprocess with this deadline BEFORE loading "
                        "the engine; on failure fall back to the CPU "
                        "backend and record the event in the report")
    a.add_argument("--max-batch-retries", type=int, default=1,
                   metavar="N",
                   help="campaign mode: whole-batch re-attempts after a "
                        "failure before bisecting it (default 1)")
    a.add_argument("--fault-inject", metavar="SPEC",
                   help="campaign mode (testing): inject deterministic "
                        "faults, e.g. 'raise:contract=c002', "
                        "'hang:batch=1', 'raise:batch=0:times=1', "
                        "'kill:batch=2', 'oom:batch=1:times=2'; "
                        "';'-separated specs; the MYTHRIL_FAULT_INJECT "
                        "env var is equivalent")
    a.add_argument("--oom-ladder", metavar="LIST",
                   default=None,
                   help="campaign mode: comma-separated degradation "
                        "rungs walked (cumulatively) when a batch hits "
                        "RESOURCE_EXHAUSTED, from 'halve-lanes', "
                        "'halve-batch', 'cpu' (default: all three in "
                        "that order); 'none' disables degradation — an "
                        "OOM then falls to retry/bisect")
    a.add_argument("--pipeline", dest="pipeline", action="store_true",
                   default=True,
                   help="campaign mode (default ON): overlap batch i's "
                        "host phase (detection modules + witness "
                        "search) with batch i+1's device execution, "
                        "and write checkpoints from a background "
                        "thread; results are byte-identical to "
                        "--no-pipeline and any fault drains back to "
                        "the serial retry/bisect path (see "
                        "docs/performance.md)")
    a.add_argument("--no-pipeline", dest="pipeline", action="store_false",
                   help="campaign mode: strictly serial batches "
                        "(device and host phases never overlap)")
    a.add_argument("--solver-workers", type=int, default=1, metavar="N",
                   help="threads for the detection-module/witness-"
                        "search pool in the campaign host phase "
                        "(N>1 implies --parallel-solving with an "
                        "N-thread pool; default 1)")
    a.add_argument("--checkpoint-every", type=int, default=1,
                   metavar="N",
                   help="campaign mode: durable checkpoint write every "
                        "N batches (default 1 — kill -9 at any instant "
                        "loses at most one batch; larger N trades "
                        "replayed batches for less checkpoint I/O)")
    a.add_argument("--trace", metavar="FILE",
                   help="write a Chrome-trace JSON to FILE (load it in "
                        "Perfetto / chrome://tracing) plus an append-"
                        "only JSONL event log beside it (FILE with a "
                        ".jsonl suffix); spans cover supersteps, "
                        "batches, checkpoints, degrades — see "
                        "docs/observability.md and tools/trace_report.py")
    a.add_argument("--metrics", metavar="FILE",
                   help="write a metrics snapshot at exit: counters/"
                        "gauges/histograms (frontier occupancy, "
                        "fork/park/spill rates, solver checks, degrade "
                        "and compile events, checkpoint latency) as "
                        "JSON, or Prometheus text format when FILE "
                        "ends in .prom/.txt")
    a.add_argument("--heartbeat", type=float, default=None, metavar="SEC",
                   help="campaign mode: print a one-line progress "
                        "heartbeat to stderr at most every SEC seconds "
                        "(contracts done, paths/s, frontier occupancy, "
                        "degrade rung, last-checkpoint age)")
    a.add_argument("--fleet", metavar="DIR",
                   help="campaign mode: elastic fleet coordination via "
                        "a shared work-ledger directory (NFS/GCS): the "
                        "corpus is cut into leased work units, workers "
                        "claim/heartbeat/commit them, and a dead "
                        "host's units migrate to survivors (see "
                        "docs/fleet.md); replaces the static "
                        "--num-hosts/--host-index split")
    a.add_argument("--lease-ttl", type=float, default=60.0, metavar="SEC",
                   help="fleet mode: a unit lease whose heartbeat is "
                        "older than SEC is reclaimed by any live "
                        "worker (default 60)")
    a.add_argument("--unit-size", type=int, default=None, metavar="N",
                   help="fleet mode: contracts per work unit (rounded "
                        "up to whole batches; default: one batch) — "
                        "the granularity of reclaim and of loss when a "
                        "worker dies mid-unit")
    a.add_argument("--max-unit-leases", type=int, default=3, metavar="N",
                   help="fleet mode: lease grants per unit before it "
                        "is marked lost instead of retried forever "
                        "(default 3 — the fleet-level analog of "
                        "bisect-to-quarantine)")
    a.add_argument("--worker-id", metavar="ID", default=None,
                   help="fleet mode: stable worker identity stamped "
                        "into leases and unit results (default: "
                        "hostname-pid-tid)")
    a.add_argument("--solver-store", metavar="DIR",
                   help="shared per-QUERY solver verdict store "
                        "(docs/solver.md): canonical constraint hashes "
                        "-> durable sat/unsat verdicts, reused across "
                        "campaigns, fleet workers, and restarts. "
                        "Default: <fleet-dir>/solver_store under "
                        "--fleet, off otherwise")
    a.add_argument("--no-solver-store", action="store_true",
                   help="disable the solver verdict store (including "
                        "the --fleet default); the in-process LRU and "
                        "the refute/probe stages stay on")
    a.add_argument("--worker-isolation", choices=["on", "off", "auto"],
                   default="auto",
                   help="campaign mode: run device batches in a "
                        "supervised engine-worker SUBPROCESS so a "
                        "libtpu segfault / OOM kill / hard hang is a "
                        "worker restart (replayed through "
                        "retry/ladder/bisect), never process death; "
                        "N rapid deaths open a crash-loop breaker "
                        "that pins work to the in-process CPU path "
                        "(docs/resilience.md). auto (default) = on "
                        "under --fleet, off otherwise")
    a.add_argument("--backend-tiers", metavar="LIST", default=None,
                   help="campaign mode: ranked backend-tier ladder "
                        "(comma-separated from 'tpu', 'gpu', 'cpu'; "
                        "default: detect from the environment). A "
                        "crash-looping or lost backend DEMOTES to the "
                        "next tier instead of pinning to CPU, and a "
                        "background prober re-promotes when the "
                        "better tier probes healthy again "
                        "(docs/resilience.md \"Backend tiers\")")
    a.add_argument("--fleet-follow", action="store_true",
                   help="fleet mode: join a serve daemon's FEED ledger "
                        "(docs/serving.md) — units carry their own "
                        "bytecode, so no --corpus is needed; the "
                        "worker polls for newly fed units and exits "
                        "when the feeder closes the feed (or "
                        "--execution-timeout lapses)")
    a.add_argument("--num-hosts", type=int, default=0, metavar="N",
                   help="campaign mode: shard the corpus across N hosts; "
                        "this process analyzes slice --host-index "
                        "(default: jax.distributed process count when "
                        "initialized, else 1)")
    a.add_argument("--host-index", type=int, default=-1, metavar="I",
                   help="which corpus shard this host takes (default: "
                        "jax.distributed process index, else 0)")
    a.add_argument("-a", "--address", metavar="ADDRESS",
                   help="analyze the on-chain contract at ADDRESS "
                        "(requires --rpc)")
    a.add_argument("--no-onchain-callees", action="store_true",
                   help="with -a: skip the dynld pre-pass that fetches "
                        "code for the target's hardcoded callee "
                        "addresses (their calls then havoc soundly)")
    a.add_argument("--rpc", metavar="URI",
                   help="JSON-RPC endpoint; 'file:PATH' uses a JSON mock "
                        "({addr: {code, storage}})")

    d = sub.add_parser("disassemble", aliases=["d"], help="print EASM")
    add_input_flags(d)

    c = sub.add_parser("concolic",
                       help="flip branches of a concrete trace "
                            "(hybrid-fuzzing helper)")
    add_input_flags(c)
    c.add_argument("--input", metavar="TRACE.json",
                   help="reference-shaped concolic trace file "
                        "(initialState.accounts + steps); supplies "
                        "code/calldata/value/caller from the last step")
    c.add_argument("--calldata", metavar="HEX",
                   help="seed transaction calldata (required unless "
                        "--input is given)")
    c.add_argument("--callvalue", type=int, default=0)
    c.add_argument("--jump-addresses", metavar="LIST",
                   help="comma-separated JUMPI pcs to flip (default: all)")
    c.add_argument("--max-steps", type=int, default=256)
    c.add_argument("--solver-iters", type=int, default=400)
    c.add_argument("--limits-profile", choices=["default", "test"],
                   default="default")

    rs = sub.add_parser("read-storage",
                        help="read a live contract's storage slot over RPC")
    rs.add_argument("index", help="storage slot (int or 0xhex)")
    rs.add_argument("address", help="contract address")
    rs.add_argument("--rpc", required=True, metavar="URI")

    f2h = sub.add_parser("function-to-hash",
                         help="4-byte selector of a function signature")
    f2h.add_argument("signature", help='e.g. "transfer(address,uint256)"')

    h2a = sub.add_parser("hash-to-address",
                         help="EIP-55 address from a 32-byte storage word")
    h2a.add_argument("hashes", nargs="+", help="32-byte hex words")

    sf_ = sub.add_parser("safe-functions",
                         help="functions with no issues found")
    add_input_flags(sf_)
    sf_.add_argument("-t", "--transaction-count", type=int, default=2)
    sf_.add_argument("--max-steps", type=int, default=512)
    sf_.add_argument("--lanes-per-contract", type=int, default=64)
    sf_.add_argument("--limits-profile", choices=["default", "test"],
                     default="default")

    cm = sub.add_parser("campaign-merge",
                        help="merge per-host campaign JSON results into "
                             "corpus-level metrics")
    cm.add_argument("results", nargs="+", metavar="JSON|LEDGER",
                    help="campaign output files (one per host) and/or "
                         "fleet ledger directories (--fleet DIR): a "
                         "directory contributes every committed unit "
                         "result — including those of workers that "
                         "died before printing a report")
    cm.add_argument("--strict-coverage", action="store_true",
                    help="exit nonzero unless the merged coverage "
                         "manifest is full (every contract analyzed or "
                         "quarantined — nothing lost or unaccounted)")

    sv = sub.add_parser(
        "serve",
        help="always-on analysis daemon: admission queue, bytecode-"
             "hash dedupe, warm-compile reuse, streaming results "
             "(docs/serving.md)")
    sv.add_argument("--host", default="127.0.0.1",
                    help="bind address (default 127.0.0.1)")
    sv.add_argument("--port", type=int, default=8780,
                    help="bind port; 0 asks the OS for a free one "
                         "(see --port-file)")
    sv.add_argument("--port-file", metavar="PATH",
                    help="write the BOUND port to PATH once listening "
                         "(the --port 0 discovery channel for "
                         "supervisors and tests)")
    sv.add_argument("--data-dir", default="serve_data", metavar="DIR",
                    help="persistent serve state (the dedupe verdict "
                         "store lives in DIR/store); survives "
                         "restarts — that is the exactly-once story")
    sv.add_argument("--no-dedupe", dest="dedupe", action="store_false",
                    default=True,
                    help="escape hatch: always re-analyze, never "
                         "serve or write stored verdicts")
    sv.add_argument("--max-queue", type=int, default=4096, metavar="N",
                    help="admission queue depth bound; overflow gets "
                         "HTTP 429 (default 4096)")
    sv.add_argument("--tenant-rate", type=float, default=None,
                    metavar="R",
                    help="default per-tenant admission rate: a token "
                         "bucket of R fresh contracts/sec per tenant "
                         "(dedupe hits are free); breach gets HTTP "
                         "429 with Retry-After (default: unlimited)")
    sv.add_argument("--tenant-burst", type=int, default=None,
                    metavar="N",
                    help="default token-bucket capacity (default: "
                         "max(8, 2*rate))")
    sv.add_argument("--tenant-max-inflight", type=int, default=None,
                    metavar="N",
                    help="default per-tenant cap on queued+running "
                         "entries (default: unlimited)")
    sv.add_argument("--quota", action="append", default=None,
                    metavar="TENANT=RATE[:BURST[:INFLIGHT]]",
                    help="per-tenant quota override (repeatable); "
                         "blank fields mean unlimited, e.g. "
                         "--quota scanner=2:8:4 --quota ops=::64")
    sv.add_argument("--shed-depth-hi", type=float, default=0.85,
                    metavar="FRAC",
                    help="enter load shedding when queue depth "
                         "reaches FRAC of --max-queue (default 0.85); "
                         "low-priority submissions then get verdict-"
                         "store-only answers until pressure clears")
    sv.add_argument("--shed-age-hi", type=float, default=30.0,
                    metavar="SEC",
                    help="enter load shedding when the oldest queued "
                         "entry is SEC old (default 30)")
    sv.add_argument("--shed-priority-max", type=int, default=0,
                    metavar="P",
                    help="submissions with priority <= P are the "
                         "sheddable class (default 0 — the default "
                         "priority; pass a higher priority to keep a "
                         "lane under overload)")
    sv.add_argument("--no-shed", action="store_true",
                    help="disable the load-shedding ladder (overflow "
                         "then only ever 429s)")
    sv.add_argument("--follow", metavar="RPC_URI",
                    help="chain-head follower: poll eth_blockNumber "
                         "on RPC_URI, ingest newly deployed contracts "
                         "as the standing lowest-priority tenant "
                         "'follower' (shed first under overload); "
                         "resumes from a durable cursor in --data-dir")
    sv.add_argument("--follow-poll", type=float, default=2.0,
                    metavar="SEC",
                    help="follower poll cadence at the chain head "
                         "(default 2.0)")
    sv.add_argument("--backfill", metavar="RPC_URI",
                    help="whole-chain backfill: walk history BACKWARD "
                         "from the head anchored at first start, "
                         "ingesting every deployed contract as the "
                         "standing tenant 'backfill' at the lowest "
                         "priority of all (below the follower, shed "
                         "first); resumes from a durable two-ended "
                         "cursor in --data-dir")
    sv.add_argument("--backfill-window", type=int, default=64,
                    metavar="N",
                    help="blocks per backfill scan window; the cursor "
                         "advances only past fully-committed windows, "
                         "so a kill re-scans at most N blocks "
                         "(default 64)")
    sv.add_argument("--compact-every", type=float, default=None,
                    metavar="SEC",
                    help="background store compaction period: fold "
                         "settled loose verdict files into immutable "
                         "checksummed segments behind a "
                         "generation-numbered manifest "
                         "(docs/serving.md 'Verdict segments & edge "
                         "replicas'); run on at most ONE replica per "
                         "data dir (default: off)")
    sv.add_argument("--store-only", action="store_true",
                    help="edge replica mode: serve dedupe-store "
                         "answers only, NO engine — store misses get "
                         "a typed unknown-contract answer with "
                         "Retry-After; the manifest snapshot is "
                         "re-polled for new generations")
    sv.add_argument("--drain-timeout", type=float, default=30.0,
                    metavar="SEC",
                    help="SIGTERM drain budget: how long the in-flight "
                         "batch (or fed fleet units) may take before "
                         "the daemon abandons them and exits "
                         "(default 30)")
    sv.add_argument("--fleet", metavar="DIR",
                    help="front a multi-host fleet: append admitted "
                         "batches to a FEED work ledger in DIR instead "
                         "of running locally; workers join with "
                         "'analyze --fleet DIR --fleet-follow' "
                         "(docs/fleet.md, docs/serving.md)")
    sv.add_argument("--solver-store", metavar="DIR",
                    help="shared per-QUERY solver verdict store "
                         "(docs/solver.md); default: "
                         "<data-dir>/solver_store — the daemon's "
                         "solver work survives restarts like its "
                         "per-contract verdicts do")
    sv.add_argument("--no-solver-store", action="store_true",
                    help="disable the per-query solver verdict store "
                         "(the per-contract dedupe store is governed "
                         "by --no-dedupe, not this flag)")
    sv.add_argument("--batch-size", type=int, default=8,
                    help="contracts per compiled service batch "
                         "(default 8)")
    sv.add_argument("--lanes-per-contract", type=int, default=32)
    sv.add_argument("--max-steps", type=int, default=256,
                    help="default superstep budget per transaction "
                         "(overridable per request)")
    sv.add_argument("-t", "--transaction-count", type=int, default=1,
                    help="default attacker transactions (overridable "
                         "per request)")
    sv.add_argument("-m", "--modules", metavar="LIST",
                    help="default detection-module allow list "
                         "(overridable per request)")
    sv.add_argument("--limits-profile", choices=["default", "test"],
                    default="default")
    sv.add_argument("--solver-iters", type=int, default=400)
    sv.add_argument("--solver-timeout", type=int, default=None,
                    metavar="MS")
    sv.add_argument("--solver-workers", type=int, default=1, metavar="N")
    sv.add_argument("--batch-timeout", type=float, default=None,
                    metavar="SEC",
                    help="per-batch watchdog (same contract as "
                         "campaign mode)")
    sv.add_argument("--max-batch-retries", type=int, default=1,
                    metavar="N")
    sv.add_argument("--oom-ladder", metavar="LIST", default=None)
    sv.add_argument("--fault-inject", metavar="SPEC",
                    help="testing: deterministic faults in service "
                         "batches (batch indices count monotonically "
                         "over the daemon lifetime)")
    sv.add_argument("--concrete-storage", action="store_true")
    sv.add_argument("--worker-isolation",
                    choices=["on", "off", "auto"], default="auto",
                    help="run service batches in a supervised "
                         "engine-worker subprocess (auto = ON under "
                         "serve): backend death becomes a worker "
                         "restart, a crash loop opens a breaker that "
                         "pins the config to in-process CPU — "
                         "reported in /healthz degraded_configs "
                         "(docs/resilience.md)")
    sv.add_argument("--backend-tiers", metavar="LIST", default=None,
                    help="ranked backend-tier ladder for resident "
                         "campaigns (comma-separated from 'tpu', "
                         "'gpu', 'cpu'; default: detect). Each config "
                         "is a capacity class placed on whatever tier "
                         "its worker holds; demotions/re-promotions "
                         "surface in /healthz backend_tiers and the "
                         "engine_tier_* metrics (docs/serving.md)")
    sv.add_argument("--compile-store", metavar="DIR", default=None,
                    help="fleet compile-artifact store: durable "
                         "shape-bucket registry + shared persistent "
                         "XLA cache, so restarted/sibling replicas and "
                         "re-promoted tiers come back warm (default: "
                         "<data-dir>/compile_store; docs/serving.md "
                         "'Compile artifacts & prewarm')")
    sv.add_argument("--prewarm", dest="prewarm", action="store_true",
                    default=True,
                    help="AOT-prewarm the registry's hottest shape "
                         "buckets on daemon start, worker respawn, and "
                         "tier re-promotion (default: on; strictly "
                         "subordinate to live traffic)")
    sv.add_argument("--no-prewarm", dest="prewarm", action="store_false",
                    help="disable the background prewarm pass (the "
                         "compile store still records warm shapes and "
                         "the shared XLA cache still serves lazy "
                         "compiles)")
    sv.add_argument("--trace", metavar="FILE",
                    help="Chrome-trace + JSONL event log (admit/"
                         "queue_wait/schedule/stream spans ride the "
                         "same spine as batch spans)")
    sv.add_argument("--metrics", metavar="FILE",
                    help="metrics snapshot at exit (the live registry "
                         "is always scrapeable at /metrics)")
    sv.add_argument("--heartbeat", type=float, default=None,
                    metavar="SEC",
                    help="print a one-line serving heartbeat to stderr "
                         "every SEC seconds: queue depth, inflight, "
                         "store size, and end-to-end request latency "
                         "p50/p95 (serve_request_seconds)")

    ld = sub.add_parser("list-detectors",
                        help="list registered detection modules")
    ld.add_argument("--plugin-dir", metavar="DIR",
                    help="also load external plugins from DIR first")
    sub.add_parser("version", help="print version")
    return p


def _limits_for(args):
    """THE limits-resolution for a parsed argv — every consumer (analyze,
    campaign, the dynld prefetch cap) must share this one derivation, or
    a cap computed from a stale copy can desync from the real account
    table and silently disable cross-contract resolution."""
    import dataclasses

    from ..config import DEFAULT_LIMITS, TEST_LIMITS

    limits = (TEST_LIMITS if getattr(args, "limits_profile", None) == "test"
              else DEFAULT_LIMITS)
    if getattr(args, "call_depth_limit", None) is not None:
        limits = dataclasses.replace(limits,
                                     call_depth=args.call_depth_limit)
    return limits


def _load_contracts(args):
    from ..mythril import MythrilDisassembler

    if getattr(args, "address", None):
        if not getattr(args, "rpc", None):
            print("error: -a/--address requires --rpc", file=sys.stderr)
            raise SystemExit(2)
        from ..utils.loader import DynLoader, rpc_client_from_uri

        dl = DynLoader(rpc_client_from_uri(args.rpc))
        args._dynld = dl  # exec_analyze reuses this client for mid-run
        # loading instead of opening a second connection to the node
        target_addr = int(args.address, 16)
        code = dl.dynld(target_addr)
        if not code:
            print(f"error: no code at {args.address}", file=sys.stderr)
            raise SystemExit(2)
        target = MythrilDisassembler.load_from_bytecode(
            code.hex(), name=args.address)
        target.address = target_addr
        out = [target]
        if getattr(args, "no_onchain_callees", False):
            return out
        # dynamic loading of statically-referenced callees (pre-pass —
        # see DynLoader.prefetch_callees): their code joins the corpus
        # under their REAL addresses so hardcoded cross-contract calls
        # resolve instead of degrading to havoc. The prefetch is capped
        # to the frontier account table (2 reserved slots + target +
        # callees must fit max_accounts, or make_frontier falls to the
        # own-contract-only layout and NOTHING cross-contract resolves),
        # and a self-referencing PUSH20 must not duplicate the target.
        A = _limits_for(args).max_accounts
        room = max(0, A - 2 - 1)
        for addr, callee in dl.prefetch_callees(code, limit=room,
                                                exclude=(target_addr,)):
            c = MythrilDisassembler.load_from_bytecode(
                callee.hex(), name=f"0x{addr:040x}")
            c.address = addr
            out.append(c)
            print(f"dynld: loaded callee 0x{addr:040x} "
                  f"({len(callee)} bytes)", file=sys.stderr)
        if room == 0:
            print("dynld: account table too small for callee prefetch "
                  f"(max_accounts={A})", file=sys.stderr)
        return out
    if getattr(args, "artifact", None):
        from ..solidity import get_contracts_from_standard_json

        contracts = get_contracts_from_standard_json(
            args.artifact, getattr(args, "solc_input", None))
        if not contracts:
            print("error: artifact holds no deployed bytecode", file=sys.stderr)
            raise SystemExit(2)
        return contracts
    if args.code:
        return [MythrilDisassembler.load_from_bytecode(args.code, name=args.name)]
    if args.codefile:
        if args.codefile.endswith(".sol"):
            # reference: `myth analyze contract.sol` (SURVEY §3.1) —
            # requires a solc on PATH (or $MYTHRIL_SOLC)
            from ..solidity import SolcError, SolcNotFound

            try:
                contracts = MythrilDisassembler.load_from_solidity(
                    args.codefile)
            except (SolcNotFound, SolcError) as e:
                print(f"error: {e}", file=sys.stderr)
                raise SystemExit(2)
            if not contracts:
                print("error: no deployed bytecode compiled", file=sys.stderr)
                raise SystemExit(2)
            return contracts
        return [MythrilDisassembler.load_from_file(
            args.codefile, creation_path=args.creation_code, name=args.name)]
    print("error: provide bytecode via -c/--code, -f/--codefile, or --artifact",
          file=sys.stderr)
    raise SystemExit(2)


def _discover_plugins(plugin_dir):
    """Outer plugin discovery (entry points + optional directory); errors
    warn on stderr rather than aborting the analysis."""
    from ..plugin import discover

    disc = discover(plugin_dir=plugin_dir)
    for name, err in disc.errors.items():
        print(f"warning: plugin {name}: {err}", file=sys.stderr)
    return disc.laser_plugins


def exec_analyze(args) -> int:
    if args.concrete_storage and args.unconstrained_storage:
        print("error: --concrete-storage conflicts with "
              "--unconstrained-storage", file=sys.stderr)
        raise SystemExit(2)
    # telemetry spine (docs/observability.md): configure the process
    # tracer / metrics registry BEFORE the engine loads, finalize on
    # every exit path — a crashed run still leaves the JSONL prefix and
    # a best-effort metrics snapshot behind. obs imports are stdlib-only
    # so this stays safe pre-backend-probe.
    from ..obs import metrics as obs_metrics
    from ..obs import trace as obs_trace

    if getattr(args, "trace", None):
        obs_trace.configure(args.trace)
    if getattr(args, "metrics", None):
        obs_metrics.REGISTRY.enabled = True
    try:
        # CLI ingestion point: the whole analyze invocation is one
        # request trace — every span/event it emits (including fleet
        # units fed to other hosts) carries this id
        with obs_trace.trace_context():
            return _exec_analyze_inner(args)
    finally:
        # best-effort: a failed telemetry flush (unwritable dir, full
        # disk) must not mask the analysis result or its exception
        if getattr(args, "trace", None):
            try:
                obs_trace.close()
            except Exception as exc:
                print(f"warning: trace write failed: {exc}",
                      file=sys.stderr)
        if getattr(args, "metrics", None):
            try:
                obs_metrics.REGISTRY.write(args.metrics)
            except Exception as exc:
                print(f"warning: metrics write failed: {exc}",
                      file=sys.stderr)


def _exec_analyze_inner(args) -> int:
    # campaign mode dispatches BEFORE any engine import: --init-timeout
    # must be able to probe (and fall back from) a wedged backend while
    # this process is still backend-free. --fleet-follow is a campaign
    # with no local corpus (the feed ledger supplies the bytecode).
    if getattr(args, "corpus", None) or (
            getattr(args, "fleet", None)
            and getattr(args, "fleet_follow", False)):
        return _exec_campaign(args)
    if getattr(args, "fleet_follow", False):
        print("error: --fleet-follow requires --fleet DIR",
              file=sys.stderr)
        raise SystemExit(2)

    import dataclasses

    from ..mythril import MythrilAnalyzer, MythrilConfig
    from ..symbolic import SymSpec
    if getattr(args, "solver_store", None) and not args.no_solver_store:
        # single-shot analyze can still read/feed a shared verdict
        # store (e.g. the one a nightly campaign maintains)
        from ..smt import portfolio as smt_portfolio

        smt_portfolio.set_store(args.solver_store)
    contracts = _load_contracts(args)
    if args.code and args.creation_code:
        with open(args.creation_code) as fh:
            from ..disassembler.disassembly import _to_bytes

            contracts[0] = dataclasses.replace(
                contracts[0], creation_code=_to_bytes(fh.read()))
    cfg = MythrilConfig(
        limits=_limits_for(args),
        transaction_count=args.transaction_count,
        # --max-depth is the reference name for the per-path depth budget;
        # on the breadth-first frontier that IS the superstep budget
        max_steps=(args.max_depth if args.max_depth is not None
                   else args.max_steps),
        lanes_per_contract=args.lanes_per_contract,
        solver_iters=args.solver_iters,
        solver_timeout=(args.solver_timeout / 1000.0
                        if args.solver_timeout is not None else None),
        parallel_solving=args.parallel_solving,
        loop_bound=args.loop_bound,
        execution_timeout=args.execution_timeout,
        create_timeout=args.create_timeout,
        strategy=args.strategy,
        spec=SymSpec(storage=not args.concrete_storage),
        enable_iprof=args.enable_iprof,
        plugins=tuple(_discover_plugins(args.plugin_dir)),
    )
    if getattr(args, "rpc", None) and not getattr(
            args, "no_onchain_callees", False):
        # mid-execution dynamic loading (reference DynLoader.dynld ⚠unv):
        # runtime-computed call targets the PUSH20 pre-pass cannot see
        # are fetched at tx seams and resolve in the following tx;
        # reuse the -a path's client when one exists
        dl = getattr(args, "_dynld", None)
        if dl is None:
            from ..utils.loader import DynLoader, rpc_client_from_uri

            dl = DynLoader(rpc_client_from_uri(args.rpc))
        cfg = dataclasses.replace(cfg, dyn_loader=dl)
    analyzer = MythrilAnalyzer(contracts, cfg)
    modules = args.modules.split(",") if args.modules else None
    report = analyzer.fire_lasers(modules=modules)
    if args.graph:
        _write_graph(args.graph, contracts[0], analyzer)
    if args.statespace_json:
        _write_statespace(args.statespace_json, analyzer)
    if args.outform == "json":
        print(report.as_json())
    elif args.outform == "jsonv2":
        print(report.as_jsonv2())
    elif args.outform == "markdown":
        print(report.as_markdown())
    else:
        print(report.as_text())
    if args.enable_iprof:
        # separate channel, like the reference's profiler dump: the report
        # formats stay schema-stable whether or not profiling is on
        print(analyzer.sym.iprof_table(), file=sys.stderr)
    return 0


def _resolve_hosts(args):
    """(num_hosts, host_index) for campaign sharding: explicit flags win;
    an initialized jax.distributed runtime supplies pod defaults; a lone
    process is host 0 of 1."""
    n, i = args.num_hosts, args.host_index
    if n <= 0 or i < 0:
        try:  # initialized only on real multi-host launches
            import jax

            if jax.process_count() > 1:
                n = n if n > 0 else jax.process_count()
                i = i if i >= 0 else jax.process_index()
        except Exception:  # noqa: BLE001 — backend may not be up yet
            pass
    n = n if n > 0 else 1
    i = i if i >= 0 else 0
    return n, i


def exec_campaign_merge(args) -> int:
    """Combine per-host campaign JSONs and/or fleet ledger dirs
    (reference has no analog — corpus scale is this rebuild's north
    star; SURVEY §5.8 corpus sharding, docs/fleet.md exactly-once
    merge). A missing or malformed input is a one-line typed error and
    a clean nonzero exit, never a traceback — merge runs on operator
    laptops against files scp'd off a pod."""
    import json
    import os

    from ..mythril.campaign import merge_campaigns

    results = []
    for p in args.results:
        if os.path.isdir(p):
            from ..fleet import ledger_results

            try:
                results.extend(ledger_results(p))
            except ValueError as e:
                print(f"error: campaign-merge: {e}", file=sys.stderr)
                return 2
            continue
        try:
            with open(p) as fh:
                doc = json.load(fh)
        except OSError as e:
            print(f"error: campaign-merge: cannot read {p}: "
                  f"{e.strerror or e}", file=sys.stderr)
            return 2
        except ValueError as e:
            print(f"error: campaign-merge: {p} is not valid JSON ({e})",
                  file=sys.stderr)
            return 2
        if not isinstance(doc, dict):
            print(f"error: campaign-merge: {p}: expected a campaign "
                  "result object", file=sys.stderr)
            return 2
        results.append(doc)
    merged = merge_campaigns(results)
    print(json.dumps(merged, indent=1))
    if args.strict_coverage:
        cov = merged.get("coverage")
        if cov is None:
            print("error: campaign-merge: --strict-coverage needs fleet "
                  "results (no coverage manifest in the inputs)",
                  file=sys.stderr)
            return 2
        if not cov.get("full"):
            print("error: campaign-merge: coverage incomplete: "
                  f"{cov.get('analyzed', 0)} analyzed + "
                  f"{cov.get('quarantined', 0)} quarantined of "
                  f"{cov.get('contracts', 0)} contracts "
                  f"({cov.get('lost', 0)} lost, "
                  f"{cov.get('unaccounted', 0)} unaccounted)",
                  file=sys.stderr)
            return 3
    return 0


def _exec_campaign(args) -> int:
    """Corpus campaign: BASELINE configs 2-3 (SURVEY §6), supervised by
    the resilience layer (watchdog + quarantine + backend fallback)."""
    import json
    import os

    from ..backend import parse_tiers
    from ..config import DEFAULT_RESILIENCE
    from ..resilience import BackendManager, FaultInjector, parse_ladder

    try:
        oom_ladder = parse_ladder(args.oom_ladder)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        raise SystemExit(2)

    # backend probe FIRST, while this process is still backend-free: a
    # wedged TPU runtime hangs jax.devices() forever (docs/
    # tpu-wedge-round5.md); the probe wedges a subprocess instead, and
    # the campaign degrades to the CPU backend with the event on record
    try:
        backend_tiers = (parse_tiers(args.backend_tiers)
                         if args.backend_tiers else None)
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        raise SystemExit(2)

    backend = None
    if args.init_timeout is not None:
        backend = BackendManager(
            init_timeout=args.init_timeout,
            max_attempts=DEFAULT_RESILIENCE.probe_attempts,
            backoff=DEFAULT_RESILIENCE.probe_backoff)
        ok, diag = backend.ensure_or_fallback(tiers=backend_tiers)
        if not ok:
            landed = os.environ.get("JAX_PLATFORMS", "cpu")
            print(f"warning: backend unavailable ({diag}); continuing "
                  f"on the {landed} backend", file=sys.stderr)

    from ..mythril.campaign import CorpusCampaign, load_corpus_dir
    from ..symbolic import SymSpec

    for flag, val in (("--create-timeout", args.create_timeout),
                      ("--statespace-json", args.statespace_json)):
        if val is not None:
            print(f"warning: {flag} has no effect in campaign mode",
                  file=sys.stderr)
    fleet_follow = getattr(args, "fleet_follow", False)
    if fleet_follow and args.corpus:
        print("error: --fleet-follow takes its contracts from the feed "
              "ledger; drop --corpus (or drop --fleet-follow for a "
              "static fleet)", file=sys.stderr)
        raise SystemExit(2)
    contracts = [] if fleet_follow else load_corpus_dir(args.corpus)
    if args.fleet:
        # the ledger IS the work distribution: every worker sees the
        # whole corpus and claims leased units (docs/fleet.md); a
        # static strided split underneath would desync the manifest
        if args.num_hosts > 0 or args.host_index >= 0:
            print("warning: --num-hosts/--host-index are ignored with "
                  "--fleet (the ledger distributes the work)",
                  file=sys.stderr)
        if args.checkpoint_dir:
            print("warning: --checkpoint-dir is unused with --fleet "
                  "(per-unit result files are the durable record)",
                  file=sys.stderr)
        num_hosts, host_index = 1, 0
    else:
        num_hosts, host_index = _resolve_hosts(args)
    campaign = CorpusCampaign(
        contracts,
        batch_size=args.batch_size,
        lanes_per_contract=args.lanes_per_contract,
        limits=_limits_for(args),
        spec=SymSpec(storage=not args.concrete_storage),
        max_steps=(args.max_depth if args.max_depth is not None
                   else args.max_steps),
        solver_timeout=(args.solver_timeout / 1000.0
                        if args.solver_timeout is not None else None),
        solver_iters=args.solver_iters,
        parallel_solving=args.parallel_solving,
        transaction_count=args.transaction_count,
        modules=args.modules.split(",") if args.modules else None,
        checkpoint_dir=args.checkpoint_dir,
        execution_timeout=args.execution_timeout,
        plugins=tuple(_discover_plugins(args.plugin_dir)),
        enable_iprof=args.enable_iprof,
        num_hosts=num_hosts,
        host_index=host_index,
        batch_timeout=args.batch_timeout,
        max_batch_retries=args.max_batch_retries,
        fault_injector=FaultInjector.from_string(args.fault_inject),
        backend=backend,
        oom_ladder=oom_ladder,
        checkpoint_every=args.checkpoint_every,
        heartbeat_every=args.heartbeat,
        pipeline=args.pipeline,
        solver_workers=args.solver_workers,
        fleet_dir=args.fleet,
        lease_ttl=args.lease_ttl,
        unit_size=args.unit_size,
        max_unit_leases=args.max_unit_leases,
        worker_id=args.worker_id,
        fleet_follow=fleet_follow,
        # "auto" lets the campaign apply the fleet default
        # (<fleet-dir>/solver_store); --no-solver-store beats both
        solver_store=(None if args.no_solver_store
                      else (args.solver_store or "auto")),
        worker_isolation=args.worker_isolation,
        backend_tiers=backend_tiers,
    )

    unit_word = "unit" if args.fleet else "batch"

    def progress(done, total, dt, n_issues):
        print(f"{unit_word} {done}/{total}: {dt:.1f}s, {n_issues} "
              "issue(s) so far", file=sys.stderr)

    res = campaign.run(progress=progress)
    out = res.as_dict()
    if args.outform in ("json", "jsonv2"):
        out["issues_detail"] = res.issues
    print(json.dumps(out, indent=1))
    return 0


def exec_serve(args) -> int:
    """Always-on analysis daemon (docs/serving.md): admission queue +
    bytecode-hash dedupe + warm-compile reuse + streaming results over
    a thin stdlib HTTP surface. Blocks until SIGTERM/SIGINT completes
    the graceful drain."""
    from ..obs import metrics as obs_metrics
    from ..obs import trace as obs_trace
    from ..resilience import parse_ladder
    from ..serve import (AnalysisDaemon, ServeOptions, ShedPolicy,
                         TenantQuota)

    try:
        oom_ladder = parse_ladder(args.oom_ladder)
        if args.backend_tiers:
            from ..backend import parse_tiers

            parse_tiers(args.backend_tiers)  # fail fast on unknown tiers
    except ValueError as e:
        print(f"error: {e}", file=sys.stderr)
        raise SystemExit(2)
    default_quota = None
    if (args.tenant_rate is not None or args.tenant_burst is not None
            or args.tenant_max_inflight is not None):
        default_quota = TenantQuota(
            rate=args.tenant_rate, burst=args.tenant_burst,
            max_inflight=args.tenant_max_inflight)
    quotas = {}
    for spec in args.quota or []:
        tenant, sep, rest = spec.partition("=")
        if not sep or not tenant:
            print(f"error: bad --quota {spec!r}; want "
                  "TENANT=RATE[:BURST[:INFLIGHT]]", file=sys.stderr)
            raise SystemExit(2)
        try:
            quotas[tenant] = TenantQuota.parse(rest)
        except ValueError as e:
            print(f"error: {e}", file=sys.stderr)
            raise SystemExit(2)
    shed = (None if args.no_shed
            else ShedPolicy(depth_hi=args.shed_depth_hi,
                            age_hi=args.shed_age_hi,
                            priority_max=args.shed_priority_max))
    if args.trace:
        obs_trace.configure(args.trace)
    opts = ServeOptions(
        batch_size=args.batch_size,
        lanes_per_contract=args.lanes_per_contract,
        max_steps=args.max_steps,
        transaction_count=args.transaction_count,
        modules=args.modules.split(",") if args.modules else None,
        limits_profile=args.limits_profile,
        solver_iters=args.solver_iters,
        solver_timeout=(args.solver_timeout / 1000.0
                        if args.solver_timeout is not None else None),
        solver_workers=args.solver_workers,
        batch_timeout=args.batch_timeout,
        max_batch_retries=args.max_batch_retries,
        oom_ladder=oom_ladder,
        fault_inject=args.fault_inject,
        concrete_storage=args.concrete_storage,
        worker_isolation=args.worker_isolation,
        backend_tiers=args.backend_tiers,
    )
    daemon = AnalysisDaemon(
        opts, data_dir=args.data_dir, host=args.host, port=args.port,
        dedupe=args.dedupe, max_queue=args.max_queue,
        drain_timeout=args.drain_timeout, fleet_dir=args.fleet,
        solver_store=(None if args.no_solver_store
                      else (args.solver_store or "auto")),
        quotas=quotas or None, default_quota=default_quota, shed=shed,
        follow_uri=args.follow, follow_poll=args.follow_poll,
        backfill_uri=args.backfill,
        backfill_window=args.backfill_window,
        compact_every=args.compact_every,
        store_only=args.store_only,
        compile_store=(args.compile_store or "auto"),
        prewarm=args.prewarm)
    daemon.install_signal_handlers()
    try:
        daemon.start()
        print(f"serving on {daemon.host}:{daemon.port} "
              f"(data dir {args.data_dir}"
              + (f", fleet feed {args.fleet}" if args.fleet else "")
              + ")", file=sys.stderr, flush=True)
        if args.port_file:
            with open(args.port_file, "w") as fh:
                fh.write(str(daemon.port))
        if args.heartbeat:
            _serve_heartbeat(daemon, args.heartbeat)
        daemon.wait_stopped()
    finally:
        daemon.shutdown("exit")
        if args.trace:
            try:
                obs_trace.close()
            except Exception as exc:  # noqa: BLE001 — never mask exit
                print(f"warning: trace write failed: {exc}",
                      file=sys.stderr)
        if args.metrics:
            try:
                obs_metrics.REGISTRY.write(args.metrics)
            except Exception as exc:  # noqa: BLE001
                print(f"warning: metrics write failed: {exc}",
                      file=sys.stderr)
    return 0


def _serve_heartbeat(daemon, period: float) -> None:
    """Start the serving heartbeat: one stderr line every ``period``
    seconds with queue depth, store size, and end-to-end request
    latency percentiles from the live ``serve_request_seconds``
    histogram (docs/observability.md "Heartbeat"). Daemon thread —
    dies with the process, never blocks drain."""
    import threading

    from ..obs import metrics as obs_metrics

    def _loop() -> None:
        while not daemon.wait_stopped(timeout=max(0.2, period)):
            rh = obs_metrics.REGISTRY.histogram(
                "serve_request_seconds",
                help="end-to-end request latency (submit to resolve)")
            rq = ""
            if rh.count:
                p50, p95 = rh.quantile(0.5), rh.quantile(0.95)
                rq = f" | req p50 {p50:.2f}s/p95 {p95:.2f}s"
            # compile-warmth token (docs/serving.md "Compile artifacts
            # & prewarm"): shape classes warm in-process / registry
            # buckets for the active tier
            wa = ""
            warm_a, warm_b = daemon.scheduler.warm_counts()
            if warm_a or warm_b:
                wa = f" warm {warm_a}/" + ("-" if warm_b is None
                                           else str(warm_b))
            print(f"[serve] depth {daemon.queue.depth()} "
                  f"store {daemon.store.count()}{wa}{rq}",
                  file=sys.stderr, flush=True)

    threading.Thread(target=_loop, daemon=True,
                     name="serve-heartbeat").start()


def _write_statespace(path: str, analyzer) -> None:
    """Explored-statespace JSON (reference: ``--statespace-json`` dumps
    the LASER node/edge graph, ``analysis/traceexplore.py`` ⚠unv). The
    frontier engine keeps no per-superstep node graph — its statespace IS
    the lane set — so the dump is per-transaction surviving paths (pc,
    frame depth, path-condition branches with their asserting pcs) plus
    per-contract instruction coverage, which carries the same audit
    content: what was reached, under which branch decisions."""
    import json

    import numpy as np

    sym = analyzer.sym
    out = {"transactions": [], "lanes": 0}
    for ti, ctx in enumerate(sym.tx_contexts):
        b = ctx.sf.base
        act = np.asarray(b.active)
        out["lanes"] = int(act.shape[0])
        pcs = np.asarray(b.pc)
        depth = np.asarray(b.depth)
        halted = np.asarray(b.halted)
        err = np.asarray(b.error)
        rev = np.asarray(b.reverted)
        cid = np.asarray(b.contract_id)
        con_pc = np.asarray(ctx.sf.con_pc)
        con_sign = np.asarray(ctx.sf.con_sign)
        con_len = np.asarray(ctx.sf.con_len)
        paths = []
        for lane in np.where(act)[0]:
            n = int(con_len[lane])
            paths.append({
                "lane": int(lane),
                "contract": ctx.cid_name(int(cid[lane])),
                "pc": int(pcs[lane]),
                "depth": int(depth[lane]),
                "halted": bool(halted[lane]),
                "error": bool(err[lane]),
                "reverted": bool(rev[lane]),
                "branches": [
                    {"pc": int(con_pc[lane, k]),
                     "taken": bool(con_sign[lane, k])}
                    for k in range(n) if int(con_pc[lane, k]) >= 0
                ],
            })
        out["transactions"].append({"tx": ti, "paths": paths})
    out["instruction_coverage_pct"] = sym.instruction_coverage()
    with open(path, "w") as fh:
        json.dump(out, fh, indent=1)


def _write_graph(path: str, contract, analyzer) -> None:
    """CFG of the first contract, explored blocks highlighted: a *.html
    path gets the self-contained interactive page (reference: the
    bundled-JS ``--graph`` HTML ⚠unv), anything else graphviz DOT."""
    from ..disassembler.cfg import CFG

    cfg = CFG(contract.code)
    sym = analyzer.sym
    if sym is not None and getattr(sym, "_visited", None) is not None:
        # runtime image index: with creation bytecodes the runtime images
        # occupy the second half of the corpus
        ci = len(sym.images) - len(analyzer.contracts)
        cfg.mark_reached(sym._visited[ci])
    render = (cfg.as_html if path.lower().endswith((".html", ".htm"))
              else cfg.as_dot)
    # explicit utf-8: the HTML template has non-ASCII (em dashes) and a
    # C-locale container would otherwise UnicodeEncodeError after the
    # whole symbolic run already succeeded
    with open(path, "w", encoding="utf-8") as fh:
        fh.write(render(contract.name))


def exec_disassemble(args) -> int:
    contract = _load_contracts(args)[0]
    print(contract.get_easm(), end="")
    return 0


def exec_concolic(args) -> int:
    """Reference: ``myth concolic`` (``mythril/concolic`` ⚠unv) — here a
    front door over :func:`concolic_execution` (one sym_run serves every
    branch flip)."""
    import json

    from ..concolic import concolic_execution, load_concrete_data

    ja = ([int(x, 0) for x in args.jump_addresses.split(",")]
          if args.jump_addresses else None)
    caller = None
    if args.input:
        # reference trace-file mode (``myth concolic input.json`` ⚠unv);
        # the trace supplies code+seed, so explicit overrides conflict
        if args.calldata or args.code or args.codefile or args.callvalue:
            print("error: --input supplies code/calldata/value from the "
                  "trace; drop the conflicting flags", file=sys.stderr)
            raise SystemExit(2)
        code, calldata, callvalue, caller = load_concrete_data(args.input)
    else:
        if not args.calldata:
            print("error: provide --calldata or a --input trace file",
                  file=sys.stderr)
            raise SystemExit(2)
        contracts = _load_contracts(args)
        code = contracts[0].code
        calldata = bytes.fromhex(args.calldata.removeprefix("0x"))
        callvalue = args.callvalue
    flips = concolic_execution(
        code,
        calldata,
        jump_addresses=ja,
        callvalue=callvalue,
        caller=caller,
        limits=_limits_for(args),
        max_steps=args.max_steps,
        solver_iters=args.solver_iters,
    )
    print(json.dumps([
        {"pc": f.pc, "constraint_index": f.constraint_index,
         "calldata": "0x" + f.calldata.hex(),
         "callvalue": f.callvalue, "caller": f"0x{f.caller:040x}"}
        for f in flips
    ], indent=1))
    return 0


def exec_read_storage(args) -> int:
    from ..utils.loader import DynLoader, rpc_client_from_uri

    dl = DynLoader(rpc_client_from_uri(args.rpc))
    word = dl.read_storage(int(args.address, 16), int(args.index, 0))
    print(f"0x{word:064x}")
    return 0


def exec_function_to_hash(args) -> int:
    from ..utils.signatures import selector_of

    print("0x" + selector_of(args.signature))
    return 0


def _checksum_address(addr20: bytes) -> str:
    """EIP-55 mixed-case checksum encoding."""
    from ..ops.keccak import keccak256_host

    hexaddr = addr20.hex()
    h = keccak256_host(hexaddr.encode()).hex()
    return "0x" + "".join(
        ch.upper() if ch.isalpha() and int(h[i], 16) >= 8 else ch
        for i, ch in enumerate(hexaddr)
    )


def exec_hash_to_address(args) -> int:
    """Reference: ``myth hash-to-address`` — a 32-byte storage word whose
    low 20 bytes are an address, rendered checksummed (⚠unv)."""
    for word in args.hashes:
        raw = bytes.fromhex(word.removeprefix("0x").rjust(64, "0"))
        print(_checksum_address(raw[12:]))
    return 0


def exec_safe_functions(args) -> int:
    """Reference: ``myth safe-functions`` — functions in which no issue
    was detected (⚠unv). Coverage warnings are printed alongside: a
    function is only as safe as the exploration was complete."""
    from ..mythril import MythrilAnalyzer, MythrilConfig
    from ..utils.signatures import SignatureDB

    contracts = _load_contracts(args)
    cfg = MythrilConfig(
        limits=_limits_for(args),
        transaction_count=args.transaction_count,
        max_steps=args.max_steps,
        lanes_per_contract=args.lanes_per_contract,
    )
    analyzer = MythrilAnalyzer(contracts, cfg)
    report = analyzer.fire_lasers()
    flagged = {i.function for i in report.issues if i.function}
    db = SignatureDB()
    for contract in contracts:
        names = []
        for sel in contract.disassembly.func_hashes:
            sigs = db.lookup(sel)
            # same fallback name _label_functions gives issues, so an
            # unknown-selector function with findings is never "safe"
            name = sigs[0] if sigs else "0x" + sel.removeprefix("0x")
            if name not in flagged:
                names.append(name)
        print(f"{contract.name}: {len(names)} safe function(s)")
        for n in sorted(names):
            print(f"  {n}")
    for w in report.coverage_warnings():
        print(f"warning: {w}", file=sys.stderr)
    return 0


def exec_list_detectors(args) -> int:
    from ..analysis import ModuleLoader

    _discover_plugins(getattr(args, "plugin_dir", None))
    for m in ModuleLoader().get_detection_modules():
        print(f"{m.name} (SWC-{m.swc_id}): {m.description}")
    return 0


def main(argv: Optional[List[str]] = None) -> int:
    parser = create_parser()
    args = parser.parse_args(argv)
    if args.command in ("analyze", "a"):
        return exec_analyze(args)
    if args.command in ("disassemble", "d"):
        return exec_disassemble(args)
    if args.command == "concolic":
        return exec_concolic(args)
    if args.command == "read-storage":
        return exec_read_storage(args)
    if args.command == "function-to-hash":
        return exec_function_to_hash(args)
    if args.command == "hash-to-address":
        return exec_hash_to_address(args)
    if args.command == "safe-functions":
        return exec_safe_functions(args)
    if args.command == "campaign-merge":
        return exec_campaign_merge(args)
    if args.command == "serve":
        return exec_serve(args)
    if args.command == "list-detectors":
        return exec_list_detectors(args)
    if args.command == "version":
        from .. import __version__

        print(f"mythril_tpu {__version__}")
        return 0
    parser.print_help()
    return 2


if __name__ == "__main__":
    raise SystemExit(main())
