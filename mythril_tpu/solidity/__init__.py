"""Solidity frontend (reference: ``mythril/solidity/`` ⚠unv)."""

from .soliditycontract import (SolcError, SolcNotFound, SolidityContract,
                               SourceMapEntry, compile_solidity,
                               get_contracts_from_standard_json,
                               parse_srcmap)

__all__ = ["SolcError", "SolcNotFound", "SolidityContract",
           "SourceMapEntry", "compile_solidity",
           "get_contracts_from_standard_json", "parse_srcmap"]
