"""Solidity artifact frontend (reference: ``mythril/solidity/`` ⚠unv)."""

from .soliditycontract import (SolidityContract, SourceMapEntry,
                               get_contracts_from_standard_json,
                               parse_srcmap)

__all__ = ["SolidityContract", "SourceMapEntry",
           "get_contracts_from_standard_json", "parse_srcmap"]
