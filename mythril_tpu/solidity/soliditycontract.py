"""Solidity frontend: solc subprocess + standard-JSON ingestion + srcmaps.

Reference: ``mythril/solidity/soliditycontract.py`` (⚠unv, SURVEY.md §2
row "Solidity frontend") shells out to solc. Two paths here:

- :func:`compile_solidity` runs ``solc --standard-json`` when a compiler
  is on PATH (gated — this image carries none; the subprocess protocol
  is stub-tested);
- :func:`get_contracts_from_standard_json` consumes solc's OUTPUT
  artifact (``evm.deployedBytecode.object`` + ``sourceMap``) — the same
  data, one process boundary earlier, for hermetic environments.

Issues then map to source lines, which the reference's golden reports
include (VERDICT r2 missing #6).

Source-map format (solc docs, public spec): ``s:l:f:j:m`` entries
separated by ``;``, empty fields inheriting the previous entry; one entry
per INSTRUCTION of the deployed code.
"""

from __future__ import annotations

import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, List, Optional, Tuple, Union

from ..disassembler.disassembly import _to_bytes, disassemble


@dataclass(frozen=True)
class SourceMapEntry:
    offset: int      # byte offset into the source file
    length: int
    file_idx: int    # -1 = compiler-generated
    jump: str        # 'i' / 'o' / '-'


def parse_srcmap(srcmap: str) -> List[SourceMapEntry]:
    out: List[SourceMapEntry] = []
    prev = [0, 0, 0, "-"]
    if not srcmap:
        return out
    for entry in srcmap.split(";"):
        fields = entry.split(":")
        for i in range(4):
            if i < len(fields) and fields[i] != "":
                prev[i] = fields[i] if i == 3 else int(fields[i])
        out.append(SourceMapEntry(int(prev[0]), int(prev[1]),
                                  int(prev[2]), str(prev[3])))
    return out


@dataclass
class SolidityContract:
    """Quacks like ``EVMContract`` (code/creation_code/name) plus source
    mapping, so ``MythrilAnalyzer`` takes it directly."""

    name: str
    code: bytes
    creation_code: Optional[bytes] = None
    srcmap: List[SourceMapEntry] = field(default_factory=list)
    # file_idx -> (filename, content-or-None)
    sources: Dict[int, Tuple[str, Optional[str]]] = field(default_factory=dict)
    _pc_to_instr: Optional[Dict[int, int]] = field(default=None, repr=False)

    def __post_init__(self):
        self._pc_to_instr = {
            ins.address: i for i, ins in enumerate(disassemble(self.code))
        }

    def get_easm(self) -> str:
        from ..disassembler.disassembly import Disassembly

        return Disassembly(self.code).get_easm()

    def source_location(self, pc: int) -> Optional[Dict]:
        """{'filename', 'offset', 'length', 'lineno', 'snippet'} for a
        deployed-code pc, or None when unmapped."""
        idx = self._pc_to_instr.get(pc)
        if idx is None or idx >= len(self.srcmap):
            return None
        e = self.srcmap[idx]
        if e.file_idx < 0 or e.file_idx not in self.sources:
            return None
        filename, content = self.sources[e.file_idx]
        loc = {"filename": filename, "offset": e.offset, "length": e.length,
               "lineno": None, "snippet": None}
        if content is not None and e.offset <= len(content):
            loc["lineno"] = content.count("\n", 0, e.offset) + 1
            snippet = content[e.offset: e.offset + e.length]
            loc["snippet"] = re.sub(r"\s+", " ", snippet)[:120]
        return loc


def make_standard_json_input(sources: Dict[str, str]) -> dict:
    """Compiler INPUT document for ``{path: source_text}`` requesting the
    artifacts the frontend consumes (deployed/creation bytecode + srcmaps)."""
    return {
        "language": "Solidity",
        "sources": {name: {"content": text} for name, text in sources.items()},
        "settings": {
            "outputSelection": {
                "*": {"*": ["evm.bytecode.object",
                            "evm.deployedBytecode.object",
                            "evm.deployedBytecode.sourceMap"]}
            }
        },
    }


def compile_solidity(paths: List[str],
                     solc_path: Optional[str] = None,
                     timeout: float = 120.0) -> List[SolidityContract]:
    """Shell out to ``solc --standard-json`` and ingest the result.

    Reference: ``SolidityContract`` invoking solc as a subprocess
    (``mythril/solidity/soliditycontract.py`` + ``ethereum/util.py``
    ⚠unv, SURVEY.md §3.1 "PROCESS BOUNDARY"). This image carries no solc
    binary, so the path is GATED: a missing compiler raises a clear
    ``SolcNotFound`` naming the artifact-ingestion alternative, and tests
    drive the subprocess protocol with a stub solc (same standard-JSON
    contract either way)."""
    import shutil
    import subprocess

    solc = solc_path or os.environ.get("MYTHRIL_SOLC", "solc")
    if shutil.which(solc) is None:
        raise SolcNotFound(
            f"solc binary {solc!r} not found on PATH; compile offline and "
            "load the standard-JSON artifact instead "
            "(get_contracts_from_standard_json)")
    sources = {}
    for p in paths:
        with open(p) as fh:
            sources[p] = fh.read()
    inp = make_standard_json_input(sources)
    try:
        r = subprocess.run([solc, "--standard-json"],
                           input=json.dumps(inp), capture_output=True,
                           text=True, timeout=timeout)
    except subprocess.TimeoutExpired as e:
        raise SolcError(f"solc timed out after {timeout:.0f}s") from e
    if r.returncode != 0:
        raise SolcError(f"solc exited {r.returncode}: {r.stderr[:500]}")
    try:
        out = json.loads(r.stdout)
    except json.JSONDecodeError as e:
        raise SolcError(f"solc emitted invalid JSON: {e}") from e
    errors = [e for e in out.get("errors", [])
              if e.get("severity") == "error"]
    if errors:
        raise SolcError("; ".join(
            e.get("formattedMessage", e.get("message", "?"))[:200]
            for e in errors[:5]))
    return get_contracts_from_standard_json(out, inp)


class SolcNotFound(RuntimeError):
    """No solc on PATH (expected in hermetic images — use artifacts)."""


class SolcError(RuntimeError):
    """solc ran but failed (compile errors, bad output)."""


def get_contracts_from_standard_json(
    artifact: Union[str, dict],
    input_json: Union[str, dict, None] = None,
) -> List[SolidityContract]:
    """Load every contract with deployed bytecode from a solc standard-
    JSON OUTPUT (path or dict). ``input_json`` (the compiler INPUT, which
    holds the source text) enables line numbers; without it locations are
    byte offsets only. Also accepts combined files that carry both under
    ``{"input": ..., "output": ...}``."""
    def load(x):
        if isinstance(x, str):
            with open(x) as fh:
                return json.load(fh)
        return x

    doc = load(artifact)
    if "output" in doc and "contracts" in doc.get("output", {}):
        input_json = input_json or doc.get("input")
        doc = doc["output"]
    inp = load(input_json) if input_json else {}

    # file name -> source index (output "sources" carries ids)
    ids = {name: meta.get("id", i)
           for i, (name, meta) in enumerate(doc.get("sources", {}).items())}
    contents = {name: src.get("content")
                for name, src in inp.get("sources", {}).items()}
    sources = {idx: (name, contents.get(name)) for name, idx in ids.items()}

    out: List[SolidityContract] = []
    for file_name, contracts in doc.get("contracts", {}).items():
        for cname, cdata in contracts.items():
            evm = cdata.get("evm", {})
            deployed = evm.get("deployedBytecode", {}) or {}
            runtime_hex = deployed.get("object") or ""
            if not runtime_hex:
                continue
            creation_hex = (evm.get("bytecode", {}) or {}).get("object")
            out.append(SolidityContract(
                name=cname,
                code=_to_bytes(runtime_hex),
                creation_code=_to_bytes(creation_hex) if creation_hex else None,
                srcmap=parse_srcmap(deployed.get("sourceMap", "")),
                sources=sources,
            ))
    return out
