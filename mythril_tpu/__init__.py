"""mythril_tpu — a TPU-native symbolic-execution security analyzer for EVM bytecode.

A ground-up JAX/XLA/Pallas re-design of the capabilities of terasum/mythril
(reference layout surveyed in SURVEY.md; mount was empty, citations ⚠unv):

- the per-opcode symbolic state transition of the reference's LASER engine
  (``mythril/laser/ethereum/svm.py`` ⚠unv) becomes a vmapped 256-bit
  (8 x u32 limb) interpreter over a struct-of-arrays frontier of
  (contract, path) lanes;
- path conditions live on an on-device SSA constraint tape decided by
  batched bit-vector constraint propagation with a massively parallel
  guided model search (the reference's Z3 ``Solver.check()`` in
  ``mythril/laser/smt`` ⚠unv has no Z3 available here — the solver stack
  is self-built and TPU-first);
- search strategies (``mythril/laser/ethereum/strategy`` ⚠unv) become
  frontier-scheduling policies over masked lanes;
- the SWC detection-module suite (``mythril/analysis/module`` ⚠unv)
  consumes *batched* states through a source-compatible API.

x64 mode is required for u64 limb intermediates and is enabled on import.
"""

import os

import jax

jax.config.update("jax_enable_x64", True)

# Some site configurations force a platform preference that overrides the
# JAX_PLATFORMS environment variable; an explicit env setting is user
# intent, so re-assert it (e.g. JAX_PLATFORMS=cpu for CI boxes).
_env_platforms = os.environ.get("JAX_PLATFORMS")
if _env_platforms:
    try:
        jax.config.update("jax_platforms", _env_platforms)
    except RuntimeError:
        pass  # backend already initialized

__version__ = "0.1.0"
