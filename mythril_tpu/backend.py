"""Backend tiers: one registry of platform profiles and the
demote-and-repromote failover ladder built on top of it.

Before this module, platform knowledge was smeared across the tree as
``JAX_PLATFORMS=cpu`` literals: the startup probe's fallback pinned the
process to CPU (resilience.py), the OOM ladder's terminal rung was the
string ``"cpu"`` (config.py), bench re-ran itself under a hard-coded
CPU env (bench.py) — and nothing ever *lifted* any of those pins, so a
transient TPU wedge demoted the process for its whole lifetime.

This module replaces all of that with two pieces:

- :class:`BackendProfile` — a frozen record per platform (tpu/gpu/cpu)
  owning the constants the rest of the tree used to hard-code: tier
  rank, default lane width, padding multiple, probe timeout, the OOM
  ladder shape, and the ``pure_callback`` dispatch strategy.

- :class:`TierManager` — the ranked failover ladder. Failures demote
  to the *next* tier (not straight to CPU); a background prober
  re-checks the better tier with the same subprocess-isolation
  contract as the startup probe and climbs back when it passes. A
  sticky demotion window plus flap damping (a bounded count of
  transitions per rolling window) keep an oscillating backend from
  thrashing warm compiles.

Import cost matters: config.py imports this module, and the engine
worker imports config before JAX — so this file is stdlib-only at
import time.
"""

from __future__ import annotations

import collections
import os
import subprocess
import sys
import threading
import time
from dataclasses import dataclass
from typing import Callable, Deque, Dict, List, Optional, Sequence, Tuple

__all__ = [
    "BackendProfile", "PROFILES", "TIER_ORDER", "TIER_RUNG",
    "profile", "terminal_tier", "default_oom_ladder", "parse_tiers",
    "detect_tiers", "tiers_below", "tier_of_platform", "probe_tier",
    "available_tiers", "TierManager",
]


@dataclass(frozen=True)
class BackendProfile:
    """Everything the rest of the tree needs to know about one
    platform, so no caller has to special-case ``if platform == "tpu"``
    again. ``rank`` orders the failover ladder (0 is best)."""

    name: str
    rank: int
    #: value written to ``JAX_PLATFORMS`` to pin a process here
    jax_platform: str
    #: default interpreter lane width (SIMD batch of contract paths)
    default_lanes: int
    #: pad batch dims to this multiple (MXU/VPU tiling on TPU; warp
    #: width on GPU; no constraint worth paying for on host CPU)
    pad_multiple: int
    #: subprocess probe budget — how long ``jax.devices()`` may take
    #: before the tier is declared wedged (TPU tunnel init is slow)
    probe_timeout: float
    #: degradation ladder walked on RESOURCE_EXHAUSTED at this tier
    oom_ladder: Tuple[str, ...]
    #: host-callback strategy: "threaded" platforms tolerate blocking
    #: io_callback bodies; "inline" runs them on the dispatch thread
    pure_callback: str
    description: str = ""


#: historical name of the terminal OOM-ladder rung. It predates tiers
#: ("cpu" literally meant pin-to-CPU); it now means "demote to the
#: next available tier" and is resolved against the tier list at walk
#: time. Config strings keep accepting both spellings.
TIER_RUNG = "cpu"
#: accepted alias in ``--oom-ladder`` strings for the terminal rung
TIER_RUNG_ALIAS = "next-tier"

PROFILES: Dict[str, BackendProfile] = {
    "tpu": BackendProfile(
        name="tpu", rank=0, jax_platform="tpu",
        default_lanes=8, pad_multiple=8, probe_timeout=75.0,
        oom_ladder=("halve-lanes", "halve-batch", TIER_RUNG),
        pure_callback="threaded",
        description="TPU via PJRT tunnel; slow init, fast lanes"),
    "gpu": BackendProfile(
        name="gpu", rank=1, jax_platform="cuda",
        default_lanes=8, pad_multiple=4, probe_timeout=30.0,
        oom_ladder=("halve-lanes", "halve-batch", TIER_RUNG),
        pure_callback="threaded",
        description="CUDA/ROCm lanes; a first-class tier, not a "
                    "second CPU"),
    "cpu": BackendProfile(
        name="cpu", rank=2, jax_platform="cpu",
        default_lanes=8, pad_multiple=1, probe_timeout=20.0,
        # on the floor tier the terminal rung is a no-op (there is no
        # tier below the host), so the floor's ladder ends at batching
        oom_ladder=("halve-lanes", "halve-batch"),
        pure_callback="inline",
        description="host CPU; always present, never probed away"),
}

#: ladder order, best first — the single source of tier rank
TIER_ORDER: Tuple[str, ...] = tuple(
    sorted(PROFILES, key=lambda n: PROFILES[n].rank))


def profile(name: str) -> BackendProfile:
    try:
        return PROFILES[name]
    except KeyError:
        raise ValueError(
            f"unknown backend tier {name!r} (known: {', '.join(TIER_ORDER)})"
        ) from None


def terminal_tier() -> str:
    """The floor of the ladder — the tier that needs no probe because
    losing it means losing the host itself."""
    return TIER_ORDER[-1]


def default_oom_ladder() -> Tuple[str, ...]:
    """The degradation ladder of the best-ranked tier: what a campaign
    walks on RESOURCE_EXHAUSTED before demoting off the tier."""
    return PROFILES[TIER_ORDER[0]].oom_ladder


def parse_tiers(value) -> Tuple[str, ...]:
    """Normalize a tier list (comma string or sequence) into a ranked,
    deduplicated tuple. Rejects unknown names; always keeps the
    terminal tier at the end so the ladder has a floor."""
    if value is None:
        return detect_tiers()
    if isinstance(value, str):
        names = [t.strip() for t in value.split(",") if t.strip()]
    else:
        names = [str(t) for t in value]
    for n in names:
        profile(n)  # raises ValueError on unknown tiers
    ranked = tuple(sorted(set(names), key=lambda n: PROFILES[n].rank))
    if not ranked:
        return (terminal_tier(),)
    if ranked[-1] != terminal_tier():
        ranked = ranked + (terminal_tier(),)
    return ranked


def detect_tiers() -> Tuple[str, ...]:
    """The ranked tier list this process should consider, without
    probing anything: ``MYTHRIL_BACKEND_TIERS`` wins, else a pinned
    ``JAX_PLATFORMS`` restricts the ladder to that platform (plus the
    floor), else the full ladder."""
    env = os.environ.get("MYTHRIL_BACKEND_TIERS")
    if env:
        return parse_tiers(env)
    pinned = os.environ.get("JAX_PLATFORMS")
    if pinned:
        known = [t for t in (p.strip() for p in pinned.split(","))
                 if t in PROFILES]
        if known:
            return parse_tiers(known)
    return TIER_ORDER


def tiers_below(name: str, tiers: Optional[Sequence[str]] = None
                ) -> Tuple[str, ...]:
    """Tiers ranked strictly worse than ``name``, best first."""
    ladder = parse_tiers(tiers) if tiers is not None else TIER_ORDER
    rank = profile(name).rank
    return tuple(t for t in ladder if PROFILES[t].rank > rank)


def tier_of_platform(platform) -> Optional[str]:
    """Map a platform label (``jax.default_backend()`` output, a bench
    ``platform`` field like ``"cpu-fallback"``, or a profile name) back
    to its tier name; None when unrecognizable."""
    if not platform:
        return None
    label = str(platform).lower()
    for name, prof in PROFILES.items():
        if label == name or label == prof.jax_platform:
            return name
        if label.startswith(name + "-") or label.startswith(
                prof.jax_platform + "-"):
            return name
    return None


# ---------------------------------------------------------------------------
# subprocess probe — the PR 10 isolation contract: the child does the
# dangerous device init; a wedged child is abandoned, never joined.


def _probe_child(env: Dict[str, str], timeout_s: float) -> Tuple[bool, str]:
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    code = ("import mythril_tpu, jax; d = jax.devices(); "
            "print('OK', jax.default_backend(), len(d))")
    try:
        proc = subprocess.Popen(
            [sys.executable, "-c", code],
            stdout=subprocess.PIPE, stderr=subprocess.PIPE,
            cwd=root, env=env, text=True)
    except OSError as e:  # pragma: no cover - spawn failure
        return False, f"probe spawn failed: {e}"
    deadline = time.monotonic() + timeout_s
    while proc.poll() is None:
        if time.monotonic() >= deadline:
            # abandon, don't join: a D-state child wedged in device
            # init survives SIGKILL and a .wait() would hang us too
            try:
                proc.kill()
            except OSError:
                pass
            return False, f"probe timed out after {timeout_s:.0f}s"
        time.sleep(0.05)
    out = (proc.stdout.read() if proc.stdout else "") or ""
    err = (proc.stderr.read() if proc.stderr else "") or ""
    if proc.returncode == 0 and out.startswith("OK"):
        return True, out.strip()
    tail = (err.strip().splitlines() or ["no stderr"])[-1]
    return False, f"probe exited rc={proc.returncode}: {tail[:200]}"


def probe_tier(tier: str, timeout_s: Optional[float] = None
               ) -> Tuple[bool, str]:
    """Health-check one tier in a subprocess pinned to that platform.
    The floor tier always passes without spawning anything — the host
    CPU being gone is not a state this process can observe."""
    prof = profile(tier)
    if tier == terminal_tier():
        return True, "terminal tier (host CPU), no probe needed"
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = prof.jax_platform
    env.pop("MYTHRIL_WORKER_FAULT", None)
    return _probe_child(
        env, prof.probe_timeout if timeout_s is None else timeout_s)


def available_tiers(tiers: Optional[Sequence[str]] = None,
                    probe_fn: Optional[Callable] = None,
                    timeout_s: Optional[float] = None) -> Tuple[str, ...]:
    """Probe each candidate tier and return the ranked subset that
    answers. The floor tier is always included."""
    probe = probe_fn or probe_tier
    out: List[str] = []
    for tier in parse_tiers(tiers) if tiers is not None else detect_tiers():
        ok, _ = probe(tier, timeout_s)
        if ok:
            out.append(tier)
    if terminal_tier() not in out:
        out.append(terminal_tier())
    return tuple(out)


# ---------------------------------------------------------------------------
# metrics — lazy import like resilience.py so backend.py stays cheap
# for the engine worker's import path


def _counter(name: str, help_: str = ""):
    try:
        from .obs import metrics as obs_metrics
        return obs_metrics.REGISTRY.counter(name, help=help_)
    except Exception:  # pragma: no cover - obs must never break tiers
        return None


def _gauge(name: str, help_: str = ""):
    try:
        from .obs import metrics as obs_metrics
        return obs_metrics.REGISTRY.gauge(name, help=help_)
    except Exception:  # pragma: no cover
        return None


class TierManager:
    """The demote-and-repromote ladder over a ranked tier list.

    State machine (docs/resilience.md "Backend tiers")::

        preferred --demote(crash-loop / device-lost)--> demoted
        demoted   --probe passes, sticky window over--> repromoted
        demoted   --window full of transitions--------> flap-damped

    Thread model: ``demote``/``tick`` are called from the campaign
    thread; the optional background prober calls ``tick`` from its own
    daemon thread. All state mutations hold ``_lock``; the campaign
    folds transitions into its own state (warm-marker invalidation,
    worker respawn) by watching ``generation`` — the prober itself
    never touches campaign state.

    ``env_pin`` controls whether :meth:`platform_env` pins spawned
    engine workers with ``JAX_PLATFORMS``; tests running synthetic
    ladders (e.g. a pretend "tpu" tier on a CPU-only box) set it False
    so the tier is an accounting state while execution stays on host.
    """

    def __init__(self,
                 tiers: Optional[Sequence[str]] = None,
                 probe_fn: Optional[Callable[[str, Optional[float]],
                                             Tuple[bool, str]]] = None,
                 sticky_window: float = 20.0,
                 flap_window: float = 120.0,
                 flap_max: int = 4,
                 probe_every: float = 30.0,
                 env_pin: bool = True,
                 auto_prober: bool = True,
                 on_event: Optional[Callable] = None):
        self.tiers: Tuple[str, ...] = parse_tiers(tiers)
        self.probe_fn = probe_fn or probe_tier
        self.sticky_window = float(sticky_window)
        self.flap_window = float(flap_window)
        self.flap_max = int(flap_max)
        self.probe_every = float(probe_every)
        self.env_pin = bool(env_pin)
        self.auto_prober = bool(auto_prober)
        self.on_event = on_event
        self.events: List[Dict] = []
        self.demotions = 0
        self.repromotions = 0
        self.probe_failures = 0
        #: bumped on every applied transition; campaigns compare it to
        #: fold warm-invalidation + worker respawn at a safe point
        self.generation = 0
        self._idx = 0
        self._lock = threading.RLock()
        self._transitions: Deque[float] = collections.deque()
        self._demoted_at: Optional[float] = None
        self._last_probe: Optional[float] = None
        self._damped_emitted = False
        self._prober: Optional[threading.Thread] = None
        self._stop = threading.Event()
        _gauge("engine_backend_tier",
               "rank of the current backend tier (0 = best)"
               ).set(profile(self.current).rank)

    # -- introspection ----------------------------------------------------

    @property
    def current(self) -> str:
        return self.tiers[self._idx]

    @property
    def preferred(self) -> str:
        return self.tiers[0]

    def demoted(self) -> bool:
        return self._idx > 0

    def current_profile(self) -> BackendProfile:
        return profile(self.current)

    def platform_env(self) -> Dict[str, str]:
        """Env overlay for spawned engine workers: pin them to the
        tier this manager currently holds (empty when env pinning is
        disabled for synthetic-ladder tests)."""
        if not self.env_pin:
            return {}
        return {"JAX_PLATFORMS": self.current_profile().jax_platform}

    def status(self) -> Dict:
        with self._lock:
            return {
                "tiers": list(self.tiers),
                "current": self.current,
                "preferred": self.preferred,
                "demoted": self.demoted(),
                "demotions": self.demotions,
                "repromotions": self.repromotions,
                "probe_failures": self.probe_failures,
                "transitions_in_window": len(self._transitions),
                "flap_damped": self._damped_emitted,
                "generation": self.generation,
            }

    # -- events -----------------------------------------------------------

    def _event(self, kind: str, detail: str = "", **kw) -> None:
        rec = {"kind": kind, "detail": detail, "t": time.time()}
        rec.update(kw)
        self.events.append(rec)
        if self.on_event is not None:
            try:
                self.on_event(kind, detail=detail, **kw)
            except Exception:  # pragma: no cover - observer must not kill us
                pass
        else:
            try:
                from .obs import trace as obs_trace
                obs_trace.event("tier_" + kind if not kind.startswith("tier")
                                else kind, detail=detail, **kw)
            except Exception:  # pragma: no cover
                pass

    def _note_transition(self, now: float) -> None:
        self._transitions.append(now)
        self._trim_window(now)
        self.generation += 1
        _gauge("engine_backend_tier").set(self.current_profile().rank)

    def _trim_window(self, now: float) -> None:
        while self._transitions and now - self._transitions[0] > self.flap_window:
            self._transitions.popleft()
        if len(self._transitions) + 2 <= self.flap_max:
            # window drained enough for a round trip again — the next
            # damping episode gets its own event
            self._damped_emitted = False

    # -- transitions ------------------------------------------------------

    def demote(self, reason: str = "", failed: Optional[str] = None) -> str:
        """Step down one tier because ``failed`` (default: the current
        tier) just proved unhealthy. No-op when we already sit below
        the failed tier (a stale report must not double-demote) or on
        the floor. Returns the tier now held."""
        with self._lock:
            failed = failed or self.current
            if profile(self.current).rank > profile(failed).rank:
                return self.current
            if self._idx + 1 >= len(self.tiers):
                # the floor: nothing below to demote to; stay pinned
                # and let the prober (if any) climb back later
                return self.current
            src = self.current
            self._idx += 1
            self.demotions += 1
            now = time.monotonic()
            self._demoted_at = now
            self._note_transition(now)
            c = _counter("engine_tier_demotions_total",
                         "backend tier demotions")
            if c is not None:
                c.inc()
            self._event("tier_demoted", detail=reason[:200],
                        src=src, dst=self.current)
            if self.auto_prober and self.probe_every > 0:
                self.start_prober()
            return self.current

    def maybe_repromote(self) -> bool:
        """Try to climb one tier back up. Gated by the sticky demotion
        window (fresh demotions hold), flap damping (no headroom for a
        demote+repromote round trip in the rolling window), and a live
        probe of the better tier. Returns True when a climb applied."""
        with self._lock:
            if self._idx == 0:
                return False
            now = time.monotonic()
            if (self._demoted_at is not None
                    and now - self._demoted_at < self.sticky_window):
                return False
            self._trim_window(now)
            if len(self._transitions) + 2 > self.flap_max:
                if not self._damped_emitted:
                    self._damped_emitted = True
                    self._event(
                        "tier_flap_damped",
                        detail=(f"{len(self._transitions)} transitions in "
                                f"{self.flap_window:.0f}s window; holding "
                                f"{self.current} (flap_max={self.flap_max})"),
                        held=self.current)
                return False
            target = self.tiers[self._idx - 1]
            self._last_probe = now
            try:
                ok, diag = self.probe_fn(target, profile(target).probe_timeout)
            except Exception as e:  # pragma: no cover - probe must not kill us
                ok, diag = False, f"probe raised: {e}"
            if not ok:
                self.probe_failures += 1
                c = _counter("engine_tier_probe_failures_total",
                             "failed re-promotion probes")
                if c is not None:
                    c.inc()
                self._event("tier_probe_failed", detail=str(diag)[:200],
                            target=target)
                return False
            self._idx -= 1
            self.repromotions += 1
            self._note_transition(time.monotonic())
            c = _counter("engine_tier_repromotions_total",
                         "backend tier re-promotions")
            if c is not None:
                c.inc()
            self._event("tier_repromoted", detail=str(diag)[:200],
                        dst=self.current)
            return True

    def tick(self) -> bool:
        """Periodic driver: attempt a re-promotion when one is due.
        Called by campaigns at batch boundaries (so transitions land at
        accounting-safe points) and by the background prober."""
        with self._lock:
            if self._idx == 0:
                return False
            if (self.probe_every > 0 and self._last_probe is not None
                    and time.monotonic() - self._last_probe < self.probe_every):
                return False
        return self.maybe_repromote()

    # -- background prober ------------------------------------------------

    def start_prober(self) -> None:
        """Start the background re-promotion prober (idempotent). It
        retires itself once the preferred tier is regained; a later
        demotion starts a fresh one."""
        with self._lock:
            if self._prober is not None and self._prober.is_alive():
                return
            self._stop.clear()
            self._prober = threading.Thread(
                target=self._probe_loop, name="tier-prober", daemon=True)
            self._prober.start()

    def stop_prober(self) -> None:
        self._stop.set()
        t = self._prober
        if t is not None and t.is_alive():
            t.join(timeout=2.0)

    def _probe_loop(self) -> None:
        pause = max(0.02, min(1.0, self.probe_every / 4.0
                              if self.probe_every > 0 else 0.05))
        while not self._stop.is_set():
            with self._lock:
                if self._idx == 0:
                    return  # climbed all the way back; prober retires
            try:
                self.tick()
            except Exception:  # pragma: no cover - prober must not die loudly
                pass
            self._stop.wait(pause)
