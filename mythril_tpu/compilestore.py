"""Durable fleet-wide compile-artifact store (the "kill cold start" item).

Every resilience mechanism in this repo ends in the same cold tax: the
warm-shape registry is process-local (serve/scheduler.py documented it
as such since PR 11), and ``_tier_sync`` invalidates warm markers and
respawns the worker by design (PR 13) — so every fresh replica, every
respawned worker, and every tier re-promotion pays full XLA compile
before its first verdict. This module promotes compilation to a durable
fleet artifact with two halves:

1. **Shape-bucket registry** under ``<data-dir>/compile_store/buckets/``:
   one JSON file per ``(tier, shape-class, semantic-config-hash)``
   bucket recording hit counts, last-seen timestamps, and the warm
   chunk step-counts observed for that shape. Writes use the repo's
   one shared durability discipline (``exclusive_write`` first-wins on
   create, ``durable_write`` with ``.1`` rotation on update), so N
   daemons on one data dir are correct; a torn newest file is
   quarantined ``.corrupt`` and the loader falls back to the rotated
   copy. A lost read-merge-update race costs at most one hit-count
   increment, never a bucket.

2. **Shared XLA cache dir** ``<data-dir>/compile_store/xla_cache/``:
   the ``MYTHRIL_WORKER_JAX_CACHE`` contract extended fleet-wide —
   worker children, respawned workers, and sibling replicas all point
   at one persistent compilation cache, so a registry-driven prewarm
   (or even a lazy first compile) after restart is a cache *hit*, not
   a recompile.

**Single-owner GC contract** (mirrors the segstore compactor): any
replica may read and record; only ONE process at a time may run
:meth:`CompileStore.gc` (operators run ``tools/store_admin.py
compile-gc``). GC never unlinks a bucket another writer could be
mid-updating destructively — bucket updates are atomic renames, so the
worst case is a concurrently re-created bucket, which the next
``record`` simply recreates.

The registry stores *shape skeletons only* (ints), never bytecode or
verdicts — prewarm compiles are driven from padded STOP-stub corpora
(the ``ShapeDtypeStruct`` idea from tools/scaling_report.py: content
never changes the jaxpr, only shape does).
"""

from __future__ import annotations

import json
import logging
import os
import hashlib
import threading
import time
from typing import Dict, Iterable, List, Optional, Sequence, Tuple

from .obs import metrics as obs_metrics
from .obs import trace as obs_trace
from .utils.checkpoint import (
    ROTATE_SUFFIX, durable_write, exclusive_write, fsync_dir)

log = logging.getLogger(__name__)

#: registry record schema
BUCKET_SCHEMA = 1
BUCKET_DIR = "buckets"
XLA_CACHE_DIR = "xla_cache"
#: default recency cap: buckets beyond this are evicted oldest-first
DEFAULT_CAP = 256

#: test hook: SIGKILL-equivalent (``os._exit``) at a named point of the
#: registry write protocol, driven by the kill-mid-registry-write chaos
#: cell. Points: pre-write (before any byte lands — old record intact),
#: post-write (record durable, caller's bookkeeping not), torn-write
#: (simulates the non-atomic failure the protocol defends against:
#: rotate the good record to ``.1``, scribble half a payload over the
#: newest, die — the next reader must quarantine + fall back).
_KILL_ENV = "MYTHRIL_COMPILESTORE_KILL"


def _maybe_kill(point: str, path: str, payload: bytes) -> None:
    if os.environ.get(_KILL_ENV) != point:
        return
    if point == "torn-write":
        # emulate the torn-newest-file state: good copy rotated away,
        # garbage half-record in its place, then die mid-"write"
        if os.path.exists(path):
            os.replace(path, path + ROTATE_SUFFIX)
        with open(path, "wb") as fh:
            fh.write(payload[: max(1, len(payload) // 2)])
        fsync_dir(path)
    os._exit(9)


def semantic_config_hash(config: Dict) -> str:
    """16-hex digest of a semantic config dict (the caller already
    stripped operational keys — serve passes its ``config_hash``
    straight through instead). Sorted-JSON so dict order never forks
    the key space."""
    blob = json.dumps(config, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()[:16]


def bucket_name(tier: str, shape: Sequence[int], cfh: str) -> str:
    """``{tier}__{w}x{l}x{ms}x{tx}__{cfh}.json`` — the flat, greppable
    key schema (docs/serving.md has the table). ``shape`` is the
    campaign's ``_shape_key`` tuple: (width, lanes, max_steps, tx)."""
    dims = "x".join(str(int(d)) for d in shape)
    return f"{tier}__{dims}__{cfh}.json"


def _parse_name(fname: str) -> Optional[Tuple[str, Tuple[int, ...], str]]:
    if not fname.endswith(".json"):
        return None
    parts = fname[:-5].split("__")
    if len(parts) != 3:
        return None
    tier, dims, cfh = parts
    try:
        shape = tuple(int(d) for d in dims.split("x"))
    except ValueError:
        return None
    return tier, shape, cfh


class CompileStore:
    """Crash-safe, replica-shared registry of hot compile buckets plus
    the fleet's persistent XLA cache dir. Thread-safe within a process
    (one lock), correct across processes by the write discipline."""

    def __init__(self, root: str, cap: int = DEFAULT_CAP):
        self.root = os.path.abspath(root)
        self.cap = int(cap)
        self._lock = threading.Lock()
        os.makedirs(os.path.join(self.root, BUCKET_DIR), exist_ok=True)
        os.makedirs(self.xla_cache_dir(), exist_ok=True)

    # --- layout --------------------------------------------------------

    def xla_cache_dir(self) -> str:
        return os.path.join(self.root, XLA_CACHE_DIR)

    def _bucket_dir(self) -> str:
        return os.path.join(self.root, BUCKET_DIR)

    def _path(self, tier: str, shape: Sequence[int], cfh: str) -> str:
        return os.path.join(self._bucket_dir(),
                            bucket_name(tier, shape, cfh))

    def install_cache(self) -> str:
        """Point the worker-cache contract at this store: set
        ``MYTHRIL_WORKER_JAX_CACHE`` for child workers IFF the operator
        hasn't already pinned one (tests do — first writer wins), and
        mirror it into an already-imported jax's persistent-cache
        config when that too is unset. Returns the cache dir in force."""
        cache = os.environ.setdefault("MYTHRIL_WORKER_JAX_CACHE",
                                      self.xla_cache_dir())
        import sys
        if "jax" in sys.modules:  # never force the import ourselves
            try:
                import jax
                if jax.config.jax_compilation_cache_dir is None:
                    jax.config.update("jax_compilation_cache_dir", cache)
                    jax.config.update(
                        "jax_persistent_cache_min_compile_time_secs", 1.0)
            except Exception:  # noqa: BLE001 — cache config is best-effort
                pass
        return cache

    # --- events / metrics ---------------------------------------------

    def _event(self, kind: str, **kw) -> None:
        obs_trace.event(kind, **kw)
        obs_metrics.REGISTRY.counter(f"{kind}_total").inc()

    # --- read path -----------------------------------------------------

    def _load_one(self, path: str) -> Optional[Dict]:
        """One file, validated; ``None`` on missing, raises ValueError
        on corrupt (torn JSON or wrong schema shape)."""
        try:
            with open(path, "rb") as fh:
                rec = json.loads(fh.read().decode("utf-8"))
        except FileNotFoundError:
            return None
        except (ValueError, OSError) as e:
            raise ValueError(f"unreadable bucket {path}: {e}") from e
        if (not isinstance(rec, dict)
                or rec.get("schema") != BUCKET_SCHEMA
                or not isinstance(rec.get("shape"), list)
                or not isinstance(rec.get("hits"), int)):
            raise ValueError(f"bucket {path} fails schema validation")
        return rec

    def _load(self, path: str) -> Optional[Dict]:
        """Newest-then-rotated read with ``.corrupt`` quarantine: the
        same fallback ladder as ``load_json_checkpoint_resilient``, per
        bucket. A corrupt newest never shadows the last-known-good."""
        try:
            return self._load_one(path)
        except ValueError as e:
            try:
                os.replace(path, path + ".corrupt")
            except OSError:
                pass
            self._event("compile_store_corrupt",
                        file=os.path.basename(path), detail=str(e)[:200])
            log.warning("compile store bucket %s corrupt (%s); "
                        "falling back to rotated copy", path, e)
        try:
            return self._load_one(path + ROTATE_SUFFIX)
        except ValueError:
            try:
                os.replace(path + ROTATE_SUFFIX,
                           path + ROTATE_SUFFIX + ".corrupt")
            except OSError:
                pass
            return None

    # --- write path ----------------------------------------------------

    def record(self, tier: str, shape: Sequence[int], cfh: str,
               chunks: Iterable[int] = ()) -> Dict:
        """Record one warm observation for a bucket: create first-wins,
        else read-merge-update (hits+1, last_seen=now, chunk union).
        Returns the record as written. Concurrent updaters may each
        lose the other's single hit increment — by design; the bucket
        itself can never be lost or torn."""
        shape = [int(d) for d in shape]
        chunks = sorted({int(c) for c in chunks})
        path = self._path(tier, shape, cfh)
        now = round(time.time(), 3)
        with self._lock:
            rec = {"schema": BUCKET_SCHEMA, "tier": tier, "shape": shape,
                   "cfh": cfh, "hits": 1, "created": now,
                   "last_seen": now, "chunks": chunks}
            payload = json.dumps(rec, sort_keys=True).encode()
            _maybe_kill("pre-write", path, payload)
            if not os.path.exists(path):
                if exclusive_write(path, payload):
                    _maybe_kill("post-write", path, payload)
                    self._enforce_cap()
                    obs_metrics.REGISTRY.counter(
                        "compile_store_records_total",
                        help="bucket observations recorded").inc()
                    return rec
            prev = self._load(path)
            if prev is not None:
                rec["hits"] = prev.get("hits", 0) + 1
                rec["created"] = prev.get("created", now)
                rec["chunks"] = sorted(
                    set(chunks) | {int(c) for c in prev.get("chunks", [])})
            payload = json.dumps(rec, sort_keys=True).encode()
            _maybe_kill("torn-write", path, payload)
            durable_write(path, payload)
            _maybe_kill("post-write", path, payload)
            obs_metrics.REGISTRY.counter(
                "compile_store_records_total",
                help="bucket observations recorded").inc()
            return rec

    def _enforce_cap(self) -> int:
        """Recency cap: evict oldest-last-seen buckets beyond ``cap``.
        Called under the lock from ``record`` (create path only — the
        only path that grows the set)."""
        recs = self._scan()
        excess = len(recs) - self.cap
        if excess <= 0:
            return 0
        recs.sort(key=lambda r: r.get("last_seen", 0.0))
        for rec in recs[:excess]:
            self._unlink_bucket(rec["_path"])
        obs_metrics.REGISTRY.counter(
            "compile_store_evicted_total",
            help="buckets evicted by the recency cap").inc(excess)
        return excess

    @staticmethod
    def _unlink_bucket(path: str) -> None:
        for p in (path, path + ROTATE_SUFFIX):
            try:
                os.unlink(p)
            except OSError:
                pass

    # --- queries -------------------------------------------------------

    def _scan(self) -> List[Dict]:
        out = []
        try:
            names = sorted(os.listdir(self._bucket_dir()))
        except OSError:
            return out
        for fname in names:
            if _parse_name(fname) is None:
                continue
            rec = self._load(os.path.join(self._bucket_dir(), fname))
            if rec is not None:
                rec["_path"] = os.path.join(self._bucket_dir(), fname)
                out.append(rec)
        return out

    def buckets(self, tier: Optional[str] = None,
                cfh: Optional[str] = None) -> List[Dict]:
        """Registry records, hottest first (hits desc, then most
        recent) — the prewarm priority order. Filter by tier and/or
        semantic config hash."""
        recs = [r for r in self._scan()
                if (tier is None or r.get("tier") == tier)
                and (cfh is None or r.get("cfh") == cfh)]
        recs.sort(key=lambda r: (-r.get("hits", 0),
                                 -r.get("last_seen", 0.0)))
        for r in recs:
            r.pop("_path", None)
        return recs

    def warm_chunks(self, tier: str, shape: Sequence[int],
                    cfh: str) -> List[int]:
        """The chunk step-counts previously observed warm for one
        bucket — the seed for a recovered process's warm-shape sets."""
        rec = self._load(self._path(tier, [int(d) for d in shape], cfh))
        if rec is None:
            return []
        return sorted(int(c) for c in rec.get("chunks", []))

    def stats(self) -> Dict:
        """Offline-inspection doc (``store_admin.py compile-stats``)."""
        recs = self._scan()
        tiers: Dict[str, int] = {}
        for r in recs:
            tiers[r.get("tier", "?")] = tiers.get(r.get("tier", "?"), 0) + 1
        try:
            names = os.listdir(self._bucket_dir())
        except OSError:
            names = []
        corrupt = sum(1 for f in names if f.endswith(".corrupt"))
        cache_files = cache_bytes = 0
        for dirpath, _dirs, files in os.walk(self.xla_cache_dir()):
            for f in files:
                cache_files += 1
                try:
                    cache_bytes += os.path.getsize(
                        os.path.join(dirpath, f))
                except OSError:
                    pass
        obs_metrics.REGISTRY.gauge(
            "compile_store_buckets",
            help="registry buckets on disk").set(len(recs))
        return {"buckets": len(recs), "tiers": tiers,
                "hits_total": sum(r.get("hits", 0) for r in recs),
                "chunks_total": sum(len(r.get("chunks", []))
                                    for r in recs),
                "corrupt_quarantined": corrupt,
                "cap": self.cap,
                "xla_cache_files": cache_files,
                "xla_cache_bytes": cache_bytes}

    # --- GC (single-owner) --------------------------------------------

    def gc(self, max_buckets: Optional[int] = None,
           ttl: Optional[float] = None,
           cache_ttl: Optional[float] = None) -> Dict:
        """Offline GC (single-owner contract — see module docstring):
        drop buckets idle past ``ttl`` seconds, enforce ``max_buckets``
        oldest-first, sweep write-tmp leftovers and aged ``.corrupt``
        evidence, and prune XLA cache artifacts untouched for
        ``cache_ttl`` seconds (orphans from evicted buckets)."""
        now = time.time()
        recs = self._scan()
        expired = ([r for r in recs
                    if now - r.get("last_seen", now) > ttl]
                   if ttl is not None else [])
        for rec in expired:
            self._unlink_bucket(rec["_path"])
        live = [r for r in recs if r not in expired]
        over = 0
        cap = max_buckets if max_buckets is not None else self.cap
        if len(live) > cap:
            live.sort(key=lambda r: r.get("last_seen", 0.0))
            over = len(live) - cap
            for rec in live[:over]:
                self._unlink_bucket(rec["_path"])
        swept = 0
        try:
            names = os.listdir(self._bucket_dir())
        except OSError:
            names = []
        for fname in names:
            p = os.path.join(self._bucket_dir(), fname)
            stale_tmp = fname.endswith(".tmp")
            aged_corrupt = (fname.endswith(".corrupt")
                            and ttl is not None
                            and now - _mtime(p, now) > ttl)
            if stale_tmp or aged_corrupt:
                try:
                    os.unlink(p)
                    swept += 1
                except OSError:
                    pass
        pruned = 0
        if cache_ttl is not None:
            for dirpath, _dirs, files in os.walk(self.xla_cache_dir()):
                for f in files:
                    p = os.path.join(dirpath, f)
                    if now - _mtime(p, now) > cache_ttl:
                        try:
                            os.unlink(p)
                            pruned += 1
                        except OSError:
                            pass
        return {"expired": len(expired), "evicted": over,
                "swept": swept, "cache_pruned": pruned,
                "buckets": len(self._scan())}


def _mtime(path: str, default: float) -> float:
    try:
        return os.path.getmtime(path)
    except OSError:
        return default


__all__ = ["BUCKET_SCHEMA", "CompileStore", "DEFAULT_CAP", "bucket_name",
           "semantic_config_hash"]
