"""Native (C) runtime components, built on first use with the system
compiler and loaded via ctypes — no pip/pybind11 in this environment.

Currently: ``tape_eval`` — the 256-bit tape evaluator the witness
search's repair loop runs hundreds of times per solver query (the
reference's analogous hot loop lives inside Z3's C++ core,
``laser/smt/solver`` ⚠unv SURVEY.md §2.2). Everything degrades to the
pure-Python evaluator when the compiler or the load fails
(``MYTHRIL_NO_NATIVE=1`` forces that path).
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading

_HERE = os.path.dirname(os.path.abspath(__file__))
_LOCK = threading.Lock()
_lib = None
_tried = False


def _build_and_load():
    src = os.path.join(_HERE, "tape_eval.c")
    so = os.path.join(_HERE, "_tape_eval.so")
    if (not os.path.exists(so)
            or os.path.getmtime(so) < os.path.getmtime(src)):
        tmp = so + ".tmp.%d" % os.getpid()
        for cc in ("cc", "gcc", "clang"):
            try:
                subprocess.run(
                    [cc, "-O2", "-shared", "-fPIC", src, "-o", tmp],
                    check=True, capture_output=True, timeout=120)
                os.replace(tmp, so)
                break
            except (OSError, subprocess.SubprocessError):
                continue
        else:
            raise RuntimeError("no working C compiler for tape_eval")
    lib = ctypes.CDLL(so)
    lib.tape_eval.restype = ctypes.c_int
    lib.tape_eval.argtypes = [
        ctypes.c_int,
        ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_int32),
        ctypes.POINTER(ctypes.c_int32),
        ctypes.c_char_p,                      # imm: read-only bytes
        ctypes.POINTER(ctypes.c_uint8),       # vals: mutable in/out
    ]
    return lib


def tape_eval_lib():
    """The loaded native library, or None (build failure / opt-out)."""
    global _lib, _tried
    if _tried:
        return _lib
    with _LOCK:
        if _tried:
            return _lib
        if os.environ.get("MYTHRIL_NO_NATIVE") == "1":
            _lib, _tried = None, True
            return None
        try:
            _lib = _build_and_load()
        except Exception:
            _lib = None
        _tried = True
    return _lib
