/* Native evaluator for the symbolic SSA tape.
 *
 * The witness search (mythril_tpu/smt/solver.py) evaluates the whole
 * tape under ~hundreds of candidate assignments per query; the Python
 * big-int evaluator (smt/eval.py evaluate()) is that loop's hot path.
 * This is the same semantics on 4x64-bit limbs: EVM wrap-around
 * arithmetic, signed ops by two's complement, and exact keccak-256 for
 * hash chains. The reference spends the analogous time inside Z3's C++
 * core (laser/smt Solver.check() ~unv, SURVEY.md section 2.2); here the
 * native tier is this evaluator plus the TPU propagation kernels.
 *
 * ABI (ctypes, see mythril_tpu/native/__init__.py):
 *   int tape_eval(int n, const int32_t* op, const int32_t* a,
 *                 const int32_t* b, const uint8_t* imm,  // n*32 BE
 *                 uint8_t* vals)                          // n*32 BE in/out
 * vals rows for FREE nodes are pre-seeded by the caller (leaf values
 * come from the Python Assignment); everything else is computed here.
 * Op codes MUST match symbolic/ops.py SymOp — pinned by the
 * differential tests in tests/test_native_eval.py.
 */

#include <stdint.h>
#include <stdlib.h>
#include <string.h>

/* ---- SymOp (mirror of mythril_tpu/symbolic/ops.py) ---- */
enum {
    OP_NULL = 0, OP_CONST = 1, OP_FREE = 2,
    OP_ADD = 3, OP_SUB = 4, OP_MUL = 5, OP_DIV = 6, OP_SDIV = 7,
    OP_MOD = 8, OP_SMOD = 9, OP_EXP = 10, OP_SIGNEXTEND = 11,
    OP_LT = 12, OP_GT = 13, OP_SLT = 14, OP_SGT = 15, OP_EQ = 16,
    OP_ISZERO = 17, OP_AND = 18, OP_OR = 19, OP_XOR = 20, OP_NOT = 21,
    OP_BYTE = 22, OP_SHL = 23, OP_SHR = 24, OP_SAR = 25,
    OP_KECCAK_SEED = 26, OP_KECCAK_ABS = 27, OP_KECCAK = 28,
};

typedef struct { uint64_t w[4]; } u256; /* w[0] = least significant */

static void u_load(u256 *r, const uint8_t *be) {
    for (int i = 0; i < 4; i++) {
        uint64_t v = 0;
        const uint8_t *p = be + (3 - i) * 8;
        for (int k = 0; k < 8; k++) v = (v << 8) | p[k];
        r->w[i] = v;
    }
}

static void u_store(uint8_t *be, const u256 *a) {
    for (int i = 0; i < 4; i++) {
        uint64_t v = a->w[i];
        uint8_t *p = be + (3 - i) * 8;
        for (int k = 7; k >= 0; k--) { p[k] = (uint8_t)v; v >>= 8; }
    }
}

static void u_zero(u256 *r) { r->w[0] = r->w[1] = r->w[2] = r->w[3] = 0; }
static void u_one(u256 *r) { u_zero(r); r->w[0] = 1; }
static int u_is_zero(const u256 *a) {
    return !(a->w[0] | a->w[1] | a->w[2] | a->w[3]);
}
static int u_cmp(const u256 *a, const u256 *b) {
    for (int i = 3; i >= 0; i--) {
        if (a->w[i] < b->w[i]) return -1;
        if (a->w[i] > b->w[i]) return 1;
    }
    return 0;
}
static int u_is_neg(const u256 *a) { return (int)(a->w[3] >> 63); }

static void u_add(u256 *r, const u256 *a, const u256 *b) {
    unsigned __int128 c = 0;
    for (int i = 0; i < 4; i++) {
        c += (unsigned __int128)a->w[i] + b->w[i];
        r->w[i] = (uint64_t)c;
        c >>= 64;
    }
}

static void u_sub(u256 *r, const u256 *a, const u256 *b) {
    unsigned __int128 borrow = 0;
    for (int i = 0; i < 4; i++) {
        unsigned __int128 d =
            (unsigned __int128)a->w[i] - b->w[i] - (uint64_t)borrow;
        r->w[i] = (uint64_t)d;
        borrow = (d >> 64) ? 1 : 0;
    }
}

static void u_neg(u256 *r, const u256 *a) {
    u256 z; u_zero(&z); u_sub(r, &z, a);
}

static void u_mul(u256 *r, const u256 *a, const u256 *b) {
    uint64_t out[4] = {0, 0, 0, 0};
    for (int i = 0; i < 4; i++) {
        unsigned __int128 carry = 0;
        for (int j = 0; i + j < 4; j++) {
            unsigned __int128 cur =
                (unsigned __int128)a->w[i] * b->w[j] + out[i + j] + carry;
            out[i + j] = (uint64_t)cur;
            carry = cur >> 64;
        }
    }
    memcpy(r->w, out, 32);
}

static void u_shl_k(u256 *r, const u256 *a, unsigned k) {
    u256 out; u_zero(&out);
    if (k >= 256) { *r = out; return; }
    unsigned limb = k / 64, bits = k % 64;
    for (int i = 3; i >= 0; i--) {
        uint64_t v = 0;
        int src = i - (int)limb;
        if (src >= 0) {
            v = a->w[src] << bits;
            if (bits && src - 1 >= 0) v |= a->w[src - 1] >> (64 - bits);
        }
        out.w[i] = v;
    }
    *r = out;
}

static void u_shr_k(u256 *r, const u256 *a, unsigned k) {
    u256 out; u_zero(&out);
    if (k >= 256) { *r = out; return; }
    unsigned limb = k / 64, bits = k % 64;
    for (int i = 0; i < 4; i++) {
        uint64_t v = 0;
        unsigned src = i + limb;
        if (src < 4) {
            v = a->w[src] >> bits;
            if (bits && src + 1 < 4) v |= a->w[src + 1] << (64 - bits);
        }
        out.w[i] = v;
    }
    *r = out;
}

/* binary long division; b must be nonzero */
static void u_divmod(const u256 *a, const u256 *b, u256 *q, u256 *rem) {
    u256 r0, q0;
    u_zero(&r0); u_zero(&q0);
    for (int i = 255; i >= 0; i--) {
        u_shl_k(&r0, &r0, 1);
        r0.w[0] |= (a->w[i / 64] >> (i % 64)) & 1ULL;
        if (u_cmp(&r0, b) >= 0) {
            u_sub(&r0, &r0, b);
            q0.w[i / 64] |= 1ULL << (i % 64);
        }
    }
    *q = q0; *rem = r0;
}

/* shift amount saturated to 256 when any high limb is set */
static unsigned shift_amount(const u256 *a) {
    if (a->w[1] | a->w[2] | a->w[3] || a->w[0] >= 256) return 256;
    return (unsigned)a->w[0];
}

/* ---- keccak-256 (keccak-f[1600], rate 136, pad 0x01..0x80) ---- */

static const uint64_t KRC[24] = {
    0x0000000000000001ULL, 0x0000000000008082ULL, 0x800000000000808aULL,
    0x8000000080008000ULL, 0x000000000000808bULL, 0x0000000080000001ULL,
    0x8000000080008081ULL, 0x8000000000008009ULL, 0x000000000000008aULL,
    0x0000000000000088ULL, 0x0000000080008009ULL, 0x000000008000000aULL,
    0x000000008000808bULL, 0x800000000000008bULL, 0x8000000000008089ULL,
    0x8000000000008003ULL, 0x8000000000008002ULL, 0x8000000000000080ULL,
    0x000000000000800aULL, 0x800000008000000aULL, 0x8000000080008081ULL,
    0x8000000000008080ULL, 0x0000000080000001ULL, 0x8000000080008008ULL,
};

static inline uint64_t rotl64(uint64_t x, int s) {
    return (x << s) | (x >> (64 - s));
}

static void keccakf(uint64_t st[25]) {
    static const int rotc[24] = {1, 3, 6, 10, 15, 21, 28, 36, 45, 55, 2, 14,
                                 27, 41, 56, 8, 25, 43, 62, 18, 39, 61, 20, 44};
    static const int piln[24] = {10, 7, 11, 17, 18, 3, 5, 16, 8, 21, 24, 4,
                                 15, 23, 19, 13, 12, 2, 20, 14, 22, 9, 6, 1};
    uint64_t bc[5], t;
    for (int round = 0; round < 24; round++) {
        for (int i = 0; i < 5; i++)
            bc[i] = st[i] ^ st[i + 5] ^ st[i + 10] ^ st[i + 15] ^ st[i + 20];
        for (int i = 0; i < 5; i++) {
            t = bc[(i + 4) % 5] ^ rotl64(bc[(i + 1) % 5], 1);
            for (int j = 0; j < 25; j += 5) st[j + i] ^= t;
        }
        t = st[1];
        for (int i = 0; i < 24; i++) {
            int j = piln[i];
            bc[0] = st[j];
            st[j] = rotl64(t, rotc[i]);
            t = bc[0];
        }
        for (int j = 0; j < 25; j += 5) {
            for (int i = 0; i < 5; i++) bc[i] = st[j + i];
            for (int i = 0; i < 5; i++)
                st[j + i] ^= (~bc[(i + 1) % 5]) & bc[(i + 2) % 5];
        }
        st[0] ^= KRC[round];
    }
}

static void keccak256(const uint8_t *data, size_t len, uint8_t out[32]) {
    uint64_t st[25];
    uint8_t block[136];
    memset(st, 0, sizeof(st));
    while (len >= 136) {
        for (int i = 0; i < 17; i++) {
            uint64_t v = 0;
            for (int k = 7; k >= 0; k--) v = (v << 8) | data[i * 8 + k];
            st[i] ^= v;
        }
        keccakf(st);
        data += 136;
        len -= 136;
    }
    memset(block, 0, sizeof(block));
    memcpy(block, data, len);
    block[len] = 0x01;
    block[135] |= 0x80;
    for (int i = 0; i < 17; i++) {
        uint64_t v = 0;
        for (int k = 7; k >= 0; k--) v = (v << 8) | block[i * 8 + k];
        st[i] ^= v;
    }
    keccakf(st);
    for (int i = 0; i < 4; i++) {
        uint64_t v = st[i];
        for (int k = 0; k < 8; k++) { out[i * 8 + k] = (uint8_t)v; v >>= 8; }
    }
}

/* ---- keccak chain bookkeeping ---- */

typedef struct {
    uint8_t *buf;
    uint32_t len;     /* bytes accumulated */
    uint32_t declen;  /* declared hash length (SEED imm low 32) */
    uint32_t start;   /* start offset in the first word (SEED imm high 32) */
} chain_t;

int tape_eval(int n, const int32_t *op, const int32_t *a, const int32_t *b,
              const uint8_t *imm, uint8_t *vals) {
    chain_t *chains = (chain_t *)calloc((size_t)n, sizeof(chain_t));
    if (!chains) return -1;
    int rc = 0;

    for (int i = 1; i < n; i++) {
        int o = op[i];
        int ia = a[i], ib = b[i];
        u256 va, vb, r;

        switch (o) {
        case OP_NULL:
        case OP_FREE: /* pre-seeded by the caller; a/b are (kind, index) */
            continue;
        case OP_CONST:
            memcpy(vals + (size_t)i * 32, imm + (size_t)i * 32, 32);
            continue;
        case OP_KECCAK_SEED: {
            u256 vi; u_load(&vi, imm + (size_t)i * 32);
            chains[i].buf = NULL;
            chains[i].len = 0;
            chains[i].declen = (uint32_t)(vi.w[0] & 0xFFFFFFFFULL);
            chains[i].start = (uint32_t)(vi.w[0] >> 32);
            continue;
        }
        case OP_KECCAK_ABS: {
            if (ia < 0 || ia >= n || ib < 0 || ib >= n) { rc = -2; goto done; }
            chain_t *p = &chains[ia];
            uint32_t nl = p->len + 32;
            uint8_t *nb = (uint8_t *)malloc(nl);
            if (!nb) { rc = -1; goto done; }
            if (p->len) memcpy(nb, p->buf, p->len);
            if (ib)
                memcpy(nb + p->len, vals + (size_t)ib * 32, 32);
            else
                memcpy(nb + p->len, imm + (size_t)i * 32, 32);
            chains[i].buf = nb;
            chains[i].len = nl;
            chains[i].declen = p->declen;
            chains[i].start = p->start;
            continue;
        }
        case OP_KECCAK: {
            if (ia < 0 || ia >= n) { rc = -2; goto done; }
            chain_t *c = &chains[ia];
            uint32_t s = c->start, l = c->declen;
            if (s > c->len) s = c->len;
            if (s + l > c->len) l = c->len - s;
            keccak256(c->buf ? c->buf + s : (const uint8_t *)"", l,
                      vals + (size_t)i * 32);
            continue;
        }
        default:
            break;
        }

        /* value ops: a/b are node ids into vals */
        if (ia < 0 || ia >= n || ib < 0 || ib >= n) { rc = -2; break; }
        u_load(&va, vals + (size_t)ia * 32);
        u_load(&vb, vals + (size_t)ib * 32);
        u_zero(&r);

        switch (o) {
        case OP_ADD: u_add(&r, &va, &vb); break;
        case OP_SUB: u_sub(&r, &va, &vb); break;
        case OP_MUL: u_mul(&r, &va, &vb); break;
        case OP_DIV:
            if (!u_is_zero(&vb)) { u256 rem; u_divmod(&va, &vb, &r, &rem); }
            break;
        case OP_SDIV:
            if (!u_is_zero(&vb)) {
                u256 aa = va, ab = vb, rem;
                int na = u_is_neg(&va), nb_ = u_is_neg(&vb);
                if (na) u_neg(&aa, &va);
                if (nb_) u_neg(&ab, &vb);
                u_divmod(&aa, &ab, &r, &rem);
                if (na != nb_) u_neg(&r, &r);
            }
            break;
        case OP_MOD:
            if (!u_is_zero(&vb)) { u256 q; u_divmod(&va, &vb, &q, &r); }
            break;
        case OP_SMOD:
            if (!u_is_zero(&vb)) {
                u256 aa = va, ab = vb, q;
                int na = u_is_neg(&va);
                if (na) u_neg(&aa, &va);
                if (u_is_neg(&vb)) u_neg(&ab, &vb);
                u_divmod(&aa, &ab, &q, &r);
                if (na) u_neg(&r, &r);
            }
            break;
        case OP_EXP: {
            u256 acc, base = va;
            u_one(&acc);
            for (int k = 0; k < 256; k++) {
                if ((vb.w[k / 64] >> (k % 64)) & 1ULL) u_mul(&acc, &acc, &base);
                u_mul(&base, &base, &base);
            }
            r = acc;
            break;
        }
        case OP_SIGNEXTEND:
            if (!(va.w[1] | va.w[2] | va.w[3]) && va.w[0] < 31) {
                unsigned bit = 8u * (unsigned)va.w[0] + 7u;
                r = vb;
                if ((vb.w[bit / 64] >> (bit % 64)) & 1ULL) {
                    /* set all bits above `bit` */
                    for (unsigned k = bit + 1; k < 256; k++)
                        r.w[k / 64] |= 1ULL << (k % 64);
                } else {
                    for (unsigned k = bit + 1; k < 256; k++)
                        r.w[k / 64] &= ~(1ULL << (k % 64));
                }
            } else {
                r = vb;
            }
            break;
        case OP_LT: if (u_cmp(&va, &vb) < 0) r.w[0] = 1; break;
        case OP_GT: if (u_cmp(&va, &vb) > 0) r.w[0] = 1; break;
        case OP_SLT: {
            int na = u_is_neg(&va), nb_ = u_is_neg(&vb);
            int lt = (na != nb_) ? na : (u_cmp(&va, &vb) < 0);
            if (lt) r.w[0] = 1;
            break;
        }
        case OP_SGT: {
            int na = u_is_neg(&va), nb_ = u_is_neg(&vb);
            int gt = (na != nb_) ? nb_ : (u_cmp(&va, &vb) > 0);
            if (gt) r.w[0] = 1;
            break;
        }
        case OP_EQ: if (u_cmp(&va, &vb) == 0) r.w[0] = 1; break;
        case OP_ISZERO: if (u_is_zero(&va)) r.w[0] = 1; break;
        case OP_AND:
            for (int k = 0; k < 4; k++) r.w[k] = va.w[k] & vb.w[k];
            break;
        case OP_OR:
            for (int k = 0; k < 4; k++) r.w[k] = va.w[k] | vb.w[k];
            break;
        case OP_XOR:
            for (int k = 0; k < 4; k++) r.w[k] = va.w[k] ^ vb.w[k];
            break;
        case OP_NOT:
            for (int k = 0; k < 4; k++) r.w[k] = ~va.w[k];
            break;
        case OP_BYTE:
            if (!(va.w[1] | va.w[2] | va.w[3]) && va.w[0] < 32) {
                unsigned sh = 8u * (31u - (unsigned)va.w[0]);
                u256 t; u_shr_k(&t, &vb, sh);
                r.w[0] = t.w[0] & 0xFFULL;
            }
            break;
        case OP_SHL: u_shl_k(&r, &vb, shift_amount(&va)); break;
        case OP_SHR: u_shr_k(&r, &vb, shift_amount(&va)); break;
        case OP_SAR: {
            unsigned k = shift_amount(&va);
            int neg = u_is_neg(&vb);
            if (k >= 256) {
                if (neg) { r.w[0] = r.w[1] = r.w[2] = r.w[3] = ~0ULL; }
            } else {
                u_shr_k(&r, &vb, k);
                if (neg && k) { /* fill the top k bits with sign */
                    for (unsigned bit = 256 - k; bit < 256; bit++)
                        r.w[bit / 64] |= 1ULL << (bit % 64);
                }
            }
            break;
        }
        default:
            /* unknown op: FAIL so evaluate() falls back to the Python
             * path — a SymOp added there but not here must not yield
             * silently-zero native values */
            rc = -3;
            goto done;
        }
        u_store(vals + (size_t)i * 32, &r);
    }

done:
    for (int i = 0; i < n; i++)
        if (chains[i].buf) free(chains[i].buf);
    free(chains);
    return rc;
}
