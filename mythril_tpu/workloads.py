"""Canonical benchmark/dry-run workloads shared by ``bench.py`` and
``__graft_entry__.py`` (single source of truth for the flagship fixture).

The reference's equivalent fixture is a solc-compiled OpenZeppelin ERC-20
(BASELINE config 1); with no solc in the image the stand-in is the
hand-assembled token contract in :mod:`mythril_tpu.disassembler.asm`.
"""

from __future__ import annotations

import numpy as np

from .config import LimitsConfig
from .core import Corpus, make_env, make_frontier
from .disassembler import ContractImage
from .disassembler.asm import abi_call, erc20_like

TRANSFER_SELECTOR = 0xA9059CBB
TRANSFER_CALLDATA_LEN = 68  # 4-byte selector + two 32-byte args
BENCH_CALLER = 0xDEADBEEF


def erc20_transfer_workload(P: int, limits: LimitsConfig):
    """(code, frontier, env, corpus): P lanes each running transfer(to, 0)."""
    code = erc20_like()
    img = ContractImage.from_bytecode(code, limits.max_code)
    corpus = Corpus.from_images([img])
    cd = np.zeros((P, limits.calldata_bytes), dtype=np.uint8)
    for i in range(P):
        blob = abi_call(TRANSFER_SELECTOR, 0x1000 + i, 0)
        cd[i, : len(blob)] = np.frombuffer(blob, dtype=np.uint8)
    f = make_frontier(
        P, limits, calldata=cd,
        calldata_len=np.full(P, TRANSFER_CALLDATA_LEN, dtype=np.int32),
        caller=BENCH_CALLER,
    )
    env = make_env(P)
    return code, f, env, corpus
