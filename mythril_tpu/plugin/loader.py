"""Plugin loader (reference: ``laser/plugin/loader.py`` singleton ⚠unv).

Explicit instance instead of a hidden singleton: build one, ``load``
builders/plugins, pass ``plugins`` to ``SymExecWrapper``.
"""

from __future__ import annotations

import logging
from typing import List, Union

from .interface import LaserPlugin, PluginBuilder

log = logging.getLogger(__name__)


class LaserPluginLoader:
    def __init__(self):
        self._plugins: List[LaserPlugin] = []

    def load(self, item: Union[LaserPlugin, PluginBuilder]) -> "LaserPluginLoader":
        plugin = item.build() if isinstance(item, PluginBuilder) else item
        self._plugins.append(plugin)
        return self

    @property
    def plugins(self) -> List[LaserPlugin]:
        return list(self._plugins)

    def fire(self, hook: str, *args) -> None:
        for p in self._plugins:
            try:
                getattr(p, hook)(*args)
            except Exception:  # noqa: BLE001 — degrade, don't kill the run
                log.exception("plugin %s failed in %s", p.name, hook)
