"""Outer plugin discovery: load third-party extensions into the runtime.

Reference: ``mythril/plugin/{loader,discovery}.py`` (⚠unv, SURVEY §2 row
"Mythril plugin system (outer)") — the reference discovers installed
plugin packages through setuptools entry points and installs them into
its module/laser registries. Same surface here, two channels:

- **installed packages**: ``importlib.metadata`` entry points in group
  ``mythril_tpu.plugins`` (each entry point resolves to a plugin object,
  see below);
- **plugin directories** (``--plugin-dir``): every ``*.py`` file in the
  directory is imported — no pip install required, which matters in
  hermetic images.

A resolved object may be any of:

- a :class:`DetectionModule` subclass — registered into the global
  :func:`register_module` registry (shows up in ``list-detectors`` and
  ``fire_lasers`` immediately);
- a :class:`LaserPlugin` / :class:`PluginBuilder` subclass or instance —
  collected for ``SymExecWrapper(plugins=...)``;
- a module (entry point to a module, or a plugin-dir file) — scanned for
  a ``MYTHRIL_PLUGINS`` list of the above; without one, every top-level
  class DEFINED IN that module is classified.

Failures are isolated per plugin (one broken extension cannot take down
an analysis run — same degrade policy as detection modules).
"""

from __future__ import annotations

import importlib.util
import logging
import os
import types
from dataclasses import dataclass, field
from typing import Dict, List, Optional

from ..analysis.module.base import DetectionModule
from ..analysis.module.loader import register_module
from .interface import LaserPlugin, PluginBuilder

log = logging.getLogger(__name__)

ENTRYPOINT_GROUP = "mythril_tpu.plugins"


@dataclass
class DiscoveredPlugins:
    """What discovery found and installed."""

    laser_plugins: List[LaserPlugin] = field(default_factory=list)
    detection_modules: List[str] = field(default_factory=list)
    errors: Dict[str, str] = field(default_factory=dict)

    def merge(self, other: "DiscoveredPlugins") -> "DiscoveredPlugins":
        self.laser_plugins += other.laser_plugins
        self.detection_modules += other.detection_modules
        self.errors.update(other.errors)
        return self


def _classify(obj, name: str, out: DiscoveredPlugins) -> bool:
    """Install one resolved object into the right registry."""
    if isinstance(obj, type):
        if issubclass(obj, DetectionModule):
            register_module(obj)
            out.detection_modules.append(obj.__name__)
            return True
        if issubclass(obj, PluginBuilder):
            out.laser_plugins.append(obj().build())
            return True
        if issubclass(obj, LaserPlugin):
            out.laser_plugins.append(obj())
            return True
        return False
    if isinstance(obj, PluginBuilder):
        out.laser_plugins.append(obj.build())
        return True
    if isinstance(obj, LaserPlugin):
        out.laser_plugins.append(obj)
        return True
    if isinstance(obj, types.ModuleType):
        _scan_module(obj, name, out)
        return True
    return False


def _scan_module(mod: types.ModuleType, name: str,
                 out: DiscoveredPlugins) -> None:
    declared = getattr(mod, "MYTHRIL_PLUGINS", None)
    if declared is not None:
        for i, obj in enumerate(declared):
            if not _classify(obj, f"{name}[{i}]", out):
                out.errors[f"{name}[{i}]"] = (
                    "not a DetectionModule/LaserPlugin/PluginBuilder: %r"
                    % (obj,))
        return
    # no manifest: classify classes defined in (not imported into) the file
    for attr in vars(mod).values():
        if isinstance(attr, type) and attr.__module__ == mod.__name__ \
                and attr not in (DetectionModule, LaserPlugin, PluginBuilder):
            _classify(attr, name, out)


def discover_entrypoints(group: str = ENTRYPOINT_GROUP) -> DiscoveredPlugins:
    """Load every installed entry point in ``group``."""
    from importlib import metadata

    out = DiscoveredPlugins()
    try:
        eps = metadata.entry_points(group=group)
    except Exception as e:  # noqa: BLE001 — metadata backends vary
        out.errors[group] = f"entry-point scan failed: {e!r}"
        return out
    for ep in eps:
        try:
            obj = ep.load()
            if not _classify(obj, ep.name, out):
                out.errors[ep.name] = f"unsupported plugin object: {obj!r}"
        except Exception as e:  # noqa: BLE001 — isolate per plugin
            log.exception("plugin entry point %s failed to load", ep.name)
            out.errors[ep.name] = repr(e)
    return out


def load_plugin_dir(path: str) -> DiscoveredPlugins:
    """Import every ``*.py`` file under ``path`` (non-recursive) and
    install what it defines/declares."""
    out = DiscoveredPlugins()
    if not os.path.isdir(path):
        out.errors[path] = "not a directory"
        return out
    for fn in sorted(os.listdir(path)):
        if not fn.endswith(".py") or fn.startswith("_"):
            continue
        name = "mythril_tpu_plugin_" + fn[:-3]
        try:
            spec = importlib.util.spec_from_file_location(
                name, os.path.join(path, fn))
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
            _scan_module(mod, fn, out)
        except Exception as e:  # noqa: BLE001 — isolate per file
            log.exception("plugin file %s failed to load", fn)
            out.errors[fn] = repr(e)
    return out


def discover(plugin_dir: Optional[str] = None,
             entrypoints: bool = True) -> DiscoveredPlugins:
    """Both channels; entry points first (installed packages are the
    stable base, directory plugins can shadow-extend per run)."""
    out = DiscoveredPlugins()
    if entrypoints:
        out.merge(discover_entrypoints())
    if plugin_dir:
        out.merge(load_plugin_dir(plugin_dir))
    return out
