"""Laser plugin framework (reference: ``mythril/laser/plugin/`` ⚠unv).

The reference instruments the per-opcode exec loop with Python hooks —
impossible frontier-first without serializing the superstep. The hook
surface here is the HOST boundary instead: transaction starts/ends,
chunk boundaries (when a deadline/checkpoint chunks the run), and run
end. That is where the reference's shipped plugins actually live too:
coverage/benchmark read state at boundaries, and the pruners
(mutation/dependency/bounded-loops) are lane-kill policies already fused
into the engine (``between_txs`` / ``_note_backjump``).
"""

from .discovery import (DiscoveredPlugins, discover, discover_entrypoints,
                        load_plugin_dir)
from .interface import LaserPlugin, PluginBuilder
from .loader import LaserPluginLoader
from .plugins import BenchmarkPlugin, CoveragePlugin

__all__ = ["LaserPlugin", "PluginBuilder", "LaserPluginLoader",
           "BenchmarkPlugin", "CoveragePlugin", "DiscoveredPlugins",
           "discover", "discover_entrypoints", "load_plugin_dir"]
