"""Shipped plugins (reference: ``laser/plugin/plugins/`` ⚠unv).

The reference's pruners (mutation/dependency) and loop bound are engine
lane-kill policies here (``between_txs`` / ``_note_backjump``) — fused,
not hook-based; the call-depth limiter is the frame array's static depth
cap. What remains hook-shaped: benchmark + coverage.
"""

from __future__ import annotations

import time
from typing import Dict, List

import numpy as np

from .interface import LaserPlugin


class BenchmarkPlugin(LaserPlugin):
    """states/sec over the run (reference: ``plugins/benchmark.py`` ⚠unv):
    per-transaction wall time + executed lane-steps from the frontier's
    ``n_steps`` counters."""

    name = "benchmark"

    def __init__(self):
        self.tx_records: List[Dict] = []
        self._t0 = None
        self._steps0 = 0

    def initialize(self, wrapper) -> None:
        self.tx_records.clear()

    def on_tx_start(self, tx_index: int, sf) -> None:
        self._t0 = time.perf_counter()
        self._steps0 = int(np.asarray(sf.base.n_steps).sum())

    def on_tx_end(self, ctx) -> None:
        dt = time.perf_counter() - (self._t0 or time.perf_counter())
        steps = int(np.asarray(ctx.sf.base.n_steps).sum()) - self._steps0
        self.tx_records.append({
            "wall_sec": round(dt, 4),
            "lane_steps": steps,
            "lane_steps_per_sec": round(steps / dt, 1) if dt > 0 else 0.0,
            "live_paths": int((np.asarray(ctx.sf.base.active)
                               & ~np.asarray(ctx.sf.base.error)).sum()),
        })

    def summary(self) -> Dict:
        total_steps = sum(r["lane_steps"] for r in self.tx_records)
        total_time = sum(r["wall_sec"] for r in self.tx_records)
        return {
            "transactions": self.tx_records,
            "total_lane_steps": total_steps,
            "total_wall_sec": round(total_time, 4),
            "lane_steps_per_sec": round(total_steps / total_time, 1)
            if total_time > 0 else 0.0,
        }


class CoveragePlugin(LaserPlugin):
    """Final instruction-coverage percentages (reference:
    ``plugins/coverage/`` ⚠unv) — reads the wrapper's visited bitmap."""

    name = "coverage"

    def __init__(self):
        self.coverage: Dict[str, float] = {}

    def on_run_end(self, wrapper) -> None:
        self.coverage = wrapper.instruction_coverage()
