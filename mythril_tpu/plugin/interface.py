"""Plugin hook surface (reference: ``laser/plugin/interface.py`` ⚠unv)."""

from __future__ import annotations


class LaserPlugin:
    """Subclass and override any subset of the hooks. Exceptions are
    caught by the wrapper (one plugin can't kill the run — same degrade
    policy as detection modules)."""

    name = "plugin"

    def initialize(self, wrapper) -> None:
        """Called once before the first transaction."""

    def on_tx_start(self, tx_index: int, sf) -> None:
        """Before a transaction's exploration starts."""

    def on_chunk(self, sf, steps_done: int) -> None:
        """After each exploration chunk (only when the run is chunked)."""

    def on_tx_end(self, ctx) -> None:
        """After a transaction's AnalysisContext snapshot is taken."""

    def on_run_end(self, wrapper) -> None:
        """After the last transaction."""


class PluginBuilder:
    """Deferred construction (reference: ``PluginBuilder.build()`` ⚠unv)."""

    name = "builder"

    def build(self) -> LaserPlugin:
        raise NotImplementedError
