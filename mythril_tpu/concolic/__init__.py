"""Concolic execution: concrete-seed trace + branch flipping.

Reference: ``mythril/concolic/{concolic,concrete_data,find_trace}.py``
(⚠unv, SURVEY.md §2 row "Concolic engine", BASELINE config 5): replay a
concrete transaction, then negate chosen branch conditions and solve for
inputs that drive the other side — the symbolic half of a hybrid fuzzer.

Frontier-first shape: the SYMBOLIC engine already explores all branches
at once, so "find the concrete trace" is a host-side selection — evaluate
each surviving lane's path condition under the seed input and pick the
lane the seed satisfies. Flipping branch k of that lane = solving its
constraint prefix with constraint k negated. One ``sym_run`` serves every
flip (no re-execution per branch, unlike the reference's replay loop).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import List, Optional, Sequence

import numpy as np

from ..config import DEFAULT_LIMITS, LimitsConfig
from ..core import Corpus, make_env
from ..disassembler import ContractImage
from ..smt.eval import Assignment, evaluate
from ..smt.solver import solve_tape
from ..smt.tape import HostTape, TapeHostCache, extract_tape
from ..symbolic import SymSpec, make_sym_frontier, sym_run


@dataclass
class FlippedBranch:
    pc: int                 # JUMPI whose condition was negated
    constraint_index: int   # index in the trace lane's path condition
    calldata: bytes         # new input driving the other side
    callvalue: int
    caller: int


def _seed_assignment(calldata: bytes, callvalue: int, caller: int) -> Assignment:
    asn = Assignment()
    t = asn.tx(0)
    t.calldata = bytearray(calldata)
    t.calldatasize = len(calldata)
    t.callvalue = callvalue
    t.caller = caller
    return asn


def _satisfied(tape: HostTape, asn: Assignment) -> bool:
    vals = evaluate(tape, asn)
    return all(bool(vals[n]) == sign for n, sign in tape.constraints)


def find_trace_lane(sf, seed: Assignment,
                    cache: Optional[TapeHostCache] = None) -> Optional[int]:
    """Lane whose path condition the seed input satisfies (the concrete
    trace the reference's ``concrete_execution`` would record ⚠unv)."""
    cache = cache or TapeHostCache(sf)
    act = np.asarray(sf.base.active)
    err = np.asarray(sf.base.error)
    for lane in np.where(act & ~err)[0]:
        if _satisfied(extract_tape(sf, int(lane), cache=cache), seed):
            return int(lane)
    return None


def concolic_execution(
    code: bytes,
    seed_calldata: bytes,
    jump_addresses: Optional[Sequence[int]] = None,
    callvalue: int = 0,
    caller: Optional[int] = None,
    limits: LimitsConfig = DEFAULT_LIMITS,
    n_lanes: int = 64,
    max_steps: int = 512,
    solver_iters: int = 400,
) -> List[FlippedBranch]:
    """Flip branches of the seed input's trace.

    ``jump_addresses`` restricts flipping to those JUMPI pcs (the
    reference's ``--jump-addresses``); None flips every branch on the
    trace. Returns one :class:`FlippedBranch` per solvable flip.
    """
    from ..core.frontier import ATTACKER_ADDRESS

    caller = ATTACKER_ADDRESS if caller is None else caller
    img = ContractImage.from_bytecode(code, limits.max_code)
    corpus = Corpus.from_images([img])
    active = np.zeros(n_lanes, dtype=bool)
    active[0] = True
    sf = make_sym_frontier(n_lanes, limits, active=active)
    env = make_env(n_lanes)
    sf = sym_run(sf, env, corpus, SymSpec(), limits, max_steps=max_steps)

    seed = _seed_assignment(seed_calldata, callvalue, caller)
    cache = TapeHostCache(sf)
    lane = find_trace_lane(sf, seed, cache=cache)
    if lane is None:
        return []  # seed diverged (e.g. exploration capped before halt)

    tape = extract_tape(sf, lane, cache=cache)
    con_pc = np.asarray(sf.con_pc)[lane]
    out: List[FlippedBranch] = []
    for j, (node, sign) in enumerate(tape.constraints):
        pc = int(con_pc[j]) if j < len(con_pc) else -1
        if jump_addresses is not None and pc not in jump_addresses:
            continue
        flipped = HostTape(
            nodes=tape.nodes,
            constraints=list(tape.constraints[:j]) + [(node, not sign)],
        )
        asn = solve_tape(flipped, max_iters=solver_iters)
        if asn is None:
            continue
        t = asn.tx(0)
        size = t.calldatasize if t.calldatasize is not None else len(t.calldata)
        size = max(0, min(size, len(t.calldata)))
        out.append(FlippedBranch(
            pc=pc, constraint_index=j,
            calldata=bytes(t.calldata[:size]),
            callvalue=t.callvalue, caller=t.caller,
        ))
    return out


def load_concrete_data(path: str):
    """Parse a reference-shaped concolic trace file (``myth concolic
    input.json``; ``mythril/concolic/concrete_data.py`` ⚠unv): a JSON
    document with ``initialState.accounts`` (code/storage/balance per
    address) and ``steps`` (one recorded transaction each: address,
    input, value, origin/caller).

    Returns ``(code, calldata, callvalue, caller)`` for the LAST step —
    the transaction whose branches get flipped (the reference replays
    the whole sequence; the frontier engine's multi-tx exploration
    subsumes the earlier steps' state effects only when they mutate the
    target's storage, a documented divergence: single-step traces are
    exact, multi-step traces flip the final call against fresh state).
    """
    import json

    def _int(v, default=0):
        if v is None:
            return default
        if isinstance(v, int):
            return v
        return int(str(v), 16 if str(v).startswith("0x") else 10)

    def _bytes(v):
        return bytes.fromhex(str(v or "0x").removeprefix("0x"))

    with open(path) as fh:
        doc = json.load(fh)
    steps = doc.get("steps") or []
    if not steps:
        raise ValueError(f"{path}: trace has no steps")
    step = steps[-1]
    target = str(step.get("address", "")).lower()
    accounts = {k.lower(): v
                for k, v in (doc.get("initialState", {})
                             .get("accounts", {})).items()}
    acct = accounts.get(target)
    if acct is None or not acct.get("code"):
        raise ValueError(
            f"{path}: no account code for step target {target!r}")
    return (
        _bytes(acct["code"]),
        _bytes(step.get("input")),
        _int(step.get("value")),
        _int(step.get("caller") or step.get("origin"), default=0) or None,
    )
