"""Batched feasibility propagation: unsigned-interval + known-bits
abstract interpretation over the per-lane SSA tapes.

This is the on-device replacement for the cheap majority of the
reference's ``Solver.check()`` calls (``mythril/laser/smt/solver`` ⚠unv,
SURVEY.md §2.2): one forward pass assigns every tape node an unsigned
interval [lo, hi] (u256 as 8xu32 limbs) AND a known-bits pair
(mask, value) — bit positions proven constant. A path constraint
``(node, sign)`` is contradicted when either domain proves the node
can't be nonzero (sign=true) or can't be zero (sign=false). Lanes with
any contradicted constraint are provably infeasible and get killed.

The two domains are complementary: intervals decide magnitude reasoning
(LT/GT bounds, dispatcher ranges); known-bits decide mask/alignment
reasoning intervals cannot — e.g. ``(x | 1) == 2`` is unsat because bit 0
of the LHS is known 1 (VERDICT r2 ask #7).

Soundness direction: both domains only ever over-approximate, so a kill
is always correct; undecided lanes stay alive (the reference keeps unsat
paths alive until a solver call too). The expensive exact residue goes to
the host model search only when a detection module needs a witness.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp
from jax import lax

from ..ops import u256
from .ops import SymOp, FreeKind
from .state import SymFrontier

I32 = jnp.int32
U32 = jnp.uint32

_MAX = jnp.full(8, 0xFFFFFFFF, dtype=U32)


def _full_like(x, top: bool):
    tgt = _MAX if top else jnp.zeros(8, U32)
    return jnp.broadcast_to(tgt, x.shape)


def _bound_2exp(shape, bits: int):
    """Inclusive upper bound 2^bits - 1 as limbs."""
    out = jnp.zeros(shape[:-1] + (8,), dtype=U32)
    full, rem = bits // 32, bits % 32
    for limb in range(8):
        if limb < full:
            out = out.at[..., limb].set(0xFFFFFFFF)
        elif limb == full and rem:
            out = out.at[..., limb].set((1 << rem) - 1)
    return out


def propagate_feasibility(sf: SymFrontier):
    """INCREMENTAL forward pass over every lane's tape.

    The tape is SSA append-only, so a node's domains never change once
    computed: the pass resumes from ``prop_len`` (the persistent
    ``iv_lo``/``iv_hi``/``kb_m``/``kb_v`` arrays hold earlier nodes) and
    walks only to the current ``tape_len`` — typically a handful of new
    nodes per sweep instead of the full static tape capacity, which
    measured as ~96% of symbolic runtime before this change.

    Returns ``(sf, infeasible)``: the frontier with updated domain arrays
    + ``prop_len``, and the per-lane infeasibility verdict (intervals AND
    known-bits combined)."""
    P, T = sf.tape_op.shape
    lo, hi = sf.iv_lo, sf.iv_hi   # node 0 == concrete zero: [0, 0]
    # known-bits: bit set in km -> that bit of the node equals the same
    # bit of kv. Node 0 is concrete zero: all bits known zero.
    km, kv = sf.kb_m, sf.kb_v

    def gather(arr, ids):
        return jnp.take_along_axis(arr, jnp.clip(ids, 0, T - 1)[:, None, None].astype(I32).repeat(8, 2), axis=1)[:, 0]

    def body(idx, carry):
        # idx is PER-LANE (i32[P]): lane p processes its own node
        # prop_len[p] + j this iteration — the loop trip count is the max
        # NEW-node count, not the global tape span (SSA order guarantees
        # operands were processed in an earlier sweep or iteration)
        lo, hi, km, kv = carry
        ci = jnp.clip(idx, 0, T - 1)[:, None]
        op = jnp.take_along_axis(sf.tape_op, ci, axis=1)[:, 0]
        a_id = jnp.take_along_axis(sf.tape_a, ci, axis=1)[:, 0]
        b_id = jnp.take_along_axis(sf.tape_b, ci, axis=1)[:, 0]
        imm = jnp.take_along_axis(sf.tape_imm, ci[:, :, None].repeat(8, 2),
                                  axis=1)[:, 0]
        la, ha = gather(lo, a_id), gather(hi, a_id)
        lb, hb = gather(lo, b_id), gather(hi, b_id)
        ka, va = gather(km, a_id), gather(kv, a_id)
        kb, vb = gather(km, b_id), gather(kv, b_id)

        top_lo = jnp.zeros_like(la)
        top_hi = _full_like(ha, True)

        # --- leaves ---
        r_lo, r_hi = top_lo, top_hi  # default TOP
        is_const = op == int(SymOp.CONST)
        r_lo = jnp.where(is_const[:, None], imm, r_lo)
        r_hi = jnp.where(is_const[:, None], imm, r_hi)
        is_free = op == int(SymOp.FREE)
        kind = a_id  # FREE stores kind in a
        addr_hi = _bound_2exp(ha.shape, 160)
        small_hi = _bound_2exp(ha.shape, 64)
        free_hi = top_hi
        free_hi = jnp.where(
            ((kind == int(FreeKind.CALLER)) | (kind == int(FreeKind.ORIGIN)))[:, None],
            addr_hi, free_hi)
        free_hi = jnp.where(
            ((kind == int(FreeKind.CALLDATASIZE)) | (kind == int(FreeKind.TIMESTAMP))
             | (kind == int(FreeKind.NUMBER)))[:, None],
            small_hi, free_hi)
        r_lo = jnp.where(is_free[:, None], 0, r_lo)
        r_hi = jnp.where(is_free[:, None], free_hi, r_hi)

        # --- helpers over operand intervals ---
        sing_a = jnp.all(la == ha, axis=-1)
        sing_b = jnp.all(lb == hb, axis=-1)
        b_can_zero = u256.is_zero(lb)
        b_all_zero = u256.is_zero(hb)

        # ADD: exact unless the hi sum wraps
        s_lo, c_lo = u256.add_carry(la, lb)
        s_hi, c_hi = u256.add_carry(ha, hb)
        add_exact = ~c_hi
        r = (jnp.where(add_exact[:, None], s_lo, 0),
             jnp.where(add_exact[:, None], s_hi, top_hi))
        r_lo = jnp.where((op == int(SymOp.ADD))[:, None], r[0], r_lo)
        r_hi = jnp.where((op == int(SymOp.ADD))[:, None], r[1], r_hi)

        # SUB: exact when a surely >= b
        no_wrap = u256.gte(la, hb)
        d_lo = u256.sub(la, hb)
        d_hi = u256.sub(ha, lb)
        r_lo = jnp.where((op == int(SymOp.SUB))[:, None],
                         jnp.where(no_wrap[:, None], d_lo, 0), r_lo)
        r_hi = jnp.where((op == int(SymOp.SUB))[:, None],
                         jnp.where(no_wrap[:, None], d_hi, top_hi), r_hi)

        # MUL: exact when hi*hi fits 256 bits
        wide = u256.mul_wide(ha, hb)
        fits = jnp.all(wide[:, 8:] == 0, axis=-1)
        m_lo = u256.mul(la, lb)
        m_hi = wide[:, :8]
        r_lo = jnp.where((op == int(SymOp.MUL))[:, None],
                         jnp.where(fits[:, None], m_lo, 0), r_lo)
        r_hi = jnp.where((op == int(SymOp.MUL))[:, None],
                         jnp.where(fits[:, None], m_hi, top_hi), r_hi)

        # DIV: b>=1 -> result <= a_hi (no 256-step division here: too slow)
        r_lo = jnp.where((op == int(SymOp.DIV))[:, None], 0, r_lo)
        r_hi = jnp.where((op == int(SymOp.DIV))[:, None], ha, r_hi)

        # MOD: < b_hi (and <= a_hi); b identically 0 -> result 0
        one = jnp.zeros_like(hb).at[:, 0].set(1)
        b_minus_1 = u256.sub(hb, one)
        mod_cap = jnp.where(u256.lt(ha, b_minus_1)[:, None], ha, b_minus_1)
        mod_hi = jnp.where(b_all_zero[:, None], 0, mod_cap)
        r_lo = jnp.where((op == int(SymOp.MOD))[:, None], 0, r_lo)
        r_hi = jnp.where((op == int(SymOp.MOD))[:, None], mod_hi, r_hi)

        # AND: <= min(a_hi, b_hi)
        and_hi = jnp.where(u256.lt(ha, hb)[:, None], ha, hb)
        r_lo = jnp.where((op == int(SymOp.AND))[:, None], 0, r_lo)
        r_hi = jnp.where((op == int(SymOp.AND))[:, None], and_hi, r_hi)

        # OR: >= max(a_lo, b_lo)
        or_lo = jnp.where(u256.gt(la, lb)[:, None], la, lb)
        r_lo = jnp.where((op == int(SymOp.OR))[:, None], or_lo, r_lo)

        # NOT: exact complement flip
        r_lo = jnp.where((op == int(SymOp.NOT))[:, None], u256.bit_not(ha), r_lo)
        r_hi = jnp.where((op == int(SymOp.NOT))[:, None], u256.bit_not(la), r_hi)

        # BYTE: [0, 255]
        byte_hi = jnp.zeros_like(ha).at[:, 0].set(255)
        r_lo = jnp.where((op == int(SymOp.BYTE))[:, None], 0, r_lo)
        r_hi = jnp.where((op == int(SymOp.BYTE))[:, None], byte_hi, r_hi)

        # SHR by singleton shift: exact; else [0, value_hi]
        shr_exact = sing_a
        shr_lo = jnp.where(shr_exact[:, None], u256.shr(la, lb), 0)
        shr_hi = jnp.where(shr_exact[:, None], u256.shr(la, hb), hb)
        r_lo = jnp.where((op == int(SymOp.SHR))[:, None], shr_lo, r_lo)
        r_hi = jnp.where((op == int(SymOp.SHR))[:, None], shr_hi, r_hi)

        # SHL by singleton shift: exact when hi<<k doesn't lose bits
        k_small = sing_a & u256.lt(la, jnp.zeros_like(la).at[:, 0].set(256))
        shifted_hi = u256.shl(la, hb)
        back = u256.shr(la, shifted_hi)
        shl_ok = k_small & u256.eq(back, hb)
        r_lo = jnp.where((op == int(SymOp.SHL))[:, None],
                         jnp.where(shl_ok[:, None], u256.shl(la, lb), 0), r_lo)
        r_hi = jnp.where((op == int(SymOp.SHL))[:, None],
                         jnp.where(shl_ok[:, None], shifted_hi, top_hi), r_hi)

        # --- boolean producers: result in [0,1], sharpened when decidable ---
        t_lo = jnp.zeros_like(ha)
        t_one = jnp.zeros_like(ha).at[:, 0].set(1)

        def bool_iv(surely_true, surely_false):
            blo = jnp.where(surely_true[:, None], t_one, t_lo)
            bhi = jnp.where(surely_false[:, None], t_lo, t_one)
            return blo, bhi

        lt_t = u256.lt(ha, lb)   # a_hi < b_lo -> surely a<b
        lt_f = u256.gte(la, hb)  # a_lo >= b_hi -> surely not
        blo, bhi = bool_iv(lt_t, lt_f)
        r_lo = jnp.where((op == int(SymOp.LT))[:, None], blo, r_lo)
        r_hi = jnp.where((op == int(SymOp.LT))[:, None], bhi, r_hi)

        gt_t = u256.gt(la, hb)
        gt_f = u256.lte(ha, lb)
        blo, bhi = bool_iv(gt_t, gt_f)
        r_lo = jnp.where((op == int(SymOp.GT))[:, None], blo, r_lo)
        r_hi = jnp.where((op == int(SymOp.GT))[:, None], bhi, r_hi)

        eq_t = sing_a & sing_b & u256.eq(la, lb)
        eq_f = u256.lt(ha, lb) | u256.lt(hb, la)  # disjoint intervals
        blo, bhi = bool_iv(eq_t, eq_f)
        r_lo = jnp.where((op == int(SymOp.EQ))[:, None], blo, r_lo)
        r_hi = jnp.where((op == int(SymOp.EQ))[:, None], bhi, r_hi)

        isz_t = u256.is_zero(ha)          # whole interval is {0}
        isz_f = ~u256.is_zero(la)         # 0 not in interval
        blo, bhi = bool_iv(isz_t, isz_f)
        r_lo = jnp.where((op == int(SymOp.ISZERO))[:, None], blo, r_lo)
        r_hi = jnp.where((op == int(SymOp.ISZERO))[:, None], bhi, r_hi)

        # SLT/SGT undecided: [0, 1]
        blo, bhi = bool_iv(jnp.zeros_like(lt_t), jnp.zeros_like(lt_t))
        r_lo = jnp.where(((op == int(SymOp.SLT)) | (op == int(SymOp.SGT)))[:, None], blo, r_lo)
        r_hi = jnp.where(((op == int(SymOp.SLT)) | (op == int(SymOp.SGT)))[:, None], bhi, r_hi)

        # --- known-bits transfer (default: nothing known) ---
        all1 = _full_like(ha, True)
        rm = jnp.zeros_like(ha)
        rv = jnp.zeros_like(ha)
        rm = jnp.where(is_const[:, None], all1, rm)
        rv = jnp.where(is_const[:, None], imm, rv)
        # bounded leaves: the high bits are known zero
        free_km = jnp.zeros_like(ha)
        free_km = jnp.where(
            ((kind == int(FreeKind.CALLER)) | (kind == int(FreeKind.ORIGIN)))[:, None],
            u256.bit_not(addr_hi), free_km)
        free_km = jnp.where(
            ((kind == int(FreeKind.CALLDATASIZE)) | (kind == int(FreeKind.TIMESTAMP))
             | (kind == int(FreeKind.NUMBER)))[:, None],
            u256.bit_not(small_hi), free_km)
        rm = jnp.where(is_free[:, None], free_km, rm)

        # bitwise ops are exact on known bits
        and_m = (ka & kb) | (ka & ~va) | (kb & ~vb)  # a known-0 forces 0
        rm = jnp.where((op == int(SymOp.AND))[:, None], and_m, rm)
        rv = jnp.where((op == int(SymOp.AND))[:, None], va & vb & and_m, rv)
        or_m = (ka & kb) | (ka & va) | (kb & vb)     # a known-1 forces 1
        rm = jnp.where((op == int(SymOp.OR))[:, None], or_m, rm)
        rv = jnp.where((op == int(SymOp.OR))[:, None], (va | vb) & or_m, rv)
        rm = jnp.where((op == int(SymOp.XOR))[:, None], ka & kb, rm)
        rv = jnp.where((op == int(SymOp.XOR))[:, None], (va ^ vb) & ka & kb, rv)
        rm = jnp.where((op == int(SymOp.NOT))[:, None], ka, rm)
        rv = jnp.where((op == int(SymOp.NOT))[:, None], ~va & ka, rv)

        # shifts by a singleton amount: masks shift too; shifted-in bits
        # are known zero (tape operand order: a = shift, b = value)
        shift_conc = sing_a & u256.lt(la, jnp.zeros_like(la).at[:, 0].set(256))
        ones_shr = u256.shr(la, all1)   # low (256-k) bits set
        ones_shl = u256.shl(la, all1)   # high (256-k) bits set
        shr_m = u256.shr(la, kb) | u256.bit_not(ones_shr)
        shl_m = u256.shl(la, kb) | u256.bit_not(ones_shl)
        is_shr_c = (op == int(SymOp.SHR)) & shift_conc
        is_shl_c = (op == int(SymOp.SHL)) & shift_conc
        rm = jnp.where(is_shr_c[:, None], shr_m, rm)
        rv = jnp.where(is_shr_c[:, None], u256.shr(la, vb), rv)
        rm = jnp.where(is_shl_c[:, None], shl_m, rm)
        rv = jnp.where(is_shl_c[:, None], u256.shl(la, vb), rv)

        # boolean producers: bits 1..255 known zero; the verdict bit when
        # known-bits alone decide it
        is_bool = ((op == int(SymOp.LT)) | (op == int(SymOp.GT))
                   | (op == int(SymOp.SLT)) | (op == int(SymOp.SGT))
                   | (op == int(SymOp.EQ)) | (op == int(SymOp.ISZERO)))
        not_one = u256.bit_not(t_one)
        diff = (va ^ vb) & ka & kb
        kb_ne = ~u256.is_zero(diff)                       # EQ surely false
        a_full = jnp.all(ka == 0xFFFFFFFF, axis=-1)
        b_full = jnp.all(kb == 0xFFFFFFFF, axis=-1)
        kb_eq = a_full & b_full & u256.is_zero(va ^ vb)   # EQ surely true
        isz_nz = ~u256.is_zero(va & ka)                   # ISZERO surely 0
        isz_z = a_full & u256.is_zero(va)                 # ISZERO surely 1
        rm = jnp.where(is_bool[:, None], not_one, rm)
        rv = jnp.where(is_bool[:, None], 0, rv)
        eq_dec = (op == int(SymOp.EQ)) & (kb_ne | kb_eq)
        isz_dec = (op == int(SymOp.ISZERO)) & (isz_nz | isz_z)
        dec = eq_dec | isz_dec
        dec_one = ((op == int(SymOp.EQ)) & kb_eq) | ((op == int(SymOp.ISZERO)) & isz_z)
        rm = jnp.where(dec[:, None], all1, rm)
        rv = jnp.where(dec_one[:, None], t_one, rv)

        live = (idx >= 1) & (idx < sf.tape_len) & (op != int(SymOp.NULL))
        lanes = jnp.arange(idx.shape[0])
        widx = jnp.where(live, jnp.clip(idx, 0, T - 1), T)
        lo = lo.at[lanes, widx].set(r_lo, mode="drop")
        hi = hi.at[lanes, widx].set(r_hi, mode="drop")
        km = km.at[lanes, widx].set(rm, mode="drop")
        kv = kv.at[lanes, widx].set(rv, mode="drop")
        return lo, hi, km, kv

    # per-lane resume: lane p walks nodes [prop_len[p], tape_len[p]);
    # trip count = the largest new-node count over lanes
    base_idx = jnp.maximum(sf.prop_len, 1).astype(jnp.int32)
    stop = jnp.max(sf.tape_len - base_idx).astype(jnp.int32)

    def wbody(state):
        j, carry = state
        return j + 1, body(base_idx + j, carry)

    _, (lo, hi, km, kv) = lax.while_loop(
        lambda s: s[0] < stop, wbody, (jnp.int32(0), (lo, hi, km, kv)))
    sf = sf.replace(
        iv_lo=lo, iv_hi=hi, kb_m=km, kb_v=kv,
        prop_len=jnp.maximum(sf.prop_len, sf.tape_len),
    )

    # constraint check (either domain may contradict)
    C = sf.con_node.shape[1]
    con_live = jnp.arange(C)[None, :] < sf.con_len[:, None]
    node = jnp.clip(sf.con_node, 0, T - 1)
    idx = node[:, :, None].repeat(8, 2)
    n_lo = jnp.take_along_axis(lo, idx, axis=1)
    n_hi = jnp.take_along_axis(hi, idx, axis=1)
    n_km = jnp.take_along_axis(km, idx, axis=1)
    n_kv = jnp.take_along_axis(kv, idx, axis=1)
    cant_be_nonzero = jnp.all(n_hi == 0, axis=-1) | (
        jnp.all(n_km == 0xFFFFFFFF, axis=-1) & jnp.all(n_kv == 0, axis=-1)
    )
    cant_be_zero = ~jnp.all(n_lo == 0, axis=-1) | jnp.any(
        (n_kv & n_km) != 0, axis=-1
    )
    contradicted = con_live & (sf.con_node != 0) & jnp.where(
        sf.con_sign, cant_be_nonzero, cant_be_zero
    )
    infeasible = jnp.any(contradicted, axis=1)
    return sf, infeasible


def kill_infeasible(sf: SymFrontier) -> SymFrontier:
    """Deactivate lanes whose path condition is provably unsatisfiable."""
    sf, inf = propagate_feasibility(sf)
    # errored lanes stay resident (not recycled) until the tx boundary so
    # their err_code survives for the per-tx trap tally; they are also not
    # "kills" — the trap already accounts for them
    inf = inf & sf.base.active & ~sf.base.error
    return sf.replace(
        base=sf.base.replace(active=sf.base.active & ~inf),
        # a killed lane's pending (deferred) fork request dies with it —
        # expand_forks also guards, but the invariant belongs here
        fork_req=sf.fork_req & ~inf,
        killed_infeasible=sf.killed_infeasible | inf,
        killed_total=sf.killed_total + jnp.sum(inf, dtype=jnp.int32),
    )
