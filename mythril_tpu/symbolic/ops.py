"""Tape node opcodes and free-variable kinds.

The tape is the device-resident analog of the reference's Z3 AST
(``mythril/laser/smt/bitvec.py`` ⚠unv): each node is
``(op, a, b, imm)`` where ``a``/``b`` are earlier node ids (SSA) and
``imm`` is a u256 payload (constants, concrete keys). Node id 0 is the
reserved concrete-zero/null node; stack slots carry a parallel sym-id of 0
to mean "concrete, value lives in the limb arrays".
"""

from __future__ import annotations

from enum import IntEnum


class SymOp(IntEnum):
    NULL = 0        # id-0 sentinel / unused slot
    CONST = 1       # imm = value
    FREE = 2        # a = FreeKind, b = index, imm = aux (e.g. storage key)
    # arithmetic (a ∘ b)
    ADD = 3
    SUB = 4
    MUL = 5
    DIV = 6
    SDIV = 7
    MOD = 8
    SMOD = 9
    EXP = 10
    SIGNEXTEND = 11
    # comparisons (result is 0/1 word)
    LT = 12
    GT = 13
    SLT = 14
    SGT = 15
    EQ = 16
    ISZERO = 17     # unary: a
    # bitwise
    AND = 18
    OR = 19
    XOR = 20
    NOT = 21        # unary: a
    BYTE = 22       # a = index expr, b = word
    SHL = 23        # a = shift, b = value   (EVM operand order)
    SHR = 24
    SAR = 25
    # keccak chain: digest = KECCAK(absorb(...absorb(KECCAK_SEED, w0)..., wn))
    KECCAK_SEED = 26  # imm = byte length
    KECCAK_ABS = 27   # a = chain, b = absorbed word id (imm = concrete word)
    KECCAK = 28       # a = final chain node -> 256-bit digest


class FreeKind(IntEnum):
    """Leaf variable kinds (the model-search variable space)."""

    CALLER = 0
    CALLVALUE = 1
    CALLDATASIZE = 2
    CALLDATA_WORD = 3   # b = BYTE offset of the 32-byte read window

    ORIGIN = 4
    TIMESTAMP = 5
    NUMBER = 6
    BALANCE = 7
    GASPRICE = 8
    STORAGE = 9         # initial storage value; imm = concrete key, a = key node id
    RETVAL = 10         # return value of external call; b = call index
    RETDATA_WORD = 11   # word of external call returndata; b = call idx * 64 + word
    HAVOC = 12          # unconstrained havoc (unaligned/symbolic-offset reads)
    PREVRANDAO = 13
    BLOCKHASH = 14
    RETDATASIZE = 15    # returndata size of an external call; b = call index
    ECRECOVER = 16      # uninterpreted ecrecover result; b = call index
    # (the reference also models ecrecover as an uninterpreted function on
    # symbolic inputs — natives.py ⚠unv; NOT attacker-controlled taint)
    PRECOMPILE = 17     # other unmodeled precompile output; b = call index


# Multi-transaction leaf identity: tx-scoped leaves encode the transaction
# index in `b` — calldata words as b = tx_id * TX_STRIDE + byte_offset,
# caller/callvalue/calldatasize as b = tx_id. Tx 0 therefore has b == the
# plain offset/0, which is exactly what the pre-seeded rows below carry, so
# hash-consing dedups first-tx reads onto the seeds. ORIGIN and the block
# environment stay global (b = 0) across the sequence.
TX_STRIDE = 1 << 16

# BALANCE leaves are keyed b = bal_epoch * BAL_STRIDE + account slot: the
# epoch versions the leaf across concrete balance-table changes (see
# SymFrontier.bal_epoch). Must exceed LimitsConfig.max_accounts.
BAL_STRIDE = 256

# Well-known leaves pre-seeded on the tape at fixed ids so the hot paths
# (CALLDATALOAD, CALLER, CALLVALUE) never need an append. Layout:
#   id 0              NULL (concrete zero)
#   id 1..N           the list below, then calldata words
_WK_BASE = [
    FreeKind.CALLER,
    FreeKind.CALLVALUE,
    FreeKind.CALLDATASIZE,
    FreeKind.ORIGIN,
    FreeKind.TIMESTAMP,
    FreeKind.NUMBER,
    FreeKind.BALANCE,
    FreeKind.GASPRICE,
    FreeKind.PREVRANDAO,
]

WK_CALLER = 1
WK_CALLVALUE = 2
WK_CALLDATASIZE = 3
WK_ORIGIN = 4
WK_TIMESTAMP = 5
WK_NUMBER = 6
WK_BALANCE = 7
WK_GASPRICE = 8
WK_PREVRANDAO = 9
# Calldata leaves are keyed by BYTE offset, matching how solc-compiled code
# actually reads calldata: the selector word at offset 0, then ABI argument
# words at offsets 4 + 32*i. WK_CALLDATA0 is the offset-0 leaf; argument i
# lives at id WK_CALLDATA0 + 1 + i. Leaves overlap byte-wise (offset 0 and
# offset 4 share bytes 4..31); the model search resolves them over one
# shared calldata byte array, the propagation treats them as independent
# (sound, merely less precise).
WK_CALLDATA0 = 10


def calldata_arg_offsets(calldata_bytes: int):
    """Byte offsets of the pre-seeded calldata leaves: 0, 4, 36, 68, ..."""
    offs = [0]
    o = 4
    while o + 32 <= calldata_bytes:
        offs.append(o)
        o += 32
    return offs


def WELL_KNOWN(calldata_bytes: int):
    """[(op, kind, index)] rows for tape slots 1..N in order."""
    rows = [(int(SymOp.FREE), int(k), 0) for k in _WK_BASE]
    for off in calldata_arg_offsets(calldata_bytes):
        rows.append((int(SymOp.FREE), int(FreeKind.CALLDATA_WORD), off))
    return rows


def N_WELL_KNOWN(calldata_bytes: int) -> int:
    return 1 + len(_WK_BASE) + len(calldata_arg_offsets(calldata_bytes))
