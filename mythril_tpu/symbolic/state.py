"""SymFrontier: the concrete frontier plus the symbolic overlay.

Design: concrete limb arrays stay authoritative for concrete values; a
parallel "sym id" overlay marks which slots hold symbolic expressions
(id != 0 → value is tape node, limbs are garbage). This replaces the
reference's per-object Z3 expressions on stack/memory/storage
(``mythril/laser/ethereum/state/*.py`` ⚠unv) with two flat arrays per
storage class.

Granularity choices (documented over-approximations; each introduces
fresh unconstrained variables rather than wrong values):
- memory symbolics are tracked per 32-byte word (``mem_sym``);
- unaligned symbolic stores/loads produce HAVOC leaves;
- an unaligned CALLDATACOPY (or symbolic-offset store) sets ``mem_havoc``:
  every later MLOAD of that lane returns a fresh HAVOC leaf.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Any

import numpy as np
import jax.numpy as jnp
from flax import struct

from ..config import LimitsConfig, DEFAULT_LIMITS
from ..core.frontier import Frontier, make_frontier
from .ops import SymOp, WELL_KNOWN, N_WELL_KNOWN

I32 = jnp.int32
U32 = jnp.uint32


def tape_row_hash(op, a, b, imm):
    """u32 fingerprint of one tape row (op, a, b, imm[..., 8]).

    The hash-cons scan in ``append_node`` compares this ONE word per
    entry instead of the full 12-word row (3 x i32 + 8 x u32 imm) — the
    full row is verified only for the single candidate the hash matched,
    so a collision degrades to a missed dedup (sound: a duplicate node,
    never a wrong id). Any writer of tape rows must store the matching
    hash (``append_node`` and the seed rows in ``make_sym_frontier``).
    """
    op = jnp.asarray(op).astype(U32)
    a = jnp.asarray(a).astype(U32)
    b = jnp.asarray(b).astype(U32)
    h = (op * U32(0x9E3779B1)) ^ (a * U32(0x85EBCA6B)) ^ (b * U32(0xC2B2AE35))
    # positional odd multipliers: permuted limbs must hash differently
    mult = jnp.asarray([0x27D4EB2F, 0x165667B1, 0xD6E8FEB9, 0xA3D8A6E3,
                        0x83B58237, 0xCC9E2D51, 0x1B873593, 0xE6546B65],
                       dtype=U32)
    h = h ^ jnp.sum(imm.astype(U32) * mult, axis=-1, dtype=U32)
    h = h ^ (h >> 16)
    h = h * U32(0x7FEB352D)
    return h ^ (h >> 15)


@dataclass(frozen=True)
class SymSpec:
    """Static (trace-time) choice of which inputs are symbolic.

    Mirrors the reference's symbolic tx setup (``execute_message_call``
    builds symbolic calldata/callvalue/caller ⚠unv, SURVEY.md §2
    "Transaction models")."""

    calldata: bool = True
    callvalue: bool = True
    caller: bool = False       # reference default: concrete ATTACKER address
    storage: bool = True       # unknown initial storage -> fresh STORAGE leaves
    block_env: bool = True     # timestamp/number/... symbolic (PredictableVars)
    # When the frontier's lane axis is sharded over a device mesh, the
    # precompile host callbacks must round-trip only shard-local lanes —
    # a bare pure_callback inside pjit gets a {maximal device=0} sharding
    # and XLA inserts a full gather/rescatter ("Involuntary full
    # rematerialization") that would serialize every superstep on a pod.
    # Setting ``mesh`` (a hashable jax.sharding.Mesh; part of the jit
    # cache key via static spec) routes them through jax.shard_map over
    # ``lane_axis`` instead. None = single-device path, no shard_map.
    mesh: Any = None
    lane_axis: str = "dp"
    # numeric storage-alias probe (VERDICT r4 ask #6): demote symbolic
    # keys with fully-known bits to their value at SSTORE/SLOAD so
    # provably-equal keys connect. Trace-time static: False compiles the
    # probe out entirely (~0-15% cost on storage-heavy CPU workloads,
    # noise-limited — see docs/perf-round5-cpu-ab.md; the soundness win
    # is the default, the flag exists for perf runs and A/B measurement).
    alias_probe: bool = True


@struct.dataclass
class SymFrontier:
    base: Frontier
    # --- overlay: sym ids (0 = concrete) ---
    stack_sym: jnp.ndarray   # i32[P, S]
    mem_sym: jnp.ndarray     # i32[P, M/32]
    mem_havoc: jnp.ndarray   # bool[P] whole-memory havoc (coarse escape hatch)
    retdata_sym: jnp.ndarray  # bool[P] returndata of last call is symbolic
    st_val_sym: jnp.ndarray  # i32[P, K]
    st_key_sym: jnp.ndarray  # i32[P, K] sym id of the key stored in the slot
    st_seq: jnp.ndarray      # i32[P, K] write sequence number of the entry
    # (0 = never written). The numeric alias probe can put MULTIPLE
    # entries in one alias group (a slot written before its key's bits
    # were proven + a concrete slot of the same value); slot INDEX order
    # does not track write order once a lower slot is re-written in
    # place, so reads/writes select the group's max-seq entry instead.
    st_seq_ctr: jnp.ndarray  # i32[P] per-lane monotonic SSTORE counter
    rv_sym: jnp.ndarray      # i32[P, RD/32] sym ids of the RETURN/REVERT payload
    rv_havoc: jnp.ndarray    # bool[P] RETURN/REVERT payload unknown (claimed
    # symbolic-offset halt) — the caller's returndata havocs on pop
    # --- sub-call frame overlay ---
    cd_from_mem: jnp.ndarray  # bool[P] calldata is caller memory (depth > 0),
    # not free symbolic leaves
    cd_havoc: jnp.ndarray    # bool[P] this frame's calldata bytes unknown
    cd_sym: jnp.ndarray      # i32[P, CD/32] per-word sym ids of frame calldata
    callvalue_sym: jnp.ndarray  # i32[P] sym id of this frame's callvalue
    caller_sym: jnp.ndarray  # i32[P] sym id of this frame's msg.sender (0 =
    # concrete; a DELEGATECALL frame inherits the caller frame's CALLER leaf)
    bal_epoch: jnp.ndarray   # i32[P] balance-leaf version: bumped whenever the
    # concrete balance table changes (transfer / rollback / tx boundary) so
    # BALANCE reads across the change get fresh leaves instead of being
    # forced equal (advisor r2 low)
    fr_mem_sym: jnp.ndarray  # i32[P, D, M/32] saved caller memory overlay
    fr_mem_havoc: jnp.ndarray  # bool[P, D]
    fr_cd_from_mem: jnp.ndarray  # bool[P, D]
    fr_cd_havoc: jnp.ndarray  # bool[P, D]
    fr_cd_sym: jnp.ndarray   # i32[P, D, CD/32]
    fr_callvalue_sym: jnp.ndarray  # i32[P, D]
    fr_caller_sym: jnp.ndarray  # i32[P, D]
    fr_st_val_sym: jnp.ndarray  # i32[P, D, K] storage-overlay snapshots
    fr_st_key_sym: jnp.ndarray  # i32[P, D, K]  (revert rollback)
    fr_st_seq: jnp.ndarray      # i32[P, D, K]
    sub_revert_pc: jnp.ndarray  # i32[P] pc of the CALL whose callee
    # reverted/failed (-1 = none; SWC-123 RequirementsViolation feed)
    sub_revert_cid: jnp.ndarray  # i32[P] contract owning that CALL site
    # --- SSA tape ---
    tape_op: jnp.ndarray     # i32[P, T]
    tape_a: jnp.ndarray      # i32[P, T]
    tape_b: jnp.ndarray      # i32[P, T]
    tape_imm: jnp.ndarray    # u32[P, T, 8]
    tape_hash: jnp.ndarray   # u32[P, T] row fingerprint (tape_row_hash) —
    # the hash-cons scan's fast path; must stay in sync with every write
    tape_len: jnp.ndarray    # i32[P]
    havoc_cnt: jnp.ndarray   # i32[P] fresh-variable counter (HAVOC uniqueness)
    create_cnt: jnp.ndarray  # i32[P] CREATE/CREATE2 counter (fresh addresses)
    # --- persistent abstract domains (incremental propagation) ---
    # the tape is SSA append-only, so a node's interval/known-bits never
    # change once computed: sweeps only propagate nodes in
    # [prop_len, tape_len) instead of re-walking the whole tape (the
    # full re-walk was ~96% of symbolic runtime at P=4096).
    # Measured tradeoff of keeping them resident (P=4096, T=512, v5e):
    # +1 GiB frontier memory and ~1.5 ms/superstep of extra expand_forks
    # gather traffic, against ~6.9 s PER SWEEP saved (57 s -> 3.6 s for a
    # 64-step run). Dropping them from the fork gather would force fresh
    # copies to re-propagate their whole tape, reverting the win.
    iv_lo: jnp.ndarray       # u32[P, T, 8] per-node interval lower bound
    iv_hi: jnp.ndarray       # u32[P, T, 8]
    kb_m: jnp.ndarray        # u32[P, T, 8] known-bits mask
    kb_v: jnp.ndarray        # u32[P, T, 8] known-bits value
    prop_len: jnp.ndarray    # i32[P] nodes already propagated
    # --- path condition ---
    tx_id: jnp.ndarray       # i32[P] current transaction index (0-based)
    con_node: jnp.ndarray    # i32[P, C]
    con_sign: jnp.ndarray    # bool[P, C]
    con_pc: jnp.ndarray      # i32[P, C] pc of the branch that asserted it
    con_len: jnp.ndarray     # i32[P]
    killed_infeasible: jnp.ndarray  # bool[P] pruned by constraint propagation
    killed_total: jnp.ndarray  # i32[] run total of propagation kills (survives
    # lane recycling — per-lane flags are lost when expand_forks reuses a slot)
    # --- bounded-loops policy (reference: BoundedLoopsStrategy ⚠unv) ---
    lb_key: jnp.ndarray      # i64[P, LBS] back-jump keys ((cid, src, dest) packed)
    lb_cnt: jnp.ndarray      # i32[P, LBS] taken-count per target
    lb_len: jnp.ndarray      # i32[P]
    # --- dependency pruner (reference: DependencyPruner ⚠unv) ---
    dep_read: jnp.ndarray    # bool[P] this tx read a key a PRIOR tx wrote
    # --- fork plumbing (filled by the JUMPI handler, drained by expand_forks) ---
    fork_req: jnp.ndarray    # bool[P]
    fork_dest: jnp.ndarray   # i32[P] jump target of the taken branch
    dropped_forks: jnp.ndarray  # i32[P] forks lost to capacity (reported)
    dropped_total: jnp.ndarray  # i32[] run total of dropped forks
    # symbolic-callee enumeration (CALL with symbolic target forks one
    # candidate account per superstep; the fork copy re-executes the CALL
    # with the target stack slot concretized — see _h_sym_call)
    call_enum: jnp.ndarray   # i32[P] next candidate account slot to try
    fork_cslot: jnp.ndarray  # i32[P] stack slot the fork copy concretizes (-1 = none)
    fork_cval: jnp.ndarray   # u32[P, 8] concrete value for that slot
    # --- detection-facing event records ---
    # every pc-bearing event also records the EXECUTING contract id at
    # record time (``*_cid``): a pc recorded inside a callee frame must not
    # be attributed to the lane's home contract (advisor r2 medium)
    sym_jump_dest: jnp.ndarray  # i32[P] node id of a symbolic JUMP dest (SWC-127)
    sym_jump_pc: jnp.ndarray    # i32[P] pc of that jump (-1 = none)
    sym_jump_cid: jnp.ndarray   # i32[P] contract executing that jump
    n_calls: jnp.ndarray     # i32[P]
    n_mut_calls: jnp.ndarray  # i32[P] CALL/CALLCODE/DELEGATECALL only (re-enterable)
    call_to: jnp.ndarray     # u32[P, CL, 8] concrete callee (if concrete)
    call_to_sym: jnp.ndarray  # i32[P, CL]
    call_value: jnp.ndarray  # u32[P, CL, 8]
    call_value_sym: jnp.ndarray  # i32[P, CL]
    call_op: jnp.ndarray     # i32[P, CL] raw opcode (CALL/DELEGATECALL/...)
    call_pc: jnp.ndarray     # i32[P, CL]
    call_cid: jnp.ndarray    # i32[P, CL] contract executing the call site
    # LOG record overlay: sym id of topic0 / first data word per record
    # (0 = concrete, -1 = unknown at symbolic offset / havoc'd memory)
    log_topic0_sym: jnp.ndarray  # i32[P, LS]
    log_data0_sym: jnp.ndarray   # i32[P, LS]
    sd_to_sym: jnp.ndarray   # i32[P] SELFDESTRUCT beneficiary sym id
    sd_to: jnp.ndarray       # u32[P, 8] concrete beneficiary
    sd_pc: jnp.ndarray       # i32[P] pc of the first SELFDESTRUCT (-1 = none)
    sd_cid: jnp.ndarray      # i32[P] contract whose code executed it
    # one-shot event records for the remaining SWC modules
    origin_read: jnp.ndarray  # bool[P] lane executed ORIGIN (SWC-111/115)
    inv_pc: jnp.ndarray      # i32[P] pc of an executed INVALID (-1 = none; SWC-110)
    inv_cid: jnp.ndarray     # i32[P]
    sstore_after_call_pc: jnp.ndarray  # i32[P] first SSTORE after an ext call (SWC-107)
    sstore_ac_cid: jnp.ndarray  # i32[P]
    arb_key_node: jnp.ndarray  # i32[P] key node of first symbolic-key SSTORE (SWC-124)
    arb_key_pc: jnp.ndarray    # i32[P]
    arb_key_cid: jnp.ndarray   # i32[P]
    # symbolic-arithmetic events (IntegerArithmetics SWC-101 feed)
    n_arith: jnp.ndarray     # i32[P]
    arith_op: jnp.ndarray    # i32[P, AL] EVM opcode (ADD/SUB/MUL/EXP)
    arith_a: jnp.ndarray     # i32[P, AL] operand node ids (post sym_or_const)
    arith_b: jnp.ndarray     # i32[P, AL]
    arith_r: jnp.ndarray     # i32[P, AL] result node id
    arith_pc: jnp.ndarray    # i32[P, AL]
    arith_cid: jnp.ndarray   # i32[P, AL]

    @property
    def n_lanes(self) -> int:
        return self.base.pc.shape[0]

    @property
    def tape_cap(self) -> int:
        return self.tape_op.shape[1]


def make_sym_frontier(
    n_lanes: int,
    limits: LimitsConfig = DEFAULT_LIMITS,
    contract_id=None,
    gas_limit: int = 10_000_000,
    active=None,
    calldata=None,
    calldata_len=None,
    **world_kw,
) -> SymFrontier:
    """Fresh frontier with the well-known leaves pre-seeded on every tape.
    Concrete ``calldata`` may be supplied for concolic/concrete replay; the
    default leaves the buffer zeroed (symbolic reads resolve to leaves).
    ``world_kw`` forwards world-state setup (n_contracts, contract_addrs,
    caller, balances) to :func:`make_frontier`."""
    P = n_lanes
    L = limits
    if calldata_len is None:
        calldata_len = np.full(P, L.calldata_bytes, dtype=np.int32)
    base = make_frontier(
        P, L, contract_id=contract_id, gas_limit=gas_limit, active=active,
        calldata=calldata, calldata_len=calldata_len, **world_kw,
    )
    T, C, K, S = L.tape_len, L.max_constraints, L.storage_slots, L.max_stack
    CL = L.call_log

    rows = WELL_KNOWN(L.calldata_bytes)
    n_wk = N_WELL_KNOWN(L.calldata_bytes)
    assert n_wk <= T, "tape too small for well-known leaves"
    t_op = np.zeros((P, T), dtype=np.int32)
    t_a = np.zeros((P, T), dtype=np.int32)
    t_b = np.zeros((P, T), dtype=np.int32)
    for i, (op, kind, idx) in enumerate(rows, start=1):
        t_op[:, i] = op
        t_a[:, i] = kind
        t_b[:, i] = idx

    z = lambda *s: jnp.zeros(s, dtype=I32)
    D = L.call_depth
    CDW = L.calldata_bytes // 32
    return SymFrontier(
        base=base,
        stack_sym=z(P, S),
        mem_sym=z(P, L.mem_bytes // 32),
        mem_havoc=jnp.zeros(P, dtype=bool),
        retdata_sym=jnp.zeros(P, dtype=bool),
        st_val_sym=z(P, K),
        st_key_sym=z(P, K),
        st_seq=z(P, K),
        st_seq_ctr=z(P),
        rv_sym=z(P, L.returndata_bytes // 32),
        rv_havoc=jnp.zeros(P, dtype=bool),
        cd_from_mem=jnp.zeros(P, dtype=bool),
        cd_havoc=jnp.zeros(P, dtype=bool),
        cd_sym=z(P, CDW),
        callvalue_sym=z(P),
        caller_sym=z(P),
        bal_epoch=z(P),
        fr_mem_sym=z(P, D, L.mem_bytes // 32),
        fr_mem_havoc=jnp.zeros((P, D), dtype=bool),
        fr_cd_from_mem=jnp.zeros((P, D), dtype=bool),
        fr_cd_havoc=jnp.zeros((P, D), dtype=bool),
        fr_cd_sym=z(P, D, CDW),
        fr_callvalue_sym=z(P, D),
        fr_caller_sym=z(P, D),
        fr_st_val_sym=z(P, D, K),
        fr_st_key_sym=z(P, D, K),
        fr_st_seq=z(P, D, K),
        sub_revert_pc=jnp.full(P, -1, dtype=I32),
        sub_revert_cid=z(P),
        tape_op=jnp.asarray(t_op),
        tape_a=jnp.asarray(t_a),
        tape_b=jnp.asarray(t_b),
        tape_imm=jnp.zeros((P, T, 8), dtype=U32),
        tape_hash=tape_row_hash(jnp.asarray(t_op), jnp.asarray(t_a),
                                jnp.asarray(t_b),
                                jnp.zeros((P, T, 8), dtype=U32)),
        tape_len=jnp.full(P, n_wk, dtype=I32),
        havoc_cnt=z(P),
        create_cnt=z(P),
        iv_lo=jnp.zeros((P, T, 8), dtype=U32),
        iv_hi=jnp.zeros((P, T, 8), dtype=U32),
        kb_m=jnp.zeros((P, T, 8), dtype=U32).at[:, 0].set(0xFFFFFFFF),
        kb_v=jnp.zeros((P, T, 8), dtype=U32),
        prop_len=jnp.ones(P, dtype=I32),  # node 0 pre-seeded ([0,0], known)
        tx_id=z(P),
        con_node=z(P, C),
        con_sign=jnp.zeros((P, C), dtype=bool),
        con_pc=z(P, C),
        con_len=z(P),
        killed_infeasible=jnp.zeros(P, dtype=bool),
        killed_total=jnp.zeros((), dtype=I32),
        lb_key=jnp.full((P, L.loop_slots), -1, dtype=jnp.int64),
        lb_cnt=z(P, L.loop_slots),
        lb_len=z(P),
        dep_read=jnp.zeros(P, dtype=bool),
        fork_req=jnp.zeros(P, dtype=bool),
        fork_dest=z(P),
        call_enum=z(P),
        fork_cslot=jnp.full(P, -1, dtype=I32),
        fork_cval=jnp.zeros((P, 8), dtype=U32),
        dropped_forks=z(P),
        dropped_total=jnp.zeros((), dtype=I32),
        sym_jump_dest=z(P),
        sym_jump_pc=jnp.full(P, -1, dtype=I32),
        sym_jump_cid=z(P),
        n_calls=z(P),
        n_mut_calls=z(P),
        call_to=jnp.zeros((P, CL, 8), dtype=U32),
        call_to_sym=z(P, CL),
        call_value=jnp.zeros((P, CL, 8), dtype=U32),
        call_value_sym=z(P, CL),
        call_op=z(P, CL),
        call_pc=z(P, CL),
        call_cid=z(P, CL),
        log_topic0_sym=z(P, L.log_slots),
        log_data0_sym=z(P, L.log_slots),
        sd_to_sym=z(P),
        sd_to=jnp.zeros((P, 8), dtype=U32),
        sd_pc=jnp.full(P, -1, dtype=I32),
        sd_cid=z(P),
        origin_read=jnp.zeros(P, dtype=bool),
        inv_pc=jnp.full(P, -1, dtype=I32),
        inv_cid=z(P),
        sstore_after_call_pc=jnp.full(P, -1, dtype=I32),
        sstore_ac_cid=z(P),
        arb_key_node=z(P),
        arb_key_pc=jnp.full(P, -1, dtype=I32),
        arb_key_cid=z(P),
        n_arith=z(P),
        arith_op=z(P, L.arith_log),
        arith_a=z(P, L.arith_log),
        arith_b=z(P, L.arith_log),
        arith_r=z(P, L.arith_log),
        arith_pc=z(P, L.arith_log),
        arith_cid=z(P, L.arith_log),
    )
