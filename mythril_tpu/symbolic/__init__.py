"""Symbolic execution layer: SSA tape, forking engine, constraint propagation.

TPU-first replacement for the reference's Z3-object symbolic state
(``mythril/laser/smt`` + symbolic values threaded through
``mythril/laser/ethereum/state`` ⚠unv, SURVEY.md §2): symbolic values are
integer node ids into a per-lane bounded SSA tape; path conditions are
(node, sign) pairs; feasibility is decided by batched abstract
interpretation over the tape (known-bits + unsigned intervals), with a
model-search fallback instead of Z3 (not available in this image).
"""

from .ops import SymOp, FreeKind, WELL_KNOWN, N_WELL_KNOWN, calldata_arg_offsets
from .state import SymFrontier, make_sym_frontier, SymSpec
from .engine import (sym_superstep, sym_run, expand_forks, append_node,
                     between_txs, migrate_parked_device)
from .propagate import propagate_feasibility, kill_infeasible

__all__ = [
    "SymOp", "FreeKind", "WELL_KNOWN", "N_WELL_KNOWN", "calldata_arg_offsets",
    "SymFrontier", "make_sym_frontier", "SymSpec",
    "sym_superstep", "sym_run", "expand_forks", "append_node", "between_txs",
    "migrate_parked_device",
    "propagate_feasibility", "kill_infeasible",
]
