"""The symbolic superstep: concrete dispatch + sym-id overlay + forking.

Counterpart of the reference's symbolic ``Instruction.evaluate`` over Z3
expressions and ``jumpi_``'s state forking
(``mythril/laser/ethereum/instructions.py`` ⚠unv, SURVEY.md §3.2), but
frontier-first:

- lanes whose current opcode touches symbolic control/addresses are
  *claimed* out of the concrete dispatch and handled by sym-aware
  handlers (storage, jumps, calls, symbolic-offset memory ops);
- everything else runs the concrete handler unchanged, and a vectorized
  overlay keeps ``stack_sym``/``mem_sym`` in sync and appends tape nodes;
- a symbolic JUMPI records a fork request; :func:`expand_forks` performs
  masked lane duplication + prefix-sum compaction into free lanes
  (the reference's ``work_list.append`` of forked GlobalStates).

Over-approximation policy: wherever byte-exact symbolic tracking is not
worth the shapes (unaligned accesses, symbolic offsets, ADDMOD), the
result is a fresh unconstrained HAVOC leaf — never a wrong value, so the
engine may explore infeasible paths but never misses feasible ones.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..config import LimitsConfig, DEFAULT_LIMITS
from ..core import interpreter as ci
from ..core.frontier import (Frontier, Env, Corpus, Trap, CAP_TRAPS,
                             KILL_TRAPS, ACCT_ATTACKER, ATTACKER_ADDRESS,
                             CODE_UNKNOWN)
from ..ops import u256
from .ops import SymOp, FreeKind, TX_STRIDE, BAL_STRIDE
from .state import SymFrontier, SymSpec

I32 = jnp.int32
I64 = jnp.int64
U32 = jnp.uint32

# EVM opcode -> SymOp for plain binary/unary value ops (0 = no mapping)
def _binop_table() -> np.ndarray:
    t = np.zeros(256, dtype=np.int32)
    m = {
        0x01: SymOp.ADD, 0x02: SymOp.MUL, 0x03: SymOp.SUB, 0x04: SymOp.DIV,
        0x05: SymOp.SDIV, 0x06: SymOp.MOD, 0x07: SymOp.SMOD, 0x0A: SymOp.EXP,
        0x0B: SymOp.SIGNEXTEND, 0x10: SymOp.LT, 0x11: SymOp.GT,
        0x12: SymOp.SLT, 0x13: SymOp.SGT, 0x14: SymOp.EQ, 0x15: SymOp.ISZERO,
        0x16: SymOp.AND, 0x17: SymOp.OR, 0x18: SymOp.XOR, 0x19: SymOp.NOT,
        0x1A: SymOp.BYTE, 0x1B: SymOp.SHL, 0x1C: SymOp.SHR, 0x1D: SymOp.SAR,
    }
    for k, v in m.items():
        t[k] = int(v)
    return t


_J_BINOP = jnp.asarray(_binop_table())


# ---------------------------------------------------------------------------
# Tape + sym-stack helpers
# ---------------------------------------------------------------------------


def _peek_sym(sf: SymFrontier, i) -> jnp.ndarray:
    sp = sf.base.sp
    S = sf.stack_sym.shape[1]
    idx = jnp.clip(sp - 1 - i, 0, S - 1)
    return jnp.take_along_axis(sf.stack_sym, idx[:, None].astype(I32), axis=1)[:, 0]


def _set_sym_slot(stack_sym, pos, val, mask):
    """Masked single-slot write (backend-adaptive, see
    interpreter._set_slot / _write_slot)."""
    S = stack_sym.shape[1]
    idx = jnp.where(mask & (pos >= 0), pos, S).astype(I32)
    return ci._write_slot(stack_sym, idx, val)


def append_node(sf: SymFrontier, mask, op, a, b, imm=None):
    """Hash-consed tape append. op/a/b scalar or i32[P]; imm u32[P,8]|None.
    Returns (sf, ids) — id per lane (0 where ~mask). Overflow errors lane.

    The dedup scan compares one u32 fingerprint per entry
    (``tape_row_hash``) and verifies only the first hash-matching row —
    12x less scan traffic than comparing full rows (this scan runs
    several times per superstep and reads the whole tape each time). A
    collision on the first match degrades to a missed dedup: a duplicate
    node, never a wrong id."""
    from .state import tape_row_hash

    P, T = sf.tape_op.shape
    op = jnp.broadcast_to(jnp.asarray(op, I32), (P,))
    a = jnp.broadcast_to(jnp.asarray(a, I32), (P,))
    b = jnp.broadcast_to(jnp.asarray(b, I32), (P,))
    if imm is None:
        imm = jnp.zeros((P, 8), dtype=U32)
    h = tape_row_hash(op, a, b, imm)
    live = jnp.arange(T)[None, :] < sf.tape_len[:, None]
    match = live & (sf.tape_hash == h[:, None])
    hit0 = jnp.any(match, axis=1)
    hit_id = jnp.argmax(match, axis=1).astype(I32)
    # verify the candidate row (per-lane gather, not a full-tape compare)
    g1 = lambda arr: jnp.take_along_axis(arr, hit_id[:, None], axis=1)[:, 0]
    c_imm = jnp.take_along_axis(sf.tape_imm, hit_id[:, None, None], axis=1)[:, 0]
    hit = (hit0 & (g1(sf.tape_op) == op) & (g1(sf.tape_a) == a)
           & (g1(sf.tape_b) == b) & jnp.all(c_imm == imm, axis=-1))
    overflow = mask & ~hit & (sf.tape_len >= T)
    write = mask & ~hit & ~overflow
    widx = jnp.where(write, jnp.minimum(sf.tape_len, T), T)  # T = dropped
    ids = jnp.where(mask, jnp.where(hit, hit_id, jnp.where(write, sf.tape_len, 0)), 0)
    return (
        sf.replace(
            tape_op=ci._write_slot(sf.tape_op, widx, op),
            tape_a=ci._write_slot(sf.tape_a, widx, a),
            tape_b=ci._write_slot(sf.tape_b, widx, b),
            tape_imm=ci._write_slot(sf.tape_imm, widx, imm),
            tape_hash=ci._write_slot(sf.tape_hash, widx, h),
            tape_len=sf.tape_len + write.astype(I32),
            base=sf.base.trap(overflow, Trap.TAPE_LIMIT),
        ),
        ids,
    )


def _sym_or_const(sf: SymFrontier, mask, sym, limbs):
    """Operand id: existing sym, id 0 for concrete zero, CONST node else."""
    need = mask & (sym == 0) & ~u256.is_zero(limbs)
    sf, cid = append_node(sf, need, int(SymOp.CONST), 0, 0, limbs)
    return sf, jnp.where(sym != 0, sym, cid)


def _havoc(sf: SymFrontier, mask):
    """Fresh unconstrained leaf per lane (unique via per-lane counter)."""
    sf2, ids = append_node(
        sf, mask, int(SymOp.FREE), int(FreeKind.HAVOC), sf.havoc_cnt
    )
    return sf2.replace(havoc_cnt=sf2.havoc_cnt + mask.astype(I32)), ids


def _event_slot(counter, mask, length: int):
    """Bounded per-lane event-log allocation: onehot[P, L] of the next
    free slot where `mask`; saturated logs silently drop (counter still
    counts attempts so overflow is observable)."""
    idx = jnp.minimum(counter, length - 1)
    rec = mask & (counter < length)
    return (jnp.arange(length)[None, :] == idx[:, None]) & rec[:, None]


def _lookup_constraint(sf: SymFrontier, node):
    """Is `node` already asserted on the path? -> (known, sign)."""
    C = sf.con_node.shape[1]
    live = jnp.arange(C)[None, :] < sf.con_len[:, None]
    m = live & (sf.con_node == node[:, None]) & (node[:, None] != 0)
    known = jnp.any(m, axis=1)
    idx = jnp.argmax(m, axis=1)
    sign = jnp.take_along_axis(sf.con_sign, idx[:, None], axis=1)[:, 0]
    return known, known & sign


def _append_constraint(sf: SymFrontier, mask, node, sign, pc):
    C = sf.con_node.shape[1]
    overflow = mask & (sf.con_len >= C)
    write = mask & ~overflow
    widx = jnp.where(write, jnp.minimum(sf.con_len, C), C)
    sign = jnp.broadcast_to(jnp.asarray(sign, bool), mask.shape)
    return sf.replace(
        con_node=ci._write_slot(sf.con_node, widx, node),
        con_sign=ci._write_slot(sf.con_sign, widx, sign),
        con_pc=ci._write_slot(sf.con_pc, widx, pc),
        con_len=sf.con_len + write.astype(I32),
        base=sf.base.trap(overflow, Trap.CONSTRAINT_LIMIT),
    )


# ---------------------------------------------------------------------------
# Claimed handlers: sym-aware replacements run after the concrete dispatch
# (their lanes were skipped there, so stack/sp are still pre-instruction)
# ---------------------------------------------------------------------------


def _h_sym_storage(sf: SymFrontier, spec: SymSpec, op, m) -> SymFrontier:
    """SLOAD/SSTORE with (possibly symbolic) keys and values.

    Key matching: concrete keys match by limb equality, symbolic keys by
    tape node id (hash-consing makes structurally equal keccak keys share
    an id — the analog of the reference's KeccakFunctionManager
    hash-linking ⚠unv), PLUS a numeric alias probe (VERDICT r4 ask #6):
    a symbolic key whose known-bits domain (propagate.py, persistent
    ``kb_m``/``kb_v``) is fully determined has a definite numeric value
    and is DEMOTED to that value — it matches concrete keys and other
    fully-determined keys numerically, its SSTORE entry is stored
    concrete, and its SLOAD-miss leaf hash-conses on the value. A write
    through ``f(x)`` and a read through a structurally different but
    provably-equal ``g(y)`` therefore connect. Keys the domain cannot
    fully determine keep node-id matching (assumed-distinct: the same
    syntactic under-approximation the reference's independent BitVec
    keys give Z3 before hash-linking resolves them ⚠unv). Nodes not yet
    reached by a propagation sweep (``>= prop_len``) never demote — their
    kb rows may hold a recycled lane's stale domains.
    """
    f = sf.base
    key = ci._peek(f, 0)
    key_sym = _peek_sym(sf, 0)
    val = ci._peek(f, 1)
    val_sym = _peek_sym(sf, 1)
    is_store = op == 0x55
    static_viol = m & is_store & f.static
    m = m & ~static_viol
    sf = sf.replace(base=f.trap(static_viol, Trap.STATIC_WRITE))
    f = sf.base

    in_acct = f.st_acct == f.cur_acct[:, None]
    # numeric alias probe: definite values for fully-known-bits keys.
    # spec.alias_probe is a trace-time bool — False compiles the kb
    # gathers out entirely and the match reduces to the syntactic form.
    if spec.alias_probe:
        T = sf.tape_op.shape[1]
        kidx = jnp.clip(key_sym, 0, T - 1)
        key_kbm = jnp.take_along_axis(sf.kb_m, kidx[:, None, None],
                                      axis=1)[:, 0]
        key_kbv = jnp.take_along_axis(sf.kb_v, kidx[:, None, None],
                                      axis=1)[:, 0]
        key_known = ((key_sym != 0) & (key_sym < sf.prop_len)
                     & jnp.all(key_kbm == U32(0xFFFFFFFF), axis=-1))
        key_num = jnp.where(key_known[:, None], key_kbv, key).astype(U32)
        ent_sym = sf.st_key_sym
        eidx = jnp.clip(ent_sym, 0, T - 1)
        ent_kbm = jnp.take_along_axis(sf.kb_m, eidx[:, :, None], axis=1)
        ent_known = ((ent_sym != 0) & (ent_sym < sf.prop_len[:, None])
                     & jnp.all(ent_kbm == U32(0xFFFFFFFF), axis=-1))
        ent_kbv = jnp.take_along_axis(sf.kb_v, eidx[:, :, None], axis=1)
        ent_num = jnp.where(ent_known[:, :, None], ent_kbv,
                            f.st_keys).astype(U32)
    else:
        key_known = jnp.zeros_like(key_sym, dtype=bool)
        key_num = key
        ent_known = jnp.zeros_like(sf.st_key_sym, dtype=bool)
        ent_num = f.st_keys
    key_def = (key_sym == 0) | key_known
    eff_key_sym = jnp.where(key_known, 0, key_sym)  # demoted-to-concrete
    ent_def = (sf.st_key_sym == 0) | ent_known

    conc = (key_def[:, None] & ent_def
            & jnp.all(ent_num == key_num[:, None, :], axis=-1))
    symm = (key_sym[:, None] != 0) & (sf.st_key_sym == key_sym[:, None])
    match = f.st_used & in_acct & (conc | symm)
    # a VALUE hit requires a value-bearing entry (st_seq > 0): berlin
    # warm-tracking (_berlin_gas_post) allocates (key, 0, unwritten)
    # entries for concrete SLOAD misses, and matching those as hits
    # would read concrete 0 where the first load of the same slot
    # produced a symbolic STORAGE leaf — the same slot must keep reading
    # as that leaf. Seq-0 entries still count for SSTORE slot reuse
    # below, so a later store overwrites the warm entry in place.
    match_val = match & (sf.st_seq > 0)
    hit = jnp.any(match_val, axis=1)
    # dependency tracking: a hit on an entry NOT written this tx is a read
    # of a prior transaction's write (entries persist across the boundary
    # with st_written cleared)
    prior_hit = jnp.any(match_val & ~f.st_written, axis=1)
    sf = sf.replace(dep_read=sf.dep_read | (m & ~is_store & prior_hit))
    # LATEST-write matching slot, not a masked sum: the alias probe can
    # connect an entry written before its key's bits were proven WITH a
    # concrete entry of the same value — and slot INDEX order does not
    # track write order once a lower slot is re-written in place, so the
    # group's max-``st_seq`` entry is the live one (reads and the SSTORE
    # reuse slot below agree on this policy; stale members stay shadowed)
    sel = jnp.argmax(jnp.where(match, sf.st_seq, -1), axis=1).astype(I32)
    cur = jnp.take_along_axis(f.st_vals, sel[:, None, None], axis=1)[:, 0]
    cur = jnp.where(hit[:, None], cur, 0).astype(U32)
    cur_sym = jnp.take_along_axis(sf.st_val_sym, sel[:, None], axis=1)[:, 0]
    cur_sym = jnp.where(hit, cur_sym, 0).astype(I32)

    # SLOAD miss -> fresh STORAGE leaf (hash-consed on (account, key), so
    # repeated loads of the same key agree while distinct accounts'
    # identical keys stay independent); concrete-zero when storage isn't
    # symbolic. b encodes key_sym * A + account slot.
    miss_load = m & ~is_store & ~hit
    A = f.acct_used.shape[1]
    if spec.storage:
        # eff_key_sym/key_num: a demoted (fully-known) key hash-conses on
        # its VALUE, sharing the leaf a concrete key of that value gets
        sf, leaf = append_node(
            sf, miss_load, int(SymOp.FREE), int(FreeKind.STORAGE),
            eff_key_sym * A + f.cur_acct,
            jnp.where((eff_key_sym == 0)[:, None], key_num, 0).astype(U32),
        )
    else:
        leaf = jnp.zeros_like(key_sym)
    f = sf.base
    loaded = jnp.where(hit[:, None], cur, 0).astype(U32)
    loaded_sym = jnp.where(hit, cur_sym, leaf)

    # SSTORE into matching-or-free slot (shared alloc policy with the
    # concrete handler); same max-seq slot the read path selects, and
    # ANY match (incl. a seq-0 warm entry) is reused rather than
    # duplicated — only the VALUE-hit predicate above is seq-gated
    slot_id = sel
    widx, overflow = ci.storage_alloc(f, jnp.any(match, axis=1), slot_id,
                                      m & is_store)
    # SWC event records: first SSTORE after a RE-ENTERABLE external call
    # (STATICCALL/CREATE can't re-enter mutably), and first SSTORE through
    # a symbolic NON-keccak key (a direct-keccak key is a mapping access;
    # recording it would mask a later genuine arbitrary write, since only
    # the first event is kept)
    store_m = m & is_store
    first_after_call = store_m & (sf.n_mut_calls > 0) & (sf.sstore_after_call_pc < 0)
    T = sf.tape_op.shape[1]
    key_op = jnp.take_along_axis(
        sf.tape_op, jnp.clip(key_sym, 0, T - 1)[:, None], axis=1
    )[:, 0]
    key_is_hash = key_op == int(SymOp.KECCAK)
    # a demoted key has ONE reachable value on this path — not an
    # attacker-controlled arbitrary write target (eff, not key_sym)
    first_arb = store_m & (eff_key_sym != 0) & ~key_is_hash & (sf.arb_key_pc < 0)
    # SLOAD results ride the aux channel to sym_superstep's shared
    # writeback — base.stack/base.sp/stack_sym stay OUT of this claimed
    # handler's cond outputs (same traffic argument as dispatch's
    # WRITE_FIELDS: an untaken/taken cond otherwise materializes the
    # whole [P,S,8] stack at the boundary every storage superstep)
    return sf.replace(
        base=f.replace(
            st_keys=ci._write_slot(f.st_keys, widx, key_num),
            st_vals=ci._write_slot(f.st_vals, widx, val),
            st_used=ci._write_slot(f.st_used, widx, True),
            st_written=ci._write_slot(f.st_written, widx, True),
            st_acct=ci._write_slot(f.st_acct, widx, f.cur_acct),
        ).trap(overflow, Trap.STORAGE_SLOTS),
        st_key_sym=ci._write_slot(sf.st_key_sym, widx, eff_key_sym),
        st_val_sym=ci._write_slot(sf.st_val_sym, widx, val_sym),
        st_seq=ci._write_slot(sf.st_seq, widx, sf.st_seq_ctr + 1),
        st_seq_ctr=sf.st_seq_ctr + store_m.astype(I32),
        sstore_after_call_pc=jnp.where(first_after_call, f.pc, sf.sstore_after_call_pc),
        sstore_ac_cid=jnp.where(first_after_call, f.contract_id, sf.sstore_ac_cid),
        arb_key_node=jnp.where(first_arb, key_sym, sf.arb_key_node),
        arb_key_pc=jnp.where(first_arb, f.pc, sf.arb_key_pc),
        arb_key_cid=jnp.where(first_arb, f.contract_id, sf.arb_key_cid),
    ), {"r": loaded, "r_sym": loaded_sym, "w": m & ~is_store}


def _h_sym_jump(sf: SymFrontier, corpus: Corpus, op, m, old_pc, known, ksign) -> SymFrontier:
    """JUMP/JUMPI with symbolic dest and/or condition.

    - symbolic unknown condition + concrete valid dest: record a fork
      request (taken branch materialized by expand_forks) and continue on
      the fallthrough with ¬cond appended to the path condition
      (reference: ``jumpi_`` returning two states ⚠unv);
    - condition already asserted on this path: no fork, follow it;
    - symbolic dest on a (possibly) taken branch: record the node for the
      ArbitraryJump detector (SWC-127) and halt that branch.
    """
    f = sf.base
    dest_w = ci._peek(f, 0)
    dest_sym = _peek_sym(sf, 0)
    cond = ci._peek(f, 1)
    cond_sym = _peek_sym(sf, 1)
    is_jumpi = op == 0x57

    dest, valid_dest = ci.validate_jump_dest(f, corpus, dest_w)
    valid_dest = valid_dest & (dest_sym == 0)

    cond_is_sym = is_jumpi & (cond_sym != 0)
    resolved = ~is_jumpi | ~cond_is_sym | known
    taken_res = jnp.where(
        is_jumpi,
        jnp.where(cond_is_sym, ksign, ~u256.is_zero(cond)),
        True,
    )

    m_res = m & resolved
    m_fork = m & ~resolved
    # resolved, taken, symbolic dest -> SWC-127 record + halt
    sym_taken = m_res & taken_res & (dest_sym != 0)
    conc_taken = m_res & taken_res & (dest_sym == 0)
    bad = conc_taken & ~valid_dest
    # unresolved, symbolic dest: fallthrough survives; record the finding
    sym_unres = m_fork & (dest_sym != 0)
    # A concrete-but-invalid dest means the taken branch is an exceptional
    # halt (the concrete engine traps it); it is intentionally not forked —
    # matching the reference, which kills invalid-jump successors. The
    # fork also requires the ¬cond constraint write to succeed: a copy
    # whose sign-flip would hit an unrelated constraint slot would carry a
    # corrupted path condition.
    con_ok = sf.con_len < sf.con_node.shape[1]
    fork_ok = m_fork & valid_dest & con_ok
    sf = _append_constraint(sf, m_fork, cond_sym, False, old_pc)

    f = sf.base
    new_pc = jnp.where(m_res & conc_taken, dest.astype(I32), old_pc + 1)
    move = (m_res & ~bad & ~sym_taken) | m_fork
    d_sp = jnp.where(is_jumpi, 2, 1)
    return sf.replace(
        base=f.replace(
            pc=jnp.where(move, new_pc, f.pc),
            sp=jnp.where(m, f.sp - d_sp, f.sp),
            halted=f.halted | sym_taken,
        ).trap(bad, Trap.BAD_JUMP),
        sym_jump_dest=jnp.where(sym_taken | sym_unres, dest_sym, sf.sym_jump_dest),
        sym_jump_pc=jnp.where(sym_taken | sym_unres, old_pc, sf.sym_jump_pc),
        sym_jump_cid=jnp.where(sym_taken | sym_unres, f.contract_id, sf.sym_jump_cid),
        fork_req=sf.fork_req | fork_ok,
        fork_dest=jnp.where(fork_ok, dest.astype(I32), sf.fork_dest),
    )


def _note_backjump(sf: SymFrontier, mask, src, dest, loop_bound: int) -> SymFrontier:
    """Count taken BACKWARD jumps per (lane, contract, source pc, target);
    retire lanes whose revisit count exceeds ``loop_bound``.

    The frontier analog of the reference's ``BoundedLoopsStrategy``
    (``strategy/extensions/bounded_loops.py`` ⚠unv, SURVEY.md §1 row 7):
    instead of CFG-cycle counting over a work list, each lane tracks its
    hottest back-jump targets in a small table; a lane spinning past the
    bound traps with ``Trap.LOOP_BOUND`` — freeing its slot and its step
    budget for other paths instead of burning ``max_steps`` for the whole
    frontier. A miss on a full table reuses the coldest slot (heuristic:
    the hot loop is by definition the one being revisited).

    The key includes the JUMP's own pc: a shared subroutine placed before
    its call sites is entered via *distinct* backward jumps, which must
    not pool into one counter — only a repeated (src, dest) edge is a
    loop iteration."""
    if loop_bound <= 0:
        return sf
    P, LBS = sf.lb_key.shape
    key = ((sf.base.contract_id.astype(jnp.int64) * 32768 + dest) * 32768
           + src)
    live = jnp.arange(LBS)[None, :] < sf.lb_len[:, None]
    match = live & (sf.lb_key == key[:, None])
    hit = jnp.any(match, axis=1)
    hit_slot = jnp.argmax(match, axis=1).astype(I32)
    has_free = sf.lb_len < LBS
    cold = jnp.argmin(sf.lb_cnt, axis=1).astype(I32)
    slot = jnp.where(hit, hit_slot,
                     jnp.where(has_free, jnp.minimum(sf.lb_len, LBS - 1), cold))
    cur = jnp.take_along_axis(sf.lb_cnt, slot[:, None], axis=1)[:, 0]
    cnt = jnp.where(hit, cur + 1, 1)
    idx = jnp.where(mask, slot, LBS)
    return sf.replace(
        lb_key=ci._write_slot(sf.lb_key, idx, key),
        lb_cnt=ci._write_slot(sf.lb_cnt, idx, cnt),
        lb_len=sf.lb_len + (mask & ~hit & has_free).astype(I32),
        base=sf.base.trap(mask & (cnt > loop_bound), Trap.LOOP_BOUND),
    )


def _fr_set(arr, d, val, mask):
    """arr[P, D, ...]; arr[lane, d[lane]] = val[lane] where mask.
    Backend-adaptive (interpreter._write_slot): scatter on CPU; on TPU a
    one-hot compare-select — D is small (call_depth), so even the
    [P, D, M] frame-memory snapshots only touch D x the slice size."""
    Dn = arr.shape[1]
    idx = jnp.where(mask & (d >= 0), d, Dn).astype(I32)
    return ci._write_slot(arr, idx, val)


def _fr_get(arr, d):
    """arr[P, D, ...] gathered at per-lane depth index d."""
    idx = jnp.clip(d, 0, arr.shape[1] - 1).astype(I32)
    idxe = idx.reshape((idx.shape[0],) + (1,) * (arr.ndim - 1))
    return jnp.take_along_axis(arr, idxe, axis=1)[:, 0]


def _record_call_event(sf: SymFrontier, m, op, old_pc, to, to_sym, value,
                       value_sym) -> SymFrontier:
    """Append to the bounded per-tx call log (detection-module feed)."""
    CL = sf.call_to.shape[1]
    onehot = _event_slot(sf.n_calls, m, CL)
    return sf.replace(
        n_calls=sf.n_calls + m.astype(I32),
        n_mut_calls=sf.n_mut_calls + (
            m & ((op == 0xF1) | (op == 0xF2) | (op == 0xF4))
        ).astype(I32),
        call_to=jnp.where(onehot[:, :, None], to[:, None, :], sf.call_to),
        call_to_sym=jnp.where(onehot, to_sym[:, None], sf.call_to_sym),
        call_value=jnp.where(onehot[:, :, None], value[:, None, :], sf.call_value),
        call_value_sym=jnp.where(onehot, value_sym[:, None], sf.call_value_sym),
        call_op=jnp.where(onehot, op[:, None], sf.call_op),
        call_pc=jnp.where(onehot, old_pc[:, None], sf.call_pc),
        call_cid=jnp.where(onehot, sf.base.contract_id[:, None], sf.call_cid),
    )


def _h_sym_call(sf: SymFrontier, corpus: Corpus, op, m, old_pc,
                spec: SymSpec, limits: LimitsConfig) -> SymFrontier:
    """CALL / CALLCODE / DELEGATECALL / STATICCALL with real sub-frames.

    Reference: ``call_`` raising TransactionStartSignal + ``call.py``'s
    callee resolution (``mythril/laser/ethereum/{instructions,call}.py``
    ⚠unv, SURVEY.md §3.2). Three outcomes per lane:

    - **internal**: concrete callee resolving to a corpus account with
      code, concrete arg/ret windows, concrete (or absent) value, depth
      headroom → push a frame and start executing the callee at pc 0;
    - **eoa**: concrete callee that is a known codeless account → value
      transfer + success=1 (no code to run);
    - **external** (everything else: symbolic callee, unknown address,
      symbolic value/windows, depth exhausted): havoc the return value
      and output memory — the round-1 over-approximation, now the
      fallback instead of the only path.
    """
    f = sf.base
    has_value = (op == 0xF1) | (op == 0xF2)  # CALL, CALLCODE
    is_call = op == 0xF1
    is_deleg = op == 0xF4
    is_static_op = op == 0xFA
    sin = ci._J_STACK_IN[op]
    D = f.fr_ret_pc.shape[1]
    CD = f.calldata.shape[1]
    CDW = sf.cd_sym.shape[1]
    M = f.memory.shape[1]

    # --- operand fetch (gas, to, [value], argsOff, argsLen, retOff, retLen)
    to = ci._peek(f, 1)
    to_sym = _peek_sym(sf, 1)
    value = jnp.where(has_value[:, None], ci._peek(f, 2), 0).astype(U32)
    value_sym = jnp.where(has_value, _peek_sym(sf, 2), 0)
    base_i = jnp.where(has_value, 3, 2)
    a_off_w, a_off_s = ci._peek(f, base_i), _peek_sym(sf, base_i)
    a_len_w, a_len_s = ci._peek(f, base_i + 1), _peek_sym(sf, base_i + 1)
    r_off_w, r_off_s = ci._peek(f, base_i + 2), _peek_sym(sf, base_i + 2)
    r_len_w, r_len_s = ci._peek(f, base_i + 3), _peek_sym(sf, base_i + 3)
    a_off = u256.to_u64_saturating(a_off_w).astype(I64)
    a_len = u256.to_u64_saturating(a_len_w).astype(I64)
    r_off = u256.to_u64_saturating(r_off_w).astype(I64)
    r_len = u256.to_u64_saturating(r_len_w).astype(I64)

    # CALL with nonzero (possibly) value inside STATICCALL: exceptional halt
    static_viol = m & is_call & f.static & (
        (value_sym != 0) | ~u256.is_zero(value)
    )
    sf = sf.replace(base=f.trap(static_viol, Trap.STATIC_WRITE))
    f = sf.base
    m = m & ~static_viol

    # --- classification
    conc_windows = (a_off_s == 0) & (a_len_s == 0) & (r_off_s == 0) & (r_len_s == 0)
    found, slot = f.acct_lookup(to)
    callee_code = f.acct_field(f.acct_code, slot)
    value_conc = value_sym == 0
    # precompiles 0x1-0x9 (reference: natives.py dispatch in call.py ⚠unv):
    # concrete low address, concrete windows; handled without a frame.
    # Value transfers to precompile addresses are not tracked (documented).
    hi_zero = jnp.all(to[:, 1:] == 0, axis=1)
    pid = jnp.where((to_sym == 0) & hi_zero, to[:, 0].astype(I32), 0)
    RD_cap = f.returndata.shape[1]
    pre = m & (pid >= 1) & (pid <= 9) & conc_windows & (
        a_len <= min(M, PRE_IN_CAP))
    # identity output = input: if it can't fit the returndata buffer the
    # concrete result would silently truncate — demote to external havoc
    pre = pre & ~((pid == 4) & (a_len > RD_cap))
    resolvable = (
        m & (to_sym == 0) & found & conc_windows & value_conc
        & (f.depth < D) & (a_len <= CD)
    )
    internal = resolvable & (callee_code >= 0)
    eoa = resolvable & (callee_code == -1)  # CODE_UNKNOWN (-2) -> external

    # --- symbolic-callee enumeration (VERDICT r3 ask #2; reference:
    # ``call.py get_call_parameters`` resolving a symbolic callee via
    # constraints ⚠unv, SURVEY §3.2). A CALL whose target word is
    # symbolic — every proxy/registry pattern — forks ONE candidate
    # account per superstep instead of havocking: the fork copy
    # re-executes this CALL with the target stack slot concretized to
    # acct_addr[k] under the path constraint to == addr_k (expand_forks
    # flips the appended constraint sign for the copy and applies the
    # fork_cslot/fork_cval concretization); the staying lane accumulates
    # ¬(to == addr_k) and, once the table is exhausted, falls through to
    # the external-havoc path carrying "to != every known account".
    # Symbolic value / symbolic windows / exhausted depth still havoc.
    A_n = f.acct_used.shape[1]
    enumable = (
        m & (to_sym != 0) & conc_windows & value_conc
        & (f.depth < D) & (a_len <= CD)
    )
    k_cand = jnp.clip(sf.call_enum, 0, A_n - 1)
    cand_valid = sf.call_enum < A_n
    slot_used = jnp.take_along_axis(f.acct_used, k_cand[:, None], axis=1)[:, 0]
    enum_spawn = (enumable & cand_valid & slot_used
                  & (sf.con_len < sf.con_node.shape[1]))
    # a GAP in the table (e.g. a reverted create unregistered its slot)
    # advances the scan without spawning; exhausted counter (or a full
    # constraint store) resolves to the external fallback
    enum_skip = enumable & cand_valid & ~slot_used
    enum_done = enumable & ~enum_spawn & ~enum_skip
    enum_hold = enum_spawn | enum_skip
    cand_addr = f.acct_field(f.acct_addr, k_cand)
    sf, caddr_id = append_node(sf, enum_spawn, int(SymOp.CONST), 0, 0,
                               cand_addr)
    sf, eq_id = append_node(sf, enum_spawn, int(SymOp.EQ), to_sym, caddr_id)
    sf = _append_constraint(sf, enum_spawn, eq_id, False, old_pc)
    sf = sf.replace(
        call_enum=jnp.where(enum_hold, sf.call_enum + 1,
                            jnp.where(enum_done, 0, sf.call_enum)),
        fork_req=sf.fork_req | enum_spawn,
        fork_dest=jnp.where(enum_spawn, old_pc, sf.fork_dest),
        fork_cslot=jnp.where(enum_spawn, f.sp - 2, sf.fork_cslot),
        fork_cval=jnp.where(enum_spawn[:, None], cand_addr, sf.fork_cval),
    )
    f = sf.base

    # a parked lane re-executes this CALL next superstep — the prologue's
    # base charge must not accumulate once per retry
    berlin = limits.gas_schedule == "berlin"
    gmin_t = ci._J_GAS_MIN_BERLIN if berlin else ci._J_GAS_MIN
    gmax_t = ci._J_GAS_MAX_BERLIN if berlin else ci._J_GAS_MAX
    # the static table charges the worst case (value transfer + new
    # account); refine for concretely-known cases so a fully concrete
    # call has exact gas (min == max): zero value never pays the 9000
    # transfer or 25000 new-account surcharge; a nonzero transfer to an
    # EXISTING account drops the 25000
    nonzero_val = has_value & value_conc & ~u256.is_zero(value)
    zero_val = has_value & value_conc & ~nonzero_val
    refund = jnp.where(is_call & zero_val, 9000 + 25000, 0)
    # the existing-account refund needs a CONCRETE target: a symbolic
    # call's true target can be outside the table (a fresh account that
    # does pay the 25000) even when its concrete shadow matches a row
    refund = jnp.where(is_call & nonzero_val & found & (to_sym == 0),
                       25000, refund)
    refund = jnp.where((op == 0xF2) & zero_val, 9000, refund)
    # berlin: a symbolic target that exhausted enumeration resolves here
    # (external havoc) without ever paying its cold-account surcharge —
    # its true target is provably outside the (warm-trackable) table
    ext_cold = 0
    if berlin:
        from ..disassembler.opcodes import G_COLD_ACCOUNT, G_WARM_ACCESS
        ext_sym = m & ~internal & ~eoa & ~pre & ~enum_hold & (to_sym != 0)
        ext_cold = jnp.where(ext_sym, G_COLD_ACCOUNT - G_WARM_ACCESS, 0)
    f = f.replace(
        gas_min=f.gas_min - jnp.where(enum_hold, gmin_t[op], 0),
        gas_max=f.gas_max + ext_cold
        - jnp.where(enum_hold, gmax_t[op], jnp.where(m, refund, 0)),
    )
    if f.op_hist is not None:
        # iprof mirrors the gas un-charge: a parked enumeration superstep
        # is bookkeeping, not an executed instance — net out epilogue's +1
        # so only the resolving superstep counts the CALL once
        f = f.replace(op_hist=ci._hist_add(
            f.op_hist, op, -enum_hold.astype(I32)))
    sf = sf.replace(base=f)

    external = m & ~internal & ~eoa & ~pre & ~enum_hold

    # memory expansion for the arg/ret windows (charged at call time)
    f = sf.base
    f, oob_a = ci._expand_memory(f, (internal | eoa | pre) & (a_len > 0), a_off + a_len)
    f, oob_r = ci._expand_memory(f, (internal | eoa | pre) & (r_len > 0), r_off + r_len)
    sf = sf.replace(base=f)
    oob = oob_a | oob_r
    internal = internal & ~oob
    eoa = eoa & ~oob
    pre = pre & ~oob

    # --- value transfer feasibility (concrete value; payer = executing acct)
    payer_bal = f.self_balance
    wants_value = has_value & ~u256.is_zero(value)
    insufficient = (internal | eoa) & wants_value & u256.lt(payer_bal, value)
    fail0 = insufficient  # push success=0, no frame, no transfer
    internal_go = internal & ~insufficient
    eoa_ok = eoa & ~insufficient
    # CALLCODE sends value to self (net zero); only plain CALL moves funds
    transfer = (internal_go | eoa_ok) & is_call & wants_value & (slot != f.cur_acct)
    # rollback snapshot must be PRE-transfer: a reverting value call undoes
    # the transfer (reference: world-state checkpoint restore ⚠unv)
    pre_transfer_bal = f.acct_bal
    payee_bal = f.acct_field(f.acct_bal, slot)
    payer_new = u256.sub(payer_bal, value)
    payee_new = u256.add(payee_bal, value)
    A = f.acct_used.shape[1]
    payer_oh = (jnp.arange(A)[None, :] == f.cur_acct[:, None]) & transfer[:, None]
    payee_oh = (jnp.arange(A)[None, :] == slot[:, None]) & transfer[:, None]
    acct_bal = jnp.where(payer_oh[:, :, None], payer_new[:, None, :], f.acct_bal)
    acct_bal = jnp.where(payee_oh[:, :, None], payee_new[:, None, :], acct_bal)
    f = f.replace(acct_bal=acct_bal)
    # the balance table changed: BALANCE reads after this point must not
    # share leaves with reads before it
    sf = sf.replace(base=f, bal_epoch=sf.bal_epoch + transfer.astype(I32))

    # --- event record for every path (modules consume this); a lane still
    # enumerating candidate callees records nothing yet — it records when
    # it finally resolves (each fork copy re-executes and records its own)
    sf = _record_call_event(sf, m & ~enum_hold, op, old_pc, to.astype(U32),
                            to_sym, value, value_sym)
    f = sf.base

    # --- external fallback: havoc retval + output region
    havoc_mem = external & ((r_len_s != 0) | ~u256.is_zero(r_len_w))
    sf, rv = append_node(sf, external, int(SymOp.FREE), int(FreeKind.RETVAL),
                         jnp.maximum(sf.n_calls - 1, 0))
    f = sf.base

    # DELEGATECALL msg.sender symbol for a top-frame push: the CURRENT
    # transaction's CALLER leaf — keyed by tx_id like the overlay's top
    # frame reads, so the delegated code constrains the same symbol the
    # witness renders (hash-consing dedups onto the seeded tx-0 leaf)
    deleg_caller = jnp.zeros_like(to_sym)
    if spec.caller:
        need_dc = internal_go & is_deleg & (f.depth == 0)
        sf, deleg_caller = append_node(sf, need_dc, int(SymOp.FREE),
                                       int(FreeKind.CALLER), sf.tx_id)
        f = sf.base

    # --- push the result word for the non-frame paths
    dest_slot = f.sp - sin
    m_push = external | eoa_ok | fail0 | pre
    one_w = jnp.zeros_like(to).at[:, 0].set(1)
    zero_w = jnp.zeros_like(to)
    res_w = jnp.where((eoa_ok | pre)[:, None], one_w, zero_w).astype(U32)
    stack = ci._set_slot(f.stack, dest_slot, res_w, m_push)
    res_sym = jnp.where(external, rv, 0)
    stack_sym = _set_sym_slot(sf.stack_sym, dest_slot, res_sym, m_push)

    # --- frame push for internal calls
    d = f.depth
    mi = internal_go
    # EIP-150 gas forwarding: the callee runs under
    # used + min(gas operand, 63/64 * remaining); a symbolic gas operand
    # forwards the cap (all-but-one-64th). pop_frames restores the
    # caller's ceiling and, on exceptional failure, burns the whole
    # forwarded amount (a REVERT keeps only what the callee spent).
    gas_op = u256.to_u64_saturating(ci._peek(f, 0)).astype(I64)
    gas_op_sym = _peek_sym(sf, 0)
    remaining = jnp.maximum(f.gas_limit - f.gas_max, 0)
    fwd_cap = remaining - remaining // 64
    fwd = jnp.where(gas_op_sym == 0, jnp.minimum(gas_op, fwd_cap), fwd_cap)
    f2 = f.replace(
        fr_gas_limit=_fr_set(f.fr_gas_limit, d, f.gas_limit, mi),
        gas_limit=jnp.where(mi, f.gas_max + fwd, f.gas_limit),
        fr_warm_acct=_fr_set(f.fr_warm_acct, d, f.warm_acct, mi),
        fr_st_warm=_fr_set(f.fr_st_warm, d, f.st_warm, mi),
        fr_ret_pc=_fr_set(f.fr_ret_pc, d, old_pc, mi),
        fr_sp=_fr_set(f.fr_sp, d, f.sp - sin, mi),
        fr_sp_base=_fr_set(f.fr_sp_base, d, f.sp_base, mi),
        fr_static=_fr_set(f.fr_static, d, f.static, mi),
        fr_cur_acct=_fr_set(f.fr_cur_acct, d, f.cur_acct, mi),
        fr_contract_id=_fr_set(f.fr_contract_id, d, f.contract_id, mi),
        fr_caller_addr=_fr_set(f.fr_caller_addr, d, f.caller_addr, mi),
        fr_callvalue=_fr_set(f.fr_callvalue, d, f.callvalue, mi),
        fr_memory=_fr_set(f.fr_memory, d, f.memory, mi),
        fr_mem_words=_fr_set(f.fr_mem_words, d, f.mem_words, mi),
        fr_calldata=_fr_set(f.fr_calldata, d, f.calldata, mi),
        fr_calldata_len=_fr_set(f.fr_calldata_len, d, f.calldata_len, mi),
        fr_ret_off=_fr_set(f.fr_ret_off, d, r_off, mi),
        fr_ret_len=_fr_set(f.fr_ret_len, d, r_len, mi),
        fr_gas_min=_fr_set(f.fr_gas_min, d, f.gas_min, mi),
        fr_gas_max=_fr_set(f.fr_gas_max, d, f.gas_max, mi),
        fr_st_keys=_fr_set(f.fr_st_keys, d, f.st_keys, mi),
        fr_st_vals=_fr_set(f.fr_st_vals, d, f.st_vals, mi),
        fr_st_used=_fr_set(f.fr_st_used, d, f.st_used, mi),
        fr_st_written=_fr_set(f.fr_st_written, d, f.st_written, mi),
        fr_st_acct=_fr_set(f.fr_st_acct, d, f.st_acct, mi),
        fr_acct_bal=_fr_set(f.fr_acct_bal, d, pre_transfer_bal, mi),
        # ordinary call frame — not constructing an account (a stale slot
        # from a popped CREATE frame at this depth must not leak in)
        fr_create_slot=_fr_set(f.fr_create_slot, d,
                               jnp.full((f.n_lanes,), -1, dtype=I32), mi),
    )

    # callee calldata: bytes from the caller's memory window
    callee_cd = ci._gather_bytes(f.memory, a_off, CD, jnp.full_like(a_off, M))
    callee_cd = jnp.where(jnp.arange(CD)[None, :] < a_len[:, None], callee_cd, 0)
    # per-word syms: aligned windows map caller mem_sym; a partially
    # covered tail word or unaligned offset with symbolic content havocs
    # the whole frame calldata (coarse, sound)
    aligned_a = (a_off % 32) == 0
    w0 = (a_off // 32).astype(I32)
    W = sf.mem_sym.shape[1]
    wids = jnp.arange(W)[None, :]
    win_lo = (a_off // 32)[:, None]
    win_hi = ((a_off + a_len + 31) // 32)[:, None]
    any_sym_window = jnp.any(
        (wids >= win_lo) & (wids < win_hi) & (sf.mem_sym != 0), axis=1
    )
    tail_partial = (a_len % 32) != 0
    tail_sym = tail_partial & (_take_word_sym(sf.mem_sym, w0 + (a_len // 32).astype(I32)) != 0)
    cd_havoc_new = sf.mem_havoc | (~aligned_a & any_sym_window) | (aligned_a & tail_sym)
    cd_sym_new = jnp.zeros_like(sf.cd_sym)
    for w in range(CDW):
        full_cover = aligned_a & ((32 * (w + 1)) <= a_len)
        src = _take_word_sym(sf.mem_sym, w0 + w)
        cd_sym_new = cd_sym_new.at[:, w].set(
            jnp.where(mi & full_cover & ~cd_havoc_new, src, 0)
        )

    new_caller = jnp.where(is_deleg[:, None], f.caller_addr, f.self_address).astype(U32)
    new_value = jnp.where(
        is_deleg[:, None], f.callvalue,
        jnp.where(has_value[:, None], value, 0),
    ).astype(U32)
    new_value_sym = jnp.where(is_deleg, sf.callvalue_sym, 0)
    # a DELEGATECALL frame inherits the caller frame's msg.sender symbol:
    # at the top frame that is the current tx's CALLER leaf (when
    # symbolic), deeper it is whatever the frame carried — sender checks
    # inside delegated code must see the same symbol the top-frame model
    # exposes
    eff_caller_sym = sf.caller_sym
    if spec.caller:
        eff_caller_sym = jnp.where(f.depth == 0, deleg_caller, eff_caller_sym)
    new_caller_sym = jnp.where(is_deleg, eff_caller_sym, 0)
    keep_acct = is_deleg | (op == 0xF2)  # DELEGATECALL/CALLCODE keep storage ctx

    f2 = f2.replace(
        pc=jnp.where(mi, 0, f2.pc),
        # enum lanes stay parked on this CALL (one candidate per superstep)
        pc_hold=f2.pc_hold | mi | enum_hold,
        sp=jnp.where(mi | m_push, f.sp - sin + m_push.astype(I32), f2.sp),
        sp_base=jnp.where(mi, f.sp - sin, f2.sp_base),
        depth=jnp.where(mi, f.depth + 1, f2.depth),
        contract_id=jnp.where(mi, callee_code, f2.contract_id),
        cur_acct=jnp.where(mi, jnp.where(keep_acct, f.cur_acct, slot), f2.cur_acct),
        caller_addr=jnp.where(mi[:, None], new_caller, f2.caller_addr),
        callvalue=jnp.where(mi[:, None], new_value, f2.callvalue),
        static=f2.static | (mi & is_static_op),
        memory=jnp.where(mi[:, None], 0, f2.memory),
        mem_words=jnp.where(mi, 0, f2.mem_words),
        calldata=jnp.where(mi[:, None], callee_cd, f2.calldata),
        calldata_len=jnp.where(mi, jnp.clip(a_len, 0, CD).astype(I32), f2.calldata_len),
        returndata_len=jnp.where(mi | m_push, 0, f2.returndata_len),
        stack=stack,
    )
    sf = sf.replace(
        base=f2,
        stack_sym=stack_sym,
        mem_sym=jnp.where(mi[:, None], 0, sf.mem_sym),
        mem_havoc=jnp.where(mi, False, sf.mem_havoc | havoc_mem),
        retdata_sym=jnp.where(mi | eoa_ok | fail0, False,
                              sf.retdata_sym | external),
        cd_from_mem=sf.cd_from_mem | mi,
        cd_havoc=jnp.where(mi, cd_havoc_new, sf.cd_havoc),
        cd_sym=jnp.where(mi[:, None], cd_sym_new, sf.cd_sym),
        callvalue_sym=jnp.where(mi, new_value_sym, sf.callvalue_sym),
        caller_sym=jnp.where(mi, new_caller_sym, sf.caller_sym),
        fr_caller_sym=_fr_set(sf.fr_caller_sym, d, sf.caller_sym, mi),
        fr_mem_sym=_fr_set(sf.fr_mem_sym, d, sf.mem_sym, mi),
        fr_mem_havoc=_fr_set(sf.fr_mem_havoc, d, sf.mem_havoc, mi),
        fr_cd_from_mem=_fr_set(sf.fr_cd_from_mem, d, sf.cd_from_mem, mi),
        fr_cd_havoc=_fr_set(sf.fr_cd_havoc, d, sf.cd_havoc, mi),
        fr_cd_sym=_fr_set(sf.fr_cd_sym, d, sf.cd_sym, mi),
        fr_callvalue_sym=_fr_set(sf.fr_callvalue_sym, d, sf.callvalue_sym, mi),
        fr_st_val_sym=_fr_set(sf.fr_st_val_sym, d, sf.st_val_sym, mi),
        fr_st_key_sym=_fr_set(sf.fr_st_key_sym, d, sf.st_key_sym, mi),
        fr_st_seq=_fr_set(sf.fr_st_seq, d, sf.st_seq, mi),
    )
    # precompile outputs land after the common bookkeeping so they can
    # override the pushed-result defaults for their lanes
    return lax.cond(
        jnp.any(pre),
        lambda s: _apply_precompiles(s, pre, pid, a_off, a_len, r_off, r_len,
                                     spec),
        lambda s: s,
        sf,
    )


CREATE_ADDR_BASE = 0xC0DE00000000  # fresh pseudo-addresses for CREATE results


PRE_IN_CAP = 448  # precompile input window cap (modexp header + 3x32-byte
# operands = 192; a 2-pair ECPAIRING check — the common signature-verify
# shape — is 384; sha256/identity accept up to this; longer inputs fall
# to the external-havoc path, counted like any unresolved call)


def _be_window_word(buf, start, width, INW: int):
    """u256 word from `width[P]` big-endian bytes at `start[P]` of buf[P,INW]
    (right-aligned: value = int.from_bytes(buf[start:start+width]))."""
    I = jnp.int64
    s = start.astype(I) + width.astype(I) - 32
    raw = ci._gather_bytes(buf, s, 32, jnp.full_like(s, INW))
    k = jnp.arange(32)[None, :]
    valid = (s[:, None] + k) >= start[:, None].astype(I)
    return ci._be_bytes_to_word(jnp.where(valid, raw, 0))


def _apply_precompiles(sf: SymFrontier, pre, pid, a_off, a_len, r_off,
                       r_len, spec: SymSpec = SymSpec()) -> SymFrontier:
    """Execute precompile calls 0x1-0x9 for the `pre` lanes.

    Reference: ``mythril/laser/ethereum/natives.py`` (⚠unv) — all nine
    computed concretely there; same here:

    - 0x2 sha256: device kernel on concrete input;
    - 0x4 identity: byte copy;
    - 0x5 modexp: device square-and-multiply for <= 32-byte operands;
    - 0x1 ecrecover: host callback (ops/secp256k1) on concrete input,
      uninterpreted ECRECOVER leaf per call site otherwise;
    - 0x3 ripemd160, 0x6/0x7/0x8 alt_bn128 add/mul/pairing, 0x9 blake2f:
      one batched host callback (ops/natives_host) on concrete input.
      A malformed input (off-curve point, bad blake2f length/flag) FAILS
      the call: success word rewritten to 0, empty returndata — the one
      precompile-failure channel the EVM has. A blake2f rounds word past
      ``BLAKE2F_MAX_ROUNDS`` falls to the sound havoc leaf instead of
      stalling the host (DoS fence, documented there).

    Symbolic input bytes demote every concrete case to the leaf path.
    Gas: per-native schedules charged below (modexp the EIP-2565 floor
    only — its input-dependent formula is not modeled, documented).
    """
    f = sf.base
    P, M = f.memory.shape
    RD = f.returndata.shape[1]
    INW = min(M, PRE_IN_CAP)  # static input gather width (pre <= this)
    W = sf.mem_sym.shape[1]

    wids = jnp.arange(W)[None, :]
    win_lo = (a_off // 32)[:, None]
    win_hi = ((a_off + a_len + 31) // 32)[:, None]
    sym_in = (sf.mem_havoc | jnp.any(
        (wids >= win_lo) & (wids < win_hi) & (sf.mem_sym != 0), axis=1
    )) & (a_len > 0)

    inp = ci._gather_bytes(f.memory, a_off, INW, jnp.full_like(a_off, M))
    inp = jnp.where(jnp.arange(INW)[None, :] < a_len[:, None], inp, 0)

    conc = pre & ~sym_in
    # trace-time capability gate: the axon runtime has no host callbacks
    # (ops/callbacks.py) — without them, concrete ecrecover and the
    # ripemd/bn128/blake2f natives degrade to the sound leaf path
    from ..ops.callbacks import host_callbacks_supported
    cb_ok = host_callbacks_supported()
    m_sha = conc & (pid == 2)
    m_id = conc & (pid == 4)
    m_ecr = conc & (pid == 1) & cb_ok

    # modexp header: three 32-byte big-endian lengths
    blen = u256.to_u64_saturating(ci._be_bytes_to_word(inp[:, 0:32])).astype(I64)
    elen = u256.to_u64_saturating(ci._be_bytes_to_word(inp[:, 32:64])).astype(I64)
    mlen = u256.to_u64_saturating(ci._be_bytes_to_word(inp[:, 64:96])).astype(I64)
    # the u64->i64 cast can wrap huge headers negative — a negative length
    # must NOT pass the <=32 window check (it would read garbage offsets)
    fits = ((blen >= 0) & (blen <= 32) & (elen >= 0) & (elen <= 32)
            & (mlen >= 0) & (mlen <= 32)
            & (96 + blen + elen + mlen <= a_len))
    m_mod = conc & (pid == 5) & fits
    # blake2f rounds word (first 4 input bytes, big-endian) read on device
    # so an attacker-size rounds count routes to the leaf, not the host
    rounds = ((inp[:, 0].astype(I64) << 24) | (inp[:, 1].astype(I64) << 16)
              | (inp[:, 2].astype(I64) << 8) | inp[:, 3].astype(I64))
    from ..ops.natives_host import BLAKE2F_MAX_ROUNDS
    m_host = conc & cb_ok & (
        (pid == 3) | (pid == 6) | (pid == 7) | (pid == 8)
        | ((pid == 9) & (rounds <= BLAKE2F_MAX_ROUNDS))
    )
    if RD < 64:  # tiny test shapes: no room for the 64-byte outputs
        m_host = m_host & (pid == 3)
    m_leaf = pre & ~m_sha & ~m_id & ~m_mod & ~m_ecr & ~m_host

    # concrete ecrecover via host callback (VERDICT r3 weak #6; reference
    # uses libsecp256k1 ⚠unv — here ops/secp256k1, pure Python, memoized).
    # Invalid signatures return EMPTY output, exactly like the precompile.
    def _host_ecr(inp_np, mask_np):
        import numpy as np

        from ..ops.secp256k1 import ecrecover_batch

        res = np.zeros((inp_np.shape[0], 32), dtype=np.uint8)
        ok = np.zeros(inp_np.shape[0], dtype=bool)
        idx = np.where(mask_np)[0]
        for i, addr in zip(idx, ecrecover_batch(inp_np[idx, :128])):
            if addr is not None:
                res[i] = np.frombuffer(addr.to_bytes(32, "big"), np.uint8)
                ok[i] = True
        return res, ok

    # ripemd160 / bn128 / blake2f: one batched host callback (rare path,
    # gated like ecrecover). ok=False = the precompile call itself fails.
    def _host_nat(inp_np, pid_np, alen_np, mask_np):
        from ..ops.natives_host import natives_batch

        return natives_batch(inp_np, pid_np, alen_np, mask_np)

    def _cb_local(inp_l, m_ecr_l, pid_l, a_len_l, m_host_l):
        """Both precompile callbacks over a (shard-)local lane block.

        Under shard_map each device round-trips only its own lanes (and
        the per-shard ``any`` gate skips the host hop entirely on shards
        with no precompile lane); without a mesh this is the whole
        frontier, identical to the pre-round-5 single-device behavior.
        """
        Pl = inp_l.shape[0]

        def _run_ecr(_):
            return jax.pure_callback(
                _host_ecr,
                (jax.ShapeDtypeStruct((Pl, 32), jnp.uint8),
                 jax.ShapeDtypeStruct((Pl,), jnp.bool_)),
                inp_l, m_ecr_l,
            )

        ecr_b, ecr_k = lax.cond(
            jnp.any(m_ecr_l), _run_ecr,
            lambda _: (jnp.zeros((Pl, 32), dtype=jnp.uint8),
                       jnp.zeros((Pl,), dtype=jnp.bool_)),
            0,
        )

        def _run_nat(_):
            return jax.pure_callback(
                _host_nat,
                (jax.ShapeDtypeStruct((Pl, 64), jnp.uint8),
                 jax.ShapeDtypeStruct((Pl,), jnp.int32),
                 jax.ShapeDtypeStruct((Pl,), jnp.bool_)),
                inp_l, pid_l, a_len_l, m_host_l,
            )

        nat_b, nat_n, nat_k = lax.cond(
            jnp.any(m_host_l), _run_nat,
            lambda _: (jnp.zeros((Pl, 64), dtype=jnp.uint8),
                       jnp.zeros((Pl,), dtype=jnp.int32),
                       jnp.zeros((Pl,), dtype=jnp.bool_)),
            0,
        )
        return ecr_b, ecr_k, nat_b, nat_n, nat_k

    # `if cb_ok` (a trace-time Python bool) keeps the callback custom-call
    # OUT of the traced program entirely on runtimes that reject it —
    # even an un-taken cond branch containing it fails axon compilation
    if cb_ok:
        if spec.mesh is not None:
            from jax.sharding import PartitionSpec as _PS
            lane = _PS(spec.lane_axis)
            lane2 = _PS(spec.lane_axis, None)
            ecr_bytes, ecr_ok, nat_bytes, nat_len, nat_ok = jax.shard_map(
                _cb_local, mesh=spec.mesh,
                in_specs=(lane2, lane, lane, lane, lane),
                out_specs=(lane2, lane, lane2, lane, lane),
                check_vma=False,
            )(inp, m_ecr, pid, a_len, m_host)
        else:
            ecr_bytes, ecr_ok, nat_bytes, nat_len, nat_ok = _cb_local(
                inp, m_ecr, pid, a_len, m_host)
    else:
        ecr_bytes = jnp.zeros((P, 32), dtype=jnp.uint8)
        ecr_ok = jnp.zeros((P,), dtype=jnp.bool_)
        nat_bytes = jnp.zeros((P, 64), dtype=jnp.uint8)
        nat_len = jnp.zeros((P,), dtype=jnp.int32)
        nat_ok = jnp.zeros((P,), dtype=jnp.bool_)
    m_hok = m_host & nat_ok
    m_hfail = m_host & ~nat_ok

    from ..ops.sha256 import sha256_device
    sha_w = lax.cond(
        jnp.any(m_sha),
        lambda: sha256_device(inp, jnp.clip(a_len, 0, INW).astype(I32)),
        lambda: jnp.zeros((P, 8), dtype=U32),
    )
    mod_w = lax.cond(
        jnp.any(m_mod),
        lambda: u256.modexp(
            _be_window_word(inp, jnp.full_like(blen, 96), blen, INW),
            _be_window_word(inp, 96 + blen, elen, INW),
            _be_window_word(inp, 96 + blen + elen, mlen, INW),
        ),
        lambda: jnp.zeros((P, 8), dtype=U32),
    )

    # precompile gas (reference: natives.py per-native schedules ⚠unv);
    # modexp charges the EIP-2565 floor — its full input-dependent
    # formula is not modeled (documented); pairing is the EIP-1108
    # per-pair schedule; blake2f charges its concrete rounds word
    words = (a_len + 31) // 32
    pcost = jnp.select(
        [pid == 1, pid == 2, pid == 3, pid == 4, pid == 5,
         pid == 6, pid == 7, pid == 8, pid == 9],
        [3000, 60 + 12 * words, 600 + 120 * words, 15 + 3 * words,
         jnp.full_like(words, 200), jnp.full_like(words, 150),
         jnp.full_like(words, 6000), 45000 + 34000 * (a_len // 192),
         rounds],
        default=jnp.zeros_like(words),
    )
    f = ci._charge(f, pre, pcost)
    sf = sf.replace(base=f)

    # leaf result node (hash-consed per call site via the call index)
    kind = jnp.where(pid == 1, int(FreeKind.ECRECOVER), int(FreeKind.PRECOMPILE))
    sf, leaf = append_node(sf, m_leaf, int(SymOp.FREE), kind,
                           jnp.maximum(sf.n_calls - 1, 0))
    f = sf.base

    # output byte image (concrete cases) + logical output length
    out_len = jnp.where(pid == 4, jnp.minimum(a_len, RD),
                        jnp.where(pid == 5, mlen,
                                  jnp.where((pid == 6) | (pid == 7) | (pid == 9),
                                            64, 32))).astype(I64)
    out_len = jnp.where(m_ecr, jnp.where(ecr_ok, 32, 0), out_len)
    out_len = jnp.where(m_host, jnp.where(nat_ok, nat_len, 0).astype(I64),
                        out_len)
    out = jnp.where(m_id[:, None], inp[:, :RD] if INW >= RD else
                    jnp.pad(inp, ((0, 0), (0, RD - INW))), 0).astype(jnp.uint8)
    sha_bytes = ci._word_to_be_bytes(sha_w)  # u8[P,32]
    mod_be = ci._word_to_be_bytes(mod_w)
    # modexp output is the result right-aligned in mlen bytes
    kk = jnp.arange(RD, dtype=I64)[None, :]
    mod_src = jnp.clip(32 - mlen[:, None] + kk, 0, 31).astype(I32)
    mod_bytes = jnp.take_along_axis(
        jnp.pad(mod_be, ((0, 0), (0, max(0, RD - 32)))),
        jnp.minimum(mod_src, 31), axis=1)
    head = kk < 32
    out = jnp.where((m_sha[:, None] & head),
                    jnp.pad(sha_bytes, ((0, 0), (0, max(0, RD - 32)))), out)
    out = jnp.where(m_mod[:, None] & (kk < mlen[:, None]), mod_bytes, out)
    out = jnp.where((m_ecr & ecr_ok)[:, None] & head,
                    jnp.pad(ecr_bytes, ((0, 0), (0, max(0, RD - 32)))), out)
    nat_pad = (jnp.pad(nat_bytes, ((0, 0), (0, RD - 64))) if RD >= 64
               else nat_bytes[:, :RD])
    out = jnp.where(m_hok[:, None] & (kk < nat_len[:, None].astype(I64)),
                    nat_pad, out)

    # returndata buffer + memory window write
    conc_res = m_sha | m_id | m_mod | m_ecr | m_hok
    n_out = jnp.clip(out_len, 0, RD).astype(I32)
    returndata = jnp.where(pre[:, None], out, f.returndata)
    returndata = jnp.where(
        pre[:, None] & (jnp.arange(RD)[None, :] >= n_out[:, None]), 0, returndata
    ).astype(jnp.uint8)
    n_mem = jnp.minimum(out_len, r_len)
    jpos = jnp.arange(M, dtype=I64)[None, :]
    in_win = (jpos >= r_off[:, None]) & (jpos < (r_off + n_mem)[:, None])
    src = ci._take_per_lane(out, jpos - r_off[:, None], n_mem)
    memory = jnp.where(in_win & conc_res[:, None], src, f.memory).astype(jnp.uint8)

    # sym overlay of the output window: concrete results clear covered
    # words (edge words with stale syms -> havoc); leaf results plant the
    # leaf on a single aligned word, anything wider/unaligned havocs
    full_lo = ((r_off + 31) // 32)[:, None]
    full_hi = ((r_off + n_mem) // 32)[:, None]
    covered = (wids >= full_lo) & (wids < full_hi) & conc_res[:, None]
    mem_sym = jnp.where(covered, 0, sf.mem_sym)
    edge = (((wids == (r_off // 32)[:, None]) | (wids == full_hi))
            & ~covered & conc_res[:, None] & (n_mem[:, None] > 0))
    edge_dirty = jnp.any(edge & (sf.mem_sym != 0), axis=1)
    leaf_word_ok = m_leaf & ((r_off % 32) == 0) & (r_len >= 32) & (out_len == 32)
    mem_sym = _set_word_sym(mem_sym, (r_off // 32).astype(I32), leaf, leaf_word_ok)
    mem_havoc = sf.mem_havoc | (conc_res & edge_dirty) | (
        m_leaf & (r_len > 0) & ~leaf_word_ok
    )

    # a malformed input FAILS the call: the success word the caller
    # pushed (top of stack after the sp update) is rewritten to 0
    stack = ci._set_slot(f.stack, f.sp - 1,
                         jnp.zeros((P, 8), dtype=U32), m_hfail)

    return sf.replace(
        base=f.replace(memory=memory, returndata=returndata, stack=stack,
                       returndata_len=jnp.where(pre, n_out, f.returndata_len)),
        mem_sym=mem_sym,
        mem_havoc=mem_havoc,
        retdata_sym=jnp.where(pre, m_leaf, sf.retdata_sym),
    )


def _init_jumpdest_scan(code, length):
    """Jumpdest map of a per-lane code buffer u8[P, IC]: a byte is a valid
    JUMPDEST iff it is 0x5B and not inside a PUSH immediate. Sequential
    push-width skip via fori_loop (runs only under the CREATE cond)."""
    P, IC = code.shape

    def body(i, carry):
        skip, jd = carry
        b = code[:, i].astype(I32)
        live = i < length
        is_jd = (skip == 0) & (b == 0x5B) & live
        jd = jd.at[:, i].set(is_jd)
        push_w = jnp.where((skip == 0) & (b >= 0x60) & (b <= 0x7F),
                           b - 0x5F, 0)
        skip = jnp.maximum(skip - 1, 0) + push_w
        return skip, jd

    _, jd = lax.fori_loop(
        0, IC, body, (jnp.zeros(P, dtype=I32), jnp.zeros((P, IC), dtype=bool))
    )
    return jd


def _h_sym_create(sf: SymFrontier, op, m, old_pc) -> SymFrontier:
    """CREATE/CREATE2: run the init code in a real sub-frame.

    Reference: ``create_`` spawning a ContractCreationTransaction
    (``mythril/laser/ethereum/instructions.py`` + ``transaction/`` ⚠unv,
    SURVEY.md §2 "Transaction models"). A lane whose init window is
    concrete (bytes, offset, length, value — and salt for CREATE2) pushes
    a frame that EXECUTES the init code from a per-lane buffer: storage
    writes land on the fresh account (``cur_acct`` = the new slot),
    RETURN's payload is the deployed runtime image (matched against the
    corpus at pop — see ``pop_frames``), REVERT rolls back storage,
    balance and the account registration. CREATE2 addresses use the real
    keccak identity (0xff ++ deployer ++ salt ++ keccak(init)); plain
    CREATE addresses are deterministic fresh values (RLP-nonce addressing
    not modeled). Fallback (symbolic window/value/salt, init too long,
    nested constructor, no table/frame headroom): the round-3 behavior —
    register a fresh CODE_UNKNOWN account, push its address, skip the
    constructor (documented over-approximation).
    """
    f = sf.base
    P = f.n_lanes
    static_viol = m & f.static
    sf = sf.replace(base=f.trap(static_viol, Trap.STATIC_WRITE))
    f = sf.base
    m = m & ~static_viol
    sin = ci._J_STACK_IN[op]
    is_c2 = op == 0xF5
    value = ci._peek(f, 0)
    value_sym = _peek_sym(sf, 0)
    off = u256.to_u64_saturating(ci._peek(f, 1)).astype(I64)
    ln = u256.to_u64_saturating(ci._peek(f, 2)).astype(I64)
    off_s, ln_s = _peek_sym(sf, 1), _peek_sym(sf, 2)
    salt = jnp.where(is_c2[:, None], ci._peek(f, 3), 0).astype(U32)
    salt_sym = jnp.where(is_c2, _peek_sym(sf, 3), 0)
    f, _ = ci._expand_memory(f, m & (ln > 0), off + ln)
    sf = sf.replace(base=f)
    sf = _record_call_event(sf, m, op, old_pc, jnp.zeros_like(value).astype(U32),
                            jnp.zeros_like(value_sym), value.astype(U32), value_sym)
    f = sf.base

    # concrete-value feasibility (symbolic value: no transfer modeled, the
    # fresh address is still pushed — the RETVAL of a create is its address)
    value_conc = value_sym == 0
    wants = m & value_conc & ~u256.is_zero(value)
    payer_bal = f.self_balance
    insufficient = wants & u256.lt(payer_bal, value)
    ok = m & ~insufficient

    # register the new account in a free slot; a full table just skips
    # registration (the pushed address then resolves nowhere -> external)
    A = f.acct_used.shape[1]
    free = ~f.acct_used
    has_free = jnp.any(free, axis=1)
    slot = jnp.argmax(free, axis=1).astype(I32)
    reg = ok & has_free
    addr_w = u256.from_u64_scalar(
        jnp.uint64(CREATE_ADDR_BASE) + sf.create_cnt.astype(jnp.uint64))
    sidx = jnp.where(reg, slot, A)
    acct_addr = ci._write_slot(f.acct_addr, sidx, addr_w)
    init_bal = jnp.where((wants & ~insufficient)[:, None], value, 0).astype(U32)
    acct_bal = ci._write_slot(f.acct_bal, sidx, init_bal)
    # CODE_UNKNOWN, not EOA: the created contract HAS code (the init
    # code's dynamic result) — calls must havoc, never succeed concretely
    acct_code = ci._write_slot(f.acct_code, sidx, CODE_UNKNOWN)
    acct_used = ci._write_slot(f.acct_used, sidx, True)
    # deduct the payer (only when the endowment actually moved)
    pay_idx = jnp.where(reg & wants, f.cur_acct, A)
    acct_bal = ci._write_slot(acct_bal, pay_idx, u256.sub(payer_bal, value))

    # --- frame-execution eligibility (VERDICT r3 ask #2): registered,
    # concrete window whose bytes carry no symbolic overlay, init fits the
    # buffer, frame + no nested constructor, concrete salt
    IC = f.init_code.shape[1]
    D = f.fr_ret_pc.shape[1]
    W = sf.mem_sym.shape[1]
    wids = jnp.arange(W)[None, :]
    win_sym = (sf.mem_havoc | jnp.any(
        (wids >= (off // 32)[:, None])
        & (wids < ((off + ln + 31) // 32)[:, None])
        & (sf.mem_sym != 0), axis=1
    )) & (ln > 0)
    want_frame = (
        reg & (off_s == 0) & (ln_s == 0) & (salt_sym == 0) & ~win_sym
        & (ln > 0) & (ln <= IC) & (f.depth < D) & (f.init_depth == 0)
    )

    dest_slot = f.sp - sin
    m_push = m & ~want_frame  # frame lanes get their result at pop_frames
    res_w = jnp.where(ok[:, None], addr_w, 0).astype(U32)
    stack = ci._set_slot(f.stack, dest_slot, res_w, m_push)
    sf = sf.replace(
        base=f.replace(
            stack=stack,
            sp=jnp.where(m_push, f.sp - sin + 1, f.sp),
            returndata_len=jnp.where(m_push, 0, f.returndata_len),
            acct_addr=acct_addr, acct_bal=acct_bal,
            acct_code=acct_code, acct_used=acct_used,
        ),
        stack_sym=_set_sym_slot(sf.stack_sym, dest_slot,
                                jnp.zeros((P,), I32), m_push),
        retdata_sym=jnp.where(m_push, False, sf.retdata_sym),
        create_cnt=sf.create_cnt + m.astype(I32),
        bal_epoch=sf.bal_epoch + (reg & wants).astype(I32),
    )
    return lax.cond(
        jnp.any(want_frame),
        lambda s: _push_create_frame(s, want_frame, is_c2, slot, sin, off, ln,
                                     salt, value, old_pc,
                                     pre_transfer_bal=f.acct_bal),
        lambda s: s,
        sf,
    )


def _push_create_frame(sf: SymFrontier, mi, is_c2, slot, sin, off, ln, salt,
                       value, old_pc, pre_transfer_bal) -> SymFrontier:
    """Push the constructor frame for ``mi`` lanes (under the CREATE cond).

    The child executes the init bytes copied from the caller's memory
    (``exec_init`` fetch override), with ``cur_acct`` = the new account
    slot so SSTOREs persist on the child, empty calldata, and the
    endowment as callvalue. CREATE2 lanes overwrite the registered fresh
    address with the real keccak identity."""
    f = sf.base
    P, M = f.memory.shape
    IC = f.init_code.shape[1]
    d = f.depth

    init_code = ci._gather_bytes(f.memory, off, IC, jnp.full_like(off, M))
    init_code = jnp.where(jnp.arange(IC)[None, :] < ln[:, None], init_code, 0)
    init_code = jnp.where(mi[:, None], init_code, f.init_code).astype(jnp.uint8)
    init_jd = jnp.where(mi[:, None], _init_jumpdest_scan(init_code, ln.astype(I32)),
                        f.init_jd)

    # CREATE2: addr = keccak(0xff ++ deployer[20] ++ salt[32] ++ keccak(init))[12:]
    from ..ops.keccak import keccak256_device
    inner = keccak256_device(init_code, jnp.clip(ln, 0, IC).astype(I32))
    self_be = ci._word_to_be_bytes(f.self_address)      # u8[P,32]
    salt_be = ci._word_to_be_bytes(salt)
    inner_be = ci._word_to_be_bytes(inner)
    buf = jnp.concatenate(
        [jnp.full((P, 1), 0xFF, dtype=jnp.uint8), self_be[:, 12:32],
         salt_be, inner_be], axis=1)                     # u8[P,85]
    c2_addr = keccak256_device(buf, jnp.full(P, 85, dtype=I32))
    c2_addr = c2_addr.at[:, 5:].set(0)                   # low 160 bits
    do_c2 = mi & is_c2
    aidx = jnp.where(do_c2, slot, f.acct_used.shape[1])
    acct_addr = ci._write_slot(f.acct_addr, aidx, c2_addr)

    # CREATE forwards all-but-one-64th (EIP-150; no gas operand)
    remaining = jnp.maximum(f.gas_limit - f.gas_max, 0)
    fwd = remaining - remaining // 64
    f2 = f.replace(
        acct_addr=acct_addr,
        fr_gas_limit=_fr_set(f.fr_gas_limit, d, f.gas_limit, mi),
        gas_limit=jnp.where(mi, f.gas_max + fwd, f.gas_limit),
        fr_warm_acct=_fr_set(f.fr_warm_acct, d, f.warm_acct, mi),
        fr_st_warm=_fr_set(f.fr_st_warm, d, f.st_warm, mi),
        fr_ret_pc=_fr_set(f.fr_ret_pc, d, old_pc, mi),
        fr_sp=_fr_set(f.fr_sp, d, f.sp - sin, mi),
        fr_sp_base=_fr_set(f.fr_sp_base, d, f.sp_base, mi),
        fr_static=_fr_set(f.fr_static, d, f.static, mi),
        fr_cur_acct=_fr_set(f.fr_cur_acct, d, f.cur_acct, mi),
        fr_contract_id=_fr_set(f.fr_contract_id, d, f.contract_id, mi),
        fr_caller_addr=_fr_set(f.fr_caller_addr, d, f.caller_addr, mi),
        fr_callvalue=_fr_set(f.fr_callvalue, d, f.callvalue, mi),
        fr_memory=_fr_set(f.fr_memory, d, f.memory, mi),
        fr_mem_words=_fr_set(f.fr_mem_words, d, f.mem_words, mi),
        fr_calldata=_fr_set(f.fr_calldata, d, f.calldata, mi),
        fr_calldata_len=_fr_set(f.fr_calldata_len, d, f.calldata_len, mi),
        fr_ret_off=_fr_set(f.fr_ret_off, d, jnp.zeros_like(off), mi),
        fr_ret_len=_fr_set(f.fr_ret_len, d, jnp.zeros_like(ln), mi),
        fr_gas_min=_fr_set(f.fr_gas_min, d, f.gas_min, mi),
        fr_gas_max=_fr_set(f.fr_gas_max, d, f.gas_max, mi),
        fr_st_keys=_fr_set(f.fr_st_keys, d, f.st_keys, mi),
        fr_st_vals=_fr_set(f.fr_st_vals, d, f.st_vals, mi),
        fr_st_used=_fr_set(f.fr_st_used, d, f.st_used, mi),
        fr_st_written=_fr_set(f.fr_st_written, d, f.st_written, mi),
        fr_st_acct=_fr_set(f.fr_st_acct, d, f.st_acct, mi),
        fr_acct_bal=_fr_set(f.fr_acct_bal, d, pre_transfer_bal, mi),
        fr_create_slot=_fr_set(f.fr_create_slot, d, slot, mi),
    )
    f2 = f2.replace(
        pc=jnp.where(mi, 0, f2.pc),
        pc_hold=f2.pc_hold | mi,
        sp=jnp.where(mi, f.sp - sin, f2.sp),
        sp_base=jnp.where(mi, f.sp - sin, f2.sp_base),
        depth=jnp.where(mi, f.depth + 1, f2.depth),
        cur_acct=jnp.where(mi, slot, f2.cur_acct),
        caller_addr=jnp.where(mi[:, None], f.self_address, f2.caller_addr),
        callvalue=jnp.where(mi[:, None], value, f2.callvalue).astype(U32),
        memory=jnp.where(mi[:, None], 0, f2.memory),
        mem_words=jnp.where(mi, 0, f2.mem_words),
        calldata=jnp.where(mi[:, None], 0, f2.calldata),
        calldata_len=jnp.where(mi, 0, f2.calldata_len),
        returndata_len=jnp.where(mi, 0, f2.returndata_len),
        init_code=init_code,
        init_len=jnp.where(mi, ln.astype(I32), f.init_len),
        init_jd=init_jd,
        init_depth=jnp.where(mi, f.depth + 1, f.init_depth),
    )
    return sf.replace(
        base=f2,
        mem_sym=jnp.where(mi[:, None], 0, sf.mem_sym),
        mem_havoc=jnp.where(mi, False, sf.mem_havoc),
        cd_from_mem=sf.cd_from_mem | mi,
        cd_havoc=jnp.where(mi, False, sf.cd_havoc),
        cd_sym=jnp.where(mi[:, None], 0, sf.cd_sym),
        callvalue_sym=jnp.where(mi, 0, sf.callvalue_sym),
        caller_sym=jnp.where(mi, 0, sf.caller_sym),
        fr_caller_sym=_fr_set(sf.fr_caller_sym, d, sf.caller_sym, mi),
        fr_mem_sym=_fr_set(sf.fr_mem_sym, d, sf.mem_sym, mi),
        fr_mem_havoc=_fr_set(sf.fr_mem_havoc, d, sf.mem_havoc, mi),
        fr_cd_from_mem=_fr_set(sf.fr_cd_from_mem, d, sf.cd_from_mem, mi),
        fr_cd_havoc=_fr_set(sf.fr_cd_havoc, d, sf.cd_havoc, mi),
        fr_cd_sym=_fr_set(sf.fr_cd_sym, d, sf.cd_sym, mi),
        fr_callvalue_sym=_fr_set(sf.fr_callvalue_sym, d, sf.callvalue_sym, mi),
        fr_st_val_sym=_fr_set(sf.fr_st_val_sym, d, sf.st_val_sym, mi),
        fr_st_key_sym=_fr_set(sf.fr_st_key_sym, d, sf.st_key_sym, mi),
        fr_st_seq=_fr_set(sf.fr_st_seq, d, sf.st_seq, mi),
    )


def pop_frames(sf: SymFrontier, corpus: Corpus) -> SymFrontier:
    """Return control to the caller for every lane whose sub-frame ended.

    Reference: ``TransactionEndSignal`` handling in ``LaserEVM.exec`` —
    ``end_message_call`` restores the caller state and pushes the call's
    success flag (⚠unv, SURVEY.md §3.2). Genuine EVM halts inside the
    callee (revert, invalid, bad jump, OOG, stack) become success=0 with
    storage/balance rollback; engine-capacity traps kill the whole lane
    (the cap is an artifact, not an EVM outcome — counted in coverage).
    """
    f = sf.base
    ended = f.active & (f.depth > 0) & (f.halted | f.error)
    is_kill = jnp.zeros_like(f.error)
    for c in KILL_TRAPS:
        is_kill = is_kill | (f.err_code == c)
    mp = ended & ~(f.error & is_kill)
    success = mp & f.halted & ~f.reverted & ~f.error
    fail = mp & (f.error | f.reverted)
    d = jnp.maximum(f.depth - 1, 0)
    # constructor frames: fr_create_slot >= 0 marks the account being built
    cslot = _fr_get(f.fr_create_slot, d)
    is_initp = mp & (cslot >= 0)

    ret_pc = _fr_get(f.fr_ret_pc, d)
    csp = _fr_get(f.fr_sp, d)
    r_off = _fr_get(f.fr_ret_off, d)
    r_len = _fr_get(f.fr_ret_len, d)

    # caller memory restore + returndata write (REVERT carries data too;
    # an exceptional halt returns nothing)
    has_rd = mp & ~f.error
    memory = jnp.where(mp[:, None], _fr_get(f.fr_memory, d), f.memory)
    n_rd = jnp.minimum(r_len, f.retval_len.astype(I64))
    P, M = f.memory.shape
    jpos = jnp.arange(M, dtype=I64)[None, :]
    in_win = (jpos >= r_off[:, None]) & (jpos < (r_off + n_rd)[:, None])
    src = ci._take_per_lane(
        f.retval, jpos - r_off[:, None], n_rd
    )
    memory = jnp.where(in_win & has_rd[:, None], src, memory).astype(jnp.uint8)

    # sym overlay: restore caller's, then map the returndata words
    mem_sym = jnp.where(mp[:, None], _fr_get(sf.fr_mem_sym, d), sf.mem_sym)
    mem_havoc = jnp.where(mp, _fr_get(sf.fr_mem_havoc, d), sf.mem_havoc)
    roff_al = (r_off % 32) == 0
    RDW = sf.rv_sym.shape[1]
    rv_words_sym = jnp.any(
        (jnp.arange(RDW)[None, :] * 32 < n_rd[:, None]) & (sf.rv_sym != 0), axis=1
    )
    rv_unknown = sf.rv_havoc | rv_words_sym
    # aligned full words map exactly; anything messier havocs coarse
    clean_map = has_rd & roff_al & ~sf.rv_havoc
    for k in range(RDW):
        full = (32 * (k + 1)) <= n_rd
        mem_sym = _set_word_sym(
            mem_sym, (r_off // 32).astype(I32) + k,
            sf.rv_sym[:, k], clean_map & full,
        )
    tail_sym_rd = ((n_rd % 32) != 0) & jnp.any(
        (jnp.arange(RDW)[None, :] == (n_rd // 32)[:, None]) & (sf.rv_sym != 0),
        axis=1,
    )
    mem_havoc = mem_havoc | (has_rd & (
        (sf.rv_havoc & (r_len > 0)) | (~roff_al & rv_words_sym)
        | (roff_al & tail_sym_rd)
    ))

    # storage + balance rollback on failure
    def roll(cur, snap):
        sel = fail.reshape((P,) + (1,) * (cur.ndim - 1))
        return jnp.where(sel, snap, cur)

    st_keys = roll(f.st_keys, _fr_get(f.fr_st_keys, d))
    st_vals = roll(f.st_vals, _fr_get(f.fr_st_vals, d))
    st_used = roll(f.st_used, _fr_get(f.fr_st_used, d))
    st_written = roll(f.st_written, _fr_get(f.fr_st_written, d))
    st_acct = roll(f.st_acct, _fr_get(f.fr_st_acct, d))
    acct_bal = roll(f.acct_bal, _fr_get(f.fr_acct_bal, d))
    st_val_sym = roll(sf.st_val_sym, _fr_get(sf.fr_st_val_sym, d))
    st_key_sym = roll(sf.st_key_sym, _fr_get(sf.fr_st_key_sym, d))
    # seq rolls back WITH the entries (the counter itself stays monotonic
    # — gaps are harmless, only relative order matters)
    st_seq = roll(sf.st_seq, _fr_get(sf.fr_st_seq, d))
    # warm sets roll back with the frame (EIP-2929: a reverted call's
    # access-list growth is undone)
    warm_acct = roll(f.warm_acct, _fr_get(f.fr_warm_acct, d))
    st_warm = roll(f.st_warm, _fr_get(f.fr_st_warm, d))
    # gas: an EXCEPTIONAL halt burns the entire forwarded allowance
    # (child_limit - caller gas at push); a REVERT returns the unused
    # remainder, so the child's accumulated totals stand
    fwd = f.gas_limit - _fr_get(f.fr_gas_max, d)
    fail_exc = mp & f.error
    gas_min = jnp.where(fail_exc, _fr_get(f.fr_gas_min, d) + fwd, f.gas_min)
    gas_max = jnp.where(fail_exc, _fr_get(f.fr_gas_max, d) + fwd, f.gas_max)
    gas_limit = jnp.where(mp, _fr_get(f.fr_gas_limit, d), f.gas_limit)

    # success flag pushed at the caller's post-args sp; a constructor frame
    # pushes the CHILD ADDRESS instead (0 on failure) — the EVM result of
    # CREATE/CREATE2 is an address, not a boolean
    one_w = jnp.zeros((P, 8), dtype=U32).at[:, 0].set(1)
    child_addr = f.acct_field(f.acct_addr, jnp.maximum(cslot, 0))
    res_w = jnp.where(
        success[:, None],
        jnp.where(is_initp[:, None], child_addr, one_w),
        0,
    ).astype(U32)
    stack = ci._set_slot(f.stack, csp, res_w, mp)
    stack_sym = _set_sym_slot(sf.stack_sym, csp, jnp.zeros((P,), I32), mp)

    # constructor epilogue: the RETURN payload is the deployed runtime
    # image. Concretely match it against the corpus (factories deploying
    # known children become callable); empty code -> EOA-like; unmatched
    # -> CODE_UNKNOWN stays. A failed constructor unregisters the account
    # (its storage/balance rolled back with the frame snapshots; accounts
    # a NESTED create registered are not rolled back — documented).
    acct_used_p = ci._write_slot(
        f.acct_used,
        jnp.where(is_initp & fail, jnp.maximum(cslot, 0),
                  f.acct_used.shape[1]),
        False)

    def _resolve_child_code(acct_code_in):
        # the deployed image is concrete bytes in `retval`: byte-compare it
        # against every corpus image (both are zero-padded past their
        # lengths, so whole-window equality + length equality suffices).
        # A match makes the child CALLABLE (factory-deploys-known-child);
        # empty code -> EOA-like; no match / image beyond the retval cap
        # -> CODE_UNKNOWN (calls to it havoc, never wrong)
        rl = f.retval_len
        RD = f.retval.shape[1]
        MC = corpus.code.shape[1]
        Wn = min(RD, MC)
        eq = (
            jnp.all(f.retval[:, None, :Wn] == corpus.code[None, :, :Wn],
                    axis=2)
            & (rl[:, None] == corpus.code_len[None, :])
            & (corpus.code_len[None, :] <= RD)
            & (corpus.code_len[None, :] > 0)
        )
        # a symbolic byte anywhere in the returned image makes the concrete
        # compare meaningless — such a deploy stays CODE_UNKNOWN
        hit = jnp.any(eq, axis=1) & ~rv_unknown
        resolved = jnp.where(
            hit, jnp.argmax(eq, axis=1).astype(I32),
            jnp.where((rl == 0) & ~rv_unknown, -1, CODE_UNKNOWN),
        )
        cidx = jnp.where(is_initp & success, jnp.maximum(cslot, 0),
                         f.acct_used.shape[1])
        return ci._write_slot(acct_code_in, cidx, resolved)

    acct_code_p = lax.cond(jnp.any(is_initp & success), _resolve_child_code,
                           lambda ac: ac, f.acct_code)

    # a successful CREATE leaves EMPTY returndata in the caller (EVM rule:
    # only a reverting create exposes its revert payload)
    has_rd = has_rd & ~(is_initp & success)

    base = f.replace(
        pc=jnp.where(mp, ret_pc + 1, f.pc),
        sp=jnp.where(mp, csp + 1, f.sp),
        sp_base=jnp.where(mp, _fr_get(f.fr_sp_base, d), f.sp_base),
        depth=jnp.where(mp, d, f.depth),
        init_depth=jnp.where(is_initp, 0, f.init_depth),
        acct_used=acct_used_p,
        acct_code=acct_code_p,
        fr_create_slot=ci._write_slot(
            f.fr_create_slot,
            jnp.where(is_initp, d, f.fr_create_slot.shape[1]), -1),
        static=jnp.where(mp, _fr_get(f.fr_static, d), f.static),
        cur_acct=jnp.where(mp, _fr_get(f.fr_cur_acct, d), f.cur_acct),
        contract_id=jnp.where(mp, _fr_get(f.fr_contract_id, d), f.contract_id),
        caller_addr=jnp.where(mp[:, None], _fr_get(f.fr_caller_addr, d), f.caller_addr),
        callvalue=jnp.where(mp[:, None], _fr_get(f.fr_callvalue, d), f.callvalue),
        memory=memory,
        mem_words=jnp.where(mp, _fr_get(f.fr_mem_words, d), f.mem_words),
        calldata=jnp.where(mp[:, None], _fr_get(f.fr_calldata, d), f.calldata),
        calldata_len=jnp.where(mp, _fr_get(f.fr_calldata_len, d), f.calldata_len),
        returndata=jnp.where((mp & has_rd)[:, None], f.retval, f.returndata),
        returndata_len=jnp.where(mp, jnp.where(has_rd, f.retval_len, 0),
                                 f.returndata_len),
        retval_len=jnp.where(mp, 0, f.retval_len),
        stack=stack,
        st_keys=st_keys, st_vals=st_vals, st_used=st_used,
        st_written=st_written, st_acct=st_acct, acct_bal=acct_bal,
        warm_acct=warm_acct, st_warm=st_warm,
        gas_min=gas_min, gas_max=gas_max, gas_limit=gas_limit,
        halted=f.halted & ~mp,
        reverted=f.reverted & ~mp,
        error=f.error & ~mp,
        err_code=jnp.where(mp, 0, f.err_code),
    )
    return sf.replace(
        base=base,
        stack_sym=stack_sym,
        mem_sym=mem_sym,
        mem_havoc=mem_havoc,
        retdata_sym=jnp.where(mp, has_rd & rv_unknown, sf.retdata_sym),
        rv_sym=jnp.where(mp[:, None], 0, sf.rv_sym),
        rv_havoc=jnp.where(mp, False, sf.rv_havoc),
        cd_from_mem=jnp.where(mp, _fr_get(sf.fr_cd_from_mem, d), sf.cd_from_mem),
        cd_havoc=jnp.where(mp, _fr_get(sf.fr_cd_havoc, d), sf.cd_havoc),
        cd_sym=jnp.where(mp[:, None], _fr_get(sf.fr_cd_sym, d), sf.cd_sym),
        callvalue_sym=jnp.where(mp, _fr_get(sf.fr_callvalue_sym, d), sf.callvalue_sym),
        caller_sym=jnp.where(mp, _fr_get(sf.fr_caller_sym, d), sf.caller_sym),
        # a failed value call rolled the balance table back — another change
        bal_epoch=sf.bal_epoch + fail.astype(I32),
        st_val_sym=st_val_sym,
        st_key_sym=st_key_sym,
        st_seq=st_seq,
        # only a genuine REVERT (require()-style) feeds SWC-123; callee
        # INVALID/OOG/bad-jump are assert-style failures (SWC-110 territory)
        sub_revert_pc=jnp.where(fail & f.reverted & ~f.error
                                & (sf.sub_revert_pc < 0), ret_pc,
                                sf.sub_revert_pc),
        sub_revert_cid=jnp.where(fail & f.reverted & ~f.error
                                 & (sf.sub_revert_pc < 0),
                                 _fr_get(f.fr_contract_id, d),
                                 sf.sub_revert_cid),
    )


def _h_sym_claimed_misc(sf: SymFrontier, op, m_memoff, m_sha3off, m_copyoff,
                        m_haltoff, m_logoff) -> SymFrontier:
    """Symbolic-offset memory/copy/sha3/halt/log ops: stack bookkeeping +
    havoc over-approximation (no byte-accurate modeling at symbolic
    addresses under static shapes)."""
    f = sf.base
    is_load = op == 0x51
    # LOG is a state modification: a symbolic-offset LOG inside a
    # STATICCALL frame must trap exactly like the concrete handler's
    static_viol = m_logoff & f.static
    m_logoff = m_logoff & ~static_viol
    sf = sf.replace(base=f.trap(static_viol, Trap.STATIC_WRITE))
    f = sf.base
    any_m = m_memoff | m_sha3off | m_copyoff | m_haltoff | m_logoff

    # MLOAD(sym off) / SHA3(sym args) -> fresh havoc result
    need_hv = (m_memoff & is_load) | m_sha3off
    sf, hv = _havoc(sf, need_hv)
    f = sf.base

    # result slots: MLOAD replaces top (sp-1); SHA3 pops 2 pushes 1 (sp-2)
    stack_sym = _set_sym_slot(sf.stack_sym, f.sp - 1, hv, m_memoff & is_load)
    stack_sym = _set_sym_slot(stack_sym, f.sp - 2, hv, m_sha3off)

    sin = ci._J_STACK_IN[op]
    sout = ci._J_STACK_OUT[op]
    d_sp = sin - sout
    is_revert = op == 0xFD
    has_data_halt = (op == 0xF3) | is_revert
    # symbolic-offset LOG: still record pc/cid/topic0 (topics may be
    # concrete even when the data window is not); payload word unknown (-1)
    LS = f.log_pc.shape[1]
    wl = jnp.where(m_logoff & (f.n_logs < LS),
                   jnp.minimum(f.n_logs, LS - 1), LS)
    n_topics = op.astype(I32) - 0xA0
    topic0 = ci._peek(f, 2)
    return sf.replace(
        base=f.replace(
            sp=jnp.where(any_m, f.sp - d_sp, f.sp),
            halted=f.halted | (m_haltoff & has_data_halt),
            reverted=f.reverted | (m_haltoff & is_revert),
            retval_len=jnp.where(m_haltoff, 0, f.retval_len),
            n_logs=f.n_logs + m_logoff.astype(I32),
            log_pc=ci._write_slot(f.log_pc, wl, f.pc),
            log_cid=ci._write_slot(f.log_cid, wl, f.contract_id),
            log_ntopics=ci._write_slot(f.log_ntopics, wl, n_topics),
            log_topic0=ci._write_slot(
                f.log_topic0, wl,
                jnp.where((n_topics >= 1)[:, None], topic0, 0).astype(
                    jnp.uint32)),
        ),
        log_topic0_sym=ci._write_slot(
            sf.log_topic0_sym, wl,
            jnp.where(n_topics >= 1, _peek_sym(sf, 2), 0)),
        log_data0_sym=ci._write_slot(sf.log_data0_sym, wl, -1),
        stack_sym=stack_sym,
        # symbolic-offset stores / copies invalidate the whole memory overlay
        mem_havoc=sf.mem_havoc | (m_memoff & ~is_load) | m_copyoff,
        # a symbolic-window RETURN/REVERT leaves the payload unknown — the
        # caller's returndata havocs when this frame pops
        rv_havoc=sf.rv_havoc | m_haltoff,
    )


# ---------------------------------------------------------------------------
# Overlay: sym-id bookkeeping for concretely-dispatched lanes
# ---------------------------------------------------------------------------


def _take_word_sym(mem_sym, w):
    W = mem_sym.shape[1]
    return jnp.take_along_axis(mem_sym, jnp.clip(w, 0, W - 1)[:, None].astype(I32), axis=1)[:, 0]


def _set_word_sym(mem_sym, w, val, mask):
    W = mem_sym.shape[1]
    idx = jnp.where(mask & (w >= 0) & (w < W), w, W).astype(I32)
    return ci._write_slot(mem_sym, idx, val)


def _overlay(sf: SymFrontier, env: Env, spec: SymSpec, op, m, cls, pre_sp,
             pre_stack_sym, a, s, limits: LimitsConfig) -> SymFrontier:
    """Mirror the concrete handlers' stack movements on the sym-id plane
    and append tape nodes where symbolic operands flowed in. Uses the
    PRE-dispatch stack/syms (`a` = operand limbs, `s` = operand sym ids).
    """
    f = sf.base
    stack_sym = sf.stack_sym
    sin = ci._J_STACK_IN[op]

    # ---- CLS_STACK: push/dup/swap/pc/msize/gas ----
    m_stk = m & (cls == ci.CLS_STACK)
    is_push = (op >= 0x5F) & (op <= 0x7F)
    is_dup = (op >= 0x80) & (op <= 0x8F)
    is_swap = (op >= 0x90) & (op <= 0x9F)
    pushes0 = is_push | (op == 0x58) | (op == 0x59) | (op == 0x5A)
    dup_n = jnp.where(is_dup, op - 0x7F, 1)
    S = stack_sym.shape[1]
    dup_sym = jnp.take_along_axis(
        pre_stack_sym, jnp.clip(pre_sp - dup_n, 0, S - 1)[:, None].astype(I32), axis=1
    )[:, 0]
    stack_sym = _set_sym_slot(stack_sym, pre_sp, jnp.zeros_like(dup_sym), m_stk & pushes0)
    stack_sym = _set_sym_slot(stack_sym, pre_sp, dup_sym, m_stk & is_dup)
    swap_n = jnp.where(is_swap, op - 0x8F, 1)
    deep_sym = jnp.take_along_axis(
        pre_stack_sym, jnp.clip(pre_sp - 1 - swap_n, 0, S - 1)[:, None].astype(I32), axis=1
    )[:, 0]
    stack_sym = _set_sym_slot(stack_sym, pre_sp - 1, deep_sym, m_stk & is_swap)
    stack_sym = _set_sym_slot(stack_sym, pre_sp - 1 - swap_n, s[0], m_stk & is_swap)
    sf = sf.replace(stack_sym=stack_sym)

    # ---- value binops/unaries (ALU/MUL/DIVMOD/EXP classes) ----
    m_bin = m & (
        (cls == ci.CLS_ALU) | (cls == ci.CLS_MUL) | (cls == ci.CLS_DIVMOD) | (cls == ci.CLS_EXP)
    )
    node_op = _J_BINOP[op]
    is_unary = (op == 0x15) | (op == 0x19)  # ISZERO NOT
    any_sym = (s[0] != 0) | (~is_unary & (s[1] != 0))
    m_node = m_bin & any_sym & (node_op != 0)
    sf, aid = _sym_or_const(sf, m_node, s[0], a[0])
    sf, bid = _sym_or_const(sf, m_node & ~is_unary, s[1], a[1])
    bid = jnp.where(is_unary, 0, bid)  # unary nodes must not carry stale b
    sf, r_bin = append_node(sf, m_node, node_op, aid, bid)

    # record symbolic ADD/SUB/MUL/EXP events for the IntegerArithmetics
    # module (reference: overflow predicates built inline in the module's
    # pre-hook on these opcodes ⚠unv SURVEY.md §3.3; here the predicate is
    # assembled host-side from the recorded operand node ids)
    is_arith = (op == 0x01) | (op == 0x02) | (op == 0x03) | (op == 0x0A)
    m_ar = m_node & is_arith
    ar_onehot = _event_slot(sf.n_arith, m_ar, sf.arith_op.shape[1])
    old_pc_arr = sf.base.pc  # prologue left pc at the instruction
    sf = sf.replace(
        n_arith=sf.n_arith + m_ar.astype(I32),
        arith_op=jnp.where(ar_onehot, op[:, None], sf.arith_op),
        arith_a=jnp.where(ar_onehot, aid[:, None], sf.arith_a),
        arith_b=jnp.where(ar_onehot, bid[:, None], sf.arith_b),
        arith_r=jnp.where(ar_onehot, r_bin[:, None], sf.arith_r),
        arith_pc=jnp.where(ar_onehot, old_pc_arr[:, None], sf.arith_pc),
        arith_cid=jnp.where(ar_onehot, sf.base.contract_id[:, None], sf.arith_cid),
    )

    # ---- CLS_MODARITH: symbolic addmod/mulmod -> havoc (documented) ----
    m_mod = m & (cls == ci.CLS_MODARITH)
    m_mod_sym = m_mod & ((s[0] != 0) | (s[1] != 0) | (s[2] != 0))

    # ---- CLS_ENV: leaves (tx-scoped identity; dedup hits the tx-0 seeds) ----
    m_env = m & (cls == ci.CLS_ENV)
    is_cdload = op == 0x35
    off64 = u256.to_u64_saturating(a[0]).astype(I64)
    CD = limits.calldata_bytes
    beyond = off64 >= CD
    txb = sf.tx_id
    # free actor/input leaves exist only at the TOP frame: a sub-frame's
    # caller/callvalue/calldata are determined by the calling contract
    at_top = sf.base.depth == 0

    kind = jnp.full_like(op, -1)
    bsel = jnp.zeros_like(op)

    def leaf(enabled: bool, sel, k: int, bval):
        nonlocal kind, bsel
        if not enabled:
            return
        kind = jnp.where(sel, k, kind)
        bsel = jnp.where(sel, bval, bsel)

    # tx-scoped actor/input leaves
    leaf(spec.caller, (op == 0x33) & at_top, int(FreeKind.CALLER), txb)
    leaf(spec.callvalue, (op == 0x34) & at_top, int(FreeKind.CALLVALUE), txb)
    leaf(spec.calldata, (op == 0x36) & at_top, int(FreeKind.CALLDATASIZE), txb)
    leaf(spec.calldata, is_cdload & (s[0] == 0) & ~beyond & at_top,
         int(FreeKind.CALLDATA_WORD),
         (txb.astype(I64) * TX_STRIDE + off64).astype(I32))
    # globals across the tx sequence: ORIGIN always symbolic (the
    # reference models tx.origin as a free symbol; SWC-115 scans for it)
    leaf(True, op == 0x32, int(FreeKind.ORIGIN), 0)
    leaf(spec.block_env, op == 0x42, int(FreeKind.TIMESTAMP), 0)
    leaf(spec.block_env, op == 0x43, int(FreeKind.NUMBER), 0)
    leaf(spec.block_env, op == 0x44, int(FreeKind.PREVRANDAO), 0)
    leaf(spec.block_env, op == 0x3A, int(FreeKind.GASPRICE), 0)
    # balances: a symbolic leaf per (epoch, ACCOUNT SLOT) — balances change
    # under symbolic value transfers, so a concrete table read could be
    # wrong; known accounts share one leaf per slot WITHIN an epoch, and
    # the epoch bumps whenever the concrete table changes (transfer /
    # rollback / tx boundary) so pre/post reads are not forced equal
    is_balance = op == 0x31
    known_acct, acct_slot = sf.base.acct_lookup(a[0])
    known_bal = is_balance & known_acct & (s[0] == 0)
    epoch_b = sf.bal_epoch * BAL_STRIDE
    leaf(spec.block_env, op == 0x47, int(FreeKind.BALANCE),
         epoch_b + sf.base.cur_acct)
    leaf(spec.block_env, known_bal, int(FreeKind.BALANCE),
         epoch_b + acct_slot)
    # RETURNDATASIZE after a symbolic call
    leaf(True, (op == 0x3D) & sf.retdata_sym, int(FreeKind.RETDATASIZE),
         jnp.maximum(sf.n_calls - 1, 0))

    need_leaf = m_env & (kind >= 0)
    sf, env_leaf = append_node(sf, need_leaf, int(SymOp.FREE), kind, bsel)

    # havoc cases: unknowable values must never collapse to a wrong
    # concrete 0 (EXTCODESIZE/EXTCODEHASH of unknown addresses, BALANCE of
    # unknown addresses, BLOCKHASH, symbolic-offset CALLDATALOAD).
    # EXTCODESIZE/EXTCODEHASH of a table account are answered concretely
    # by the concrete handler (corpus image hashes precomputed).
    unknown_addr = (s[0] != 0) | ~known_acct
    # a table account whose CODE is unknown (CREATE result): size/bytes
    # must havoc, never read as the concrete 0/zeros the table yields
    code_unknown = known_acct & (
        sf.base.acct_field(sf.base.acct_code, acct_slot) == CODE_UNKNOWN
    )
    # a concrete-offset CALLDATALOAD past the modeled window would read a
    # silent concrete 0 even though CALLDATASIZE is symbolic beyond it —
    # havoc instead (the engine's own policy: never a wrong value)
    cd_beyond_window = bool(spec.calldata) & is_cdload & (s[0] == 0) & beyond & at_top
    env_hv_need = m_env & (
        (is_cdload & (s[0] != 0))
        | cd_beyond_window
        | (is_balance & unknown_addr)
        | (op == 0x40)  # BLOCKHASH
        | (((op == 0x3B) | (op == 0x3F)) & (unknown_addr | code_unknown))
    )
    # sub-frame CALLVALUE / CALLDATALOAD: values flow from the caller's
    # frame (tracked sym ids), not free leaves
    sub = ~at_top
    cv_sub = m_env & (op == 0x34) & sub
    CDW = sf.cd_sym.shape[1]
    cw = (off64 // 32).astype(I32)
    cd_al = (off64 % 32) == 0

    def _cd_sym_at(w):
        v = jnp.take_along_axis(
            sf.cd_sym, jnp.clip(w, 0, CDW - 1)[:, None], axis=1
        )[:, 0]
        return jnp.where((w >= 0) & (w < CDW), v, 0)

    cda = _cd_sym_at(cw)
    cdb = _cd_sym_at(cw + 1)
    cd_sub = m_env & is_cdload & sub & (s[0] == 0)
    hv_cd_need = cd_sub & (sf.cd_havoc | (~cd_al & ((cda != 0) | (cdb != 0))))

    env_hv_need = env_hv_need | hv_cd_need
    sf, env_hv = _havoc(sf, env_hv_need)
    # sub-frame CALLER: a DELEGATECALL frame carries the caller frame's
    # msg.sender symbol (advisor r2: sender checks inside delegated code
    # must not be decided concretely while the top-frame model is symbolic)
    cl_sub = m_env & (op == 0x33) & sub & (sf.caller_sym != 0)
    r_env = jnp.where(need_leaf, env_leaf, 0)
    r_env = jnp.where(cv_sub, sf.callvalue_sym, r_env)
    r_env = jnp.where(cl_sub, sf.caller_sym, r_env)
    r_env = jnp.where(cd_sub & cd_al & ~sf.cd_havoc, cda, r_env)
    r_env = jnp.where(env_hv_need, env_hv, r_env)
    # "executed ORIGIN" flag (DeprecatedOperations SWC-111): the leaf node
    # may pre-exist via seeding, so presence on the tape is not evidence
    sf = sf.replace(origin_read=sf.origin_read | (m_env & (op == 0x32)))

    # ---- CLS_SHA3 (concrete args): keccak chain over the hashed window ----
    m_sha = m & (cls == ci.CLS_SHA3)
    ln64 = u256.to_u64_saturating(a[1]).astype(I64)
    w0 = (off64 // 32).astype(I32)
    # chain span derived from the concrete handler's hash cap so they can't
    # drift: any ln the concrete handler accepts (<= MAX_HASH_BYTES, else
    # the lane errors there) fits in NCW words from w0
    NCW = (ci.MAX_HASH_BYTES + 31 + 31) // 32
    nw = jnp.clip((off64 % 32 + ln64 + 31) // 32, 0, NCW).astype(I32)
    wsyms = [
        _take_word_sym(sf.mem_sym, w0 + k) for k in range(NCW)
    ]
    in_win = [(jnp.int32(k) < nw) for k in range(NCW)]
    any_w_sym = jnp.zeros_like(m_sha)
    for k in range(NCW):
        any_w_sym = any_w_sym | (in_win[k] & (wsyms[k] != 0))
    # a window that does not fully fit the chain span would truncate the
    # hashed data and yield a WRONG digest downstream — havoc instead
    # (over-approximation policy: never a wrong value)
    fits_chain = (off64 % 32 + ln64) <= 32 * NCW
    m_hvsha = m_sha & (ln64 > 0) & (sf.mem_havoc | (any_w_sym & ~fits_chain))
    m_chain = m_sha & any_w_sym & ~sf.mem_havoc & fits_chain
    sf, sha_hv = _havoc(sf, m_hvsha)
    seed_imm = jnp.zeros((f.pc.shape[0], 8), dtype=U32)
    seed_imm = seed_imm.at[:, 0].set(jnp.clip(ln64, 0, 2**31).astype(U32))
    seed_imm = seed_imm.at[:, 1].set((off64 % 32).astype(U32))
    sf, chain = append_node(sf, m_chain, int(SymOp.KECCAK_SEED), 0, 0, seed_imm)
    M = f.memory.shape[1]
    for k in range(NCW):
        mk = m_chain & in_win[k]
        w_conc = ci._be_bytes_to_word(
            ci._gather_bytes(sf.base.memory, (w0 + k).astype(I64) * 32, 32,
                             jnp.full_like(off64, M))
        )
        imm_k = jnp.where((wsyms[k] == 0)[:, None], w_conc, 0).astype(U32)
        sf, chain2 = append_node(sf, mk, int(SymOp.KECCAK_ABS), chain, wsyms[k], imm_k)
        chain = jnp.where(mk, chain2, chain)
    sf, dig = append_node(sf, m_chain, int(SymOp.KECCAK), chain, 0)
    r_sha = jnp.where(m_hvsha, sha_hv, jnp.where(m_chain, dig, 0))

    # ---- CLS_MEM (concrete offsets) ----
    m_mem = m & (cls == ci.CLS_MEM)
    is_load = op == 0x51
    is_store8 = op == 0x53
    aligned = (off64 % 32) == 0
    wm = (off64 // 32).astype(I32)
    wsym_a = _take_word_sym(sf.mem_sym, wm)
    wsym_b = _take_word_sym(sf.mem_sym, wm + 1)
    # MLOAD
    load_sym_needed = m_mem & is_load & (
        (aligned & ((wsym_a != 0) | sf.mem_havoc))
        | (~aligned & ((wsym_a != 0) | (wsym_b != 0) | sf.mem_havoc))
    )
    hv_load_need = load_sym_needed & (~aligned | sf.mem_havoc)
    # unaligned MSTORE: havoc both covered words if anything symbolic
    st_mask = m_mem & ~is_load
    un_any = st_mask & ~is_store8 & ~aligned & (
        (s[1] != 0) | (wsym_a != 0) | (wsym_b != 0) | sf.mem_havoc
    )
    sf, hv_a = _havoc(sf, hv_load_need | un_any)
    r_mload = jnp.where(
        load_sym_needed, jnp.where(aligned & ~sf.mem_havoc, wsym_a, hv_a), 0
    )
    mstore_aligned = st_mask & ~is_store8 & aligned
    mem_sym = _set_word_sym(sf.mem_sym, wm, s[1], mstore_aligned)
    sf, hv_b = _havoc(sf, un_any)
    mem_sym = _set_word_sym(mem_sym, wm, hv_a, un_any)
    mem_sym = _set_word_sym(mem_sym, wm + 1, hv_b, un_any)
    # MSTORE8: havoc the word if value or word symbolic
    m8_any = st_mask & is_store8 & ((s[1] != 0) | (wsym_a != 0) | sf.mem_havoc)
    sf, hv_c = _havoc(sf, m8_any)
    mem_sym = _set_word_sym(mem_sym, wm, hv_c, m8_any)
    sf = sf.replace(mem_sym=mem_sym)

    # ---- CLS_COPY (concrete args) ----
    m_cp = m & (cls == ci.CLS_COPY)
    is_ext = op == 0x3C
    dst64 = jnp.where(is_ext, u256.to_u64_saturating(a[1]), off64).astype(I64)
    cln64 = u256.to_u64_saturating(jnp.where(is_ext[:, None], a[3], a[2])).astype(I64)
    is_cdcopy = op == 0x37
    is_rdcopy = op == 0x3E
    # calldatacopy of symbolic calldata / returndatacopy after a symbolic
    # call: coarse whole-memory havoc (v1). Sub-frame calldata is only
    # symbolic where the caller's memory window was.
    cd_symbolic = jnp.where(
        at_top,
        jnp.full_like(sf.cd_havoc, spec.calldata),
        sf.cd_havoc | jnp.any(sf.cd_sym != 0, axis=1),
    )
    cd_havoc = m_cp & (cln64 > 0) & (
        (is_cdcopy & cd_symbolic) | (is_rdcopy & sf.retdata_sym)
    )
    # concrete-source copies (code/extcode/concrete returndata): fully
    # covered words become concrete; partial edge words with stale syms ->
    # havoc flag. EXTCODECOPY of an unknown-code account (CREATE result)
    # is NOT a concrete source — the zeros the concrete handler wrote are
    # wrong, so the window havocs instead.
    ext_unknown = is_ext & code_unknown
    conc_src = (m_cp & ~is_cdcopy & ~(is_rdcopy & sf.retdata_sym)
                & (cln64 > 0) & ~ext_unknown)
    W = sf.mem_sym.shape[1]
    wids = jnp.arange(W)[None, :]
    full_lo = ((dst64 + 31) // 32)[:, None]
    full_hi = ((dst64 + cln64) // 32)[:, None]
    full_cover = (wids >= full_lo) & (wids < full_hi) & conc_src[:, None]
    mem_sym2 = jnp.where(full_cover, 0, sf.mem_sym)
    edge_lo = (dst64 // 32)[:, None]
    edge_hi = ((dst64 + cln64) // 32)[:, None]
    edge = ((wids == edge_lo) | (wids == edge_hi)) & ~full_cover & conc_src[:, None]
    edge_dirty = jnp.any(edge & (sf.mem_sym != 0), axis=1)
    sf = sf.replace(
        mem_sym=mem_sym2,
        mem_havoc=sf.mem_havoc | cd_havoc | (conc_src & edge_dirty)
        | (m_cp & ext_unknown & (cln64 > 0)),
    )

    # ---- CLS_HALT: capture return-payload syms; SELFDESTRUCT beneficiary ----
    m_halt = m & (cls == ci.CLS_HALT)
    has_data = (op == 0xF3) | (op == 0xFD)
    rv_words = sf.rv_sym.shape[1]
    cap_ok = m_halt & has_data & aligned & ~sf.mem_havoc
    rv_sym = sf.rv_sym
    for k in range(rv_words):
        in_rv = (jnp.int32(k) * 32) < ln64
        rv_sym = rv_sym.at[:, k].set(
            jnp.where(cap_ok & in_rv, _take_word_sym(sf.mem_sym, wm + k), rv_sym[:, k])
        )
    is_sd = op == 0xFF
    is_inv = op == 0xFE
    first_inv = m_halt & is_inv & (sf.inv_pc < 0)
    first_sd = m_halt & is_sd & (sf.sd_pc < 0)

    # SELFDESTRUCT balance sweep (reference: selfdestruct_ transfer
    # ⚠unv): a CONCRETE beneficiary in the account table is credited and
    # the executing account zeroed; a symbolic/unknown beneficiary only
    # zeroes self (funds leave the modeled world) — the epoch bump makes
    # later BALANCE reads fresh leaves either way, never a stale value.
    fb = sf.base
    m_sd = m_halt & is_sd
    ben_found, ben_slot = fb.acct_lookup(a[0])
    sweep = m_sd & (s[0] == 0) & ben_found & (ben_slot != fb.cur_acct)
    lanes_sd = jnp.arange(fb.pc.shape[0])
    A_sd = fb.acct_used.shape[1]
    ben_bal = fb.acct_field(fb.acct_bal, ben_slot)
    self_bal = fb.self_balance
    acct_bal_sd = fb.acct_bal.at[
        lanes_sd, jnp.where(sweep, ben_slot, A_sd)].set(
        u256.add(ben_bal, self_bal), mode="drop")
    acct_bal_sd = acct_bal_sd.at[
        lanes_sd, jnp.where(m_sd, fb.cur_acct, A_sd)].set(
        jnp.zeros_like(self_bal), mode="drop")
    sf = sf.replace(base=fb.replace(acct_bal=acct_bal_sd),
                    bal_epoch=sf.bal_epoch + m_sd.astype(I32))

    sf = sf.replace(
        rv_sym=rv_sym,
        sd_to_sym=jnp.where(m_halt & is_sd, s[0], sf.sd_to_sym),
        sd_to=jnp.where((m_halt & is_sd)[:, None], a[0], sf.sd_to).astype(U32),
        sd_pc=jnp.where(first_sd, sf.base.pc, sf.sd_pc),
        sd_cid=jnp.where(first_sd, sf.base.contract_id, sf.sd_cid),
        inv_pc=jnp.where(first_inv, sf.base.pc, sf.inv_pc),
        inv_cid=jnp.where(first_inv, sf.base.contract_id, sf.inv_cid),
    )

    # ---- CLS_LOG: sym overlay of the record the concrete handler wrote ----
    m_log = m & (cls == ci.CLS_LOG)
    LS = sf.base.log_pc.shape[1]
    log_idx = sf.base.n_logs - 1  # concrete handler already bumped it
    wl = jnp.where(m_log & (log_idx >= 0) & (log_idx < LS), log_idx, LS)
    lanes_all = jnp.arange(f.pc.shape[0])
    d0_sym = jnp.where(aligned & ~sf.mem_havoc, wsym_a, -1)
    d0_sym = jnp.where(u256.to_u64_saturating(a[1]) == 0, 0, d0_sym)
    log_nt = op - 0xA0  # LOG0 has no topic: s[2] is an unrelated slot
    sf = sf.replace(
        log_topic0_sym=ci._write_slot(
            sf.log_topic0_sym, wl, jnp.where(log_nt >= 1, s[2], 0)),
        log_data0_sym=ci._write_slot(sf.log_data0_sym, wl, d0_sym),
    )

    # ---- write result syms into the result slot (clears stale ids) ----
    r = jnp.zeros_like(op)
    r = jnp.where(m_node, r_bin, r)
    r = jnp.where(m_env, r_env, r)
    r = jnp.where(m_sha, r_sha, r)
    r = jnp.where(m_mem & is_load, r_mload, r)
    m_modhv = m_mod_sym
    sf2, hv_mod = _havoc(sf, m_modhv)
    sf = sf2
    r = jnp.where(m_modhv, hv_mod, r)
    writes_result = (
        m_bin | m_mod | m_env | m_sha | (m_mem & is_load)
    )
    res_slot = pre_sp - sin
    sf = sf.replace(
        stack_sym=_set_sym_slot(sf.stack_sym, res_slot, r, writes_result)
    )
    return sf


# ---------------------------------------------------------------------------
# Superstep / forking / run loop
# ---------------------------------------------------------------------------


def _berlin_gas_pre(sf: SymFrontier, op, run, a, s) -> SymFrontier:
    """EIP-2929 cold surcharges, charged to the EXECUTING frame before
    dispatch (so a sub-call's rollback snapshot includes its caller's
    access cost — access-list growth is never refunded... except by frame
    revert, which the fr_warm_* snapshots handle).

    Warm/cold resolution: storage keys against the associative cache's
    per-tx ``st_warm`` bits; addresses against the account table's
    ``warm_acct``. A SYMBOLIC key/address — and any address outside the
    table — cannot be tracked: the surcharge lands in ``gas_max`` only
    (``gas_min`` keeps the all-warm floor), preserving min <= actual <=
    max. Account-op targets are marked warm here; storage marking happens
    post-dispatch (``_berlin_gas_post``) once SSTORE has allocated."""
    from ..disassembler.opcodes import (G_COLD_ACCOUNT, G_COLD_SLOAD,
                                        G_WARM_ACCESS)

    f = sf.base
    P = f.n_lanes
    # the static berlin table already charged the WARM base; the cold
    # surcharge is the DIFFERENCE (EVM: cold replaces, not augments)
    SUR_SLOAD = G_COLD_SLOAD - G_WARM_ACCESS
    SUR_ACCT = G_COLD_ACCOUNT - G_WARM_ACCESS

    # --- storage: SLOAD/SSTORE (key = operand 0)
    m_st = run & ((op == 0x54) | (op == 0x55))
    key_conc = s[0] == 0
    hit, _, slot = ci._storage_lookup(f, a[0])
    K = f.st_warm.shape[1]
    warm_bit = jnp.take_along_axis(
        f.st_warm, jnp.clip(slot, 0, K - 1)[:, None], axis=1)[:, 0]
    st_cold = ~(hit & warm_bit)
    st_sur_max = jnp.where(m_st, SUR_SLOAD, 0).astype(I64)
    st_sur_min = jnp.where(m_st & key_conc & st_cold, SUR_SLOAD, 0).astype(I64)
    st_sur_max = jnp.where(m_st & key_conc & ~st_cold, 0, st_sur_max)

    # --- account access: BALANCE/EXTCODESIZE/EXTCODECOPY/EXTCODEHASH
    # (addr = operand 0), CALL family (operand 1), SELFDESTRUCT (operand 0)
    m_acct0 = run & ((op == 0x31) | (op == 0x3B) | (op == 0x3C)
                     | (op == 0x3F) | (op == 0xFF))
    m_call = run & (ci._J_CLASS[op] == ci.CLS_CALL)
    addr_w = jnp.where(m_call[:, None], a[1], a[0])
    addr_sym = jnp.where(m_call, s[1], s[0])
    m_addr = (m_acct0 | m_call)
    found, aslot = f.acct_lookup(addr_w)
    A = f.warm_acct.shape[1]
    awarm = found & jnp.take_along_axis(
        f.warm_acct, jnp.clip(aslot, 0, A - 1)[:, None], axis=1)[:, 0]
    addr_conc = addr_sym == 0
    tracked = addr_conc & found
    # a SYMBOLIC CALL target is not charged here: the callee-enumeration
    # fork that resolves it re-executes with a concrete target and pays
    # then (charging the parked lane once per retry would compound); the
    # never-resolving external fallback pays in _h_sym_call. The other
    # address ops (BALANCE/EXTCODE*/SELFDESTRUCT) execute exactly once,
    # so a symbolic address charges cold into gas_max right now.
    ac_sur_min = jnp.where(m_addr & tracked & ~awarm, SUR_ACCT, 0).astype(I64)
    ac_sur_max = jnp.where(
        (m_addr & addr_conc & (~found | ~awarm))
        | (m_acct0 & ~addr_conc), SUR_ACCT, 0).astype(I64)

    # mark touched table accounts warm (symbolic addresses can't resolve)
    aidx = jnp.where(m_addr & tracked, aslot, A)
    warm_acct = ci._write_slot(f.warm_acct, aidx, True)

    return sf.replace(base=f.replace(
        gas_min=f.gas_min + st_sur_min + ac_sur_min,
        gas_max=f.gas_max + st_sur_max + ac_sur_max,
        warm_acct=warm_acct,
    ))


def _berlin_gas_post(sf: SymFrontier, op, run, key_w, key_s) -> SymFrontier:
    """Post-dispatch storage warm marking: the touched key's cache entry
    (allocated by SSTORE, the symbolic SLOAD memo, or here for a concrete
    SLOAD miss) gets its per-tx warm bit."""
    f = sf.base
    P = f.n_lanes
    m_st = run & ((op == 0x54) | (op == 0x55)) & (key_s == 0) & ~f.error
    hit, _, slot = ci._storage_lookup(f, key_w)
    # concrete SLOAD miss: allocate a (key, 0, unwritten) entry so the
    # NEXT access is provably warm (the concrete handler doesn't insert)
    need_alloc = m_st & ~hit & (op == 0x54)
    widx, overflow = ci.storage_alloc(f, hit, slot, need_alloc)
    st_keys = ci._write_slot(f.st_keys, widx, key_w)
    st_used = ci._write_slot(f.st_used, widx, True)
    st_acct = ci._write_slot(f.st_acct, widx, f.cur_acct)
    # a full cache simply loses warm tracking (overcharges later, sound)
    K = f.st_warm.shape[1]
    midx = jnp.where(m_st & hit, slot,
                     jnp.where(need_alloc & ~overflow, widx, K))
    st_warm = ci._write_slot(f.st_warm, jnp.clip(midx, 0, K), True)
    return sf.replace(base=f.replace(
        st_keys=st_keys, st_used=st_used, st_acct=st_acct, st_warm=st_warm,
    ))


# declared write sets for the narrow claimed-handler conds (dotted paths
# into the SymFrontier pytree; enforced at trace time by ci.narrow_cond)
_TAPE_WRITES = ("tape_op", "tape_a", "tape_b", "tape_imm", "tape_hash",
                "tape_len")
_STORAGE_WRITES = (
    "base.st_keys", "base.st_vals", "base.st_used",
    "base.st_written", "base.st_acct", "base.error", "base.err_code",
    "st_key_sym", "st_val_sym", "st_seq", "st_seq_ctr", "dep_read",
    "sstore_after_call_pc", "sstore_ac_cid", "arb_key_node", "arb_key_pc",
    "arb_key_cid",
) + _TAPE_WRITES
_JUMP_WRITES = (
    "base.pc", "base.sp", "base.halted", "base.error", "base.err_code",
    "con_node", "con_sign", "con_pc", "con_len",
    "sym_jump_dest", "sym_jump_pc", "sym_jump_cid", "fork_req", "fork_dest",
)
_MISC_WRITES = (
    "base.sp", "base.halted", "base.reverted", "base.retval_len",
    "base.n_logs", "base.log_pc", "base.log_cid", "base.log_ntopics",
    "base.log_topic0", "base.error", "base.err_code",
    "havoc_cnt", "log_topic0_sym", "log_data0_sym", "stack_sym",
    "mem_havoc", "rv_havoc",
) + _TAPE_WRITES

# pop_frames' declared write set: everything the caller-restore touches —
# but NOT the fr_* frame stacks ([P, D, ...] snapshots, read-only here),
# the tape, kb_m/kb_v, or the con_* constraint arrays. Keeping those out
# of the cond boundary matters: the old full-state ``lax.cond`` carried
# every leaf of the frontier (frame-stack snapshots alone are D× the base
# state) through the boundary on EVERY superstep — one of the cond-copy
# buckets tools/scaling_report.py attributes.
_POP_FRAME_WRITES = (
    "base.pc", "base.sp", "base.sp_base", "base.depth", "base.init_depth",
    "base.acct_used", "base.acct_code", "base.fr_create_slot",
    "base.static", "base.cur_acct", "base.contract_id",
    "base.caller_addr", "base.callvalue", "base.memory", "base.mem_words",
    "base.calldata", "base.calldata_len", "base.returndata",
    "base.returndata_len", "base.retval_len", "base.stack",
    "base.st_keys", "base.st_vals", "base.st_used", "base.st_written",
    "base.st_acct", "base.acct_bal", "base.warm_acct", "base.st_warm",
    "base.gas_min", "base.gas_max", "base.gas_limit",
    "base.halted", "base.reverted", "base.error", "base.err_code",
    "stack_sym", "mem_sym", "mem_havoc", "retdata_sym", "rv_sym",
    "rv_havoc", "cd_from_mem", "cd_havoc", "cd_sym", "callvalue_sym",
    "caller_sym", "bal_epoch", "st_val_sym", "st_key_sym", "st_seq",
    "sub_revert_pc", "sub_revert_cid",
)


def sym_superstep(sf: SymFrontier, env: Env, corpus: Corpus,
                  spec: SymSpec = SymSpec(),
                  limits: LimitsConfig = DEFAULT_LIMITS) -> SymFrontier:
    """Advance every running lane by one instruction, symbolically."""
    berlin = limits.gas_schedule == "berlin"
    f, op, run, old_pc = ci.prologue(sf.base, corpus, berlin=berlin)
    sf = sf.replace(base=f)
    cls = ci._J_CLASS[op]
    pre_sp = f.sp
    pre_stack_sym = sf.stack_sym
    a = [ci._peek(f, i) for i in range(4)]
    s = [_peek_sym(sf, i) for i in range(7)]
    if berlin:
        sf = _berlin_gas_pre(sf, op, run, a, s)
        f = sf.base

    is_jumpi = op == 0x57
    known, ksign = _lookup_constraint(sf, s[1])
    claim_jump = run & (cls == ci.CLS_JUMP) & ((s[0] != 0) | (is_jumpi & (s[1] != 0)))
    claim_storage = run & (cls == ci.CLS_STORAGE)
    claim_call = run & (cls == ci.CLS_CALL)
    claim_create = run & (cls == ci.CLS_CREATE)
    claim_callish = claim_call | claim_create
    claim_memoff = run & (cls == ci.CLS_MEM) & (s[0] != 0)
    claim_sha3off = run & (cls == ci.CLS_SHA3) & ((s[0] != 0) | (s[1] != 0))
    is_ext = op == 0x3C
    claim_copyoff = run & (cls == ci.CLS_COPY) & (
        (s[0] != 0) | (s[1] != 0) | (s[2] != 0) | (is_ext & (s[3] != 0))
    )
    has_data_halt = (op == 0xF3) | (op == 0xFD)
    claim_haltoff = run & (cls == ci.CLS_HALT) & has_data_halt & ((s[0] != 0) | (s[1] != 0))
    claim_logoff = run & (cls == ci.CLS_LOG) & ((s[0] != 0) | (s[1] != 0))
    claimed = (
        claim_jump | claim_storage | claim_callish | claim_memoff
        | claim_sha3off | claim_copyoff | claim_haltoff | claim_logoff
    )

    f = ci.dispatch(sf.base, env, corpus, op, run, old_pc, skip=claimed)
    sf = sf.replace(base=f)

    sf = _overlay(sf, env, spec, op, run & ~claimed, cls, pre_sp,
                  pre_stack_sym, a, s, limits)

    def _cond_apply(sf, mask, fn):
        return lax.cond(jnp.any(mask), fn, lambda x: x, sf)

    # the hot claimed handlers run behind NARROW conds (ci.narrow_cond):
    # only their declared write sets become cond outputs, keeping the
    # rest of the SymFrontier (frame stacks, memory, calldata overlays)
    # out of the boundary. CALL/CREATE write half the frontier and fire
    # rarely — they keep the plain full-state cond.
    P = sf.base.pc.shape[0]
    sf, st_aux = ci.narrow_cond(
        jnp.any(claim_storage),
        lambda x: _h_sym_storage(x, spec, op, claim_storage),
        sf, _STORAGE_WRITES,
        aux_defaults={
            "r": jnp.zeros((P, 8), dtype=jnp.uint32),
            "r_sym": jnp.zeros(P, dtype=I32),
            "w": jnp.zeros(P, dtype=bool),
        })
    # shared claimed writeback: the SLOAD result lands here, and sp for
    # ALL storage-claimed lanes advances by the arity table (SLOAD 0,
    # SSTORE -2) — one stack pass instead of a stack-carrying cond
    fb = sf.base
    sf = sf.replace(
        base=fb.replace(
            stack=ci._set_slot(fb.stack, fb.sp - 1, st_aux["r"], st_aux["w"]),
            sp=jnp.where(claim_storage, fb.sp + ci._J_D_SP[op], fb.sp),
        ),
        stack_sym=_set_sym_slot(sf.stack_sym, fb.sp - 1, st_aux["r_sym"],
                                st_aux["w"]),
    )
    sf = ci.narrow_cond(
        jnp.any(claim_jump),
        lambda x: _h_sym_jump(x, corpus, op, claim_jump, old_pc, known,
                              ksign),
        sf, _JUMP_WRITES)
    sf = _cond_apply(sf, claim_call,
                     lambda x: _h_sym_call(x, corpus, op, claim_call, old_pc,
                                           spec, limits))
    sf = _cond_apply(sf, claim_create,
                     lambda x: _h_sym_create(x, op, claim_create, old_pc))
    misc = claim_memoff | claim_sha3off | claim_copyoff | claim_haltoff | claim_logoff
    sf = ci.narrow_cond(
        jnp.any(misc),
        lambda x: _h_sym_claimed_misc(x, op, claim_memoff, claim_sha3off,
                                      claim_copyoff, claim_haltoff,
                                      claim_logoff),
        sf, _MISC_WRITES)

    if berlin:
        sf = _berlin_gas_post(sf, op, run, a[0], s[0])

    # bounded loops: any jump that landed at-or-before its own pc (the
    # fork-taken copies are counted in expand_forks)
    fb = sf.base
    back = (run & (cls == ci.CLS_JUMP) & ~fb.halted & ~fb.error
            & (fb.pc <= old_pc))
    sf = _note_backjump(sf, back, old_pc, fb.pc, limits.loop_bound)

    f = ci.epilogue(sf.base, op, run, old_pc)
    sf = sf.replace(base=f)
    # sub-frames that halted (or failed) this step return to their caller.
    # Narrow cond: only pop_frames' declared writes cross the boundary —
    # the fr_* snapshot stacks, tape, and constraint arrays bypass it, so
    # the (rare) pop never forces a full-frontier carry copy.
    any_ended = jnp.any(sf.base.active & (sf.base.depth > 0)
                        & (sf.base.halted | sf.base.error))
    return ci.narrow_cond(any_ended, lambda x: pop_frames(x, corpus),
                          sf, _POP_FRAME_WRITES)


def between_txs(sf: SymFrontier, require_mutation: bool = True,
                new_contract_id=None,
                dependency_prune: bool = True,
                first_message_tx: int = 0) -> SymFrontier:
    """Advance surviving lanes to the next symbolic transaction.

    Counterpart of the reference's ``open_states`` handoff
    (``transaction/symbolic.py:execute_message_call`` iterating world
    states that survived the previous tx ⚠unv, SURVEY.md §3.2): a lane
    proceeds iff it halted normally AND mutated storage — dropping
    non-mutating paths is exactly the reference's MutationPruner
    (``laser/plugin/plugins/mutation_pruner.py`` ⚠unv). A selfdestructed
    contract has no code left, so those lanes retire too. Per-tx machine
    state resets; storage, the tape, and path constraints carry over;
    the one-shot event records (calls, arith, INVALID/SSTORE pcs) are
    per-transaction and reset — the per-tx context snapshots taken by
    ``SymExecWrapper`` already preserved them for detection.
    tx-scoped leaves re-key via tx_id (TX_STRIDE encoding).

    ``require_mutation=False`` + ``new_contract_id`` serve the
    creation→runtime handoff (reference: ``execute_contract_creation``
    then message calls ⚠unv): a constructor needn't write storage for its
    deploy to count, and the surviving lanes switch from the creation
    image to the runtime image while keeping their storage.
    """
    b = sf.base
    P = sf.n_lanes
    go = b.active & b.halted & ~b.error & ~b.reverted & ~b.selfdestructed
    if require_mutation:
        go = go & jnp.any(b.st_written, axis=1)
    if dependency_prune:
        # DependencyPruner (reference: ``plugins/dependency_pruner.py``
        # ⚠unv, SURVEY §5.7 "the single biggest algorithmic speedup"): a
        # later message-call path that read nothing any prior tx wrote
        # behaved exactly like an earlier message call from the same state
        # — its issues were already collected in this tx's snapshot, so it
        # retires instead of spawning redundant deeper exploration. The
        # FIRST message call is exempt (``first_message_tx`` shifts by one
        # when a creation tx ran: the constructor is different code, not
        # an equivalent ancestor).
        go = go & ((sf.tx_id <= first_message_tx) | sf.dep_read)
    if new_contract_id is None:
        new_home = b.home_contract
    else:
        new_home = jnp.asarray(new_contract_id, dtype=b.home_contract.dtype)
    attacker = jnp.broadcast_to(
        jnp.asarray(u256.from_int(ATTACKER_ADDRESS)), (P, 8)
    ).astype(jnp.uint32)
    return sf.replace(
        base=b.replace(
            active=go,
            halted=jnp.zeros_like(b.halted),
            err_code=jnp.zeros_like(b.err_code),
            reverted=jnp.zeros_like(b.reverted),
            pc=jnp.where(go, 0, b.pc),
            stack=jnp.where(go[:, None, None], 0, b.stack),
            sp=jnp.where(go, 0, b.sp),
            depth=jnp.where(go, 0, b.depth),
            sp_base=jnp.where(go, 0, b.sp_base),
            static=jnp.where(go, False, b.static),
            cur_acct=jnp.where(go, b.home_acct, b.cur_acct),
            home_contract=jnp.where(go, new_home, b.home_contract),
            contract_id=jnp.where(go, new_home, b.contract_id),
            caller_addr=jnp.where(go[:, None], attacker, b.caller_addr),
            callvalue=jnp.where(go[:, None], 0, b.callvalue).astype(jnp.uint32),
            memory=jnp.where(go[:, None], 0, b.memory),
            mem_words=jnp.where(go, 0, b.mem_words),
            gas_min=jnp.where(go, 0, b.gas_min),
            gas_max=jnp.where(go, 0, b.gas_max),
            calldata_len=jnp.where(go, b.calldata.shape[1], b.calldata_len),
            returndata_len=jnp.where(go, 0, b.returndata_len),
            retval_len=jnp.where(go, 0, b.retval_len),
            n_logs=jnp.where(go, 0, b.n_logs),
            log_pc=jnp.where(go[:, None], 0, b.log_pc),
            log_cid=jnp.where(go[:, None], 0, b.log_cid),
            log_ntopics=jnp.where(go[:, None], 0, b.log_ntopics),
            log_topic0=jnp.where(go[:, None, None], 0, b.log_topic0),
            log_data0=jnp.where(go[:, None, None], 0, b.log_data0),
            st_written=jnp.where(go[:, None], False, b.st_written),
            init_depth=jnp.where(go, 0, b.init_depth),
            init_len=jnp.where(go, 0, b.init_len),
            # EIP-2929 access lists are per-transaction: reset to the
            # tx-start warm set (origin/caller + the target account)
            warm_acct=jnp.where(
                go[:, None],
                (jnp.arange(b.warm_acct.shape[1])[None, :] == ACCT_ATTACKER)
                | (jnp.arange(b.warm_acct.shape[1])[None, :]
                   == b.home_acct[:, None]),
                b.warm_acct),
            st_warm=jnp.where(go[:, None], False, b.st_warm),
        ),
        stack_sym=jnp.where(go[:, None], 0, sf.stack_sym),
        mem_sym=jnp.where(go[:, None], 0, sf.mem_sym),
        mem_havoc=jnp.where(go, False, sf.mem_havoc),
        retdata_sym=jnp.where(go, False, sf.retdata_sym),
        rv_sym=jnp.where(go[:, None], 0, sf.rv_sym),
        rv_havoc=jnp.where(go, False, sf.rv_havoc),
        cd_from_mem=jnp.where(go, False, sf.cd_from_mem),
        cd_havoc=jnp.where(go, False, sf.cd_havoc),
        cd_sym=jnp.where(go[:, None], 0, sf.cd_sym),
        callvalue_sym=jnp.where(go, 0, sf.callvalue_sym),
        caller_sym=jnp.where(go, 0, sf.caller_sym),
        # new tx: the (symbolic) incoming callvalue changes balances again
        bal_epoch=sf.bal_epoch + go.astype(I32),
        sub_revert_pc=jnp.where(go, -1, sf.sub_revert_pc),
        sub_revert_cid=jnp.where(go, 0, sf.sub_revert_cid),
        tx_id=jnp.where(go, sf.tx_id + 1, sf.tx_id),
        # per-tx one-shot event records reset so tx N+1 can't inherit
        # tx N's calls/arith/SSTORE-after-call evidence (the per-tx
        # snapshot consumed them already)
        sym_jump_dest=jnp.where(go, 0, sf.sym_jump_dest),
        sym_jump_pc=jnp.where(go, -1, sf.sym_jump_pc),
        sym_jump_cid=jnp.where(go, 0, sf.sym_jump_cid),
        # the saturation counters reset for EVERY lane (not just survivors):
        # coverage_summary sums them across tx snapshots, and a retired
        # lane's stale count would be recounted each remaining tx
        n_calls=jnp.zeros_like(sf.n_calls),
        n_mut_calls=jnp.zeros_like(sf.n_mut_calls),
        call_op=jnp.where(go[:, None], 0, sf.call_op),
        call_to=jnp.where(go[:, None, None], 0, sf.call_to),
        call_to_sym=jnp.where(go[:, None], 0, sf.call_to_sym),
        call_value=jnp.where(go[:, None, None], 0, sf.call_value),
        call_value_sym=jnp.where(go[:, None], 0, sf.call_value_sym),
        call_pc=jnp.where(go[:, None], 0, sf.call_pc),
        call_cid=jnp.where(go[:, None], 0, sf.call_cid),
        log_topic0_sym=jnp.where(go[:, None], 0, sf.log_topic0_sym),
        log_data0_sym=jnp.where(go[:, None], 0, sf.log_data0_sym),
        origin_read=jnp.where(go, False, sf.origin_read),
        inv_pc=jnp.where(go, -1, sf.inv_pc),
        inv_cid=jnp.where(go, 0, sf.inv_cid),
        sstore_after_call_pc=jnp.where(go, -1, sf.sstore_after_call_pc),
        sstore_ac_cid=jnp.where(go, 0, sf.sstore_ac_cid),
        arb_key_node=jnp.where(go, 0, sf.arb_key_node),
        arb_key_pc=jnp.where(go, -1, sf.arb_key_pc),
        arb_key_cid=jnp.where(go, 0, sf.arb_key_cid),
        dropped_forks=jnp.zeros_like(sf.dropped_forks),
        call_enum=jnp.zeros_like(sf.call_enum),
        fork_cslot=jnp.full_like(sf.fork_cslot, -1),
        n_arith=jnp.zeros_like(sf.n_arith),
        arith_op=jnp.where(go[:, None], 0, sf.arith_op),
        arith_a=jnp.where(go[:, None], 0, sf.arith_a),
        arith_b=jnp.where(go[:, None], 0, sf.arith_b),
        arith_r=jnp.where(go[:, None], 0, sf.arith_r),
        arith_pc=jnp.where(go[:, None], 0, sf.arith_pc),
        arith_cid=jnp.where(go[:, None], 0, sf.arith_cid),
        # retired lanes (reverted / error / non-mutating) free their slots
        # for forks of the surviving ones; their results were consumed by
        # the per-tx detection pass before this call. Loss accounting
        # (err_code / killed_infeasible) resets so the host-side per-tx
        # tally in SymExecWrapper counts each lost lane exactly once even
        # after its slot is recycled by expand_forks.
        killed_infeasible=jnp.zeros_like(sf.killed_infeasible),
        # per-tx loop budget + dependency evidence reset
        lb_key=jnp.where(go[:, None], -1, sf.lb_key),
        lb_cnt=jnp.where(go[:, None], 0, sf.lb_cnt),
        lb_len=jnp.where(go, 0, sf.lb_len),
        dep_read=jnp.where(go, False, sf.dep_read),
    )


def plan_fork_map(req2, free2, key, fork_policy: str = "fifo",
                  fork_impl: str = "packed"):
    """The fork source→destination mapping machinery, factored out of
    :func:`expand_forks` so tools/scaling_report.py can trace and cost
    it in isolation (the whole-frontier copy around it is linear in P
    and drowns this term inside the full ``expand_forks`` jaxpr).

    Inputs are block-shaped ``[G, B]``: the live request mask, the free
    mask, and the policy key (ignored for fifo). Returns
    ``(src2 [G, B], is_copy [P], slot [P])`` — per-destination source
    index, the copy mask, and the per-source admission sentinel
    (``slot == P`` ⇔ starved; intermediate values are only meaningful
    on the legacy path where they are real slot ids).
    """
    G, B = req2.shape
    P = G * B
    loc = jnp.arange(B, dtype=I32)[None, :]
    gidx = jnp.broadcast_to(jnp.arange(G, dtype=I32)[:, None], (G, B))
    n_free = jnp.sum(free2.astype(I32), axis=1, keepdims=True)
    if fork_policy == "fifo":
        rank = jnp.cumsum(req2.astype(I32), axis=1) - req2.astype(I32)
        order = None
    elif fork_impl == "packed":
        # pack (key, lane) into ONE int32 composite: composites are
        # unique (the lane index breaks key ties exactly like the
        # legacy stable argsort), so a single sort gives the
        # admission order and a searchsorted over the sorted
        # composites gives each lane's rank — no second argsort.
        # The key budget shrinks when B is huge so the composite
        # stays inside int32; policy keys are ≤ 16 bits by
        # construction (weighted caps at 65535, random at 0x7FFF,
        # depth at the constraint capacity), so KSENT only bites on
        # absurd B — where a key collision merely falls back to
        # lane-order tie-breaking, still a valid admission order.
        KSENT = min(1 << 16, (2 ** 31 - 1 - (B - 1)) // B)
        kcap = jnp.minimum(key, KSENT - 1)
        ukey = jnp.where(req2, kcap, KSENT) * B + loc
        skey = jnp.sort(ukey, axis=1)
        order = (skey % B).astype(I32)
        rank = jax.vmap(jnp.searchsorted)(skey, ukey).astype(I32)
    else:
        key = jnp.where(req2, key, 1 << 20)  # non-requesters sort last
        order = jnp.argsort(key, axis=1, stable=True).astype(I32)
        # rank = inverse permutation of order; argsort(order) IS that
        # inverse, and sorts lower on TPU than a [G, B] scatter
        rank = jnp.argsort(order, axis=1).astype(I32)
    # beam: admit at most B//4 forks per block per superstep (shallowest
    # first via the key above) — the frontier analog of a beam width
    # (reference: beam.py ⚠unv); the rest defer/drop by mode
    n_adm = (jnp.minimum(n_free, max(1, B // 4))
             if fork_policy == "beam" else n_free)
    if fork_impl == "packed":
        # destination-major mapping (scatter-free, compare-free): the
        # free slot with free-rank t receives the t-th admitted request
        # — precisely the pairing the legacy source-major formulation
        # produced via free_ids[rank] — so a cumsum over the free mask
        # plus one gather of `order` replaces the [G, B] scatter (CPU
        # legacy) / [G, B, B] one-hot compare (TPU legacy, the O(P²)
        # superlinear term tools/scaling_report.py names).
        if fork_policy == "fifo":
            # requesters in lane order; B pads the tail (never gathered:
            # free_rank < n_admit <= n_req keeps the index in-range)
            order = jnp.sort(jnp.where(req2, loc, B), axis=1).astype(I32)
        n_req = jnp.sum(req2.astype(I32), axis=1, keepdims=True)
        n_admit = jnp.minimum(n_adm, n_req)
        free_rank = jnp.cumsum(free2.astype(I32), axis=1) - free2.astype(I32)
        is_copy2 = free2 & (free_rank < n_admit)
        src_i = jnp.take_along_axis(
            order, jnp.clip(free_rank, 0, B - 1), axis=1)
        src2 = jnp.where(is_copy2, src_i, jnp.broadcast_to(loc, (G, B)))
        is_copy = is_copy2.reshape(P)
        # per-source admission bit (drop/defer accounting): admitted
        # requests are exactly those ranked inside the admission window
        slot = jnp.where(req2 & (rank < n_adm), 0, P).reshape(P)
    elif fork_impl == "legacy":
        free_ids = jnp.sort(jnp.where(free2, loc, B), axis=1)
        slot2 = jnp.where(
            req2 & (rank < n_adm),
            jnp.take_along_axis(free_ids, jnp.clip(rank, 0, B - 1), axis=1),
            B,
        )  # local free-slot index per forking lane; B = dropped
        if ci._use_scatter():
            src2 = jnp.broadcast_to(loc, (G, B)).at[gidx, slot2].set(
                jnp.broadcast_to(loc, (G, B)), mode="drop")
            is_copy = jnp.zeros((G, B), dtype=bool).at[gidx, slot2].set(
                True, mode="drop").reshape(P)
        else:
            # dense inverse-map: dst j is a copy iff some source i chose it
            # (slot2 values are unique: distinct ranks -> distinct free ids),
            # and its source is that i. [G, B, B] compare instead of scatter.
            eq = slot2[:, :, None] == jnp.arange(B, dtype=I32)[None, None, :]
            is_copy2 = jnp.any(eq, axis=1)
            src_i = jnp.argmax(eq, axis=1).astype(I32)
            src2 = jnp.where(is_copy2, src_i, jnp.broadcast_to(loc, (G, B)))
            is_copy = is_copy2.reshape(P)
        slot = jnp.where(slot2 < B,
                         slot2 + jnp.arange(G, dtype=I32)[:, None] * B,
                         P).reshape(P)
    else:
        raise ValueError(f"unknown fork_impl: {fork_impl}")
    return src2, is_copy, slot


def expand_forks(sf: SymFrontier, loop_bound: int = 0,
                 fork_block: int = 0,
                 fork_policy: str = "fifo",
                 defer_starved: bool = False,
                 visited=None,
                 fork_impl: str = "packed") -> SymFrontier:
    """Materialize fork requests: copy each forking lane into a free lane
    (prefix-sum compaction), point the copy at the jump target, and flip
    its final path-condition sign to "taken". Forks beyond capacity are
    counted in ``dropped_forks`` (the frontier equivalent of the
    reference's unbounded ``work_list.append`` ⚠unv). A copy whose taken
    target is a BACKWARD jump feeds the bounded-loops policy.

    ``defer_starved=True`` (SURVEY §5.7 spill machinery, VERDICT r3 ask
    #3) turns the drop channel into a RETRY: a request with no free lane
    un-executes its branch decision — pc back on the JUMPI (or still
    parked on the CALL), operand pops and the appended constraint undone
    — and the lane re-raises the identical request next superstep, when
    retiring lanes may have freed slots. ``fork_req`` stays set on parked
    lanes so the host seam can see persistent starvation and rebalance
    them into other blocks' free lanes (``rebalance_parked``); nothing is
    lost inside a chunk.

    ``fork_block`` makes the compaction SHARD-LOCAL (VERDICT r2 ask #5):
    with the lane axis sharded over devices, a global cumsum/sort would
    gather the whole frontier every superstep. Blocked, every reduction /
    sort / gather runs along the intra-block axis — lanes fork only into
    free lanes of their own block, so a block-aligned sharding never
    communicates here. ``0`` means one global block (single-chip default);
    results are identical for equal blocking regardless of the mesh.

    ``fork_policy`` is the search-strategy lever (reference: BFS/DFS
    ``BasicSearchStrategy`` orderings ⚠unv, SURVEY §1 row 7 — here the
    frontier steps together, so ordering only matters when fork slots run
    short): "fifo" admits by lane order, "shallow" prefers forks with the
    SHORTEST path condition (breadth-flavored), "deep" the longest
    (depth-flavored).

    ``fork_impl`` selects the source→slot mapping machinery (the scaling
    cliff's named term — docs/performance.md "Scaling cliff"):

    - ``"packed"`` (default): scatter-free on EVERY backend. One sort of
      a packed (key, lane) composite yields the admission order; the
      per-lane admission rank comes from a searchsorted over the unique
      composites (no argsort-of-argsort); and the destination map is
      built destination-major — free slot j with free-rank t copies from
      ``order[t]`` — a cumsum + gather instead of the legacy [G, B, B]
      one-hot compare (O(P²) when fork_block=0) or [G, B] scatter.
    - ``"legacy"``: the pre-restructure path (double argsort + backend-
      adaptive scatter/dense inverse map), kept as the byte-parity
      baseline (tests/test_superstep_parity.py) and for
      tools/scaling_report.py to attribute the old curve.

    Both produce identical frontiers for identical inputs.
    """
    P = sf.n_lanes
    if fork_block > 0 and P % fork_block != 0:
        # silent fallback would reintroduce the cross-shard gather the
        # blocking exists to avoid — surface the misconfiguration
        raise ValueError(f"fork_block {fork_block} must divide P={P}")
    if fork_block <= 0:
        fork_block = P
    B = fork_block
    G = P // B
    # a lane the feasibility sweep killed between its request and this
    # expansion must NOT be copied back to life (its con_len was already
    # unwound, so the sign-flip would land on an unrelated constraint)
    req_live = sf.fork_req & sf.base.active
    req2 = req_live.reshape(G, B)
    free2 = (~sf.base.active).reshape(G, B)
    if fork_policy == "fifo":
        key = None
    else:
        depth = sf.con_len.reshape(G, B)
        C = sf.con_node.shape[1]
        if fork_policy in ("shallow", "beam"):
            key = depth
        elif fork_policy == "deep":
            key = C - depth
        elif fork_policy in ("weighted", "random"):
            # shared per-(lane, target, depth) hash — deterministic
            # (counter-free) so runs replay exactly. "weighted" scales it
            # by path depth (reference: the weighted-random strategy's
            # 2^-depth bias ⚠unv, SURVEY §1 row 7 — shallow paths
            # usually win but a lucky deep fork can jump the queue);
            # "random" uses it raw (reference: ``strategy/basic.py``
            # naive-random ordering ⚠unv, no depth bias).
            h = (jnp.arange(P, dtype=jnp.uint32) * jnp.uint32(2654435761)
                 + sf.fork_dest.astype(jnp.uint32) * jnp.uint32(40503)
                 + sf.con_len.astype(jnp.uint32) * jnp.uint32(131))
            h = (h >> 16) ^ h
            if fork_policy == "weighted":
                key = ((h.astype(I32) & 1023).reshape(G, B)
                       * (depth + 1)) % 65536
            else:
                key = (h & jnp.uint32(0x7FFF)).astype(I32).reshape(G, B)
        elif fork_policy == "coverage":
            # coverage-guided: forks whose taken target has NOT been
            # visited admit first (reference: coverage_strategy wrapper
            # ⚠unv); ties resolve by lane order (stable sort)
            if visited is None:
                key = jnp.zeros((G, B), dtype=I32)
            else:
                MC = visited.shape[1]
                seen = visited[
                    jnp.clip(sf.base.contract_id, 0, visited.shape[0] - 1),
                    jnp.clip(sf.fork_dest, 0, MC - 1)]
                key = seen.astype(I32).reshape(G, B)
        else:
            raise ValueError(f"unknown fork_policy: {fork_policy}")
    src2, is_copy, slot = plan_fork_map(req2, free2, key,
                                        fork_policy, fork_impl)
    req = req_live

    # the iprof residual sidecar is lane-independent: detach it so the
    # lane-axis gather below never touches it (and cannot mistake the
    # [256] row for a [P]-shaped leaf when P happens to equal 256)
    resid = sf.base.op_resid
    if resid is not None:
        sf = sf.replace(base=sf.base.replace(op_resid=None))

    # scalar run-total counters pass through untouched (ndim == 0); they
    # must not be gathered over the lane axis. The gather itself runs
    # along the intra-block axis only.
    def _gather(x):
        if x.ndim == 0:
            return x
        xb = x.reshape((G, B) + x.shape[1:])
        idx = src2.reshape((G, B) + (1,) * (x.ndim - 1))
        return jnp.take_along_axis(xb, idx, axis=1).reshape(x.shape)

    new = jax.tree.map(_gather, sf)
    b = new.base
    C = new.con_sign.shape[1]
    last = (jnp.arange(C)[None, :] == (new.con_len - 1)[:, None]) & is_copy[:, None]
    # fork copies must not inherit the source lane's loss counter — that
    # would double-count every prior drop once per fork
    starved = req & (slot == P)
    n_dropped = jnp.zeros(P, I32) if defer_starved else starved.astype(I32)
    dropped = jnp.where(is_copy, 0, new.dropped_forks) + n_dropped
    # the source lane sits at (JUMPI pc)+1 after the superstep, so a taken
    # target strictly below the copied pc is a backward jump
    back_copy = is_copy & (new.fork_dest < b.pc)
    # symbolic-callee forks: the copy re-executes the CALL with the target
    # stack slot concretized to the candidate address (its flipped EQ
    # constraint asserts to == addr, so the concrete write is faithful)
    cs = new.fork_cslot
    S = b.stack.shape[1]
    cidx = jnp.where(is_copy & (cs >= 0) & (cs < S), cs, S).astype(I32)
    stack_c = ci._write_slot(b.stack, cidx, new.fork_cval)
    stack_sym_c = ci._write_slot(new.stack_sym, cidx, 0)

    is_cf = cs >= 0  # call-enumeration fork (source parked on the CALL)
    if defer_starved:
        # un-execute the branch decision so the lane retries next superstep:
        # JUMPI sources step back onto the branch and re-push its operands;
        # CALL sources (already parked) rewind the candidate counter; both
        # pop the constraint the handler appended this superstep
        pc_new = jnp.where(is_copy, new.fork_dest,
                           jnp.where(starved & ~is_cf, b.pc - 1, b.pc))
        sp_new = jnp.where(starved & ~is_cf, b.sp + 2, b.sp)
        con_len_new = new.con_len - starved.astype(I32)
        # the retried JUMPI re-pays its static charge next superstep
        # (10 = G_HIGH; schedule-independent); CALL retries refund inside
        # the call handler itself
        g_undo = jnp.where(starved & ~is_cf, 10, 0).astype(b.gas_min.dtype)
        b = b.replace(gas_min=b.gas_min - g_undo, gas_max=b.gas_max - g_undo)
        if b.op_hist is not None:
            # iprof: the un-executed JUMPI re-runs next superstep — take
            # back epilogue's +1 so the retry loop nets to one count
            # (0x57 = JUMPI; non-call forks only come from JUMPI)
            b = b.replace(op_hist=b.op_hist.at[:, 0x57].add(
                -(starved & ~is_cf).astype(I32)))
        call_enum_new = jnp.where(
            is_copy, 0, new.call_enum - (starved & is_cf).astype(I32))
        fork_req_new = starved
    else:
        pc_new = jnp.where(is_copy, new.fork_dest, b.pc)
        sp_new = b.sp
        con_len_new = new.con_len
        call_enum_new = jnp.where(is_copy, 0, new.call_enum)
        fork_req_new = jnp.zeros_like(new.fork_req)
    if b.op_hist is not None:
        # iprof: a fork copy starts with an empty executed-op histogram —
        # its pre-fork instructions were already counted on the source
        # lane. But the RECYCLED slot may hold a retired lane's not-yet-
        # harvested counts (harvest only runs at tx boundaries): those
        # rows accumulate into the residual sidecar before the zeroing —
        # harvest sums every row plus the sidecar, so totals are
        # conserved while every live lane's row stays its own (ADVICE
        # r5). Legacy frontiers without the sidecar fold into a live
        # lane's row as before.
        dead_rows = jnp.sum(
            jnp.where(is_copy[:, None], sf.base.op_hist, 0), axis=0,
            dtype=I32)
        if resid is not None:
            resid = resid + dead_rows
            b = b.replace(op_hist=jnp.where(is_copy[:, None], 0, b.op_hist))
        else:
            tgt = jnp.argmax(b.active & ~is_copy).astype(I32)
            b = b.replace(
                op_hist=jnp.where(is_copy[:, None], 0, b.op_hist)
                .at[tgt].add(dead_rows))
    new = new.replace(
        base=b.replace(
            pc=pc_new,
            sp=sp_new,
            active=b.active | is_copy,
            stack=stack_c,
            op_resid=resid,
        ),
        stack_sym=stack_sym_c,
        con_sign=jnp.where(last, True, new.con_sign),
        con_len=con_len_new,
        fork_req=fork_req_new,
        fork_cslot=jnp.full_like(new.fork_cslot, -1),
        fork_cval=jnp.zeros_like(new.fork_cval),
        # a concretized copy is no longer enumerating; its next symbolic
        # call site (if any) must scan the table from slot 0
        call_enum=call_enum_new,
        dropped_forks=dropped,
        dropped_total=new.dropped_total + jnp.sum(n_dropped, dtype=I32),
    )
    return _note_backjump(new, back_copy, b.pc - 1, new.fork_dest, loop_bound)


def rebalance_parked(sf: SymFrontier, fork_block: int = 0,
                     active=None, fork_req=None):
    """Move persistently starved fork-requesting lanes into other blocks'
    free slots. Host-planned at the chunk seam, device-applied as one
    gather/scatter per leaf — the jitted superstep loop stays shard-local
    (SURVEY §5.7 spill-to-host overflow + §5.8 cross-device rebalancing:
    only the scheduler boundary communicates).

    A lane parked on a starved fork (``fork_req`` still set after
    ``expand_forks`` with ``defer_starved``) whose own block has no free
    slot is RELOCATED to the block with the most free slots (needs >= 2:
    one for the lane, one for the fork it will re-raise); its old slot
    frees up for its neighbors. Returns ``(sf, n_moved)``.

    ``active``/``fork_req`` accept host copies of those leaves a caller
    already transferred this chunk boundary (SymExecWrapper shares ONE
    fetch between this planner, the drain check, and the telemetry
    gauges) — each is a device→host sync, and paying it twice per chunk
    was measurable on the device path."""
    import numpy as np

    if active is None:
        active = np.asarray(sf.base.active)
    if fork_req is None:
        fork_req = np.asarray(sf.fork_req)
    parked = np.asarray(fork_req) & np.asarray(active)
    if not parked.any():
        return sf, 0
    P = parked.shape[0]
    B = fork_block if fork_block > 0 else P
    G = P // B
    free = ~np.asarray(active)
    free_cnt = free.reshape(G, B).sum(axis=1)
    free_lists = [list(np.where(free.reshape(G, B)[g])[0] + g * B)
                  for g in range(G)]
    src_idx, dst_idx = [], []
    for lane in np.where(parked)[0]:
        g = lane // B
        if free_cnt[g] > 0:
            continue  # the local retry will succeed on its own
        g2 = int(np.argmax(free_cnt))
        if free_cnt[g2] < 2:
            continue  # no global headroom for (lane + its fork)
        dst = free_lists[g2].pop()
        free_cnt[g2] -= 1
        src_idx.append(int(lane))
        dst_idx.append(int(dst))
        # the vacated slot serves the source block's remaining requests
        free_cnt[g] += 1
        free_lists[g].append(int(lane))
    if not src_idx:
        return sf, 0
    src = jnp.asarray(src_idx, dtype=I32)
    dst = jnp.asarray(dst_idx, dtype=I32)

    # lane-independent residual sidecar: keep it out of the lane move
    resid = sf.base.op_resid
    if resid is not None:
        sf = sf.replace(base=sf.base.replace(op_resid=None))

    def move(x):
        if not hasattr(x, "ndim") or x.ndim == 0:
            return x
        return x.at[dst].set(x[src])

    new = jax.tree.map(move, sf)
    b = new.base.replace(active=new.base.active.at[src].set(False))
    if b.op_hist is not None:
        # iprof: the lane's counts moved with it; the vacated slot must
        # not keep a stale copy (the harvest sums every row), and the
        # DESTINATION slots' pre-move rows (a retired lane's unharvested
        # counts) must not vanish — they land in the residual sidecar
        # (legacy frontiers without one: fold into the first moved row)
        dead_rows = jnp.sum(sf.base.op_hist[dst], axis=0, dtype=I32)
        if resid is not None:
            resid = resid + dead_rows
            b = b.replace(op_hist=b.op_hist.at[src].set(0))
        else:
            b = b.replace(
                op_hist=b.op_hist.at[src].set(0).at[dst[0]].add(dead_rows))
    return new.replace(
        base=b.replace(op_resid=resid),
        fork_req=new.fork_req.at[src].set(False),
    ), len(src_idx)


def migrate_parked_device(sf: SymFrontier, fork_block: int,
                          mig_cap: int = 8) -> SymFrontier:
    """In-jit cross-block migration of starved fork-requesting lanes.

    The TPU-native tier of SURVEY §5.8's "cross-device rebalancing":
    where ``rebalance_parked`` plans on the host at the CHUNK seam (a
    device→host→device round trip — DCN on a pod), this runs INSIDE the
    jitted superstep loop. The only cross-block data flow is a compact
    ``[G, MIG]`` lane-payload buffer: every reduction/cumsum runs along
    the intra-block axis (shard-local under a block-aligned lane
    sharding), the assignment plan is [G]-shaped metadata, and GSPMD
    lowers the buffer exchange to a small all-gather that rides ICI.
    The reference has no analog (single process, unbounded worklist —
    ``mythril/laser/ethereum/svm.py`` ⚠unv); the pattern is the
    scaling-playbook "communicate at the scheduler boundary, and only
    compact state".

    Semantics (mirrors the host planner): a lane parked on a starved
    fork (``defer_starved`` retry machinery) whose block has ZERO free
    slots is moved to a block with >= 2 free slots (one for the lane,
    one headroom for the fork it re-raises next superstep); freer blocks
    fill first; at most ``mig_cap`` lanes leave or enter any block per
    call (bounded buffer — the rest stay parked and retry). The moved
    lane keeps ``fork_req`` set; its old slot deactivates. iprof rows
    travel with the lane; a replaced slot's unharvested row folds into
    the migrant's row so harvest totals are conserved.
    """
    P = sf.n_lanes
    B = fork_block if fork_block > 0 else P
    G = P // B
    if G <= 1:
        return sf  # single block: nothing to migrate into
    MIG = max(1, min(mig_cap, B // 2))
    NF = G * MIG  # flat buffer size

    # lane-independent residual sidecar: keep it out of the lane-axis
    # reshape/gather below (reattached, with any newly orphaned rows,
    # at the end — structure in == structure out, as lax.cond requires)
    resid = sf.base.op_resid
    if resid is not None:
        sf = sf.replace(base=sf.base.replace(op_resid=None))

    ab = sf.base.active.reshape(G, B)
    stb = (sf.fork_req & sf.base.active).reshape(G, B)
    freeb = ~ab
    fc = jnp.sum(freeb, axis=1, dtype=I32)            # free slots per block
    expb = stb & (fc == 0)[:, None]                    # exportable lanes
    r_exp = jnp.cumsum(expb.astype(I32), axis=1) - 1   # intra-block rank
    sel = expb & (r_exp < MIG)
    n_exp = jnp.minimum(jnp.sum(expb, axis=1, dtype=I32), MIG)

    # export buffer slot j <- intra-block lane with rank j (B = empty pad)
    hit = sel[:, :, None] & (r_exp[:, :, None] == jnp.arange(MIG)[None, None, :])
    exp_idx = jnp.where(jnp.any(hit, axis=1),
                        jnp.argmax(hit, axis=1), B).astype(I32)  # [G, MIG]

    # import capacity: fc-1 keeps one slot of fork headroom; freer blocks
    # get lower global import ranks so they fill first
    cap = jnp.clip(fc - 1, 0, MIG)
    order = jnp.argsort(-fc, stable=True)
    cap_sorted = cap[order]
    ioff_sorted = jnp.cumsum(cap_sorted) - cap_sorted  # exclusive prefix
    ioff = jnp.zeros(G, I32).at[order].set(ioff_sorted.astype(I32))
    total_cap = jnp.sum(cap, dtype=I32)

    eoff = (jnp.cumsum(n_exp) - n_exp).astype(I32)     # global export ranks
    total_exp = jnp.sum(n_exp, dtype=I32)
    M = jnp.minimum(total_exp, total_cap)              # matched moves

    # flat buffer id per global export rank (NF = unmatched sentinel)
    grank = eoff[:, None] + jnp.arange(MIG, dtype=I32)[None, :]
    valid_e = jnp.arange(MIG)[None, :] < n_exp[:, None]
    flat_ids = jnp.arange(NF, dtype=I32).reshape(G, MIG)
    src_of_rank = jnp.full(NF, NF, I32).at[
        jnp.where(valid_e, grank, NF)].set(flat_ids, mode="drop")

    # t-th free slot of block g receives global import rank ioff[g] + t
    r_free = jnp.cumsum(freeb.astype(I32), axis=1) - 1
    imp_take = jnp.clip(M - ioff, 0, cap)              # imports per block
    is_imp = freeb & (r_free < imp_take[:, None])      # [G, B]
    q = ioff[:, None] + r_free
    srcflat = src_of_rank[jnp.clip(q, 0, NF - 1)]      # [G, B]
    srcflat = jnp.where(is_imp, srcflat, 0)            # harden pads

    exported = sel & ((eoff[:, None] + r_exp) < M)     # claimed -> vacate
    imp_flat = is_imp.reshape(P)

    def mv(x):
        if not hasattr(x, "ndim") or x.ndim == 0:
            return x
        rest = x.shape[1:]
        xb = x.reshape((G, B) + rest)
        idx = jnp.clip(exp_idx, 0, B - 1).reshape(
            (G, MIG) + (1,) * len(rest))
        buf = jnp.take_along_axis(
            xb, jnp.broadcast_to(idx, (G, MIG) + rest), axis=1)
        flat = buf.reshape((NF,) + rest)
        vals = flat[srcflat]                           # [G, B, ...] from NF
        sel_imp = is_imp.reshape((G, B) + (1,) * len(rest))
        return jnp.where(sel_imp, vals, xb).reshape(x.shape)

    new = jax.tree.map(mv, sf)
    vac = exported.reshape(P)
    b = new.base.replace(active=new.base.active & ~vac)
    if b.op_hist is not None:
        # migrant rows travelled via mv(); vacated rows zero (they
        # moved); replaced slots' pre-import rows (retired-lane counts
        # harvest has not seen) accumulate into the residual sidecar —
        # totals are conserved because harvest sums every row plus the
        # sidecar, and no live lane's row absorbs another lane's counts
        # (ADVICE r5). Legacy frontiers without a sidecar keep the old
        # fold into the first imported slot's row.
        dead_rows = jnp.sum(
            jnp.where(imp_flat[:, None], sf.base.op_hist, 0),
            axis=0).astype(I32)
        if resid is not None:
            resid = resid + dead_rows
            b = b.replace(op_hist=jnp.where(vac[:, None], 0, b.op_hist))
        else:
            tgt = jnp.argmax(imp_flat).astype(I32)
            b = b.replace(op_hist=jnp.where(vac[:, None], 0, b.op_hist)
                          .at[tgt].add(jnp.where(jnp.any(imp_flat),
                                                 dead_rows, 0)))
    return new.replace(base=b.replace(op_resid=resid),
                       fork_req=new.fork_req & ~vac)


def _sym_run_impl(sf: SymFrontier, env: Env, corpus: Corpus,
                  spec: SymSpec = SymSpec(),
                  limits: LimitsConfig = DEFAULT_LIMITS,
                  max_steps: int = 256,
                  propagate_every=None,
                  fork_block: int = 0,
                  track_coverage: bool = False,
                  fork_policy: str = "fifo",
                  defer_starved: bool = False,
                  migrate_every: int = 0,
                  fork_impl: str = "packed",
                  unroll: int = 1):
    """Run the symbolic engine until quiescence or max_steps supersteps.
    ``propagate_every`` > 0 interleaves feasibility sweeps that kill
    provably-unsat lanes (reference: lazy ``Solver.check()`` pruning);
    0 disables them; None uses ``limits.propagate_every``.
    ``fork_block`` confines fork compaction to lane blocks (pass the
    per-device lane count when sharding the lane axis).
    ``track_coverage=True`` additionally returns a ``bool[C, MAX_CODE]``
    visited-pc bitmap (reference: InstructionCoveragePlugin ⚠unv) —
    return type becomes ``(sf, visited)``.
    ``migrate_every`` > 0 (with ``defer_starved`` and a multi-block
    ``fork_block``) runs the in-jit cross-block lane migration
    (``migrate_parked_device``) every that many supersteps — the ICI
    tier of SURVEY §5.8's rebalancing; the host-seam
    ``rebalance_parked`` remains the chunk-boundary tier.
    ``fork_impl`` selects :func:`expand_forks`' slot-mapping machinery
    ("packed" scatter-free default / "legacy" parity baseline).
    ``unroll`` > 1 rolls that many supersteps into ONE while-loop body
    (Python-unrolled at trace time), amortizing the loop's per-iteration
    carry handling over K steps. Byte-parity with unroll=1 is preserved:
    the quiescence check runs every K steps instead of every step, but a
    quiesced frontier's supersteps are exact no-ops (every write is
    masked by ``running``), and the cadence-gated passes (propagation
    sweep, migration) gain an explicit any-running gate so a tail step
    after mid-block quiescence cannot fire them where the per-step loop
    would have exited. Cadences stay anchored to the absolute step index.
    ``unroll`` values not dividing ``max_steps`` are lowered to the
    largest divisor so the loop cannot overshoot the step budget."""
    from .propagate import kill_infeasible

    if propagate_every is None:
        propagate_every = limits.propagate_every
    unroll = max(1, int(unroll))
    while unroll > 1 and max_steps % unroll:
        unroll -= 1

    P_run = sf.n_lanes
    C, MC = corpus.code.shape
    visited0 = jnp.zeros((C, MC), dtype=bool)

    def cond(state):
        i, s, _ = state
        return (i < max_steps) & jnp.any(s.base.running)

    def one_step(i, s, visited):
        if unroll > 1:
            # the per-step loop re-checks its cond BEFORE each body: a
            # step that begins quiesced never runs — including its
            # cadence passes. Unrolled tail steps replicate that exact
            # gate with the ENTRY state (post-superstep running would
            # over-suppress: a sweep whose step started live runs in the
            # per-step path even when that step quiesced the frontier)
            alive = jnp.any(s.base.running)
        if track_coverage:
            # init-frame pcs index the per-lane init buffer, not the
            # contract image — they must not pollute its bitmap
            run = s.base.running & ~s.base.exec_init
            cid = jnp.where(run, s.base.contract_id, C)
            pc = jnp.clip(s.base.pc, 0, MC - 1)
            visited = visited.at[cid, pc].set(True, mode="drop")
        s = sym_superstep(s, env, corpus, spec, limits)
        # expand_forks tree-gathers EVERY leaf of the frontier; gate it so
        # supersteps with no pending fork request (the common case) skip
        # that full-frontier pass. Identity-valued when no live request.
        pred = jnp.any(s.fork_req & s.base.active)
        if unroll > 1:
            pred = pred & alive
        s = lax.cond(
            pred,
            lambda x: expand_forks(x, limits.loop_bound, fork_block,
                                   fork_policy, defer_starved,
                                   visited if track_coverage else None,
                                   fork_impl),
            lambda x: x,
            s,
        )
        if propagate_every:
            gate = (i % propagate_every) == propagate_every - 1
            if unroll > 1:
                gate = gate & alive
            s = ci.narrow_cond(
                gate,
                kill_infeasible, s,
                ("iv_lo", "iv_hi", "kb_m", "kb_v", "prop_len",
                 "base.active", "fork_req", "killed_infeasible",
                 "killed_total"),
            )
        if migrate_every > 0 and defer_starved and 0 < fork_block < P_run:
            # fire only when some block is BOTH exhausted and starving —
            # the [G] predicate is metadata-cheap; the payload pass is
            # inside the cond
            Bm = fork_block
            abm = s.base.active.reshape(P_run // Bm, Bm)
            stm = (s.fork_req & s.base.active).reshape(P_run // Bm, Bm)
            occ = jnp.sum(abm, axis=1)
            # a starving exhausted block AND a destination with >= 2 free
            # slots — without the capacity side a saturated frontier would
            # pay the full-leaf no-op migration pass every firing
            need = (jnp.any(jnp.any(stm, axis=1) & (occ == Bm))
                    & jnp.any(occ <= Bm - 2))
            if unroll > 1:
                need = need & alive
            s = lax.cond(
                ((i % migrate_every) == migrate_every - 1) & need,
                lambda x: migrate_parked_device(x, fork_block),
                lambda x: x,
                s,
            )
        return s, visited

    def body(state):
        i, s, visited = state
        for k in range(unroll):
            s, visited = one_step(i + k, s, visited)
        return i + unroll, s, visited

    _, sf, visited = lax.while_loop(cond, body, (jnp.int32(0), sf, visited0))
    return (sf, visited) if track_coverage else sf


_SYM_RUN_STATIC = ("spec", "limits", "max_steps", "propagate_every",
                   "fork_block", "track_coverage", "fork_policy",
                   "defer_starved", "migrate_every", "fork_impl", "unroll")

sym_run = jax.jit(_sym_run_impl, static_argnames=_SYM_RUN_STATIC)

# Donating entry for callers that consume their input frontier (the
# analysis chunk loop rebinds ``sf`` on every call): XLA aliases the
# input buffers into the outputs, so the superstep loop's carry never
# holds two copies of a multi-GiB frontier. Never use this where the
# input ``sf`` is reused afterwards (bench reps, parity tests). CPU
# ignores donation — callers gate on backend to avoid warning spam.
sym_run_donated = jax.jit(_sym_run_impl, static_argnames=_SYM_RUN_STATIC,
                          donate_argnums=(0,))


# Resolve the host-callback capability now, at import — OUTSIDE any jax
# trace. Probing lazily from inside a traced `_apply_precompiles` embeds
# the probe's callback into the outer program as dead code, which the
# axon runtime then refuses to compile (ops/callbacks.py has the full
# story). Import of this module already initializes the backend (the
# jnp metadata tables above), so this adds one trivial extra compile.
from ..ops.callbacks import host_callbacks_supported as _probe_host_callbacks  # noqa: E402

_probe_host_callbacks()
