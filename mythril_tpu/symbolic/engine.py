"""The symbolic superstep: concrete dispatch + sym-id overlay + forking.

Counterpart of the reference's symbolic ``Instruction.evaluate`` over Z3
expressions and ``jumpi_``'s state forking
(``mythril/laser/ethereum/instructions.py`` ⚠unv, SURVEY.md §3.2), but
frontier-first:

- lanes whose current opcode touches symbolic control/addresses are
  *claimed* out of the concrete dispatch and handled by sym-aware
  handlers (storage, jumps, calls, symbolic-offset memory ops);
- everything else runs the concrete handler unchanged, and a vectorized
  overlay keeps ``stack_sym``/``mem_sym`` in sync and appends tape nodes;
- a symbolic JUMPI records a fork request; :func:`expand_forks` performs
  masked lane duplication + prefix-sum compaction into free lanes
  (the reference's ``work_list.append`` of forked GlobalStates).

Over-approximation policy: wherever byte-exact symbolic tracking is not
worth the shapes (unaligned accesses, symbolic offsets, ADDMOD), the
result is a fresh unconstrained HAVOC leaf — never a wrong value, so the
engine may explore infeasible paths but never misses feasible ones.
"""

from __future__ import annotations

import functools

import numpy as np
import jax
import jax.numpy as jnp
from jax import lax

from ..config import LimitsConfig, DEFAULT_LIMITS
from ..core import interpreter as ci
from ..core.frontier import Frontier, Env, Corpus, Trap
from ..ops import u256
from .ops import SymOp, FreeKind, TX_STRIDE
from .state import SymFrontier, SymSpec

I32 = jnp.int32
I64 = jnp.int64
U32 = jnp.uint32

# EVM opcode -> SymOp for plain binary/unary value ops (0 = no mapping)
def _binop_table() -> np.ndarray:
    t = np.zeros(256, dtype=np.int32)
    m = {
        0x01: SymOp.ADD, 0x02: SymOp.MUL, 0x03: SymOp.SUB, 0x04: SymOp.DIV,
        0x05: SymOp.SDIV, 0x06: SymOp.MOD, 0x07: SymOp.SMOD, 0x0A: SymOp.EXP,
        0x0B: SymOp.SIGNEXTEND, 0x10: SymOp.LT, 0x11: SymOp.GT,
        0x12: SymOp.SLT, 0x13: SymOp.SGT, 0x14: SymOp.EQ, 0x15: SymOp.ISZERO,
        0x16: SymOp.AND, 0x17: SymOp.OR, 0x18: SymOp.XOR, 0x19: SymOp.NOT,
        0x1A: SymOp.BYTE, 0x1B: SymOp.SHL, 0x1C: SymOp.SHR, 0x1D: SymOp.SAR,
    }
    for k, v in m.items():
        t[k] = int(v)
    return t


_J_BINOP = jnp.asarray(_binop_table())


# ---------------------------------------------------------------------------
# Tape + sym-stack helpers
# ---------------------------------------------------------------------------


def _peek_sym(sf: SymFrontier, i) -> jnp.ndarray:
    sp = sf.base.sp
    S = sf.stack_sym.shape[1]
    idx = jnp.clip(sp - 1 - i, 0, S - 1)
    return jnp.take_along_axis(sf.stack_sym, idx[:, None].astype(I32), axis=1)[:, 0]


def _set_sym_slot(stack_sym, pos, val, mask):
    S = stack_sym.shape[1]
    sel = (jnp.arange(S)[None, :] == pos[:, None]) & mask[:, None]
    return jnp.where(sel, val[:, None], stack_sym)


def append_node(sf: SymFrontier, mask, op, a, b, imm=None):
    """Hash-consed tape append. op/a/b scalar or i32[P]; imm u32[P,8]|None.
    Returns (sf, ids) — id per lane (0 where ~mask). Overflow errors lane."""
    P, T = sf.tape_op.shape
    op = jnp.broadcast_to(jnp.asarray(op, I32), (P,))
    a = jnp.broadcast_to(jnp.asarray(a, I32), (P,))
    b = jnp.broadcast_to(jnp.asarray(b, I32), (P,))
    if imm is None:
        imm = jnp.zeros((P, 8), dtype=U32)
    live = jnp.arange(T)[None, :] < sf.tape_len[:, None]
    match = (
        live
        & (sf.tape_op == op[:, None])
        & (sf.tape_a == a[:, None])
        & (sf.tape_b == b[:, None])
        & jnp.all(sf.tape_imm == imm[:, None, :], axis=-1)
    )
    hit = jnp.any(match, axis=1)
    hit_id = jnp.argmax(match, axis=1).astype(I32)
    overflow = mask & ~hit & (sf.tape_len >= T)
    write = mask & ~hit & ~overflow
    onehot = (jnp.arange(T)[None, :] == sf.tape_len[:, None]) & write[:, None]
    ids = jnp.where(mask, jnp.where(hit, hit_id, jnp.where(write, sf.tape_len, 0)), 0)
    return (
        sf.replace(
            tape_op=jnp.where(onehot, op[:, None], sf.tape_op),
            tape_a=jnp.where(onehot, a[:, None], sf.tape_a),
            tape_b=jnp.where(onehot, b[:, None], sf.tape_b),
            tape_imm=jnp.where(onehot[:, :, None], imm[:, None, :], sf.tape_imm),
            tape_len=sf.tape_len + write.astype(I32),
            base=sf.base.trap(overflow, Trap.TAPE_LIMIT),
        ),
        ids,
    )


def _sym_or_const(sf: SymFrontier, mask, sym, limbs):
    """Operand id: existing sym, id 0 for concrete zero, CONST node else."""
    need = mask & (sym == 0) & ~u256.is_zero(limbs)
    sf, cid = append_node(sf, need, int(SymOp.CONST), 0, 0, limbs)
    return sf, jnp.where(sym != 0, sym, cid)


def _havoc(sf: SymFrontier, mask):
    """Fresh unconstrained leaf per lane (unique via per-lane counter)."""
    sf2, ids = append_node(
        sf, mask, int(SymOp.FREE), int(FreeKind.HAVOC), sf.havoc_cnt
    )
    return sf2.replace(havoc_cnt=sf2.havoc_cnt + mask.astype(I32)), ids


def _event_slot(counter, mask, length: int):
    """Bounded per-lane event-log allocation: onehot[P, L] of the next
    free slot where `mask`; saturated logs silently drop (counter still
    counts attempts so overflow is observable)."""
    idx = jnp.minimum(counter, length - 1)
    rec = mask & (counter < length)
    return (jnp.arange(length)[None, :] == idx[:, None]) & rec[:, None]


def _lookup_constraint(sf: SymFrontier, node):
    """Is `node` already asserted on the path? -> (known, sign)."""
    C = sf.con_node.shape[1]
    live = jnp.arange(C)[None, :] < sf.con_len[:, None]
    m = live & (sf.con_node == node[:, None]) & (node[:, None] != 0)
    known = jnp.any(m, axis=1)
    idx = jnp.argmax(m, axis=1)
    sign = jnp.take_along_axis(sf.con_sign, idx[:, None], axis=1)[:, 0]
    return known, known & sign


def _append_constraint(sf: SymFrontier, mask, node, sign, pc):
    C = sf.con_node.shape[1]
    overflow = mask & (sf.con_len >= C)
    write = mask & ~overflow
    onehot = (jnp.arange(C)[None, :] == sf.con_len[:, None]) & write[:, None]
    sign = jnp.broadcast_to(jnp.asarray(sign, bool), mask.shape)
    return sf.replace(
        con_node=jnp.where(onehot, node[:, None], sf.con_node),
        con_sign=jnp.where(onehot, sign[:, None], sf.con_sign),
        con_pc=jnp.where(onehot, pc[:, None], sf.con_pc),
        con_len=sf.con_len + write.astype(I32),
        base=sf.base.trap(overflow, Trap.CONSTRAINT_LIMIT),
    )


# ---------------------------------------------------------------------------
# Claimed handlers: sym-aware replacements run after the concrete dispatch
# (their lanes were skipped there, so stack/sp are still pre-instruction)
# ---------------------------------------------------------------------------


def _h_sym_storage(sf: SymFrontier, spec: SymSpec, op, m) -> SymFrontier:
    """SLOAD/SSTORE with (possibly symbolic) keys and values.

    Key matching is syntactic: concrete keys match by limb equality,
    symbolic keys by tape node id (hash-consing makes structurally equal
    keccak keys share an id — the analog of the reference's
    KeccakFunctionManager hash-linking ⚠unv). Distinct node ids are
    treated as distinct slots; numeric aliasing between them is missed.
    """
    f = sf.base
    key = ci._peek(f, 0)
    key_sym = _peek_sym(sf, 0)
    val = ci._peek(f, 1)
    val_sym = _peek_sym(sf, 1)
    is_store = op == 0x55

    conc = (key_sym[:, None] == 0) & (sf.st_key_sym == 0) & jnp.all(
        f.st_keys == key[:, None, :], axis=-1
    )
    symm = (key_sym[:, None] != 0) & (sf.st_key_sym == key_sym[:, None])
    match = f.st_used & (conc | symm)
    hit = jnp.any(match, axis=1)
    cur = jnp.sum(jnp.where(match[:, :, None], f.st_vals, 0), axis=1).astype(U32)
    cur_sym = jnp.sum(jnp.where(match, sf.st_val_sym, 0), axis=1).astype(I32)

    # SLOAD miss -> fresh STORAGE leaf (hash-consed on key, so repeated
    # loads of the same key agree); concrete-zero when storage isn't symbolic
    miss_load = m & ~is_store & ~hit
    if spec.storage:
        sf, leaf = append_node(
            sf, miss_load, int(SymOp.FREE), int(FreeKind.STORAGE), key_sym,
            jnp.where((key_sym == 0)[:, None], key, 0).astype(U32),
        )
    else:
        leaf = jnp.zeros_like(key_sym)
    f = sf.base
    loaded = jnp.where(hit[:, None], cur, 0).astype(U32)
    loaded_sym = jnp.where(hit, cur_sym, leaf)
    stack = ci._set_slot(f.stack, f.sp - 1, loaded, m & ~is_store)
    stack_sym = _set_sym_slot(sf.stack_sym, f.sp - 1, loaded_sym, m & ~is_store)

    # SSTORE into matching-or-free slot (shared alloc policy with the
    # concrete handler)
    slot_id = jnp.argmax(match, axis=1).astype(I32)
    onehot, overflow = ci.storage_alloc(f, hit, slot_id, m & is_store)
    # SWC event records: first SSTORE after a RE-ENTERABLE external call
    # (STATICCALL/CREATE can't re-enter mutably), and first SSTORE through
    # a symbolic NON-keccak key (a direct-keccak key is a mapping access;
    # recording it would mask a later genuine arbitrary write, since only
    # the first event is kept)
    store_m = m & is_store
    first_after_call = store_m & (sf.n_mut_calls > 0) & (sf.sstore_after_call_pc < 0)
    T = sf.tape_op.shape[1]
    key_op = jnp.take_along_axis(
        sf.tape_op, jnp.clip(key_sym, 0, T - 1)[:, None], axis=1
    )[:, 0]
    key_is_hash = key_op == int(SymOp.KECCAK)
    first_arb = store_m & (key_sym != 0) & ~key_is_hash & (sf.arb_key_pc < 0)
    return sf.replace(
        base=f.replace(
            stack=stack,
            sp=jnp.where(m & is_store, f.sp - 2, f.sp),
            st_keys=jnp.where(onehot[:, :, None], key[:, None, :], f.st_keys),
            st_vals=jnp.where(onehot[:, :, None], val[:, None, :], f.st_vals),
            st_used=f.st_used | onehot,
            st_written=f.st_written | onehot,
        ).trap(overflow, Trap.STORAGE_SLOTS),
        stack_sym=stack_sym,
        st_key_sym=jnp.where(onehot, key_sym[:, None], sf.st_key_sym),
        st_val_sym=jnp.where(onehot, val_sym[:, None], sf.st_val_sym),
        sstore_after_call_pc=jnp.where(first_after_call, f.pc, sf.sstore_after_call_pc),
        arb_key_node=jnp.where(first_arb, key_sym, sf.arb_key_node),
        arb_key_pc=jnp.where(first_arb, f.pc, sf.arb_key_pc),
    )


def _h_sym_jump(sf: SymFrontier, corpus: Corpus, op, m, old_pc, known, ksign) -> SymFrontier:
    """JUMP/JUMPI with symbolic dest and/or condition.

    - symbolic unknown condition + concrete valid dest: record a fork
      request (taken branch materialized by expand_forks) and continue on
      the fallthrough with ¬cond appended to the path condition
      (reference: ``jumpi_`` returning two states ⚠unv);
    - condition already asserted on this path: no fork, follow it;
    - symbolic dest on a (possibly) taken branch: record the node for the
      ArbitraryJump detector (SWC-127) and halt that branch.
    """
    f = sf.base
    dest_w = ci._peek(f, 0)
    dest_sym = _peek_sym(sf, 0)
    cond = ci._peek(f, 1)
    cond_sym = _peek_sym(sf, 1)
    is_jumpi = op == 0x57

    dest, valid_dest = ci.validate_jump_dest(f, corpus, dest_w)
    valid_dest = valid_dest & (dest_sym == 0)

    cond_is_sym = is_jumpi & (cond_sym != 0)
    resolved = ~is_jumpi | ~cond_is_sym | known
    taken_res = jnp.where(
        is_jumpi,
        jnp.where(cond_is_sym, ksign, ~u256.is_zero(cond)),
        True,
    )

    m_res = m & resolved
    m_fork = m & ~resolved
    # resolved, taken, symbolic dest -> SWC-127 record + halt
    sym_taken = m_res & taken_res & (dest_sym != 0)
    conc_taken = m_res & taken_res & (dest_sym == 0)
    bad = conc_taken & ~valid_dest
    # unresolved, symbolic dest: fallthrough survives; record the finding
    sym_unres = m_fork & (dest_sym != 0)
    # A concrete-but-invalid dest means the taken branch is an exceptional
    # halt (the concrete engine traps it); it is intentionally not forked —
    # matching the reference, which kills invalid-jump successors. The
    # fork also requires the ¬cond constraint write to succeed: a copy
    # whose sign-flip would hit an unrelated constraint slot would carry a
    # corrupted path condition.
    con_ok = sf.con_len < sf.con_node.shape[1]
    fork_ok = m_fork & valid_dest & con_ok
    sf = _append_constraint(sf, m_fork, cond_sym, False, old_pc)

    f = sf.base
    new_pc = jnp.where(m_res & conc_taken, dest.astype(I32), old_pc + 1)
    move = (m_res & ~bad & ~sym_taken) | m_fork
    d_sp = jnp.where(is_jumpi, 2, 1)
    return sf.replace(
        base=f.replace(
            pc=jnp.where(move, new_pc, f.pc),
            sp=jnp.where(m, f.sp - d_sp, f.sp),
            halted=f.halted | sym_taken,
        ).trap(bad, Trap.BAD_JUMP),
        sym_jump_dest=jnp.where(sym_taken | sym_unres, dest_sym, sf.sym_jump_dest),
        sym_jump_pc=jnp.where(sym_taken | sym_unres, old_pc, sf.sym_jump_pc),
        fork_req=sf.fork_req | fork_ok,
        fork_dest=jnp.where(fork_ok, dest.astype(I32), sf.fork_dest),
    )


def _h_sym_callish(sf: SymFrontier, op, m, old_pc) -> SymFrontier:
    """CALL family + CREATE/CREATE2: record the event for detection
    modules, push a fresh symbolic return value (reference: ``call_``
    raising TransactionStartSignal; sub-tx semantics arrive with the
    transaction layer)."""
    f = sf.base
    is_create = (op == 0xF0) | (op == 0xF5)
    has_value = (op == 0xF1) | (op == 0xF2)  # CALL, CALLCODE
    sin = ci._J_STACK_IN[op]

    to = ci._peek(f, 1)
    to_sym = _peek_sym(sf, 1)
    v_call = ci._peek(f, 2)
    v_call_sym = _peek_sym(sf, 2)
    v_create = ci._peek(f, 0)
    v_create_sym = _peek_sym(sf, 0)
    value = jnp.where(is_create[:, None], v_create, jnp.where(has_value[:, None], v_call, 0)).astype(U32)
    value_sym = jnp.where(is_create, v_create_sym, jnp.where(has_value, v_call_sym, 0))
    to_rec = jnp.where(is_create[:, None], 0, to).astype(U32)
    to_sym_rec = jnp.where(is_create, 0, to_sym)

    # output region havoc (call writes returndata into memory)
    out_len = jnp.where(has_value[:, None], ci._peek(f, 6), ci._peek(f, 5))
    out_len_sym = jnp.where(has_value, _peek_sym(sf, 6), _peek_sym(sf, 5))
    havoc_mem = m & ~is_create & ((out_len_sym != 0) | ~u256.is_zero(out_len))

    CL = sf.call_to.shape[1]
    onehot = _event_slot(sf.n_calls, m, CL)

    sf, rv = append_node(sf, m, int(SymOp.FREE), int(FreeKind.RETVAL), sf.n_calls)
    f = sf.base
    dest_slot = f.sp - sin
    zero_w = jnp.zeros_like(to)
    return sf.replace(
        base=f.replace(
            stack=ci._set_slot(f.stack, dest_slot, zero_w, m),
            sp=jnp.where(m, f.sp - sin + 1, f.sp),
            returndata_len=jnp.where(m, 0, f.returndata_len),
        ),
        stack_sym=_set_sym_slot(sf.stack_sym, dest_slot, rv, m),
        mem_havoc=sf.mem_havoc | havoc_mem,
        retdata_sym=sf.retdata_sym | (m & ~is_create),
        n_calls=sf.n_calls + m.astype(I32),
        n_mut_calls=sf.n_mut_calls + (
            m & ((op == 0xF1) | (op == 0xF2) | (op == 0xF4))
        ).astype(I32),
        call_to=jnp.where(onehot[:, :, None], to_rec[:, None, :], sf.call_to),
        call_to_sym=jnp.where(onehot, to_sym_rec[:, None], sf.call_to_sym),
        call_value=jnp.where(onehot[:, :, None], value[:, None, :], sf.call_value),
        call_value_sym=jnp.where(onehot, value_sym[:, None], sf.call_value_sym),
        call_op=jnp.where(onehot, op[:, None], sf.call_op),
        call_pc=jnp.where(onehot, old_pc[:, None], sf.call_pc),
    )


def _h_sym_claimed_misc(sf: SymFrontier, op, m_memoff, m_sha3off, m_copyoff,
                        m_haltoff, m_logoff) -> SymFrontier:
    """Symbolic-offset memory/copy/sha3/halt/log ops: stack bookkeeping +
    havoc over-approximation (no byte-accurate modeling at symbolic
    addresses under static shapes)."""
    f = sf.base
    is_load = op == 0x51
    any_m = m_memoff | m_sha3off | m_copyoff | m_haltoff | m_logoff

    # MLOAD(sym off) / SHA3(sym args) -> fresh havoc result
    need_hv = (m_memoff & is_load) | m_sha3off
    sf, hv = _havoc(sf, need_hv)
    f = sf.base

    # result slots: MLOAD replaces top (sp-1); SHA3 pops 2 pushes 1 (sp-2)
    stack_sym = _set_sym_slot(sf.stack_sym, f.sp - 1, hv, m_memoff & is_load)
    stack_sym = _set_sym_slot(stack_sym, f.sp - 2, hv, m_sha3off)

    sin = ci._J_STACK_IN[op]
    sout = ci._J_STACK_OUT[op]
    d_sp = sin - sout
    is_revert = op == 0xFD
    has_data_halt = (op == 0xF3) | is_revert
    return sf.replace(
        base=f.replace(
            sp=jnp.where(any_m, f.sp - d_sp, f.sp),
            halted=f.halted | (m_haltoff & has_data_halt),
            reverted=f.reverted | (m_haltoff & is_revert),
            retval_len=jnp.where(m_haltoff, 0, f.retval_len),
            n_logs=f.n_logs + m_logoff.astype(I32),
        ),
        stack_sym=stack_sym,
        # symbolic-offset stores / copies invalidate the whole memory overlay
        mem_havoc=sf.mem_havoc | (m_memoff & ~is_load) | m_copyoff,
    )


# ---------------------------------------------------------------------------
# Overlay: sym-id bookkeeping for concretely-dispatched lanes
# ---------------------------------------------------------------------------


def _take_word_sym(mem_sym, w):
    W = mem_sym.shape[1]
    return jnp.take_along_axis(mem_sym, jnp.clip(w, 0, W - 1)[:, None].astype(I32), axis=1)[:, 0]


def _set_word_sym(mem_sym, w, val, mask):
    W = mem_sym.shape[1]
    sel = (jnp.arange(W)[None, :] == w[:, None]) & mask[:, None] & (w[:, None] < W) & (w[:, None] >= 0)
    return jnp.where(sel, val[:, None], mem_sym)


def _overlay(sf: SymFrontier, env: Env, spec: SymSpec, op, m, cls, pre_sp,
             pre_stack_sym, a, s, limits: LimitsConfig) -> SymFrontier:
    """Mirror the concrete handlers' stack movements on the sym-id plane
    and append tape nodes where symbolic operands flowed in. Uses the
    PRE-dispatch stack/syms (`a` = operand limbs, `s` = operand sym ids).
    """
    f = sf.base
    stack_sym = sf.stack_sym
    sin = ci._J_STACK_IN[op]

    # ---- CLS_STACK: push/dup/swap/pc/msize/gas ----
    m_stk = m & (cls == ci.CLS_STACK)
    is_push = (op >= 0x5F) & (op <= 0x7F)
    is_dup = (op >= 0x80) & (op <= 0x8F)
    is_swap = (op >= 0x90) & (op <= 0x9F)
    pushes0 = is_push | (op == 0x58) | (op == 0x59) | (op == 0x5A)
    dup_n = jnp.where(is_dup, op - 0x7F, 1)
    S = stack_sym.shape[1]
    dup_sym = jnp.take_along_axis(
        pre_stack_sym, jnp.clip(pre_sp - dup_n, 0, S - 1)[:, None].astype(I32), axis=1
    )[:, 0]
    stack_sym = _set_sym_slot(stack_sym, pre_sp, jnp.zeros_like(dup_sym), m_stk & pushes0)
    stack_sym = _set_sym_slot(stack_sym, pre_sp, dup_sym, m_stk & is_dup)
    swap_n = jnp.where(is_swap, op - 0x8F, 1)
    deep_sym = jnp.take_along_axis(
        pre_stack_sym, jnp.clip(pre_sp - 1 - swap_n, 0, S - 1)[:, None].astype(I32), axis=1
    )[:, 0]
    stack_sym = _set_sym_slot(stack_sym, pre_sp - 1, deep_sym, m_stk & is_swap)
    stack_sym = _set_sym_slot(stack_sym, pre_sp - 1 - swap_n, s[0], m_stk & is_swap)
    sf = sf.replace(stack_sym=stack_sym)

    # ---- value binops/unaries (ALU/MUL/DIVMOD/EXP classes) ----
    m_bin = m & (
        (cls == ci.CLS_ALU) | (cls == ci.CLS_MUL) | (cls == ci.CLS_DIVMOD) | (cls == ci.CLS_EXP)
    )
    node_op = _J_BINOP[op]
    is_unary = (op == 0x15) | (op == 0x19)  # ISZERO NOT
    any_sym = (s[0] != 0) | (~is_unary & (s[1] != 0))
    m_node = m_bin & any_sym & (node_op != 0)
    sf, aid = _sym_or_const(sf, m_node, s[0], a[0])
    sf, bid = _sym_or_const(sf, m_node & ~is_unary, s[1], a[1])
    bid = jnp.where(is_unary, 0, bid)  # unary nodes must not carry stale b
    sf, r_bin = append_node(sf, m_node, node_op, aid, bid)

    # record symbolic ADD/SUB/MUL/EXP events for the IntegerArithmetics
    # module (reference: overflow predicates built inline in the module's
    # pre-hook on these opcodes ⚠unv SURVEY.md §3.3; here the predicate is
    # assembled host-side from the recorded operand node ids)
    is_arith = (op == 0x01) | (op == 0x02) | (op == 0x03) | (op == 0x0A)
    m_ar = m_node & is_arith
    ar_onehot = _event_slot(sf.n_arith, m_ar, sf.arith_op.shape[1])
    old_pc_arr = sf.base.pc  # prologue left pc at the instruction
    sf = sf.replace(
        n_arith=sf.n_arith + m_ar.astype(I32),
        arith_op=jnp.where(ar_onehot, op[:, None], sf.arith_op),
        arith_a=jnp.where(ar_onehot, aid[:, None], sf.arith_a),
        arith_b=jnp.where(ar_onehot, bid[:, None], sf.arith_b),
        arith_r=jnp.where(ar_onehot, r_bin[:, None], sf.arith_r),
        arith_pc=jnp.where(ar_onehot, old_pc_arr[:, None], sf.arith_pc),
    )

    # ---- CLS_MODARITH: symbolic addmod/mulmod -> havoc (documented) ----
    m_mod = m & (cls == ci.CLS_MODARITH)
    m_mod_sym = m_mod & ((s[0] != 0) | (s[1] != 0) | (s[2] != 0))

    # ---- CLS_ENV: leaves (tx-scoped identity; dedup hits the tx-0 seeds) ----
    m_env = m & (cls == ci.CLS_ENV)
    is_cdload = op == 0x35
    off64 = u256.to_u64_saturating(a[0]).astype(I64)
    CD = limits.calldata_bytes
    beyond = off64 >= CD
    txb = sf.tx_id

    kind = jnp.full_like(op, -1)
    bsel = jnp.zeros_like(op)

    def leaf(enabled: bool, sel, k: int, bval):
        nonlocal kind, bsel
        if not enabled:
            return
        kind = jnp.where(sel, k, kind)
        bsel = jnp.where(sel, bval, bsel)

    # tx-scoped actor/input leaves
    leaf(spec.caller, op == 0x33, int(FreeKind.CALLER), txb)
    leaf(spec.callvalue, op == 0x34, int(FreeKind.CALLVALUE), txb)
    leaf(spec.calldata, op == 0x36, int(FreeKind.CALLDATASIZE), txb)
    leaf(spec.calldata, is_cdload & (s[0] == 0) & ~beyond,
         int(FreeKind.CALLDATA_WORD),
         (txb.astype(I64) * TX_STRIDE + off64).astype(I32))
    # globals across the tx sequence: ORIGIN always symbolic (the
    # reference models tx.origin as a free symbol; SWC-115 scans for it)
    leaf(True, op == 0x32, int(FreeKind.ORIGIN), 0)
    leaf(spec.block_env, op == 0x42, int(FreeKind.TIMESTAMP), 0)
    leaf(spec.block_env, op == 0x43, int(FreeKind.NUMBER), 0)
    leaf(spec.block_env, op == 0x44, int(FreeKind.PREVRANDAO), 0)
    leaf(spec.block_env, op == 0x3A, int(FreeKind.GASPRICE), 0)
    leaf(spec.block_env, op == 0x47, int(FreeKind.BALANCE), 0)
    is_balance = op == 0x31
    self_query = u256.eq(a[0], env.address) & (s[0] == 0)
    bal_self = is_balance & self_query
    leaf(spec.block_env, bal_self, int(FreeKind.BALANCE), 0)
    # RETURNDATASIZE after a symbolic call
    leaf(True, (op == 0x3D) & sf.retdata_sym, int(FreeKind.RETDATASIZE),
         jnp.maximum(sf.n_calls - 1, 0))

    need_leaf = m_env & (kind >= 0)
    sf, env_leaf = append_node(sf, need_leaf, int(SymOp.FREE), kind, bsel)

    # havoc cases: unknowable values must never collapse to a wrong
    # concrete 0 (EXTCODESIZE/EXTCODEHASH of unknown addresses, BALANCE of
    # others, BLOCKHASH, symbolic-offset CALLDATALOAD)
    ext_query = (op == 0x3B) | (op == 0x3F)
    env_hv_need = m_env & (
        (is_cdload & (s[0] != 0))
        | (is_balance & ~bal_self)
        | (op == 0x40)  # BLOCKHASH
        | (ext_query & ~self_query)
    )
    sf, env_hv = _havoc(sf, env_hv_need)
    r_env = jnp.where(need_leaf, env_leaf, 0)
    r_env = jnp.where(env_hv_need, env_hv, r_env)
    # "executed ORIGIN" flag (DeprecatedOperations SWC-111): the leaf node
    # may pre-exist via seeding, so presence on the tape is not evidence
    sf = sf.replace(origin_read=sf.origin_read | (m_env & (op == 0x32)))

    # ---- CLS_SHA3 (concrete args): keccak chain over the hashed window ----
    m_sha = m & (cls == ci.CLS_SHA3)
    ln64 = u256.to_u64_saturating(a[1]).astype(I64)
    w0 = (off64 // 32).astype(I32)
    # chain span derived from the concrete handler's hash cap so they can't
    # drift: any ln the concrete handler accepts (<= MAX_HASH_BYTES, else
    # the lane errors there) fits in NCW words from w0
    NCW = (ci.MAX_HASH_BYTES + 31 + 31) // 32
    nw = jnp.clip((off64 % 32 + ln64 + 31) // 32, 0, NCW).astype(I32)
    wsyms = [
        _take_word_sym(sf.mem_sym, w0 + k) for k in range(NCW)
    ]
    in_win = [(jnp.int32(k) < nw) for k in range(NCW)]
    any_w_sym = jnp.zeros_like(m_sha)
    for k in range(NCW):
        any_w_sym = any_w_sym | (in_win[k] & (wsyms[k] != 0))
    # a window that does not fully fit the chain span would truncate the
    # hashed data and yield a WRONG digest downstream — havoc instead
    # (over-approximation policy: never a wrong value)
    fits_chain = (off64 % 32 + ln64) <= 32 * NCW
    m_hvsha = m_sha & (ln64 > 0) & (sf.mem_havoc | (any_w_sym & ~fits_chain))
    m_chain = m_sha & any_w_sym & ~sf.mem_havoc & fits_chain
    sf, sha_hv = _havoc(sf, m_hvsha)
    seed_imm = jnp.zeros((f.pc.shape[0], 8), dtype=U32)
    seed_imm = seed_imm.at[:, 0].set(jnp.clip(ln64, 0, 2**31).astype(U32))
    seed_imm = seed_imm.at[:, 1].set((off64 % 32).astype(U32))
    sf, chain = append_node(sf, m_chain, int(SymOp.KECCAK_SEED), 0, 0, seed_imm)
    M = f.memory.shape[1]
    for k in range(NCW):
        mk = m_chain & in_win[k]
        w_conc = ci._be_bytes_to_word(
            ci._gather_bytes(sf.base.memory, (w0 + k).astype(I64) * 32, 32,
                             jnp.full_like(off64, M))
        )
        imm_k = jnp.where((wsyms[k] == 0)[:, None], w_conc, 0).astype(U32)
        sf, chain2 = append_node(sf, mk, int(SymOp.KECCAK_ABS), chain, wsyms[k], imm_k)
        chain = jnp.where(mk, chain2, chain)
    sf, dig = append_node(sf, m_chain, int(SymOp.KECCAK), chain, 0)
    r_sha = jnp.where(m_hvsha, sha_hv, jnp.where(m_chain, dig, 0))

    # ---- CLS_MEM (concrete offsets) ----
    m_mem = m & (cls == ci.CLS_MEM)
    is_load = op == 0x51
    is_store8 = op == 0x53
    aligned = (off64 % 32) == 0
    wm = (off64 // 32).astype(I32)
    wsym_a = _take_word_sym(sf.mem_sym, wm)
    wsym_b = _take_word_sym(sf.mem_sym, wm + 1)
    # MLOAD
    load_sym_needed = m_mem & is_load & (
        (aligned & ((wsym_a != 0) | sf.mem_havoc))
        | (~aligned & ((wsym_a != 0) | (wsym_b != 0) | sf.mem_havoc))
    )
    hv_load_need = load_sym_needed & (~aligned | sf.mem_havoc)
    # unaligned MSTORE: havoc both covered words if anything symbolic
    st_mask = m_mem & ~is_load
    un_any = st_mask & ~is_store8 & ~aligned & (
        (s[1] != 0) | (wsym_a != 0) | (wsym_b != 0) | sf.mem_havoc
    )
    sf, hv_a = _havoc(sf, hv_load_need | un_any)
    r_mload = jnp.where(
        load_sym_needed, jnp.where(aligned & ~sf.mem_havoc, wsym_a, hv_a), 0
    )
    mstore_aligned = st_mask & ~is_store8 & aligned
    mem_sym = _set_word_sym(sf.mem_sym, wm, s[1], mstore_aligned)
    sf, hv_b = _havoc(sf, un_any)
    mem_sym = _set_word_sym(mem_sym, wm, hv_a, un_any)
    mem_sym = _set_word_sym(mem_sym, wm + 1, hv_b, un_any)
    # MSTORE8: havoc the word if value or word symbolic
    m8_any = st_mask & is_store8 & ((s[1] != 0) | (wsym_a != 0) | sf.mem_havoc)
    sf, hv_c = _havoc(sf, m8_any)
    mem_sym = _set_word_sym(mem_sym, wm, hv_c, m8_any)
    sf = sf.replace(mem_sym=mem_sym)

    # ---- CLS_COPY (concrete args) ----
    m_cp = m & (cls == ci.CLS_COPY)
    is_ext = op == 0x3C
    dst64 = jnp.where(is_ext, u256.to_u64_saturating(a[1]), off64).astype(I64)
    cln64 = u256.to_u64_saturating(jnp.where(is_ext[:, None], a[3], a[2])).astype(I64)
    is_cdcopy = op == 0x37
    is_rdcopy = op == 0x3E
    # calldatacopy of symbolic calldata / returndatacopy after a symbolic
    # call: coarse whole-memory havoc (v1)
    cd_havoc = m_cp & (cln64 > 0) & (
        (is_cdcopy & spec.calldata) | (is_rdcopy & sf.retdata_sym)
    )
    # concrete-source copies (code/extcode/concrete returndata): fully
    # covered words become concrete; partial edge words with stale syms ->
    # havoc flag
    conc_src = m_cp & ~is_cdcopy & ~(is_rdcopy & sf.retdata_sym) & (cln64 > 0)
    W = sf.mem_sym.shape[1]
    wids = jnp.arange(W)[None, :]
    full_lo = ((dst64 + 31) // 32)[:, None]
    full_hi = ((dst64 + cln64) // 32)[:, None]
    full_cover = (wids >= full_lo) & (wids < full_hi) & conc_src[:, None]
    mem_sym2 = jnp.where(full_cover, 0, sf.mem_sym)
    edge_lo = (dst64 // 32)[:, None]
    edge_hi = ((dst64 + cln64) // 32)[:, None]
    edge = ((wids == edge_lo) | (wids == edge_hi)) & ~full_cover & conc_src[:, None]
    edge_dirty = jnp.any(edge & (sf.mem_sym != 0), axis=1)
    sf = sf.replace(
        mem_sym=mem_sym2,
        mem_havoc=sf.mem_havoc | cd_havoc | (conc_src & edge_dirty),
    )

    # ---- CLS_HALT: capture return-payload syms; SELFDESTRUCT beneficiary ----
    m_halt = m & (cls == ci.CLS_HALT)
    has_data = (op == 0xF3) | (op == 0xFD)
    rv_words = sf.rv_sym.shape[1]
    cap_ok = m_halt & has_data & aligned & ~sf.mem_havoc
    rv_sym = sf.rv_sym
    for k in range(rv_words):
        in_rv = (jnp.int32(k) * 32) < ln64
        rv_sym = rv_sym.at[:, k].set(
            jnp.where(cap_ok & in_rv, _take_word_sym(sf.mem_sym, wm + k), rv_sym[:, k])
        )
    is_sd = op == 0xFF
    is_inv = op == 0xFE
    first_inv = m_halt & is_inv & (sf.inv_pc < 0)
    first_sd = m_halt & is_sd & (sf.sd_pc < 0)
    sf = sf.replace(
        rv_sym=rv_sym,
        sd_to_sym=jnp.where(m_halt & is_sd, s[0], sf.sd_to_sym),
        sd_to=jnp.where((m_halt & is_sd)[:, None], a[0], sf.sd_to).astype(U32),
        sd_pc=jnp.where(first_sd, sf.base.pc, sf.sd_pc),
        inv_pc=jnp.where(first_inv, sf.base.pc, sf.inv_pc),
    )

    # ---- write result syms into the result slot (clears stale ids) ----
    r = jnp.zeros_like(op)
    r = jnp.where(m_node, r_bin, r)
    r = jnp.where(m_env, r_env, r)
    r = jnp.where(m_sha, r_sha, r)
    r = jnp.where(m_mem & is_load, r_mload, r)
    m_modhv = m_mod_sym
    sf2, hv_mod = _havoc(sf, m_modhv)
    sf = sf2
    r = jnp.where(m_modhv, hv_mod, r)
    writes_result = (
        m_bin | m_mod | m_env | m_sha | (m_mem & is_load)
    )
    res_slot = pre_sp - sin
    sf = sf.replace(
        stack_sym=_set_sym_slot(sf.stack_sym, res_slot, r, writes_result)
    )
    return sf


# ---------------------------------------------------------------------------
# Superstep / forking / run loop
# ---------------------------------------------------------------------------


def sym_superstep(sf: SymFrontier, env: Env, corpus: Corpus,
                  spec: SymSpec = SymSpec(),
                  limits: LimitsConfig = DEFAULT_LIMITS) -> SymFrontier:
    """Advance every running lane by one instruction, symbolically."""
    f, op, run, old_pc = ci.prologue(sf.base, corpus)
    sf = sf.replace(base=f)
    cls = ci._J_CLASS[op]
    pre_sp = f.sp
    pre_stack_sym = sf.stack_sym
    a = [ci._peek(f, i) for i in range(4)]
    s = [_peek_sym(sf, i) for i in range(7)]

    is_jumpi = op == 0x57
    known, ksign = _lookup_constraint(sf, s[1])
    claim_jump = run & (cls == ci.CLS_JUMP) & ((s[0] != 0) | (is_jumpi & (s[1] != 0)))
    claim_storage = run & (cls == ci.CLS_STORAGE)
    claim_callish = run & ((cls == ci.CLS_CALL) | (cls == ci.CLS_CREATE))
    claim_memoff = run & (cls == ci.CLS_MEM) & (s[0] != 0)
    claim_sha3off = run & (cls == ci.CLS_SHA3) & ((s[0] != 0) | (s[1] != 0))
    is_ext = op == 0x3C
    claim_copyoff = run & (cls == ci.CLS_COPY) & (
        (s[0] != 0) | (s[1] != 0) | (s[2] != 0) | (is_ext & (s[3] != 0))
    )
    has_data_halt = (op == 0xF3) | (op == 0xFD)
    claim_haltoff = run & (cls == ci.CLS_HALT) & has_data_halt & ((s[0] != 0) | (s[1] != 0))
    claim_logoff = run & (cls == ci.CLS_LOG) & ((s[0] != 0) | (s[1] != 0))
    claimed = (
        claim_jump | claim_storage | claim_callish | claim_memoff
        | claim_sha3off | claim_copyoff | claim_haltoff | claim_logoff
    )

    f = ci.dispatch(sf.base, env, corpus, op, run, old_pc, skip=claimed)
    sf = sf.replace(base=f)

    sf = _overlay(sf, env, spec, op, run & ~claimed, cls, pre_sp,
                  pre_stack_sym, a, s, limits)

    def _cond_apply(sf, mask, fn):
        return lax.cond(jnp.any(mask), fn, lambda x: x, sf)

    sf = _cond_apply(sf, claim_storage,
                     lambda x: _h_sym_storage(x, spec, op, claim_storage))
    sf = _cond_apply(sf, claim_jump,
                     lambda x: _h_sym_jump(x, corpus, op, claim_jump, old_pc, known, ksign))
    sf = _cond_apply(sf, claim_callish,
                     lambda x: _h_sym_callish(x, op, claim_callish, old_pc))
    misc = claim_memoff | claim_sha3off | claim_copyoff | claim_haltoff | claim_logoff
    sf = _cond_apply(sf, misc,
                     lambda x: _h_sym_claimed_misc(x, op, claim_memoff, claim_sha3off,
                                                   claim_copyoff, claim_haltoff, claim_logoff))

    f = ci.epilogue(sf.base, op, run, old_pc)
    return sf.replace(base=f)


def between_txs(sf: SymFrontier) -> SymFrontier:
    """Advance surviving lanes to the next symbolic transaction.

    Counterpart of the reference's ``open_states`` handoff
    (``transaction/symbolic.py:execute_message_call`` iterating world
    states that survived the previous tx ⚠unv, SURVEY.md §3.2): a lane
    proceeds iff it halted normally AND mutated storage — dropping
    non-mutating paths is exactly the reference's MutationPruner
    (``laser/plugin/plugins/mutation_pruner.py`` ⚠unv). A selfdestructed
    contract has no code left, so those lanes retire too. Per-tx machine
    state resets; storage, the tape, and path constraints carry over;
    the one-shot event records (calls, arith, INVALID/SSTORE pcs) are
    per-transaction and reset — the per-tx context snapshots taken by
    ``SymExecWrapper`` already preserved them for detection.
    tx-scoped leaves re-key via tx_id (TX_STRIDE encoding).
    """
    b = sf.base
    P = sf.n_lanes
    mutated = jnp.any(b.st_written, axis=1)
    go = b.active & b.halted & ~b.error & ~b.reverted & ~b.selfdestructed & mutated
    return sf.replace(
        base=b.replace(
            active=go,
            halted=jnp.zeros_like(b.halted),
            err_code=jnp.zeros_like(b.err_code),
            reverted=jnp.zeros_like(b.reverted),
            pc=jnp.where(go, 0, b.pc),
            stack=jnp.where(go[:, None, None], 0, b.stack),
            sp=jnp.where(go, 0, b.sp),
            memory=jnp.where(go[:, None], 0, b.memory),
            mem_words=jnp.where(go, 0, b.mem_words),
            gas_min=jnp.where(go, 0, b.gas_min),
            gas_max=jnp.where(go, 0, b.gas_max),
            calldata_len=jnp.where(go, b.calldata.shape[1], b.calldata_len),
            returndata_len=jnp.where(go, 0, b.returndata_len),
            retval_len=jnp.where(go, 0, b.retval_len),
            n_logs=jnp.where(go, 0, b.n_logs),
            st_written=jnp.where(go[:, None], False, b.st_written),
        ),
        stack_sym=jnp.where(go[:, None], 0, sf.stack_sym),
        mem_sym=jnp.where(go[:, None], 0, sf.mem_sym),
        mem_havoc=jnp.where(go, False, sf.mem_havoc),
        retdata_sym=jnp.where(go, False, sf.retdata_sym),
        rv_sym=jnp.where(go[:, None], 0, sf.rv_sym),
        tx_id=jnp.where(go, sf.tx_id + 1, sf.tx_id),
        # per-tx one-shot event records reset so tx N+1 can't inherit
        # tx N's calls/arith/SSTORE-after-call evidence (the per-tx
        # snapshot consumed them already)
        sym_jump_dest=jnp.where(go, 0, sf.sym_jump_dest),
        sym_jump_pc=jnp.where(go, -1, sf.sym_jump_pc),
        # the saturation counters reset for EVERY lane (not just survivors):
        # coverage_summary sums them across tx snapshots, and a retired
        # lane's stale count would be recounted each remaining tx
        n_calls=jnp.zeros_like(sf.n_calls),
        n_mut_calls=jnp.zeros_like(sf.n_mut_calls),
        call_op=jnp.where(go[:, None], 0, sf.call_op),
        call_to=jnp.where(go[:, None, None], 0, sf.call_to),
        call_to_sym=jnp.where(go[:, None], 0, sf.call_to_sym),
        call_value=jnp.where(go[:, None, None], 0, sf.call_value),
        call_value_sym=jnp.where(go[:, None], 0, sf.call_value_sym),
        call_pc=jnp.where(go[:, None], 0, sf.call_pc),
        origin_read=jnp.where(go, False, sf.origin_read),
        inv_pc=jnp.where(go, -1, sf.inv_pc),
        sstore_after_call_pc=jnp.where(go, -1, sf.sstore_after_call_pc),
        arb_key_node=jnp.where(go, 0, sf.arb_key_node),
        arb_key_pc=jnp.where(go, -1, sf.arb_key_pc),
        dropped_forks=jnp.zeros_like(sf.dropped_forks),
        n_arith=jnp.zeros_like(sf.n_arith),
        arith_op=jnp.where(go[:, None], 0, sf.arith_op),
        arith_a=jnp.where(go[:, None], 0, sf.arith_a),
        arith_b=jnp.where(go[:, None], 0, sf.arith_b),
        arith_r=jnp.where(go[:, None], 0, sf.arith_r),
        arith_pc=jnp.where(go[:, None], 0, sf.arith_pc),
        # retired lanes (reverted / error / non-mutating) free their slots
        # for forks of the surviving ones; their results were consumed by
        # the per-tx detection pass before this call. Loss accounting
        # (err_code / killed_infeasible) resets so the host-side per-tx
        # tally in SymExecWrapper counts each lost lane exactly once even
        # after its slot is recycled by expand_forks.
        killed_infeasible=jnp.zeros_like(sf.killed_infeasible),
    )


def expand_forks(sf: SymFrontier) -> SymFrontier:
    """Materialize fork requests: copy each forking lane into a free lane
    (prefix-sum compaction), point the copy at the jump target, and flip
    its final path-condition sign to "taken". Forks beyond capacity are
    counted in ``dropped_forks`` (the frontier equivalent of the
    reference's unbounded ``work_list.append`` ⚠unv)."""
    P = sf.n_lanes
    req = sf.fork_req
    free = ~sf.base.active
    n_free = jnp.sum(free.astype(I32))
    rank = jnp.cumsum(req.astype(I32)) - req.astype(I32)  # exclusive
    free_ids = jnp.sort(jnp.where(free, jnp.arange(P, dtype=I32), P))
    slot = jnp.where(req & (rank < n_free), free_ids[jnp.clip(rank, 0, P - 1)], P)
    src = jnp.arange(P, dtype=I32).at[slot].set(jnp.arange(P, dtype=I32), mode="drop")
    is_copy = jnp.zeros(P, dtype=bool).at[slot].set(True, mode="drop")

    # scalar run-total counters pass through untouched (ndim == 0); they
    # must not be gathered over the lane axis
    new = jax.tree.map(
        lambda x: x if x.ndim == 0 else jnp.take(x, src, axis=0), sf
    )
    b = new.base
    C = new.con_sign.shape[1]
    last = (jnp.arange(C)[None, :] == (new.con_len - 1)[:, None]) & is_copy[:, None]
    # fork copies must not inherit the source lane's loss counter — that
    # would double-count every prior drop once per fork
    n_dropped = (req & (slot == P)).astype(I32)
    dropped = jnp.where(is_copy, 0, new.dropped_forks) + n_dropped
    return new.replace(
        base=b.replace(
            pc=jnp.where(is_copy, new.fork_dest, b.pc),
            active=b.active | is_copy,
        ),
        con_sign=jnp.where(last, True, new.con_sign),
        fork_req=jnp.zeros_like(new.fork_req),
        dropped_forks=dropped,
        dropped_total=new.dropped_total + jnp.sum(n_dropped, dtype=I32),
    )


@functools.partial(
    jax.jit, static_argnames=("spec", "limits", "max_steps", "propagate_every")
)
def sym_run(sf: SymFrontier, env: Env, corpus: Corpus,
            spec: SymSpec = SymSpec(),
            limits: LimitsConfig = DEFAULT_LIMITS,
            max_steps: int = 256,
            propagate_every=None) -> SymFrontier:
    """Run the symbolic engine until quiescence or max_steps supersteps.
    ``propagate_every`` > 0 interleaves feasibility sweeps that kill
    provably-unsat lanes (reference: lazy ``Solver.check()`` pruning);
    0 disables them; None uses ``limits.propagate_every``."""
    from .propagate import kill_infeasible

    if propagate_every is None:
        propagate_every = limits.propagate_every

    def cond(state):
        i, s = state
        return (i < max_steps) & jnp.any(s.base.running)

    def body(state):
        i, s = state
        s = sym_superstep(s, env, corpus, spec, limits)
        s = expand_forks(s)
        if propagate_every:
            s = lax.cond(
                (i % propagate_every) == propagate_every - 1,
                kill_infeasible, lambda x: x, s,
            )
        return i + 1, s

    _, sf = lax.while_loop(cond, body, (jnp.int32(0), sf))
    return sf
