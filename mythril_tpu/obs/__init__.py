"""Unified telemetry spine (docs/observability.md).

Two halves:

- :mod:`.trace` — span tracer: Chrome-trace JSON (Perfetto-loadable)
  plus an append-only, versioned JSONL event log; near-zero overhead
  when disabled;
- :mod:`.metrics` — process-local counter/gauge/histogram registry,
  snapshotted to JSON or Prometheus text format.

Both are stdlib-only imports (no jax, no engine) so backend-free front
ends — ``campaign-merge``, bench's pre-probe phase, the trace report
tool — can load them without initializing a backend.
"""

from . import metrics, trace

__all__ = ["metrics", "trace"]
