"""Process-local metrics registry: counters, gauges, histograms.

The numeric half of the telemetry spine (obs/trace.py is the temporal
half): frontier occupancy, fork/park/spill rates, solver checks, compile
and degrade events, checkpoint write latency — one registry, snapshotted
to JSON (``--metrics FILE``) and optionally rendered in Prometheus text
exposition format (``FILE.prom``) for scrape-style collection.

Design points:

- updates are lock-guarded but allocation-free on the hot path; a
  metric object is created once (``REGISTRY.counter("x")`` get-or-create)
  and then ``inc``/``set``/``observe`` are O(1);
- the registry itself is always live — recording a counter costs tens of
  nanoseconds — but EXPENSIVE collection (host transfers of device
  arrays to compute occupancy) must be gated on ``REGISTRY.enabled``
  (set by ``--metrics`` / the soak) or ``trace.active()``;
- snapshots are plain dicts with a ``schema`` stamp so downstream
  tooling can evolve; histogram snapshots carry count/sum/min/max plus
  cumulative bucket counts (Prometheus ``le`` semantics).

Stdlib-only import, like obs/trace.py.
"""

from __future__ import annotations

import json
import math
import os
import re
import threading
import time
from typing import Dict, List, Optional, Sequence

#: version stamped into every snapshot
SCHEMA = 1

#: default histogram buckets (seconds): spans engine chunk times (~ms)
#: through cold XLA compiles (~minutes)
DEFAULT_BUCKETS = (0.001, 0.0025, 0.005, 0.01, 0.025, 0.05, 0.1, 0.25,
                   0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0, 120.0, 300.0)

_NAME_RE = re.compile(r"[^a-zA-Z0-9_:]")


def _prom_name(name: str) -> str:
    """Prometheus-legal metric name (invalid chars become ``_``)."""
    n = _NAME_RE.sub("_", name)
    if n and n[0].isdigit():
        n = "_" + n
    return n


def _esc_label(v) -> str:
    return (str(v).replace("\\", "\\\\").replace('"', '\\"')
            .replace("\n", " "))


def label_key(name: str, labels: Optional[Dict]) -> str:
    """Registry key for one labeled series: the base name plus a
    canonical (sorted, escaped) Prometheus label block. Two call sites
    with the same labels in any order share one series."""
    if not labels:
        return name
    inner = ",".join(f'{k}="{_esc_label(labels[k])}"'
                     for k in sorted(labels))
    return f"{name}{{{inner}}}"


def _split_labels(name: str):
    """``(base, label_block)`` from a registry key; the block keeps its
    braces (``'{reason="depth"}'``) or is empty."""
    base, sep, rest = name.partition("{")
    return base, (sep + rest if sep else "")


class Counter:
    """Monotonically increasing value."""

    __slots__ = ("name", "help", "value", "_lock")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0.0
        self._lock = threading.Lock()

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Gauge:
    """Last-written value."""

    __slots__ = ("name", "help", "value", "_lock")

    def __init__(self, name: str, help: str = ""):
        self.name = name
        self.help = help
        self.value = 0.0
        self._lock = threading.Lock()

    def set(self, v: float) -> None:
        with self._lock:
            self.value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self.value += n


class Histogram:
    """Fixed-bucket histogram with count/sum/min/max."""

    __slots__ = ("name", "help", "buckets", "bucket_counts", "count",
                 "sum", "min", "max", "_lock")

    def __init__(self, name: str, help: str = "",
                 buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.help = help
        self.buckets = tuple(sorted(buckets or DEFAULT_BUCKETS))
        # one slot per finite bucket + the +Inf overflow slot
        self.bucket_counts = [0] * (len(self.buckets) + 1)
        self.count = 0
        self.sum = 0.0
        self.min = math.inf
        self.max = -math.inf
        self._lock = threading.Lock()

    def observe(self, v: float) -> None:
        v = float(v)
        with self._lock:
            self.count += 1
            self.sum += v
            if v < self.min:
                self.min = v
            if v > self.max:
                self.max = v
            for i, le in enumerate(self.buckets):
                if v <= le:
                    self.bucket_counts[i] += 1
                    return
            self.bucket_counts[-1] += 1

    def quantile(self, q: float) -> Optional[float]:
        """Bucket-resolution quantile estimate (``q`` in [0, 1]): the
        upper bound of the first bucket whose cumulative count reaches
        ``q * count``, clamped to the observed ``max`` (the heartbeat's
        ``req p50/p95`` token — coarse by design, no sample storage).
        ``None`` when empty."""
        with self._lock:
            if not self.count:
                return None
            target = q * self.count
            running = 0
            for le, n in zip(self.buckets, self.bucket_counts):
                running += n
                if running >= target:
                    return min(le, self.max)
            return self.max

    def as_dict(self) -> Dict:
        with self._lock:
            cumulative: Dict[str, int] = {}
            running = 0
            for le, n in zip(self.buckets, self.bucket_counts):
                running += n
                cumulative[repr(le)] = running
            cumulative["+Inf"] = running + self.bucket_counts[-1]
            return {
                "count": self.count,
                "sum": round(self.sum, 6),
                "min": (round(self.min, 6) if self.count else None),
                "max": (round(self.max, 6) if self.count else None),
                "buckets": cumulative,
            }


class MetricsRegistry:
    """Get-or-create registry of named metrics. One module-level
    instance (:data:`REGISTRY`) serves the whole process; tests build
    private ones."""

    def __init__(self):
        self._lock = threading.Lock()
        self._metrics: Dict[str, object] = {}
        #: gate for EXPENSIVE collection only (device syncs etc.);
        #: plain inc/set/observe calls are always accepted
        self.enabled = False

    def _get(self, name: str, cls, **kw):
        with self._lock:
            m = self._metrics.get(name)
            if m is None:
                m = cls(name, **kw)
                self._metrics[name] = m
            elif not isinstance(m, cls):
                raise TypeError(
                    f"metric {name!r} already registered as "
                    f"{type(m).__name__}, requested {cls.__name__}")
            return m

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict] = None) -> Counter:
        """``labels`` names one series of a labeled family
        (``counter("serve_shed_total", labels={"reason": "depth"})``);
        the Prometheus rendering groups the family under one
        HELP/TYPE block."""
        return self._get(label_key(name, labels), Counter, help=help)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict] = None) -> Gauge:
        return self._get(label_key(name, labels), Gauge, help=help)

    def histogram(self, name: str, help: str = "",
                  buckets: Optional[Sequence[float]] = None,
                  labels: Optional[Dict] = None) -> Histogram:
        return self._get(label_key(name, labels), Histogram, help=help,
                         buckets=buckets)

    def reset(self) -> None:
        with self._lock:
            self._metrics.clear()
            self.enabled = False

    # --- export --------------------------------------------------------
    def snapshot(self) -> Dict:
        """Plain-dict snapshot: ``{"schema", "t", "counters", "gauges",
        "histograms"}`` — the ``--metrics FILE`` payload."""
        with self._lock:
            items = list(self._metrics.items())
        out: Dict = {"schema": SCHEMA, "t": round(time.time(), 3),
                     "counters": {}, "gauges": {}, "histograms": {}}
        for name, m in items:
            if isinstance(m, Counter):
                out["counters"][name] = round(m.value, 6)
            elif isinstance(m, Gauge):
                out["gauges"][name] = round(m.value, 6)
            elif isinstance(m, Histogram):
                out["histograms"][name] = m.as_dict()
        return out

    def to_prometheus(self, prefix: str = "mythril_") -> str:
        """Prometheus text exposition format (0.0.4)."""
        with self._lock:
            items = list(self._metrics.items())
        lines: List[str] = []
        # HELP/TYPE are per FAMILY: labeled series of one base name
        # share a single header block (Prometheus exposition contract)
        headed: set = set()

        def head(pn: str, kind: str, help_text: str) -> None:
            if pn in headed:
                return
            headed.add(pn)
            if help_text:
                lines.append(f"# HELP {pn} {help_text}")
            lines.append(f"# TYPE {pn} {kind}")

        for name, m in items:
            base, labels = _split_labels(name)
            pn = _prom_name(prefix + base)
            if isinstance(m, Counter):
                head(pn, "counter", m.help)
                lines.append(f"{pn}{labels} {m.value:g}")
            elif isinstance(m, Gauge):
                head(pn, "gauge", m.help)
                lines.append(f"{pn}{labels} {m.value:g}")
            elif isinstance(m, Histogram):
                head(pn, "histogram", m.help)
                d = m.as_dict()
                # merge the series' label block with the ``le`` label
                # (``x_bucket{stage="device",le="0.5"}``)
                inner = labels[1:-1] if labels else ""
                for le, n in d["buckets"].items():
                    lb = f'{inner},le="{le}"' if inner else f'le="{le}"'
                    lines.append(f"{pn}_bucket{{{lb}}} {n}")
                lines.append(f"{pn}_sum{labels} {d['sum']:g}")
                lines.append(f"{pn}_count{labels} {d['count']}")
        return "\n".join(lines) + ("\n" if lines else "")

    def write(self, path: str) -> None:
        """Snapshot to ``path``: Prometheus text when the suffix is
        ``.prom``/``.txt``, JSON otherwise. Atomic (tmp + rename) so a
        kill mid-write never leaves a half snapshot."""
        d = os.path.dirname(os.path.abspath(path))
        os.makedirs(d, exist_ok=True)
        if path.endswith((".prom", ".txt")):
            data = self.to_prometheus()
        else:
            data = json.dumps(self.snapshot(), indent=1)
        tmp = f"{path}.{os.getpid()}.tmp"
        with open(tmp, "w", encoding="utf-8") as fh:
            fh.write(data)
        os.replace(tmp, path)


#: the process-global registry every instrumentation site uses
REGISTRY = MetricsRegistry()


def get_registry() -> MetricsRegistry:
    return REGISTRY


# --- cross-process metric backhaul (engine worker -> supervisor) --------

def snapshot_delta(after: Dict, before: Dict) -> Dict:
    """What changed between two :meth:`MetricsRegistry.snapshot` dicts —
    the engine worker ships this per batch reply so its counter ticks
    and histogram observations land in the parent registry instead of
    dying with the subprocess. Counters and histogram counts/buckets are
    differenced; gauges carry their last value."""
    out: Dict = {"counters": {}, "gauges": {}, "histograms": {}}
    b_ctr = before.get("counters", {})
    for k, v in after.get("counters", {}).items():
        d = v - b_ctr.get(k, 0.0)
        if d:
            out["counters"][k] = round(d, 6)
    for k, v in after.get("gauges", {}).items():
        if v != before.get("gauges", {}).get(k):
            out["gauges"][k] = v
    b_h = before.get("histograms", {})
    for k, h in after.get("histograms", {}).items():
        hb = b_h.get(k, {})
        dc = h["count"] - hb.get("count", 0)
        if not dc:
            continue
        bb = hb.get("buckets", {})
        out["histograms"][k] = {
            "count": dc,
            "sum": round(h["sum"] - hb.get("sum", 0.0), 6),
            "min": h.get("min"), "max": h.get("max"),
            "buckets": {le: n - bb.get(le, 0)
                        for le, n in h["buckets"].items()},
        }
    return out


def apply_delta(delta: Optional[Dict],
                registry: Optional[MetricsRegistry] = None) -> None:
    """Fold a :func:`snapshot_delta` payload into a registry (the
    supervisor side of the backhaul). Histogram bucket deltas are
    de-cumulated back into per-bucket increments; min/max fold through
    direct comparison."""
    if not delta:
        return
    reg = registry if registry is not None else REGISTRY
    for k, v in delta.get("counters", {}).items():
        reg._get(k, Counter).inc(v)
    for k, v in delta.get("gauges", {}).items():
        reg._get(k, Gauge).set(v)
    for k, h in delta.get("histograms", {}).items():
        m = reg._get(k, Histogram)
        cum = h.get("buckets", {})
        with m._lock:
            m.count += int(h.get("count", 0))
            m.sum += float(h.get("sum", 0.0))
            for bound in ("min", "max"):
                v = h.get(bound)
                if isinstance(v, (int, float)):
                    if bound == "min" and v < m.min:
                        m.min = v
                    elif bound == "max" and v > m.max:
                        m.max = v
            prev = 0
            for i, le in enumerate(m.buckets):
                c = cum.get(repr(le), prev)
                m.bucket_counts[i] += max(0, c - prev)
                prev = max(prev, c)
            inf = cum.get("+Inf", prev)
            m.bucket_counts[-1] += max(0, inf - prev)


__all__ = ["SCHEMA", "DEFAULT_BUCKETS", "Counter", "Gauge", "Histogram",
           "MetricsRegistry", "REGISTRY", "apply_delta", "get_registry",
           "label_key", "snapshot_delta"]
