"""Span tracer: one ordered, schema'd event stream for the whole stack.

Before this module, diagnosing a slow or degraded campaign meant
grepping four disjoint channels (iprof histograms, ``CorpusCampaign``
events, ``BackendManager`` events, ad-hoc ``time.monotonic()`` deltas in
bench/tools). The tracer unifies them:

- ``with trace.span("superstep", steps=64):`` times a phase and emits it
  as BOTH a Chrome-trace event (open the ``--trace`` file in Perfetto /
  ``chrome://tracing``) and one line of an append-only JSONL event log
  with a versioned schema (``tools/trace_report.py`` summarizes it, the
  soak asserts it);
- ``trace.event("degrade", batch=3, step="halve-lanes")`` emits an
  instant event — the campaign re-emits its existing ``_events`` /
  ``backend.events`` channels here so the one stream carries everything
  in order;
- disabled (the default — no ``--trace`` flag), ``span()`` returns a
  shared no-op singleton and ``event()`` returns immediately: no
  allocation, no clock read, no file. Hot paths stay hot.

The JSONL schema (version :data:`SCHEMA`): every line is one JSON object
with at least ``kind`` (``"span"`` or an instant-event kind), ``t``
(wall-clock ``time.time()``, seconds) and ``schema``. Spans add ``name``,
``dur`` (seconds), ``mono`` (``time.monotonic()`` at span start — orders
events within a session where wall time may step) and ``tid``; all
``span(...)`` keyword attributes ride along verbatim. ``session`` is a
per-process token so streams from resumed/merged sessions stay sortable
(see ``merge_campaigns``).

``timer()`` is the always-measuring variant: it returns a real
:class:`Span` whose ``elapsed`` property works whether or not tracing is
enabled (emitting only when it is). bench.py and the profilers use it in
place of their former ad-hoc ``perf_counter``/``monotonic`` pairs, so
one mechanism both measures and (when asked) records.

Import cost is stdlib-only — no jax, no engine — so backend-free
front-ends (``campaign-merge``, bench's pre-probe phase) can load it.
"""

from __future__ import annotations

import collections
import contextvars
import json
import os
import threading
import time
from typing import Any, Dict, List, Optional, Sequence

#: version stamped into every JSONL event (bump on breaking field
#: changes; readers must reject newer-than-known schemas)
SCHEMA = 1

#: default JSONL size-rotation threshold (docs/observability.md): an
#: always-on serve daemon must not grow the event log without bound
DEFAULT_MAX_JSONL_BYTES = int(os.environ.get(
    "MYTHRIL_TRACE_MAX_BYTES", 64 * 1024 * 1024))

#: cap on buffered (child-process) records per batch — a runaway span
#: source must not grow the IPC reply without bound
BUFFER_CAP = 20000


def jsonl_path_for(chrome_path: str) -> str:
    """The JSONL event-log path derived from a ``--trace FILE``:
    ``t.json -> t.jsonl``, anything else gets ``.jsonl`` appended."""
    if chrome_path.endswith(".json"):
        return chrome_path[:-5] + ".jsonl"
    return chrome_path + ".jsonl"


# --- request trace context (docs/observability.md "Distributed
# --- tracing") ----------------------------------------------------------
#
# One ``trace_id`` is minted at every ingestion point (HTTP submit,
# follower block, fleet unit claim, CLI analyze) and rides the ambient
# context below through every span/event emitted inside its scope —
# including across process boundaries, where an explicit
# ``context_snapshot()`` travels in the engine-worker IPC frame and is
# re-entered child-side with ``apply_context()``.

_CTX: "contextvars.ContextVar" = contextvars.ContextVar(
    "mythril_trace_ctx", default=None)


def new_trace_id() -> str:
    """A fresh 16-hex-char request trace id."""
    return os.urandom(8).hex()


def new_span_id() -> str:
    """A fresh 8-hex-char span id (unique within a trace)."""
    return os.urandom(4).hex()


class _CtxGuard:
    """Context-manager handle for one entered trace scope."""

    __slots__ = ("_token",)

    def __init__(self, token):
        self._token = token

    def __enter__(self) -> "_CtxGuard":
        return self

    def __exit__(self, *exc) -> bool:
        if self._token is not None:
            try:
                _CTX.reset(self._token)
            except ValueError:
                pass  # exited in a different context (thread hand-off)
            self._token = None
        return False


def trace_context(trace_id: Optional[str] = None,
                  parent: Optional[str] = None,
                  link_ids: Sequence[str] = ()) -> _CtxGuard:
    """Enter a request trace scope: every span/event emitted inside it
    carries ``trace_id`` (+ ``parent`` span linkage). ``trace_id=None``
    MINTS a fresh id — the ingestion-point spelling. ``link_ids`` are
    additional trace ids sharing this scope (a scheduler batch serves
    entries from several requests; its spans index under every one)."""
    ids = [trace_id or new_trace_id()]
    for x in link_ids:
        if x and x not in ids:
            ids.append(x)
    return _CtxGuard(_CTX.set((tuple(ids), parent)))


def context_snapshot() -> Optional[Dict]:
    """The current trace scope as a plain dict (``{"ids", "span"}``) —
    the form that crosses process/thread boundaries (engine-worker IPC
    frames, the pipelined host-phase thread). ``None`` outside any
    scope."""
    ctx = _CTX.get()
    if ctx is None:
        return None
    ids, parent = ctx
    return {"ids": list(ids), "span": parent}


def apply_context(snap: Optional[Dict]) -> _CtxGuard:
    """Re-enter a scope captured by :func:`context_snapshot` (no-op
    guard for ``None`` — callers need not branch)."""
    if not isinstance(snap, dict) or not snap.get("ids"):
        return _CtxGuard(None)
    ids = tuple(str(x) for x in snap["ids"])
    return _CtxGuard(_CTX.set((ids, snap.get("span"))))


def current_trace_id() -> Optional[str]:
    ctx = _CTX.get()
    return ctx[0][0] if ctx is not None else None


def _stamp_ctx(attrs: Dict) -> None:
    """Fold the ambient trace scope into one record's attrs (setdefault
    semantics: explicitly-carried ids — e.g. re-emitted worker records
    — always win)."""
    ctx = _CTX.get()
    if ctx is None:
        return
    ids, parent = ctx
    attrs.setdefault("trace_id", ids[0])
    if len(ids) > 1:
        attrs.setdefault("trace_ids", list(ids))
    if parent is not None:
        attrs.setdefault("parent", parent)


class _TraceIndex:
    """Bounded in-memory per-trace record index: the stitched-span
    source for ``GET /v1/trace/<id>``. Records land here as they are
    emitted (parent-side only — buffering child tracers skip it); both
    bounds are hard caps, oldest trace evicted first."""

    def __init__(self, max_traces: int = 256,
                 max_records_per_trace: int = 4096):
        self._lock = threading.Lock()
        self._traces: "collections.OrderedDict[str, List[Dict]]" = (
            collections.OrderedDict())
        self.max_traces = max_traces
        self.max_records = max_records_per_trace

    def add(self, rec: Dict) -> None:
        ids = []
        tid = rec.get("trace_id")
        if tid:
            ids.append(tid)
        for x in rec.get("trace_ids") or ():
            if x not in ids:
                ids.append(x)
        if not ids:
            return
        with self._lock:
            for t in ids:
                recs = self._traces.get(t)
                if recs is None:
                    recs = self._traces[t] = []
                    while len(self._traces) > self.max_traces:
                        self._traces.popitem(last=False)
                else:
                    self._traces.move_to_end(t)
                if len(recs) < self.max_records:
                    recs.append(rec)

    def get(self, trace_id: str) -> Optional[List[Dict]]:
        with self._lock:
            recs = self._traces.get(trace_id)
            if recs is None:
                return None
            recs = list(recs)
        # one coherent timeline: monotonic order (worker records were
        # offset-corrected onto the parent clock before landing here)
        return sorted(recs, key=lambda r: (
            r.get("mono") if isinstance(r.get("mono"), (int, float))
            else 0.0))

    def clear(self) -> None:
        with self._lock:
            self._traces.clear()


_TRACE_INDEX = _TraceIndex()


def trace_records(trace_id: str) -> Optional[List[Dict]]:
    """Every indexed span/event of one trace, stitched into monotonic
    order, or ``None`` for an unknown id."""
    return _TRACE_INDEX.get(trace_id)


class Span:
    """One timed phase. Context manager; ``elapsed`` is live inside the
    ``with`` block (seconds since entry) and frozen to the final
    duration after exit — callers can both drive budget loops off it
    mid-flight and read the measurement afterwards."""

    __slots__ = ("_tracer", "name", "attrs", "t_wall", "_t0", "dur",
                 "sid", "_ctx_token")

    def __init__(self, tracer: Optional["Tracer"], name: str,
                 attrs: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self.t_wall = 0.0
        self._t0 = 0.0
        self.dur: Optional[float] = None
        self.sid: Optional[str] = None
        self._ctx_token = None

    def __enter__(self) -> "Span":
        self.t_wall = time.time()
        self._t0 = time.monotonic()
        # inside a request trace scope: take a span id, link to the
        # enclosing span, and become the parent for anything nested
        ctx = _CTX.get()
        if ctx is not None:
            ids, parent = ctx
            self.sid = new_span_id()
            self.attrs.setdefault("trace_id", ids[0])
            if len(ids) > 1:
                self.attrs.setdefault("trace_ids", list(ids))
            if parent is not None:
                self.attrs.setdefault("parent", parent)
            self.attrs.setdefault("span", self.sid)
            self._ctx_token = _CTX.set((ids, self.sid))
        return self

    #: stopwatch use outside a ``with`` block (``sw = timer("x").start()``;
    #: read ``sw.elapsed``; call ``sw.stop()`` if the span should emit)
    start = __enter__

    def stop(self) -> float:
        self.__exit__(None, None, None)
        return self.dur or 0.0

    def __exit__(self, *exc) -> bool:
        self.dur = time.monotonic() - self._t0
        if self._ctx_token is not None:
            try:
                _CTX.reset(self._ctx_token)
            except ValueError:
                pass  # stopped from a different thread/context
            self._ctx_token = None
        if self._tracer is not None:
            self._tracer._emit_span(self)
        return False

    @property
    def elapsed(self) -> float:
        if self.dur is not None:
            return self.dur
        return time.monotonic() - self._t0


class _NullSpan:
    """The disabled-tracer singleton: zero state, zero clock reads.
    ``elapsed`` is 0.0 — code that needs a measurement regardless of
    tracing must use :func:`timer`, not :func:`span`."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    # mirror Span's stopwatch surface so ``span(...).start()`` /
    # ``.stop()`` stay safe when tracing is disabled
    start = __enter__

    def stop(self) -> float:
        return 0.0

    elapsed = 0.0


_NULL_SPAN = _NullSpan()


class Tracer:
    """Emits spans/events to an in-memory Chrome-trace buffer plus an
    append-only JSONL log (flushed per event, so a killed run leaves a
    readable prefix). Thread-safe; one per process is the normal case
    (the module-level :func:`configure` installs it globally)."""

    def __init__(self, chrome_path: Optional[str] = None,
                 jsonl_path: Optional[str] = None, *,
                 buffer: bool = False,
                 max_jsonl_bytes: Optional[int] = None):
        self.chrome_path = chrome_path
        self.jsonl_path = (jsonl_path if jsonl_path is not None
                           else (jsonl_path_for(chrome_path)
                                 if chrome_path else None))
        self._lock = threading.Lock()
        self._chrome: List[Dict] = []
        self._t0_mono = time.monotonic()
        self._t0_wall = time.time()
        self._pid = os.getpid()
        #: per-process token: orders/merges event streams across resumed
        #: sessions and hosts (wall clocks may disagree; sessions don't)
        self.session = f"{self._pid:x}-{int(self._t0_wall * 1000):x}"
        #: child-process mode (engine worker): records accumulate in
        #: memory and are DRAINED into the batch reply instead of
        #: touching any file — the parent re-emits them offset-corrected
        self.buffer_records: Optional[List[Dict]] = [] if buffer else None
        self.max_jsonl_bytes = (DEFAULT_MAX_JSONL_BYTES
                                if max_jsonl_bytes is None
                                else int(max_jsonl_bytes))
        self._jsonl_bytes = 0
        self._fh = None
        if self.jsonl_path and not buffer:
            d = os.path.dirname(os.path.abspath(self.jsonl_path))
            os.makedirs(d, exist_ok=True)
            self._fh = open(self.jsonl_path, "a", encoding="utf-8")
            try:
                self._jsonl_bytes = self._fh.tell()
            except OSError:
                self._jsonl_bytes = 0
        self._closed = False

    # --- emission ------------------------------------------------------
    @staticmethod
    def _count_dropped() -> None:
        """One record arrived while this tracer was closed (disabled
        mid-run): never silent — docs/observability.md."""
        from . import metrics as obs_metrics

        obs_metrics.REGISTRY.counter(
            "obs_events_dropped_total",
            help="trace records dropped because the tracer was closed "
                 "or its child buffer was full").inc()

    def _rotate_locked(self) -> None:
        """Size-based set-aside of the JSONL event log: the current
        file becomes ``<path>.1`` (replacing any previous set-aside —
        the checkpoint-rotation contract) and a fresh log continues,
        opening with a ``trace_log_rotated`` record so readers can see
        the seam."""
        rotated = self._jsonl_bytes
        try:
            self._fh.close()
            os.replace(self.jsonl_path, self.jsonl_path + ".1")
        except OSError:
            pass
        self._fh = open(self.jsonl_path, "a", encoding="utf-8")
        rec = {"schema": SCHEMA, "kind": "trace_log_rotated",
               "t": round(time.time(), 6),
               "mono": round(time.monotonic(), 6),
               "session": self.session, "rotated_bytes": rotated,
               "set_aside": self.jsonl_path + ".1"}
        line = json.dumps(rec)
        self._fh.write(line + "\n")
        self._fh.flush()
        self._jsonl_bytes = len(line) + 1
        from . import metrics as obs_metrics

        obs_metrics.REGISTRY.counter(
            "obs_event_log_rotations_total",
            help="JSONL event-log size rotations (.1 set-aside)").inc()

    def _write_jsonl(self, rec: Dict) -> None:
        if self.buffer_records is not None:
            with self._lock:
                if self._closed or len(self.buffer_records) >= BUFFER_CAP:
                    dropped = True
                else:
                    self.buffer_records.append(rec)
                    dropped = False
            if dropped:
                self._count_dropped()
            return
        _TRACE_INDEX.add(rec)
        if self._fh is None:
            return
        line = json.dumps(rec, default=str)
        dropped = False
        with self._lock:
            if self._closed:
                dropped = True
            else:
                self._fh.write(line + "\n")
                self._fh.flush()
                self._jsonl_bytes += len(line) + 1
                if (self.max_jsonl_bytes
                        and self._jsonl_bytes >= self.max_jsonl_bytes):
                    self._rotate_locked()
            size = self._jsonl_bytes
        if dropped:
            self._count_dropped()
            return
        from . import metrics as obs_metrics

        obs_metrics.REGISTRY.gauge(
            "obs_event_log_bytes",
            help="current size of the JSONL event log").set(size)

    def drain_buffer(self) -> List[Dict]:
        """Take (and clear) the buffered records — the engine worker
        calls this once per batch reply, so telemetry is flushed with
        the result it describes."""
        with self._lock:
            recs = list(self.buffer_records or ())
            if self.buffer_records is not None:
                self.buffer_records.clear()
        return recs

    def _emit_span(self, sp: Span) -> None:
        tid = threading.get_ident()
        rec = {"schema": SCHEMA, "kind": "span", "name": sp.name,
               "t": round(sp.t_wall, 6), "mono": round(sp._t0, 6),
               "dur": round(sp.dur or 0.0, 6), "tid": tid,
               "session": self.session}
        for k, v in sp.attrs.items():
            rec.setdefault(k, v)
        self._write_jsonl(rec)
        ev = {"name": sp.name, "ph": "X", "pid": self._pid, "tid": tid,
              "ts": round((sp._t0 - self._t0_mono) * 1e6, 3),
              "dur": round((sp.dur or 0.0) * 1e6, 3)}
        if sp.attrs:
            ev["args"] = dict(sp.attrs)
        with self._lock:
            self._chrome.append(ev)

    def span(self, name: str, **attrs) -> Span:
        return Span(self, name, attrs)

    def event(self, kind: str, **attrs) -> None:
        """Instant event (Chrome phase ``i``). ``attrs`` may carry its
        own ``t``/``mono`` (a re-emitted historical event keeps its
        original clock readings); missing ones are stamped now."""
        now_wall = time.time()
        now_mono = time.monotonic()
        rec = {"schema": SCHEMA, "kind": kind,
               "t": round(now_wall, 6), "mono": round(now_mono, 6),
               "session": self.session}
        rec.update(attrs)
        _stamp_ctx(rec)
        self._write_jsonl(rec)
        mono = rec.get("mono", now_mono)
        if not isinstance(mono, (int, float)):
            mono = now_mono
        ev = {"name": kind, "ph": "i", "s": "p", "pid": self._pid,
              "tid": threading.get_ident(),
              "ts": round((mono - self._t0_mono) * 1e6, 3)}
        args = {k: v for k, v in attrs.items() if k not in ("t", "mono")}
        if args:
            ev["args"] = args
        with self._lock:
            self._chrome.append(ev)

    # --- lifecycle -----------------------------------------------------
    def flush(self) -> None:
        """Write the Chrome-trace file now (idempotent; ``close`` calls
        it). The JSONL log is already flushed per event."""
        if not self.chrome_path:
            return
        with self._lock:
            events = list(self._chrome)
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": {"schema": SCHEMA, "session": self.session,
                             "t0_wall": round(self._t0_wall, 6)}}
        tmp = f"{self.chrome_path}.{self._pid}.tmp"
        d = os.path.dirname(os.path.abspath(self.chrome_path))
        os.makedirs(d, exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
        os.replace(tmp, self.chrome_path)

    def close(self) -> None:
        self.flush()
        with self._lock:
            self._closed = True
            if self._fh is not None:
                self._fh.close()
                self._fh = None


# --- module-level API (the one most call sites use) --------------------

_TRACER: Optional[Tracer] = None


def configure(chrome_path: Optional[str] = None,
              jsonl_path: Optional[str] = None, *,
              buffer: bool = False,
              max_jsonl_bytes: Optional[int] = None) -> Tracer:
    """Install the process-global tracer (replacing any previous one,
    which is closed first). ``--trace t.json`` maps to
    ``configure("t.json")`` → Chrome trace at ``t.json``, JSONL event
    log at ``t.jsonl``. ``buffer=True`` is the engine-worker mode: no
    files — records accumulate for :meth:`Tracer.drain_buffer`."""
    global _TRACER
    if _TRACER is not None:
        _TRACER.close()
    _TRACER = Tracer(chrome_path, jsonl_path, buffer=buffer,
                     max_jsonl_bytes=max_jsonl_bytes)
    _TRACE_INDEX.clear()
    return _TRACER


def get_tracer() -> Optional[Tracer]:
    return _TRACER


def active() -> bool:
    """True when a tracer is installed — gate EXPENSIVE collection
    (device syncs, array reductions) on this, never plain span calls
    (those are already near-free when disabled)."""
    return _TRACER is not None


def span(name: str, **attrs):
    """Phase span on the global tracer; the shared no-op singleton when
    tracing is off (zero allocation, zero clock reads)."""
    t = _TRACER
    if t is None:
        return _NULL_SPAN
    return t.span(name, **attrs)


def timer(name: str, **attrs) -> Span:
    """Always-measuring span: ``elapsed`` works with tracing off; the
    event is emitted only when tracing is on. The replacement for
    ad-hoc ``t0 = monotonic(); ...; dt = monotonic() - t0`` pairs."""
    return Span(_TRACER, name, attrs)


def event(kind: str, **attrs) -> None:
    t = _TRACER
    if t is not None:
        t.event(kind, **attrs)


def complete(name: str, dur: float, t_wall: Optional[float] = None,
             mono: Optional[float] = None, **attrs) -> None:
    """Emit an already-measured span. For durations assembled from
    overlapping phases (the campaign pipeline's per-batch wall is
    ``device_dur + commit_stall``, which no single ``with`` block
    brackets) a caller computes the value and records it here. No-op
    when tracing is off; ``t_wall``/``mono`` default to "ended just
    now" so the span lands at the right place on the timeline."""
    t = _TRACER
    if t is None:
        return
    _stamp_ctx(attrs)
    if attrs.get("trace_id"):
        attrs.setdefault("span", new_span_id())
    sp = Span(None, name, attrs)
    sp.dur = max(0.0, float(dur))
    sp.t_wall = time.time() - sp.dur if t_wall is None else t_wall
    sp._t0 = time.monotonic() - sp.dur if mono is None else mono
    t._emit_span(sp)


#: record keys that are TRANSPORT metadata, not span/event attributes —
#: stripped before re-emission (the parent tracer re-stamps its own)
_META_KEYS = frozenset(("schema", "kind", "name", "t", "mono", "dur",
                        "tid", "session"))


def reemit_records(records: Sequence[Dict], mono_offset: float = 0.0,
                   **extra) -> int:
    """Re-emit telemetry drained from a child process (engine worker)
    onto the parent's global tracer, correcting each record's ``mono``
    by ``mono_offset`` (``parent_mono - child_mono``, from the spawn
    handshake) so both processes share one coherent timeline. ``extra``
    attrs (``proc="worker"``, ``wpid=...``) tag the records' origin;
    the child's own ``session`` is preserved as ``src_session``.
    Returns the number of records re-emitted."""
    t = _TRACER
    if t is None or not records:
        return 0
    n = 0
    for rec in records:
        if not isinstance(rec, dict) or "kind" not in rec:
            continue
        attrs = {k: v for k, v in rec.items() if k not in _META_KEYS}
        attrs.update(extra)
        if rec.get("session"):
            attrs.setdefault("src_session", rec["session"])
        mono = rec.get("mono")
        if isinstance(mono, (int, float)):
            mono = round(float(mono) + mono_offset, 6)
        else:
            mono = time.monotonic()
        if rec.get("kind") == "span":
            complete(str(rec.get("name", "?")),
                     float(rec.get("dur") or 0.0),
                     t_wall=rec.get("t"), mono=mono, **attrs)
        else:
            event(str(rec["kind"]), t=rec.get("t", round(time.time(), 6)),
                  mono=mono, **attrs)
        n += 1
    return n


def close() -> None:
    """Close and uninstall the global tracer (writes the Chrome file)."""
    global _TRACER
    if _TRACER is not None:
        _TRACER.close()
        _TRACER = None


__all__ = ["SCHEMA", "Span", "Tracer", "active", "apply_context",
           "close", "complete", "configure", "context_snapshot",
           "current_trace_id", "event", "get_tracer", "jsonl_path_for",
           "new_span_id", "new_trace_id", "reemit_records", "span",
           "timer", "trace_context", "trace_records"]
