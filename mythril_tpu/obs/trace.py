"""Span tracer: one ordered, schema'd event stream for the whole stack.

Before this module, diagnosing a slow or degraded campaign meant
grepping four disjoint channels (iprof histograms, ``CorpusCampaign``
events, ``BackendManager`` events, ad-hoc ``time.monotonic()`` deltas in
bench/tools). The tracer unifies them:

- ``with trace.span("superstep", steps=64):`` times a phase and emits it
  as BOTH a Chrome-trace event (open the ``--trace`` file in Perfetto /
  ``chrome://tracing``) and one line of an append-only JSONL event log
  with a versioned schema (``tools/trace_report.py`` summarizes it, the
  soak asserts it);
- ``trace.event("degrade", batch=3, step="halve-lanes")`` emits an
  instant event — the campaign re-emits its existing ``_events`` /
  ``backend.events`` channels here so the one stream carries everything
  in order;
- disabled (the default — no ``--trace`` flag), ``span()`` returns a
  shared no-op singleton and ``event()`` returns immediately: no
  allocation, no clock read, no file. Hot paths stay hot.

The JSONL schema (version :data:`SCHEMA`): every line is one JSON object
with at least ``kind`` (``"span"`` or an instant-event kind), ``t``
(wall-clock ``time.time()``, seconds) and ``schema``. Spans add ``name``,
``dur`` (seconds), ``mono`` (``time.monotonic()`` at span start — orders
events within a session where wall time may step) and ``tid``; all
``span(...)`` keyword attributes ride along verbatim. ``session`` is a
per-process token so streams from resumed/merged sessions stay sortable
(see ``merge_campaigns``).

``timer()`` is the always-measuring variant: it returns a real
:class:`Span` whose ``elapsed`` property works whether or not tracing is
enabled (emitting only when it is). bench.py and the profilers use it in
place of their former ad-hoc ``perf_counter``/``monotonic`` pairs, so
one mechanism both measures and (when asked) records.

Import cost is stdlib-only — no jax, no engine — so backend-free
front-ends (``campaign-merge``, bench's pre-probe phase) can load it.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, List, Optional

#: version stamped into every JSONL event (bump on breaking field
#: changes; readers must reject newer-than-known schemas)
SCHEMA = 1


def jsonl_path_for(chrome_path: str) -> str:
    """The JSONL event-log path derived from a ``--trace FILE``:
    ``t.json -> t.jsonl``, anything else gets ``.jsonl`` appended."""
    if chrome_path.endswith(".json"):
        return chrome_path[:-5] + ".jsonl"
    return chrome_path + ".jsonl"


class Span:
    """One timed phase. Context manager; ``elapsed`` is live inside the
    ``with`` block (seconds since entry) and frozen to the final
    duration after exit — callers can both drive budget loops off it
    mid-flight and read the measurement afterwards."""

    __slots__ = ("_tracer", "name", "attrs", "t_wall", "_t0", "dur")

    def __init__(self, tracer: Optional["Tracer"], name: str,
                 attrs: Dict[str, Any]):
        self._tracer = tracer
        self.name = name
        self.attrs = attrs
        self.t_wall = 0.0
        self._t0 = 0.0
        self.dur: Optional[float] = None

    def __enter__(self) -> "Span":
        self.t_wall = time.time()
        self._t0 = time.monotonic()
        return self

    #: stopwatch use outside a ``with`` block (``sw = timer("x").start()``;
    #: read ``sw.elapsed``; call ``sw.stop()`` if the span should emit)
    start = __enter__

    def stop(self) -> float:
        self.__exit__(None, None, None)
        return self.dur or 0.0

    def __exit__(self, *exc) -> bool:
        self.dur = time.monotonic() - self._t0
        if self._tracer is not None:
            self._tracer._emit_span(self)
        return False

    @property
    def elapsed(self) -> float:
        if self.dur is not None:
            return self.dur
        return time.monotonic() - self._t0


class _NullSpan:
    """The disabled-tracer singleton: zero state, zero clock reads.
    ``elapsed`` is 0.0 — code that needs a measurement regardless of
    tracing must use :func:`timer`, not :func:`span`."""

    __slots__ = ()

    def __enter__(self) -> "_NullSpan":
        return self

    def __exit__(self, *exc) -> bool:
        return False

    # mirror Span's stopwatch surface so ``span(...).start()`` /
    # ``.stop()`` stay safe when tracing is disabled
    start = __enter__

    def stop(self) -> float:
        return 0.0

    elapsed = 0.0


_NULL_SPAN = _NullSpan()


class Tracer:
    """Emits spans/events to an in-memory Chrome-trace buffer plus an
    append-only JSONL log (flushed per event, so a killed run leaves a
    readable prefix). Thread-safe; one per process is the normal case
    (the module-level :func:`configure` installs it globally)."""

    def __init__(self, chrome_path: Optional[str] = None,
                 jsonl_path: Optional[str] = None):
        self.chrome_path = chrome_path
        self.jsonl_path = (jsonl_path if jsonl_path is not None
                           else (jsonl_path_for(chrome_path)
                                 if chrome_path else None))
        self._lock = threading.Lock()
        self._chrome: List[Dict] = []
        self._t0_mono = time.monotonic()
        self._t0_wall = time.time()
        self._pid = os.getpid()
        #: per-process token: orders/merges event streams across resumed
        #: sessions and hosts (wall clocks may disagree; sessions don't)
        self.session = f"{self._pid:x}-{int(self._t0_wall * 1000):x}"
        self._fh = None
        if self.jsonl_path:
            d = os.path.dirname(os.path.abspath(self.jsonl_path))
            os.makedirs(d, exist_ok=True)
            self._fh = open(self.jsonl_path, "a", encoding="utf-8")
        self._closed = False

    # --- emission ------------------------------------------------------
    def _write_jsonl(self, rec: Dict) -> None:
        if self._fh is None:
            return
        line = json.dumps(rec, default=str)
        with self._lock:
            if not self._closed:
                self._fh.write(line + "\n")
                self._fh.flush()

    def _emit_span(self, sp: Span) -> None:
        tid = threading.get_ident()
        rec = {"schema": SCHEMA, "kind": "span", "name": sp.name,
               "t": round(sp.t_wall, 6), "mono": round(sp._t0, 6),
               "dur": round(sp.dur or 0.0, 6), "tid": tid,
               "session": self.session}
        for k, v in sp.attrs.items():
            rec.setdefault(k, v)
        self._write_jsonl(rec)
        ev = {"name": sp.name, "ph": "X", "pid": self._pid, "tid": tid,
              "ts": round((sp._t0 - self._t0_mono) * 1e6, 3),
              "dur": round((sp.dur or 0.0) * 1e6, 3)}
        if sp.attrs:
            ev["args"] = dict(sp.attrs)
        with self._lock:
            self._chrome.append(ev)

    def span(self, name: str, **attrs) -> Span:
        return Span(self, name, attrs)

    def event(self, kind: str, **attrs) -> None:
        """Instant event (Chrome phase ``i``). ``attrs`` may carry its
        own ``t``/``mono`` (a re-emitted historical event keeps its
        original clock readings); missing ones are stamped now."""
        now_wall = time.time()
        now_mono = time.monotonic()
        rec = {"schema": SCHEMA, "kind": kind,
               "t": round(now_wall, 6), "mono": round(now_mono, 6),
               "session": self.session}
        rec.update(attrs)
        self._write_jsonl(rec)
        mono = rec.get("mono", now_mono)
        if not isinstance(mono, (int, float)):
            mono = now_mono
        ev = {"name": kind, "ph": "i", "s": "p", "pid": self._pid,
              "tid": threading.get_ident(),
              "ts": round((mono - self._t0_mono) * 1e6, 3)}
        args = {k: v for k, v in attrs.items() if k not in ("t", "mono")}
        if args:
            ev["args"] = args
        with self._lock:
            self._chrome.append(ev)

    # --- lifecycle -----------------------------------------------------
    def flush(self) -> None:
        """Write the Chrome-trace file now (idempotent; ``close`` calls
        it). The JSONL log is already flushed per event."""
        if not self.chrome_path:
            return
        with self._lock:
            events = list(self._chrome)
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": {"schema": SCHEMA, "session": self.session,
                             "t0_wall": round(self._t0_wall, 6)}}
        tmp = f"{self.chrome_path}.{self._pid}.tmp"
        d = os.path.dirname(os.path.abspath(self.chrome_path))
        os.makedirs(d, exist_ok=True)
        with open(tmp, "w", encoding="utf-8") as fh:
            json.dump(doc, fh)
        os.replace(tmp, self.chrome_path)

    def close(self) -> None:
        self.flush()
        with self._lock:
            self._closed = True
            if self._fh is not None:
                self._fh.close()
                self._fh = None


# --- module-level API (the one most call sites use) --------------------

_TRACER: Optional[Tracer] = None


def configure(chrome_path: Optional[str] = None,
              jsonl_path: Optional[str] = None) -> Tracer:
    """Install the process-global tracer (replacing any previous one,
    which is closed first). ``--trace t.json`` maps to
    ``configure("t.json")`` → Chrome trace at ``t.json``, JSONL event
    log at ``t.jsonl``."""
    global _TRACER
    if _TRACER is not None:
        _TRACER.close()
    _TRACER = Tracer(chrome_path, jsonl_path)
    return _TRACER


def get_tracer() -> Optional[Tracer]:
    return _TRACER


def active() -> bool:
    """True when a tracer is installed — gate EXPENSIVE collection
    (device syncs, array reductions) on this, never plain span calls
    (those are already near-free when disabled)."""
    return _TRACER is not None


def span(name: str, **attrs):
    """Phase span on the global tracer; the shared no-op singleton when
    tracing is off (zero allocation, zero clock reads)."""
    t = _TRACER
    if t is None:
        return _NULL_SPAN
    return t.span(name, **attrs)


def timer(name: str, **attrs) -> Span:
    """Always-measuring span: ``elapsed`` works with tracing off; the
    event is emitted only when tracing is on. The replacement for
    ad-hoc ``t0 = monotonic(); ...; dt = monotonic() - t0`` pairs."""
    return Span(_TRACER, name, attrs)


def event(kind: str, **attrs) -> None:
    t = _TRACER
    if t is not None:
        t.event(kind, **attrs)


def complete(name: str, dur: float, t_wall: Optional[float] = None,
             mono: Optional[float] = None, **attrs) -> None:
    """Emit an already-measured span. For durations assembled from
    overlapping phases (the campaign pipeline's per-batch wall is
    ``device_dur + commit_stall``, which no single ``with`` block
    brackets) a caller computes the value and records it here. No-op
    when tracing is off; ``t_wall``/``mono`` default to "ended just
    now" so the span lands at the right place on the timeline."""
    t = _TRACER
    if t is None:
        return
    sp = Span(None, name, attrs)
    sp.dur = max(0.0, float(dur))
    sp.t_wall = time.time() - sp.dur if t_wall is None else t_wall
    sp._t0 = time.monotonic() - sp.dur if mono is None else mono
    t._emit_span(sp)


def close() -> None:
    """Close and uninstall the global tracer (writes the Chrome file)."""
    global _TRACER
    if _TRACER is not None:
        _TRACER.close()
        _TRACER = None


__all__ = ["SCHEMA", "Span", "Tracer", "active", "close", "complete",
           "configure", "event", "get_tracer", "jsonl_path_for", "span",
           "timer"]
