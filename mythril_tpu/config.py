"""Static-shape configuration for the device interpreter.

The reference has no analog — Python objects grow unboundedly
(``MachineState.stack`` is a list, memory a lazy dict ⚠unv, SURVEY.md §2
"State model"). On TPU every dimension is static; these caps define the
frontier array shapes. Lanes that exceed a cap raise a per-lane error flag
(masked trap) rather than crashing the batch — SURVEY.md §5.2.
"""

from __future__ import annotations

from dataclasses import dataclass

from .backend import default_oom_ladder


@dataclass(frozen=True)
class LimitsConfig:
    """Shape caps for one frontier. All sizes static at trace time."""

    max_stack: int = 256  # EVM allows 1024; solc output stays far below —
    # deep real-world frames trip ~30-60; 256 leaves 4x headroom and any
    # trip is attributed in the report coverage block (Trap.STACK)
    mem_bytes: int = 4096  # byte-addressable memory cap per lane
    calldata_bytes: int = 256  # symbolic tx calldata cap
    returndata_bytes: int = 256
    storage_slots: int = 64  # associative storage-cache entries per lane
    max_accounts: int = 8  # per-lane world-state account slots
    max_code: int = 24576  # EIP-170 runtime-code limit
    max_hash_bytes: int = 200  # SHA3 input cap (mapping keys are 64 bytes)
    log_slots: int = 8  # recorded LOG entries per lane
    tape_len: int = 512  # symbolic SSA tape nodes per lane
    max_constraints: int = 128  # path-condition slots per lane
    call_depth: int = 4  # saved call contexts per lane
    init_code_bytes: int = 1024  # in-tx CREATE/CREATE2 init-code buffer per
    # lane (longer init code falls back to the codeless-account path)
    call_log: int = 16  # recorded external-call events per lane
    arith_log: int = 32  # recorded symbolic-arithmetic events per lane
    propagate_every: int = 8  # supersteps between feasibility sweeps
    loop_bound: int = 8  # max taken backward jumps to one target per lane
    # (0 disables; reference: BoundedLoopsStrategy --loop-bound ⚠unv)
    loop_slots: int = 8  # tracked distinct back-jump targets per lane
    gas_schedule: str = "istanbul"  # "istanbul" (reference-era static
    # table) or "berlin" (EIP-2929 warm/cold access accounting)

    def __post_init__(self):
        assert self.max_stack >= 17  # SWAP16 arity
        assert self.mem_bytes % 32 == 0


DEFAULT_LIMITS = LimitsConfig()


@dataclass(frozen=True)
class ResilienceConfig:
    """Campaign-supervisor knobs (see ``mythril_tpu/resilience.py``).

    ``batch_timeout=None`` disables the per-batch watchdog (an
    interactive single-contract analyze has ``--execution-timeout`` for
    pacing; the watchdog exists for unattended corpus campaigns).
    ``init_timeout`` bounds the subprocess backend probe — 75 s
    comfortably covers a healthy TPU init (~20 s measured) while a
    wedged runtime hangs forever (docs/tpu-wedge-round5.md)."""

    batch_timeout: float | None = None  # seconds per campaign batch
    init_timeout: float = 75.0          # seconds per backend-init probe
    max_batch_retries: int = 1          # re-attempts before bisection
    probe_attempts: int = 2             # backend re-init attempts
    probe_backoff: float = 5.0          # seconds between probe attempts
    # RESOURCE_EXHAUSTED degradation ladder, walked in order and
    # cumulatively (see resilience.DEGRADE_RUNGS / docs/resilience.md):
    # shrink the work until the batch fits instead of aborting the run.
    # The shape comes from the BackendProfile registry; the terminal
    # rung means "demote to the next available tier", not "pin to CPU"
    oom_ladder: tuple = default_oom_ladder()
    # batches between durable campaign-checkpoint writes (1 = every
    # batch — kill -9 at any instant loses at most one batch; larger
    # values trade replayed batches for less checkpoint I/O)
    checkpoint_every: int = 1
    # --- backend tiers (mythril_tpu/backend.py, docs/resilience.md
    # "Backend tiers"): the demote-and-repromote failover ladder
    backend_tiers: tuple | None = None   # ranked tier names; None = detect
    tier_probe_every: float = 30.0       # s between re-promotion probes
    tier_sticky_window: float = 20.0     # s a fresh demotion must hold
    tier_flap_window: float = 120.0      # rolling window for flap damping
    tier_flap_max: int = 4               # max transitions per flap window


DEFAULT_RESILIENCE = ResilienceConfig()

# Small limits for fast unit tests
TEST_LIMITS = LimitsConfig(
    max_stack=32,
    mem_bytes=1024,
    calldata_bytes=128,
    returndata_bytes=128,
    storage_slots=16,
    max_accounts=4,
    max_code=512,
    max_hash_bytes=136,
    log_slots=4,
    tape_len=128,
    max_constraints=32,
    call_depth=2,
    init_code_bytes=256,
    call_log=4,
    arith_log=8,
    propagate_every=4,
    loop_bound=4,
    loop_slots=4,
)
