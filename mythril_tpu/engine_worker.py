"""Supervised engine worker: subprocess-isolated device execution.

The child half of the process-isolation boundary
(docs/resilience.md "Process isolation & supervision"): this process
OWNS the JAX backend and runs device batches on behalf of a parent
``WorkerSupervisor`` (mythril_tpu/resilience.py), speaking a
length-prefixed pickle protocol over its stdin/stdout pipes. The
division of labor:

- a libtpu segfault, an OOM kill, or a wedged XLA compile happens
  HERE — the parent observes pipe EOF (death) or a missed deadline
  (hang) and restarts this process, feeding the failed batch back
  through the campaign's retry→ladder→bisect machinery;
- an engine EXCEPTION (solver error, RESOURCE_EXHAUSTED, a poison
  contract) is caught, classified with
  :func:`mythril_tpu.resilience.classify_backend_error`, and returned
  as an error reply — the worker survives, and the parent rehydrates
  the same typed error its in-process path would have seen.

Protocol (every frame = 8-byte big-endian length + pickle):

- ``{"op": "init", "stub": bool, "config": {...}}`` → builds the
  resident engine (or nothing, in stub mode) and replies
  ``{"ok": True, "value": {"pid": ...}}``. ``config`` carries the
  parent campaign's engine knobs (shapes, limits, spec, solver
  budget); the worker builds its own corpus-less ``CorpusCampaign``
  and serves batches through its ``_explore_batch``/``_harvest_batch``
  seam, so batch semantics (padding, warm shapes, pad filtering) are
  the campaign's own code, not a re-implementation.
- ``{"op": "batch", "bi", "names", "codes", "lanes", "width",
  "on_cpu"}`` → ``{"ok": True, "value": {issues/paths/dropped/iprof}}``
  or ``{"ok": False, "etype", "emsg", "classify"}``.
- ``{"op": "ping"}`` → rss diagnostics; ``{"op": "exit"}`` → clean 0.

Stdout is the protocol channel: the REAL fd is duplicated away at
startup and fd 1 is re-pointed at stderr, so engine prints and jax
warnings can never corrupt a frame. EOF on stdin (parent death) exits
the worker — an orphaned worker never outlives its supervisor.

Deterministic chaos (tools/chaos_campaign.py): the
``MYTHRIL_WORKER_FAULT`` env var — ``sig:point:nth[:once=PATH]`` with
``sig`` ∈ kill|segv and ``point`` ∈ mid-compile|mid-superstep|
mid-reply — makes the worker deliver a REAL signal to itself at the
named point of its ``nth`` batch request (``once=PATH`` is a cookie
file so the fault fires exactly once across restarts). ``mid-reply``
writes a torn half-frame first, so the parent also exercises the
truncated-IPC path.

Stub mode (``init`` with ``stub=True``) skips every engine import and
answers batches with deterministic counts — the fast worker for
supervision-machinery tests; pipes, signals and process death are just
as real. A stub batch whose names include ``__hang__`` sleeps forever
(the parent-deadline fixture).
"""

from __future__ import annotations

import contextlib
import os
import pickle
import signal
import struct
import sys
import time
from typing import BinaryIO, Dict, List, Optional

from .obs import metrics as obs_metrics
from .obs import trace as obs_trace

#: frame header: one 8-byte big-endian payload length
FRAME_HEADER = struct.Struct(">Q")

PROTOCOL_VERSION = 1

_FAULT_SIGNALS = {"kill": signal.SIGKILL, "segv": signal.SIGSEGV}
_FAULT_POINTS = ("mid-compile", "mid-superstep", "mid-reply")


def pack_frame(obj) -> bytes:
    data = pickle.dumps(obj, protocol=pickle.HIGHEST_PROTOCOL)
    return FRAME_HEADER.pack(len(data)) + data


def read_frame(stream: BinaryIO):
    """One frame from a blocking stream, or None on EOF (the child's
    read side; the parent reads with a deadline instead — see
    ``WorkerSupervisor._read_frame``)."""
    hdr = b""
    while len(hdr) < FRAME_HEADER.size:
        chunk = stream.read(FRAME_HEADER.size - len(hdr))
        if not chunk:
            return None
        hdr += chunk
    (n,) = FRAME_HEADER.unpack(hdr)
    buf = b""
    while len(buf) < n:
        chunk = stream.read(n - len(buf))
        if not chunk:
            return None
        buf += chunk
    return pickle.loads(buf)


class ChildFault:
    """Parsed ``MYTHRIL_WORKER_FAULT`` spec (see module docstring)."""

    def __init__(self, sig: int, point: str, nth: int,
                 once: Optional[str] = None):
        self.sig = sig
        self.point = point
        self.nth = nth
        self.once = once

    @classmethod
    def from_env(cls) -> Optional["ChildFault"]:
        text = os.environ.get("MYTHRIL_WORKER_FAULT")
        if not text:
            return None
        parts = text.strip().split(":")
        if len(parts) < 3 or parts[0] not in _FAULT_SIGNALS \
                or parts[1] not in _FAULT_POINTS:
            raise ValueError(
                f"MYTHRIL_WORKER_FAULT {text!r}: expected "
                f"sig:point:nth[:once=PATH] with sig of "
                f"{tuple(_FAULT_SIGNALS)} and point of {_FAULT_POINTS}")
        once = None
        for extra in parts[3:]:
            if extra.startswith("once="):
                once = extra[len("once="):]
            else:
                raise ValueError(
                    f"MYTHRIL_WORKER_FAULT {text!r}: unknown option "
                    f"{extra!r}")
        return cls(_FAULT_SIGNALS[parts[0]], parts[1], int(parts[2]),
                   once)

    def _take(self) -> bool:
        """Claim the fault. With ``once=PATH`` the cookie file is the
        cross-restart memory: the first taker creates it and fires,
        every later (restarted) worker sees it and stays healthy."""
        if self.once is None:
            return True
        try:
            fd = os.open(self.once, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
        except FileExistsError:
            return False
        except OSError:
            return True  # unwritable cookie dir: still fire (visible)
        os.close(fd)
        return True

    def should(self, point: str, nth: int) -> bool:
        return (point == self.point and nth == self.nth
                and self._take())

    def fire(self, point: str, nth: int) -> None:
        """Deliver the REAL signal to this process at a named point —
        a genuine SIGSEGV/SIGKILL death, not a Python exception."""
        if self.should(point, nth):
            os.kill(os.getpid(), self.sig)
            time.sleep(5)  # SIGKILL delivery is async; don't race on


def _rss_bytes() -> int:
    try:
        with open("/proc/self/statm") as fh:
            return int(fh.read().split()[1]) * os.sysconf("SC_PAGE_SIZE")
    except (OSError, ValueError, IndexError, AttributeError):
        return 0


#: marker a supervisor drops into the shared XLA cache dir when a
#: worker dies uncleanly mid-batch: the NEXT spawn must probe the cache
#: before trusting it (a killed writer can leave a torn entry that
#: segfaults later readers — tests/conftest.py documents the original
#: incident)
CACHE_DIRTY_MARKER = ".dirty"

# the probe body: a minimal jit through the suspect cache dir, run in
# a THROWAWAY subprocess (PR-13 pattern: a poisoned cache segfaults the
# probe child, never this worker). MYTHRIL_CACHE_PROBE_FAULT=segv|hang
# is the deterministic-chaos hook standing in for a real torn entry.
_PROBE_SRC = """\
import os, signal, sys, time
f = os.environ.get("MYTHRIL_CACHE_PROBE_FAULT")
if f == "segv":
    os.kill(os.getpid(), signal.SIGSEGV); time.sleep(5)
if f == "hang":
    time.sleep(3600)
import jax
jax.config.update("jax_compilation_cache_dir", sys.argv[1])
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)
import jax.numpy as jnp
jax.jit(lambda x: x + 1)(jnp.zeros((8,), jnp.int32)).block_until_ready()
"""


def probe_cache(cache: str, timeout: Optional[float] = None) -> bool:
    """Whether a probe compile through ``cache`` survives. Best-effort
    by construction (a torn entry only fires when ITS key is read; the
    probe catches index/deserializer-level poison), but the failure
    mode is contained: the probe child dies, not the engine."""
    import subprocess

    if timeout is None:
        timeout = float(os.environ.get(
            "MYTHRIL_CACHE_PROBE_TIMEOUT", "180"))
    env = dict(os.environ)
    env.setdefault("JAX_PLATFORMS", "cpu")
    try:
        r = subprocess.run([sys.executable, "-c", _PROBE_SRC, cache],
                           capture_output=True, timeout=timeout,
                           env=env)
        return r.returncode == 0
    except (subprocess.TimeoutExpired, OSError):
        return False


def _maybe_probe_cache(cache: str) -> str:
    """Corrupt-persistent-cache resilience: when the supervisor flagged
    the cache ``.dirty`` (a worker died uncleanly) or the operator
    forces it (``MYTHRIL_CACHE_PROBE=1``), probe-compile in a subprocess
    before the engine touches a single entry. A failed probe sets the
    WHOLE dir aside as ``<cache>.corrupt`` (evidence preserved — never
    a silent wipe) and continues cold on a fresh dir with a loud
    ``compile_cache_quarantined`` event; a clean probe clears the
    marker. Returns the cache dir the engine should use."""
    marker = os.path.join(cache, CACHE_DIRTY_MARKER)
    forced = os.environ.get("MYTHRIL_CACHE_PROBE") == "1"
    if not (forced or os.path.exists(marker)):
        return cache
    if probe_cache(cache):
        try:
            os.unlink(marker)
        except OSError:
            pass
        return cache
    dest = cache + ".corrupt"
    if os.path.exists(dest):
        dest = f"{cache}.corrupt.{os.getpid()}"
    try:
        os.replace(cache, dest)
    except OSError:
        dest = None  # couldn't set aside; still never serve it as-is
    os.makedirs(cache, exist_ok=True)
    obs_trace.event("compile_cache_quarantined", cache=cache,
                    quarantined_to=dest or "")
    obs_metrics.REGISTRY.counter(
        "compile_cache_quarantined_total",
        help="poisoned XLA cache dirs set aside .corrupt").inc()
    print(f"[worker] XLA cache {cache} failed its probe compile; "
          f"quarantined to {dest}, continuing cold", file=sys.stderr,
          flush=True)
    return cache


def _build_campaign(config: Dict):
    """The worker's resident engine: a corpus-less CorpusCampaign with
    the parent's knobs. Heavy imports happen here, under the parent's
    spawn deadline — a wedged backend init is a killed worker, not a
    wedged fleet."""
    import mythril_tpu  # noqa: F401  (enables x64)

    cache = os.environ.get("MYTHRIL_WORKER_JAX_CACHE")
    if cache:
        cache = _maybe_probe_cache(cache)
        import jax

        jax.config.update("jax_compilation_cache_dir", cache)
        jax.config.update("jax_persistent_cache_min_compile_time_secs",
                          1.0)
    if config.get("solver_store"):
        from .smt import portfolio as smt_portfolio

        smt_portfolio.set_store(config["solver_store"])
    from .mythril.campaign import CorpusCampaign

    return CorpusCampaign(
        [],
        batch_size=int(config.get("batch_size", 32)),
        lanes_per_contract=int(config.get("lanes_per_contract", 32)),
        limits=config["limits"],
        spec=config.get("spec"),
        max_steps=int(config.get("max_steps", 256)),
        transaction_count=int(config.get("transaction_count", 1)),
        modules=config.get("modules"),
        solver_timeout=config.get("solver_timeout"),
        solver_iters=int(config.get("solver_iters", 400)),
        parallel_solving=bool(config.get("parallel_solving", False)),
        solver_workers=int(config.get("solver_workers", 1)),
        enable_iprof=bool(config.get("enable_iprof", False)),
        batch_timeout=None,         # the PARENT enforces the deadline
        worker_isolation="off",     # no recursive workers
        solver_store=None,          # installed above, process-global
    )


def _run_batch(camp, stub: bool, msg: Dict,
               fault: Optional[ChildFault], nth: int) -> Dict:
    bi = int(msg["bi"])
    names = list(msg["names"])
    codes = list(msg["codes"])
    lanes = msg.get("lanes")
    width = msg.get("width")
    # re-enter the parent's request trace scope: every span/event this
    # batch emits (device_phase, superstep, solver stages) carries the
    # same trace_id the HTTP submit minted, two processes away
    with obs_trace.apply_context(msg.get("trace")):
        if fault is not None:
            fault.fire("mid-compile", nth)
        if stub:
            if "__hang__" in names:
                time.sleep(3600)
            with obs_trace.timer("device_phase", bi=bi,
                                 n=len(names)) as dv:
                if fault is not None:
                    fault.fire("mid-superstep", nth)
            return {"issues": [], "paths": len(names), "dropped": 0,
                    "iprof": {},
                    "phases": {"device": dv.dur or 0.0, "host": 0.0}}
        # tier pin: honor the explicit tier label when present (a
        # demoted parent pins degraded batches to its tier), else the
        # historical on_cpu bool from older supervisors
        tier = (msg.get("on_tier")
                or ("cpu" if msg.get("on_cpu") else None))
        cm = camp._tier_device(tier) if tier else None
        with (cm if cm is not None else contextlib.nullcontext()):
            with obs_trace.timer("device_phase", bi=bi,
                                 n=len(names)) as dv:
                sym = camp._explore_batch(bi, names, codes, lanes,
                                          width)
                if fault is not None:
                    # after the device work ran, before the host
                    # harvest: the closest honest stand-in for
                    # "mid-superstep" a process boundary allows
                    fault.fire("mid-superstep", nth)
            with obs_trace.timer("host_phase", bi=bi) as hp:
                out = camp._harvest_batch(bi, sym)
        out["phases"] = {"device": dv.dur or 0.0, "host": hp.dur or 0.0}
        # the chunk step-counts this worker has compiled through the
        # shared persistent cache: the parent folds them into its
        # compile-store bucket so a RESTARTED daemon's prewarm can seed
        # them and keep engine_compiles_total flat across the restart
        out["warm_chunks"] = sorted(
            {int(c) for c in camp._warm_set(lanes, width)
             if not isinstance(c, tuple)})
        return out


def _run_prewarm(camp, stub: bool, msg: Dict) -> Dict:
    """AOT prewarm verb: compile a list of shape buckets ahead of
    traffic. Each bucket is a shape SKELETON — ``{lanes, width,
    tier?}`` — compiled by running ``_explore_batch`` over an all-pad
    STOP-stub corpus (shape, not content, keys the jaxpr: the
    ShapeDtypeStruct idea from tools/scaling_report.py without needing
    AOT export plumbing; the persistent cache makes the artifact
    durable). One bucket per frame-roundtrip would be cleaner but
    slower; instead the whole list rides one verb and the reply carries
    how far it got. Stub mode validates shapes and counts — the
    supervision-machinery tests' fast path."""
    buckets = list(msg.get("buckets") or [])
    done = 0
    warm_chunks: List[List[int]] = []
    for b in buckets:
        lanes = int(b.get("lanes") or 0)
        width = int(b.get("width") or 0)
        if lanes <= 0 or width <= 0:
            raise ValueError(
                f"prewarm bucket {b!r}: non-positive shape")
        if stub:
            done += 1
            warm_chunks.append([])
            continue
        # the bucket's recorded chunks are warm FLEET-wide (their
        # executables live in the shared persistent cache), so mark
        # them before exploring: the compile counter must read this
        # pass as cache traffic, not fresh compilation
        camp._warm_set(lanes, width).update(
            int(c) for c in b.get("chunks") or ())
        tier = b.get("tier") or msg.get("on_tier")
        cm = camp._tier_device(tier) if tier else None
        with (cm if cm is not None else contextlib.nullcontext()):
            with obs_trace.timer("prewarm_compile", lanes=lanes,
                                 width=width, tier=tier or ""):
                sym = camp._explore_batch(-1, [], [], lanes, width)
                # the wrapper compiles lazily as chunks run; touching
                # the exploration result forces every chunk through
                camp._harvest_batch(-1, sym)
        warm_chunks.append(sorted(
            {int(c) for c in camp._warm_set(lanes, width)
             if not isinstance(c, tuple)}))
        done += 1
    return {"done": done, "total": len(buckets), "stub": stub,
            "warm_chunks": warm_chunks}


def _drain_telemetry(msnap: Optional[Dict]) -> Optional[Dict]:
    """The per-reply telemetry payload: buffered spans/events, a fresh
    child ``monotonic()`` reading (the parent refreshes its clock
    offset against it), and the metric delta since the last reply.
    ``None`` when the parent didn't ask for tracing at init."""
    tracer = obs_trace.get_tracer()
    if tracer is None or tracer.buffer_records is None:
        return None
    after = obs_metrics.REGISTRY.snapshot()
    return {"records": tracer.drain_buffer(),
            "mono": time.monotonic(),
            "metrics": obs_metrics.snapshot_delta(after, msnap or {}),
            "_after": after}


def worker_main() -> int:
    # claim the protocol channel, then point fd 1 at stderr so engine
    # prints / jax warnings cannot corrupt a frame
    inp = os.fdopen(os.dup(sys.stdin.fileno()), "rb", buffering=0)
    out = os.fdopen(os.dup(sys.stdout.fileno()), "wb", buffering=0)
    os.dup2(sys.stderr.fileno(), sys.stdout.fileno())
    sys.stdout = sys.stderr
    fault = ChildFault.from_env()
    camp = None
    stub = False
    nbatch = 0
    msnap: Optional[Dict] = None
    while True:
        msg = read_frame(inp)
        if msg is None:
            return 0  # parent closed the pipe (or died): exit with it
        op = msg.get("op")
        tear = False
        try:
            if op == "init":
                stub = bool(msg.get("stub"))
                if msg.get("trace"):
                    # parent is tracing: buffer spans/events locally
                    # and ship them back with each batch reply
                    obs_trace.configure(buffer=True)
                    msnap = obs_metrics.REGISTRY.snapshot()
                if not stub:
                    camp = _build_campaign(msg.get("config") or {})
                # the child monotonic reading is half of the clock
                # handshake: the parent computes
                # offset = parent_mono - child_mono for span stitching
                reply = {"ok": True,
                         "value": {"pid": os.getpid(), "stub": stub,
                                   "protocol": PROTOCOL_VERSION,
                                   "mono": time.monotonic()}}
            elif op == "ping":
                reply = {"ok": True, "value": {"pid": os.getpid(),
                                               "rss": _rss_bytes()}}
            elif op == "batch":
                nbatch += 1
                value = _run_batch(camp, stub, msg, fault, nbatch)
                tel = _drain_telemetry(msnap)
                if tel is not None:
                    msnap = tel.pop("_after")
                    value["telemetry"] = tel
                reply = {"ok": True, "value": value}
                tear = (fault is not None
                        and fault.should("mid-reply", nbatch))
            elif op == "prewarm":
                value = _run_prewarm(camp, stub, msg)
                tel = _drain_telemetry(msnap)
                if tel is not None:
                    msnap = tel.pop("_after")
                    value["telemetry"] = tel
                reply = {"ok": True, "value": value}
            elif op == "exit":
                try:
                    out.write(pack_frame({"ok": True, "value": None}))
                    out.flush()
                except OSError:
                    pass
                return 0
            else:
                reply = {"ok": False, "etype": "ValueError",
                         "emsg": f"unknown op {op!r}", "classify": None}
        except BaseException as e:  # noqa: BLE001 — relayed typed
            from .resilience import classify_backend_error

            reply = {"ok": False, "etype": type(e).__name__,
                     "emsg": str(e)[:2000],
                     "classify": classify_backend_error(e)}
        frame = pack_frame(reply)
        try:
            if tear:
                # torn mid-reply: half a frame on the wire, then a real
                # signal — the parent must treat it as worker death
                out.write(frame[:max(1, len(frame) // 2)])
                out.flush()
                os.kill(os.getpid(), fault.sig)
                time.sleep(5)
            out.write(frame)
            out.flush()
        except OSError:
            return 0  # parent went away mid-reply


if __name__ == "__main__":
    raise SystemExit(worker_main())
