"""Host-callback capability probe.

The axon_pjrt TPU runtime used in this environment rejects
``jax.pure_callback`` / ``io_callback`` outright
("UNIMPLEMENTED: axon_pjrt does not support host send/recv callbacks") —
measured round 4: the ecrecover host callback made ``sym_run`` fail to
compile on the real chip while passing every CPU test. Standard TPU
runtimes DO support callbacks, so this is a runtime property, not a
platform property, and ``jax.default_backend()`` reports plain "tpu"
either way. The only robust detection is an empirical probe: compile and
run a trivial callback once per process and cache the verdict.

Callers (the precompile dispatcher) choose at TRACE TIME between the
host-callback path and the sound uninterpreted-leaf fallback, so an
unsupported runtime costs precision (concrete ecrecover/bn128/blake2f
degrade to havoc leaves), never correctness.
"""

from __future__ import annotations

import logging
import os
from typing import Optional

log = logging.getLogger(__name__)

_CB_OK: Optional[bool] = None


def host_callbacks_supported() -> bool:
    """True iff jitted ``pure_callback`` works on the default backend.

    MUST resolve OUTSIDE any active jax trace: probing while another
    function is being traced embeds the probe's callback into the OUTER
    jaxpr as a dead pjit equation, which axon then refuses to compile —
    exactly the failure the probe exists to prevent (measured round 4:
    bench's sym section failed while the later analyze section, served by
    the cached verdict, passed). The engine module triggers an eager
    probe at import; if this is nonetheless first called mid-trace, the
    verdict is a conservative False for that trace (not cached)."""
    global _CB_OK
    if _CB_OK is None:
        forced = os.environ.get("MYTHRIL_HOST_CALLBACKS")
        if forced is not None:
            _CB_OK = forced not in ("0", "off", "no")
            return _CB_OK
        import jax
        import jax.numpy as jnp

        try:
            if not jax.core.trace_state_clean():
                log.warning(
                    "host-callback probe requested mid-trace; answering "
                    "False for this trace (probe at import next time)")
                return False  # deliberately NOT cached
        except Exception:  # noqa: BLE001 — trace-state API drift
            pass
        try:
            out = jax.jit(
                lambda x: jax.pure_callback(
                    lambda a: a,
                    jax.ShapeDtypeStruct((), jnp.int32),
                    x,
                )
            )(jnp.int32(7))
            _CB_OK = int(out) == 7
        except Exception as e:  # noqa: BLE001 — any failure means "no"
            log.info("host callbacks unavailable on %s: %r",
                     jax.default_backend(), e)
            _CB_OK = False
    return _CB_OK
