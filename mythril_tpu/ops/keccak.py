"""Keccak-256 — host reference + batched JAX keccak-f[1600] kernel.

The reference delegates hashing to the C ``pysha3``/``safe-pysha3`` extension
(⚠unv, SURVEY.md §2.2). Here:

- :func:`keccak256_host` — pure-Python implementation for host-side needs
  (selectors, CREATE2 addresses, test oracle). Anchored against published
  keccak-256 test vectors in tests.
- :func:`keccak_f1600` / :func:`keccak256_device` — the same permutation as
  pure u64 bitwise ops over ``u64[..., 25]`` lane arrays, fully batched:
  hashing N lanes of M bytes is one fused XLA op sequence. This is the
  TPU replacement for per-call C hashing (SHA3 opcode over concrete
  memory, storage-key hashing for mappings).

Keccak (pre-NIST) padding: ``msg || 0x01 || 0* || 0x80``; rate 136 bytes.
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

RATE_BYTES = 136  # 1088-bit rate for keccak-256
RATE_LANES = RATE_BYTES // 8

_RC = np.array(
    [
        0x0000000000000001, 0x0000000000008082, 0x800000000000808A, 0x8000000080008000,
        0x000000000000808B, 0x0000000080000001, 0x8000000080008081, 0x8000000000008009,
        0x000000000000008A, 0x0000000000000088, 0x0000000080008009, 0x000000008000000A,
        0x000000008000808B, 0x800000000000008B, 0x8000000000008089, 0x8000000000008003,
        0x8000000000008002, 0x8000000000000080, 0x000000000000800A, 0x800000008000000A,
        0x8000000080008081, 0x8000000000008080, 0x0000000080000001, 0x8000000080008008,
    ],
    dtype=np.uint64,
)

# rotation offsets r[x][y] for lane A[x, y]
_ROT = [
    [0, 36, 3, 41, 18],
    [1, 44, 10, 45, 2],
    [62, 6, 43, 15, 61],
    [28, 55, 25, 21, 56],
    [27, 20, 39, 8, 14],
]

_M64 = (1 << 64) - 1


# ---------------------------------------------------------------------------
# Host reference (pure Python ints)
# ---------------------------------------------------------------------------


def _rotl_int(x: int, n: int) -> int:
    n %= 64
    return ((x << n) | (x >> (64 - n))) & _M64


def _f1600_host(lanes: list) -> list:
    # lanes: flat list of 25 ints, A[x, y] = lanes[5*y + x]
    a = [[lanes[5 * y + x] for y in range(5)] for x in range(5)]
    for rnd in range(24):
        c = [a[x][0] ^ a[x][1] ^ a[x][2] ^ a[x][3] ^ a[x][4] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl_int(c[(x + 1) % 5], 1) for x in range(5)]
        for x in range(5):
            for y in range(5):
                a[x][y] ^= d[x]
        b = [[0] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                b[y][(2 * x + 3 * y) % 5] = _rotl_int(a[x][y], _ROT[x][y])
        for x in range(5):
            for y in range(5):
                a[x][y] = b[x][y] ^ ((~b[(x + 1) % 5][y] & _M64) & b[(x + 2) % 5][y])
        a[0][0] ^= int(_RC[rnd])
    return [a[x][y] for y in range(5) for x in range(5)]


def keccak256_host(data: bytes) -> bytes:
    """Keccak-256 of concrete bytes (host path; test oracle)."""
    padded = bytearray(data)
    padded.append(0x01)
    while len(padded) % RATE_BYTES:
        padded.append(0x00)
    padded[-1] |= 0x80
    lanes = [0] * 25
    for off in range(0, len(padded), RATE_BYTES):
        block = padded[off : off + RATE_BYTES]
        for i in range(RATE_LANES):
            lanes[i] ^= int.from_bytes(block[8 * i : 8 * i + 8], "little")
        lanes = _f1600_host(lanes)
    out = b"".join(int(lanes[i]).to_bytes(8, "little") for i in range(4))
    return out


def keccak256_host_int(data: bytes) -> int:
    return int.from_bytes(keccak256_host(data), "big")


# ---------------------------------------------------------------------------
# Batched JAX kernel
# ---------------------------------------------------------------------------


def _rotl(x, n: int):
    n %= 64
    if n == 0:
        return x
    return (x << jnp.uint64(n)) | (x >> jnp.uint64(64 - n))


def keccak_f1600(state):
    """keccak-f[1600] permutation over ``u64[..., 25]`` (A[x,y] = [..., 5y+x])."""
    a = [[state[..., 5 * y + x] for y in range(5)] for x in range(5)]
    rc = jnp.asarray(_RC)

    def round_fn(rnd, a_flat):
        a = [[a_flat[5 * y + x] for y in range(5)] for x in range(5)]
        c = [a[x][0] ^ a[x][1] ^ a[x][2] ^ a[x][3] ^ a[x][4] for x in range(5)]
        d = [c[(x - 1) % 5] ^ _rotl(c[(x + 1) % 5], 1) for x in range(5)]
        a = [[a[x][y] ^ d[x] for y in range(5)] for x in range(5)]
        b = [[None] * 5 for _ in range(5)]
        for x in range(5):
            for y in range(5):
                b[y][(2 * x + 3 * y) % 5] = _rotl(a[x][y], _ROT[x][y])
        a = [
            [b[x][y] ^ ((~b[(x + 1) % 5][y]) & b[(x + 2) % 5][y]) for y in range(5)]
            for x in range(5)
        ]
        a[0][0] = a[0][0] ^ rc[rnd]
        return [a[x][y] for y in range(5) for x in range(5)]

    a_flat = [a[x][y] for y in range(5) for x in range(5)]
    a_flat = jax.lax.fori_loop(0, 24, round_fn, a_flat)
    return jnp.stack(a_flat, axis=-1)


def keccak256_device(data, length):
    """Batched keccak-256.

    data:   ``u8[..., max_len]`` zero-padded message bytes
    length: ``i32[...]`` actual message lengths (<= max_len)
    returns ``u32[..., 8]`` hash as little-endian u256 limbs
            (limb 0 = least-significant 32 bits of the big-endian hash value).
    """
    data = jnp.asarray(data, dtype=jnp.uint8)
    length = jnp.asarray(length, dtype=jnp.int32)
    max_len = data.shape[-1]
    batch = data.shape[:-1]
    # message + at least one pad byte must fit
    n_blocks = (max_len + 1 + RATE_BYTES - 1) // RATE_BYTES
    padded_len = n_blocks * RATE_BYTES

    pos = jnp.arange(padded_len, dtype=jnp.int32)
    src = jnp.pad(data, [(0, 0)] * len(batch) + [(0, padded_len - max_len)])
    msg = jnp.where(pos < length[..., None], src, 0)
    msg = jnp.where(pos == length[..., None], jnp.uint8(0x01), msg)
    # 0x80 closes the final block (the one containing the 0x01)
    final_block = length // RATE_BYTES  # block index holding byte `length`
    last_byte_pos = (final_block + 1) * RATE_BYTES - 1
    msg = jnp.where(pos == last_byte_pos[..., None], msg | jnp.uint8(0x80), msg)

    # bytes -> u64 lanes, little-endian: lane j of block b = bytes [b*136+8j .. +8)
    msg64 = msg.astype(jnp.uint64)
    lanes_all = msg64.reshape(batch + (n_blocks, RATE_LANES, 8))
    shifts = (jnp.arange(8, dtype=jnp.uint64) * 8)
    blocks = jnp.sum(lanes_all << shifts, axis=-1)  # [..., n_blocks, 17]

    state0 = jnp.zeros(batch + (25,), dtype=jnp.uint64)

    def absorb(i, state):
        blk = jnp.take(blocks, i, axis=-2)  # [..., 17]
        xored = state.at[..., :RATE_LANES].set(state[..., :RATE_LANES] ^ blk)
        nxt = keccak_f1600(xored)
        active = (i <= final_block)[..., None]
        return jnp.where(active, nxt, state)

    state = jax.lax.fori_loop(0, n_blocks, absorb, state0)

    # squeeze 32 bytes = lanes 0..3 little-endian; convert to LE u32 limbs of
    # the big-endian hash integer: byte k of the hash (k=0 most significant
    # byte... k=0 is FIRST hash byte = most significant of the value)
    lanes4 = state[..., :4]  # u64
    byte_idx = jnp.arange(32)
    hash_bytes = (
        jnp.take(lanes4, byte_idx // 8, axis=-1) >> (8 * (byte_idx % 8)).astype(jnp.uint64)
    ) & jnp.uint64(0xFF)  # [..., 32], hash byte k
    # limb i (value bits [32i, 32i+32)) = bytes k in [28-4i, 31-4i], k smaller = more significant
    limb_ids = jnp.arange(8)
    k_base = 28 - 4 * limb_ids  # most-significant byte index per limb
    gather = k_base[:, None] + jnp.arange(4)[None, :]  # [8, 4]
    b = jnp.take(hash_bytes, gather.reshape(-1), axis=-1).reshape(batch + (8, 4))
    weights = jnp.uint64(1) << (jnp.uint64(8) * (3 - jnp.arange(4)).astype(jnp.uint64))
    limbs = jnp.sum(b * weights, axis=-1).astype(jnp.uint32)
    return limbs
