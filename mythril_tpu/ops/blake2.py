"""BLAKE2b compression function F (EIP-152 precompile 0x09), pure Python.

The reference computes this native via the blake2b-py Rust crate
(``mythril/laser/ethereum/natives.py`` ⚠unv, SURVEY.md §2.2); Rust is not
available in this image, and the precompile is a rare concrete-input host
path, so a direct RFC-7693 implementation is the right shape. Validated
against ``hashlib.blake2b`` by running the full hash through this F
(tests/test_precompiles.py).
"""

from __future__ import annotations

from typing import List, Optional

MASK64 = (1 << 64) - 1

IV = (
    0x6A09E667F3BCC908, 0xBB67AE8584CAA73B,
    0x3C6EF372FE94F82B, 0xA54FF53A5F1D36F1,
    0x510E527FADE682D1, 0x9B05688C2B3E6C1F,
    0x1F83D9ABFB41BD6B, 0x5BE0CD19137E2179,
)

SIGMA = (
    (0, 1, 2, 3, 4, 5, 6, 7, 8, 9, 10, 11, 12, 13, 14, 15),
    (14, 10, 4, 8, 9, 15, 13, 6, 1, 12, 0, 2, 11, 7, 5, 3),
    (11, 8, 12, 0, 5, 2, 15, 13, 10, 14, 3, 6, 7, 1, 9, 4),
    (7, 9, 3, 1, 13, 12, 11, 14, 2, 6, 5, 10, 4, 0, 15, 8),
    (9, 0, 5, 7, 2, 4, 10, 15, 14, 1, 11, 12, 6, 8, 3, 13),
    (2, 12, 6, 10, 0, 11, 8, 3, 4, 13, 7, 5, 15, 14, 1, 9),
    (12, 5, 1, 15, 14, 13, 4, 10, 0, 7, 6, 3, 9, 2, 8, 11),
    (13, 11, 7, 14, 12, 1, 3, 9, 5, 0, 15, 4, 8, 6, 2, 10),
    (6, 15, 14, 9, 11, 3, 0, 8, 12, 2, 13, 7, 1, 4, 10, 5),
    (10, 2, 8, 4, 7, 6, 1, 5, 15, 11, 9, 14, 3, 12, 13, 0),
)


def _rotr(x: int, n: int) -> int:
    return ((x >> n) | (x << (64 - n))) & MASK64


def blake2b_f(rounds: int, h: List[int], m: List[int], t: List[int],
              final: bool) -> List[int]:
    """One F compression: h[8], m[16], t[2] are u64 words; returns h'[8]."""
    v = list(h) + list(IV)
    v[12] ^= t[0]
    v[13] ^= t[1]
    if final:
        v[14] ^= MASK64

    for r in range(rounds):
        s = SIGMA[r % 10]

        def g(a, b, c, d, x, y):
            v[a] = (v[a] + v[b] + x) & MASK64
            v[d] = _rotr(v[d] ^ v[a], 32)
            v[c] = (v[c] + v[d]) & MASK64
            v[b] = _rotr(v[b] ^ v[c], 24)
            v[a] = (v[a] + v[b] + y) & MASK64
            v[d] = _rotr(v[d] ^ v[a], 16)
            v[c] = (v[c] + v[d]) & MASK64
            v[b] = _rotr(v[b] ^ v[c], 63)

        g(0, 4, 8, 12, m[s[0]], m[s[1]])
        g(1, 5, 9, 13, m[s[2]], m[s[3]])
        g(2, 6, 10, 14, m[s[4]], m[s[5]])
        g(3, 7, 11, 15, m[s[6]], m[s[7]])
        g(0, 5, 10, 15, m[s[8]], m[s[9]])
        g(1, 6, 11, 12, m[s[10]], m[s[11]])
        g(2, 7, 8, 13, m[s[12]], m[s[13]])
        g(3, 4, 9, 14, m[s[14]], m[s[15]])

    return [h[i] ^ v[i] ^ v[i + 8] for i in range(8)]


def blake2f_precompile(data: bytes) -> Optional[bytes]:
    """EIP-152 byte-level semantics: 213-byte input
    rounds(4 BE) || h(64 LE) || m(128 LE) || t(16 LE) || final(1);
    returns 64 bytes, or None = precompile failure (bad length / flag)."""
    if len(data) != 213:
        return None
    final = data[212]
    if final not in (0, 1):
        return None
    rounds = int.from_bytes(data[0:4], "big")
    h = [int.from_bytes(data[4 + 8 * i:12 + 8 * i], "little") for i in range(8)]
    m = [int.from_bytes(data[68 + 8 * i:76 + 8 * i], "little") for i in range(16)]
    t = [int.from_bytes(data[196 + 8 * i:204 + 8 * i], "little") for i in range(2)]
    out = blake2b_f(rounds, h, m, t, final == 1)
    return b"".join(x.to_bytes(8, "little") for x in out)


def blake2b_hash(data: bytes, digest_size: int = 64) -> bytes:
    """Full BLAKE2b built on :func:`blake2b_f` — the test oracle path
    (compared against ``hashlib.blake2b``), not used by the precompile."""
    h = list(IV)
    h[0] ^= 0x01010000 ^ digest_size  # param block: digest len, fanout=depth=1
    blocks = [data[i:i + 128] for i in range(0, len(data), 128)] or [b""]
    t = 0
    for blk in blocks[:-1]:
        t += 128
        m = [int.from_bytes(blk[8 * i:8 * i + 8], "little") for i in range(16)]
        h = blake2b_f(12, h, m, [t & MASK64, t >> 64], False)
    last = blocks[-1]
    t += len(last)
    last = last.ljust(128, b"\x00")
    m = [int.from_bytes(last[8 * i:8 * i + 8], "little") for i in range(16)]
    h = blake2b_f(12, h, m, [t & MASK64, t >> 64], True)
    return b"".join(x.to_bytes(8, "little") for x in h)[:digest_size]
