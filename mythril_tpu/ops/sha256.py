"""SHA-256 — batched JAX kernel (+ host reference via hashlib).

The reference's sha256 precompile delegates to Python's ``hashlib`` (C)
(``mythril/laser/ethereum/natives.py`` ⚠unv, SURVEY.md §2 "Precompiles").
Here the compression function is pure u32 bitwise ops over the whole
frontier: hashing P lanes of up-to-N bytes is one fused XLA op sequence —
the same design as :mod:`.keccak`.
"""

from __future__ import annotations

import hashlib

import numpy as np
import jax.numpy as jnp
from jax import lax

U32 = jnp.uint32
U8 = jnp.uint8
I32 = jnp.int32

_K = np.array([
    0x428a2f98, 0x71374491, 0xb5c0fbcf, 0xe9b5dba5, 0x3956c25b, 0x59f111f1,
    0x923f82a4, 0xab1c5ed5, 0xd807aa98, 0x12835b01, 0x243185be, 0x550c7dc3,
    0x72be5d74, 0x80deb1fe, 0x9bdc06a7, 0xc19bf174, 0xe49b69c1, 0xefbe4786,
    0x0fc19dc6, 0x240ca1cc, 0x2de92c6f, 0x4a7484aa, 0x5cb0a9dc, 0x76f988da,
    0x983e5152, 0xa831c66d, 0xb00327c8, 0xbf597fc7, 0xc6e00bf3, 0xd5a79147,
    0x06ca6351, 0x14292967, 0x27b70a85, 0x2e1b2138, 0x4d2c6dfc, 0x53380d13,
    0x650a7354, 0x766a0abb, 0x81c2c92e, 0x92722c85, 0xa2bfe8a1, 0xa81a664b,
    0xc24b8b70, 0xc76c51a3, 0xd192e819, 0xd6990624, 0xf40e3585, 0x106aa070,
    0x19a4c116, 0x1e376c08, 0x2748774c, 0x34b0bcb5, 0x391c0cb3, 0x4ed8aa4a,
    0x5b9cca4f, 0x682e6ff3, 0x748f82ee, 0x78a5636f, 0x84c87814, 0x8cc70208,
    0x90befffa, 0xa4506ceb, 0xbef9a3f7, 0xc67178f2,
], dtype=np.uint32)

_H0 = np.array([
    0x6a09e667, 0xbb67ae85, 0x3c6ef372, 0xa54ff53a,
    0x510e527f, 0x9b05688c, 0x1f83d9ab, 0x5be0cd19,
], dtype=np.uint32)


def _rotr(x, n: int):
    return (x >> U32(n)) | (x << U32(32 - n))


def sha256_device(data: jnp.ndarray, ln: jnp.ndarray) -> jnp.ndarray:
    """SHA-256 of per-lane byte buffers.

    ``data`` u8[P, N] (bytes past ``ln`` ignored), ``ln`` i32[P] logical
    lengths (0 <= ln <= N). Returns the digest as u256 limbs u32[P, 8]
    (little-endian limb order, the frontier word format).
    """
    P, N = data.shape
    max_blocks = (N + 9 + 63) // 64
    M = max_blocks * 64

    # build padded message: msg || 0x80 || 0* || len64_be
    k = jnp.arange(M)
    d = jnp.where(k[None, :] < N,
                  jnp.pad(data, ((0, 0), (0, M - N))), 0).astype(U32)
    in_msg = k[None, :] < ln[:, None]
    is_pad1 = k[None, :] == ln[:, None]
    msg = jnp.where(in_msg, d, jnp.where(is_pad1, 0x80, 0))
    # bit length goes in the last 8 bytes of the lane's final block
    n_blocks = (ln + 9 + 63) // 64
    total = n_blocks * 64
    bitlen = (ln.astype(jnp.uint64) * 8)
    len_pos = k[None, :] - (total - 8)[:, None]  # 0..7 inside the length field
    len_byte = jnp.where(
        (len_pos >= 0) & (len_pos < 8),
        (bitlen[:, None] >> ((7 - jnp.maximum(len_pos, 0)).astype(jnp.uint64) * 8))
        & 0xFF,
        0,
    ).astype(U32)
    msg = jnp.where((len_pos >= 0) & (len_pos < 8), len_byte, msg)

    # bytes -> big-endian u32 words [P, M/4]
    w32 = (
        (msg[:, 0::4] << U32(24)) | (msg[:, 1::4] << U32(16))
        | (msg[:, 2::4] << U32(8)) | msg[:, 3::4]
    ).astype(U32)

    K = jnp.asarray(_K)
    state0 = jnp.broadcast_to(jnp.asarray(_H0), (P, 8)).astype(U32)

    def block(b, state):
        w = jnp.zeros((P, 64), dtype=U32)
        w = w.at[:, :16].set(lax.dynamic_slice_in_dim(w32, b * 16, 16, axis=1))

        def sched(t, w):
            s0 = _rotr(w[:, t - 15], 7) ^ _rotr(w[:, t - 15], 18) ^ (w[:, t - 15] >> U32(3))
            s1 = _rotr(w[:, t - 2], 17) ^ _rotr(w[:, t - 2], 19) ^ (w[:, t - 2] >> U32(10))
            return w.at[:, t].set(w[:, t - 16] + s0 + w[:, t - 7] + s1)

        for t in range(16, 64):
            w = sched(t, w)

        def rnd(t, hv):
            a, bb, c, dd, e, f, g, h = [hv[:, i] for i in range(8)]
            S1 = _rotr(e, 6) ^ _rotr(e, 11) ^ _rotr(e, 25)
            ch = (e & f) ^ (~e & g)
            t1 = h + S1 + ch + K[t] + w[:, t]
            S0 = _rotr(a, 2) ^ _rotr(a, 13) ^ _rotr(a, 22)
            mj = (a & bb) ^ (a & c) ^ (bb & c)
            t2 = S0 + mj
            return jnp.stack([t1 + t2, a, bb, c, dd + t1, e, f, g], axis=1)

        hv = lax.fori_loop(0, 64, rnd, state)
        new_state = state + hv
        # blocks past the lane's message leave the state untouched
        live = (b < n_blocks)[:, None]
        return jnp.where(live, new_state, state)

    state = lax.fori_loop(0, max_blocks, block, state0)

    # big-endian digest words -> u256 limbs (little-endian limb order:
    # limb 0 = least-significant 32 bits = last digest word)
    return state[:, ::-1]


def sha256_host(data: bytes) -> bytes:
    return hashlib.sha256(data).digest()
