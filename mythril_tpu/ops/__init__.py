"""Device-side op kernels: u256 limb arithmetic, keccak, opcode semantics."""

from mythril_tpu.ops import u256  # noqa: F401
