"""Batched host dispatch for the 'slow' concrete precompiles.

0x3 ripemd160 (hashlib), 0x6/0x7/0x8 alt_bn128 (ops/bn128), 0x9 blake2f
(ops/blake2). The symbolic engine reaches this through one
``jax.pure_callback`` gated behind ``lax.cond`` — only supersteps where
some lane concretely calls one of these pay the host round-trip
(reference: every native is a host-side C call too,
``mythril/laser/ethereum/natives.py`` ⚠unv).

Contract per lane: returns (out_bytes[64], out_len, ok). ``ok=False``
means the PRECOMPILE CALL FAILS (the EVM pushes 0 and returndata is
empty) — distinct from ecrecover's "invalid signature" which succeeds
with empty output.
"""

from __future__ import annotations

import hashlib
from typing import Tuple

import numpy as np

from . import bn128
from .blake2 import blake2f_precompile

# blake2f rounds fence: gas charges 1/round so real traffic is small;
# an attacker-size rounds word (2^32) would stall the host callback for
# minutes. Above the cap the ENGINE routes the call to the sound havoc
# leaf instead of calling here (engine._apply_precompiles).
BLAKE2F_MAX_ROUNDS = 1 << 16


def _ripemd160(data: bytes) -> bytes:
    h = hashlib.new("ripemd160", data).digest()
    return b"\x00" * 12 + h  # left-padded to 32 bytes, as the precompile


def natives_batch(inp: np.ndarray, pid: np.ndarray, a_len: np.ndarray,
                  mask: np.ndarray) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """inp u8[P, INW], pid i32[P], a_len i64[P], mask bool[P] ->
    (out u8[P, 64], out_len i32[P], ok bool[P])."""
    P_lanes = inp.shape[0]
    out = np.zeros((P_lanes, 64), dtype=np.uint8)
    out_len = np.zeros(P_lanes, dtype=np.int32)
    ok = np.zeros(P_lanes, dtype=bool)
    for i in np.where(mask)[0]:
        data = bytes(inp[i, : int(a_len[i])])
        p = int(pid[i])
        res = None
        if p == 3:
            res = _ripemd160(data)
        elif p == 6:
            res = bn128.ecadd(data)
        elif p == 7:
            res = bn128.ecmul(data)
        elif p == 8:
            res = bn128.ecpairing(data)
        elif p == 9:
            res = blake2f_precompile(data)
        if res is not None:
            out[i, : len(res)] = np.frombuffer(res, dtype=np.uint8)
            out_len[i] = len(res)
            ok[i] = True
    return out, out_len, ok
