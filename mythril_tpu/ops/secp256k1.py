"""Concrete ECRECOVER (precompile 0x1) on the host.

The reference recovers via libsecp256k1 (``coincurve``; SURVEY §2.2) —
unavailable here, so this is a self-contained affine-arithmetic
implementation of public-key recovery over secp256k1. It serves the
CONCRETE path only (witness replay through signature-gated code, and the
engine's concrete-input precompile dispatch via a host callback); the
symbolic case stays an uninterpreted ECRECOVER leaf, as in the reference.

Performance note: ~1 ms/recovery in pure Python. That is fine for its
role — signature checks are rare in fixtures and each concrete (hash, v,
r, s) tuple is memoized.
"""

from __future__ import annotations

import functools
from typing import Optional

from .keccak import keccak256_host

# secp256k1 domain parameters
P = 2**256 - 2**32 - 977
N = 0xFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFFEBAAEDCE6AF48A03BBFD25E8CD0364141
GX = 0x79BE667EF9DCBBAC55A06295CE870B07029BFCDB2DCE28D959F2815B16F81798
GY = 0x483ADA7726A3C4655DA4FBFC0E1108A8FD17B448A68554199C47D08FFB10D4B8


def _add(p1, p2):
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if (y1 + y2) % P == 0:
            return None
        m = (3 * x1 * x1) * pow(2 * y1, -1, P) % P
    else:
        m = (y2 - y1) * pow(x2 - x1, -1, P) % P
    x3 = (m * m - x1 - x2) % P
    return x3, (m * (x1 - x3) - y1) % P


def _mul(point, k: int):
    out = None
    while k:
        if k & 1:
            out = _add(out, point)
        point = _add(point, point)
        k >>= 1
    return out


@functools.lru_cache(maxsize=4096)
def ecrecover(msg_hash: int, v: int, r: int, s: int) -> Optional[int]:
    """Recovered 160-bit address, or None for an invalid signature
    (the precompile then returns empty output)."""
    if v not in (27, 28):
        return None
    if not (1 <= r < N and 1 <= s < N):
        return None
    # lift x = r onto the curve (the r + N branch needs x < P; r-values
    # that large do not occur for v in {27, 28})
    x = r
    y_sq = (pow(x, 3, P) + 7) % P
    y = pow(y_sq, (P + 1) // 4, P)
    if (y * y) % P != y_sq:
        return None  # x not on the curve
    if y % 2 != (v - 27):
        y = P - y
    e = msg_hash % (1 << 256)
    r_inv = pow(r, -1, N)
    # Q = r^-1 * (s*R - e*G)
    q = _mul((x, y), (s * r_inv) % N)
    ge = _mul((GX, GY), (N - e % N) * r_inv % N)
    q = _add(q, ge)
    if q is None:
        return None
    pub = q[0].to_bytes(32, "big") + q[1].to_bytes(32, "big")
    return int.from_bytes(keccak256_host(pub)[12:], "big")


def ecrecover_batch(inputs):
    """inputs: iterable of 128-byte precompile payloads
    (hash32 ++ v32 ++ r32 ++ s32). Returns a list of Optional[int]."""
    out = []
    for blob in inputs:
        b = bytes(blob).ljust(128, b"\x00")[:128]
        h = int.from_bytes(b[0:32], "big")
        v = int.from_bytes(b[32:64], "big")
        r = int.from_bytes(b[64:96], "big")
        s = int.from_bytes(b[96:128], "big")
        out.append(ecrecover(h, v, r, s))
    return out
