"""alt_bn128 (BN254) curve arithmetic + optimal-ate pairing, pure Python.

Host-side backend for precompiles 0x6 (ECADD), 0x7 (ECMUL), 0x8
(ECPAIRING) — the reference computes these natives via py_ecc
(``mythril/laser/ethereum/natives.py`` ⚠unv, SURVEY.md §2.2). These are
rare, concrete-input-only paths reached through a gated host callback,
so plain Python bigints are the right tool (no device kernel).

The tower is the standard one for BN254:

    Fp2  = Fp[u]  / (u^2 + 1)
    Fp12 = Fp[w]  / (w^12 - 18 w^6 + 82),   u = w^6 - 9

G2 lives on the sextic twist y^2 = x^3 + 3/(9+u) over Fp2; the pairing
untwists G2 into Fp12 (x·w^2, y·w^3) and runs a double-and-add Miller
loop over the ate loop count, then one final exponentiation
(p^12 - 1)/n. Validity rules follow EIP-196/197: coordinates must be
canonical field elements, points must be on their curve, and G2 inputs
must additionally lie in the order-n subgroup.
"""

from __future__ import annotations

from typing import Optional, Sequence, Tuple

P = 21888242871839275222246405745257275088696311157297823662689037894645226208583
CURVE_ORDER = 21888242871839275222246405745257275088548364400416034343698204186575808495617
B1 = 3  # G1: y^2 = x^3 + 3
ATE_LOOP_COUNT = 29793968203157093288  # 6t + 2 for the BN parameter t


def _finv(a: int) -> int:
    return pow(a, P - 2, P)


class Fq:
    """Canonical Fp element — the generic point ops rely on canonical
    equality (infinity detection), which raw ints don't give."""

    __slots__ = ("n",)

    def __init__(self, n: int):
        self.n = n % P

    def __add__(self, o):
        return Fq(self.n + (o.n if isinstance(o, Fq) else o))

    __radd__ = __add__

    def __sub__(self, o):
        return Fq(self.n - (o.n if isinstance(o, Fq) else o))

    def __rsub__(self, o):
        return Fq((o.n if isinstance(o, Fq) else o) - self.n)

    def __neg__(self):
        return Fq(-self.n)

    def __mul__(self, o):
        return Fq(self.n * (o.n if isinstance(o, Fq) else o))

    __rmul__ = __mul__

    def inv(self) -> "Fq":
        return Fq(_finv(self.n))

    def is_zero(self) -> bool:
        return self.n == 0

    def __eq__(self, o) -> bool:
        return isinstance(o, Fq) and self.n == o.n

    def __hash__(self):
        return hash(self.n)

    def __repr__(self):
        return f"Fq({self.n})"


# ---------------------------------------------------------------------------
# Fp2
# ---------------------------------------------------------------------------


class Fq2:
    """c0 + c1·u with u^2 = -1."""

    __slots__ = ("c0", "c1")

    def __init__(self, c0: int, c1: int):
        self.c0 = c0 % P
        self.c1 = c1 % P

    def __add__(self, o: "Fq2") -> "Fq2":
        return Fq2(self.c0 + o.c0, self.c1 + o.c1)

    def __sub__(self, o: "Fq2") -> "Fq2":
        return Fq2(self.c0 - o.c0, self.c1 - o.c1)

    def __neg__(self) -> "Fq2":
        return Fq2(-self.c0, -self.c1)

    def __mul__(self, o):
        if isinstance(o, int):
            return Fq2(self.c0 * o, self.c1 * o)
        return Fq2(self.c0 * o.c0 - self.c1 * o.c1,
                   self.c0 * o.c1 + self.c1 * o.c0)

    __rmul__ = __mul__

    def inv(self) -> "Fq2":
        den = _finv(self.c0 * self.c0 + self.c1 * self.c1)
        return Fq2(self.c0 * den, -self.c1 * den)

    def __eq__(self, o) -> bool:
        return isinstance(o, Fq2) and self.c0 == o.c0 and self.c1 == o.c1

    def __hash__(self):
        return hash((self.c0, self.c1))

    def is_zero(self) -> bool:
        return self.c0 == 0 and self.c1 == 0

    def __repr__(self):
        return f"Fq2({self.c0}, {self.c1})"


FQ2_ONE = Fq2(1, 0)
FQ2_ZERO = Fq2(0, 0)
B2 = Fq2(3, 0) * Fq2(9, 1).inv()  # twist constant 3/(9+u)

# ---------------------------------------------------------------------------
# Fp12 as a dense degree-11 polynomial in w, reduced by w^12 = 18 w^6 - 82
# ---------------------------------------------------------------------------


class Fq12:
    __slots__ = ("c",)

    MOD = (82, 0, 0, 0, 0, 0, -18, 0, 0, 0, 0, 0)  # w^12 + MOD·(1..w^11) = 0

    def __init__(self, coeffs: Sequence[int]):
        assert len(coeffs) == 12
        self.c = tuple(x % P for x in coeffs)

    @staticmethod
    def one() -> "Fq12":
        return Fq12((1,) + (0,) * 11)

    def __add__(self, o: "Fq12") -> "Fq12":
        return Fq12(tuple(a + b for a, b in zip(self.c, o.c)))

    def __sub__(self, o: "Fq12") -> "Fq12":
        return Fq12(tuple(a - b for a, b in zip(self.c, o.c)))

    def __neg__(self) -> "Fq12":
        return Fq12(tuple(-a for a in self.c))

    def __mul__(self, o):
        if isinstance(o, int):
            return Fq12(tuple(a * o for a in self.c))
        raw = [0] * 23
        for i, a in enumerate(self.c):
            if a:
                for j, b in enumerate(o.c):
                    raw[i + j] += a * b
        # reduce degrees 22..12 via w^12 = 18 w^6 - 82
        for d in range(22, 11, -1):
            v = raw[d]
            if v:
                raw[d] = 0
                raw[d - 6] += 18 * v
                raw[d - 12] -= 82 * v
        return Fq12(raw[:12])

    __rmul__ = __mul__

    def inv(self) -> "Fq12":
        # extended Euclid over Fp[w] against the modulus polynomial
        lm, hm = [1] + [0] * 12, [0] * 13
        low = list(self.c) + [0]
        high = [m % P for m in self.MOD] + [1]

        def deg(p):
            for d in range(len(p) - 1, -1, -1):
                if p[d]:
                    return d
            return 0

        while deg(low):
            # r = high / low  (polynomial long division, leading terms)
            r = [0] * 13
            rem = list(high)
            dl = deg(low)
            inv_lead = _finv(low[dl])
            for d in range(deg(rem) - dl, -1, -1):
                q = rem[d + dl] * inv_lead % P
                r[d] = q
                if q:
                    for i in range(dl + 1):
                        rem[d + i] = (rem[d + i] - q * low[i]) % P
            nm, new = list(hm), list(high)
            for i in range(13):
                if lm[i] or low[i]:
                    for j in range(13 - i):
                        if r[j]:
                            nm[i + j] -= lm[i] * r[j]
                            new[i + j] -= low[i] * r[j]
            nm = [x % P for x in nm]
            new = [x % P for x in new]
            lm, low, hm, high = nm, new, lm, low
        scale = _finv(low[0])
        return Fq12(tuple(x * scale % P for x in lm[:12]))

    def pow(self, e: int) -> "Fq12":
        r, b = Fq12.one(), self
        while e:
            if e & 1:
                r = r * b
            b = b * b
            e >>= 1
        return r

    def __eq__(self, o) -> bool:
        return isinstance(o, Fq12) and self.c == o.c

    def __hash__(self):
        return hash(self.c)

    def is_zero(self) -> bool:
        return all(a == 0 for a in self.c)


# ---------------------------------------------------------------------------
# Generic affine short-Weierstrass ops (field-agnostic; None = infinity)
# ---------------------------------------------------------------------------

Pt = Optional[Tuple[object, object]]


def _pt_double(pt: Pt) -> Pt:
    if pt is None:
        return None
    x, y = pt
    if _is_zero(y):
        return None
    m = _fdiv(3 * (x * x), 2 * y)
    nx = m * m - x - x
    return (nx, m * (x - nx) - y)


def _pt_add(p1: Pt, p2: Pt) -> Pt:
    if p1 is None:
        return p2
    if p2 is None:
        return p1
    x1, y1 = p1
    x2, y2 = p2
    if x1 == x2:
        if y1 == y2:
            return _pt_double(p1)
        return None
    m = _fdiv(y2 - y1, x2 - x1)
    nx = m * m - x1 - x2
    return (nx, m * (x1 - nx) - y1)


def _pt_mul(pt: Pt, n: int) -> Pt:
    r: Pt = None
    while n:
        if n & 1:
            r = _pt_add(r, pt)
        pt = _pt_double(pt)
        n >>= 1
    return r


def _pt_neg(pt: Pt) -> Pt:
    if pt is None:
        return None
    x, y = pt
    return (x, -y)


def _is_zero(v) -> bool:
    return v.is_zero()


def _fdiv(a, b):
    return a * b.inv()


# G1/G2 generators (standard BN254 constants, as in EIP-197)
G1 = (Fq(1), Fq(2))
G2 = (
    Fq2(10857046999023057135944570762232829481370756359578518086990519993285655852781,
        11559732032986387107991004021392285783925812861821192530917403151452391805634),
    Fq2(8495653923123431417604973247489272438418190587263600148770280649306958101930,
        4082367875863433681332203403145435568316851327593401208105741076214120093531),
)


def on_curve_g1(pt: Pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - (x * x * x + B1)).is_zero()


def on_curve_g2(pt: Pt) -> bool:
    if pt is None:
        return True
    x, y = pt
    return (y * y - (x * x * x + B2)).is_zero()


def in_g2_subgroup(pt: Pt) -> bool:
    return _pt_mul(pt, CURVE_ORDER) is None


# ---------------------------------------------------------------------------
# Pairing
# ---------------------------------------------------------------------------


def _twist(pt: Pt) -> Pt:
    """Map a twist point (Fq2 coords) onto the Fp12 curve y^2 = x^3 + 3."""
    if pt is None:
        return None
    x, y = pt
    # change of basis u -> w^6 - 9, then scale x by w^2, y by w^3
    xc = [(x.c0 - 9 * x.c1) % P, x.c1]
    yc = [(y.c0 - 9 * y.c1) % P, y.c1]
    nx = [0] * 12
    ny = [0] * 12
    nx[2], nx[8] = xc[0], xc[1]   # (xc0 + xc1 w^6) * w^2
    ny[3], ny[9] = yc[0], yc[1]   # (yc0 + yc1 w^6) * w^3
    return (Fq12(nx), Fq12(ny))


def _embed_g1(pt: Pt) -> Pt:
    if pt is None:
        return None
    x, y = pt
    return (Fq12((x.n,) + (0,) * 11), Fq12((y.n,) + (0,) * 11))


def _linefunc(p1, p2, t):
    """Evaluate the line through p1,p2 (Fp12 points) at t."""
    x1, y1 = p1
    x2, y2 = p2
    xt, yt = t
    if x1 != x2:
        m = _fdiv(y2 - y1, x2 - x1)
        return m * (xt - x1) - (yt - y1)
    if y1 == y2:
        m = _fdiv(3 * (x1 * x1), 2 * y1)
        return m * (xt - x1) - (yt - y1)
    return xt - x1


def _frob(pt: Pt) -> Pt:
    x, y = pt
    return (x.pow(P), y.pow(P))


def miller_loop(q_twisted: Pt, p_g1: Pt) -> Fq12:
    """Miller loop WITHOUT the final exponentiation (so a product of
    pairings pays the big exponentiation once)."""
    if q_twisted is None or p_g1 is None:
        return Fq12.one()
    q = _twist(q_twisted)
    pt = _embed_g1(p_g1)
    r = q
    f = Fq12.one()
    for i in range(ATE_LOOP_COUNT.bit_length() - 2, -1, -1):
        f = f * f * _linefunc(r, r, pt)
        r = _pt_double(r)
        if ATE_LOOP_COUNT & (1 << i):
            f = f * _linefunc(r, q, pt)
            r = _pt_add(r, q)
    q1 = _frob(q)
    nq2 = _pt_neg(_frob(q1))
    f = f * _linefunc(r, q1, pt)
    r = _pt_add(r, q1)
    f = f * _linefunc(r, nq2, pt)
    return f


_FINAL_EXP = (P ** 12 - 1) // CURVE_ORDER


def pairing_check(pairs) -> bool:
    """prod e(P_i, Q_i) == 1 for [(g1_pt, g2_pt), ...]; callers must have
    validated the points (on curve, G2 subgroup)."""
    acc = Fq12.one()
    for g1_pt, g2_pt in pairs:
        acc = acc * miller_loop(g2_pt, g1_pt)
    return acc.pow(_FINAL_EXP) == Fq12.one()


def pairing(g1_pt: Pt, g2_pt: Pt) -> Fq12:
    """Full single pairing (tests/bilinearity checks)."""
    return miller_loop(g2_pt, g1_pt).pow(_FINAL_EXP)


# ---------------------------------------------------------------------------
# Precompile entry points (EIP-196/197 semantics, byte-level)
# ---------------------------------------------------------------------------


def _read_g1(data: bytes) -> Tuple[Pt, bool]:
    """64 bytes -> (point, ok). (0,0) is infinity; out-of-range or
    off-curve coordinates are invalid."""
    x = int.from_bytes(data[0:32], "big")
    y = int.from_bytes(data[32:64], "big")
    if x >= P or y >= P:
        return None, False
    if x == 0 and y == 0:
        return None, True
    pt = (Fq(x), Fq(y))
    return pt, on_curve_g1(pt)


def _read_g2(data: bytes) -> Tuple[Pt, bool]:
    """128 bytes -> (point, ok). EIP-197 encodes Fp2 as (imag, real)."""
    xi = int.from_bytes(data[0:32], "big")
    xr = int.from_bytes(data[32:64], "big")
    yi = int.from_bytes(data[64:96], "big")
    yr = int.from_bytes(data[96:128], "big")
    if max(xi, xr, yi, yr) >= P:
        return None, False
    if xi == xr == yi == yr == 0:
        return None, True
    pt = (Fq2(xr, xi), Fq2(yr, yi))
    if not on_curve_g2(pt):
        return None, False
    return pt, in_g2_subgroup(pt)


def _write_g1(pt: Pt) -> bytes:
    if pt is None:
        return b"\x00" * 64
    x, y = pt
    return x.n.to_bytes(32, "big") + y.n.to_bytes(32, "big")


def ecadd(data: bytes) -> Optional[bytes]:
    """0x06: add two G1 points; None = precompile failure."""
    data = data[:128].ljust(128, b"\x00")
    a, ok_a = _read_g1(data[0:64])
    b, ok_b = _read_g1(data[64:128])
    if not (ok_a and ok_b):
        return None
    return _write_g1(_pt_add(a, b))


def ecmul(data: bytes) -> Optional[bytes]:
    """0x07: scalar-multiply a G1 point; None = failure."""
    data = data[:96].ljust(96, b"\x00")
    pt, ok = _read_g1(data[0:64])
    if not ok:
        return None
    n = int.from_bytes(data[64:96], "big")
    return _write_g1(_pt_mul(pt, n))


def ecpairing(data: bytes) -> Optional[bytes]:
    """0x08: pairing product check; None = failure (bad length/points)."""
    if len(data) % 192 != 0:
        return None
    pairs = []
    for k in range(0, len(data), 192):
        g1_pt, ok1 = _read_g1(data[k:k + 64])
        g2_pt, ok2 = _read_g2(data[k + 64:k + 192])
        if not (ok1 and ok2):
            return None
        if g1_pt is not None and g2_pt is not None:
            pairs.append((g1_pt, g2_pt))
    ok = pairing_check(pairs) if pairs else True
    return int(ok).to_bytes(32, "big")
