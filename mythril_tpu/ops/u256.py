"""256-bit word arithmetic as 8 x u32 limb vectors (little-endian limbs).

This is the foundation of the TPU interpreter: every EVM word is a
``uint32[..., 8]`` array (limb 0 = least significant 32 bits). All ops are
elementwise over leading batch dims, so the whole frontier's stacks are
transformed in one XLA op sequence — this is the idiomatic replacement for
the reference's per-object Python bigints in
``mythril/laser/ethereum/instructions.py`` (⚠unv, see SURVEY.md §2).

Intermediates use u64 (requires jax_enable_x64; enabled in package
__init__). A Pallas kernel can later replace the hot paths (mul/div) —
the API here is the stable surface.

Conventions:
- all binary ops broadcast over leading dims;
- EVM semantics: DIV/MOD by zero -> 0; SDIV overflow (-2^255 / -1) -> -2^255;
- shifts with amount >= 256 -> 0 (SAR -> sign fill).
"""

from __future__ import annotations

import numpy as np
import jax
import jax.numpy as jnp

NLIMBS = 8
LIMB_BITS = 32
WORD_BITS = 256
_U32 = jnp.uint32
_U64 = jnp.uint64
# np scalar, NOT jnp.uint64(...): a module-level jnp array commits to a
# device and therefore INITIALIZES the backend at import time — which
# hangs every light CLI command (version/function-to-hash/campaign-merge)
# on a wedged TPU runtime. numpy scalars promote identically inside jit.
_MASK32 = np.uint64(0xFFFFFFFF)

# ---------------------------------------------------------------------------
# Host-side conversions (numpy, not traced)
# ---------------------------------------------------------------------------


def from_int(x: int) -> np.ndarray:
    """Python int (mod 2^256) -> u32[8] limbs, little-endian."""
    x &= (1 << 256) - 1
    return np.array([(x >> (32 * i)) & 0xFFFFFFFF for i in range(NLIMBS)], dtype=np.uint32)


def from_ints(xs) -> np.ndarray:
    return np.stack([from_int(int(x)) for x in xs], axis=0)


def to_int(limbs) -> int:
    """u32[8] limbs -> Python int."""
    limbs = np.asarray(limbs, dtype=np.uint64)
    out = 0
    for i in range(NLIMBS):
        out |= int(limbs[..., i]) << (32 * i)
    return out


def to_ints(arr) -> list:
    arr = np.asarray(arr)
    flat = arr.reshape(-1, NLIMBS)
    return [to_int(row) for row in flat]


def from_bytes(b: bytes) -> np.ndarray:
    """Big-endian byte string (<=32 bytes) -> u32[8]."""
    return from_int(int.from_bytes(b, "big"))


def to_bytes(limbs) -> bytes:
    return to_int(limbs).to_bytes(32, "big")


# ---------------------------------------------------------------------------
# Constructors (traced)
# ---------------------------------------------------------------------------


def zeros(shape=()) -> jax.Array:
    return jnp.zeros(tuple(shape) + (NLIMBS,), dtype=_U32)


def ones_word(shape=()) -> jax.Array:
    """The value 1."""
    z = np.zeros(tuple(shape) + (NLIMBS,), dtype=np.uint32)
    z[..., 0] = 1
    return jnp.asarray(z)


def full_like_int(ref: jax.Array, value: int) -> jax.Array:
    """Broadcast a Python constant to ref's batch shape."""
    w = jnp.asarray(from_int(value))
    return jnp.broadcast_to(w, ref.shape[:-1] + (NLIMBS,))


def from_u64_scalar(x) -> jax.Array:
    """Traced u64 scalar (batched) -> u256 limbs."""
    x = x.astype(_U64)
    lo = (x & _MASK32).astype(_U32)
    hi = (x >> 32).astype(_U32)
    rest = jnp.zeros(x.shape + (NLIMBS - 2,), dtype=_U32)
    return jnp.concatenate([lo[..., None], hi[..., None], rest], axis=-1)


def to_u64_saturating(a: jax.Array):
    """Low 64 bits, saturating to 2^64-1 if any higher limb set (for gas/len)."""
    lo = a[..., 0].astype(_U64) | (a[..., 1].astype(_U64) << 32)
    overflow = jnp.any(a[..., 2:] != 0, axis=-1)
    return jnp.where(overflow, jnp.uint64(0xFFFFFFFFFFFFFFFF), lo)


def to_u32_saturating(a: jax.Array):
    """Low 32 bits, saturating if any higher limb set (for pc/offsets)."""
    overflow = jnp.any(a[..., 1:] != 0, axis=-1)
    return jnp.where(overflow, jnp.uint32(0xFFFFFFFF), a[..., 0])


# ---------------------------------------------------------------------------
# Bitwise
# ---------------------------------------------------------------------------


def bit_and(a, b):
    return a & b


def bit_or(a, b):
    return a | b


def bit_xor(a, b):
    return a ^ b


def bit_not(a):
    return ~a


# ---------------------------------------------------------------------------
# Predicates
# ---------------------------------------------------------------------------


def is_zero(a) -> jax.Array:
    return jnp.all(a == 0, axis=-1)


def eq(a, b) -> jax.Array:
    return jnp.all(a == b, axis=-1)


def msb(a) -> jax.Array:
    """Sign bit (bit 255) as bool."""
    return (a[..., NLIMBS - 1] >> 31) != 0


is_neg = msb


def lt(a, b) -> jax.Array:
    """Unsigned a < b."""
    # Compare from the most significant limb down, vectorized:
    # a < b iff at the highest differing limb, a's limb < b's limb.
    neq = a != b  # [..., 8]
    a_lt = a < b  # [..., 8]
    # index of most significant differing limb; if none differ -> equal -> False
    # Trick: scan from high to low using a "decided" mask.
    decided = jnp.zeros(a.shape[:-1], dtype=bool)
    result = jnp.zeros(a.shape[:-1], dtype=bool)
    for i in range(NLIMBS - 1, -1, -1):
        take = (~decided) & neq[..., i]
        result = jnp.where(take, a_lt[..., i], result)
        decided = decided | neq[..., i]
    return result


def gt(a, b) -> jax.Array:
    return lt(b, a)


def gte(a, b) -> jax.Array:
    return ~lt(a, b)


def lte(a, b) -> jax.Array:
    return ~lt(b, a)


def slt(a, b) -> jax.Array:
    """Signed a < b (two's complement)."""
    sa, sb = msb(a), msb(b)
    # different signs: a<b iff a is negative
    return jnp.where(sa != sb, sa, lt(a, b))


def sgt(a, b) -> jax.Array:
    return slt(b, a)


def bool_to_word(p) -> jax.Array:
    """bool[...] -> u256 0/1."""
    out = jnp.zeros(p.shape + (NLIMBS,), dtype=_U32)
    return out.at[..., 0].set(p.astype(_U32))


# ---------------------------------------------------------------------------
# Add / Sub / Neg
# ---------------------------------------------------------------------------


def add(a, b):
    return add_carry(a, b)[0]


def add_carry(a, b):
    """(a + b mod 2^256, carry_out bool)."""
    a, b = jnp.broadcast_arrays(a, b)
    out = []
    c = jnp.zeros(a.shape[:-1], dtype=_U64)
    for i in range(NLIMBS):
        s = a[..., i].astype(_U64) + b[..., i].astype(_U64) + c
        out.append((s & _MASK32).astype(_U32))
        c = s >> 32
    return jnp.stack(out, axis=-1), c != 0


def sub(a, b):
    return sub_borrow(a, b)[0]


def sub_borrow(a, b):
    """(a - b mod 2^256, borrow_out bool). borrow_out == (a < b)."""
    a, b = jnp.broadcast_arrays(a, b)
    out = []
    borrow = jnp.zeros(a.shape[:-1], dtype=_U64)
    for i in range(NLIMBS):
        d = a[..., i].astype(_U64) - b[..., i].astype(_U64) - borrow
        out.append((d & _MASK32).astype(_U32))
        borrow = (d >> 63) & 1  # underflow wraps in u64; top bit set iff borrow
    return jnp.stack(out, axis=-1), borrow != 0


def neg(a):
    """Two's complement negation."""
    return add(~a, ones_word(a.shape[:-1]))


def abs_signed(a):
    """(|a| as unsigned, was_negative)."""
    n = msb(a)
    return jnp.where(n[..., None], neg(a), a), n


# ---------------------------------------------------------------------------
# Mul
# ---------------------------------------------------------------------------


def mul(a, b):
    """Low 256 bits of a*b (schoolbook, u64 accumulation)."""
    a, b = jnp.broadcast_arrays(a, b)
    a64 = a.astype(_U64)
    b64 = b.astype(_U64)
    res = [jnp.zeros(a.shape[:-1], dtype=_U64) for _ in range(NLIMBS)]
    for i in range(NLIMBS):
        carry = jnp.zeros(a.shape[:-1], dtype=_U64)
        for j in range(NLIMBS - i):
            t = res[i + j] + a64[..., i] * b64[..., j] + carry
            res[i + j] = t & _MASK32
            carry = t >> 32
    return jnp.stack([r.astype(_U32) for r in res], axis=-1)


def mul_wide(a, b):
    """Full 512-bit product as u32[..., 16] limbs."""
    a, b = jnp.broadcast_arrays(a, b)
    a64 = a.astype(_U64)
    b64 = b.astype(_U64)
    n_out = 2 * NLIMBS
    res = [jnp.zeros(a.shape[:-1], dtype=_U64) for _ in range(n_out)]
    for i in range(NLIMBS):
        carry = jnp.zeros(a.shape[:-1], dtype=_U64)
        for j in range(NLIMBS):
            t = res[i + j] + a64[..., i] * b64[..., j] + carry
            res[i + j] = t & _MASK32
            carry = t >> 32
        res[i + NLIMBS] = res[i + NLIMBS] + carry
    # res[i+8] accumulated raw carries; normalize the top half
    carry = jnp.zeros(a.shape[:-1], dtype=_U64)
    for k in range(NLIMBS, n_out):
        t = res[k] + carry
        res[k] = t & _MASK32
        carry = t >> 32
    return jnp.stack([r.astype(_U32) for r in res], axis=-1)


def mul_overflows(a, b):
    """True iff a*b >= 2^256 (used by integer-overflow detection)."""
    wide = mul_wide(a, b)
    return jnp.any(wide[..., NLIMBS:] != 0, axis=-1)


# ---------------------------------------------------------------------------
# Division (binary long division) and modulo
# ---------------------------------------------------------------------------


def divmod_u(a, b):
    """Unsigned (a // b, a % b); division by zero -> (0, 0) per EVM.

    Invariant r < b; r<<1 can still overflow past 2^256 when b > 2^255, so
    the shifted-out bit is tracked: if set, the true r' >= 2^256 > b and the
    subtraction must occur (the wrapped subtraction then yields the right
    residue since 0 <= 2r+bit-b < 2^256).
    """
    a, b = jnp.broadcast_arrays(a, b)
    batch = a.shape[:-1]

    def body_safe(k, state):
        q, r = state
        i = 255 - k
        limb = i // LIMB_BITS
        shift = i % LIMB_BITS
        bit = (jnp.take(a, limb, axis=-1) >> _U32(shift)) & _U32(1)
        overflow = (r[..., NLIMBS - 1] >> 31) != 0  # bit shifted out of 2^256
        hi_bits = r >> 31
        r2 = r << 1
        r2 = r2.at[..., 1:].set(r2[..., 1:] | hi_bits[..., :-1])
        r2 = r2.at[..., 0].set(r2[..., 0] | bit)
        ge = gte(r2, b) | overflow
        r2 = jnp.where(ge[..., None], sub(r2, b), r2)
        qbit = ge.astype(_U32) << _U32(shift)
        qvec = jnp.where(jnp.arange(NLIMBS) == limb, qbit[..., None], _U32(0))
        q = q | qvec
        return (q, r2)

    q0 = jnp.zeros(batch + (NLIMBS,), dtype=_U32)
    r0 = jnp.zeros(batch + (NLIMBS,), dtype=_U32)
    q, r = jax.lax.fori_loop(0, 256, body_safe, (q0, r0))
    bz = is_zero(b)[..., None]
    return jnp.where(bz, 0, q).astype(_U32), jnp.where(bz, 0, r).astype(_U32)


def div(a, b):
    return divmod_u(a, b)[0]


def mod(a, b):
    return divmod_u(a, b)[1]


def sdiv(a, b):
    aa, na = abs_signed(a)
    ab, nb = abs_signed(b)
    q = div(aa, ab)
    flip = na != nb
    q = jnp.where(flip[..., None], neg(q), q)
    # EVM: -2^255 / -1 wraps to -2^255 — this falls out of two's complement
    return jnp.where(is_zero(b)[..., None], 0, q).astype(_U32)


def smod(a, b):
    aa, na = abs_signed(a)
    ab, _ = abs_signed(b)
    r = mod(aa, ab)
    r = jnp.where(na[..., None], neg(r), r)
    return jnp.where(is_zero(b)[..., None], 0, r).astype(_U32)


def _mod_wide(wide, m):
    """(u32[...,16] value) mod (u256 m); m==0 -> 0. 512-step long division."""
    batch = wide.shape[:-1]
    n_in = wide.shape[-1]
    nbits = n_in * LIMB_BITS

    def body(k, r):
        i = nbits - 1 - k
        limb = i // LIMB_BITS
        shift = i % LIMB_BITS
        bit = (jnp.take(wide, limb, axis=-1) >> _U32(shift)) & _U32(1)
        overflow = (r[..., NLIMBS - 1] >> 31) != 0
        hi_bits = r >> 31
        r2 = r << 1
        r2 = r2.at[..., 1:].set(r2[..., 1:] | hi_bits[..., :-1])
        r2 = r2.at[..., 0].set(r2[..., 0] | bit)
        ge = gte(r2, m) | overflow
        r2 = jnp.where(ge[..., None], sub(r2, m), r2)
        return r2

    r0 = jnp.zeros(batch + (NLIMBS,), dtype=_U32)
    r = jax.lax.fori_loop(0, nbits, body, r0)
    return jnp.where(is_zero(m)[..., None], 0, r).astype(_U32)


def addmod(a, b, m):
    """(a + b) mod m over a 9-limb (288-bit) intermediate."""
    s, carry = add_carry(a, b)
    wide = jnp.concatenate([s, carry.astype(_U32)[..., None]], axis=-1)
    return _mod_wide(wide, m)


def mulmod(a, b, m):
    return _mod_wide(mul_wide(a, b), m)


def modexp(base, e, m):
    """base ** e mod m (m == 0 -> 0), square-and-multiply MSB-first.

    Serves the 0x05 MODEXP precompile for <= 32-byte operands. Cost: 256
    iterations of two long-division mulmods — expensive, but the caller
    gates it behind ``lax.cond`` so frontiers without MODEXP never pay."""
    base, e, m = jnp.broadcast_arrays(base, e, m)
    batch = base.shape[:-1]

    def body(k, acc):
        i = 255 - k
        limb = i // LIMB_BITS
        shift = i % LIMB_BITS
        bit = ((jnp.take(e, limb, axis=-1) >> _U32(shift)) & _U32(1)) != 0
        acc = mulmod(acc, acc, m)
        acc = jnp.where(bit[..., None], mulmod(acc, base, m), acc)
        return acc

    one = jnp.broadcast_to(jnp.asarray(from_int(1)), batch + (NLIMBS,)).astype(_U32)
    r = jax.lax.fori_loop(0, 256, body, one)
    return jnp.where(is_zero(m)[..., None], 0, r).astype(_U32)


# ---------------------------------------------------------------------------
# Exp / SignExtend / Byte / Shifts
# ---------------------------------------------------------------------------


def exp(base, e):
    """base ** e mod 2^256, square-and-multiply (MSB-first)."""
    base, e = jnp.broadcast_arrays(base, e)
    batch = base.shape[:-1]

    def body(k, acc):
        i = 255 - k
        limb = i // LIMB_BITS
        shift = i % LIMB_BITS
        bit = ((jnp.take(e, limb, axis=-1) >> _U32(shift)) & _U32(1)) != 0
        acc = mul(acc, acc)
        acc = jnp.where(bit[..., None], mul(acc, base), acc)
        return acc

    one = jnp.broadcast_to(jnp.asarray(from_int(1)), batch + (NLIMBS,))
    return jax.lax.fori_loop(0, 256, body, one)


def signextend(k, x):
    """EVM SIGNEXTEND: extend sign from byte k (0 = least significant byte).

    If k >= 31, x is unchanged.
    """
    k32 = to_u32_saturating(k).astype(jnp.int64)  # saturates; >=31 -> no-op
    t = 8 * k32 + 7  # sign bit position
    bit_index = jnp.clip(t, 0, 255)
    limb = (bit_index // LIMB_BITS).astype(jnp.int32)
    shift = (bit_index % LIMB_BITS).astype(_U32)
    sign = ((jnp.take_along_axis(x, limb[..., None], axis=-1)[..., 0] >> shift) & 1) != 0
    # mask of bits <= t (keep), bits above t get the sign
    limb_ids = jnp.arange(NLIMBS)
    # per-limb: bits kept in this limb
    bits_into_limb = bit_index[..., None] - limb_ids * LIMB_BITS  # how many bits-1 kept
    keep_all = bits_into_limb >= (LIMB_BITS - 1)
    keep_none = bits_into_limb < 0
    partial_shift = jnp.clip(bits_into_limb + 1, 0, LIMB_BITS - 1).astype(_U32)
    partial_mask = ((_U32(1) << partial_shift) - _U32(1)).astype(_U32)
    keep_mask = jnp.where(keep_all, _U32(0xFFFFFFFF), jnp.where(keep_none, _U32(0), partial_mask))
    ext = jnp.where(sign[..., None], ~keep_mask, _U32(0))
    res = (x & keep_mask) | ext
    noop = k32 >= 31
    return jnp.where(noop[..., None], x, res).astype(_U32)


def byte_op(i, x):
    """EVM BYTE: i-th byte of x counting from the most significant; >=32 -> 0."""
    i32 = to_u32_saturating(i).astype(jnp.int64)
    oob = i32 >= 32
    j = jnp.clip(31 - i32, 0, 31)  # byte index from LSB
    limb = (j // 4).astype(jnp.int32)
    shift = ((j % 4) * 8).astype(_U32)
    b = (jnp.take_along_axis(x, limb[..., None], axis=-1)[..., 0] >> shift) & _U32(0xFF)
    b = jnp.where(oob, _U32(0), b)
    out = jnp.zeros(x.shape, dtype=_U32)
    return out.at[..., 0].set(b)


def _shift_limbs_left(a, limb_shift):
    """Shift left by limb_shift whole limbs (traced int32)."""
    idx = jnp.arange(NLIMBS) - limb_shift[..., None]
    valid = idx >= 0
    gathered = jnp.take_along_axis(a, jnp.clip(idx, 0, NLIMBS - 1).astype(jnp.int32), axis=-1)
    return jnp.where(valid, gathered, _U32(0))


def _shift_limbs_right(a, limb_shift):
    idx = jnp.arange(NLIMBS) + limb_shift[..., None]
    valid = idx < NLIMBS
    gathered = jnp.take_along_axis(a, jnp.clip(idx, 0, NLIMBS - 1).astype(jnp.int32), axis=-1)
    return jnp.where(valid, gathered, _U32(0))


def shl(s, a):
    """a << s (EVM operand order: shift amount first)."""
    s64 = to_u64_saturating(s)
    big = s64 >= 256
    sh = jnp.clip(s64, 0, 255).astype(jnp.int64)
    ls = (sh // LIMB_BITS).astype(jnp.int32)
    bs = (sh % LIMB_BITS).astype(_U32)
    moved = _shift_limbs_left(a, ls)
    lo = moved << bs[..., None]
    # bits carried from the next-lower limb; when bs != 0, (32-bs) is in [1,31]
    carry = jnp.where(bs[..., None] == 0, _U32(0),
                      (moved >> ((_U32(32) - bs) % _U32(32))[..., None]))
    out = lo
    out = out.at[..., 1:].set(out[..., 1:] | carry[..., :-1])
    return jnp.where(big[..., None], _U32(0), out)


def shr(s, a):
    """Logical a >> s."""
    s64 = to_u64_saturating(s)
    big = s64 >= 256
    sh = jnp.clip(s64, 0, 255).astype(jnp.int64)
    ls = (sh // LIMB_BITS).astype(jnp.int32)
    bs = (sh % LIMB_BITS).astype(_U32)
    moved = _shift_limbs_right(a, ls)
    hi = moved >> bs[..., None]
    carry = jnp.where(bs[..., None] == 0, _U32(0),
                      (moved << ((_U32(32) - bs) % _U32(32))[..., None]))
    out = hi
    out = out.at[..., :-1].set(out[..., :-1] | carry[..., 1:])
    return jnp.where(big[..., None], _U32(0), out)


def sar(s, a):
    """Arithmetic a >> s."""
    neg_in = msb(a)
    logical = shr(s, a)
    s64 = to_u64_saturating(s)
    big = s64 >= 256
    sh = jnp.clip(s64, 0, 255).astype(jnp.int64)
    # fill mask: top `sh` bits set
    # build via shl of all-ones by (256 - sh)
    all_ones = jnp.broadcast_to(_U32(0xFFFFFFFF), a.shape)
    fill_amount = 256 - sh
    fa = from_u64_scalar(fill_amount.astype(_U64))
    fill = shl(fa, all_ones)
    filled = logical | fill
    res = jnp.where(neg_in[..., None], filled, logical)
    neg_big = jnp.broadcast_to(_U32(0xFFFFFFFF), a.shape)
    res_big = jnp.where(neg_in[..., None], neg_big, _U32(0))
    return jnp.where(big[..., None], res_big, res).astype(_U32)
