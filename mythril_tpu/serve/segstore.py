"""Compacted, content-addressed verdict segments under the results
store (docs/serving.md "Verdict segments & edge replicas").

The live store (``serve/store.py``) keeps one loose JSON file per
``(bytecode_hash, config_hash)`` key — the proven first-wins
multi-replica write contract. That is correct for N writers but wrong
for millions-of-keys READ scale: every ``count()`` is an ``os.listdir``
and every cold read is a dentry lookup in a directory with a million
entries. This module is the read-scale half: a background compactor
folds settled loose files into immutable SEGMENT files (sorted
key→verdict records, per-record and whole-file sha256), and a
generation-numbered ``MANIFEST.json`` — committed via the repo-wide
checkpoint contract (``save_json_checkpoint``: tmp + fsync + rotate +
rename, content sha over the state) — names the segment set that IS
generation N.

Crash-safety argument (the PR 2 checkpoint contract, applied to a
multi-file structure):

* A segment file is content-addressed (``seg-<sha256(payload)[:32]>``)
  and created with ``exclusive_write`` — it either exists complete or
  not at all, and a re-run of the same compaction writes the same
  bytes to the same name (EEXIST == already durable, not a conflict).
* The manifest is the ONLY commit point. Loose files are unlinked
  strictly AFTER the new manifest generation is durable, so a SIGKILL
  at any instant leaves every verdict readable from either its loose
  file or the previous manifest generation. An orphan segment from a
  crashed compaction is harmless (unreferenced, GC'd by the next
  successful commit).
* A torn/bit-rotted segment is DETECTED by checksum on read,
  quarantined to ``*.corrupt`` with a counter tick, and dropped from
  the in-memory index — its keys become misses that fall back to
  re-analysis. Never a wrong answer.
* A half-written manifest falls back to the rotated ``.1`` previous
  generation (``load_json_checkpoint_resilient``); because manifests
  only ever carry segments forward, generation N−1 references a subset
  of the segments on disk — no key vanishes, the newest fold is simply
  re-done from the still-present loose files.

Readers (``SegmentStore``) hold a bounded in-memory key→segment index
(one dict entry per key, no verdict bodies) plus a small LRU of parsed
segments, and refresh by stat()ing the manifest — a ``--store-only``
edge replica polls this to pick up generations committed by the
analysis fleet.
"""

from __future__ import annotations

import hashlib
import json
import os
import re
import threading
import time
from collections import OrderedDict
from typing import Callable, Dict, List, Optional, Tuple

from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..utils.checkpoint import (
    CheckpointCorrupt, exclusive_write, load_json_checkpoint_resilient,
    save_json_checkpoint)

#: manifest state schema (inside the checkpoint wrapper)
MANIFEST_SCHEMA = 1
#: segment payload schema
SEGMENT_SCHEMA = 1
MANIFEST_NAME = "MANIFEST.json"
SEGMENT_DIR = "segments"

#: loose verdict files eligible for compaction: <bch>.<cfh>.json
LOOSE_RE = re.compile(r"^[0-9a-f]{32}\.[0-9a-f]{16}\.json$")
_SEG_RE = re.compile(r"^seg-([0-9a-f]{32})\.json$")

#: test hook: SIGKILL-equivalent (``os._exit``) at a named point of the
#: compaction protocol, driven by the chaos cells and the kill-mid-
#: compaction tests. Points: after-segment (segment durable, manifest
#: not), after-manifest (manifest durable, loose files not yet
#: unlinked), before-unlink (same, from the store's fold loop).
_KILL_ENV = "MYTHRIL_SEGSTORE_KILL"


def _maybe_kill(point: str) -> None:
    if os.environ.get(_KILL_ENV) == point:
        os._exit(9)


def record_sha(key: str, verdict: Dict) -> str:
    """Per-record integrity hash: the key and the canonical verdict
    JSON together, so a record can't be silently re-homed onto another
    key inside an otherwise-valid segment."""
    blob = key + "\n" + json.dumps(verdict, sort_keys=True, default=str)
    return hashlib.sha256(blob.encode()).hexdigest()


def _segment_payload(records: List[Dict]) -> bytes:
    return json.dumps({"schema": SEGMENT_SCHEMA, "records": records},
                      sort_keys=True).encode()


class SegmentStore:
    """Read/compact view over ``<store>/segments/`` + ``MANIFEST.json``.

    Thread-safe (one RLock); safe to point at a read-only snapshot of
    a data dir (``__init__`` creates nothing — only ``compact_commit``
    makes directories). ``validate`` is the owning store's per-doc
    check (schema / bytecode_hash / config_hash), injected so this
    layer stays ignorant of the verdict schema."""

    def __init__(self, path: str,
                 validate: Optional[Callable[[str, Dict], bool]] = None,
                 cache_segments: int = 4):
        self.path = path
        self.seg_dir = os.path.join(path, SEGMENT_DIR)
        self.manifest_path = os.path.join(path, MANIFEST_NAME)
        self.validate = validate
        self.generation = 0
        self._index: Dict[str, str] = {}      # key -> segment filename
        self._segments: List[Dict] = []       # manifest descriptors
        self._cache: "OrderedDict[str, Dict[str, Tuple[str, Dict]]]" = \
            OrderedDict()                      # seg fn -> key -> (sha, doc)
        self._cache_segments = max(1, int(cache_segments))
        self._manifest_sig: Optional[Tuple[int, int]] = None
        self._lock = threading.RLock()
        self.refresh(force=True)

    # -- manifest / index --------------------------------------------

    def _stat_sig(self) -> Optional[Tuple[int, int]]:
        try:
            st = os.stat(self.manifest_path)
        except OSError:
            return None
        return (st.st_mtime_ns, st.st_size)

    def refresh(self, force: bool = False) -> bool:
        """Re-read the manifest if it changed on disk (cheap stat
        compare unless ``force``). Returns whether a new generation was
        installed. A corrupt manifest NEVER drops the in-memory index:
        the resilient loader falls back to the rotated previous
        generation, and if both copies are torn we keep serving the
        generation already loaded — keys fall back to loose files or
        re-analysis, never to a 500."""
        with self._lock:
            sig = self._stat_sig()
            if not force and sig == self._manifest_sig:
                return False
            try:
                state, _src = load_json_checkpoint_resilient(
                    self.manifest_path)
            except CheckpointCorrupt:
                obs_metrics.REGISTRY.counter(
                    "serve_store_manifest_corrupt_total",
                    help="manifest loads where every copy was torn "
                         "(previous in-memory generation kept)").inc()
                self._manifest_sig = sig
                return False
            self._manifest_sig = sig
            if state is None:
                return False
            if (not isinstance(state, dict)
                    or int(state.get("schema", 0)) > MANIFEST_SCHEMA):
                return False
            segments = []
            index: Dict[str, str] = {}
            for seg in state.get("segments") or []:
                fn = seg.get("file", "")
                if (not _SEG_RE.match(fn) or not os.path.exists(
                        os.path.join(self.seg_dir, fn))):
                    # quarantined/missing segment: its keys fall back
                    # to loose files or re-analysis
                    continue
                segments.append(seg)
                for k in seg.get("keys") or []:
                    index[k] = fn
            self._segments = segments
            self._index = index
            self.generation = int(state.get("generation", 0))
            self._cache.clear()
            reg = obs_metrics.REGISTRY
            reg.gauge(
                "serve_store_segment_keys",
                help="verdict keys indexed by the newest manifest "
                     "generation").set(len(index))
            reg.gauge(
                "serve_store_generation",
                help="newest loaded manifest generation").set(
                self.generation)
            return True

    # -- reads --------------------------------------------------------

    def _quarantine(self, fn: str, why: str) -> None:
        """One torn/invalid segment: move aside as ``.corrupt`` (never
        served again, kept for forensics), tick the counter, drop its
        keys from the index so they fall back to re-analysis."""
        p = os.path.join(self.seg_dir, fn)
        try:
            os.replace(p, p + ".corrupt")
        except OSError:
            pass
        obs_metrics.REGISTRY.counter(
            "serve_store_segment_corrupt_total",
            help="segments quarantined .corrupt on checksum/schema "
                 "failure (their keys fall back to re-analysis)").inc()
        obs_trace.event("segment_quarantined", file=fn, why=why)
        with self._lock:
            self._cache.pop(fn, None)
            self._segments = [s for s in self._segments
                              if s.get("file") != fn]
            self._index = {k: v for k, v in self._index.items()
                           if v != fn}

    def _load_segment(self, fn: str) -> Optional[Dict[str, Tuple[str, Dict]]]:
        with self._lock:
            cached = self._cache.get(fn)
            if cached is not None:
                self._cache.move_to_end(fn)
                return cached
        p = os.path.join(self.seg_dir, fn)
        try:
            with open(p, "rb") as fh:
                raw = fh.read()
        except OSError:
            self._quarantine(fn, "unreadable")
            return None
        m = _SEG_RE.match(fn)
        if (m is None or
                hashlib.sha256(raw).hexdigest()[:32] != m.group(1)):
            self._quarantine(fn, "checksum")
            return None
        try:
            payload = json.loads(raw)
        except ValueError:
            self._quarantine(fn, "json")
            return None
        if (not isinstance(payload, dict)
                or int(payload.get("schema", 0)) > SEGMENT_SCHEMA):
            self._quarantine(fn, "schema")
            return None
        parsed: Dict[str, Tuple[str, Dict]] = {}
        for rec in payload.get("records") or []:
            if not isinstance(rec, dict):
                continue
            parsed[str(rec.get("key"))] = (
                str(rec.get("sha256")), rec.get("verdict"))
        with self._lock:
            self._cache[fn] = parsed
            self._cache.move_to_end(fn)
            while len(self._cache) > self._cache_segments:
                self._cache.popitem(last=False)
        return parsed

    def get(self, bch: str, cfh: str) -> Optional[Dict]:
        """The compacted verdict for one key, or None. Any integrity
        failure — torn file, content-hash mismatch, per-record sha
        mismatch, validator rejection — quarantines the segment and
        returns None (a counted miss upstream)."""
        key = f"{bch}.{cfh}"
        with self._lock:
            fn = self._index.get(key)
        if fn is None:
            return None
        parsed = self._load_segment(fn)
        if parsed is None:
            return None
        entry = parsed.get(key)
        if entry is None:
            self._quarantine(fn, "missing-key")
            return None
        sha, doc = entry
        if (not isinstance(doc, dict) or sha != record_sha(key, doc)
                or (self.validate is not None
                    and not self.validate(key, doc))):
            self._quarantine(fn, "record")
            return None
        return doc

    def key_count(self) -> int:
        with self._lock:
            return len(self._index)

    def keys(self) -> List[str]:
        with self._lock:
            return list(self._index)

    def has(self, key: str) -> bool:
        with self._lock:
            return key in self._index

    # -- compaction ---------------------------------------------------

    def compact_commit(self, records: Dict[str, Dict]) -> Dict:
        """Fold ``records`` (key → verdict doc) into one new immutable
        segment and commit manifest generation N+1 that carries every
        prior segment forward plus the new one. Returns stats. The
        caller (``ResultsStore.compact``) unlinks the folded loose
        files only AFTER this returns — the manifest commit is the
        point of no return."""
        with self._lock:
            if not records:
                return {"generation": self.generation, "folded": 0,
                        "segments": len(self._segments)}
            recs = [{"key": k, "sha256": record_sha(k, v), "verdict": v}
                    for k, v in sorted(records.items())]
            payload = _segment_payload(recs)
            fn = f"seg-{hashlib.sha256(payload).hexdigest()[:32]}.json"
            os.makedirs(self.seg_dir, exist_ok=True)
            # content-addressed: EEXIST means the identical segment is
            # already durable (a re-run after a crash), not a conflict
            exclusive_write(os.path.join(self.seg_dir, fn), payload)
            _maybe_kill("after-segment")
            desc = {"file": fn,
                    "sha256": hashlib.sha256(payload).hexdigest(),
                    "count": len(recs),
                    "keys": [r["key"] for r in recs]}
            segments = [s for s in self._segments
                        if s.get("file") != fn] + [desc]
            state = {"schema": MANIFEST_SCHEMA,
                     "generation": self.generation + 1,
                     "t": round(time.time(), 3),
                     "segments": segments}
            save_json_checkpoint(self.manifest_path, state)
            _maybe_kill("after-manifest")
            # install in memory without a disk round-trip
            self._segments = segments
            for r in recs:
                self._index[r["key"]] = fn
            self.generation = state["generation"]
            self._manifest_sig = self._stat_sig()
            self._cache.clear()
            self._gc_orphans()
            reg = obs_metrics.REGISTRY
            reg.counter(
                "serve_store_compactions_total",
                help="manifest generations committed by the "
                     "compactor").inc()
            reg.gauge("serve_store_segment_keys",
                      help="verdict keys indexed by the newest "
                           "manifest generation").set(len(self._index))
            reg.gauge("serve_store_generation",
                      help="newest loaded manifest generation").set(
                self.generation)
            obs_trace.event("store_compaction", generation=self.generation,
                   folded=len(recs), segments=len(segments))
            return {"generation": self.generation, "folded": len(recs),
                    "segments": len(segments)}

    def _gc_orphans(self) -> None:
        """Remove segment files no manifest generation references —
        leftovers of compactions that died between segment write and
        manifest commit. Only called right after a successful commit,
        so anything unreferenced by the NEW manifest is garbage (the
        rotated previous manifest references a subset of it)."""
        live = {s.get("file") for s in self._segments}
        try:
            names = os.listdir(self.seg_dir)
        except OSError:
            return
        for fn in names:
            if _SEG_RE.match(fn) and fn not in live:
                try:
                    os.unlink(os.path.join(self.seg_dir, fn))
                except OSError:
                    pass

    # -- offline verification (tools/store_admin.py) ------------------

    def verify(self) -> Dict:
        """Read-only integrity sweep for the admin tool: load the
        manifest WITHOUT installing it, checksum every referenced
        segment (whole-file and per-record), and report — no
        quarantining, no counters; safe on a live store."""
        report: Dict = {"generation": 0, "segments": 0, "records": 0,
                        "corrupt": []}
        try:
            state, _src = load_json_checkpoint_resilient(
                self.manifest_path)
        except CheckpointCorrupt:
            report["corrupt"].append(
                {"file": MANIFEST_NAME, "why": "all copies torn"})
            return report
        if not isinstance(state, dict):
            return report
        report["generation"] = int(state.get("generation", 0))
        for seg in state.get("segments") or []:
            fn = str(seg.get("file", ""))
            p = os.path.join(self.seg_dir, fn)
            m = _SEG_RE.match(fn)
            try:
                with open(p, "rb") as fh:
                    raw = fh.read()
            except OSError:
                report["corrupt"].append({"file": fn, "why": "missing"})
                continue
            if (m is None
                    or hashlib.sha256(raw).hexdigest()[:32] != m.group(1)
                    or hashlib.sha256(raw).hexdigest()
                    != seg.get("sha256")):
                report["corrupt"].append({"file": fn, "why": "checksum"})
                continue
            try:
                payload = json.loads(raw)
            except ValueError:
                report["corrupt"].append({"file": fn, "why": "json"})
                continue
            report["segments"] += 1
            for rec in payload.get("records") or []:
                key, doc = str(rec.get("key")), rec.get("verdict")
                if rec.get("sha256") != record_sha(key, doc):
                    report["corrupt"].append(
                        {"file": fn, "key": key, "why": "record"})
                else:
                    report["records"] += 1
        return report


__all__ = ["MANIFEST_NAME", "MANIFEST_SCHEMA", "SEGMENT_DIR",
           "SEGMENT_SCHEMA", "LOOSE_RE", "SegmentStore", "record_sha"]
