"""Always-on analysis service (docs/serving.md, ROADMAP open item #3).

Everything below this package is batch-shaped: one ``analyze`` /
campaign per process, recompiling the superstep on entry. Serving heavy
traffic needs the opposite — a RESIDENT daemon that amortizes the two
big per-request costs across every request it will ever take:

- XLA compilation: the scheduler keeps one :class:`CorpusCampaign` per
  engine shape class alive for the process lifetime, so request N>0 of
  a shape replays ``sym_run``'s cached executables instead of paying a
  cold compile (``serve_warm_compile_hits_total``);
- solver + lane work on duplicate bytecode: mainnet is dominated by
  proxy/clone bytecode, so the admission queue dedupes by
  ``(bytecode_hash, config_hash)`` BEFORE anything reaches a lane —
  against the persistent results store and against in-flight work
  (``serve_dedupe_hits_total``).

The pieces:

- :mod:`serve.store` — durable per-contract verdict store (the first
  slice of ROADMAP's cross-campaign verdict store), first-wins across
  N replica daemons sharing one ``--data-dir``;
- :mod:`serve.queue` — admission queue: dedupe, per-tenant priority +
  deadline ordering, deadline eviction, bounded depth, per-tenant
  token-bucket quotas + SLO accounting, and the load-shedding ladder
  (overload degrades low-priority submissions to store-only answers);
- :mod:`serve.scheduler` — drains the queue into resident campaigns
  (or a fleet FEED ledger fronting remote workers, docs/fleet.md);
- :mod:`serve.http` — thin stdlib HTTP surface (`POST /v1/submit`,
  long-poll / chunked-streaming `GET /v1/result/<id>`, `/healthz`,
  Prometheus `/metrics`);
- :mod:`serve.follower` — chain-head follower (`serve --follow URI`):
  ingests newly deployed contracts as a standing lowest-priority
  tenant, shed first under overload;
- :mod:`serve.segstore` — compacted verdict segments: a background
  compactor folds settled loose verdicts into immutable,
  content-addressed segment files behind a generation-numbered
  manifest, so read cost stops scaling with ``os.listdir``;
- :mod:`serve.backfill` — whole-chain backfill (`serve --backfill
  URI`): a backward window walker with a durable two-ended cursor,
  submitting history as the lowest-priority tenant of all;
- :mod:`serve.daemon` — lifecycle: wiring, signal handling, graceful
  drain (SIGTERM finishes the in-flight batch, persists its verdicts,
  rejects new submissions with 503, then exits — a restart serves the
  finished work from the store, exactly once). ``--store-only`` runs
  it as an engine-free edge replica serving dedupe-store answers from
  a manifest snapshot.

Import cost is stdlib-only until the first batch actually runs (the
engine loads lazily inside the scheduler), mirroring the campaign CLI's
backend-free front door.
"""

from .backfill import BACKFILL_PRIORITY, ChainBackfill
from .daemon import AnalysisDaemon, ServeOptions
from .follower import FOLLOWER_PRIORITY, ChainFollower
from .queue import (AdmissionQueue, Entry, QueueClosed, QueueFull,
                    QuotaExceeded, ShedPolicy, Submission, TenantQuota)
from .scheduler import Scheduler, StoreOnlyScheduler
from .segstore import SegmentStore
from .store import ResultsStore, bytecode_hash, config_hash

__all__ = ["AdmissionQueue", "AnalysisDaemon", "BACKFILL_PRIORITY",
           "ChainBackfill", "ChainFollower", "Entry",
           "FOLLOWER_PRIORITY", "QueueClosed", "QueueFull",
           "QuotaExceeded", "ResultsStore", "Scheduler",
           "SegmentStore", "ServeOptions", "ShedPolicy",
           "StoreOnlyScheduler", "Submission", "TenantQuota",
           "bytecode_hash", "config_hash"]
