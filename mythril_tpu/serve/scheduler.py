"""Scheduler: drains the admission queue into resident campaigns.

The loop that makes the daemon WARM (docs/serving.md): it pops
same-config batches from the queue and runs them through one
long-lived :class:`CorpusCampaign` per effective config — the full PR
1/2 resilience machinery (watchdog / OOM ladder / retry / bisect)
applies per batch, and because all campaigns share ONE warm-shape
registry (keyed by the engine shape class: batch width x lanes x step
budget x tx count), the second batch of any shape replays ``sym_run``'s
process-wide XLA cache instead of recompiling
(``serve_warm_compile_hits_total``). Verdicts are persisted to the
results store as each batch commits, so completed work survives a
daemon kill and is served from dedupe after restart — exactly once.

With a ``fleet_dir`` the scheduler FRONTS a fleet instead of running
locally (docs/fleet.md): admitted batches are appended to a FEED ledger
as self-contained work units (bytecode rides the unit descriptor),
remote ``--fleet-follow`` workers claim/heartbeat/commit them, and this
loop polls committed unit results back into the same entry-resolution
path. Dedupe and queue semantics are identical — the fleet only
replaces WHERE lanes run.

Single scheduler thread; entry resolution goes through the queue's one
condition, so HTTP waiters wake exactly when their results commit.

Replica safety (docs/serving.md "Overload & multi-replica serving"):
N daemons may point at ONE ``--data-dir``. Verdict persistence is
first-wins (``ResultsStore.put`` via ``exclusive_write``), so two
replicas racing the same ``(bytecode, config)`` commit exactly one
file and the loser's copy is dropped (equal by construction) — each
replica still resolves its own waiters from its own batch result. The
in-flight dedupe index is deliberately process-local: cross-replica
dedupe happens through the shared store the moment the first replica
commits. The warm-shape registry is process-local too (warmth is an
XLA-cache property of one process), but since PR 20 it is no longer a
process-local *accident*: with a compile store attached
(mythril_tpu/compilestore.py), warm observations are recorded durably
per (tier, shape-class, config-hash) bucket and replayed by the
daemon's prewarm thread, so a restarted or sibling replica re-acquires
warmth from the shared persistent cache instead of recompiling
(docs/serving.md "Compile artifacts & prewarm").
"""

from __future__ import annotations

import logging
import threading
import time
from typing import Callable, Dict, List, Optional

from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from .queue import AdmissionQueue, Entry
from .store import ResultsStore

log = logging.getLogger(__name__)


def default_campaign_factory(config: Dict):
    """Build the resident engine for one effective config. Loads the
    engine lazily — the daemon stays backend-free until the first
    non-dedupe submission actually needs lanes."""
    from ..config import DEFAULT_LIMITS, TEST_LIMITS
    from ..mythril.campaign import CorpusCampaign
    from ..resilience import FaultInjector

    limits = (TEST_LIMITS if config.get("limits_profile") == "test"
              else DEFAULT_LIMITS)
    spec = None
    if config.get("concrete_storage"):
        from ..symbolic import SymSpec

        spec = SymSpec(storage=False)
    # worker isolation (docs/resilience.md): "auto" means ON under
    # serve — an always-on daemon is exactly where a libtpu segfault
    # must be a worker restart, not daemon death
    isolation = config.get("worker_isolation", "auto")
    if isolation == "auto":
        isolation = "on"
    return CorpusCampaign(
        [],
        batch_size=int(config.get("batch_size", 8)),
        lanes_per_contract=int(config.get("lanes_per_contract", 32)),
        limits=limits,
        spec=spec,
        max_steps=int(config.get("max_steps", 256)),
        transaction_count=int(config.get("transaction_count", 1)),
        modules=config.get("modules"),
        solver_timeout=config.get("solver_timeout"),
        solver_iters=int(config.get("solver_iters", 400)),
        batch_timeout=config.get("batch_timeout"),
        max_batch_retries=int(config.get("max_batch_retries", 1)),
        fault_injector=FaultInjector.from_string(
            config.get("fault_inject")),
        oom_ladder=config.get("oom_ladder"),
        solver_workers=int(config.get("solver_workers", 1)),
        worker_isolation=isolation,
        # backend tiers (docs/resilience.md "Backend tiers"): each
        # resident campaign is placed on whatever tier its worker
        # currently holds — a crash-looping accelerator demotes just
        # this config's capacity class, and the ladder's prober climbs
        # back without a daemon restart
        backend_tiers=config.get("backend_tiers"),
    )


class Scheduler:
    def __init__(self, queue: AdmissionQueue,
                 store: Optional[ResultsStore] = None,
                 batch_size: int = 8,
                 poll: float = 0.25,
                 fleet_dir: Optional[str] = None,
                 campaign_factory: Optional[Callable] = None,
                 compile_store=None):
        self.queue = queue
        self.store = store
        self.batch_size = max(1, int(batch_size))
        self.poll = max(0.02, float(poll))
        self.fleet_dir = fleet_dir
        self.campaign_factory = campaign_factory or default_campaign_factory
        #: fleet compile-artifact store (mythril_tpu/compilestore.py):
        #: when set, every resident campaign records its warm shapes
        #: durably and the daemon's prewarm thread can replay them —
        #: this is what retires the "warmth is process-local" caveat
        #: in the module docstring for RECOVERY (in-process warmth is
        #: still per-process; the registry + shared persistent cache
        #: make re-acquiring it cheap)
        self.compile_store = compile_store
        #: one resident campaign per effective config (cfh); all share
        #: the warm-shape registry below, so config variants of one
        #: ENGINE shape class (same width/lanes/steps/tx, e.g. a
        #: different module list) still count as warm
        self._campaigns: Dict[str, object] = {}
        #: guards campaign get-or-create: the daemon's prewarm thread
        #: may materialize the baseline campaign while the loop creates
        #: one for the first request — exactly one instance must win
        self._camp_lock = threading.Lock()
        self._warm_shapes: Dict[tuple, set] = {}
        self._ledger = None
        #: fleet mode: fed-but-uncommitted units -> their entries
        self._pending: Dict[str, List[Entry]] = {}
        self._stop = threading.Event()     # drain: finish in-flight
        self._abort = threading.Event()    # give up on fleet pending
        self._thread: Optional[threading.Thread] = None
        self.batches_run = 0
        #: set to "<Type>: <msg>" if the loop thread dies of an
        #: unhandled error — /healthz flips to "degraded" and every
        #: pending request fails immediately instead of hanging until
        #: its deadline
        self.crashed: Optional[str] = None
        self._reg = obs_metrics.REGISTRY
        #: commit accounting for the serve_contracts_per_min gauge: one
        #: (monotonic time, n_contracts) sample per committed batch,
        #: pruned to the trailing window. The headline end-to-end rate
        #: (ROADMAP "contracts/min") as production sees it — fed by
        #: verdict commits, not engine internals, so fleet-committed and
        #: resident batches count the same way.
        self._commit_log: List[tuple] = []
        self._commit_window = 300.0

    # --- lifecycle ------------------------------------------------------
    def start(self) -> None:
        if self.fleet_dir is not None:
            from ..fleet import WorkLedger

            self._ledger = WorkLedger(self.fleet_dir)
            self._ledger.ensure_feed()
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-scheduler")
        self._thread.start()

    def request_stop(self) -> None:
        """Begin draining: the in-flight batch (and, fleet mode,
        already-fed units) completes; nothing new is popped. Pair with
        ``queue.close()`` so nothing new is admitted either."""
        self._stop.set()

    def abort(self) -> None:
        """Hard stop: also abandon fed-but-uncommitted fleet units
        (their entries resolve as errors so no waiter hangs)."""
        self._abort.set()
        self._stop.set()

    def join(self, timeout: Optional[float] = None) -> bool:
        if self._thread is None:
            return True
        self._thread.join(timeout)
        return not self._thread.is_alive()

    # --- the loop -------------------------------------------------------
    def _loop(self) -> None:
        """Crash containment around the real loop: if the scheduler
        thread dies of an unhandled error, pending requests used to
        hang until their deadlines — now they FAIL immediately with
        the error string, the queue closes (new submissions get 503),
        and ``/healthz`` reports ``degraded``. The daemon keeps
        serving reads (results, metrics, health) — dying quietly is
        the one thing the loop may not do."""
        try:
            self._loop_inner()
        except Exception as e:  # noqa: BLE001 — the containment seam
            self.crashed = f"{type(e).__name__}: {str(e)[:300]}"
            log.exception("serve scheduler loop died")
            self._reg.counter(
                "serve_scheduler_crashes_total",
                help="unhandled errors that killed the scheduler "
                     "loop").inc()
            obs_trace.event("scheduler_crashed", detail=self.crashed)
            try:
                self.queue.close()
                self.queue.fail_pending(
                    f"scheduler loop died ({self.crashed}); restart "
                    "the daemon — completed contracts will be served "
                    "from the dedupe store")
            except Exception:  # noqa: BLE001 — best-effort unblock
                log.exception("failing pending entries after "
                              "scheduler crash")
            for uid, entries in list(self._pending.items()):
                for en in entries:
                    self.queue.resolve(
                        en, {"status": "error",
                             "error": f"scheduler loop died before "
                                      f"fleet unit {uid} committed "
                                      f"({self.crashed})"})
            self._pending.clear()
        finally:
            for camp in list(self._campaigns.values()):
                close = getattr(camp, "close_worker", None)
                if callable(close):
                    try:
                        close()
                    except Exception:  # noqa: BLE001 — exit path
                        log.exception("closing engine worker")
            if self._ledger is not None:
                # tell --fleet-follow workers the feed is complete so
                # they drain and exit instead of polling a dead
                # daemon's ledger
                try:
                    self._ledger.feed_close()
                except OSError:
                    pass

    def _loop_inner(self) -> None:
        while True:
            if self._ledger is not None:
                self._poll_fleet()
            if self._stop.is_set():
                if self._ledger is None or not self._pending \
                        or self._abort.is_set():
                    break
                # draining a fleet: the fed units are on remote
                # workers; keep polling for their commits
                time.sleep(min(self.poll, 0.1))
                continue
            entries = self.queue.pop_batch(self.batch_size,
                                           timeout=self.poll)
            if not entries:
                continue
            try:
                if self._ledger is not None:
                    self._feed_batch(entries)
                else:
                    self._run_batch(entries)
            except Exception as e:  # noqa: BLE001 — no waiter may hang
                log.exception("serve batch failed")
                self._reg.counter(
                    "serve_batch_errors_total",
                    help="scheduler batches that raised").inc()
                for en in entries:
                    self.queue.resolve(
                        en, {"status": "error",
                             "error": f"{type(e).__name__}: "
                                      f"{str(e)[:200]}"})
        if self._abort.is_set() and self._pending:
            for uid, entries in list(self._pending.items()):
                for en in entries:
                    self.queue.resolve(
                        en, {"status": "error",
                             "error": "daemon exited before fleet "
                                      f"unit {uid} committed"})
            self._pending.clear()

    # --- local (resident-campaign) execution ----------------------------
    def campaign_for_config(self, config: Dict, cfh: str):
        """Get-or-create the resident campaign for one effective
        config. Public so the daemon's background prewarm thread can
        materialize (and warm) the baseline config's campaign before
        the first request ever arrives."""
        with self._camp_lock:
            camp = self._campaigns.get(cfh)
            if camp is None:
                camp = self.campaign_factory(config)
                # one warm-shape registry across every resident
                # campaign: sym_run's XLA cache is process-wide, so
                # warmth is a process property, not a per-config one
                if hasattr(camp, "_warm_shapes"):
                    camp._warm_shapes = self._warm_shapes
                if (self.compile_store is not None
                        and hasattr(camp, "attach_compile_store")):
                    self.compile_store.install_cache()
                    camp.attach_compile_store(self.compile_store,
                                              cfh=cfh)
                self._campaigns[cfh] = camp
            return camp

    def _campaign_for(self, e: Entry):
        return self.campaign_for_config(e.config, e.cfh)

    def _run_batch(self, entries: List[Entry]) -> None:
        camp = self._campaign_for(entries[0])
        warm = False
        if hasattr(camp, "shape_is_warm"):
            warm = bool(camp.shape_is_warm())
        items = [(e.uname, e.code) for e in entries]
        tenants = sorted({e.submission.tenant for e in entries})
        # one batch may serve several requests: the first entry's
        # trace_id leads the scope, the rest ride as link ids — every
        # span below (campaign, worker, solver) indexes under ALL of
        # them, so each request's /v1/trace view is complete
        ids: List[str] = []
        for e in entries:
            if e.trace_id and e.trace_id not in ids:
                ids.append(e.trace_id)
        ids = ids or [obs_trace.new_trace_id()]
        with obs_trace.trace_context(ids[0], link_ids=ids[1:]), \
                obs_trace.span("schedule", n=len(entries),
                               cfh=entries[0].cfh, warm=warm,
                               tenants=tenants):
            out = camp.run_external_batch(items)
        # stage attribution: each entry waited through the whole batch
        # device + host phases, so the batch totals ARE its stage costs
        ph = out.get("phases") if isinstance(out, dict) else None
        if isinstance(ph, dict):
            for e in entries:
                for k in ("device", "host"):
                    v = float(ph.get(k) or 0.0)
                    if v:
                        e.timings[k] = v
        self.batches_run += 1
        self._reg.counter(
            "serve_batches_total",
            help="batches the scheduler ran through resident "
                 "campaigns").inc()
        if warm:
            self._reg.counter(
                "serve_warm_compile_hits_total",
                help="batches that reused an already-compiled engine "
                     "shape class (no XLA recompile)").inc()
        self._bind_results(entries, out.get("issues") or [],
                           out.get("quarantined") or [],
                           batch=out.get("batch"),
                           batch_status=str(out.get("status", "ok")))

    def _note_commits(self, n: int) -> None:
        """Record ``n`` contract verdicts committed now and refresh the
        ``serve_contracts_per_min`` gauge over the trailing window."""
        now = time.monotonic()
        self._commit_log.append((now, n))
        cut = now - self._commit_window
        while self._commit_log and self._commit_log[0][0] < cut:
            self._commit_log.pop(0)
        total = sum(c for _, c in self._commit_log)
        # rate over the observed span (first sample to now), floored at
        # one second so a burst of early commits cannot print as an
        # absurd rate; a single sample reports over the full window
        span = max(1.0, now - self._commit_log[0][0]) \
            if len(self._commit_log) > 1 else self._commit_window
        self._reg.gauge(
            "serve_contracts_per_min",
            help="contract verdicts committed per minute "
                 "(trailing window)").set(round(total * 60.0 / span, 2))

    def _bind_results(self, entries: List[Entry], issues: List[Dict],
                      quarantined: List[Dict],
                      batch=None, batch_status: str = "ok") -> None:
        """Map a batch's engine output back onto its entries (issues
        and quarantine records name the per-entry ``uname``), persist
        fresh verdicts, resolve every entry + its dedupe followers."""
        by_uname: Dict[str, List[Dict]] = {}
        for i in issues:
            by_uname.setdefault(str(i.get("contract")), []).append(i)
        quar = {str(q.get("name")): q for q in quarantined}
        for e in entries:
            if e.uname in quar:
                # a poison contract's verdict is an error, not a
                # finding — do NOT cache it (the quarantine reason may
                # be environmental: a wedged device, an OOM'd rung)
                self.queue.resolve(
                    e, {"status": "quarantined",
                        "error": str(quar[e.uname].get("reason",
                                                       ""))[:300],
                        "issues": [], "batch": batch})
                continue
            my = []
            for i in by_uname.get(e.uname, []):
                i = dict(i)
                i["contract"] = e.name
                my.append(i)
            verdict = {"status": "ok", "issues": my,
                       "batch_status": batch_status}
            if e.trace_id:
                # provenance: the stored verdict names the request
                # trace that computed it (dedupe-served copies keep it)
                verdict["trace_id"] = e.trace_id
            if self.store is not None and self.queue.dedupe:
                t0 = time.monotonic()
                self.store.put(e.bch, e.cfh, verdict)
                e.timings["commit"] = time.monotonic() - t0
                obs_trace.event("verdict_commit", eid=e.eid,
                                bch=e.bch, trace_id=e.trace_id,
                                dur=round(e.timings["commit"], 6))
            res = dict(verdict)
            res["batch"] = batch
            self.queue.resolve(e, res)
        self._note_commits(len(entries))

    # --- fleet-fed execution (docs/fleet.md) ----------------------------
    def _feed_batch(self, entries: List[Entry]) -> None:
        # the unit config carries the requests' trace ids across the
        # ledger: the claiming worker re-enters the same trace scope
        # (campaign._run_unit), so remote spans join these requests
        cfg = dict(entries[0].config)
        ids: List[str] = []
        for e in entries:
            if e.trace_id and e.trace_id not in ids:
                ids.append(e.trace_id)
        if ids:
            cfg["trace"] = {"ids": ids}
        uid = self._ledger.feed_unit(
            [(e.uname, e.code) for e in entries], config=cfg)
        self._pending[uid] = entries
        self._reg.counter(
            "serve_fleet_units_fed_total",
            help="admitted batches appended to the feed ledger").inc()
        self._reg.gauge(
            "serve_fleet_units_pending",
            help="fed units awaiting a worker commit").set(
            len(self._pending))

    def _poll_fleet(self) -> None:
        for uid, entries in list(self._pending.items()):
            rec = self._ledger.result_record(uid)
            if rec is not None:
                self._bind_results(
                    entries, rec.get("issues") or [],
                    rec.get("quarantined") or [],
                    batch=uid,
                    batch_status=";".join(rec.get("batch_status")
                                          or []) or "ok")
                del self._pending[uid]
                self.batches_run += 1
                self._reg.counter("serve_batches_total").inc()
                continue
            if self._ledger.unit_lost(uid):
                for e in entries:
                    self.queue.resolve(
                        e, {"status": "error",
                            "error": f"fleet unit {uid} lost (re-lease "
                                     "cap exhausted)"})
                del self._pending[uid]
        self._reg.gauge("serve_fleet_units_pending").set(
            len(self._pending))

    def pending_fleet_units(self) -> int:
        return len(self._pending)

    # --- worker supervision surface (docs/resilience.md) ----------------
    def degraded_configs(self) -> List[Dict]:
        """Configs whose engine-worker crash-loop breaker is not
        closed — ``/healthz`` reports them so an orchestrator can see
        "this daemon serves, but config X runs pinned to CPU"."""
        out: List[Dict] = []
        for cfh, camp in list(self._campaigns.items()):
            status = getattr(camp, "worker_status", None)
            st = status() if callable(status) else None
            if st is not None and st.get("breaker") != "closed":
                out.append({"config": cfh, "breaker": st["breaker"],
                            "deaths_in_window": st.get(
                                "deaths_in_window"),
                            "restarts": st.get("restarts")})
        return out

    def worker_restarts(self) -> int:
        """Total engine-worker respawns across resident campaigns."""
        n = 0
        for camp in list(self._campaigns.values()):
            status = getattr(camp, "worker_status", None)
            st = status() if callable(status) else None
            if st is not None:
                n += int(st.get("restarts", 0))
        return n

    def warm_counts(self) -> tuple:
        """``(warm shape classes in this process, registry buckets)``
        for the serve heartbeat's ``warm a/b`` token. Prefers a
        resident campaign's tier-scoped count (its registry view is
        filtered to the tier it holds); a campaign-less daemon falls
        back to the store-wide bucket count. ``None`` second element =
        no compile store attached."""
        a = sum(1 for s in self._warm_shapes.values() if s)
        if self.compile_store is None:
            return a, None
        for camp in list(self._campaigns.values()):
            wc = getattr(camp, "warm_counts", None)
            if callable(wc):
                try:
                    return wc()
                except Exception:  # noqa: BLE001 — heartbeat decoration
                    break
        try:
            return a, len(self.compile_store.buckets())
        except Exception:  # noqa: BLE001 — registry scan is best-effort
            return a, 0

    # --- backend-tier surface (docs/resilience.md "Backend tiers") ------
    def tier_status(self) -> List[Dict]:
        """Per-config backend-tier ladder state: which capacity class
        each resident campaign currently holds, plus its demotion /
        re-promotion / flap-damping accounting. ``/healthz`` reports it
        so an orchestrator can see "config X runs demoted on cpu, the
        prober is climbing" without grepping logs."""
        out: List[Dict] = []
        for cfh, camp in list(self._campaigns.items()):
            status = getattr(camp, "tier_status", None)
            st = status() if callable(status) else None
            if st is not None:
                st["config"] = cfh
                out.append(st)
        return out


class StoreOnlyScheduler:
    """The null scheduler behind ``serve --store-only`` (docs/serving.md
    "Verdict segments & edge replicas"): an edge replica has NO engine
    — every answer comes from the dedupe store at admission time, so
    nothing ever reaches a scheduler. This stub keeps the daemon's
    lifecycle and ``/healthz`` surfaces working without importing any
    engine/JAX code (the light-imports contract the store-only mode is
    built on)."""

    batches_run = 0
    crashed = None

    def start(self) -> None:
        pass

    def request_stop(self) -> None:
        pass

    def abort(self) -> None:
        pass

    def join(self, timeout: Optional[float] = None) -> bool:
        return True

    def pending_fleet_units(self) -> int:
        return 0

    def degraded_configs(self) -> List[Dict]:
        return []

    def worker_restarts(self) -> int:
        return 0

    def tier_status(self) -> List[Dict]:
        return []

    def warm_counts(self) -> tuple:
        return 0, None


__all__ = ["Scheduler", "StoreOnlyScheduler", "default_campaign_factory"]
