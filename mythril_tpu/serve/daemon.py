"""Daemon lifecycle: wiring, signal handling, graceful drain.

:class:`AnalysisDaemon` composes the serve layer (docs/serving.md):
one results store, one admission queue, one scheduler thread, one
threaded HTTP server. The lifecycle contract:

- **start** — scheduler + HTTP come up; the engine itself loads lazily
  on the first batch that actually needs lanes, so a daemon fronting a
  pure-dedupe workload never initializes a backend;
- **SIGTERM / SIGINT** (or :meth:`shutdown`) — DRAIN: new submissions
  get HTTP 503 immediately, the in-flight batch finishes and its
  verdicts persist to the store (fleet mode: already-fed units get up
  to ``drain_timeout`` for their workers to commit, then the feed is
  closed), every still-queued entry resolves with an error so no
  long-poller hangs, and the process exits;
- **restart** — completed verdicts are durable files keyed on
  ``(bytecode_hash, config_hash)``, so resubmitting after a kill
  serves finished work from the dedupe store and re-analyzes only what
  never committed: exactly-once results without any WAL. This is the
  serve-layer face of the PR 4/5 kill+resume guarantees (the soak's
  ``serve`` leg kills a daemon mid-batch and asserts it).

A second signal while draining aborts the drain (fleet pending included)
— the operator's escalation path when a batch is wedged.
"""

from __future__ import annotations

import logging
import os
import signal
import threading
import time
from dataclasses import dataclass
from typing import Dict, List, Optional, Sequence, Tuple

from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from .http import ServeHTTPServer
from .queue import AdmissionQueue, ShedPolicy, TenantQuota
from .scheduler import Scheduler, StoreOnlyScheduler
from .store import ResultsStore

log = logging.getLogger(__name__)


@dataclass
class ServeOptions:
    """Daemon-level analysis configuration — the baseline every
    submission's effective config derives from. ``OVERRIDABLE`` names
    the per-request knobs; everything else is fixed at daemon start so
    one tenant cannot stampede the compile cache with exotic shapes."""

    batch_size: int = 8
    lanes_per_contract: int = 32
    max_steps: int = 256
    transaction_count: int = 1
    modules: Optional[List[str]] = None
    limits_profile: str = "default"
    solver_iters: int = 400
    solver_timeout: Optional[float] = None
    solver_workers: int = 1
    batch_timeout: Optional[float] = None
    max_batch_retries: int = 1
    oom_ladder: Optional[Sequence[str]] = None
    fault_inject: Optional[str] = None
    concrete_storage: bool = False
    #: engine-worker process isolation (docs/resilience.md): "auto"
    #: resolves to ON under serve — backend death must be a worker
    #: restart, not daemon death. Operational (excluded from the
    #: dedupe config hash): flipping it must not split the verdict
    #: cache.
    worker_isolation: str = "auto"
    #: ranked backend-tier ladder for resident campaigns (comma string
    #: or sequence; None = detect — docs/resilience.md "Backend
    #: tiers"). Operational like worker_isolation: which tier served a
    #: batch must not split the verdict cache — the issues in the
    #: bytecode don't depend on the silicon that found them.
    backend_tiers: Optional[Sequence[str]] = None
    #: per-request overrides accepted in the submit body's ``options``
    OVERRIDABLE = ("max_steps", "transaction_count", "modules")

    def effective(self, overrides: Dict) -> Dict:
        """The config dict that keys dedupe (``config_hash``) and
        shape-class bucketing. Unknown / non-overridable option keys
        raise — silently ignoring them would dedupe two analyses the
        client believes are different."""
        bad = [k for k in overrides if k not in self.OVERRIDABLE]
        if bad:
            raise ValueError(
                f"options {sorted(bad)} are not overridable per "
                f"request (allowed: {list(self.OVERRIDABLE)})")
        cfg = {
            "batch_size": self.batch_size,
            "lanes_per_contract": self.lanes_per_contract,
            "max_steps": int(overrides.get("max_steps",
                                           self.max_steps)),
            "transaction_count": int(
                overrides.get("transaction_count",
                              self.transaction_count)),
            "modules": (list(overrides["modules"])
                        if overrides.get("modules") is not None
                        else (list(self.modules)
                              if self.modules else None)),
            "limits_profile": self.limits_profile,
            "solver_iters": self.solver_iters,
            "solver_timeout": self.solver_timeout,
            "solver_workers": self.solver_workers,
            "batch_timeout": self.batch_timeout,
            "max_batch_retries": self.max_batch_retries,
            "oom_ladder": (tuple(self.oom_ladder)
                           if self.oom_ladder is not None else None),
            "fault_inject": self.fault_inject,
            "concrete_storage": self.concrete_storage,
            "worker_isolation": self.worker_isolation,
            "backend_tiers": (tuple(self.backend_tiers)
                              if isinstance(self.backend_tiers,
                                            (list, tuple))
                              else self.backend_tiers),
        }
        return cfg


class AnalysisDaemon:
    def __init__(self, options: Optional[ServeOptions] = None,
                 data_dir: str = "serve_data",
                 host: str = "127.0.0.1", port: int = 8780,
                 dedupe: bool = True, max_queue: int = 4096,
                 drain_timeout: float = 30.0,
                 fleet_dir: Optional[str] = None,
                 campaign_factory=None,
                 solver_store: Optional[str] = "auto",
                 quotas: Optional[Dict[str, TenantQuota]] = None,
                 default_quota: Optional[TenantQuota] = None,
                 shed: Optional[ShedPolicy] = "auto",
                 follow_uri: Optional[str] = None,
                 follow_poll: float = 2.0,
                 backfill_uri: Optional[str] = None,
                 backfill_window: int = 64,
                 backfill_poll: float = 2.0,
                 compact_every: Optional[float] = None,
                 store_only: bool = False,
                 store_refresh: float = 2.0,
                 compile_store: Optional[str] = "auto",
                 prewarm: bool = True):
        if store_only:
            # an edge replica has no engine: it cannot host a fleet,
            # tail the chain, backfill history, or serve without the
            # store it exists to serve from
            bad = [n for n, v in (("--fleet", fleet_dir),
                                  ("--follow", follow_uri),
                                  ("--backfill", backfill_uri),
                                  ("--compact-every", compact_every))
                   if v]
            if bad:
                raise ValueError(
                    f"--store-only is incompatible with "
                    f"{', '.join(bad)}")
            if not dedupe:
                raise ValueError(
                    "--store-only needs the dedupe store "
                    "(--no-dedupe makes no sense here)")
        self.options = options or ServeOptions()
        self.data_dir = data_dir
        os.makedirs(data_dir, exist_ok=True)
        self.store = ResultsStore(os.path.join(data_dir, "store"))
        # overload protection defaults ON (docs/serving.md "Overload &
        # multi-replica serving"): the default thresholds only engage
        # when the queue is nearly full or entries have sat for tens
        # of seconds — an unloaded daemon never sheds. None disables.
        if shed == "auto":
            shed = ShedPolicy()
        # per-QUERY solver verdict store (docs/solver.md) beside the
        # per-CONTRACT dedupe store: the daemon's solver work survives
        # restarts and is shared with any fleet workers it fronts.
        # "auto" = <data-dir>/solver_store; None disables.
        if solver_store == "auto":
            solver_store = os.path.join(data_dir, "solver_store")
        if store_only:
            solver_store = None  # no solver work will ever run here
        self.solver_store = solver_store
        self.store_only = bool(store_only)
        self.queue = AdmissionQueue(
            store=self.store, dedupe=dedupe, max_depth=max_queue,
            config_fn=self.options.effective, quotas=quotas,
            default_quota=default_quota, shed=shed,
            store_only=store_only)
        self.follow_uri = follow_uri
        self.follow_poll = float(follow_poll)
        self.follower = None
        self.backfill_uri = backfill_uri
        self.backfill_window = int(backfill_window)
        self.backfill_poll = float(backfill_poll)
        self.backfill = None
        self.compact_every = compact_every
        self.store_refresh = max(0.05, float(store_refresh))
        self._bg_stop = threading.Event()
        self._bg_threads: List[threading.Thread] = []
        # fleet compile-artifact store + AOT prewarm (docs/serving.md
        # "Compile artifacts & prewarm"): "auto" puts the registry +
        # shared XLA cache under the data dir so sibling/restarted
        # replicas share it; None disables. A store-only replica has
        # no engine and therefore nothing to compile. Created lazily
        # in start() — the compilestore import chain reaches jax, and
        # the daemon constructor stays backend-free.
        if compile_store == "auto":
            compile_store = (None if store_only
                             else os.path.join(data_dir, "compile_store"))
        self.compile_store_dir = compile_store
        self.compile_store = None
        self.prewarm = bool(prewarm) and compile_store is not None
        self._prewarm_doc: Optional[Dict] = None
        if store_only:
            self.scheduler = StoreOnlyScheduler()
        else:
            self.scheduler = Scheduler(
                self.queue, store=self.store,
                batch_size=self.options.batch_size,
                fleet_dir=fleet_dir, campaign_factory=campaign_factory)
        self.host = host
        self._port = port
        self.drain_timeout = float(drain_timeout)
        self.httpd: Optional[ServeHTTPServer] = None
        self._http_thread: Optional[threading.Thread] = None
        self.state = "starting"
        self.t_start = time.monotonic()
        self._done = threading.Event()
        self._shutdown_lock = threading.Lock()
        self._signals = 0

    # --- surface the HTTP layer routes through --------------------------
    def submit(self, contracts: Sequence[Tuple[str, bytes]], **kw):
        if self.state != "serving":
            from .queue import QueueClosed

            raise QueueClosed(f"daemon is {self.state}")
        return self.queue.submit(contracts, **kw)

    def health(self) -> Dict:
        if self.store_only:
            # the smt package import chain reaches JAX — a store-only
            # replica's healthz must stay backend-free (there is no
            # solver store here anyway)
            vstore = None
        else:
            from ..smt import portfolio as smt_portfolio

            vstore = smt_portfolio.get_store()
        qstats = self.queue.stats()
        doc = {
            "ok": True,
            "state": self.state,
            "queue_depth": qstats["queue_depth"],
            "oldest_entry_age_sec": qstats["oldest_entry_age_sec"],
            "shed_state": qstats["shed_state"],
            "tenants": qstats["tenants"],
            "batches_run": self.scheduler.batches_run,
            "fleet_units_pending": self.scheduler.pending_fleet_units(),
            "store_verdicts": self.store.count(),
            "solver_verdicts": vstore.count() if vstore else 0,
            "uptime_sec": round(time.monotonic() - self.t_start, 3),
            "pid": os.getpid(),
            "engine_worker_restarts": self.scheduler.worker_restarts(),
        }
        # a dead scheduler loop degrades the whole daemon (requests
        # would never schedule); an OPEN crash-loop breaker degrades
        # one config (its batches run pinned to in-process CPU) while
        # everything else serves normally — orchestrators see both
        if self.scheduler.crashed:
            doc["ok"] = False
            doc["state"] = "degraded"
            doc["error"] = f"scheduler loop died: {self.scheduler.crashed}"
        degraded = self.scheduler.degraded_configs()
        if degraded:
            doc["degraded_configs"] = degraded
        # backend-tier capacity classes (docs/resilience.md "Backend
        # tiers"): per-config ladder state, present once any resident
        # campaign has needed a ladder
        tiers = self.scheduler.tier_status()
        if tiers:
            doc["backend_tiers"] = tiers
        # compile-artifact prewarm state (docs/serving.md "Compile
        # artifacts & prewarm"): what the background pass did / is
        # doing, so an orchestrator can tell "came back warm" from
        # "still compiling lazily"
        if self.compile_store_dir and not self.store_only:
            doc["prewarm"] = (dict(self._prewarm_doc)
                              if self._prewarm_doc is not None
                              else {"state": ("pending" if self.prewarm
                                              else "disabled"),
                                    "done": 0, "total": 0,
                                    "last_error": None})
        if self.follower is not None:
            doc["follower"] = self.follower.status()
        if self.backfill is not None:
            doc["backfill"] = self.backfill.status()
        doc["store_generation"] = self.store.generation()
        if self.store_only:
            doc["store_only"] = True
        return doc

    @property
    def port(self) -> int:
        """The BOUND port (``--port 0`` asks the OS for a free one)."""
        if self.httpd is not None:
            return self.httpd.server_address[1]
        return self._port

    # --- lifecycle ------------------------------------------------------
    def start(self) -> None:
        obs_metrics.REGISTRY.enabled = True  # /metrics is always on
        # serve is always-traced (docs/observability.md "Distributed
        # tracing"): without an operator-installed tracer (--trace),
        # install one on the data dir so /v1/trace, per-result timings
        # and worker span backhaul work out of the box. Size rotation
        # bounds the JSONL log for long-lived daemons.
        self._own_tracer = None
        if not obs_trace.active():
            self._own_tracer = obs_trace.configure(
                os.path.join(self.data_dir, "trace.json"))
        if self.solver_store:
            # resident campaigns run with solver_store=None, so the
            # daemon-installed store stays in force for every batch;
            # /metrics exposes the portfolio ladder from the first
            # scrape (register_metrics inside set_store). The previous
            # store is restored on shutdown — in-process daemons
            # (tests) must not leak their store into later work.
            from ..smt import portfolio as smt_portfolio

            self._prev_solver_store = smt_portfolio.set_store(
                self.solver_store)
        if self.compile_store_dir and not self.store_only:
            from ..compilestore import CompileStore

            self.compile_store = CompileStore(self.compile_store_dir)
            # point the worker-cache contract at the shared dir BEFORE
            # any campaign spawns a worker (setdefault: an operator /
            # test-pinned MYTHRIL_WORKER_JAX_CACHE wins)
            self.compile_store.install_cache()
            self.scheduler.compile_store = self.compile_store
        self.scheduler.start()
        self.httpd = ServeHTTPServer((self.host, self._port), self)
        self._http_thread = threading.Thread(
            target=self.httpd.serve_forever, daemon=True,
            name="serve-http")
        self._http_thread.start()
        self.state = "serving"
        if self.follow_uri:
            from ..utils.loader import rpc_client_from_uri
            from .follower import ChainFollower

            self.follower = ChainFollower(
                self, rpc_client_from_uri(self.follow_uri),
                poll=self.follow_poll)
            self.follower.start()
        if self.backfill_uri:
            from ..utils.loader import rpc_client_from_uri
            from .backfill import ChainBackfill

            self.backfill = ChainBackfill(
                self, rpc_client_from_uri(self.backfill_uri),
                window=self.backfill_window, poll=self.backfill_poll)
            self.backfill.start()
        if self.compact_every and not self.store_only:
            t = threading.Thread(target=self._compact_loop,
                                 daemon=True, name="serve-compactor")
            t.start()
            self._bg_threads.append(t)
        if self.store_only:
            t = threading.Thread(target=self._refresh_loop,
                                 daemon=True, name="serve-refresher")
            t.start()
            self._bg_threads.append(t)
        if self.prewarm and self.compile_store is not None:
            t = threading.Thread(target=self._prewarm_loop,
                                 daemon=True, name="serve-prewarm")
            t.start()
            self._bg_threads.append(t)
        obs_trace.event("serve_started", host=self.host, port=self.port,
                        data_dir=self.data_dir)
        log.info("serving on %s:%d (data dir %s)", self.host, self.port,
                 self.data_dir)

    def _compact_loop(self) -> None:
        """Background compactor (``--compact-every``): periodically
        fold settled loose verdicts into the segment tier. ONE replica
        per data dir runs this (docs/serving.md deployment contract);
        a failed pass is logged and retried next period — the loose
        files it would have folded are still fully servable."""
        while not self._bg_stop.wait(self.compact_every):
            try:
                stats = self.store.compact()
                if stats.get("folded") or stats.get("dupes"):
                    log.info("compacted store: %s", stats)
            except Exception as e:  # noqa: BLE001 — keep the daemon up
                obs_metrics.REGISTRY.counter(
                    "serve_store_compaction_errors_total",
                    help="background compaction passes that failed "
                         "(retried next period)").inc()
                log.warning("compaction failed: %s: %s",
                            type(e).__name__, str(e)[:200])

    def _prewarm_loop(self) -> None:
        """Background AOT prewarm (docs/serving.md "Compile artifacts
        & prewarm"): on daemon start, materialize the baseline config's
        resident campaign and replay the registry's hottest buckets for
        its tier; afterwards, poll for recovery events — a worker
        respawn or a tier re-promotion flags ``_prewarm_pending`` on
        its campaign — and re-prewarm. Strictly subordinate to live
        traffic: the pass yields between buckets whenever the queue has
        work (or the daemon is draining), so a submitted request is
        scheduled without waiting for prewarm completion. Every failure
        here degrades to lazy compile — this thread may never take the
        daemon down."""

        def busy() -> bool:
            if self._bg_stop.is_set():
                return True
            try:
                return self.queue.stats()["queue_depth"] > 0
            except Exception:  # noqa: BLE001 — err on yielding
                return True

        try:
            from .store import config_hash

            cfg = self.options.effective({})
            camp = self.scheduler.campaign_for_config(cfg,
                                                      config_hash(cfg))
        except Exception as e:  # noqa: BLE001 — degrade to lazy compile
            self._prewarm_doc = {"state": "failed", "done": 0,
                                 "total": 0,
                                 "last_error": f"{type(e).__name__}: "
                                               f"{str(e)[:200]}"}
            log.warning("prewarm: baseline campaign unavailable: %s", e)
            return
        first = True
        while not self._bg_stop.is_set():
            for camp in list(self.scheduler._campaigns.values()):
                if self._bg_stop.is_set():
                    break
                # a factory-injected stand-in campaign (tests, custom
                # embedders) may not speak the prewarm protocol
                if not hasattr(camp, "prewarm_from_store"):
                    continue
                if not (first or getattr(camp, "_prewarm_pending",
                                         False)):
                    continue
                try:
                    self._prewarm_doc = camp.prewarm_from_store(
                        should_stop=busy)
                except Exception as e:  # noqa: BLE001 — lazy compile
                    self._prewarm_doc = {
                        "state": "failed", "done": 0, "total": 0,
                        "last_error": f"{type(e).__name__}: "
                                      f"{str(e)[:200]}"}
                    log.warning("prewarm pass failed: %s", e)
            first = False
            self._bg_stop.wait(0.25)

    def _refresh_loop(self) -> None:
        """Store-only replica poll: pick up manifest generations
        committed by the analysis fleet on the shared/snapshotted data
        dir."""
        while not self._bg_stop.wait(self.store_refresh):
            try:
                self.store.refresh()
            except Exception as e:  # noqa: BLE001 — keep serving
                log.warning("manifest refresh failed: %s: %s",
                            type(e).__name__, str(e)[:200])

    def shutdown(self, reason: str = "shutdown") -> None:
        """Graceful drain; idempotent and safe from any thread (the
        signal path runs it on a helper thread so the handler itself
        stays async-signal-trivial)."""
        with self._shutdown_lock:
            if self.state in ("draining", "stopped"):
                return
            self.state = "draining"
        obs_trace.event("serve_draining", reason=reason)
        log.info("draining (%s): rejecting new submissions, finishing "
                 "the in-flight batch", reason)
        self._bg_stop.set()
        if self.follower is not None:
            # the follower stops BEFORE the queue closes, so its last
            # block either submitted fully or will be retried from the
            # durable cursor on restart — never half-ingested
            self.follower.stop()
        if self.backfill is not None:
            # same ordering argument: a window interrupted before its
            # cursor advanced is simply re-scanned on restart, and the
            # dedupe store makes the overlap free
            self.backfill.stop()
        self.queue.close()
        self.scheduler.request_stop()
        if not self.scheduler.join(self.drain_timeout):
            # the in-flight batch (or a fleet worker) is wedged past
            # the budget: abandon it — its entries resolve as errors,
            # its verdicts simply never land (re-analyzed on restart)
            log.warning("drain timeout (%.1fs): abandoning the "
                        "in-flight work", self.drain_timeout)
            self.scheduler.abort()
            self.scheduler.join(2.0)
        failed = self.queue.fail_pending(
            "daemon shut down before this entry was scheduled; "
            "resubmit — completed contracts will be served from the "
            "dedupe store")
        if failed:
            log.info("failed %d still-queued entries", failed)
        if self.httpd is not None:
            self.httpd.shutdown()
            self.httpd.server_close()
        if self.solver_store and hasattr(self, "_prev_solver_store"):
            from ..smt import portfolio as smt_portfolio

            smt_portfolio.set_store(self._prev_solver_store)
        self.state = "stopped"
        obs_trace.event("serve_stopped", reason=reason,
                        queued_failed=failed)
        if (getattr(self, "_own_tracer", None) is not None
                and obs_trace.get_tracer() is self._own_tracer):
            obs_trace.close()
            self._own_tracer = None
        self._done.set()

    def handle_signal(self, signum, frame=None) -> None:
        """SIGTERM/SIGINT: first one drains, a second one escalates to
        abort (the wedged-batch escape hatch)."""
        self._signals += 1
        if self._signals >= 2:
            self.scheduler.abort()
        name = signal.Signals(signum).name
        threading.Thread(target=self.shutdown, args=(name,),
                         daemon=True,
                         name="serve-shutdown").start()

    def install_signal_handlers(self) -> None:
        signal.signal(signal.SIGTERM, self.handle_signal)
        signal.signal(signal.SIGINT, self.handle_signal)

    def serve_forever(self) -> None:
        """Start, then block until a signal (or another thread's
        :meth:`shutdown`) completes the drain."""
        self.start()
        self._done.wait()

    def wait_stopped(self, timeout: Optional[float] = None) -> bool:
        return self._done.wait(timeout)


__all__ = ["AnalysisDaemon", "ServeOptions"]
