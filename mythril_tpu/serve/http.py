"""Thin stdlib HTTP surface for the analysis daemon (docs/serving.md).

Endpoints (JSON in/out, no dependencies beyond ``http.server``):

- ``POST /v1/submit`` — body ``{"code": "<hex>"}`` or ``{"contracts":
  [{"name": "...", "code": "<hex>"}, ...]}`` plus optional ``tenant``,
  ``priority`` (int, higher first), ``deadline_sec`` (float) and
  ``options`` (per-request analysis overrides, see
  ``ServeOptions.OVERRIDABLE``). Returns 202 with the submission id
  (dedupe-served entries are already in ``results``; shed-served
  entries likewise, while the daemon is overloaded), 429 when the
  queue is full or the tenant's quota is spent (``Retry-After`` set
  either way), 503 while draining, 400 on a malformed body.
- ``GET /v1/result/<id>[?wait=SEC]`` — submission snapshot; ``wait``
  long-polls until NEW results commit (or the timeout lapses).
- ``GET /v1/result/<id>?stream=1`` — chunked transfer: one JSON line
  per contract result, written in COMMIT ORDER as batches land; the
  response ends when the submission completes. A slow or dead reader
  costs one daemon thread, nothing else (ThreadingHTTPServer).
- ``GET /v1/trace/<trace_id>`` — the stitched span/event timeline of
  one request trace (docs/observability.md "Distributed tracing"):
  every record the daemon's bounded in-memory trace index holds for
  that id, in monotonic order — including worker-subprocess spans
  backhauled and clock-corrected by the supervisor. 404 when the id is
  unknown or evicted.
- ``GET /healthz`` — liveness + ``serving``/``draining`` state (a
  draining daemon answers, so orchestrators can distinguish "dying
  gracefully" from "dead").
- ``GET /metrics`` — the obs registry in Prometheus text exposition
  format (the same payload ``--metrics FILE.prom`` snapshots).
"""

from __future__ import annotations

import json
import urllib.parse
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional, Tuple

from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from .queue import (QueueClosed, QueueFull, QuotaExceeded,
                    UNKNOWN_RETRY_AFTER)

#: cap on submission body size: serve is an analysis API, not an
#: artifact store; 64 MiB covers thousands of max-size contracts
MAX_BODY = 64 << 20


def parse_submit_body(doc: Dict) -> Tuple[list, Dict]:
    """``(contracts, kwargs-for-queue.submit)`` from a request body;
    raises ValueError with a client-facing message."""
    if not isinstance(doc, dict):
        raise ValueError("body must be a JSON object")
    contracts = []
    if "contracts" in doc:
        if not isinstance(doc["contracts"], list) or not doc["contracts"]:
            raise ValueError("'contracts' must be a non-empty list")
        for k, c in enumerate(doc["contracts"]):
            if not isinstance(c, dict) or "code" not in c:
                raise ValueError("each contract needs a 'code' hex field")
            contracts.append((str(c.get("name", f"contract_{k}")),
                              _hex_bytes(c["code"])))
    elif "code" in doc:
        contracts.append((str(doc.get("name", "contract_0")),
                          _hex_bytes(doc["code"])))
    else:
        raise ValueError("provide 'code' or 'contracts'")
    opts = doc.get("options") or {}
    if not isinstance(opts, dict):
        raise ValueError("'options' must be an object")
    kw = {
        "tenant": str(doc.get("tenant", "default")),
        "priority": int(doc.get("priority", 0)),
        "options": opts,
    }
    if doc.get("deadline_sec") is not None:
        kw["deadline_sec"] = float(doc["deadline_sec"])
    return contracts, kw


def _hex_bytes(text) -> bytes:
    if not isinstance(text, str):
        raise ValueError("bytecode must be a hex string")
    t = text.strip().removeprefix("0x")
    try:
        return bytes.fromhex(t)
    except ValueError:
        raise ValueError("bytecode is not valid hex") from None


class _Handler(BaseHTTPRequestHandler):
    protocol_version = "HTTP/1.1"
    server_version = "mythril-tpu-serve"

    # route access logs to logging.debug instead of stderr chatter
    def log_message(self, fmt, *args):  # noqa: D102
        import logging

        logging.getLogger(__name__).debug("http: " + fmt, *args)

    @property
    def daemon(self):
        return self.server.analysis_daemon

    # --- helpers --------------------------------------------------------
    def _json(self, code: int, doc: Dict,
              extra_headers: Dict = ()) -> None:
        body = (json.dumps(doc, indent=1) + "\n").encode()
        self.send_response(code)
        self.send_header("Content-Type", "application/json")
        self.send_header("Content-Length", str(len(body)))
        for k, v in dict(extra_headers or {}).items():
            self.send_header(k, v)
        self.end_headers()
        try:
            self.wfile.write(body)
        except (BrokenPipeError, ConnectionResetError):
            pass

    def _chunk(self, data: bytes) -> None:
        self.wfile.write(f"{len(data):x}\r\n".encode() + data + b"\r\n")

    # --- routes ---------------------------------------------------------
    def do_POST(self) -> None:  # noqa: N802 (stdlib casing)
        url = urllib.parse.urlparse(self.path)
        if url.path not in ("/v1/submit", "/v1/submit/"):
            self._json(404, {"error": f"no such endpoint {url.path}"})
            return
        try:
            length = int(self.headers.get("Content-Length", 0))
        except ValueError:
            length = 0
        if length <= 0 or length > MAX_BODY:
            self._json(400, {"error": "missing or oversized body"})
            return
        try:
            doc = json.loads(self.rfile.read(length))
            contracts, kw = parse_submit_body(doc)
        except ValueError as e:
            self._json(400, {"error": str(e)})
            return
        # trace ingestion point: the transport mints the request trace
        # id (or honors one a tracing client carried in), so the admit
        # span and everything downstream share it
        kw["trace_id"] = (str(doc.get("trace_id"))
                          if doc.get("trace_id")
                          else obs_trace.new_trace_id())
        try:
            sub = self.daemon.submit(contracts, **kw)
        except ValueError as e:
            # non-overridable / unknown option keys (ServeOptions
            # .effective) — a client error, not a daemon fault
            self._json(400, {"error": str(e)})
            return
        except QueueClosed:
            self._json(503, {"error": "daemon is draining; resubmit "
                                      "to a live instance"},
                       {"Retry-After": "5"})
            return
        except QuotaExceeded as e:
            # per-tenant quota breach: Retry-After tells the client
            # when its token bucket will cover the submission
            import math

            self._json(429, {"error": str(e)},
                       {"Retry-After": str(math.ceil(e.retry_after))})
            return
        except QueueFull as e:
            self._json(429, {"error": str(e)}, {"Retry-After": "1"})
            return
        snap = sub.snapshot()
        snap["queue_depth"] = self.daemon.queue.depth()
        headers = {}
        if any(r.get("status") == "unknown-contract"
               for r in snap.get("results") or []):
            # a store-only replica answered at least one miss: tell
            # the client when the next manifest refresh is worth a
            # retry (the verdict may be compacting its way here)
            headers["Retry-After"] = str(UNKNOWN_RETRY_AFTER)
        self._json(202, snap, headers)

    def do_GET(self) -> None:  # noqa: N802
        url = urllib.parse.urlparse(self.path)
        q = urllib.parse.parse_qs(url.query)
        if url.path == "/healthz":
            self._json(200, self.daemon.health())
            return
        if url.path == "/metrics":
            body = obs_metrics.REGISTRY.to_prometheus().encode()
            self.send_response(200)
            self.send_header("Content-Type",
                             "text/plain; version=0.0.4")
            self.send_header("Content-Length", str(len(body)))
            self.end_headers()
            try:
                self.wfile.write(body)
            except (BrokenPipeError, ConnectionResetError):
                pass
            return
        if url.path.startswith("/v1/result/"):
            sid = url.path[len("/v1/result/"):].strip("/")
            sub = self.daemon.queue.get(sid)
            if sub is None:
                self._json(404, {"error": f"unknown submission {sid!r}"})
                return
            if q.get("stream", ["0"])[0] in ("1", "true", "yes"):
                self._stream(sub)
                return
            wait = float(q.get("wait", ["0"])[0] or 0)
            if wait > 0:
                sub.wait_done(timeout=min(wait, 300.0))
            self._json(200, sub.snapshot())
            return
        if url.path.startswith("/v1/trace/"):
            tid = url.path[len("/v1/trace/"):].strip("/")
            recs = obs_trace.trace_records(tid)
            if recs is None:
                self._json(404, {"error": f"unknown trace {tid!r} "
                                          "(expired from the index, "
                                          "or never minted here)"})
                return
            self._json(200, {
                "trace_id": tid,
                "spans": sum(1 for r in recs
                             if r.get("kind") == "span"),
                "records": recs})
            return
        self._json(404, {"error": f"no such endpoint {url.path}"})

    def _stream(self, sub) -> None:
        """Chunked per-contract result stream in commit order. Each
        chunk is one JSON line; the final chunk is a ``done`` marker
        carrying the totals."""
        self.send_response(200)
        self.send_header("Content-Type", "application/x-ndjson")
        self.send_header("Transfer-Encoding", "chunked")
        self.end_headers()
        reg = obs_metrics.REGISTRY
        sent = 0
        with obs_trace.span("stream", id=sub.sid):
            try:
                while True:
                    snap = sub.snapshot()
                    results = snap["results"]
                    while sent < len(results):
                        self._chunk(json.dumps(
                            results[sent]).encode() + b"\n")
                        reg.counter(
                            "serve_results_streamed_total",
                            help="per-contract results written to "
                                 "streaming responses").inc()
                        sent += 1
                    if snap["state"] == "done":
                        break
                    sub.wait_results(sent, timeout=5.0)
                self._chunk(json.dumps(
                    {"done": True, "id": sub.sid,
                     "contracts": snap["contracts"],
                     "completed": sent}).encode() + b"\n")
                self._chunk(b"")  # terminal zero-length chunk
            except (BrokenPipeError, ConnectionResetError):
                pass  # reader went away; the verdicts are still stored


class ServeHTTPServer(ThreadingHTTPServer):
    """One daemon thread per connection; ``analysis_daemon`` is the
    back-reference the handler routes through."""

    daemon_threads = True
    allow_reuse_address = True

    def __init__(self, addr: Tuple[str, int], analysis_daemon):
        super().__init__(addr, _Handler)
        self.analysis_daemon = analysis_daemon


__all__ = ["MAX_BODY", "ServeHTTPServer", "parse_submit_body"]
