"""Chain-head follower: a standing lowest-priority background tenant.

``serve --follow RPC_URI`` (docs/serving.md "Overload & multi-replica
serving") runs this loop beside the scheduler: poll the node's
``eth_blockNumber``, walk every new block's transactions for contract
creations (``to == null`` → ``eth_getTransactionReceipt`` →
``contractAddress``), fetch each new contract's runtime bytecode with
``eth_getCode``, and submit it through the normal admission queue as
``tenant="follower"`` at :data:`FOLLOWER_PRIORITY` — the lowest
priority in the system, BY DESIGN the first workload shed under
overload and the last to claim a lane. The payoff is the ROADMAP
chain-follower story: by the time a user asks about a contract, its
verdict is usually precomputed (mainnet's proxy/clone dominance means
the marginal new contract is a canonical-hash dedupe hit anyway; the
follower turns the rest into warm store entries during quiet periods).

Contracts:

- **durable cursor** — the last fully-ingested block number persists
  to ``<data-dir>/follower_cursor.json`` (repo-wide ``durable_write``)
  after each block, so a restarted daemon resumes where it left off
  instead of re-walking or skipping the gap. A FRESH follower starts
  at the current head (no genesis backfill);
- **bounded backoff** — RPC failures (node down, malformed replies)
  double a capped backoff and tick
  ``serve_follower_rpc_errors_total``; the loop never dies, never
  spins, and recovers to the poll cadence on the first success;
- **backpressure, not pressure** — a full queue or a spent quota makes
  the follower WAIT (cursor unmoved, block retried); while the daemon
  sheds, follower submissions resolve as store-hits/typed-shed
  answers like any other low-priority tenant — the follower is the
  standing proof-load for the quota/shed machinery;
- **lag visibility** — ``serve_follower_lag_blocks`` (head − cursor)
  and ``serve_follower_ingested_total`` are live in ``/metrics``;
  ``/healthz`` carries ``follower: {cursor, head, lag, ingested, ...}``.
"""

from __future__ import annotations

import json
import logging
import os
import threading
import time
from typing import Dict, List, Optional, Tuple

from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..utils.checkpoint import durable_write
from .queue import QueueClosed, QueueFull, QuotaExceeded

log = logging.getLogger(__name__)

#: the follower's fixed priority: below every interactive submission
#: (default 0), so it is shed first and scheduled last
FOLLOWER_PRIORITY = -100

#: cursor-file schema (readers reject newer-than-known)
CURSOR_SCHEMA = 1


def deployed_contracts(client, n: int) -> List[Tuple[str, bytes]]:
    """``(address, runtime_bytecode)`` for every contract created in
    block ``n`` — the deployment-scan shared by the head follower and
    the backward backfill walker (``serve/backfill.py``). Creations
    without a receipt/address or with empty runtime code
    (selfdestructed in the same block, EOA funding) are skipped."""
    blk = client.eth_getBlockByNumber(hex(n), True)
    out: List[Tuple[str, bytes]] = []
    for tx in (blk or {}).get("transactions") or []:
        if not isinstance(tx, dict) or tx.get("to"):
            continue
        txh = tx.get("hash")
        if not txh:
            continue
        rcpt = client.eth_getTransactionReceipt(txh) or {}
        addr = rcpt.get("contractAddress")
        if not addr:
            continue
        code = client.eth_getCode(addr)
        try:
            raw = bytes.fromhex(str(code).removeprefix("0x"))
        except ValueError:
            continue
        if raw:
            out.append((str(addr), raw))
    return out


class ChainFollower:
    """Background ingestion loop over the existing JSON-RPC client
    (``utils/loader.HttpRpcClient`` — anything with ``eth_blockNumber``
    / ``eth_getBlockByNumber`` / ``eth_getTransactionReceipt`` /
    ``eth_getCode`` duck-types)."""

    def __init__(self, daemon, client, poll: float = 2.0,
                 cursor_path: Optional[str] = None,
                 tenant: str = "follower",
                 priority: int = FOLLOWER_PRIORITY,
                 max_backoff: float = 60.0,
                 max_blocks_per_poll: int = 16):
        self.daemon = daemon
        self.client = client
        self.poll = max(0.05, float(poll))
        self.cursor_path = cursor_path or os.path.join(
            daemon.data_dir, "follower_cursor.json")
        self.tenant = tenant
        self.priority = int(priority)
        self.max_backoff = float(max_backoff)
        self.max_blocks_per_poll = max(1, int(max_blocks_per_poll))
        self.cursor: Optional[int] = self._load_cursor()
        self.head: Optional[int] = None
        self.ingested = 0
        self.rpc_errors = 0
        self._backoff = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._reg = obs_metrics.REGISTRY

    # --- cursor durability ----------------------------------------------
    def _load_cursor(self) -> Optional[int]:
        try:
            with open(self.cursor_path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            return None
        if (not isinstance(doc, dict)
                or int(doc.get("schema", 0) or 0) > CURSOR_SCHEMA
                or not isinstance(doc.get("block"), int)):
            return None
        return doc["block"]

    def _save_cursor(self) -> None:
        durable_write(
            self.cursor_path,
            json.dumps({"schema": CURSOR_SCHEMA, "block": self.cursor,
                        "t": round(time.time(), 3)}).encode(),
            rotate=False)

    # --- lifecycle ------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-follower")
        self._thread.start()
        obs_trace.event("follower_started", cursor=self.cursor,
                        tenant=self.tenant, priority=self.priority)

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    def status(self) -> Dict:
        lag = (max(0, self.head - self.cursor)
               if self.head is not None and self.cursor is not None
               else None)
        return {"cursor": self.cursor, "head": self.head, "lag": lag,
                "ingested": self.ingested,
                "rpc_errors": self.rpc_errors,
                "backoff_sec": round(self._backoff, 3)}

    # --- the loop -------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                delay = self._tick()
                self._backoff = 0.0
            except Exception as e:  # noqa: BLE001 — the loop may not die
                self.rpc_errors += 1
                self._reg.counter(
                    "serve_follower_rpc_errors_total",
                    help="follower poll/ingest failures (backed "
                         "off, retried)").inc()
                self._backoff = min(self.max_backoff,
                                    max(self.poll, self._backoff * 2))
                obs_trace.event("follower_rpc_error",
                                detail=f"{type(e).__name__}: "
                                       f"{str(e)[:200]}",
                                backoff=round(self._backoff, 3))
                log.warning("follower: %s: %s (backing off %.1fs)",
                            type(e).__name__, str(e)[:200],
                            self._backoff)
                delay = self._backoff
            self._stop.wait(delay)
        obs_trace.event("follower_stopped", cursor=self.cursor,
                        ingested=self.ingested)

    def _tick(self) -> float:
        """One poll: advance the cursor toward the head by up to
        ``max_blocks_per_poll`` blocks. Returns how long to sleep
        before the next tick (0 while catching up a backlog)."""
        self.head = int(self.client.eth_blockNumber(), 16)
        if self.cursor is None:
            # fresh follower: start AT the head — ingest what deploys
            # from now on, don't backfill the whole chain
            self.cursor = self.head
            self._save_cursor()
        self._lag_gauge()
        done = 0
        while (self.cursor < self.head
               and done < self.max_blocks_per_poll
               and not self._stop.is_set()):
            if not self._ingest_block(self.cursor + 1):
                return self.poll     # backpressure: retry this block
            self.cursor += 1
            self._save_cursor()
            done += 1
            self._reg.counter(
                "serve_follower_blocks_total",
                help="chain blocks the follower has walked").inc()
        self._lag_gauge()
        return 0.0 if self.cursor < self.head else self.poll

    def _lag_gauge(self) -> None:
        if self.head is not None and self.cursor is not None:
            self._reg.gauge(
                "serve_follower_lag_blocks",
                help="blocks between the chain head and the "
                     "follower's durable cursor").set(
                max(0, self.head - self.cursor))

    def _new_contracts(self, n: int) -> List[Tuple[str, bytes]]:
        return deployed_contracts(self.client, n)

    def _ingest_block(self, n: int) -> bool:
        """Submit block ``n``'s new contracts. Returns False on
        BACKPRESSURE (queue full / quota spent) so the caller retries
        the same block later — the cursor only advances past blocks
        whose contracts were actually answered for."""
        contracts = self._new_contracts(n)
        if not contracts:
            return True
        # trace ingestion point: one trace id per ingested block (its
        # contracts are one submission — one stitched timeline)
        tid = obs_trace.new_trace_id()
        try:
            self.daemon.queue.submit(contracts, tenant=self.tenant,
                                     priority=self.priority,
                                     trace_id=tid)
        except (QueueFull, QuotaExceeded):
            self._reg.counter(
                "serve_follower_backpressure_total",
                help="follower submissions deferred by a full queue "
                     "or spent quota").inc()
            return False
        except QueueClosed:
            self._stop.set()
            return False
        self.ingested += len(contracts)
        self._reg.counter(
            "serve_follower_ingested_total",
            help="newly deployed contracts submitted by the "
                 "follower").inc(len(contracts))
        obs_trace.event("follower_ingest", block=n, n=len(contracts),
                        trace_id=tid)
        return True


__all__ = ["CURSOR_SCHEMA", "ChainFollower", "FOLLOWER_PRIORITY",
           "deployed_contracts"]
