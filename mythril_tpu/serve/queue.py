"""Admission queue: dedupe before lanes, priority + deadline ordering.

The front half of the serve layer (docs/serving.md). Every submitted
contract becomes one :class:`Entry`; admission runs, in order:

1. **store dedupe** — a persisted verdict under the same
   ``(bytecode_hash, config_hash)`` resolves the entry immediately
   (``served_from="dedupe-store"``), no lane is touched;
2. **in-flight dedupe** — the same key already queued or running
   attaches this entry as a FOLLOWER of the primary: when the primary's
   batch commits, every follower resolves from the same verdict
   (``served_from="dedupe-inflight"``) — N concurrent submitters of one
   proxy bytecode cost one analysis;
3. **admission** — the entry joins the queue, ordered by
   ``(-priority, deadline, arrival)``: higher tenant priority first,
   earlier deadline breaks ties, FIFO within equals. A bounded queue
   (``max_depth``) rejects the overflow with :class:`QueueFull` (HTTP
   429) instead of buffering unboundedly.

Entries whose deadline lapses while queued are EVICTED at scheduling
time (``status="evicted"``) — a deadline is "answer by", not "try
anyway"; the scheduler never spends lanes on an answer nobody is
waiting for.

Telemetry: an ``admit`` span per submission, a ``queue_wait`` span per
entry (emitted when the scheduler pops it, measuring time spent
queued), ``serve_requests_total`` / ``serve_contracts_total`` /
``serve_dedupe_hits_total`` / ``serve_evicted_total`` counters and the
``serve_queue_depth`` gauge.

Thread-safety: one condition guards the queue, the in-flight index and
every entry/submission state transition; HTTP threads submit and wait,
the scheduler thread pops and resolves.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from .store import ResultsStore, bytecode_hash, config_hash


class QueueFull(Exception):
    """Admission would exceed ``max_depth`` — back off and retry."""


class QueueClosed(Exception):
    """The daemon is draining; no new submissions (HTTP 503)."""


#: config keys that define the ENGINE SHAPE a contract compiles into —
#: entries batch together only within one shape class, so one compiled
#: executable serves the whole batch
SHAPE_KEYS = ("batch_size", "lanes_per_contract", "max_steps",
              "transaction_count")


def shape_key_of(config: Dict) -> Tuple:
    return tuple(config.get(k) for k in SHAPE_KEYS)


class Entry:
    """One contract of one submission, from admission to verdict."""

    __slots__ = ("eid", "name", "code", "bch", "cfh", "config",
                 "shape_key", "priority", "deadline", "seq", "state",
                 "result", "submission", "followers", "t_submit")

    def __init__(self, eid: str, name: str, code: bytes, config: Dict,
                 priority: int, deadline: Optional[float], seq: int,
                 submission: "Submission"):
        self.eid = eid
        self.name = name
        self.code = code
        self.bch = bytecode_hash(code)
        self.cfh = config_hash(config)
        self.config = config
        self.shape_key = shape_key_of(config)
        self.priority = priority
        self.deadline = deadline        # absolute monotonic, or None
        self.seq = seq
        self.state = "queued"           # queued|running|done
        self.result: Optional[Dict] = None
        self.submission = submission
        self.followers: List["Entry"] = []
        self.t_submit = time.monotonic()

    @property
    def uname(self) -> str:
        """Engine-side contract name: unique within any batch (issue
        attribution maps back through it), never colliding with the
        campaign's ``_pad_*`` stubs."""
        return f"{self.name}@{self.eid}"

    def sort_key(self) -> Tuple:
        return (-self.priority,
                self.deadline if self.deadline is not None
                else float("inf"),
                self.seq)


class Submission:
    """One ``POST /v1/submit`` — a list of entries plus the stream of
    their results in COMMIT ORDER (dedupe-served entries first, then
    batch commits as they land)."""

    def __init__(self, sid: str, tenant: str, cond: threading.Condition):
        self.sid = sid
        self.tenant = tenant
        self.t = time.time()
        self.entries: List[Entry] = []
        #: per-contract results, appended strictly in commit order —
        #: the ``?stream=1`` wire order
        self.results: List[Dict] = []
        self._cond = cond

    @property
    def done(self) -> bool:
        return len(self.results) >= len(self.entries)

    def wait_results(self, seen: int, timeout: Optional[float]) -> bool:
        """Block until ``results`` grew past ``seen`` (or the
        submission finished, or the timeout lapsed). Returns done."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cond:
            while len(self.results) <= seen and not self.done:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    break
                self._cond.wait(remaining if remaining is not None
                                else 1.0)
            return self.done

    def wait_done(self, timeout: Optional[float]) -> bool:
        """Block until every entry resolved (long-poll). Returns
        done."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cond:
            while not self.done:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    break
                self._cond.wait(remaining if remaining is not None
                                else 1.0)
            return self.done

    def snapshot(self) -> Dict:
        with self._cond:
            results = list(self.results)
            done = len(results) >= len(self.entries)
            return {"id": self.sid, "tenant": self.tenant,
                    "contracts": len(self.entries),
                    "completed": len(results),
                    "state": "done" if done else "pending",
                    "results": results}


class AdmissionQueue:
    def __init__(self, store: Optional[ResultsStore] = None,
                 dedupe: bool = True, max_depth: int = 4096,
                 config_fn: Optional[Callable[[Dict], Dict]] = None):
        self.store = store
        self.dedupe = bool(dedupe) and store is not None
        self.max_depth = max(1, int(max_depth))
        #: merges per-request option overrides into the daemon's base
        #: analysis config — the dict that config_hash covers
        self.config_fn = config_fn or (lambda overrides: dict(overrides))
        self.closed = False
        self._cond = threading.Condition()
        self._queue: List[Entry] = []
        self._inflight: Dict[Tuple[str, str], Entry] = {}
        self._subs: Dict[str, Submission] = {}
        self._seq = itertools.count()
        self._nsub = itertools.count()
        self._reg = obs_metrics.REGISTRY

    # --- admission ------------------------------------------------------
    def _depth_gauge(self) -> None:
        self._reg.gauge(
            "serve_queue_depth",
            help="entries admitted and not yet scheduled").set(
            len(self._queue))

    def submit(self, contracts: Sequence[Tuple[str, bytes]],
               tenant: str = "default", priority: int = 0,
               deadline_sec: Optional[float] = None,
               options: Optional[Dict] = None) -> Submission:
        """Admit one submission of ``(name, bytecode)`` pairs. Raises
        :class:`QueueClosed` while draining, :class:`QueueFull` when
        the whole submission cannot fit (all-or-nothing: a partially
        admitted submission would stream a partial result set that
        LOOKS complete)."""
        config = self.config_fn(dict(options or {}))
        with obs_trace.timer("admit", tenant=tenant,
                             n=len(contracts)) as sp:
            with self._cond:
                if self.closed:
                    raise QueueClosed("daemon is draining")
                self._reg.counter(
                    "serve_requests_total",
                    help="submissions accepted for admission").inc()
                self._reg.counter("serve_contracts_total").inc(
                    len(contracts))
                sid = f"s{next(self._nsub):06d}-{os.getpid():x}"
                sub = Submission(sid, tenant, self._cond)
                fresh: List[Entry] = []
                deadline = (None if deadline_sec is None
                            else time.monotonic() + float(deadline_sec))
                for name, code in contracts:
                    e = Entry(f"e{next(self._seq):07d}", str(name),
                              bytes(code), config, int(priority),
                              deadline, next(self._seq), sub)
                    sub.entries.append(e)
                    key = (e.bch, e.cfh)
                    if self.dedupe:
                        doc = self.store.get(e.bch, e.cfh)
                        if doc is not None:
                            self._reg.counter(
                                "serve_dedupe_hits_total",
                                help="submissions served from the "
                                     "verdict store or in-flight "
                                     "work, no lane touched").inc()
                            self._resolve_locked(
                                e, self._verdict_result(e, doc),
                                served_from="dedupe-store")
                            continue
                        # in-flight attach covers clones WITHIN this
                        # submission too (the index is updated as
                        # entries are admitted below): a corpus of N
                        # proxy copies costs one analysis, not N
                        primary = self._inflight.get(key)
                        if primary is not None:
                            self._reg.counter(
                                "serve_dedupe_hits_total",
                                help="submissions served from the "
                                     "verdict store or in-flight "
                                     "work, no lane touched").inc()
                            primary.followers.append(e)
                            e.state = "running"
                            continue
                        self._inflight[key] = e
                    fresh.append(e)
                if len(self._queue) + len(fresh) > self.max_depth:
                    # roll back: drop this submission's in-flight
                    # registrations and followers (resolved store-hits
                    # stand — they cost nothing, their verdicts are
                    # real)
                    for e in fresh:
                        e.state = "done"
                        if self._inflight.get((e.bch, e.cfh)) is e:
                            del self._inflight[(e.bch, e.cfh)]
                    for e in sub.entries:
                        primary = self._inflight.get((e.bch, e.cfh))
                        if primary is not None and e in primary.followers:
                            primary.followers.remove(e)
                    raise QueueFull(
                        f"queue depth {len(self._queue)} + "
                        f"{len(fresh)} exceeds {self.max_depth}")
                for e in fresh:
                    self._queue.append(e)
                self._subs[sid] = sub
                self._depth_gauge()
                self._cond.notify_all()
        sp.attrs["id"] = sub.sid
        return sub

    @staticmethod
    def _verdict_result(e: Entry, doc: Dict) -> Dict:
        """Entry result from a stored verdict: the issues are re-homed
        onto THIS entry's display name (the verdict was computed under
        some other submission's engine name)."""
        issues = []
        for i in doc.get("issues") or []:
            i = dict(i)
            i["contract"] = e.name
            issues.append(i)
        return {"status": str(doc.get("status", "ok")),
                "issues": issues}

    # --- scheduling side ------------------------------------------------
    def _evict_expired_locked(self, now: float) -> None:
        keep = []
        for e in self._queue:
            if e.deadline is not None and now >= e.deadline:
                self._reg.counter(
                    "serve_evicted_total",
                    help="entries whose deadline lapsed while "
                         "queued").inc()
                if self._inflight.get((e.bch, e.cfh)) is e:
                    del self._inflight[(e.bch, e.cfh)]
                self._resolve_locked(
                    e, {"status": "evicted",
                        "error": "deadline exceeded before scheduling"},
                    served_from=None)
            else:
                keep.append(e)
        self._queue = keep

    def pop_batch(self, max_items: int,
                  timeout: Optional[float] = None) -> List[Entry]:
        """The scheduler's drain: block up to ``timeout`` for work,
        evict lapsed deadlines, then pop the best-priority entry plus
        up to ``max_items - 1`` more entries of the SAME effective
        config (one module list, one engine shape — one compiled
        executable and one host-phase recipe serve the whole batch) in
        priority order. Different configs of one shape class still
        share compiled executables ACROSS batches via the scheduler's
        warm-shape registry."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cond:
            while True:
                self._evict_expired_locked(time.monotonic())
                if self._queue:
                    break
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    self._depth_gauge()
                    return []
                self._cond.wait(remaining if remaining is not None
                                else 1.0)
            ordered = sorted(self._queue, key=Entry.sort_key)
            cfh = ordered[0].cfh
            batch = [e for e in ordered
                     if e.cfh == cfh][:max(1, int(max_items))]
            taken = set(id(e) for e in batch)
            self._queue = [e for e in self._queue
                           if id(e) not in taken]
            now = time.monotonic()
            for e in batch:
                e.state = "running"
                obs_trace.complete("queue_wait", now - e.t_submit,
                                   eid=e.eid, tenant=e.submission.tenant,
                                   priority=e.priority)
                self._reg.histogram(
                    "serve_queue_wait_seconds",
                    help="admission-to-schedule latency").observe(
                    now - e.t_submit)
            self._depth_gauge()
            return batch

    # --- resolution -----------------------------------------------------
    def _resolve_locked(self, e: Entry, result: Dict,
                        served_from: Optional[str]) -> None:
        if e.state == "done":
            return
        e.state = "done"
        res = dict(result)
        res.setdefault("status", "ok")
        res["name"] = e.name
        res["bytecode_hash"] = e.bch
        res["config_hash"] = e.cfh
        if served_from:
            res["served_from"] = served_from
        e.result = res
        e.submission.results.append(res)
        for f in e.followers:
            self._resolve_locked(f, self._verdict_result(f, res),
                                 served_from="dedupe-inflight")
        e.followers = []

    def resolve(self, e: Entry, result: Dict,
                served_from: Optional[str] = None) -> None:
        """Scheduler-side: commit one entry's verdict (and its
        followers') and wake every waiter. ``served_from`` marks
        DEDUPE provenance only; a fresh analysis carries no marker."""
        with self._cond:
            self._inflight.pop((e.bch, e.cfh), None)
            self._resolve_locked(e, result, served_from)
            self._cond.notify_all()

    # --- lifecycle ------------------------------------------------------
    def get(self, sid: str) -> Optional[Submission]:
        with self._cond:
            return self._subs.get(sid)

    def depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def close(self) -> None:
        """Stop admitting (drain begins). Queued entries stay queued —
        the scheduler decides whether to run or fail them."""
        with self._cond:
            self.closed = True
            self._cond.notify_all()

    def fail_pending(self, reason: str) -> int:
        """Resolve every still-queued entry with an error status (the
        drain's last act: nothing may wait forever on a daemon that is
        exiting). Returns how many were failed."""
        with self._cond:
            n = 0
            for e in list(self._queue):
                self._inflight.pop((e.bch, e.cfh), None)
                self._resolve_locked(
                    e, {"status": "error", "error": reason},
                    served_from=None)
                n += 1
            self._queue = []
            self._depth_gauge()
            self._cond.notify_all()
            return n


__all__ = ["AdmissionQueue", "Entry", "QueueClosed", "QueueFull",
           "SHAPE_KEYS", "Submission", "shape_key_of"]
