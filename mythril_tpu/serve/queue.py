"""Admission queue: dedupe before lanes, priority + deadline ordering.

The front half of the serve layer (docs/serving.md). Every submitted
contract becomes one :class:`Entry`; admission runs, in order:

1. **store dedupe** — a persisted verdict under the same
   ``(bytecode_hash, config_hash)`` resolves the entry immediately
   (``served_from="dedupe-store"``), no lane is touched;
2. **in-flight dedupe** — the same key already queued or running
   attaches this entry as a FOLLOWER of the primary: when the primary's
   batch commits, every follower resolves from the same verdict
   (``served_from="dedupe-inflight"``) — N concurrent submitters of one
   proxy bytecode cost one analysis;
3. **quota** — per-tenant admission control (docs/serving.md "Overload
   & multi-replica serving"): a token-bucket rate (tokens buy FRESH
   entries — dedupe hits are free) and a max-in-flight cap per tenant.
   A breach raises :class:`QuotaExceeded` (HTTP 429 with a computed
   ``Retry-After``). Quotas are per tenant, so one throttled tenant
   can never starve the others;
4. **load shedding** — under overload (queue depth or oldest-entry age
   past :class:`ShedPolicy` thresholds) LOW-priority submissions stop
   reaching the queue at all: every contract is answered from the
   verdict store (``served_from="shed-store"``) or resolved with a
   typed ``status="shed"`` result — degraded, never dropped, never
   buffered. Recovery is automatic (hysteresis low-watermarks) the
   moment pressure clears; every transition is an event + counter;
5. **admission** — the entry joins the queue, ordered by
   ``(-priority, deadline, arrival)``: higher tenant priority first,
   earlier deadline breaks ties, FIFO within equals. A bounded queue
   (``max_depth``) rejects the overflow with :class:`QueueFull` (HTTP
   429) instead of buffering unboundedly.

Entries whose deadline lapses while queued are EVICTED at scheduling
time (``status="evicted"``) — a deadline is "answer by", not "try
anyway"; the scheduler never spends lanes on an answer nobody is
waiting for.

Per-tenant SLO accounting rides resolution: every entry with a
deadline lands as a deadline HIT or MISS for its tenant
(``serve_tenant_deadline_misses_total{tenant=...}``), latency is
accumulated per tenant, and ``stats()`` surfaces the whole per-tenant
table for ``/healthz``.

Telemetry: an ``admit`` span per submission, a ``queue_wait`` span per
entry (emitted when the scheduler pops it, measuring time spent
queued), ``serve_requests_total`` / ``serve_contracts_total`` /
``serve_dedupe_hits_total`` / ``serve_evicted_total`` /
``serve_shed_total{reason}`` / ``serve_quota_rejections_total{tenant}``
counters and the ``serve_queue_depth`` / ``serve_oldest_entry_age_sec``
/ ``serve_shed_state`` gauges.

Thread-safety: one condition guards the queue, the in-flight index and
every entry/submission state transition; HTTP threads submit and wait,
the scheduler thread pops and resolves.
"""

from __future__ import annotations

import itertools
import os
import threading
import time
from dataclasses import dataclass
from typing import Callable, Dict, List, Optional, Sequence, Tuple

from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from .store import ResultsStore, bytecode_hash, config_hash

#: the Retry-After (seconds) a store-only replica attaches to a typed
#: ``unknown-contract`` answer — one manifest-refresh poll away
UNKNOWN_RETRY_AFTER = 5


class QueueFull(Exception):
    """Admission would exceed ``max_depth`` — back off and retry."""


class QueueClosed(Exception):
    """The daemon is draining; no new submissions (HTTP 503)."""


class QuotaExceeded(Exception):
    """One tenant's rate or in-flight quota is spent (HTTP 429 with
    ``Retry-After``); other tenants are unaffected."""

    def __init__(self, msg: str, retry_after: float = 1.0):
        super().__init__(msg)
        self.retry_after = max(0.1, float(retry_after))


@dataclass
class TenantQuota:
    """Per-tenant admission limits. ``None`` fields are unlimited.

    ``rate`` is a token bucket over FRESH contracts per second (dedupe
    hits and shed answers cost nothing — cached answers are the cheap
    path overload protection exists to preserve); ``burst`` is the
    bucket capacity (default ``max(8, 2*rate)``); ``max_inflight``
    caps this tenant's queued+running fresh entries."""

    rate: Optional[float] = None
    burst: Optional[int] = None
    max_inflight: Optional[int] = None

    def bucket_cap(self) -> float:
        if self.burst is not None:
            return max(1.0, float(self.burst))
        return max(8.0, 2.0 * float(self.rate or 0.0))

    @classmethod
    def parse(cls, text: str) -> "TenantQuota":
        """``"rate[:burst[:max_inflight]]"`` with blank fields meaning
        unlimited — the ``--quota TENANT=2:8:4`` CLI format."""
        parts = (text.split(":") + ["", "", ""])[:3]
        try:
            return cls(
                rate=float(parts[0]) if parts[0] else None,
                burst=int(parts[1]) if parts[1] else None,
                max_inflight=int(parts[2]) if parts[2] else None)
        except ValueError:
            raise ValueError(
                f"bad quota spec {text!r}; want rate[:burst[:inflight]]"
                " with numeric or empty fields") from None


@dataclass
class ShedPolicy:
    """When to degrade low-priority admissions to store-only answers.

    Enter shedding when queue depth ≥ ``depth_hi * max_depth`` OR the
    oldest queued entry is older than ``age_hi`` seconds; exit when
    depth and age are back under the low watermarks (hysteresis, so
    the state doesn't flap at the threshold). Submissions with
    ``priority <= priority_max`` are the sheddable class — the default
    priority 0 traffic degrades first, anything explicitly prioritized
    above it keeps its lane."""

    depth_hi: float = 0.85
    age_hi: float = 30.0
    depth_lo: Optional[float] = None   # default: depth_hi / 2
    age_lo: Optional[float] = None     # default: age_hi / 2
    priority_max: int = 0

    def lo_marks(self) -> Tuple[float, float]:
        return (self.depth_lo if self.depth_lo is not None
                else self.depth_hi / 2.0,
                self.age_lo if self.age_lo is not None
                else self.age_hi / 2.0)


class _TenantState:
    """One tenant's token bucket + SLO ledger (guarded by the queue's
    condition like everything else)."""

    __slots__ = ("tokens", "t_refill", "inflight", "admitted",
                 "completed", "shed", "deadline_hits",
                 "deadline_misses", "lat_sum")

    def __init__(self, cap: float):
        self.tokens = cap
        self.t_refill = time.monotonic()
        self.inflight = 0          # fresh entries queued or running
        self.admitted = 0          # fresh entries ever admitted
        self.completed = 0         # entries resolved (any provenance)
        self.shed = 0              # typed shed results (store misses)
        self.deadline_hits = 0
        self.deadline_misses = 0
        self.lat_sum = 0.0

    def refill(self, quota: TenantQuota, now: float) -> None:
        if quota.rate:
            cap = quota.bucket_cap()
            self.tokens = min(
                cap, self.tokens + (now - self.t_refill) * quota.rate)
        self.t_refill = now

    def as_dict(self) -> Dict:
        done = self.completed
        return {
            "inflight": self.inflight,
            "admitted": self.admitted,
            "completed": done,
            "shed": self.shed,
            "deadline_hits": self.deadline_hits,
            "deadline_misses": self.deadline_misses,
            "mean_latency_sec": (round(self.lat_sum / done, 4)
                                 if done else 0.0),
        }


#: config keys that define the ENGINE SHAPE a contract compiles into —
#: entries batch together only within one shape class, so one compiled
#: executable serves the whole batch
SHAPE_KEYS = ("batch_size", "lanes_per_contract", "max_steps",
              "transaction_count")


def shape_key_of(config: Dict) -> Tuple:
    return tuple(config.get(k) for k in SHAPE_KEYS)


class Entry:
    """One contract of one submission, from admission to verdict."""

    __slots__ = ("eid", "name", "code", "bch", "cfh", "config",
                 "shape_key", "priority", "deadline", "seq", "state",
                 "result", "submission", "followers", "t_submit",
                 "counted_inflight", "trace_id", "timings")

    def __init__(self, eid: str, name: str, code: bytes, config: Dict,
                 priority: int, deadline: Optional[float], seq: int,
                 submission: "Submission"):
        self.eid = eid
        self.name = name
        self.code = code
        self.bch = bytecode_hash(code)
        self.cfh = config_hash(config)
        self.config = config
        self.shape_key = shape_key_of(config)
        self.priority = priority
        self.deadline = deadline        # absolute monotonic, or None
        self.seq = seq
        self.state = "queued"           # queued|running|done
        self.result: Optional[Dict] = None
        self.submission = submission
        self.followers: List["Entry"] = []
        self.t_submit = time.monotonic()
        #: True while this FRESH entry holds one of its tenant's
        #: in-flight slots (queued or running; released at resolution)
        self.counted_inflight = False
        #: request trace id (minted at the ingestion point) and the
        #: per-stage latency ledger filled in as the entry moves
        #: admission → schedule → device/host → commit
        self.trace_id: Optional[str] = None
        self.timings: Dict[str, float] = {}

    @property
    def uname(self) -> str:
        """Engine-side contract name: unique within any batch (issue
        attribution maps back through it), never colliding with the
        campaign's ``_pad_*`` stubs."""
        return f"{self.name}@{self.eid}"

    def sort_key(self) -> Tuple:
        return (-self.priority,
                self.deadline if self.deadline is not None
                else float("inf"),
                self.seq)


class Submission:
    """One ``POST /v1/submit`` — a list of entries plus the stream of
    their results in COMMIT ORDER (dedupe-served entries first, then
    batch commits as they land)."""

    def __init__(self, sid: str, tenant: str, cond: threading.Condition):
        self.sid = sid
        self.tenant = tenant
        self.t = time.time()
        self.entries: List[Entry] = []
        #: per-contract results, appended strictly in commit order —
        #: the ``?stream=1`` wire order
        self.results: List[Dict] = []
        self._cond = cond

    @property
    def done(self) -> bool:
        return len(self.results) >= len(self.entries)

    def wait_results(self, seen: int, timeout: Optional[float]) -> bool:
        """Block until ``results`` grew past ``seen`` (or the
        submission finished, or the timeout lapsed). Returns done."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cond:
            while len(self.results) <= seen and not self.done:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    break
                self._cond.wait(remaining if remaining is not None
                                else 1.0)
            return self.done

    def wait_done(self, timeout: Optional[float]) -> bool:
        """Block until every entry resolved (long-poll). Returns
        done."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cond:
            while not self.done:
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    break
                self._cond.wait(remaining if remaining is not None
                                else 1.0)
            return self.done

    def snapshot(self) -> Dict:
        with self._cond:
            results = list(self.results)
            done = len(results) >= len(self.entries)
            return {"id": self.sid, "tenant": self.tenant,
                    "contracts": len(self.entries),
                    "completed": len(results),
                    "state": "done" if done else "pending",
                    "trace_id": getattr(self, "trace_id", None),
                    "results": results}


class AdmissionQueue:
    def __init__(self, store: Optional[ResultsStore] = None,
                 dedupe: bool = True, max_depth: int = 4096,
                 config_fn: Optional[Callable[[Dict], Dict]] = None,
                 quotas: Optional[Dict[str, TenantQuota]] = None,
                 default_quota: Optional[TenantQuota] = None,
                 shed: Optional[ShedPolicy] = None,
                 store_only: bool = False):
        self.store = store
        self.dedupe = bool(dedupe) and store is not None
        #: edge-replica mode: NO engine behind this queue — a store
        #: miss resolves at admission as a typed ``unknown-contract``
        #: answer instead of queuing (docs/serving.md "Verdict
        #: segments & edge replicas")
        self.store_only = bool(store_only) and store is not None
        self.max_depth = max(1, int(max_depth))
        #: merges per-request option overrides into the daemon's base
        #: analysis config — the dict that config_hash covers
        self.config_fn = config_fn or (lambda overrides: dict(overrides))
        #: per-tenant overrides; ``default_quota`` applies to every
        #: tenant without one (None = unlimited)
        self.quotas = dict(quotas or {})
        self.default_quota = default_quota
        self.shed_policy = shed
        self.shed_state = "ok"            # "ok" | "shedding"
        self._shed_reason: Optional[str] = None
        self.closed = False
        self._cond = threading.Condition()
        self._queue: List[Entry] = []
        self._inflight: Dict[Tuple[str, str], Entry] = {}
        self._subs: Dict[str, Submission] = {}
        self._tenants: Dict[str, _TenantState] = {}
        self._seq = itertools.count()
        self._nsub = itertools.count()
        self._reg = obs_metrics.REGISTRY

    # --- admission ------------------------------------------------------
    def _depth_gauge(self) -> None:
        self._reg.gauge(
            "serve_queue_depth",
            help="entries admitted and not yet scheduled").set(
            len(self._queue))

    def _tenant_locked(self, tenant: str) -> _TenantState:
        st = self._tenants.get(tenant)
        if st is None:
            q = self._quota_for(tenant)
            st = _TenantState(q.bucket_cap() if q else 0.0)
            self._tenants[tenant] = st
        return st

    def _quota_for(self, tenant: str) -> Optional[TenantQuota]:
        return self.quotas.get(tenant, self.default_quota)

    def _oldest_age_locked(self, now: float) -> float:
        if not self._queue:
            return 0.0
        return now - min(e.t_submit for e in self._queue)

    def _update_shed_locked(self, now: float) -> None:
        """Shed-state transitions from current pressure (queue depth /
        oldest-entry age), with hysteresis so the state can't flap at
        the threshold. Called on every submit and every scheduler
        drain, so recovery is automatic as pressure clears."""
        pol = self.shed_policy
        if pol is None:
            return
        depth = len(self._queue)
        age = self._oldest_age_locked(now)
        self._reg.gauge(
            "serve_oldest_entry_age_sec",
            help="age of the oldest still-queued entry").set(age)
        if self.shed_state == "ok":
            reason = None
            if depth >= pol.depth_hi * self.max_depth:
                reason = "depth"
            elif age >= pol.age_hi:
                reason = "age"
            if reason:
                self.shed_state = "shedding"
                self._shed_reason = reason
                self._reg.counter(
                    "serve_shed_transitions_total",
                    help="shed-state transitions",
                    labels={"dir": "enter"}).inc()
                obs_trace.event("shed_enter", reason=reason,
                                depth=depth, age=round(age, 3))
        else:
            depth_lo, age_lo = pol.lo_marks()
            if depth <= depth_lo * self.max_depth and age <= age_lo:
                self.shed_state = "ok"
                self._shed_reason = None
                self._reg.counter(
                    "serve_shed_transitions_total",
                    help="shed-state transitions",
                    labels={"dir": "exit"}).inc()
                obs_trace.event("shed_exit", depth=depth,
                                age=round(age, 3))
        self._reg.gauge(
            "serve_shed_state",
            help="1 while low-priority admissions degrade to "
                 "store-only answers").set(
            1.0 if self.shed_state == "shedding" else 0.0)

    def _check_quota_locked(self, tenant: str, fresh: int,
                            now: float) -> None:
        """Raise :class:`QuotaExceeded` if admitting ``fresh`` more
        entries would breach the tenant's in-flight cap or outrun its
        token bucket; on success the tokens are spent."""
        quota = self._quota_for(tenant)
        if quota is None or fresh <= 0:
            return
        st = self._tenant_locked(tenant)
        if (quota.max_inflight is not None
                and st.inflight + fresh > quota.max_inflight):
            self._reg.counter(
                "serve_quota_rejections_total",
                help="submissions rejected by a per-tenant quota",
                labels={"tenant": tenant}).inc()
            obs_trace.event("quota_rejected", tenant=tenant,
                            reason="inflight", inflight=st.inflight,
                            fresh=fresh, cap=quota.max_inflight)
            raise QuotaExceeded(
                f"tenant {tenant!r} has {st.inflight} entries in "
                f"flight; +{fresh} would exceed the cap of "
                f"{quota.max_inflight}", retry_after=1.0)
        if quota.rate:
            st.refill(quota, now)
            if st.tokens < fresh:
                retry = (fresh - st.tokens) / quota.rate
                self._reg.counter(
                    "serve_quota_rejections_total",
                    help="submissions rejected by a per-tenant quota",
                    labels={"tenant": tenant}).inc()
                obs_trace.event("quota_rejected", tenant=tenant,
                                reason="rate", fresh=fresh,
                                retry_after=round(retry, 3))
                raise QuotaExceeded(
                    f"tenant {tenant!r} admission rate "
                    f"{quota.rate:g}/s exhausted; retry in "
                    f"{retry:.1f}s", retry_after=retry)
            st.tokens -= fresh

    def _shed_submission_locked(self, sub: Submission, config: Dict,
                                contracts, priority: int,
                                deadline: Optional[float]) -> None:
        """Store-only degraded answers for one low-priority submission
        while shedding: a stored verdict is served
        (``served_from="shed-store"``), a miss becomes a typed
        ``status="shed"`` result — an answer either way, never a
        silent drop, and no lane or queue slot is touched."""
        st = self._tenant_locked(sub.tenant)
        for name, code in contracts:
            e = Entry(f"e{next(self._seq):07d}", str(name),
                      bytes(code), config, int(priority), deadline,
                      next(self._seq), sub)
            sub.entries.append(e)
            # --no-dedupe disables store ANSWERS here too: shedding
            # then degrades every low-priority contract to a typed
            # shed result (the store is neither read nor written)
            doc = (self.store.get(e.bch, e.cfh)
                   if self.dedupe else None)
            if doc is not None:
                self._reg.counter(
                    "serve_shed_total",
                    help="contracts answered degraded under overload",
                    labels={"reason": "store-hit"}).inc()
                self._resolve_locked(
                    e, self._verdict_result(e, doc),
                    served_from="shed-store")
            else:
                st.shed += 1
                self._reg.counter(
                    "serve_shed_total",
                    help="contracts answered degraded under overload",
                    labels={"reason": "store-miss"}).inc()
                self._resolve_locked(
                    e, {"status": "shed",
                        "error": "daemon overloaded "
                                 f"({self._shed_reason}); low-priority "
                                 "work is served from the verdict "
                                 "store only — no cached verdict for "
                                 "this contract, resubmit later or "
                                 "raise priority"},
                    served_from=None)

    def submit(self, contracts: Sequence[Tuple[str, bytes]],
               tenant: str = "default", priority: int = 0,
               deadline_sec: Optional[float] = None,
               options: Optional[Dict] = None,
               trace_id: Optional[str] = None) -> Submission:
        """Admit one submission of ``(name, bytecode)`` pairs. Raises
        :class:`QueueClosed` while draining, :class:`QuotaExceeded` on
        a per-tenant quota breach, :class:`QueueFull` when the whole
        submission cannot fit (all-or-nothing: a partially admitted
        submission would stream a partial result set that LOOKS
        complete). While shedding, a low-priority submission resolves
        entirely at admission with store-only answers. ``trace_id``
        continues a trace the transport minted (HTTP handler,
        follower); ``None`` mints one here — either way the id rides
        every span, event, and verdict this submission produces."""
        config = self.config_fn(dict(options or {}))
        tid = trace_id or obs_trace.new_trace_id()
        with obs_trace.trace_context(tid), \
                obs_trace.timer("admit", tenant=tenant,
                                n=len(contracts)) as sp:
            with self._cond:
                if self.closed:
                    raise QueueClosed("daemon is draining")
                now = time.monotonic()
                self._update_shed_locked(now)
                self._reg.counter(
                    "serve_requests_total",
                    help="submissions accepted for admission").inc()
                self._reg.counter("serve_contracts_total").inc(
                    len(contracts))
                sid = f"s{next(self._nsub):06d}-{os.getpid():x}"
                sub = Submission(sid, tenant, self._cond)
                sub.trace_id = tid
                deadline = (None if deadline_sec is None
                            else now + float(deadline_sec))
                if (self.shed_state == "shedding"
                        and self.shed_policy is not None
                        and int(priority)
                        <= self.shed_policy.priority_max):
                    self._shed_submission_locked(
                        sub, config, contracts, int(priority),
                        deadline)
                    self._subs[sid] = sub
                    self._cond.notify_all()
                    sp.attrs["id"] = sub.sid
                    sp.attrs["shed"] = True
                    return sub
                fresh: List[Entry] = []

                def rollback() -> None:
                    # drop this submission's in-flight registrations
                    # and followers (resolved store-hits stand — they
                    # cost nothing, their verdicts are real)
                    for e in fresh:
                        e.state = "done"
                        if self._inflight.get((e.bch, e.cfh)) is e:
                            del self._inflight[(e.bch, e.cfh)]
                    for e in sub.entries:
                        primary = self._inflight.get((e.bch, e.cfh))
                        if primary is not None and e in primary.followers:
                            primary.followers.remove(e)

                for name, code in contracts:
                    e = Entry(f"e{next(self._seq):07d}", str(name),
                              bytes(code), config, int(priority),
                              deadline, next(self._seq), sub)
                    e.trace_id = tid
                    e.timings["admission"] = sp.elapsed
                    sub.entries.append(e)
                    key = (e.bch, e.cfh)
                    if self.dedupe:
                        doc = self.store.get(e.bch, e.cfh)
                        if doc is not None:
                            self._reg.counter(
                                "serve_dedupe_hits_total",
                                help="submissions served from the "
                                     "verdict store or in-flight "
                                     "work, no lane touched").inc()
                            self._resolve_locked(
                                e, self._verdict_result(e, doc),
                                served_from="dedupe-store")
                            continue
                        if self.store_only:
                            # edge replica: no engine to queue for —
                            # a miss is a typed answer, never a 500
                            self._reg.counter(
                                "serve_unknown_contract_total",
                                help="store-only submissions whose "
                                     "verdict is not in the store "
                                     "snapshot yet").inc()
                            self._resolve_locked(
                                e, {"status": "unknown-contract",
                                    "error": "no stored verdict for "
                                             "this (bytecode, config) "
                                             "on this read replica; "
                                             "retry after the next "
                                             "manifest refresh or "
                                             "submit to an analysis "
                                             "daemon",
                                    "retry_after": UNKNOWN_RETRY_AFTER},
                                served_from=None)
                            continue
                        # in-flight attach covers clones WITHIN this
                        # submission too (the index is updated as
                        # entries are admitted below): a corpus of N
                        # proxy copies costs one analysis, not N
                        primary = self._inflight.get(key)
                        if primary is not None:
                            self._reg.counter(
                                "serve_dedupe_hits_total",
                                help="submissions served from the "
                                     "verdict store or in-flight "
                                     "work, no lane touched").inc()
                            primary.followers.append(e)
                            e.state = "running"
                            continue
                        self._inflight[key] = e
                    fresh.append(e)
                if len(self._queue) + len(fresh) > self.max_depth:
                    rollback()
                    raise QueueFull(
                        f"queue depth {len(self._queue)} + "
                        f"{len(fresh)} exceeds {self.max_depth}")
                try:
                    self._check_quota_locked(tenant, len(fresh), now)
                except QuotaExceeded:
                    rollback()
                    raise
                st = self._tenant_locked(tenant)
                st.admitted += len(fresh)
                st.inflight += len(fresh)
                for e in fresh:
                    e.counted_inflight = True
                    self._queue.append(e)
                self._subs[sid] = sub
                self._depth_gauge()
                self._update_shed_locked(now)
                self._cond.notify_all()
        sp.attrs["id"] = sub.sid
        return sub

    @staticmethod
    def _verdict_result(e: Entry, doc: Dict) -> Dict:
        """Entry result from a stored verdict: the issues are re-homed
        onto THIS entry's display name (the verdict was computed under
        some other submission's engine name)."""
        issues = []
        for i in doc.get("issues") or []:
            i = dict(i)
            i["contract"] = e.name
            issues.append(i)
        return {"status": str(doc.get("status", "ok")),
                "issues": issues}

    # --- scheduling side ------------------------------------------------
    def _evict_expired_locked(self, now: float) -> None:
        keep = []
        for e in self._queue:
            if e.deadline is not None and now >= e.deadline:
                self._reg.counter(
                    "serve_evicted_total",
                    help="entries whose deadline lapsed while "
                         "queued").inc()
                if self._inflight.get((e.bch, e.cfh)) is e:
                    del self._inflight[(e.bch, e.cfh)]
                self._resolve_locked(
                    e, {"status": "evicted",
                        "error": "deadline exceeded before scheduling"},
                    served_from=None)
            else:
                keep.append(e)
        self._queue = keep

    def pop_batch(self, max_items: int,
                  timeout: Optional[float] = None) -> List[Entry]:
        """The scheduler's drain: block up to ``timeout`` for work,
        evict lapsed deadlines, then pop the best-priority entry plus
        up to ``max_items - 1`` more entries of the SAME effective
        config (one module list, one engine shape — one compiled
        executable and one host-phase recipe serve the whole batch) in
        priority order. Different configs of one shape class still
        share compiled executables ACROSS batches via the scheduler's
        warm-shape registry."""
        deadline = (None if timeout is None
                    else time.monotonic() + timeout)
        with self._cond:
            while True:
                now = time.monotonic()
                self._evict_expired_locked(now)
                self._update_shed_locked(now)
                if self._queue:
                    break
                remaining = (None if deadline is None
                             else deadline - time.monotonic())
                if remaining is not None and remaining <= 0:
                    self._depth_gauge()
                    return []
                self._cond.wait(remaining if remaining is not None
                                else 1.0)
            ordered = sorted(self._queue, key=Entry.sort_key)
            cfh = ordered[0].cfh
            batch = [e for e in ordered
                     if e.cfh == cfh][:max(1, int(max_items))]
            taken = set(id(e) for e in batch)
            self._queue = [e for e in self._queue
                           if id(e) not in taken]
            now = time.monotonic()
            for e in batch:
                e.state = "running"
                wait = now - e.t_submit
                e.timings["sched_wait"] = max(
                    0.0, wait - e.timings.get("admission", 0.0))
                obs_trace.complete("queue_wait", wait,
                                   eid=e.eid, tenant=e.submission.tenant,
                                   priority=e.priority,
                                   trace_id=e.trace_id)
                self._reg.histogram(
                    "serve_queue_wait_seconds",
                    help="admission-to-schedule latency").observe(wait)
            self._depth_gauge()
            return batch

    # --- resolution -----------------------------------------------------
    def _resolve_locked(self, e: Entry, result: Dict,
                        served_from: Optional[str]) -> None:
        if e.state == "done":
            return
        e.state = "done"
        res = dict(result)
        res.setdefault("status", "ok")
        res["name"] = e.name
        res["bytecode_hash"] = e.bch
        res["config_hash"] = e.cfh
        if served_from:
            res["served_from"] = served_from
        # --- per-stage latency attribution (docs/observability.md):
        # the entry's stage ledger + total, rounded for the wire; the
        # stage histograms feed the heartbeat's req p50/p95 token
        now = time.monotonic()
        total = now - e.t_submit
        tm = dict(e.timings)
        tm["total"] = total
        res["timings"] = {k: round(v, 6) for k, v in tm.items()}
        if e.trace_id:
            res["trace_id"] = e.trace_id
        self._reg.histogram(
            "serve_request_seconds",
            help="end-to-end request latency (submit to "
                 "resolve)").observe(total)
        for stage in ("admission", "sched_wait", "device", "host",
                      "commit"):
            if stage in e.timings:
                self._reg.histogram(
                    "serve_request_stage_seconds",
                    help="per-request latency by pipeline stage",
                    labels={"stage": stage}).observe(e.timings[stage])
        e.result = res
        e.submission.results.append(res)
        # --- per-tenant SLO ledger (docs/serving.md) ---
        st = self._tenant_locked(e.submission.tenant)
        st.completed += 1
        st.lat_sum += now - e.t_submit
        if e.counted_inflight:
            e.counted_inflight = False
            st.inflight = max(0, st.inflight - 1)
        deadline_hit: Optional[bool] = None
        if e.deadline is not None:
            deadline_hit = now <= e.deadline
            if deadline_hit:
                st.deadline_hits += 1
            else:
                st.deadline_misses += 1
                self._reg.counter(
                    "serve_tenant_deadline_misses_total",
                    help="entries resolved after their deadline",
                    labels={"tenant": e.submission.tenant}).inc()
        obs_trace.event("serve_resolved", tenant=e.submission.tenant,
                        status=res.get("status"),
                        served_from=served_from,
                        deadline_hit=deadline_hit,
                        trace_id=e.trace_id,
                        wait=round(now - e.t_submit, 4))
        for f in e.followers:
            self._resolve_locked(f, self._verdict_result(f, res),
                                 served_from="dedupe-inflight")
        e.followers = []

    def resolve(self, e: Entry, result: Dict,
                served_from: Optional[str] = None) -> None:
        """Scheduler-side: commit one entry's verdict (and its
        followers') and wake every waiter. ``served_from`` marks
        DEDUPE provenance only; a fresh analysis carries no marker."""
        with self._cond:
            self._inflight.pop((e.bch, e.cfh), None)
            self._resolve_locked(e, result, served_from)
            self._cond.notify_all()

    # --- lifecycle ------------------------------------------------------
    def get(self, sid: str) -> Optional[Submission]:
        with self._cond:
            return self._subs.get(sid)

    def depth(self) -> int:
        with self._cond:
            return len(self._queue)

    def stats(self) -> Dict:
        """The admission-side health surface: depth, oldest-entry age,
        shed state, and the per-tenant SLO table (``/healthz``)."""
        with self._cond:
            now = time.monotonic()
            return {
                "queue_depth": len(self._queue),
                "oldest_entry_age_sec": round(
                    self._oldest_age_locked(now), 3),
                "shed_state": self.shed_state,
                "tenants": {t: st.as_dict()
                            for t, st in sorted(self._tenants.items())},
            }

    def close(self) -> None:
        """Stop admitting (drain begins). Queued entries stay queued —
        the scheduler decides whether to run or fail them."""
        with self._cond:
            self.closed = True
            self._cond.notify_all()

    def fail_pending(self, reason: str) -> int:
        """Resolve every still-queued entry with an error status (the
        drain's last act: nothing may wait forever on a daemon that is
        exiting). Returns how many were failed."""
        with self._cond:
            n = 0
            for e in list(self._queue):
                self._inflight.pop((e.bch, e.cfh), None)
                self._resolve_locked(
                    e, {"status": "error", "error": reason},
                    served_from=None)
                n += 1
            self._queue = []
            self._depth_gauge()
            self._cond.notify_all()
            return n


__all__ = ["AdmissionQueue", "Entry", "QueueClosed", "QueueFull",
           "QuotaExceeded", "SHAPE_KEYS", "ShedPolicy", "Submission",
           "TenantQuota", "UNKNOWN_RETRY_AFTER", "shape_key_of"]
