"""Whole-chain backfill: a backward historical walker feeding the
verdict index (docs/serving.md "Verdict segments & edge replicas").

``serve --backfill RPC_URI`` runs this loop beside the scheduler (and
usually beside ``--follow``, which covers the head while this covers
history): anchor ``hi`` at the current chain head once, then walk
BACKWARD in windows of ``--backfill-window`` blocks, scanning each
block for contract creations with the same deployment-scan the
follower uses (``serve/follower.deployed_contracts``), and submitting
every discovered bytecode as the standing tenant ``backfill`` at
:data:`BACKFILL_PRIORITY` — below even the follower, BY DESIGN the
first workload shed and the last scheduled. Combined with clone/proxy
dominance and the dedupe store, this converges on "the index already
knows every mainnet contract".

Contracts:

- **two-ended durable cursor** — ``<data-dir>/backfill_cursor.json``
  holds ``{lo, hi}``: ``hi`` is the head anchored at FIRST start
  (fixed — the follower owns everything after it), ``lo`` is the
  lowest block whose window has fully committed. Fresh cursor starts
  at ``lo = hi + 1``; the walk is done when ``lo == 0``.
- **exactly-once per window** — the cursor only moves past a window
  after EVERY contract in it resolved through the queue
  (analyzed-or-deduped, statuses checked). A SIGKILL mid-window means
  the restart re-scans at most that one window, and the dedupe store
  makes the overlap free (re-submissions are store hits).
- **bounded backoff with jitter** — RPC failures double a capped
  backoff with multiplicative jitter and tick
  ``serve_backfill_rpc_errors_total``; N backfilling replicas won't
  stampede a recovering node.
- **backpressure, not pressure** — a full queue or spent quota leaves
  the cursor unmoved and retries the window at the poll cadence.
- **visibility** — ``serve_backfill_remaining_blocks`` /
  ``serve_backfill_ingested_total`` / ``serve_backfill_rpc_errors_total``
  in ``/metrics``; ``/healthz`` carries a ``backfill`` block; one
  trace id is minted per window (docs/observability.md) so a window's
  contracts share a stitched timeline.
"""

from __future__ import annotations

import json
import logging
import os
import random
import threading
import time
from typing import Dict, Optional

from ..obs import metrics as obs_metrics
from ..obs import trace as obs_trace
from ..utils.checkpoint import durable_write
from .follower import deployed_contracts
from .queue import QueueClosed, QueueFull, QuotaExceeded

log = logging.getLogger(__name__)

#: the backfill tenant's fixed priority: below even the follower
#: (−100) — history is the least urgent work in the system
BACKFILL_PRIORITY = -200

#: cursor-file schema (readers reject newer-than-known)
BACKFILL_CURSOR_SCHEMA = 1


class ChainBackfill:
    """Backward window walker over the same JSON-RPC duck type the
    follower uses (``eth_blockNumber`` / ``eth_getBlockByNumber`` /
    ``eth_getTransactionReceipt`` / ``eth_getCode``)."""

    def __init__(self, daemon, client, window: int = 64,
                 poll: float = 2.0,
                 cursor_path: Optional[str] = None,
                 tenant: str = "backfill",
                 priority: int = BACKFILL_PRIORITY,
                 max_backoff: float = 60.0,
                 idle_poll: float = 60.0,
                 window_attempts: int = 5):
        self.daemon = daemon
        self.client = client
        self.window = max(1, int(window))
        self.poll = max(0.05, float(poll))
        self.cursor_path = cursor_path or os.path.join(
            daemon.data_dir, "backfill_cursor.json")
        self.tenant = tenant
        self.priority = int(priority)
        self.max_backoff = float(max_backoff)
        self.idle_poll = float(idle_poll)
        self.window_attempts = max(1, int(window_attempts))
        self.lo: Optional[int] = None
        self.hi: Optional[int] = None
        self._load_cursor()
        self.ingested = 0
        self.rpc_errors = 0
        self.windows = 0
        self._attempts = 0
        self._done_emitted = False
        self._backoff = 0.0
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._reg = obs_metrics.REGISTRY

    # --- cursor durability ----------------------------------------------
    def _load_cursor(self) -> None:
        try:
            with open(self.cursor_path) as fh:
                doc = json.load(fh)
        except (OSError, ValueError):
            return
        if (not isinstance(doc, dict)
                or int(doc.get("schema", 0) or 0) > BACKFILL_CURSOR_SCHEMA
                or not isinstance(doc.get("lo"), int)
                or not isinstance(doc.get("hi"), int)):
            return
        self.lo, self.hi = doc["lo"], doc["hi"]

    def _save_cursor(self, lo: Optional[int] = None,
                     hi: Optional[int] = None) -> None:
        """Persist the cursor. Callers pass the NEW position and only
        assign ``self.lo``/``self.hi`` after this returns — a position
        visible in ``status()`` (and thus ``/healthz``) is always
        already durable, so "done" can never be observed ahead of the
        on-disk cursor."""
        durable_write(
            self.cursor_path,
            json.dumps({"schema": BACKFILL_CURSOR_SCHEMA,
                        "lo": self.lo if lo is None else lo,
                        "hi": self.hi if hi is None else hi,
                        "t": round(time.time(), 3)}).encode(),
            rotate=False)

    # --- lifecycle ------------------------------------------------------
    def start(self) -> None:
        self._thread = threading.Thread(target=self._loop, daemon=True,
                                        name="serve-backfill")
        self._thread.start()
        obs_trace.event("backfill_started", lo=self.lo, hi=self.hi,
                        window=self.window, tenant=self.tenant,
                        priority=self.priority)

    def stop(self, timeout: float = 5.0) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout)

    def status(self) -> Dict:
        remaining = self.lo if self.lo is not None else None
        return {"lo": self.lo, "hi": self.hi,
                "remaining_blocks": remaining,
                "ingested": self.ingested,
                "rpc_errors": self.rpc_errors,
                "windows": self.windows,
                "backoff_sec": round(self._backoff, 3),
                "done": self.lo == 0}

    # --- the loop -------------------------------------------------------
    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                delay = self._tick()
                self._backoff = 0.0
            except Exception as e:  # noqa: BLE001 — the loop may not die
                self.rpc_errors += 1
                self._reg.counter(
                    "serve_backfill_rpc_errors_total",
                    help="backfill scan/ingest failures (backed off "
                         "with jitter, window retried)").inc()
                self._backoff = min(self.max_backoff,
                                    max(self.poll, self._backoff * 2))
                # multiplicative jitter so N replicas desynchronize
                delay = self._backoff * (0.5 + random.random())
                obs_trace.event("backfill_rpc_error",
                                detail=f"{type(e).__name__}: "
                                       f"{str(e)[:200]}",
                                backoff=round(delay, 3))
                log.warning("backfill: %s: %s (backing off %.1fs)",
                            type(e).__name__, str(e)[:200], delay)
            self._stop.wait(delay)
        obs_trace.event("backfill_stopped", lo=self.lo, hi=self.hi,
                        ingested=self.ingested)

    def _tick(self) -> float:
        """Scan and commit ONE window of blocks ``[lo-window, lo-1]``.
        Returns how long to sleep before the next tick (0 while blocks
        remain). The cursor advances only after every contract in the
        window is durably submitted-or-deduped."""
        if self.hi is None:
            # first ever start: anchor the walk at the current head —
            # the follower owns everything after this block
            hi = int(self.client.eth_blockNumber(), 16)
            self._save_cursor(lo=hi + 1, hi=hi)
            self.hi, self.lo = hi, hi + 1
        if self.lo is None:  # torn cursor healed as fresh anchor
            self._save_cursor(lo=self.hi + 1)
            self.lo = self.hi + 1
        self._remaining_gauge()
        if self.lo <= 0:
            if not self._done_emitted:
                self._done_emitted = True
                obs_trace.event("backfill_done", hi=self.hi,
                                ingested=self.ingested)
            return self.idle_poll
        w_lo = max(0, self.lo - self.window)
        w_hi = self.lo - 1
        contracts = []
        for n in range(w_hi, w_lo - 1, -1):
            if self._stop.is_set():
                return self.poll     # cursor unmoved: window re-scanned
            contracts.extend(deployed_contracts(self.client, n))
        if contracts and not self._commit_window(w_lo, w_hi, contracts):
            return self.poll         # backpressure/incomplete: retry
        self._save_cursor(lo=w_lo)  # durable BEFORE visible
        self.lo = w_lo
        self.windows += 1
        self._attempts = 0
        self._reg.counter(
            "serve_backfill_windows_total",
            help="backfill windows fully committed (cursor "
                 "advanced)").inc()
        self._remaining_gauge()
        return 0.0 if self.lo > 0 else 0.0

    def _commit_window(self, w_lo: int, w_hi: int, contracts) -> bool:
        """Submit one window's contracts as ONE submission and wait for
        every one of them to resolve. Returns whether the cursor may
        advance. Shed/errored results mean the window was NOT fully
        answered — retried up to ``window_attempts`` times (dedupe
        makes each retry nearly free), then advanced anyway with a
        ``backfill_window_incomplete`` event so one poisoned window
        can't wedge the whole-chain walk."""
        tid = obs_trace.new_trace_id()
        try:
            sub = self.daemon.queue.submit(
                contracts, tenant=self.tenant, priority=self.priority,
                trace_id=tid)
        except (QueueFull, QuotaExceeded):
            self._reg.counter(
                "serve_backfill_backpressure_total",
                help="backfill windows deferred by a full queue or "
                     "spent quota").inc()
            return False
        except QueueClosed:
            self._stop.set()
            return False
        while not self._stop.is_set() and not sub.wait_done(timeout=1.0):
            pass
        if self._stop.is_set() and not sub.wait_done(timeout=0.0):
            return False             # shutdown mid-window: re-scan it
        snap = sub.snapshot()
        bad = [r for r in snap.get("results") or []
               if r.get("status") not in ("ok", "quarantined")]
        if bad:
            self._attempts += 1
            self._reg.counter(
                "serve_backfill_window_retries_total",
                help="backfill windows retried because some results "
                     "came back shed/errored").inc()
            if self._attempts < self.window_attempts:
                return False
            obs_trace.event("backfill_window_incomplete",
                            lo=w_lo, hi=w_hi, bad=len(bad),
                            attempts=self._attempts, trace_id=tid)
            log.warning("backfill: window [%d, %d] advanced with %d "
                        "unresolved result(s) after %d attempts",
                        w_lo, w_hi, len(bad), self._attempts)
        self.ingested += len(contracts)
        self._reg.counter(
            "serve_backfill_ingested_total",
            help="historical contracts submitted-or-deduped by the "
                 "backfill walker").inc(len(contracts))
        obs_trace.event("backfill_window", lo=w_lo, hi=w_hi,
                        n=len(contracts), trace_id=tid)
        return True

    def _remaining_gauge(self) -> None:
        if self.lo is not None:
            self._reg.gauge(
                "serve_backfill_remaining_blocks",
                help="blocks below the backfill cursor still to be "
                     "walked").set(max(0, self.lo))


__all__ = ["BACKFILL_CURSOR_SCHEMA", "BACKFILL_PRIORITY",
           "ChainBackfill"]
