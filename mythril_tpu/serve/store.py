"""Persistent per-contract verdict store, keyed on
``(bytecode_hash, config_hash)``.

The dedupe backbone of the serve layer (docs/serving.md) and the first
slice of ROADMAP's cross-campaign constraint-verdict store: mainnet
bytecode is dominated by proxy/clone copies, so most submissions should
resolve here — a verdict lookup instead of lanes + solver work. The key
pairs WHAT was analyzed (sha256 of the runtime bytecode) with HOW
(sha256 of the effective analysis config: step budget, lanes, tx count,
module list, solver knobs) — the same bytecode under a deeper budget is
a different verdict, never served stale.

Every verdict is one JSON file created with the repo-wide
``utils/checkpoint.exclusive_write`` contract (tmp + fsync +
link-exclusive create) — FIRST WINS, the multi-replica story
(docs/serving.md "Overload & multi-replica serving"): N daemons on one
``--data-dir`` may commit the same ``(bytecode, config)`` verdict
concurrently and exactly one file lands; the losers drop their copies
(equal by construction) with a ``serve_store_write_races_total`` tick.
A SIGKILL mid-write never leaves a half verdict: the restarted daemon
either has the verdict or re-analyzes — exactly-once either way.
Corrupt files are treated as counted misses, never errors, and are
UNLINKED on read (mirroring ``smt/vstore.py``) so a first-wins
re-commit can rewrite them instead of preserving the corruption
forever.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Dict, Optional

from ..obs import metrics as obs_metrics
from ..utils.checkpoint import exclusive_write

#: verdict-file schema (readers reject newer-than-known)
STORE_SCHEMA = 1


def bytecode_hash(code: bytes) -> str:
    """Content identity of one runtime bytecode (sha256, 32 hex chars —
    collision-safe at corpus scale, short enough for filenames)."""
    return hashlib.sha256(bytes(code)).hexdigest()[:32]


#: config keys that are OPERATIONAL, not semantic — they shape how a
#: batch is supervised (watchdogs, retries, degradation, test fault
#: injection, host-phase thread count) or packed (batch width is
#: padding: the campaign's bisect/degrade machinery already treats
#: per-contract verdicts as batch-composition-independent), never which
#: issues exist in the bytecode. They are excluded from the verdict
#: key: a daemon restarted with a different drain budget or batch width
#: (or with a soak fault spec removed) must still recognize its own
#: verdicts. ``lanes_per_contract`` stays SEMANTIC — fork capacity
#: changes which paths survive.
OPERATIONAL_KEYS = frozenset((
    "fault_inject", "batch_timeout", "max_batch_retries", "oom_ladder",
    "solver_workers", "batch_size", "worker_isolation",
    "backend_tiers", "trace"))


def config_hash(config: Dict) -> str:
    """Identity of the effective analysis configuration — the
    SEMANTIC knobs only (step budget, lanes, tx count, modules, limits
    profile, solver budget, storage model). Canonical JSON (sorted
    keys) so dict ordering can't split the cache."""
    sem = {k: v for k, v in config.items()
           if k not in OPERATIONAL_KEYS}
    blob = json.dumps(sem, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


class ResultsStore:
    """One directory of verdict files: ``<dir>/<bch>.<cfh>.json``.

    Many writers (N replica daemons' scheduler threads), many readers
    (HTTP threads, the queue's admission check), across processes and
    hosts; file-level atomicity via first-wins ``exclusive_write`` is
    the whole concurrency story — no lock, no index file to corrupt."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)

    def _file(self, bch: str, cfh: str) -> str:
        return os.path.join(self.path, f"{bch}.{cfh}.json")

    def _corrupt_miss(self, path: str) -> None:
        """Count and UNLINK one unreadable verdict file so re-analysis
        can rewrite it (a first-wins create would otherwise preserve
        the corruption forever)."""
        obs_metrics.REGISTRY.counter(
            "serve_store_corrupt_total",
            help="unreadable verdict files treated as misses "
                 "(and unlinked)").inc()
        try:
            os.unlink(path)
        except OSError:
            pass

    def get(self, bch: str, cfh: str) -> Optional[Dict]:
        """The stored verdict, or None on miss. A corrupt or
        newer-schema file is a MISS with a counter tick (and the file
        is removed for rewrite), never an exception on the admission
        path."""
        p = self._file(bch, cfh)
        try:
            with open(p) as fh:
                doc = json.load(fh)
        except FileNotFoundError:
            return None
        except (OSError, ValueError):
            self._corrupt_miss(p)
            return None
        if (not isinstance(doc, dict)
                or int(doc.get("schema", 0)) > STORE_SCHEMA
                or doc.get("bytecode_hash") != bch):
            self._corrupt_miss(p)
            return None
        return doc

    def put(self, bch: str, cfh: str, verdict: Dict) -> bool:
        """Durably persist one verdict (issues + status for one
        contract under one config), first-wins across replicas.
        Returns whether this caller's file is the one on disk; a
        losing write is dropped (the verdicts are equal by
        construction) with a race-counter tick — unless the file on
        disk is CORRUPT, in which case it is unlinked and the write
        retried so a torn replica write heals instead of poisoning
        the key."""
        doc = {"schema": STORE_SCHEMA, "bytecode_hash": bch,
               "config_hash": cfh, "t": round(time.time(), 3)}
        doc.update(verdict)
        blob = json.dumps(doc, sort_keys=True).encode()
        won = exclusive_write(self._file(bch, cfh), blob)
        if not won and self.get(bch, cfh) is None:
            # the incumbent was corrupt: get() unlinked it — retry
            won = exclusive_write(self._file(bch, cfh), blob)
        reg = obs_metrics.REGISTRY
        if won:
            reg.counter(
                "serve_store_writes_total",
                help="verdicts persisted to the results store").inc()
        else:
            reg.counter(
                "serve_store_write_races_total",
                help="verdict writes dropped because another replica "
                     "committed the key first").inc()
        return won

    def count(self) -> int:
        """Number of stored verdicts (healthz diagnostics; O(dir))."""
        try:
            return sum(1 for f in os.listdir(self.path)
                       if f.endswith(".json"))
        except OSError:
            return 0


__all__ = ["STORE_SCHEMA", "ResultsStore", "bytecode_hash",
           "config_hash"]
