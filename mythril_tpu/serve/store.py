"""Persistent per-contract verdict store, keyed on
``(bytecode_hash, config_hash)``.

The dedupe backbone of the serve layer (docs/serving.md) and the first
slice of ROADMAP's cross-campaign constraint-verdict store: mainnet
bytecode is dominated by proxy/clone copies, so most submissions should
resolve here — a verdict lookup instead of lanes + solver work. The key
pairs WHAT was analyzed (sha256 of the runtime bytecode) with HOW
(sha256 of the effective analysis config: step budget, lanes, tx count,
module list, solver knobs) — the same bytecode under a deeper budget is
a different verdict, never served stale.

Every verdict is one JSON file created with the repo-wide
``utils/checkpoint.exclusive_write`` contract (tmp + fsync +
link-exclusive create) — FIRST WINS, the multi-replica story
(docs/serving.md "Overload & multi-replica serving"): N daemons on one
``--data-dir`` may commit the same ``(bytecode, config)`` verdict
concurrently and exactly one file lands; the losers drop their copies
(equal by construction) with a ``serve_store_write_races_total`` tick.
A SIGKILL mid-write never leaves a half verdict: the restarted daemon
either has the verdict or re-analyzes — exactly-once either way.
Corrupt files are treated as counted misses, never errors, and are
UNLINKED on read (mirroring ``smt/vstore.py``) so a first-wins
re-commit can rewrite them instead of preserving the corruption
forever.

At backfill scale the loose-file layout stops being enough on the READ
side, so the store is two-tier (docs/serving.md "Verdict segments &
edge replicas"): reads check the loose file first (newest writes win),
then the compacted segment index (``serve/segstore.py``).
``compact()`` folds settled loose files into immutable segments behind
a generation-numbered manifest and only THEN unlinks them — a SIGKILL
anywhere leaves every key readable from one tier or the other.
"""

from __future__ import annotations

import hashlib
import json
import os
import time
from typing import Dict, Optional

from ..obs import metrics as obs_metrics
from ..utils.checkpoint import exclusive_write
from .segstore import LOOSE_RE, SegmentStore, _maybe_kill

#: verdict-file schema (readers reject newer-than-known)
STORE_SCHEMA = 1

#: how stale the cached loose-file tally in ``count()`` may be, in
#: seconds — healthz hits between recounts serve the cached number
#: instead of an O(dir) listdir
COUNT_TTL = 5.0


def bytecode_hash(code: bytes) -> str:
    """Content identity of one runtime bytecode (sha256, 32 hex chars —
    collision-safe at corpus scale, short enough for filenames)."""
    return hashlib.sha256(bytes(code)).hexdigest()[:32]


#: config keys that are OPERATIONAL, not semantic — they shape how a
#: batch is supervised (watchdogs, retries, degradation, test fault
#: injection, host-phase thread count) or packed (batch width is
#: padding: the campaign's bisect/degrade machinery already treats
#: per-contract verdicts as batch-composition-independent), never which
#: issues exist in the bytecode. They are excluded from the verdict
#: key: a daemon restarted with a different drain budget or batch width
#: (or with a soak fault spec removed) must still recognize its own
#: verdicts. ``lanes_per_contract`` stays SEMANTIC — fork capacity
#: changes which paths survive.
OPERATIONAL_KEYS = frozenset((
    "fault_inject", "batch_timeout", "max_batch_retries", "oom_ladder",
    "solver_workers", "batch_size", "worker_isolation",
    "backend_tiers", "trace"))


def config_hash(config: Dict) -> str:
    """Identity of the effective analysis configuration — the
    SEMANTIC knobs only (step budget, lanes, tx count, modules, limits
    profile, solver budget, storage model). Canonical JSON (sorted
    keys) so dict ordering can't split the cache."""
    sem = {k: v for k, v in config.items()
           if k not in OPERATIONAL_KEYS}
    blob = json.dumps(sem, sort_keys=True, default=str).encode()
    return hashlib.sha256(blob).hexdigest()[:16]


class ResultsStore:
    """One directory of verdict files: ``<dir>/<bch>.<cfh>.json``, plus
    the compacted ``segments/`` tier behind ``MANIFEST.json``.

    Many writers (N replica daemons' scheduler threads), many readers
    (HTTP threads, the queue's admission check), across processes and
    hosts; file-level atomicity via first-wins ``exclusive_write`` is
    the whole concurrency story for the loose tier — no lock, no index
    file to corrupt. The segment tier is written by AT MOST ONE
    compactor (deployment contract, docs/serving.md) and read by
    everyone."""

    def __init__(self, path: str):
        self.path = path
        os.makedirs(path, exist_ok=True)
        self.segments = SegmentStore(path, validate=self._valid_key_doc)
        self._loose_n = 0
        self._loose_t = -1e18  # force a recount on first count()

    def _file(self, bch: str, cfh: str) -> str:
        return os.path.join(self.path, f"{bch}.{cfh}.json")

    def _valid_doc(self, bch: str, cfh: str, doc) -> bool:
        """One verdict doc is servable for the REQUESTED key: right
        schema, right bytecode hash, and right config hash — a
        misnamed or cross-linked file must not serve a verdict computed
        under a different config."""
        return (isinstance(doc, dict)
                and int(doc.get("schema", 0)) <= STORE_SCHEMA
                and doc.get("bytecode_hash") == bch
                and doc.get("config_hash") == cfh)

    def _valid_key_doc(self, key: str, doc) -> bool:
        bch, _, cfh = key.partition(".")
        return self._valid_doc(bch, cfh, doc)

    def _corrupt_miss(self, path: str) -> None:
        """Count and UNLINK one unreadable verdict file so re-analysis
        can rewrite it (a first-wins create would otherwise preserve
        the corruption forever)."""
        obs_metrics.REGISTRY.counter(
            "serve_store_corrupt_total",
            help="unreadable verdict files treated as misses "
                 "(and unlinked)").inc()
        try:
            os.unlink(path)
        except OSError:
            pass

    def get(self, bch: str, cfh: str) -> Optional[Dict]:
        """The stored verdict, or None on miss. The loose file wins
        over the segment tier (it can only be the SAME verdict or a
        fresher first-wins commit). A corrupt or newer-schema or
        wrong-key file is a MISS with a counter tick (and the file is
        removed for rewrite), never an exception on the admission
        path."""
        p = self._file(bch, cfh)
        try:
            with open(p) as fh:
                doc = json.load(fh)
        except FileNotFoundError:
            return self.segments.get(bch, cfh)
        except (OSError, ValueError):
            self._corrupt_miss(p)
            return self.segments.get(bch, cfh)
        if not self._valid_doc(bch, cfh, doc):
            self._corrupt_miss(p)
            return self.segments.get(bch, cfh)
        return doc

    def put(self, bch: str, cfh: str, verdict: Dict) -> bool:
        """Durably persist one verdict (issues + status for one
        contract under one config), first-wins across replicas.
        Returns whether this caller's file is the one on disk; a
        losing write is dropped (the verdicts are equal by
        construction) with a race-counter tick — unless the file on
        disk is CORRUPT, in which case it is unlinked and the write
        retried so a torn replica write heals instead of poisoning
        the key."""
        doc = {"schema": STORE_SCHEMA, "bytecode_hash": bch,
               "config_hash": cfh, "t": round(time.time(), 3)}
        doc.update(verdict)
        blob = json.dumps(doc, sort_keys=True).encode()
        p = self._file(bch, cfh)
        won = exclusive_write(p, blob)
        if not won:
            # probe the INCUMBENT loose file only (not the segment
            # tier): if it is corrupt, heal it and retry the write
            try:
                with open(p) as fh:
                    incumbent = json.load(fh)
            except FileNotFoundError:
                incumbent = None
            except (OSError, ValueError):
                self._corrupt_miss(p)
                incumbent = None
            else:
                if not self._valid_doc(bch, cfh, incumbent):
                    self._corrupt_miss(p)
                    incumbent = None
            if incumbent is None:
                won = exclusive_write(p, blob)
        reg = obs_metrics.REGISTRY
        if won:
            self._loose_n += 1
            reg.counter(
                "serve_store_writes_total",
                help="verdicts persisted to the results store").inc()
        else:
            reg.counter(
                "serve_store_write_races_total",
                help="verdict writes dropped because another replica "
                     "committed the key first").inc()
        return won

    def count(self) -> int:
        """Number of stored verdicts: the manifest's key count plus a
        cached loose-file tally recounted at most every ``COUNT_TTL``
        seconds — bounded staleness instead of an O(dir) listdir on
        every healthz probe."""
        now = time.monotonic()
        if now - self._loose_t > COUNT_TTL:
            try:
                self._loose_n = sum(
                    1 for f in os.listdir(self.path) if LOOSE_RE.match(f))
            except OSError:
                self._loose_n = 0
            self._loose_t = now
        return self.segments.key_count() + self._loose_n

    def refresh(self) -> bool:
        """Pick up a manifest generation committed by another process
        (the edge-replica poll)."""
        return self.segments.refresh()

    def generation(self) -> int:
        return self.segments.generation

    def compact(self) -> Dict:
        """Fold every settled loose verdict into the segment tier and
        unlink the folded files. Crash-safe at any instant: loose
        files are removed only AFTER the new manifest generation is
        durable, and keys already compacted are unlinked without
        rewriting (the overlap after a crash-resume is free). Corrupt
        loose files are counted misses and unlinked, never folded.
        Returns stats ``{generation, folded, dupes, corrupt,
        segments}``."""
        self.segments.refresh(force=True)
        fresh: Dict[str, Dict] = {}
        dupes = []
        corrupt = 0
        try:
            names = sorted(os.listdir(self.path))
        except OSError:
            names = []
        for fn in names:
            if not LOOSE_RE.match(fn):
                continue
            key = fn[:-len(".json")]
            p = os.path.join(self.path, fn)
            if self.segments.has(key):
                dupes.append(p)
                continue
            try:
                with open(p) as fh:
                    doc = json.load(fh)
            except FileNotFoundError:
                continue
            except (OSError, ValueError):
                self._corrupt_miss(p)
                corrupt += 1
                continue
            if not self._valid_key_doc(key, doc):
                self._corrupt_miss(p)
                corrupt += 1
                continue
            fresh[key] = doc
        stats = self.segments.compact_commit(fresh)
        _maybe_kill("before-unlink")
        # manifest is durable: the loose copies are now redundant
        for key in fresh:
            try:
                os.unlink(os.path.join(self.path, key + ".json"))
            except OSError:
                pass
        for p in dupes:
            try:
                os.unlink(p)
            except OSError:
                pass
        self._loose_t = -1e18  # invalidate the cached tally
        stats = dict(stats)
        stats["dupes"] = len(dupes)
        stats["corrupt"] = corrupt
        return stats


__all__ = ["STORE_SCHEMA", "COUNT_TTL", "ResultsStore", "bytecode_hash",
           "config_hash"]
