"""Analysis driver: symbolic wrapper, detection modules, reporting.

Reference layout counterpart: ``mythril/analysis/`` (⚠unv) —
``symbolic.py`` (SymExecWrapper), ``security.py`` (fire_lasers),
``module/`` (DetectionModule + loader + the SWC suite), ``report.py``.
"""

from .report import Issue, Report, SWC_TITLES
from .symbolic import AnalysisContext, SymExecWrapper
from .security import fire_lasers
from .module.base import DetectionModule, EntryPoint
from .module.loader import ModuleLoader, register_module
from .module import modules  # noqa: F401  (registers the SWC suite)

__all__ = [
    "Issue", "Report", "SWC_TITLES",
    "AnalysisContext", "SymExecWrapper", "fire_lasers",
    "DetectionModule", "EntryPoint", "ModuleLoader", "register_module",
]
