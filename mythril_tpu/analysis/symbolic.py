"""SymExecWrapper + AnalysisContext: wire the engine to the modules.

Reference: ``mythril/analysis/symbolic.py`` (⚠unv) — ``SymExecWrapper``
builds the LASER VM with strategy/plugins/modules and runs it. Here it
builds the corpus + frontier, runs ``sym_run`` (one jitted call — the
whole exploration), and exposes an :class:`AnalysisContext` that modules
consume batched.
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Dict, List, Optional, Sequence

import numpy as np

from ..config import DEFAULT_LIMITS, LimitsConfig
from ..core import Corpus, make_env
from ..core.frontier import CAP_TRAPS, TRAP_NAMES
from ..disassembler import ContractImage
from ..smt.eval import Assignment
from ..smt.solver import solve_tape
from ..smt.tape import HostTape, extract_tape
from ..symbolic import SymSpec, between_txs, make_sym_frontier, sym_run


@dataclass
class AnalysisContext:
    """Batched view of one finished exploration, handed to modules."""

    sf: object               # final SymFrontier
    corpus: Corpus
    limits: LimitsConfig
    contract_names: List[str]
    solver_iters: int = 400
    # lanes newly errored during THIS transaction, per trap name (filled by
    # SymExecWrapper; None for standalone contexts, where coverage falls
    # back to reading the snapshot directly)
    trap_counts: Optional[Dict[str, int]] = None
    _tapes: Dict[int, HostTape] = field(default_factory=dict)

    def lanes(self, include_errors: bool = False,
              include_reverted: bool = False) -> np.ndarray:
        """Lane indices that hold surviving paths. Exceptional halts are
        discarded like the reference's VmException states; reverted paths
        are excluded by default — a reverting transaction has no effect,
        so predicates witnessed only on a revert path (e.g. the guard
        branch of a SafeMath add) are not findings. The Exceptions module
        opts into error lanes explicitly."""
        act = np.asarray(self.sf.base.active)
        err = np.asarray(self.sf.base.error)
        rev = np.asarray(self.sf.base.reverted)
        keep = act.copy()
        if not include_errors:
            keep &= ~err
        if not include_reverted:
            keep &= ~rev
        return np.where(keep)[0]

    def tape(self, lane: int) -> HostTape:
        if lane not in self._tapes:
            self._tapes[lane] = extract_tape(self.sf, lane)
        return self._tapes[lane]

    def solve(self, lane: int, extra_constraints=(),
              extra_nodes=()) -> Optional[Assignment]:
        """Witness for the lane's path condition + extra (node, sign)
        constraints. ``extra_nodes`` are appended to the tape first (ids
        continue after the lane's last node) so modules can constrain
        derived predicates without touching the device tape."""
        base = self.tape(lane)
        t = HostTape(nodes=list(base.nodes) + list(extra_nodes),
                     constraints=list(base.constraints) + list(extra_constraints))
        return solve_tape(t, max_iters=self.solver_iters)

    def contract_of(self, lane: int) -> int:
        return int(np.asarray(self.sf.base.contract_id[lane]))

    def contract_name(self, lane: int) -> str:
        cid = self.contract_of(lane)
        return self.contract_names[cid] if cid < len(self.contract_names) else f"contract_{cid}"

    def tx_sequence(self, asn: Assignment) -> List[dict]:
        """Render a witness as the reference-style concrete tx list (one
        entry per symbolic transaction). All `calldatasize` bytes are
        emitted — trimming zeros would change CALLDATASIZE on replay and
        can flip size-check branches."""
        from ..symbolic.ops import FreeKind

        origin = asn.scalars.get((int(FreeKind.ORIGIN), 0), asn.caller)
        out = []
        for t in asn.txs:
            size = t.calldatasize if t.calldatasize is not None else len(t.calldata)
            size = max(0, min(size, len(t.calldata)))
            out.append({
                "input": "0x" + bytes(t.calldata[:size]).hex(),
                "value": hex(t.callvalue),
                "origin": hex(origin),
                "caller": hex(t.caller),
            })
        return out


def _count_traps(err_code: np.ndarray) -> Dict[str, int]:
    out: Dict[str, int] = {}
    for code, name in TRAP_NAMES.items():
        n = int((err_code == code).sum())
        if n:
            out[name] = n
    return out


def coverage_summary(tx_contexts) -> dict:
    """Lost-coverage accounting over a run's per-tx context snapshots.

    The reference silently discards VmException states; here every loss
    channel is counted so parity claims are auditable (VERDICT.md round-1
    weak #4): lanes errored per trap cause, forks dropped to capacity,
    saturated event logs, and propagation kills.
    """
    final = tx_contexts[-1].sf
    limits = tx_contexts[-1].limits
    errored: dict = {}
    if all(c.trap_counts is not None for c in tx_contexts):
        # per-tx tallies (exact even when expand_forks recycled an errored
        # lane's slot in a later transaction)
        for c in tx_contexts:
            for name, n in c.trap_counts.items():
                errored[name] = errored.get(name, 0) + n
    else:
        errored = _count_traps(np.asarray(final.base.err_code))
    cap_names = {TRAP_NAMES[c] for c in CAP_TRAPS}
    cap_lost = sum(n for name, n in errored.items() if name in cap_names)
    # event logs reset per tx, so saturation counts sum across snapshots
    sat_calls = sum(
        int((np.asarray(c.sf.n_calls) > limits.call_log).sum()) for c in tx_contexts
    )
    sat_arith = sum(
        int((np.asarray(c.sf.n_arith) > limits.arith_log).sum()) for c in tx_contexts
    )
    return {
        "lanes": int(np.asarray(final.base.active).shape[0]),
        "surviving_paths": int(
            (np.asarray(final.base.active) & ~np.asarray(final.base.error)).sum()
        ),
        "lanes_errored": errored,
        "lanes_lost_to_caps": cap_lost,
        "dropped_forks": int(np.asarray(final.dropped_total)),
        "killed_infeasible": int(np.asarray(final.killed_total)),
        "saturated_call_logs": sat_calls,
        "saturated_arith_logs": sat_arith,
    }


class SymExecWrapper:
    """Build + run the symbolic exploration for a batch of contracts."""

    def __init__(
        self,
        bytecodes: Sequence[bytes],
        contract_names: Optional[Sequence[str]] = None,
        limits: LimitsConfig = DEFAULT_LIMITS,
        spec: SymSpec = SymSpec(),
        lanes_per_contract: int = 64,
        max_steps: int = 512,
        solver_iters: int = 400,
        transaction_count: int = 1,
    ):
        self.limits = limits
        self.spec = spec
        images = [ContractImage.from_bytecode(c, limits.max_code) for c in bytecodes]
        self.corpus = Corpus.from_images(images)
        C = len(images)
        P = C * lanes_per_contract
        contract_id = np.repeat(np.arange(C, dtype=np.int32), lanes_per_contract)
        active = np.zeros(P, dtype=bool)
        active[::lanes_per_contract] = True  # one seed lane per contract
        sf = make_sym_frontier(P, limits, contract_id=contract_id, active=active,
                               n_contracts=C)
        env = make_env(P)
        names = list(contract_names or [f"contract_{i}" for i in range(C)])

        # multi-tx outer loop (reference: execute_transactions iterating
        # open_states ⚠unv SURVEY.md §3.2): snapshot a context after each
        # tx so detection sees lanes that between_txs retires
        self.tx_contexts: List[AnalysisContext] = []
        for t in range(transaction_count):
            sf = sym_run(sf, env, self.corpus, spec, limits, max_steps=max_steps)
            # err_code is zeroed by between_txs, so every nonzero code here
            # is a loss from THIS transaction
            trap_counts = _count_traps(np.asarray(sf.base.err_code))
            self.tx_contexts.append(AnalysisContext(
                sf=sf, corpus=self.corpus, limits=limits,
                contract_names=names, solver_iters=solver_iters,
                trap_counts=trap_counts,
            ))
            if t < transaction_count - 1:
                sf = between_txs(sf)
                if not bool(np.asarray(sf.base.active).any()):
                    break  # no mutating state survived: nothing to extend
        self.sf = sf
        self.ctx = self.tx_contexts[-1]

    @property
    def coverage(self) -> dict:
        return coverage_summary(self.tx_contexts)
